"""Package metadata (parity: reference setup.py — version, minimal deps)."""

from setuptools import find_packages, setup

setup(
    name="tensorflowonspark_tpu",
    version="0.1.0",
    description=(
        "TPU-native cluster-federation framework: bring up distributed "
        "JAX/XLA training from a data-engine scheduler and stream "
        "partitions into the TPU infeed."
    ),
    packages=find_packages(include=["tensorflowonspark_tpu*"]),
    python_requires=">=3.10",
    install_requires=["cloudpickle", "numpy"],
    extras_require={
        "tpu": ["jax", "optax", "orbax-checkpoint"],
        "spark": ["pyspark>=3.0"],
        # remote record IO / checkpoints on gs:// (other schemes: install
        # the matching fsspec driver, e.g. s3fs, pyarrow for hdfs)
        "fs": ["fsspec", "gcsfs"],
    },
    entry_points={
        "console_scripts": [
            # parity: the reference's spark-submit Inference.scala CLI
            "tfos-inference=tensorflowonspark_tpu.inference:main",
            # online serving (docs/serving.md) — no reference equivalent
            "tfos-serve=tensorflowonspark_tpu.serving.server:main",
            # live cluster view (docs/observability.md)
            "tfos-top=tensorflowonspark_tpu.obs.top:main",
            # flight-recorder dump assembly (docs/telemetry.md)
            "tfos-postmortem=tensorflowonspark_tpu.obs.postmortem:main",
        ],
    },
)
