#!/bin/bash
# Test entry point (parity: reference test/run_tests.sh, which boots a
# local Spark Standalone cluster before `unittest discover`).
#
# The equivalent multi-process fixture here is built in: LocalEngine
# starts real executor *processes* (engine.py), and multi-chip sharding
# runs on a virtual 8-device CPU mesh (tests/conftest.py) — so no
# external daemons are needed.  With pyspark installed, the same suite
# exercises the SparkEngine adapters automatically where applicable.
set -euo pipefail
cd "$(dirname "$0")/.."

# build the native library if a toolchain is present (tests fall back to
# the pure-python recordio/queue implementations without it)
if command -v g++ >/dev/null 2>&1; then
  make -C native >/dev/null || echo "native build failed; using python fallbacks"
fi

exec python -m pytest tests/ -q "$@"
