// Native TFRecord IO + tf.train.Example wire codec.
//
// TPU-native replacement for the reference's vendored tensorflow-hadoop
// jar (record-level TFRecord IO, reference lib/tensorflow-hadoop-1.0-
// SNAPSHOT.jar used at dfutil.py:39-41) and the JVM Example marshalling
// (DFUtil.scala:119-258): a small C library exposed to Python via ctypes.
//
// File format (TFRecord):
//   uint64le length
//   uint32le masked_crc32c(length bytes)
//   byte     data[length]
//   uint32le masked_crc32c(data)
// mask(crc) = ((crc >> 15) | (crc << 17)) + 0xa282ead8
//
// The Example protobuf schema is tiny and stable; the encoder/decoder
// below speaks raw proto wire format (varint + length-delimited) so no
// libprotobuf link is needed:
//   Example       { Features features = 1; }
//   Features      { map<string, Feature> feature = 1; }
//   Feature       { oneof { BytesList b = 1; FloatList f = 2; Int64List i = 3; } }
//   BytesList     { repeated bytes value = 1; }
//   FloatList     { repeated float value = 1 [packed]; }
//   Int64List     { repeated int64 value = 1 [packed]; }

#include <cstdint>
#include <cstdio>
#include <cstdlib>
#include <cstring>
#include <string>
#include <vector>

// ---------------------------------------------------------------------------
// crc32c (Castagnoli), slicing-by-8
// ---------------------------------------------------------------------------

static uint32_t kCrcTable[8][256];
static bool kCrcInit = false;

static void crc_init() {
  if (kCrcInit) return;
  const uint32_t poly = 0x82f63b78u;  // reflected CRC-32C
  for (uint32_t i = 0; i < 256; i++) {
    uint32_t c = i;
    for (int k = 0; k < 8; k++) c = (c & 1) ? (poly ^ (c >> 1)) : (c >> 1);
    kCrcTable[0][i] = c;
  }
  for (uint32_t i = 0; i < 256; i++) {
    uint32_t c = kCrcTable[0][i];
    for (int s = 1; s < 8; s++) {
      c = kCrcTable[0][c & 0xff] ^ (c >> 8);
      kCrcTable[s][i] = c;
    }
  }
  kCrcInit = true;
}

static uint32_t crc32c(const uint8_t* p, size_t n) {
  crc_init();
  uint32_t c = 0xffffffffu;
  while (n >= 8) {
    uint64_t w;
    memcpy(&w, p, 8);
    w ^= c;  // little-endian host assumed (x86/arm64)
    c = kCrcTable[7][w & 0xff] ^ kCrcTable[6][(w >> 8) & 0xff] ^
        kCrcTable[5][(w >> 16) & 0xff] ^ kCrcTable[4][(w >> 24) & 0xff] ^
        kCrcTable[3][(w >> 32) & 0xff] ^ kCrcTable[2][(w >> 40) & 0xff] ^
        kCrcTable[1][(w >> 48) & 0xff] ^ kCrcTable[0][(w >> 56) & 0xff];
    p += 8;
    n -= 8;
  }
  while (n--) c = kCrcTable[0][(c ^ *p++) & 0xff] ^ (c >> 8);
  return c ^ 0xffffffffu;
}

static uint32_t masked_crc(const uint8_t* p, size_t n) {
  uint32_t crc = crc32c(p, n);
  return ((crc >> 15) | (crc << 17)) + 0xa282ead8u;
}

extern "C" {

// ---------------------------------------------------------------------------
// Writer
// ---------------------------------------------------------------------------

struct TFRWriter {
  FILE* f;
};

TFRWriter* tfr_writer_open(const char* path) {
  FILE* f = fopen(path, "wb");
  if (!f) return nullptr;
  auto* w = new TFRWriter{f};
  setvbuf(f, nullptr, _IOFBF, 1 << 20);
  return w;
}

int tfr_writer_write(TFRWriter* w, const uint8_t* data, uint64_t len) {
  uint8_t header[12];
  memcpy(header, &len, 8);
  uint32_t lcrc = masked_crc(header, 8);
  memcpy(header + 8, &lcrc, 4);
  if (fwrite(header, 1, 12, w->f) != 12) return -1;
  if (len && fwrite(data, 1, len, w->f) != len) return -1;
  uint32_t dcrc = masked_crc(data, len);
  if (fwrite(&dcrc, 1, 4, w->f) != 4) return -1;
  return 0;
}

int tfr_writer_close(TFRWriter* w) {
  int rc = fclose(w->f);
  delete w;
  return rc;
}

// ---------------------------------------------------------------------------
// Reader
// ---------------------------------------------------------------------------

struct TFRReader {
  FILE* f;
  std::vector<uint8_t> buf;
};

TFRReader* tfr_reader_open(const char* path) {
  FILE* f = fopen(path, "rb");
  if (!f) return nullptr;
  auto* r = new TFRReader{f, {}};
  setvbuf(f, nullptr, _IOFBF, 1 << 20);
  return r;
}

// Returns record length (>= 0, may be 0 for an empty record) and sets
// *out to an internal buffer valid until the next call; -1 at clean EOF;
// < -1 on truncation/corruption.
int64_t tfr_reader_next(TFRReader* r, const uint8_t** out) {
  uint8_t header[12];
  size_t got = fread(header, 1, 12, r->f);
  if (got == 0) return -1;  // clean EOF
  if (got != 12) return -2;
  uint64_t len;
  memcpy(&len, header, 8);
  uint32_t lcrc;
  memcpy(&lcrc, header + 8, 4);
  if (masked_crc(header, 8) != lcrc) return -3;
  if (len > (1ull << 34)) return -4;  // sanity: >16GB record
  r->buf.resize(len);
  if (len && fread(r->buf.data(), 1, len, r->f) != len) return -5;
  uint32_t dcrc;
  if (fread(&dcrc, 1, 4, r->f) != 4) return -6;
  if (masked_crc(r->buf.data(), len) != dcrc) return -7;
  *out = r->buf.data();
  return (int64_t)len;
}

int tfr_reader_close(TFRReader* r) {
  int rc = fclose(r->f);
  delete r;
  return rc;
}

// ---------------------------------------------------------------------------
// Memory-buffer variants: same framing over bytes owned by the caller.
// Lets Python stream remote objects (gs://, hdfs://, s3://) through
// fsspec while this library still does all framing + crc work.
// ---------------------------------------------------------------------------

struct TFRMemWriter {
  std::string out;
};

TFRMemWriter* tfr_mem_writer_new() { return new TFRMemWriter(); }

int tfr_mem_writer_write(TFRMemWriter* w, const uint8_t* data, uint64_t len) {
  uint8_t header[12];
  memcpy(header, &len, 8);
  uint32_t lcrc = masked_crc(header, 8);
  memcpy(header + 8, &lcrc, 4);
  w->out.append((const char*)header, 12);
  if (len) w->out.append((const char*)data, len);
  uint32_t dcrc = masked_crc(data, len);
  w->out.append((const char*)&dcrc, 4);
  return 0;
}

// Buffer valid until the next write/free; *n receives the size.
const uint8_t* tfr_mem_writer_data(TFRMemWriter* w, uint64_t* n) {
  *n = w->out.size();
  return (const uint8_t*)w->out.data();
}

void tfr_mem_writer_clear(TFRMemWriter* w) { w->out.clear(); }

void tfr_mem_writer_free(TFRMemWriter* w) { delete w; }

struct TFRMemReader {
  const uint8_t* data;  // caller-owned; must outlive the reader
  uint64_t len;
  uint64_t pos;
};

TFRMemReader* tfr_mem_reader_new(const uint8_t* data, uint64_t len) {
  return new TFRMemReader{data, len, 0};
}

// Same contract as tfr_reader_next; *out points into the caller's buffer.
int64_t tfr_mem_reader_next(TFRMemReader* r, const uint8_t** out) {
  if (r->pos == r->len) return -1;  // clean EOF
  if (r->len - r->pos < 12) return -2;
  const uint8_t* header = r->data + r->pos;
  uint64_t len;
  memcpy(&len, header, 8);
  uint32_t lcrc;
  memcpy(&lcrc, header + 8, 4);
  if (masked_crc(header, 8) != lcrc) return -3;
  if (len > (1ull << 34)) return -4;
  if (r->len - r->pos - 12 < len + 4) return -5;
  const uint8_t* body = header + 12;
  uint32_t dcrc;
  memcpy(&dcrc, body + len, 4);
  if (masked_crc(body, len) != dcrc) return -7;
  r->pos += 12 + len + 4;
  *out = body;
  return (int64_t)len;
}

void tfr_mem_reader_free(TFRMemReader* r) { delete r; }

// ---------------------------------------------------------------------------
// Proto wire helpers
// ---------------------------------------------------------------------------

static void put_varint(std::string& s, uint64_t v) {
  while (v >= 0x80) {
    s.push_back((char)((v & 0x7f) | 0x80));
    v >>= 7;
  }
  s.push_back((char)v);
}

static bool get_varint(const uint8_t*& p, const uint8_t* end, uint64_t* v) {
  uint64_t r = 0;
  int shift = 0;
  while (p < end && shift < 64) {
    uint8_t b = *p++;
    r |= (uint64_t)(b & 0x7f) << shift;
    if (!(b & 0x80)) {
      *v = r;
      return true;
    }
    shift += 7;
  }
  return false;
}

static void put_tag(std::string& s, int field, int wire) {
  put_varint(s, (uint64_t)(field << 3 | wire));
}

static void put_len_delim(std::string& s, int field, const std::string& payload) {
  put_tag(s, field, 2);
  put_varint(s, payload.size());
  s.append(payload);
}

// ---------------------------------------------------------------------------
// Example encoder
//
// The builder API assembles one Example from typed feature columns.
// ---------------------------------------------------------------------------

struct ExampleBuilder {
  std::string features;  // serialized map entries
};

ExampleBuilder* exb_new() { return new ExampleBuilder(); }
void exb_free(ExampleBuilder* b) { delete b; }

static void exb_add_entry(ExampleBuilder* b, const char* name,
                          const std::string& feature) {
  std::string entry;
  std::string key(name);
  put_tag(entry, 1, 2);
  put_varint(entry, key.size());
  entry.append(key);
  put_len_delim(entry, 2, feature);
  put_len_delim(b->features, 1, entry);
}

void exb_add_int64(ExampleBuilder* b, const char* name, const int64_t* vals,
                   int n) {
  std::string packed;
  for (int i = 0; i < n; i++) put_varint(packed, (uint64_t)vals[i]);
  std::string list;
  put_len_delim(list, 1, packed);
  std::string feature;
  put_len_delim(feature, 3, list);  // Feature.int64_list = 3
  exb_add_entry(b, name, feature);
}

void exb_add_float(ExampleBuilder* b, const char* name, const float* vals,
                   int n) {
  std::string packed((const char*)vals, (size_t)n * 4);
  std::string list;
  put_len_delim(list, 1, packed);
  std::string feature;
  put_len_delim(feature, 2, list);  // Feature.float_list = 2
  exb_add_entry(b, name, feature);
}

void exb_add_bytes(ExampleBuilder* b, const char* name, const uint8_t** vals,
                   const uint64_t* lens, int n) {
  std::string list;
  for (int i = 0; i < n; i++) {
    std::string v((const char*)vals[i], lens[i]);
    put_len_delim(list, 1, v);
  }
  std::string feature;
  put_len_delim(feature, 1, list);  // Feature.bytes_list = 1
  exb_add_entry(b, name, feature);
}

// Serialize Example into caller-readable buffer (valid until next call/free).
const uint8_t* exb_serialize(ExampleBuilder* b, uint64_t* out_len) {
  static thread_local std::string out;
  out.clear();
  put_len_delim(out, 1, b->features);  // Example.features = 1
  *out_len = out.size();
  b->features.clear();
  return (const uint8_t*)out.data();
}

// ---------------------------------------------------------------------------
// Example decoder: parses a serialized Example into a flat feature table
// the Python side walks via accessors.
// ---------------------------------------------------------------------------

struct DecodedFeature {
  std::string name;
  int kind;  // 1=bytes 2=float 3=int64
  std::vector<std::string> bytes_vals;
  std::vector<float> float_vals;
  std::vector<int64_t> int64_vals;
};

struct ExampleDecoder {
  std::vector<DecodedFeature> feats;
};

static bool parse_feature(const uint8_t* p, const uint8_t* end,
                          DecodedFeature* f) {
  while (p < end) {
    uint64_t tag;
    if (!get_varint(p, end, &tag)) return false;
    int field = (int)(tag >> 3);
    uint64_t len;
    if (!get_varint(p, end, &len)) return false;
    const uint8_t* lend = p + len;
    if (lend > end) return false;
    // field ∈ {1,2,3} → the list message; inside: field 1 = values
    f->kind = field;
    const uint8_t* q = p;
    while (q < lend) {
      uint64_t vtag;
      if (!get_varint(q, lend, &vtag)) return false;
      int vfield = (int)(vtag >> 3);
      int vwire = (int)(vtag & 7);
      if (vfield != 1) return false;
      if (field == 1) {  // bytes values, wire 2
        uint64_t blen;
        if (!get_varint(q, lend, &blen)) return false;
        if (q + blen > lend) return false;
        f->bytes_vals.emplace_back((const char*)q, blen);
        q += blen;
      } else if (field == 2) {  // floats: packed (wire 2) or single (wire 5)
        if (vwire == 2) {
          uint64_t blen;
          if (!get_varint(q, lend, &blen)) return false;
          if (q + blen > lend || blen % 4) return false;
          size_t cnt = blen / 4;
          size_t base = f->float_vals.size();
          f->float_vals.resize(base + cnt);
          memcpy(f->float_vals.data() + base, q, blen);
          q += blen;
        } else if (vwire == 5) {
          if (q + 4 > lend) return false;
          float v;
          memcpy(&v, q, 4);
          f->float_vals.push_back(v);
          q += 4;
        } else {
          return false;
        }
      } else if (field == 3) {  // int64: packed or single varints
        if (vwire == 2) {
          uint64_t blen;
          if (!get_varint(q, lend, &blen)) return false;
          const uint8_t* vend = q + blen;
          if (vend > lend) return false;
          while (q < vend) {
            uint64_t v;
            if (!get_varint(q, vend, &v)) return false;
            f->int64_vals.push_back((int64_t)v);
          }
        } else if (vwire == 0) {
          uint64_t v;
          if (!get_varint(q, lend, &v)) return false;
          f->int64_vals.push_back((int64_t)v);
        } else {
          return false;
        }
      } else {
        return false;
      }
    }
    p = lend;
  }
  return true;
}

ExampleDecoder* exd_parse(const uint8_t* data, uint64_t len) {
  auto* d = new ExampleDecoder();
  const uint8_t* p = data;
  const uint8_t* end = data + len;
  while (p < end) {
    uint64_t tag;
    if (!get_varint(p, end, &tag)) goto fail;
    {
      int field = (int)(tag >> 3);
      int wire = (int)(tag & 7);
      if (wire != 2) goto fail;
      uint64_t len2;
      if (!get_varint(p, end, &len2)) goto fail;
      const uint8_t* fend = p + len2;
      if (fend > end) goto fail;
      if (field == 1) {  // Features
        const uint8_t* q = p;
        while (q < fend) {
          uint64_t etag;
          if (!get_varint(q, fend, &etag)) goto fail;
          if ((etag & 7) != 2 || (etag >> 3) != 1) goto fail;
          uint64_t elen;
          if (!get_varint(q, fend, &elen)) goto fail;
          const uint8_t* eend = q + elen;
          if (eend > fend) goto fail;
          DecodedFeature feat;
          feat.kind = 0;
          // map entry: key=1 (string), value=2 (Feature)
          const uint8_t* m = q;
          while (m < eend) {
            uint64_t mtag;
            if (!get_varint(m, eend, &mtag)) goto fail;
            uint64_t mlen;
            if (!get_varint(m, eend, &mlen)) goto fail;
            if (m + mlen > eend) goto fail;
            if ((mtag >> 3) == 1) {
              feat.name.assign((const char*)m, mlen);
            } else if ((mtag >> 3) == 2) {
              if (!parse_feature(m, m + mlen, &feat)) goto fail;
            }
            m += mlen;
          }
          d->feats.push_back(std::move(feat));
          q = eend;
        }
      }
      p = fend;
    }
  }
  return d;
fail:
  delete d;
  return nullptr;
}

void exd_free(ExampleDecoder* d) { delete d; }

int exd_num_features(ExampleDecoder* d) { return (int)d->feats.size(); }

const char* exd_name(ExampleDecoder* d, int i) {
  return d->feats[i].name.c_str();
}

int exd_kind(ExampleDecoder* d, int i) { return d->feats[i].kind; }

int64_t exd_value_count(ExampleDecoder* d, int i) {
  auto& f = d->feats[i];
  switch (f.kind) {
    case 1: return (int64_t)f.bytes_vals.size();
    case 2: return (int64_t)f.float_vals.size();
    case 3: return (int64_t)f.int64_vals.size();
  }
  return 0;
}

const float* exd_floats(ExampleDecoder* d, int i) {
  return d->feats[i].float_vals.data();
}

const int64_t* exd_int64s(ExampleDecoder* d, int i) {
  return d->feats[i].int64_vals.data();
}

const uint8_t* exd_bytes(ExampleDecoder* d, int i, int j, uint64_t* len) {
  auto& v = d->feats[i].bytes_vals[j];
  *len = v.size();
  return (const uint8_t*)v.data();
}

// crc utility exposed for tests
uint32_t tfr_crc32c(const uint8_t* p, uint64_t n) { return crc32c(p, n); }

}  // extern "C"
