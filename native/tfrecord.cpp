// Native TFRecord IO + tf.train.Example wire codec.
//
// TPU-native replacement for the reference's vendored tensorflow-hadoop
// jar (record-level TFRecord IO, reference lib/tensorflow-hadoop-1.0-
// SNAPSHOT.jar used at dfutil.py:39-41) and the JVM Example marshalling
// (DFUtil.scala:119-258): a small C library exposed to Python via ctypes.
//
// File format (TFRecord):
//   uint64le length
//   uint32le masked_crc32c(length bytes)
//   byte     data[length]
//   uint32le masked_crc32c(data)
// mask(crc) = ((crc >> 15) | (crc << 17)) + 0xa282ead8
//
// The Example protobuf schema is tiny and stable; the encoder/decoder
// below speaks raw proto wire format (varint + length-delimited) so no
// libprotobuf link is needed:
//   Example       { Features features = 1; }
//   Features      { map<string, Feature> feature = 1; }
//   Feature       { oneof { BytesList b = 1; FloatList f = 2; Int64List i = 3; } }
//   BytesList     { repeated bytes value = 1; }
//   FloatList     { repeated float value = 1 [packed]; }
//   Int64List     { repeated int64 value = 1 [packed]; }

#include <cstdint>
#include <cstdio>
#include <cstdlib>
#include <cstring>
#include <string>
#include <vector>

// ---------------------------------------------------------------------------
// crc32c (Castagnoli), slicing-by-8
// ---------------------------------------------------------------------------

static uint32_t kCrcTable[8][256];
static bool kCrcInit = false;

static void crc_init() {
  if (kCrcInit) return;
  const uint32_t poly = 0x82f63b78u;  // reflected CRC-32C
  for (uint32_t i = 0; i < 256; i++) {
    uint32_t c = i;
    for (int k = 0; k < 8; k++) c = (c & 1) ? (poly ^ (c >> 1)) : (c >> 1);
    kCrcTable[0][i] = c;
  }
  for (uint32_t i = 0; i < 256; i++) {
    uint32_t c = kCrcTable[0][i];
    for (int s = 1; s < 8; s++) {
      c = kCrcTable[0][c & 0xff] ^ (c >> 8);
      kCrcTable[s][i] = c;
    }
  }
  kCrcInit = true;
}

static uint32_t crc32c(const uint8_t* p, size_t n) {
  crc_init();
  uint32_t c = 0xffffffffu;
  while (n >= 8) {
    uint64_t w;
    memcpy(&w, p, 8);
    w ^= c;  // little-endian host assumed (x86/arm64)
    c = kCrcTable[7][w & 0xff] ^ kCrcTable[6][(w >> 8) & 0xff] ^
        kCrcTable[5][(w >> 16) & 0xff] ^ kCrcTable[4][(w >> 24) & 0xff] ^
        kCrcTable[3][(w >> 32) & 0xff] ^ kCrcTable[2][(w >> 40) & 0xff] ^
        kCrcTable[1][(w >> 48) & 0xff] ^ kCrcTable[0][(w >> 56) & 0xff];
    p += 8;
    n -= 8;
  }
  while (n--) c = kCrcTable[0][(c ^ *p++) & 0xff] ^ (c >> 8);
  return c ^ 0xffffffffu;
}

static uint32_t masked_crc(const uint8_t* p, size_t n) {
  uint32_t crc = crc32c(p, n);
  return ((crc >> 15) | (crc << 17)) + 0xa282ead8u;
}

extern "C" {

// ---------------------------------------------------------------------------
// Writer
// ---------------------------------------------------------------------------

struct TFRWriter {
  FILE* f;
};

TFRWriter* tfr_writer_open(const char* path) {
  FILE* f = fopen(path, "wb");
  if (!f) return nullptr;
  auto* w = new TFRWriter{f};
  setvbuf(f, nullptr, _IOFBF, 1 << 20);
  return w;
}

int tfr_writer_write(TFRWriter* w, const uint8_t* data, uint64_t len) {
  uint8_t header[12];
  memcpy(header, &len, 8);
  uint32_t lcrc = masked_crc(header, 8);
  memcpy(header + 8, &lcrc, 4);
  if (fwrite(header, 1, 12, w->f) != 12) return -1;
  if (len && fwrite(data, 1, len, w->f) != len) return -1;
  uint32_t dcrc = masked_crc(data, len);
  if (fwrite(&dcrc, 1, 4, w->f) != 4) return -1;
  return 0;
}

int tfr_writer_close(TFRWriter* w) {
  int rc = fclose(w->f);
  delete w;
  return rc;
}

// ---------------------------------------------------------------------------
// Reader
// ---------------------------------------------------------------------------

struct TFRReader {
  FILE* f;
  std::vector<uint8_t> buf;
};

TFRReader* tfr_reader_open(const char* path) {
  FILE* f = fopen(path, "rb");
  if (!f) return nullptr;
  auto* r = new TFRReader{f, {}};
  setvbuf(f, nullptr, _IOFBF, 1 << 20);
  return r;
}

// Returns record length (>= 0, may be 0 for an empty record) and sets
// *out to an internal buffer valid until the next call; -1 at clean EOF;
// < -1 on truncation/corruption.
int64_t tfr_reader_next(TFRReader* r, const uint8_t** out) {
  uint8_t header[12];
  size_t got = fread(header, 1, 12, r->f);
  if (got == 0) return -1;  // clean EOF
  if (got != 12) return -2;
  uint64_t len;
  memcpy(&len, header, 8);
  uint32_t lcrc;
  memcpy(&lcrc, header + 8, 4);
  if (masked_crc(header, 8) != lcrc) return -3;
  if (len > (1ull << 34)) return -4;  // sanity: >16GB record
  r->buf.resize(len);
  if (len && fread(r->buf.data(), 1, len, r->f) != len) return -5;
  uint32_t dcrc;
  if (fread(&dcrc, 1, 4, r->f) != 4) return -6;
  if (masked_crc(r->buf.data(), len) != dcrc) return -7;
  *out = r->buf.data();
  return (int64_t)len;
}

int tfr_reader_close(TFRReader* r) {
  int rc = fclose(r->f);
  delete r;
  return rc;
}

// ---------------------------------------------------------------------------
// Memory-buffer variants: same framing over bytes owned by the caller.
// Lets Python stream remote objects (gs://, hdfs://, s3://) through
// fsspec while this library still does all framing + crc work.
// ---------------------------------------------------------------------------

struct TFRMemWriter {
  std::string out;
};

TFRMemWriter* tfr_mem_writer_new() { return new TFRMemWriter(); }

int tfr_mem_writer_write(TFRMemWriter* w, const uint8_t* data, uint64_t len) {
  uint8_t header[12];
  memcpy(header, &len, 8);
  uint32_t lcrc = masked_crc(header, 8);
  memcpy(header + 8, &lcrc, 4);
  w->out.append((const char*)header, 12);
  if (len) w->out.append((const char*)data, len);
  uint32_t dcrc = masked_crc(data, len);
  w->out.append((const char*)&dcrc, 4);
  return 0;
}

// Buffer valid until the next write/free; *n receives the size.
const uint8_t* tfr_mem_writer_data(TFRMemWriter* w, uint64_t* n) {
  *n = w->out.size();
  return (const uint8_t*)w->out.data();
}

void tfr_mem_writer_clear(TFRMemWriter* w) { w->out.clear(); }

void tfr_mem_writer_free(TFRMemWriter* w) { delete w; }

struct TFRMemReader {
  const uint8_t* data;  // caller-owned; must outlive the reader
  uint64_t len;
  uint64_t pos;
};

TFRMemReader* tfr_mem_reader_new(const uint8_t* data, uint64_t len) {
  return new TFRMemReader{data, len, 0};
}

// Same contract as tfr_reader_next; *out points into the caller's buffer.
int64_t tfr_mem_reader_next(TFRMemReader* r, const uint8_t** out) {
  if (r->pos == r->len) return -1;  // clean EOF
  if (r->len - r->pos < 12) return -2;
  const uint8_t* header = r->data + r->pos;
  uint64_t len;
  memcpy(&len, header, 8);
  uint32_t lcrc;
  memcpy(&lcrc, header + 8, 4);
  if (masked_crc(header, 8) != lcrc) return -3;
  if (len > (1ull << 34)) return -4;
  if (r->len - r->pos - 12 < len + 4) return -5;
  const uint8_t* body = header + 12;
  uint32_t dcrc;
  memcpy(&dcrc, body + len, 4);
  if (masked_crc(body, len) != dcrc) return -7;
  r->pos += 12 + len + 4;
  *out = body;
  return (int64_t)len;
}

void tfr_mem_reader_free(TFRMemReader* r) { delete r; }

// ---------------------------------------------------------------------------
// Proto wire helpers
// ---------------------------------------------------------------------------

static void put_varint(std::string& s, uint64_t v) {
  while (v >= 0x80) {
    s.push_back((char)((v & 0x7f) | 0x80));
    v >>= 7;
  }
  s.push_back((char)v);
}

static bool get_varint(const uint8_t*& p, const uint8_t* end, uint64_t* v) {
  uint64_t r = 0;
  int shift = 0;
  while (p < end && shift < 64) {
    uint8_t b = *p++;
    r |= (uint64_t)(b & 0x7f) << shift;
    if (!(b & 0x80)) {
      *v = r;
      return true;
    }
    shift += 7;
  }
  return false;
}

static void put_tag(std::string& s, int field, int wire) {
  put_varint(s, (uint64_t)(field << 3 | wire));
}

static void put_len_delim(std::string& s, int field, const std::string& payload) {
  put_tag(s, field, 2);
  put_varint(s, payload.size());
  s.append(payload);
}

// ---------------------------------------------------------------------------
// Example encoder
//
// The builder API assembles one Example from typed feature columns.
// ---------------------------------------------------------------------------

struct ExampleBuilder {
  std::string features;  // serialized map entries
};

ExampleBuilder* exb_new() { return new ExampleBuilder(); }
void exb_free(ExampleBuilder* b) { delete b; }

static void exb_add_entry(ExampleBuilder* b, const char* name,
                          const std::string& feature) {
  std::string entry;
  std::string key(name);
  put_tag(entry, 1, 2);
  put_varint(entry, key.size());
  entry.append(key);
  put_len_delim(entry, 2, feature);
  put_len_delim(b->features, 1, entry);
}

void exb_add_int64(ExampleBuilder* b, const char* name, const int64_t* vals,
                   int n) {
  std::string packed;
  for (int i = 0; i < n; i++) put_varint(packed, (uint64_t)vals[i]);
  std::string list;
  put_len_delim(list, 1, packed);
  std::string feature;
  put_len_delim(feature, 3, list);  // Feature.int64_list = 3
  exb_add_entry(b, name, feature);
}

void exb_add_float(ExampleBuilder* b, const char* name, const float* vals,
                   int n) {
  std::string packed((const char*)vals, (size_t)n * 4);
  std::string list;
  put_len_delim(list, 1, packed);
  std::string feature;
  put_len_delim(feature, 2, list);  // Feature.float_list = 2
  exb_add_entry(b, name, feature);
}

void exb_add_bytes(ExampleBuilder* b, const char* name, const uint8_t** vals,
                   const uint64_t* lens, int n) {
  std::string list;
  for (int i = 0; i < n; i++) {
    std::string v((const char*)vals[i], lens[i]);
    put_len_delim(list, 1, v);
  }
  std::string feature;
  put_len_delim(feature, 1, list);  // Feature.bytes_list = 1
  exb_add_entry(b, name, feature);
}

// Serialize Example into caller-readable buffer (valid until next call/free).
const uint8_t* exb_serialize(ExampleBuilder* b, uint64_t* out_len) {
  static thread_local std::string out;
  out.clear();
  put_len_delim(out, 1, b->features);  // Example.features = 1
  *out_len = out.size();
  b->features.clear();
  return (const uint8_t*)out.data();
}

// ---------------------------------------------------------------------------
// Example decoder: parses a serialized Example into a flat feature table
// the Python side walks via accessors.
// ---------------------------------------------------------------------------

struct DecodedFeature {
  std::string name;
  int kind;  // 1=bytes 2=float 3=int64
  std::vector<std::string> bytes_vals;
  std::vector<float> float_vals;
  std::vector<int64_t> int64_vals;
};

struct ExampleDecoder {
  std::vector<DecodedFeature> feats;
};

static bool parse_feature(const uint8_t* p, const uint8_t* end,
                          DecodedFeature* f) {
  while (p < end) {
    uint64_t tag;
    if (!get_varint(p, end, &tag)) return false;
    int field = (int)(tag >> 3);
    uint64_t len;
    if (!get_varint(p, end, &len)) return false;
    if (len > (uint64_t)(end - p)) return false;
    const uint8_t* lend = p + len;
    // field ∈ {1,2,3} → the list message; inside: field 1 = values
    f->kind = field;
    const uint8_t* q = p;
    while (q < lend) {
      uint64_t vtag;
      if (!get_varint(q, lend, &vtag)) return false;
      int vfield = (int)(vtag >> 3);
      int vwire = (int)(vtag & 7);
      if (vfield != 1) return false;
      if (field == 1) {  // bytes values, wire 2
        uint64_t blen;
        if (!get_varint(q, lend, &blen)) return false;
        if (blen > (uint64_t)(lend - q)) return false;
        f->bytes_vals.emplace_back((const char*)q, blen);
        q += blen;
      } else if (field == 2) {  // floats: packed (wire 2) or single (wire 5)
        if (vwire == 2) {
          uint64_t blen;
          if (!get_varint(q, lend, &blen)) return false;
          if (blen > (uint64_t)(lend - q) || blen % 4) return false;
          size_t cnt = blen / 4;
          size_t base = f->float_vals.size();
          f->float_vals.resize(base + cnt);
          memcpy(f->float_vals.data() + base, q, blen);
          q += blen;
        } else if (vwire == 5) {
          if (q + 4 > lend) return false;
          float v;
          memcpy(&v, q, 4);
          f->float_vals.push_back(v);
          q += 4;
        } else {
          return false;
        }
      } else if (field == 3) {  // int64: packed or single varints
        if (vwire == 2) {
          uint64_t blen;
          if (!get_varint(q, lend, &blen)) return false;
          if (blen > (uint64_t)(lend - q)) return false;
          const uint8_t* vend = q + blen;
          while (q < vend) {
            uint64_t v;
            if (!get_varint(q, vend, &v)) return false;
            f->int64_vals.push_back((int64_t)v);
          }
        } else if (vwire == 0) {
          uint64_t v;
          if (!get_varint(q, lend, &v)) return false;
          f->int64_vals.push_back((int64_t)v);
        } else {
          return false;
        }
      } else {
        return false;
      }
    }
    p = lend;
  }
  return true;
}

ExampleDecoder* exd_parse(const uint8_t* data, uint64_t len) {
  auto* d = new ExampleDecoder();
  const uint8_t* p = data;
  const uint8_t* end = data + len;
  while (p < end) {
    uint64_t tag;
    if (!get_varint(p, end, &tag)) goto fail;
    {
      int field = (int)(tag >> 3);
      int wire = (int)(tag & 7);
      if (wire != 2) goto fail;
      uint64_t len2;
      if (!get_varint(p, end, &len2)) goto fail;
      if (len2 > (uint64_t)(end - p)) goto fail;
      const uint8_t* fend = p + len2;
      if (field == 1) {  // Features
        const uint8_t* q = p;
        while (q < fend) {
          uint64_t etag;
          if (!get_varint(q, fend, &etag)) goto fail;
          if ((etag & 7) != 2 || (etag >> 3) != 1) goto fail;
          uint64_t elen;
          if (!get_varint(q, fend, &elen)) goto fail;
          if (elen > (uint64_t)(fend - q)) goto fail;
          const uint8_t* eend = q + elen;
          DecodedFeature feat;
          feat.kind = 0;
          // map entry: key=1 (string), value=2 (Feature)
          const uint8_t* m = q;
          while (m < eend) {
            uint64_t mtag;
            if (!get_varint(m, eend, &mtag)) goto fail;
            uint64_t mlen;
            if (!get_varint(m, eend, &mlen)) goto fail;
            if (mlen > (uint64_t)(eend - m)) goto fail;
            if ((mtag >> 3) == 1) {
              feat.name.assign((const char*)m, mlen);
            } else if ((mtag >> 3) == 2) {
              if (!parse_feature(m, m + mlen, &feat)) goto fail;
            }
            m += mlen;
          }
          d->feats.push_back(std::move(feat));
          q = eend;
        }
      }
      p = fend;
    }
  }
  return d;
fail:
  delete d;
  return nullptr;
}

void exd_free(ExampleDecoder* d) { delete d; }

int exd_num_features(ExampleDecoder* d) { return (int)d->feats.size(); }

const char* exd_name(ExampleDecoder* d, int i) {
  return d->feats[i].name.c_str();
}

int exd_kind(ExampleDecoder* d, int i) { return d->feats[i].kind; }

int64_t exd_value_count(ExampleDecoder* d, int i) {
  auto& f = d->feats[i];
  switch (f.kind) {
    case 1: return (int64_t)f.bytes_vals.size();
    case 2: return (int64_t)f.float_vals.size();
    case 3: return (int64_t)f.int64_vals.size();
  }
  return 0;
}

const float* exd_floats(ExampleDecoder* d, int i) {
  return d->feats[i].float_vals.data();
}

const int64_t* exd_int64s(ExampleDecoder* d, int i) {
  return d->feats[i].int64_vals.data();
}

const uint8_t* exd_bytes(ExampleDecoder* d, int i, int j, uint64_t* len) {
  auto& v = d->feats[i].bytes_vals[j];
  *len = v.size();
  return (const uint8_t*)v.data();
}

// ---------------------------------------------------------------------------
// Columnar batch loader: read an entire TFRecord stream and decode every
// Example straight into dense per-feature columns in one C pass — the
// bulk-load analogue of the reference's Hadoop TFRecordFileInputFormat +
// per-row DFUtil.fromTFExample (DFUtil.scala:119-184), shaped for numpy:
// no per-value Python objects, one buffer per feature.
//
// Schema is taken from the first record (names, kinds, value counts);
// every later record must match it exactly.  A mismatch (ragged widths,
// missing/extra features, kind drift) sets an error and the Python side
// falls back to per-row decoding.
// ---------------------------------------------------------------------------

struct ColumnarBatch {
  std::vector<std::string> names;
  std::vector<int> kinds;       // 1=bytes 2=float 3=int64
  std::vector<int64_t> widths;  // values per record per feature
  int64_t nrows = 0;
  std::vector<std::vector<float>> fcols;
  std::vector<std::vector<int64_t>> icols;
  std::vector<std::string> bblobs;            // bytes columns: packed blob
  std::vector<std::vector<uint64_t>> boffs;   // and offsets (count*width+1)
  std::string error;
};

// Parse one Feature submessage, appending values into column slot `c`.
// Returns the number of values appended, or -1 on malformed input.
static int64_t parse_feature_into(ColumnarBatch* cb, int c, int* kind,
                                  const uint8_t* p, const uint8_t* end) {
  int64_t count = 0;
  while (p < end) {
    uint64_t tag;
    if (!get_varint(p, end, &tag)) return -1;
    int field = (int)(tag >> 3);
    uint64_t len;
    if (!get_varint(p, end, &len)) return -1;
    if (len > (uint64_t)(end - p)) return -1;
    const uint8_t* lend = p + len;
    if (*kind != 0 && *kind != field) return -1;  // mixed-kind Feature
    *kind = field;
    const uint8_t* q = p;
    while (q < lend) {
      uint64_t vtag;
      if (!get_varint(q, lend, &vtag)) return -1;
      if ((int)(vtag >> 3) != 1) return -1;
      int vwire = (int)(vtag & 7);
      if (field == 1) {  // bytes
        uint64_t blen;
        if (vwire != 2 || !get_varint(q, lend, &blen)) return -1;
        if (blen > (uint64_t)(lend - q)) return -1;
        cb->bblobs[c].append((const char*)q, blen);
        cb->boffs[c].push_back(cb->bblobs[c].size());
        q += blen;
        count++;
      } else if (field == 2) {  // float: packed or single fixed32
        if (vwire == 2) {
          uint64_t blen;
          if (!get_varint(q, lend, &blen)) return -1;
          if (blen > (uint64_t)(lend - q) || blen % 4) return -1;
          size_t cnt = blen / 4;
          auto& col = cb->fcols[c];
          size_t base = col.size();
          col.resize(base + cnt);
          memcpy(col.data() + base, q, blen);
          q += blen;
          count += (int64_t)cnt;
        } else if (vwire == 5) {
          if (q + 4 > lend) return -1;
          float v;
          memcpy(&v, q, 4);
          cb->fcols[c].push_back(v);
          q += 4;
          count++;
        } else {
          return -1;
        }
      } else if (field == 3) {  // int64: packed or single varint
        if (vwire == 2) {
          uint64_t blen;
          if (!get_varint(q, lend, &blen)) return -1;
          if (blen > (uint64_t)(lend - q)) return -1;
          const uint8_t* vend = q + blen;
          while (q < vend) {
            uint64_t v;
            if (!get_varint(q, vend, &v)) return -1;
            cb->icols[c].push_back((int64_t)v);
            count++;
          }
        } else if (vwire == 0) {
          uint64_t v;
          if (!get_varint(q, lend, &v)) return -1;
          cb->icols[c].push_back((int64_t)v);
          count++;
        } else {
          return -1;
        }
      } else {
        return -1;
      }
    }
    p = lend;
  }
  return count;
}

static int colb_index_of(ColumnarBatch* cb, const char* name, size_t len) {
  for (size_t i = 0; i < cb->names.size(); i++)
    if (cb->names[i].size() == len && !memcmp(cb->names[i].data(), name, len))
      return (int)i;
  return -1;
}

// Decode one Example record into the batch; grows the schema on row 0.
static bool colb_add_record(ColumnarBatch* cb, const uint8_t* data,
                            uint64_t len) {
  bool first = (cb->nrows == 0);
  std::vector<uint8_t> seen(cb->names.size(), 0);
  const uint8_t* p = data;
  const uint8_t* end = data + len;
  while (p < end) {
    uint64_t tag;
    if (!get_varint(p, end, &tag)) return false;
    if ((tag & 7) != 2) return false;
    uint64_t len2;
    if (!get_varint(p, end, &len2)) return false;
    if (len2 > (uint64_t)(end - p)) return false;
    const uint8_t* fend = p + len2;
    if ((int)(tag >> 3) == 1) {  // Features
      const uint8_t* q = p;
      while (q < fend) {
        uint64_t etag;
        if (!get_varint(q, fend, &etag)) return false;
        if ((etag & 7) != 2 || (etag >> 3) != 1) return false;
        uint64_t elen;
        if (!get_varint(q, fend, &elen)) return false;
        if (elen > (uint64_t)(fend - q)) return false;
        const uint8_t* eend = q + elen;
        // map entry: key=1 (string), value=2 (Feature)
        const char* kname = nullptr;
        size_t klen = 0;
        const uint8_t* fmsg = nullptr;
        uint64_t fmlen = 0;
        const uint8_t* m = q;
        while (m < eend) {
          uint64_t mtag;
          if (!get_varint(m, eend, &mtag)) return false;
          uint64_t mlen;
          if (!get_varint(m, eend, &mlen)) return false;
          if (mlen > (uint64_t)(eend - m)) return false;
          if ((mtag >> 3) == 1) {
            kname = (const char*)m;
            klen = mlen;
          } else if ((mtag >> 3) == 2) {
            fmsg = m;
            fmlen = mlen;
          }
          m += mlen;
        }
        if (!kname || !fmsg) return false;
        int c = colb_index_of(cb, kname, klen);
        if (c < 0) {
          if (!first) {
            cb->error = "feature '" + std::string(kname, klen) +
                        "' absent from the first record";
            return false;
          }
          c = (int)cb->names.size();
          cb->names.emplace_back(kname, klen);
          cb->kinds.push_back(0);
          cb->widths.push_back(-1);
          cb->fcols.emplace_back();
          cb->icols.emplace_back();
          cb->bblobs.emplace_back();
          cb->boffs.emplace_back(1, 0);
          seen.push_back(0);
        }
        // a repeated key would append a second run of values to the same
        // column and shift every later row — corrupt, not mergeable
        if (seen[c]) {
          cb->error = "feature '" + cb->names[c] + "' repeated in a record";
          return false;
        }
        seen[c] = 1;
        int kind = 0;
        int64_t cnt = parse_feature_into(cb, c, &kind, fmsg, fmsg + fmlen);
        if (cnt < 0) return false;
        if (first) {
          cb->kinds[c] = kind;
          cb->widths[c] = cnt;
        } else if (cb->kinds[c] != kind) {
          cb->error = "feature '" + cb->names[c] + "' changed kind";
          return false;
        } else if (cb->widths[c] != cnt) {
          cb->error = "feature '" + cb->names[c] + "' is ragged";
          return false;
        }
        q = eend;
      }
    }
    p = fend;
  }
  if (!first)
    for (size_t i = 0; i < seen.size(); i++)
      if (!seen[i]) {
        cb->error = "feature '" + cb->names[i] + "' missing from a record";
        return false;
      }
  cb->nrows++;
  return true;
}

ColumnarBatch* tfr_load_columnar_mem(const uint8_t* data, uint64_t len) {
  auto* cb = new ColumnarBatch();
  TFRMemReader r{data, len, 0};
  const uint8_t* rec;
  int64_t rlen;
  while ((rlen = tfr_mem_reader_next(&r, &rec)) >= 0) {
    if (!colb_add_record(cb, rec, (uint64_t)rlen)) {
      if (cb->error.empty()) cb->error = "unparseable tf.train.Example";
      return cb;
    }
  }
  if (rlen < -1) cb->error = "corrupt TFRecord framing";
  return cb;
}

ColumnarBatch* tfr_load_columnar(const char* path) {
  auto* cb = new ColumnarBatch();
  TFRReader* r = tfr_reader_open(path);
  if (!r) {
    cb->error = "cannot open file";
    return cb;
  }
  const uint8_t* rec;
  int64_t rlen;
  while ((rlen = tfr_reader_next(r, &rec)) >= 0) {
    if (!colb_add_record(cb, rec, (uint64_t)rlen)) {
      if (cb->error.empty()) cb->error = "unparseable tf.train.Example";
      break;
    }
  }
  if (rlen < -1) cb->error = "corrupt TFRecord framing";
  tfr_reader_close(r);
  return cb;
}

int colb_ok(ColumnarBatch* cb) { return cb->error.empty() ? 1 : 0; }
const char* colb_error(ColumnarBatch* cb) { return cb->error.c_str(); }
int64_t colb_num_rows(ColumnarBatch* cb) { return cb->nrows; }
int colb_num_features(ColumnarBatch* cb) { return (int)cb->names.size(); }
const char* colb_name(ColumnarBatch* cb, int i) { return cb->names[i].c_str(); }
int colb_kind(ColumnarBatch* cb, int i) { return cb->kinds[i]; }
int64_t colb_width(ColumnarBatch* cb, int i) { return cb->widths[i]; }
const float* colb_floats(ColumnarBatch* cb, int i) {
  return cb->fcols[i].data();
}
const int64_t* colb_int64s(ColumnarBatch* cb, int i) {
  return cb->icols[i].data();
}
const uint8_t* colb_bytes_blob(ColumnarBatch* cb, int i) {
  return (const uint8_t*)cb->bblobs[i].data();
}
const uint64_t* colb_bytes_offsets(ColumnarBatch* cb, int i) {
  return cb->boffs[i].data();
}
void colb_free(ColumnarBatch* cb) { delete cb; }

// crc utility exposed for tests
uint32_t tfr_crc32c(const uint8_t* p, uint64_t n) { return crc32c(p, n); }

}  // extern "C"
