/* Native JPEG decode for the ImageNet host input path.
 *
 * Re-implements the capability the reference delegates to TF's
 * tf.image.decode_jpeg inside its input_fn (reference
 * examples/resnet/imagenet_preprocessing.py — JPEG bytes to RGB
 * tensors on the host): PIL decode measured ~700 img/s GIL-bound
 * (PERF.md); this decoder is called through ctypes (GIL released for
 * the call's duration) so a thread pool scales across cores, and it
 * uses libjpeg DCT scaling to decode directly near the target size
 * (1/2, 1/4, 1/8) instead of full resolution.
 *
 * API (ctypes, also re-exported via libtfos_native.so):
 *   tfos_jpeg_decode(buf, len, target_min, out, out_cap, &w, &h)
 *     Decode to RGB8 rows in `out`.  target_min > 0 picks the largest
 *     DCT downscale whose output still has min(w, h) >= target_min;
 *     target_min <= 0 decodes at full size.  Returns 0 on success,
 *     -1 corrupt/not-a-jpeg, -2 output buffer too small.
 */

#include <setjmp.h>
#include <stdlib.h>
#include <stddef.h>
#include <stdio.h>
#include <string.h>

#include <jpeglib.h>

struct tfos_jpeg_err {
    struct jpeg_error_mgr mgr;
    jmp_buf jump;
};

static void tfos_jpeg_error_exit(j_common_ptr cinfo) {
    struct tfos_jpeg_err *err = (struct tfos_jpeg_err *)cinfo->err;
    longjmp(err->jump, 1); /* corrupt stream: unwind, no abort()/stderr */
}

static void tfos_jpeg_silence(j_common_ptr cinfo) { (void)cinfo; }

int tfos_jpeg_decode(const unsigned char *buf, size_t len, int target_min,
                     unsigned char *out, size_t out_cap, int *out_w,
                     int *out_h) {
    struct jpeg_decompress_struct cinfo;
    struct tfos_jpeg_err err;

    cinfo.err = jpeg_std_error(&err.mgr);
    err.mgr.error_exit = tfos_jpeg_error_exit;
    err.mgr.output_message = tfos_jpeg_silence;
    if (setjmp(err.jump)) {
        jpeg_destroy_decompress(&cinfo);
        return -1;
    }
    jpeg_create_decompress(&cinfo);
    jpeg_mem_src(&cinfo, (unsigned char *)buf, (unsigned long)len);
    if (jpeg_read_header(&cinfo, TRUE) != JPEG_HEADER_OK) {
        jpeg_destroy_decompress(&cinfo);
        return -1;
    }
    cinfo.out_color_space = JCS_RGB;
    cinfo.scale_num = 1;
    cinfo.scale_denom = 1;
    if (target_min > 0) {
        /* largest denominator in {8,4,2} keeping min-dim >= target */
        unsigned d;
        unsigned minside = cinfo.image_width < cinfo.image_height
                               ? cinfo.image_width
                               : cinfo.image_height;
        for (d = 8; d > 1; d /= 2) {
            if (minside / d >= (unsigned)target_min) {
                cinfo.scale_denom = d;
                break;
            }
        }
    }
    jpeg_calc_output_dimensions(&cinfo);
    if ((size_t)cinfo.output_width * cinfo.output_height * 3 > out_cap) {
        jpeg_destroy_decompress(&cinfo);
        return -2;
    }
    jpeg_start_decompress(&cinfo);
    {
        size_t stride = (size_t)cinfo.output_width * cinfo.output_components;
        while (cinfo.output_scanline < cinfo.output_height) {
            JSAMPROW row = out + (size_t)cinfo.output_scanline * stride;
            jpeg_read_scanlines(&cinfo, &row, 1);
        }
    }
    *out_w = (int)cinfo.output_width;
    *out_h = (int)cinfo.output_height;
    jpeg_finish_decompress(&cinfo);
    /* jpeg_mem_src pads a truncated stream with a fake EOI and decodes
     * the rest as gray — only a WARNING records it.  Be strict: any
     * warning is a failure (-3); the Python layer arbitrates by
     * retrying through PIL, so weird-but-valid warning-emitting JPEGs
     * degrade to the old path instead of garbage training data. */
    if (cinfo.err->num_warnings > 0) {
        jpeg_destroy_decompress(&cinfo);
        return -3;
    }
    jpeg_destroy_decompress(&cinfo);
    return 0;
}

/* Separable half-pixel-center bilinear resize, RGB8 [h,w] -> [size,size].
 * Kept native so the whole decode+resize pipeline runs GIL-free under a
 * Python thread pool (the numpy version measured 116 img/s and held the
 * GIL — slower than PIL end to end). */
int tfos_resize_bilinear_rgb(const unsigned char *src, int h, int w,
                             unsigned char *dst, int size) {
    int x, y, c;
    if (h <= 0 || w <= 0 || size <= 0) return -1;
    /* precompute x-axis sampling */
    int *x0 = (int *)malloc(sizeof(int) * size);
    float *wx = (float *)malloc(sizeof(float) * size);
    if (!x0 || !wx) {
        if (x0) free(x0);
        if (wx) free(wx);
        return -2;
    }
    for (x = 0; x < size; x++) {
        float fx = ((float)x + 0.5f) * ((float)w / (float)size) - 0.5f;
        if (fx < 0) fx = 0;
        if (fx > (float)(w - 1)) fx = (float)(w - 1);
        int ix = (int)fx;
        if (ix > w - 2) ix = w > 1 ? w - 2 : 0;
        x0[x] = ix;
        wx[x] = w > 1 ? fx - (float)ix : 0.0f;
    }
    for (y = 0; y < size; y++) {
        float fy = ((float)y + 0.5f) * ((float)h / (float)size) - 0.5f;
        if (fy < 0) fy = 0;
        if (fy > (float)(h - 1)) fy = (float)(h - 1);
        int iy = (int)fy;
        if (iy > h - 2) iy = h > 1 ? h - 2 : 0;
        float vy = h > 1 ? fy - (float)iy : 0.0f;
        const unsigned char *r0 = src + (size_t)iy * w * 3;
        const unsigned char *r1 = src + (size_t)(h > 1 ? iy + 1 : iy) * w * 3;
        unsigned char *out = dst + (size_t)y * size * 3;
        for (x = 0; x < size; x++) {
            const unsigned char *a = r0 + (size_t)x0[x] * 3;
            const unsigned char *b = a + (w > 1 ? 3 : 0);
            const unsigned char *cta = r1 + (size_t)x0[x] * 3;
            const unsigned char *ctb = cta + (w > 1 ? 3 : 0);
            float u = wx[x];
            for (c = 0; c < 3; c++) {
                float top = (float)a[c] * (1.0f - u) + (float)b[c] * u;
                float bot = (float)cta[c] * (1.0f - u) + (float)ctb[c] * u;
                float v = top * (1.0f - vy) + bot * vy + 0.5f;
                out[x * 3 + c] = (unsigned char)(v < 0 ? 0 : v > 255 ? 255 : v);
            }
        }
    }
    free(x0);
    free(wx);
    return 0;
}

/* Decode + exact-size bilinear in one native call (GIL-free end to end
 * through ctypes): DCT-scaled decode near `size`, then resize. `scratch`
 * must hold the scaled decode (<= full-size w*h*3; use tfos_jpeg_info). */
int tfos_jpeg_decode_resized(const unsigned char *buf, size_t len, int size,
                             unsigned char *scratch, size_t scratch_cap,
                             unsigned char *dst) {
    int w = 0, h = 0;
    int rc = tfos_jpeg_decode(buf, len, size, scratch, scratch_cap, &w, &h);
    if (rc != 0) return rc;
    if (w == size && h == size) {
        memcpy(dst, scratch, (size_t)size * size * 3);
        return 0;
    }
    return tfos_resize_bilinear_rgb(scratch, h, w, dst, size);
}

/* Probe dimensions without decoding (for buffer sizing).  target_min
 * applies the same DCT-scale rule as tfos_jpeg_decode, so callers can
 * size the scratch buffer to the SCALED decode (as little as 1/64th
 * of full resolution) instead of the full image. */
int tfos_jpeg_info(const unsigned char *buf, size_t len, int target_min,
                   int *out_w, int *out_h) {
    struct jpeg_decompress_struct cinfo;
    struct tfos_jpeg_err err;

    cinfo.err = jpeg_std_error(&err.mgr);
    err.mgr.error_exit = tfos_jpeg_error_exit;
    err.mgr.output_message = tfos_jpeg_silence;
    if (setjmp(err.jump)) {
        jpeg_destroy_decompress(&cinfo);
        return -1;
    }
    jpeg_create_decompress(&cinfo);
    jpeg_mem_src(&cinfo, (unsigned char *)buf, (unsigned long)len);
    if (jpeg_read_header(&cinfo, TRUE) != JPEG_HEADER_OK) {
        jpeg_destroy_decompress(&cinfo);
        return -1;
    }
    cinfo.out_color_space = JCS_RGB;
    cinfo.scale_num = 1;
    cinfo.scale_denom = 1;
    if (target_min > 0) {
        unsigned d;
        unsigned minside = cinfo.image_width < cinfo.image_height
                               ? cinfo.image_width
                               : cinfo.image_height;
        for (d = 8; d > 1; d /= 2) {
            if (minside / d >= (unsigned)target_min) {
                cinfo.scale_denom = d;
                break;
            }
        }
    }
    jpeg_calc_output_dimensions(&cinfo);
    *out_w = (int)cinfo.output_width;
    *out_h = (int)cinfo.output_height;
    jpeg_destroy_decompress(&cinfo);
    return 0;
}
