// Shared-memory ring buffer: the zero-copy feed path between the engine's
// feeder task and the training process on one host.
//
// TPU-native replacement for the reference's per-record pickled
// multiprocessing queues (the documented hot-loop bottleneck,
// TFSparkNode.py:480-482 ↔ TFNode.py:265-287): a single-producer /
// single-consumer byte ring in POSIX shared memory carrying *batches*
// (e.g. serialized record chunks or raw tensor blocks) with no syscalls
// on the fast path.
//
// Layout: Header | data[capacity]
//   head: next write offset (producer-owned), tail: next read offset
//   (consumer-owned); both are free-running uint64 counters mod capacity.
//   Each message: uint32 len | payload | padding to 8 bytes.
//   closed: producer sets when done (consumer drains then sees EOF).

#include <atomic>
#include <cerrno>
#include <cstdint>
#include <string>
#include <cstdio>
#include <cstring>
#include <ctime>
#include <fcntl.h>
#include <sys/mman.h>
#include <sys/stat.h>
#include <unistd.h>
#include <vector>

namespace {

constexpr uint64_t kMagic = 0x54464f53514d5631ull;  // "TFOSQMV1"

struct Header {
  uint64_t magic;
  uint64_t capacity;
  std::atomic<uint64_t> head;
  std::atomic<uint64_t> tail;
  std::atomic<uint32_t> closed;
  uint32_t _pad;
};

struct Queue {
  Header* h;
  uint8_t* data;
  size_t map_len;
  std::string name;
  std::vector<uint8_t> scratch;
  bool owner;
};

inline uint64_t align8(uint64_t n) { return (n + 7) & ~7ull; }

void sleep_us(unsigned us) {
  struct timespec ts {0, (long)us * 1000};
  nanosleep(&ts, nullptr);
}

}  // namespace

extern "C" {

Queue* shq_create(const char* name, uint64_t capacity) {
  capacity = align8(capacity);
  shm_unlink(name);  // stale segment from a crashed run
  int fd = shm_open(name, O_CREAT | O_EXCL | O_RDWR, 0600);
  if (fd < 0) return nullptr;
  size_t len = sizeof(Header) + capacity;
  if (ftruncate(fd, (off_t)len) != 0) {
    close(fd);
    shm_unlink(name);
    return nullptr;
  }
  void* mem = mmap(nullptr, len, PROT_READ | PROT_WRITE, MAP_SHARED, fd, 0);
  close(fd);
  if (mem == MAP_FAILED) {
    shm_unlink(name);
    return nullptr;
  }
  auto* h = new (mem) Header();
  h->capacity = capacity;
  h->head.store(0);
  h->tail.store(0);
  h->closed.store(0);
  h->magic = kMagic;  // published last
  auto* q = new Queue{h, (uint8_t*)mem + sizeof(Header), len, name, {}, true};
  return q;
}

Queue* shq_open(const char* name, int timeout_ms) {
  int fd = -1;
  for (int waited = 0;; waited += 10) {
    fd = shm_open(name, O_RDWR, 0600);
    if (fd >= 0) break;
    if (waited >= timeout_ms) return nullptr;
    sleep_us(10000);
  }
  struct stat st;
  if (fstat(fd, &st) != 0 || (size_t)st.st_size < sizeof(Header)) {
    close(fd);
    return nullptr;
  }
  void* mem = mmap(nullptr, (size_t)st.st_size, PROT_READ | PROT_WRITE,
                   MAP_SHARED, fd, 0);
  close(fd);
  if (mem == MAP_FAILED) return nullptr;
  auto* h = (Header*)mem;
  for (int waited = 0; h->magic != kMagic; waited += 1) {
    if (waited > 1000) {
      munmap(mem, (size_t)st.st_size);
      return nullptr;
    }
    sleep_us(1000);
  }
  auto* q = new Queue{h, (uint8_t*)mem + sizeof(Header), (size_t)st.st_size,
                      name, {}, false};
  return q;
}

// 0 ok; -1 timeout; -2 closed; -3 message larger than capacity
int shq_push(Queue* q, const uint8_t* buf, uint64_t len, int timeout_ms) {
  Header* h = q->h;
  uint64_t need = align8(4 + len);
  if (need + 8 > h->capacity) return -3;
  int waited_us = 0;
  for (;;) {
    if (h->closed.load(std::memory_order_acquire)) return -2;
    uint64_t head = h->head.load(std::memory_order_relaxed);
    uint64_t tail = h->tail.load(std::memory_order_acquire);
    if (head + need - tail <= h->capacity - 8) {
      uint64_t off = head % h->capacity;
      uint32_t len32 = (uint32_t)len;
      // header word never wraps (8-byte alignment); payload may wrap
      memcpy(q->data + off, &len32, 4);
      uint64_t poff = (off + 4) % h->capacity;
      uint64_t first = std::min(len, h->capacity - poff);
      memcpy(q->data + poff, buf, first);
      if (first < len) memcpy(q->data, buf + first, len - first);
      h->head.store(head + need, std::memory_order_release);
      return 0;
    }
    if (timeout_ms >= 0 && waited_us / 1000 >= timeout_ms) return -1;
    sleep_us(waited_us < 2000 ? 50 : 500);
    waited_us += waited_us < 2000 ? 50 : 500;
  }
}

// Scatter-gather push: one reservation, each segment memcpy'd straight
// from its source buffer (e.g. numpy column data) into the ring — no
// python-side assembly of a contiguous message.  Same returns as
// shq_push.
int shq_push_iov(Queue* q, const uint8_t** bufs, const uint64_t* lens,
                 int n, int timeout_ms) {
  Header* h = q->h;
  uint64_t len = 0;
  for (int i = 0; i < n; i++) len += lens[i];
  uint64_t need = align8(4 + len);
  if (need + 8 > h->capacity) return -3;
  int waited_us = 0;
  for (;;) {
    if (h->closed.load(std::memory_order_acquire)) return -2;
    uint64_t head = h->head.load(std::memory_order_relaxed);
    uint64_t tail = h->tail.load(std::memory_order_acquire);
    if (head + need - tail <= h->capacity - 8) {
      uint64_t off = head % h->capacity;
      uint32_t len32 = (uint32_t)len;
      memcpy(q->data + off, &len32, 4);
      uint64_t poff = (off + 4) % h->capacity;
      for (int i = 0; i < n; i++) {
        uint64_t first = std::min(lens[i], h->capacity - poff);
        memcpy(q->data + poff, bufs[i], first);
        if (first < lens[i]) memcpy(q->data, bufs[i] + first, lens[i] - first);
        poff = (poff + lens[i]) % h->capacity;
      }
      h->head.store(head + need, std::memory_order_release);
      return 0;
    }
    if (timeout_ms >= 0 && waited_us / 1000 >= timeout_ms) return -1;
    sleep_us(waited_us < 2000 ? 50 : 500);
    waited_us += waited_us < 2000 ? 50 : 500;
  }
}

// Wait for the next message and return its length WITHOUT consuming it
// (-1 timeout, -2 EOF).  Pair with shq_pop_into to copy the payload
// directly into a caller-owned buffer: one copy on the consumer side,
// vs pop-to-scratch + a python-level copy.
int64_t shq_peek_len(Queue* q, int timeout_ms) {
  Header* h = q->h;
  int waited_us = 0;
  for (;;) {
    uint64_t tail = h->tail.load(std::memory_order_relaxed);
    uint64_t head = h->head.load(std::memory_order_acquire);
    if (head != tail) {
      uint32_t len32;
      memcpy(&len32, q->data + (tail % h->capacity), 4);
      return (int64_t)len32;
    }
    if (h->closed.load(std::memory_order_acquire)) return -2;
    if (timeout_ms >= 0 && waited_us / 1000 >= timeout_ms) return -1;
    sleep_us(waited_us < 2000 ? 50 : 500);
    waited_us += waited_us < 2000 ? 50 : 500;
  }
}

// Copy the pending message's payload into dst (size from shq_peek_len)
// and consume it.  Returns the length, or -1 if no message is pending
// (misuse: call only after a successful shq_peek_len).
int64_t shq_pop_into(Queue* q, uint8_t* dst) {
  Header* h = q->h;
  uint64_t tail = h->tail.load(std::memory_order_relaxed);
  uint64_t head = h->head.load(std::memory_order_acquire);
  if (head == tail) return -1;
  uint64_t off = tail % h->capacity;
  uint32_t len32;
  memcpy(&len32, q->data + off, 4);
  uint64_t poff = (off + 4) % h->capacity;
  uint64_t first = std::min((uint64_t)len32, h->capacity - poff);
  memcpy(dst, q->data + poff, first);
  if (first < len32) memcpy(dst + first, q->data, len32 - first);
  h->tail.store(tail + align8(4 + len32), std::memory_order_release);
  return (int64_t)len32;
}

// >=0: message length (0 = legitimately empty payload) copied into
// internal scratch (get via shq_buffer); -1: timeout; -2: EOF (closed and
// drained).
int64_t shq_pop(Queue* q, int timeout_ms) {
  Header* h = q->h;
  int waited_us = 0;
  for (;;) {
    uint64_t tail = h->tail.load(std::memory_order_relaxed);
    uint64_t head = h->head.load(std::memory_order_acquire);
    if (head != tail) {
      uint64_t off = tail % h->capacity;
      uint32_t len32;
      memcpy(&len32, q->data + off, 4);
      q->scratch.resize(len32);
      uint64_t poff = (off + 4) % h->capacity;
      uint64_t first = std::min((uint64_t)len32, h->capacity - poff);
      memcpy(q->scratch.data(), q->data + poff, first);
      if (first < len32)
        memcpy(q->scratch.data() + first, q->data, len32 - first);
      h->tail.store(tail + align8(4 + len32), std::memory_order_release);
      return (int64_t)len32;
    }
    if (h->closed.load(std::memory_order_acquire)) return -2;
    if (timeout_ms >= 0 && waited_us / 1000 >= timeout_ms) return -1;
    sleep_us(waited_us < 2000 ? 50 : 500);
    waited_us += waited_us < 2000 ? 50 : 500;
  }
}

const uint8_t* shq_buffer(Queue* q) { return q->scratch.data(); }

void shq_close_write(Queue* q) {
  q->h->closed.store(1, std::memory_order_release);
}

uint64_t shq_size(Queue* q) {
  return q->h->head.load() - q->h->tail.load();
}

void shq_free(Queue* q) {
  bool owner = q->owner;
  std::string name = q->name;
  munmap((void*)((uint8_t*)q->h), q->map_len);
  if (owner) shm_unlink(name.c_str());
  delete q;
}

}  // extern "C"
