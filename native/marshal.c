/* Row-batch <-> typed-column marshalling (CPython extension).
 *
 * TPU-native equivalent of the reference JVM engine's Row<->Tensor
 * marshalling (TFModel.scala:51-239: batch2tensors / tensors2batch): the
 * per-dtype conversion between a batch of row tuples and dense
 * per-column buffers runs in compiled code, not the Python interpreter.
 *
 * Exposed as module `_tfos_marshal`:
 *   rows_to_columns(rows, spec) -> tuple of numpy arrays
 *     rows: sequence of row tuples/lists (all the same arity)
 *     spec: sequence of (dtype_char, width) per column:
 *       '?' bool, 'i' int32, 'l' int64, 'f' float32, 'd' float64
 *       width 0 -> scalar column (result shape [n]);
 *       width w>0 -> fixed-length sequence column (result shape [n, w])
 *   columns_to_rows(columns) -> list of row tuples
 *     columns: sequence of C-contiguous numpy arrays, 1-D (scalar per
 *     row) or 2-D (python list per row) — mirroring tensors2batch's
 *     "size>1 becomes a Seq" rule.
 *
 * Arrays are allocated by calling back into numpy (np.empty) and filled
 * through the buffer protocol, so no numpy C headers are needed at
 * build time.
 */

#define PY_SSIZE_T_CLEAN
#include <Python.h>
#include <string.h>

static PyObject *np_empty = NULL; /* numpy.empty */

static PyObject *make_array(Py_ssize_t n, Py_ssize_t width, char code) {
  char dtype[3] = {code, 0, 0};
  PyObject *shape, *args, *kw, *arr, *dt;
  if (width > 0)
    shape = Py_BuildValue("(nn)", n, width);
  else
    shape = Py_BuildValue("(n)", n);
  if (!shape) return NULL;
  dt = PyUnicode_FromString(dtype);
  if (!dt) { Py_DECREF(shape); return NULL; }
  args = PyTuple_Pack(2, shape, dt);
  Py_DECREF(shape);
  Py_DECREF(dt);
  if (!args) return NULL;
  kw = NULL;
  arr = PyObject_Call(np_empty, args, kw);
  Py_DECREF(args);
  return arr;
}

static int fill_value(char code, char *dst, Py_ssize_t idx, PyObject *v) {
  switch (code) {
    case '?': {
      /* only genuine bools (python bool or numpy.bool_): truthiness of
       * an int/float here would be a silent lossy cast (2 -> True) the
       * row path never performs */
      const char *tn = Py_TYPE(v)->tp_name;
      if (!PyBool_Check(v) && strcmp(tn, "numpy.bool_") != 0 &&
          strcmp(tn, "numpy.bool") != 0) {
        PyErr_SetString(PyExc_TypeError, "bool column requires bool values");
        return -1;
      }
      int b = PyObject_IsTrue(v);
      if (b < 0) return -1;
      ((unsigned char *)dst)[idx] = (unsigned char)b;
      return 0;
    }
    case 'i': {
      long long x = PyLong_AsLongLong(v);
      if (x == -1 && PyErr_Occurred()) return -1;
      if (x > 2147483647LL || x < -2147483648LL) {
        PyErr_SetString(PyExc_OverflowError,
                        "value overflows the int32 column spec");
        return -1;
      }
      ((int *)dst)[idx] = (int)x;
      return 0;
    }
    case 'l': {
      long long x = PyLong_AsLongLong(v);
      if (x == -1 && PyErr_Occurred()) return -1;
      ((long long *)dst)[idx] = x;
      return 0;
    }
    case 'f': {
      double x = PyFloat_AsDouble(v);
      if (x == -1.0 && PyErr_Occurred()) return -1;
      ((float *)dst)[idx] = (float)x;
      return 0;
    }
    case 'd': {
      double x = PyFloat_AsDouble(v);
      if (x == -1.0 && PyErr_Occurred()) return -1;
      ((double *)dst)[idx] = x;
      return 0;
    }
    default:
      PyErr_Format(PyExc_ValueError, "unsupported dtype code '%c'", code);
      return -1;
  }
}

static PyObject *rows_to_columns(PyObject *self, PyObject *args) {
  PyObject *rows_obj, *spec_obj;
  if (!PyArg_ParseTuple(args, "OO", &rows_obj, &spec_obj)) return NULL;

  PyObject *rows = PySequence_Fast(rows_obj, "rows must be a sequence");
  if (!rows) return NULL;
  PyObject *spec = PySequence_Fast(spec_obj, "spec must be a sequence");
  if (!spec) { Py_DECREF(rows); return NULL; }

  Py_ssize_t n = PySequence_Fast_GET_SIZE(rows);
  Py_ssize_t ncols = PySequence_Fast_GET_SIZE(spec);

  PyObject *out = PyTuple_New(ncols);
  Py_buffer *bufs = PyMem_Calloc(ncols, sizeof(Py_buffer));
  char *codes = PyMem_Calloc(ncols, 1);
  Py_ssize_t *widths = PyMem_Calloc(ncols, sizeof(Py_ssize_t));
  int ok = (out && bufs && codes && widths);

  for (Py_ssize_t c = 0; ok && c < ncols; c++) {
    PyObject *entry = PySequence_Fast_GET_ITEM(spec, c);
    const char *code_s;
    Py_ssize_t w;
    if (!PyArg_ParseTuple(entry, "sn", &code_s, &w)) { ok = 0; break; }
    codes[c] = code_s[0];
    widths[c] = w;
    PyObject *arr = make_array(n, w, codes[c]);
    if (!arr) { ok = 0; break; }
    PyTuple_SET_ITEM(out, c, arr); /* steals ref */
    if (PyObject_GetBuffer(arr, &bufs[c], PyBUF_WRITABLE | PyBUF_C_CONTIGUOUS) < 0) {
      ok = 0;
      break;
    }
  }

  for (Py_ssize_t r = 0; ok && r < n; r++) {
    PyObject *row = PySequence_Fast_GET_ITEM(rows, r);
    PyObject *rowf = PySequence_Fast(row, "row must be a sequence");
    if (!rowf) { ok = 0; break; }
    if (PySequence_Fast_GET_SIZE(rowf) != ncols) {
      PyErr_Format(PyExc_ValueError,
                   "row %zd has %zd fields, spec has %zd columns", r,
                   PySequence_Fast_GET_SIZE(rowf), ncols);
      Py_DECREF(rowf);
      ok = 0;
      break;
    }
    for (Py_ssize_t c = 0; ok && c < ncols; c++) {
      PyObject *v = PySequence_Fast_GET_ITEM(rowf, c);
      if (widths[c] == 0) {
        if (fill_value(codes[c], bufs[c].buf, r, v) < 0) ok = 0;
      } else {
        PyObject *vf = PySequence_Fast(v, "array column value must be a sequence");
        if (!vf) { ok = 0; break; }
        if (PySequence_Fast_GET_SIZE(vf) != widths[c]) {
          PyErr_Format(PyExc_ValueError,
                       "row %zd col %zd: length %zd != spec width %zd", r, c,
                       PySequence_Fast_GET_SIZE(vf), widths[c]);
          Py_DECREF(vf);
          ok = 0;
          break;
        }
        for (Py_ssize_t k = 0; k < widths[c]; k++) {
          if (fill_value(codes[c], bufs[c].buf, r * widths[c] + k,
                         PySequence_Fast_GET_ITEM(vf, k)) < 0) {
            ok = 0;
            break;
          }
        }
        Py_DECREF(vf);
      }
    }
    Py_DECREF(rowf);
  }

  for (Py_ssize_t c = 0; c < ncols; c++)
    if (bufs && bufs[c].obj) PyBuffer_Release(&bufs[c]);
  PyMem_Free(bufs);
  PyMem_Free(codes);
  PyMem_Free(widths);
  Py_DECREF(rows);
  Py_DECREF(spec);
  if (!ok) {
    Py_XDECREF(out);
    return NULL;
  }
  return out;
}

static PyObject *value_from(char code, const char *src, Py_ssize_t idx) {
  switch (code) {
    case '?': return PyBool_FromLong(((const unsigned char *)src)[idx]);
    case 'b': return PyLong_FromLong(((const signed char *)src)[idx]);
    case 'i': return PyLong_FromLong(((const int *)src)[idx]);
    case 'l': return PyLong_FromLongLong(((const long long *)src)[idx]);
    case 'f': return PyFloat_FromDouble(((const float *)src)[idx]);
    case 'd': return PyFloat_FromDouble(((const double *)src)[idx]);
    default:
      PyErr_Format(PyExc_ValueError, "unsupported output dtype '%c'", code);
      return NULL;
  }
}

/* map a numpy format string (buffer protocol) to our dtype code */
static char format_code(const char *fmt) {
  if (!fmt) return 0;
  /* skip byte-order prefix */
  if (*fmt == '<' || *fmt == '>' || *fmt == '=' || *fmt == '|') fmt++;
  switch (*fmt) {
    case '?': return '?';
    case 'b': return 'b';
    case 'i': return 'i';
    case 'l': return sizeof(long) == 8 ? 'l' : 'i';
    case 'q': return 'l';
    case 'f': return 'f';
    case 'd': return 'd';
    default: return 0;
  }
}

/* Build the python list for one row of a 2-D column with a per-dtype
 * tight loop: hoisting the dtype switch out of the element loop makes
 * wide sequence columns (e.g. 784-float feature rows) ~2x faster than
 * per-element dispatch — the difference between losing and winning
 * against numpy's tolist() on the reconstruction path. */
static PyObject *row_list_from(char code, const char *src, Py_ssize_t off,
                               Py_ssize_t w) {
  PyObject *v = PyList_New(w);
  if (!v) return NULL;
  Py_ssize_t k = 0;
  switch (code) {
    case 'f': {
      const float *p = (const float *)src + off;
      for (; k < w; k++) {
        PyObject *e = PyFloat_FromDouble(p[k]);
        if (!e) goto fail;
        PyList_SET_ITEM(v, k, e);
      }
      return v;
    }
    case 'd': {
      const double *p = (const double *)src + off;
      for (; k < w; k++) {
        PyObject *e = PyFloat_FromDouble(p[k]);
        if (!e) goto fail;
        PyList_SET_ITEM(v, k, e);
      }
      return v;
    }
    case 'i': {
      const int *p = (const int *)src + off;
      for (; k < w; k++) {
        PyObject *e = PyLong_FromLong(p[k]);
        if (!e) goto fail;
        PyList_SET_ITEM(v, k, e);
      }
      return v;
    }
    case 'l': {
      const long long *p = (const long long *)src + off;
      for (; k < w; k++) {
        PyObject *e = PyLong_FromLongLong(p[k]);
        if (!e) goto fail;
        PyList_SET_ITEM(v, k, e);
      }
      return v;
    }
    default:
      for (; k < w; k++) {
        PyObject *e = value_from(code, src, off + k);
        if (!e) goto fail;
        PyList_SET_ITEM(v, k, e);
      }
      return v;
  }
fail:
  Py_DECREF(v);
  return NULL;
}

static PyObject *columns_to_rows(PyObject *self, PyObject *args) {
  PyObject *cols_obj;
  if (!PyArg_ParseTuple(args, "O", &cols_obj)) return NULL;
  PyObject *cols = PySequence_Fast(cols_obj, "columns must be a sequence");
  if (!cols) return NULL;
  Py_ssize_t ncols = PySequence_Fast_GET_SIZE(cols);

  Py_buffer *bufs = PyMem_Calloc(ncols, sizeof(Py_buffer));
  char *codes = PyMem_Calloc(ncols, 1);
  Py_ssize_t n = -1;
  int ok = (bufs && codes);
  PyObject *out = NULL;

  for (Py_ssize_t c = 0; ok && c < ncols; c++) {
    PyObject *arr = PySequence_Fast_GET_ITEM(cols, c);
    if (PyObject_GetBuffer(arr, &bufs[c], PyBUF_FORMAT | PyBUF_C_CONTIGUOUS) < 0) {
      ok = 0;
      break;
    }
    if (bufs[c].ndim < 1 || bufs[c].ndim > 2) {
      PyErr_Format(PyExc_ValueError, "column %zd: ndim %d not in {1,2}", c,
                   bufs[c].ndim);
      ok = 0;
      break;
    }
    codes[c] = format_code(bufs[c].format);
    if (!codes[c]) {
      PyErr_Format(PyExc_ValueError, "column %zd: unsupported format '%s'", c,
                   bufs[c].format ? bufs[c].format : "?");
      ok = 0;
      break;
    }
    if (n == -1) n = bufs[c].shape[0];
    else if (bufs[c].shape[0] != n) {
      PyErr_Format(PyExc_ValueError,
                   "column %zd has %zd rows, expected %zd", c,
                   bufs[c].shape[0], n);
      ok = 0;
      break;
    }
  }
  if (n < 0) n = 0;

  if (ok) {
    out = PyList_New(n);
    if (!out) ok = 0;
  }
  for (Py_ssize_t r = 0; ok && r < n; r++) {
    PyObject *row = PyTuple_New(ncols);
    if (!row) { ok = 0; break; }
    for (Py_ssize_t c = 0; c < ncols; c++) {
      PyObject *v;
      if (bufs[c].ndim == 1) {
        v = value_from(codes[c], bufs[c].buf, r);
      } else {
        Py_ssize_t w = bufs[c].shape[1];
        v = row_list_from(codes[c], bufs[c].buf, r * w, w);
      }
      if (!v) { Py_DECREF(row); ok = 0; break; }
      PyTuple_SET_ITEM(row, c, v);
    }
    if (!ok) break;
    PyList_SET_ITEM(out, r, row);
  }

  for (Py_ssize_t c = 0; c < ncols; c++)
    if (bufs && bufs[c].obj) PyBuffer_Release(&bufs[c]);
  PyMem_Free(bufs);
  PyMem_Free(codes);
  Py_DECREF(cols);
  if (!ok) {
    Py_XDECREF(out);
    return NULL;
  }
  return out;
}

static PyMethodDef methods[] = {
    {"rows_to_columns", rows_to_columns, METH_VARARGS,
     "rows_to_columns(rows, spec) -> tuple of numpy arrays"},
    {"columns_to_rows", columns_to_rows, METH_VARARGS,
     "columns_to_rows(columns) -> list of row tuples"},
    {NULL, NULL, 0, NULL},
};

static struct PyModuleDef moduledef = {
    PyModuleDef_HEAD_INIT, "_tfos_marshal",
    "native row-batch <-> typed-column marshalling", -1, methods,
};

PyMODINIT_FUNC PyInit__tfos_marshal(void) {
  PyObject *np = PyImport_ImportModule("numpy");
  if (!np) return NULL;
  np_empty = PyObject_GetAttrString(np, "empty");
  Py_DECREF(np);
  if (!np_empty) return NULL;
  return PyModule_Create(&moduledef);
}
