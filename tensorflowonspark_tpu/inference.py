"""Batch-inference CLI (parity: src/main/scala Inference.scala:27-79).

The reference ships a spark-submit JVM app: parse args → load TFRecords
with an optional schema hint → run the cached-model Model.transform →
write JSON predictions.  Same contract here as a console entry point on
the framework's engine layer (LocalEngine by default, Spark when a
SparkContext is available), with the C++ recordio reader underneath:

    python -m tensorflowonspark_tpu.inference \\
        --export_dir /path/export \\
        --input /path/tfrecords --output /path/preds \\
        --schema_hint 'struct<image:array<float>,label:bigint>' \\
        --input_mapping '{"image": "x"}' \\
        --output_mapping '{"prediction": "preds"}'
"""

from __future__ import annotations

import argparse
import json
import logging
import os

logger = logging.getLogger(__name__)


def build_parser():
    p = argparse.ArgumentParser(
        prog="tensorflowonspark_tpu.inference",
        description="Batch inference over TFRecords with an exported model",
    )
    p.add_argument("--export_dir", required=True,
                   help="export directory (utils.checkpoint.export_model)")
    p.add_argument("--input", required=True, help="TFRecord dir or file")
    p.add_argument("--output", required=True, help="output dir (JSON lines)")
    p.add_argument("--schema_hint", default=None,
                   help="struct<name:type,...> partial schema hint")
    p.add_argument("--input_mapping", default=None,
                   help='JSON {column: tensor_name}')
    p.add_argument("--output_mapping", default=None,
                   help='JSON {tensor_name: column}')
    p.add_argument("--batch_size", type=int, default=128)
    p.add_argument("--no_pad_partial", dest="pad_partial",
                   action="store_false", default=True,
                   help="disable padding the final partial batch up to "
                        "--batch_size (padding keeps the predict shape "
                        "constant — one compile; padded rows are sliced "
                        "off the outputs)")
    p.add_argument("--signature_def_key", default=None,
                   help="module:function predict override")
    p.add_argument("--num_executors", type=int, default=2,
                   help="LocalEngine pool size (ignored under Spark)")
    return p


def run(args, source=None):
    """Programmatic entry; ``source`` overrides the engine (tests pass a
    LocalEngine; a live SparkContext works via engine.SparkEngine)."""
    from tensorflowonspark_tpu import dfutil, pipeline
    from tensorflowonspark_tpu.engine import LocalEngine
    from tensorflowonspark_tpu.utils import schema as schema_util

    hint = schema_util.parse_schema(args.schema_hint) if args.schema_hint else {}
    binary_features = [n for n, (k, _) in hint.items() if k == "bytes"]

    own_engine = source is None
    engine = source or LocalEngine(num_executors=args.num_executors)
    try:
        ds, inferred = dfutil.load_tfrecords(
            engine, args.input, binary_features=binary_features
        )
        schema = schema_util.merge_schemas(inferred, hint)
        logger.info("input schema: %s", schema_util.format_schema(schema))

        input_mapping = (
            json.loads(args.input_mapping) if args.input_mapping else None
        )
        output_mapping = (
            json.loads(args.output_mapping) if args.output_mapping else None
        )
        # set as ML Params (they win over args in merge_args_params —
        # same precedence as the reference's TFModel.setExportDir etc.)
        # pad_partial is a plain tf_arg (not an ML Param): padding the
        # final partial batch keeps the predict shape constant — one
        # compile; padded rows are sliced off the outputs
        model = pipeline.TFModel({"pad_partial": args.pad_partial})
        settings = {
            "export_dir": args.export_dir,
            "batch_size": args.batch_size,
            "input_mapping": input_mapping,
            "output_mapping": output_mapping,
            "signature_def_key": args.signature_def_key,
        }
        model._set(**{k: v for k, v in settings.items() if v is not None})
        # rows are dicts; Model.transform selects sorted(input_mapping)
        # columns — project dicts onto tuples the predictor expects
        if input_mapping:
            cols = sorted(input_mapping)
            ds = ds.map_partitions(
                _project(cols)
            )
        preds = model.transform(ds)

        from tensorflowonspark_tpu.recordio import fs as _fs

        _fs.makedirs(args.output)
        shards = preds.map_partitions(_write_json(args.output)).collect()
        shards = [s for s in shards if s]
        logger.info("wrote %d shards under %s", len(shards), args.output)
        return shards
    finally:
        if own_engine:
            engine.stop()


def _project(cols):
    def project(it):
        return [tuple(row[c] for c in cols) for row in it]
    return project


def _write_json(output_dir):
    def write(it):
        import json as _json
        import os as _os
        import uuid as _uuid

        from tensorflowonspark_tpu.recordio import fs as _ffs

        rows = list(it)
        if not rows:
            return []
        # unique per partition: pid alone repeats when one executor gets
        # several partitions, and id()-style keys can collide after reuse
        path = _ffs.join(
            output_dir, f"part-{_os.getpid()}-{_uuid.uuid4().hex[:8]}.json"
        )
        with _ffs.open_file(path, "w") as f:
            for row in rows:
                f.write(_json.dumps(row) + "\n")
        return [path]
    return write


def main(argv=None):
    logging.basicConfig(level=logging.INFO)
    args = build_parser().parse_args(argv)
    run(args)


if __name__ == "__main__":
    main()
