"""TFRecord ⇄ row-table interop (parity: reference tensorflowonspark/dfutil.py
+ DFUtil.scala).

The reference converts Spark DataFrames to tf.train.Example TFRecords and
back, inferring the schema from the first record with a
``binary_features`` hint to disambiguate bytes vs string
(dfutil.py:44-81,134-168).  Here rows are plain dicts (the engine's
datasets carry them; a Spark DataFrame's ``.rdd`` of Rows works
unchanged), and record IO is the native C++ reader/writer — no
TensorFlow or Hadoop dependency.

Type mapping (dfutil.py:84-131 / DFUtil.scala:195-258 dtype matrix):
  int/bool      → int64_list        float         → float_list
  str           → bytes_list(utf8)  bytes         → bytes_list
  list[...]     → the element kind's list (marked array in the schema)
"""

from __future__ import annotations

import logging

from tensorflowonspark_tpu import recordio
from tensorflowonspark_tpu.engine import LocalDataset, as_dataset
from tensorflowonspark_tpu.recordio import fs as _fs

logger = logging.getLogger(__name__)

# provenance registry of loaded datasets (parity: dfutil.py:18-26 loadedDF)
loaded_schemas = {}


# -- row ⇄ Example -----------------------------------------------------------

def to_example(row: dict) -> bytes:
    """Encode one row dict as a serialized tf.train.Example."""
    feats = {}
    for name, value in row.items():
        is_list = isinstance(value, (list, tuple))
        vals = list(value) if is_list else [value]
        if not vals:
            feats[name] = ("float", [])
        elif isinstance(vals[0], bool):
            feats[name] = ("int64", [int(v) for v in vals])
        elif isinstance(vals[0], int):
            feats[name] = ("int64", vals)
        elif isinstance(vals[0], float):
            feats[name] = ("float", vals)
        elif isinstance(vals[0], str):
            feats[name] = ("bytes", [v.encode() for v in vals])
        elif isinstance(vals[0], (bytes, bytearray)):
            feats[name] = ("bytes", [bytes(v) for v in vals])
        else:
            import numpy as np

            if isinstance(vals[0], (np.integer,)):
                feats[name] = ("int64", [int(v) for v in vals])
            elif isinstance(vals[0], (np.floating,)):
                feats[name] = ("float", [float(v) for v in vals])
            elif isinstance(vals[0], np.ndarray):
                arr = np.asarray(vals[0])
                if arr.dtype.kind in "iu":
                    feats[name] = ("int64", [int(x) for x in arr.ravel()])
                else:
                    feats[name] = ("float", [float(x) for x in arr.ravel()])
            else:
                raise TypeError(f"unsupported type for {name}: {type(vals[0])}")
    return recordio.encode_example(feats)


def infer_schema(example_bytes: bytes, binary_features=()):
    """{name: (kind, is_array)} from the first record
    (parity: dfutil.infer_schema :134-168 — arrays inferred when a feature
    holds more than one value; bytes decode as str unless hinted binary)."""
    feats = recordio.decode_example(example_bytes)
    schema = {}
    for name, (kind, values) in feats.items():
        if kind == "bytes" and name not in binary_features:
            kind = "string"
        schema[name] = (kind, len(values) > 1)
    return schema


def from_example(example_bytes: bytes, schema=None, binary_features=()) -> dict:
    """Decode a serialized Example into a row dict."""
    feats = recordio.decode_example(example_bytes)
    if schema is None:
        schema = infer_schema(example_bytes, binary_features)
    row = {}
    for name, (kind, values) in feats.items():
        skind, is_array = schema.get(name, (kind, len(values) > 1))
        if skind == "string":
            values = [v.decode() for v in values]
        row[name] = list(values) if is_array else (values[0] if values else None)
    return row


# -- save / load -------------------------------------------------------------

def save_as_tfrecords(dataset_or_rows, output_dir):
    """Write rows as sharded TFRecord files on any filesystem — local,
    gs://, hdfs://, ... via fsspec (parity: dfutil.saveAsTFRecords :29-41,
    which writes through the Hadoop OutputFormat — one part file per
    partition)."""
    _fs.makedirs(output_dir)
    try:
        ds = as_dataset(dataset_or_rows)
    except TypeError:
        ds = None
    if ds is None:
        _write_shard(dataset_or_rows, _fs.join(output_dir, "part-r-00000"))
        return output_dir

    def write_partition(it):
        import os as _os
        import uuid as _uuid

        from tensorflowonspark_tpu.recordio import fs as _ffs

        rows = list(it)
        if not rows:
            return []
        # unique per partition even when one executor writes several
        # shards back to back (id()-based names can repeat after reuse)
        shard = _ffs.join(
            output_dir, f"part-r-{_os.getpid()}-{_uuid.uuid4().hex[:8]}"
        )
        _write_shard(rows, shard)
        return [shard]

    shards = ds.map_partitions(write_partition).collect()
    logger.info("saved %d shards under %s", len(shards), output_dir)
    return output_dir


def _write_shard(rows, path):
    with recordio.TFRecordWriter(path) as w:
        for row in rows:
            w.write(to_example(row))


def part_files(input_dir):
    """Public shard list for a TFRecord dir (or a single file path):
    sorted ``part-*`` files, ``.tmp`` spill excluded.  The shard
    enumeration contract shared by ``load_tfrecords*``,
    ``iter_tfrecords_columnar`` and ``data.from_tfrecords`` (whose
    ``interleave`` opens these files round-robin)."""
    return _part_files(input_dir)


def _part_files(input_dir):
    """Shard list for a TFRecord dir (or a single file path)."""
    files = sorted(
        _fs.join(input_dir, f)
        for f in _fs.listdir(input_dir)
        if f.startswith("part-") and not f.endswith(".tmp")
    ) if _fs.isdir(input_dir) else [input_dir]
    if not files:
        raise FileNotFoundError(f"no TFRecord part files under {input_dir}")
    return files


def load_tfrecords(source, input_dir, binary_features=(), min_partitions=None):
    """Load TFRecords into a dataset of row dicts with an inferred schema
    (parity: dfutil.loadTFRecords :44-81).

    ``source``: an engine (LocalEngine/SparkEngine) used to parallelize
    the shard list; pass None for a plain list of rows.

    ``min_partitions``: when the directory has fewer shard FILES than
    this (typical: fewer shards than feeder workers, which would starve
    workers and trigger the synchronized stop at step 0), each file is
    STRIPED across ceil(min_partitions/len(files)) read units — unit
    ``(path, stride, offset)`` keeps records where ``index % stride ==
    offset``.  Every unit still scans its whole file (TFRecords have no
    index), but nothing materializes through the driver, unlike
    ``Dataset.repartition`` on the local engine.
    """
    files = _part_files(input_dir)

    first = next(iter(recordio.TFRecordReader(files[0])))
    schema = infer_schema(first, binary_features)

    def read_shard(it):
        out = []
        for unit in it:
            path, stride, offset = (
                unit if isinstance(unit, tuple) else (unit, 1, 0))
            for i, rec in enumerate(recordio.TFRecordReader(path)):
                if stride == 1 or i % stride == offset:
                    out.append(from_example(rec, schema, binary_features))
        return out

    if source is None:
        rows = list(read_shard(iter(files)))
        loaded_schemas[input_dir] = schema
        return rows, schema
    if min_partitions and len(files) < min_partitions:
        stripes = -(-min_partitions // len(files))  # ceil
        units = [(f, stripes, off) for f in files for off in range(stripes)]
        logger.info(
            "striping %d shard file(s) into %d read units to reach "
            "min_partitions=%d (each unit rescans its file, keeping "
            "1/%d of the records)",
            len(files), len(units), min_partitions, stripes)
    else:
        units = list(files)
    n_parts = min(len(units),
                  max(source.num_executors * 2, min_partitions or 0))
    ds = source.parallelize(units, n_parts)
    ds = ds.map_partitions(read_shard)
    loaded_schemas[input_dir] = schema
    return ds, schema


def load_tfrecords_columnar(source):
    """Bulk-load TFRecords into dense per-feature columns:
    {name: ndarray [n]/[n,w] or list-of-bytes} — the TPU-first fast path
    for InputMode.TENSORFLOW-style direct reads (one C pass per shard, no
    per-value Python objects; columns np-slice straight into device
    batches).  Row-level parity lives in ``load_tfrecords``; this is the
    bulk analogue of the reference's Hadoop TFRecordFileInputFormat scan
    (dfutil.py:44-81 via the tensorflow-hadoop jar).

    ``source``: a dir (its part files), a single file path, or an
    explicit list of paths (e.g. one worker's disjoint shard subset).
    Empty shards are skipped; cross-shard dtype/width drift errors.
    """
    import numpy as np

    files = source if isinstance(source, (list, tuple)) \
        else _part_files(source)
    pairs = [(f, s) for f in files
             if (s := recordio.load_columnar(f))]  # skip empty parts
    if not pairs:
        return {}
    files = [f for f, _ in pairs]
    shards = [s for _, s in pairs]

    sig = _columnar_signature(shards[0])
    for f, s in zip(files[1:], shards[1:]):
        if _columnar_signature(s) != sig:
            raise ValueError(
                f"shard {f} schema {_columnar_signature(s)} != "
                f"first shard's {sig}")
    out = {}
    for name, (kind, col) in shards[0].items():
        parts = [col] + [s[name][1] for s in shards[1:]]
        if isinstance(col, np.ndarray):
            out[name] = np.concatenate(parts, axis=0)
        else:
            merged = []
            for p in parts:
                merged.extend(p)
            out[name] = merged
    return out


def _columnar_signature(shard):
    """name -> (kind, dtype, trailing shape): dtype/width drift across
    shards must error, not silently upcast under np.concatenate.  List
    (bytes) columns distinguish flat (one value/record) from nested
    (multi-value) so width drift errors there too instead of silently
    mixing bytes with lists."""
    import numpy as np

    def sig(kind, col):
        if isinstance(col, np.ndarray):
            return (kind, col.dtype.name, col.shape[1:])
        # scan the whole column: col[0] alone mislabels a ragged
        # fallback column whose first record happened to be single-value
        n_lists = sum(1 for v in col if isinstance(v, list))
        shape = ("flat" if n_lists == 0
                 else "nested" if n_lists == len(col) else "ragged")
        return (kind, "list", shape)

    return {name: sig(kind, col) for name, (kind, col) in shard.items()}


def iter_tfrecords_columnar(source, batch_size, *, drop_remainder=False):
    """Stream dense column batches from TFRecords one shard at a time:
    yields {name: ndarray [b]/[b,w] or list-of-bytes} without ever
    holding more than one shard (plus a batch remainder) in memory —
    the larger-than-RAM companion to ``load_tfrecords_columnar``.

    ``source``: dir, single file, or explicit shard list.  Batches are
    exactly ``batch_size`` rows except a final short batch (dropped with
    ``drop_remainder=True`` — SPMD steps want full shapes).  Cross-shard
    dtype/width drift raises, empty shards are skipped, and row order is
    shard order (matching the bulk loader).
    """
    import numpy as np

    if batch_size < 1:
        raise ValueError(f"batch_size must be >= 1, got {batch_size}")
    files = source if isinstance(source, (list, tuple)) \
        else _part_files(source)

    def concat(parts):
        if isinstance(parts[0], np.ndarray):
            return parts[0] if len(parts) == 1 else np.concatenate(parts)
        out = []
        for p in parts:
            out.extend(p)
        return out

    sig = None
    rest = None  # {name: bare partial column} carried across shards
    for f in files:
        shard = recordio.load_columnar(f)
        if not shard:
            continue
        shard_sig = _columnar_signature(shard)
        if sig is None:
            sig = shard_sig
        elif shard_sig != sig:
            raise ValueError(
                f"shard {f} schema {shard_sig} != first shard's {sig}")
        cols = {name: col for name, (_k, col) in shard.items()}
        if rest:
            cols = {name: concat([rest[name], cols[name]]) for name in cols}
        n = len(next(iter(cols.values())))
        lo = 0
        while n - lo >= batch_size:
            yield {name: col[lo:lo + batch_size]
                   for name, col in cols.items()}
            lo += batch_size
        # copy ndarray remainders: a slice VIEW would pin the whole
        # shard-sized base array until the next shard's concat
        rest = ({name: (col[lo:].copy() if isinstance(col, np.ndarray)
                        else col[lo:])
                 for name, col in cols.items()}
                if lo < n else None)
    if rest and not drop_remainder:
        yield rest


def is_loaded_df(path):
    """Provenance check (parity: dfutil.isLoadedDF :18-26): True if this
    path was produced by load_tfrecords in this process."""
    return path in loaded_schemas


# reference-spelling aliases (dfutil.py public surface is camelCase) so
# ported call sites work unchanged
saveAsTFRecords = save_as_tfrecords
loadTFRecords = load_tfrecords
toTFExample = to_example
fromTFExample = from_example
inferSchema = infer_schema
isLoadedDF = is_loaded_df
