"""Black-box flight recorder: span-ring snapshots on supervision events.

No reference counterpart (the reference's failure story is free-text
log lines at node granularity, ``TFSparkNode.py:356`` / SURVEY.md §5 —
when an executor died you got whatever stdout survived).  Here every
process already keeps a bounded ring of its most recent telemetry
records (``telemetry.Recorder.ring``, ``TFOS_FLIGHT_RING`` deep);
this module freezes that ring to disk the moment supervision notices
something died — replica lost (serving/replicas.py ``_monitor``),
executor respawn (engine.py ``_respawn_executor``), actor lost
(actors/runtime.py ``_monitor``), fault-site fire (utils/faults.py) —
so the *last N seconds before the death* survive the death.  The
training-health watchtower triggers it too: every ``health/<kind>``
anomaly (obs/health.py) and every on-demand ``POST /flightz``
directive (obs/publish.py ``serve_control``) snapshots the ring, so a
NaN or a straggler leaves the same black-box evidence a crash does.
``tfos-postmortem`` (obs/postmortem.py) assembles the dumps plus the
telemetry spools into a "what was everyone doing" report.

Contracts (ISSUE 12 satellite: bounded + redaction-safe):

- **no-op when telemetry is disabled** — ``snapshot`` returns None
  without touching the filesystem;
- **bounded** — each dump is clipped to ``TFOS_FLIGHT_CAP`` bytes
  (oldest ring records dropped first, drop count kept), and at most
  ``TFOS_FLIGHT_KEEP`` dumps per process are retained (oldest deleted);
- **redaction-safe** — record attrs and in-flight entries are
  sanitized to small scalars before writing: no prompts, tensors,
  pickled blobs, or strings past 200 chars ever land in a dump.

Dumps are one-JSON-object files named
``flight-<node>-<pid>-<seq>.json`` in the process's telemetry sink
dir (the spool the driver drain already collects), so postmortem
assembly needs no new transport.
"""

from __future__ import annotations

import itertools
import json
import logging
import os
import time

from tensorflowonspark_tpu.utils import telemetry

logger = logging.getLogger(__name__)

PREFIX = "flight-"
CAP_ENV = "TFOS_FLIGHT_CAP"        # max bytes per dump file
WINDOW_ENV = "TFOS_FLIGHT_WINDOW"  # trailing seconds of ring per dump
KEEP_ENV = "TFOS_FLIGHT_KEEP"      # dumps retained per process

_MAX_STR = 200        # longest attr string kept verbatim
_MAX_INFLIGHT = 64    # in-flight entries kept per dump


def cap_default():
    return int(os.environ.get(CAP_ENV, str(256 * 1024)))


def window_default():
    return float(os.environ.get(WINDOW_ENV, "30"))


def keep_default():
    return int(os.environ.get(KEEP_ENV, "8"))


_SEQ = itertools.count(1)


def _clean_value(v):
    """One attr value, reduced to a small scalar (redaction contract)."""
    if v is None or isinstance(v, (bool, int, float)):
        return v
    if isinstance(v, str):
        return v if len(v) <= _MAX_STR else v[:_MAX_STR] + "…"
    return f"<redacted {type(v).__name__}>"


def _clean_attrs(attrs):
    if not isinstance(attrs, dict):
        return {}
    return {str(k): _clean_value(v) for k, v in attrs.items()}


def _clean_record(rec):
    """A telemetry record with its attrs sanitized; schema unchanged."""
    out = {k: rec.get(k) for k in telemetry.SCHEMA_KEYS}
    out["attrs"] = _clean_attrs(rec.get("attrs"))
    return out


def snapshot(trigger, node=None, reason=None, inflight=None,
             window_s=None):
    """Freeze this process's flight ring to one bounded dump file.

    ``trigger`` names the supervision event (e.g.
    ``"serve/replica_lost"``); ``node`` the victim; ``inflight`` an
    optional small-scalar summary of outstanding work (the caller is
    responsible for pre-shrinking — entries are sanitized again here).
    Returns the dump path, or None when telemetry is disabled or the
    sink is unwritable (a flight dump must never take supervision
    down)."""
    rec = telemetry._get()
    if rec is None:
        return None
    window = window_default() if window_s is None else float(window_s)
    dump = {
        "ts": time.time(),
        "trigger": str(trigger),
        # victim defaults to the snapshotting process itself (the
        # faults.py self-snapshot path: the process about to die IS it)
        "node": str(node) if node is not None else rec.node_id,
        "reason": _clean_value(reason),
        "recorded_by": {"node_id": rec.node_id, "role": rec.role,
                        "pid": rec.pid},
        "window_s": window,
        "inflight": [_clean_attrs(e)
                     for e in (inflight or [])[:_MAX_INFLIGHT]],
        "truncated": 0,
        "records": [_clean_record(r) for r in telemetry.recent(window)],
    }
    cap = max(cap_default(), 4096)
    blob = json.dumps(dump, default=str)
    while len(blob) > cap and dump["records"]:
        drop = max(1, len(dump["records"]) // 4)  # oldest first
        dump["records"] = dump["records"][drop:]
        dump["truncated"] += drop
        blob = json.dumps(dump, default=str)
    name = (f"{PREFIX}{telemetry._safe(rec.node_id)}-{rec.pid}-"
            f"{next(_SEQ):04d}.json")
    path = os.path.join(rec.sink_dir, name)
    try:
        os.makedirs(rec.sink_dir, exist_ok=True)
        with open(path, "w", encoding="utf-8") as f:
            f.write(blob + "\n")
        _rotate(rec)
    except OSError as e:
        logger.warning("flight dump unwritable (%s): %s", path, e)
        return None
    return path


def _rotate(rec):
    """Keep only the newest TFOS_FLIGHT_KEEP dumps of this process."""
    keep = max(keep_default(), 1)
    mine = f"{PREFIX}{telemetry._safe(rec.node_id)}-{rec.pid}-"
    try:
        names = sorted(n for n in os.listdir(rec.sink_dir)
                       if n.startswith(mine) and n.endswith(".json"))
    except OSError:
        return
    for name in names[:-keep]:
        try:
            os.remove(os.path.join(rec.sink_dir, name))
        except OSError:
            pass
