"""Training-health watchtower: streaming anomaly detectors + reactions.

No reference counterpart: the reference delegates run health entirely to
TensorFlow's in-graph hooks (NaN guards, summary writers — SURVEY.md §5)
and the rest of our obs stack is *forensic* (metrics, traces, flight
dumps, SLO burn record what happened).  This module is the watching
half: a :class:`HealthMonitor` rides the training loop's existing
instrumentation (``utils.metrics.TrainMetrics`` feeds it step time,
infeed stall fraction and the per-step loss; ``utils.train.health_probe``
adds a device-computed global grad-norm behind ``TFOS_HEALTH_GRADNORM``)
and edge-triggers four streaming detectors:

- **NaN/Inf gate** — a non-finite loss (or grad norm) fires ``nan``;
- **loss spike** — loss above the EWMA mean by ``TFOS_HEALTH_SPIKE_SIGMA``
  EWMA standard deviations (after ``TFOS_HEALTH_WARMUP`` steps) fires
  ``loss_spike``;
- **step-time regression** — ``TFOS_HEALTH_STEP_PATIENCE`` consecutive
  steps slower than ``TFOS_HEALTH_STEP_FACTOR`` x the EWMA baseline
  fires ``slow_step``;
- **infeed stall** — the window stall fraction crossing
  ``TFOS_HEALTH_STALL_FRAC`` fires ``infeed_stall``.

Every firing lands in all three observability planes at once: a
``health/<kind>`` telemetry event, a flight-recorder snapshot
(``obs/flight.py`` — the ring freezes while the anomaly is fresh), and
the ``tfos_health_*`` registry metrics the obs publisher already ships
(so ``/healthz`` flips to ``degraded`` and ``tfos-top --health`` shows
the counts).  Edge-triggered means a detector fires on the transition
into its anomalous state and re-arms when the signal recovers — a
diverged run logs one event, not one per step.

Reactions (``TFOS_HEALTH_ACTION=none|checkpoint|halt``, numeric kinds
``nan`` only — spikes and stalls are advisory): ``checkpoint`` invokes
the monitor's ``checkpoint_fn`` (the trainer wires it to save the last
*finite* state), ``halt`` checkpoints then raises :class:`HealthHalt`,
which ``node.wrapper_fn`` catches and turns into a clean stop — a NaN at
step N costs one step of chip time, not the rest of the job.

The driver-side half, :func:`straggler_report`, runs over the per-node
``tfos_train_step_ms`` histograms the manager obs KV already carries
(``obs/http.ObsServer`` polls them): cross-node p50 skew, the slow node
named, exported as ``tfos_node_skew`` and a ``/statusz`` stragglers
table — the signal ROADMAP item 1's replica autoscaling consumes.
"""

from __future__ import annotations

import logging
import math
import os
import threading
import weakref

from tensorflowonspark_tpu.utils import metrics_registry, telemetry

logger = logging.getLogger(__name__)

ENABLE_ENV = "TFOS_HEALTH"                  # "0" disables the detectors
ACTION_ENV = "TFOS_HEALTH_ACTION"           # none | checkpoint | halt
GRADNORM_ENV = "TFOS_HEALTH_GRADNORM"       # device-side probe gate
SPIKE_SIGMA_ENV = "TFOS_HEALTH_SPIKE_SIGMA"
WARMUP_ENV = "TFOS_HEALTH_WARMUP"
STEP_FACTOR_ENV = "TFOS_HEALTH_STEP_FACTOR"
STEP_PATIENCE_ENV = "TFOS_HEALTH_STEP_PATIENCE"
STALL_FRAC_ENV = "TFOS_HEALTH_STALL_FRAC"

ACTIONS = ("none", "checkpoint", "halt")

#: Detector kinds a monitor can fire (the ``kind`` label of
#: ``tfos_health_anomalies_total`` and the suffix of ``health/<kind>``).
KINDS = ("nan", "loss_spike", "slow_step", "infeed_stall")

#: Kinds the configured reaction applies to: only numeric corruption is
#: worth stopping a run for — spikes and stalls are advisory signals.
REACT_KINDS = ("nan",)

_EWMA_ALPHA = 0.05  # ~20-step memory for the loss/step-time baselines


def enabled():
    """Detectors on unless ``TFOS_HEALTH=0`` (they are pure python and
    cost a few comparisons per step)."""
    return os.environ.get(ENABLE_ENV, "1").strip().lower() not in (
        "0", "false", "off", "no")


def action_from_env():
    """The configured reaction; an unknown value warns and means none
    (a typo'd reaction must not silently halt — or silently not)."""
    raw = os.environ.get(ACTION_ENV, "none").strip().lower() or "none"
    if raw not in ACTIONS:
        logger.warning("%s=%r not in %s; treating as 'none'",
                       ACTION_ENV, raw, ACTIONS)
        return "none"
    return raw


def _env_float(name, default):
    try:
        return float(os.environ.get(name, "") or default)
    except ValueError:
        logger.warning("%s is not a number; using %s", name, default)
        return float(default)


class HealthHalt(RuntimeError):
    """Raised by a monitor whose reaction is ``halt``; ``node.wrapper_fn``
    converts it into a clean run stop (checkpoint already written)."""


# Monitors constructed in this process, for bench.py's summary block and
# debugging; weak so short-lived trainers don't accumulate.
_MONITORS = weakref.WeakSet()
_MONITORS_LOCK = threading.Lock()

# Last straggler report computed in this process (driver side), folded
# into bench.py's health summary as max_skew.
_LAST_STRAGGLERS = {}


class HealthMonitor:
    """Streaming detectors over one trainer's step stream.

    Feed it from the loop via ``observe_step`` (``TrainMetrics.step``
    does this automatically when constructed with a monitor or when the
    detectors are enabled); every argument is optional — a detector
    without its signal simply stays quiet.
    """

    def __init__(self, action=None, checkpoint_fn=None, node=None):
        self.action = action_from_env() if action is None else str(action)
        if self.action not in ACTIONS:
            raise ValueError(f"action {self.action!r} not in {ACTIONS}")
        self.checkpoint_fn = checkpoint_fn
        self.node = node
        self.spike_sigma = _env_float(SPIKE_SIGMA_ENV, 6.0)
        self.warmup = int(_env_float(WARMUP_ENV, 20))
        self.step_factor = _env_float(STEP_FACTOR_ENV, 2.0)
        self.step_patience = int(_env_float(STEP_PATIENCE_ENV, 5))
        self.stall_frac = _env_float(STALL_FRAC_ENV, 0.5)
        # detector state
        self._loss_mean = None   # EWMA of loss
        self._loss_var = 0.0     # EWMA of squared deviation
        self._loss_seen = 0
        self._time_mean = None   # EWMA of step seconds
        self._time_seen = 0
        self._slow_run = 0       # consecutive slow steps
        self._in_anomaly = {}    # kind -> currently anomalous (edge state)
        self.counts = {}         # kind -> total firings
        self.last_anomaly = None  # dict describing the newest firing
        self.last_finite_step = None  # newest step with a finite loss
        with _MONITORS_LOCK:
            _MONITORS.add(self)

    # -- observation ---------------------------------------------------

    def observe_step(self, loss=None, step_time_s=None, infeed_frac=None,
                     grad_norm=None, grad_finite=None, step=None):
        """One completed train step's signals; returns the list of
        anomaly kinds that fired (edge transitions only).

        ``loss``/``grad_norm`` must already be host floats — the caller
        decides when to pay the device sync (``TrainMetrics`` fetches
        the loss it is handed; the grad probe is one scalar)."""
        fired = []
        fired += self._observe_finite(loss, grad_norm, grad_finite, step)
        if loss is not None and math.isfinite(float(loss)):
            fired += self._observe_spike(float(loss), step)
        if grad_norm is not None and math.isfinite(float(grad_norm)):
            metrics_registry.set_gauge("tfos_health_grad_norm",
                                       float(grad_norm))
        if step_time_s is not None:
            fired += self._observe_step_time(float(step_time_s), step)
        if infeed_frac is not None:
            fired += self._observe_stall(float(infeed_frac), step)
        return fired

    def _observe_finite(self, loss, grad_norm, grad_finite, step):
        bad = []
        if loss is not None and not math.isfinite(float(loss)):
            bad.append(("loss", float(loss)))
        if grad_norm is not None and not math.isfinite(float(grad_norm)):
            bad.append(("grad_norm", float(grad_norm)))
        if grad_finite is not None and not bool(grad_finite):
            bad.append(("grad_finite", 0.0))
        if not bad:
            if loss is not None and step is not None:
                self.last_finite_step = step
            self._in_anomaly["nan"] = False
            return []
        source, value = bad[0]
        return self._fire("nan", step, source=source, value=str(value),
                          last_finite_step=self.last_finite_step)

    def _observe_spike(self, loss, step):
        mean, var, seen = self._loss_mean, self._loss_var, self._loss_seen
        fired = []
        if seen >= self.warmup and mean is not None:
            sigma = math.sqrt(max(var, 0.0))
            floor = 1e-3 * max(abs(mean), 1.0)  # dead-flat loss guard
            threshold = mean + self.spike_sigma * max(sigma, floor)
            if loss > threshold:
                fired = self._fire("loss_spike", step, loss=round(loss, 6),
                                   mean=round(mean, 6),
                                   threshold=round(threshold, 6))
            else:
                self._in_anomaly["loss_spike"] = False
        # update the baseline AFTER the test (a spike must not drag the
        # mean up before it is judged); spikes still enter the EWMA so a
        # genuine regime change re-arms within ~1/alpha steps
        if mean is None:
            self._loss_mean, self._loss_var = loss, 0.0
        else:
            d = loss - mean
            self._loss_mean = mean + _EWMA_ALPHA * d
            self._loss_var = (1 - _EWMA_ALPHA) * (var + _EWMA_ALPHA * d * d)
        self._loss_seen = seen + 1
        return fired

    def _observe_step_time(self, dur_s, step):
        mean, seen = self._time_mean, self._time_seen
        fired = []
        if seen >= self.warmup and mean is not None and mean > 0:
            if dur_s > self.step_factor * mean:
                self._slow_run += 1
                if self._slow_run >= self.step_patience:
                    fired = self._fire(
                        "slow_step", step,
                        step_ms=round(dur_s * 1000.0, 3),
                        baseline_ms=round(mean * 1000.0, 3),
                        consecutive=self._slow_run)
            else:
                self._slow_run = 0
                self._in_anomaly["slow_step"] = False
            # slow steps are excluded from the baseline while the run is
            # anomalous — a stuck-slow node must keep comparing against
            # its healthy self, not converge to the regression
            if self._slow_run:
                return fired
        if mean is None:
            self._time_mean = dur_s
        else:
            self._time_mean = mean + _EWMA_ALPHA * (dur_s - mean)
        self._time_seen = seen + 1
        return fired

    def _observe_stall(self, frac, step):
        if self._loss_seen + self._time_seen < self.warmup:
            return []
        if frac >= self.stall_frac:
            return self._fire("infeed_stall", step,
                              stall_frac=round(frac, 4),
                              threshold=self.stall_frac)
        self._in_anomaly["infeed_stall"] = False
        return []

    # -- firing + reactions --------------------------------------------

    def _fire(self, kind, step, **attrs):
        if not self._in_anomaly.get(kind):
            self._in_anomaly[kind] = True
            self.counts[kind] = self.counts.get(kind, 0) + 1
            self.last_anomaly = dict(kind=kind, step=step, **attrs)
            logger.warning("health: %s anomaly at step %s (%s)",
                           kind, step, attrs)
            telemetry.event(f"health/{kind}", step=step,
                            action=self.action, **attrs)
            metrics_registry.inc("tfos_health_anomalies_total", kind=kind)
            metrics_registry.set_gauge("tfos_health_status", 1.0)
            if step is not None:
                metrics_registry.set_gauge("tfos_health_last_anomaly_step",
                                           float(step))
            # freeze the flight ring while the last N seconds still show
            # the approach to the anomaly (ISSUE 16 satellite: health/*
            # joins the supervision events as a dump trigger)
            try:
                from tensorflowonspark_tpu.obs import flight as _flight

                _flight.snapshot(f"health/{kind}", node=self.node,
                                 reason=f"{kind} at step {step}")
            except Exception:  # noqa: BLE001 - dumps are best-effort
                logger.debug("flight snapshot failed", exc_info=True)
            self._react(kind, step)
            return [kind]
        return []

    def _react(self, kind, step):
        if self.action == "none" or kind not in REACT_KINDS:
            return
        if self.checkpoint_fn is not None:
            try:
                self.checkpoint_fn()
                logger.warning(
                    "health: checkpointed at last finite step %s "
                    "(action=%s)", self.last_finite_step, self.action)
            except Exception:  # noqa: BLE001 - still halt if asked
                logger.exception("health: reaction checkpoint failed")
        if self.action == "halt":
            telemetry.flush()  # the event must survive the stop
            raise HealthHalt(
                f"health: {kind} at step {step} (action=halt; "
                f"last finite step {self.last_finite_step})")

    # -- reading -------------------------------------------------------

    @property
    def status(self):
        return "degraded" if any(self._in_anomaly.values()) else "ok"

    def summary(self):
        return {"anomalies": dict(self.counts),
                "total": sum(self.counts.values()),
                "status": self.status,
                "last": self.last_anomaly}


def monitor_from_env(checkpoint_fn=None, node=None):
    """The zero-config constructor ``TrainMetrics`` uses: a monitor when
    the detectors are enabled, else None (every observe call skipped)."""
    if not enabled():
        return None
    return HealthMonitor(checkpoint_fn=checkpoint_fn, node=node)


def process_summary():
    """Aggregate health over every monitor this process created plus the
    last straggler report — bench.py's ``health`` block."""
    anomalies = {}
    total = 0
    status = "ok"
    with _MONITORS_LOCK:
        monitors = list(_MONITORS)
    for m in monitors:
        for kind, n in m.counts.items():
            anomalies[kind] = anomalies.get(kind, 0) + n
            total += n
        if m.status == "degraded":
            status = "degraded"
    out = {"anomalies": anomalies, "total": total, "status": status,
           "max_skew": _LAST_STRAGGLERS.get("skew")}
    if _LAST_STRAGGLERS.get("slowest"):
        out["slowest_node"] = _LAST_STRAGGLERS["slowest"]
    return out


# -- driver-side straggler analysis ------------------------------------


def _step_hist(snap, metric="tfos_train_step_ms"):
    ent = (snap or {}).get(metric)
    for s in (ent or {}).get("series", ()):
        if "count" in s:
            return s
    return None


def straggler_report(node_entries, min_nodes=2, min_count=2,
                     emit=True):
    """Cross-node step-time skew from ``ObsServer`` node entries.

    ``node_entries`` is ``{node_id: {"metrics": snapshot, ...}}`` (the
    shape ``ObsServer._node_entries`` returns).  Nodes publishing a
    ``tfos_train_step_ms`` histogram with at least ``min_count`` samples
    enter the comparison; with fewer than ``min_nodes`` of them there is
    no cross-node statement to make and the report is None.

    Returns ``{"skew", "slowest", "fastest", "nodes": [{node, p50_ms,
    steps, rel}...]}`` where ``skew`` = slowest p50 / fastest p50 and
    ``rel`` is each node's p50 relative to the fastest.  ``emit=True``
    also sets the driver-registry ``tfos_node_skew`` gauge and caches
    the result for :func:`process_summary`."""
    rows = []
    for nid, ent in sorted((node_entries or {}).items()):
        h = _step_hist(ent.get("metrics"))
        if not h or h.get("count", 0) < min_count:
            continue
        p50 = metrics_registry.quantile(h, 0.5)
        if p50 is None or p50 <= 0:
            continue
        rows.append({"node": nid, "p50_ms": round(float(p50), 3),
                     "steps": int(h["count"])})
    if len(rows) < min_nodes:
        return None
    fastest = min(rows, key=lambda r: r["p50_ms"])
    slowest = max(rows, key=lambda r: r["p50_ms"])
    for r in rows:
        r["rel"] = round(r["p50_ms"] / fastest["p50_ms"], 3)
    skew = round(slowest["p50_ms"] / fastest["p50_ms"], 3)
    report = {"skew": skew, "slowest": slowest["node"],
              "fastest": fastest["node"], "nodes": rows}
    if emit:
        metrics_registry.set_gauge("tfos_node_skew", skew)
        _LAST_STRAGGLERS.clear()
        _LAST_STRAGGLERS.update(skew=skew, slowest=slowest["node"])
    return report


def snapshot_anomaly_total(snap):
    """Total ``tfos_health_anomalies_total`` across kinds in one registry
    snapshot (the ``/healthz`` degraded test), or None when unreported."""
    ent = (snap or {}).get("tfos_health_anomalies_total")
    if not ent:
        return None
    return sum(s.get("value", 0.0) for s in ent.get("series", ()))
