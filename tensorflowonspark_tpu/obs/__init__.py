"""Live observability plane (no reference equivalent — the reference's
observability is log lines only, reference ``TFCluster.py:343-344``,
SURVEY.md §5).

Three layers, all stdlib-only and env-gated on ``TFOS_OBS_PORT``:

- ``utils/metrics_registry.py`` — in-process counters/gauges/histograms
  bumped by the instrumented subsystems (engine, feed, train metrics,
  data service, serving, checkpoint).
- ``obs/publish.py`` — a per-node daemon thread snapshotting the
  registry into the executor manager's KV (``obs:<node_id>`` keys).
- ``obs/http.py`` — the driver-side HTTP server polling every node's
  KV and exposing ``/metrics`` (Prometheus text), ``/healthz`` and
  ``/statusz``; ``obs/top.py`` renders ``/statusz`` as a live table
  (``tfos-top``).

When ``TFOS_OBS_PORT`` is unset everything here is inert: no server,
no threads, no registry, and every instrumentation call is a cached
no-op (see docs/observability.md).
"""

from tensorflowonspark_tpu.utils.metrics_registry import (  # noqa: F401
    PORT_ENV,
    enabled,
)
from tensorflowonspark_tpu.obs.http import ObsServer, start_for_cluster  # noqa: F401
from tensorflowonspark_tpu.obs.publish import start_publisher  # noqa: F401
