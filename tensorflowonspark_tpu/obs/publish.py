"""Node-side metrics publisher: registry snapshot -> manager KV.

The executor-side half of the live metrics plane (driver half:
``obs/http.py``).  Each instrumented process that holds a manager
connection — the trainer (``node.wrapper_fn``), a data worker
(``data/service.py``) — runs ``start_publisher`` / calls
``publish_once`` to ship its ``metrics_registry.snapshot()`` into the
manager KV under ``obs:<node_id>`` (``manager.TFManager.obs_publish``),
where the driver's poll thread collects it.  Same wire and same
best-effort discipline as the telemetry spool registry
(``telemetry.register_with``): publishing must never take a worker
down, and when ``TFOS_OBS_PORT`` is unset nothing runs at all.

The daemon is also the node end of the **on-demand control plane**
(ISSUE 16): each tick it consumes at most one directive the driver
posted under ``obsctl:<node_id>`` (``POST /profilez`` asks for a
``utils.profiler.trace`` capture of ``ms`` milliseconds; ``/flightz``
for a flight-recorder dump), executes it in place, and spools the
result — capture/dump path, or the degrade reason — back under
``obsack:<node_id>`` for the driver to pick up.  A sick node can be
profiled mid-run without restarting anything; a capture that cannot
start (CPU image without the profiler backend) acks the warning instead
of dying.
"""

from __future__ import annotations

import logging
import os
import tempfile
import threading
import time

from tensorflowonspark_tpu.utils import metrics_registry, telemetry

logger = logging.getLogger(__name__)

#: Longest on-demand profile window honored, ms (a typo'd ``ms=`` must
#: not wedge the publish daemon for an hour).
MAX_PROFILE_MS = 60_000


def publish_once(mgr, node_id, role=None):
    """Snapshot this process's registry into the manager KV; returns
    True when a payload landed.  Best-effort: a dead manager (node
    tearing down) is a debug line, never an error."""
    snap = metrics_registry.snapshot()
    if snap is None:
        return False
    payload = {
        "ts": time.time(),
        "node_id": str(node_id),
        "role": str(role or "proc"),
        "pid": os.getpid(),
        "metrics": snap,
    }
    try:
        mgr.obs_publish(str(node_id), payload)
        return True
    except Exception as e:  # noqa: BLE001 - publishing is best-effort
        logger.debug("obs publish failed for %s: %s", node_id, e)
        return False


def _capture_dir(node_id):
    """Where an on-demand capture lands: the telemetry sink dir when the
    tracing plane is on (the driver drain already collects it), else a
    tmpdir — the ack carries the absolute path either way."""
    rec = telemetry._get()
    base = rec.sink_dir if rec is not None else tempfile.gettempdir()
    return os.path.join(base, f"profile-{node_id}-{os.getpid()}")


def serve_control(mgr, node_id):
    """Consume and execute at most one control directive for this node;
    returns the ack dict that was spooled back, or None when the slot
    was empty.  Best-effort like everything on this wire: a dead manager
    or a broken directive is a debug line, never a worker death."""
    try:
        d = mgr.obs_control_take(str(node_id))
    except Exception as e:  # noqa: BLE001 - manager gone / old manager
        logger.debug("obs control take failed for %s: %s", node_id, e)
        return None
    if not isinstance(d, dict):
        return None
    cmd = str(d.get("cmd", ""))
    ack = {"seq": d.get("seq"), "cmd": cmd, "node_id": str(node_id),
           "ts": time.time(), "ok": False}
    try:
        if cmd == "profile":
            ms = min(max(int(d.get("ms") or 1000), 1), MAX_PROFILE_MS)
            from tensorflowonspark_tpu.utils import profiler

            out = _capture_dir(node_id)
            started = profiler.start_trace(out)
            time.sleep(ms / 1000.0)
            if started:
                started = profiler.stop_trace()
            ack.update(ok=bool(started), ms=ms,
                       capture=out if started else None)
            if not started:
                ack["error"] = "profiler capture unavailable (no-op)"
            metrics_registry.inc("tfos_health_captures_total",
                                 kind="profile",
                                 status="ok" if started else "degraded")
        elif cmd == "flight":
            from tensorflowonspark_tpu.obs import flight

            path = flight.snapshot("health/on_demand", node=str(node_id),
                                   reason=d.get("reason") or "on-demand")
            ack.update(ok=path is not None, capture=path)
            if path is None:
                ack["error"] = "telemetry disabled: no flight ring"
            metrics_registry.inc("tfos_health_captures_total",
                                 kind="flight",
                                 status="ok" if path else "degraded")
        else:
            ack["error"] = f"unknown cmd {cmd!r}"
    except Exception as e:  # noqa: BLE001 - directive must still ack
        logger.warning("obs control %r failed on %s: %s", cmd, node_id, e)
        ack["error"] = str(e)[:200]
    try:
        mgr.obs_control_ack(str(node_id), ack)
    except Exception as e:  # noqa: BLE001 - manager gone
        logger.debug("obs control ack failed for %s: %s", node_id, e)
    return ack


def start_publisher(mgr, node_id, role=None, interval=None):
    """Daemon thread publishing every ``interval`` seconds
    (``TFOS_OBS_INTERVAL``); returns a stop Event, or None when the
    metrics plane is disabled.  Setting the event publishes one final
    snapshot so short-lived processes still land their tail counts.
    Each tick also serves one pending control directive (profile /
    flight — see :func:`serve_control`)."""
    if not metrics_registry.enabled():
        return None
    period = metrics_registry.interval() if interval is None else float(interval)
    stop = threading.Event()

    def _run():
        while not stop.wait(period):
            if not publish_once(mgr, node_id, role):
                # manager gone: the node is exiting, stop quietly
                return
            serve_control(mgr, node_id)
        publish_once(mgr, node_id, role)

    t = threading.Thread(target=_run, name="tfos-obs-publish", daemon=True)
    t.start()
    return stop
