"""Node-side metrics publisher: registry snapshot -> manager KV.

The executor-side half of the live metrics plane (driver half:
``obs/http.py``).  Each instrumented process that holds a manager
connection — the trainer (``node.wrapper_fn``), a data worker
(``data/service.py``) — runs ``start_publisher`` / calls
``publish_once`` to ship its ``metrics_registry.snapshot()`` into the
manager KV under ``obs:<node_id>`` (``manager.TFManager.obs_publish``),
where the driver's poll thread collects it.  Same wire and same
best-effort discipline as the telemetry spool registry
(``telemetry.register_with``): publishing must never take a worker
down, and when ``TFOS_OBS_PORT`` is unset nothing runs at all.
"""

from __future__ import annotations

import logging
import os
import threading
import time

from tensorflowonspark_tpu.utils import metrics_registry

logger = logging.getLogger(__name__)


def publish_once(mgr, node_id, role=None):
    """Snapshot this process's registry into the manager KV; returns
    True when a payload landed.  Best-effort: a dead manager (node
    tearing down) is a debug line, never an error."""
    snap = metrics_registry.snapshot()
    if snap is None:
        return False
    payload = {
        "ts": time.time(),
        "node_id": str(node_id),
        "role": str(role or "proc"),
        "pid": os.getpid(),
        "metrics": snap,
    }
    try:
        mgr.obs_publish(str(node_id), payload)
        return True
    except Exception as e:  # noqa: BLE001 - publishing is best-effort
        logger.debug("obs publish failed for %s: %s", node_id, e)
        return False


def start_publisher(mgr, node_id, role=None, interval=None):
    """Daemon thread publishing every ``interval`` seconds
    (``TFOS_OBS_INTERVAL``); returns a stop Event, or None when the
    metrics plane is disabled.  Setting the event publishes one final
    snapshot so short-lived processes still land their tail counts."""
    if not metrics_registry.enabled():
        return None
    period = metrics_registry.interval() if interval is None else float(interval)
    stop = threading.Event()

    def _run():
        while not stop.wait(period):
            if not publish_once(mgr, node_id, role):
                # manager gone: the node is exiting, stop quietly
                return
        publish_once(mgr, node_id, role)

    t = threading.Thread(target=_run, name="tfos-obs-publish", daemon=True)
    t.start()
    return stop
