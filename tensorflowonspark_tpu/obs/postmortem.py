"""``tfos-postmortem``: assemble flight dumps into a death timeline.

No reference counterpart (the reference's postmortem workflow is
grepping executor stdout, SURVEY.md §5).  This tool answers "what was
everyone doing in the last N seconds before worker-3 died": it walks a
telemetry tree for ``flight-*.json`` dumps (written by
obs/flight.py on supervision events) plus the per-process ``*.jsonl``
spools, and renders one report per trigger — victim, reason, the
victim's last records, the in-flight work at the moment of death, and
a per-node activity table over the trailing window.

Hardening mirrors ``telemetry.read_spool``: truncated or corrupt
dumps (a SIGKILL can land mid-``write``) are skipped and *counted*,
never fatal; spool lines are parsed tolerantly the same way.

Usage::

    tfos-postmortem --dir TELEMETRY_DIR [--window 30] [--all]
    python -m tensorflowonspark_tpu.obs.postmortem --dir ...
"""

from __future__ import annotations

import argparse
import glob
import json
import os
import sys
import time

from tensorflowonspark_tpu.utils import telemetry


def load_dumps(root):
    """(dumps oldest->newest, corrupt_count) under ``root`` (recursive).

    A usable dump is one JSON object with a ``trigger`` key; anything
    else — truncated write, garbage, wrong shape — is skipped-with-
    count (the read_spool hardening contract)."""
    dumps, corrupt = [], 0
    pattern = os.path.join(root, "**", "flight-*.json")
    for path in sorted(glob.glob(pattern, recursive=True)):
        try:
            with open(path, encoding="utf-8", errors="replace") as f:
                doc = json.load(f)
            if not isinstance(doc, dict) or "trigger" not in doc:
                raise ValueError("not a flight dump")
        except (OSError, ValueError):
            corrupt += 1
            continue
        doc["_path"] = path
        dumps.append(doc)
    dumps.sort(key=lambda d: d.get("ts") or 0.0)
    return dumps, corrupt


def load_spool_records(root):
    """Every parseable telemetry record under ``root`` (recursive),
    via the hardened ``telemetry.read_spool`` per directory."""
    dirs = {os.path.dirname(p) for p in glob.glob(
        os.path.join(root, "**", "*.jsonl"), recursive=True)}
    records = []
    for d in sorted(dirs):
        for _name, text in telemetry.read_spool(d):
            for line in text.splitlines():
                try:
                    rec = json.loads(line)
                except ValueError:
                    continue
                if isinstance(rec, dict):
                    records.append(rec)
    records.sort(key=lambda r: r.get("ts") or 0.0)
    return records


def _fmt_ts(ts):
    return time.strftime("%Y-%m-%dT%H:%M:%S", time.gmtime(ts)) + (
        "%.3fZ" % (ts % 1))[1:]


def _fmt_rec(rec, t0):
    dt = (rec.get("ts") or 0.0) - t0
    dur = rec.get("dur_ms")
    dur_s = f" ({dur:.1f}ms)" if isinstance(dur, (int, float)) else ""
    attrs = rec.get("attrs") or {}
    keys = ("trace_id", "sid", "error", "reason", "replica", "queue_ms")
    hint = " ".join(f"{k}={attrs[k]}" for k in keys if k in attrs)
    return (f"  {dt:+8.2f}s {rec.get('kind', '?'):<5} "
            f"{rec.get('name', '?')}{dur_s}"
            + (f"  [{hint}]" if hint else ""))


def render_report(dump, records, window, out):
    """One postmortem section for ``dump`` onto stream ``out``."""
    t0 = dump.get("ts") or 0.0
    victim = dump.get("node") or "<unknown>"
    by = dump.get("recorded_by") or {}
    print(f"POSTMORTEM  trigger={dump['trigger']}  victim={victim}  "
          f"reason={dump.get('reason')}", file=out)
    print(f"  at {_fmt_ts(t0)}  "
          f"(observed by {by.get('node_id')}/{by.get('role')}, "
          f"dump {os.path.basename(dump.get('_path', '?'))})", file=out)

    inflight = dump.get("inflight") or []
    print(f"\n  In flight at the event ({len(inflight)}):", file=out)
    for item in inflight or [{"(none)": ""}]:
        line = " ".join(f"{k}={v}" for k, v in item.items())
        print(f"    {line}", file=out)

    window_recs = [r for r in records
                   if t0 - window <= (r.get("ts") or 0.0) <= t0 + 1.0]
    nodes = {}
    for r in window_recs:
        nodes.setdefault(r.get("node_id", "?"), []).append(r)
    print(f"\n  Last {window:.0f}s before the event, per node:", file=out)
    for nid in sorted(nodes):
        recs = nodes[nid]
        last = recs[-1]
        mark = "  <- victim" if nid == victim else ""
        print(f"    {nid:<16} {len(recs):>5} records   last: "
              f"{last.get('name', '?')} "
              f"({(last.get('ts') or 0) - t0:+.2f}s){mark}", file=out)
    if not nodes:
        print("    (no spool records in the window)", file=out)

    victim_recs = (nodes.get(victim)
                   or [r for r in dump.get("records") or []
                       if r.get("node_id") == victim])[-10:]
    print(f"\n  {victim}'s last records:", file=out)
    for r in victim_recs or ():
        print(_fmt_rec(r, t0), file=out)
    if not victim_recs:
        print("    (none found — the ring died with the process; see "
              "the observer's dump records above)", file=out)
    print("", file=out)


def build_parser():
    p = argparse.ArgumentParser(
        prog="tfos-postmortem",
        description="Assemble flight-recorder dumps into a "
                    "who-was-doing-what report",
    )
    p.add_argument("--dir", required=True,
                   help="telemetry tree holding flight-*.json dumps "
                        "and *.jsonl spools (TFOS_TELEMETRY_DIR)")
    p.add_argument("--window", type=float, default=None,
                   help="trailing seconds of context per report "
                        "(default: the dump's own window)")
    p.add_argument("--all", action="store_true",
                   help="render every dump, not just the newest")
    return p


def main(argv=None, out=None):
    out = out or sys.stdout
    args = build_parser().parse_args(argv)
    dumps, corrupt = load_dumps(args.dir)
    if corrupt:
        print(f"tfos-postmortem: skipped {corrupt} corrupt/truncated "
              f"dump(s)", file=out)
    if not dumps:
        print(f"tfos-postmortem: no usable flight dumps under "
              f"{args.dir}", file=out)
        return 2
    records = load_spool_records(args.dir)
    for dump in (dumps if args.all else dumps[-1:]):
        window = (args.window if args.window is not None
                  else float(dump.get("window_s") or 30.0))
        render_report(dump, records, window, out)
    return 0


if __name__ == "__main__":
    sys.exit(main())
