"""Declarative SLOs + error-budget burn rate over the live metrics plane.

No reference counterpart: the reference's only service-level signal is
TensorBoard scalars written by user code (``TFNode.py:152`` hands back a
summary writer; SURVEY.md §6) — nothing states an objective, so nothing
can say how fast it is being missed.  Here objectives are declared once
(``TFOS_SLO``, defaults below), evaluated continuously from the same
registry snapshots the obs plane already polls out of the manager KV
(``obs/http.py`` ``ObsServer``), and surfaced three ways:

- ``tfos_slo_*`` gauges/counters in the driver registry (``/metrics``);
- a ``slo`` section on ``/statusz`` plus a dedicated ``/slo`` endpoint;
- the ``tfos-top --slo`` pane (obs/top.py).

Objective grammar (``TFOS_SLO``; semicolon-separated)::

    entry := name ":latency:" histogram "<" threshold_ms "@" good_pct
           | name ":availability:" counter "@" good_pct

``latency`` reads one histogram metric (merged across nodes) and asks
that ``good_pct``% of observations land at or under ``threshold_ms``.
``availability`` reads one status-labelled counter (``status="ok"`` is
good, anything else is bad) and asks that ``good_pct``% of outcomes be
good.  A typo'd spec fails loudly at parse and disables the engine —
a silently-wrong SLO is worse than none.

Burn rate is the standard error-budget quotient: the observed bad
fraction divided by the allowed bad fraction (``1 - good_pct/100``).
``burn == 1.0`` spends the budget exactly as fast as the objective
allows; ``burn > 1`` is a breach in progress.  Breach *transitions*
(edge-triggered, per objective) increment ``tfos_slo_breaches_total``.
"""

from __future__ import annotations

import logging
import math
import os
import time

from tensorflowonspark_tpu.utils import metrics_registry

logger = logging.getLogger(__name__)

SPEC_ENV = "TFOS_SLO"

#: Ships the two objectives the serving tiers document (docs/serving.md):
#: decode TTFT p99 under 500 ms, and 99% of serve requests not shed or
#: errored.  Override (or disable with an empty string) via TFOS_SLO.
DEFAULT_SPEC = ("decode_ttft:latency:tfos_decode_ttft_ms<500@99;"
                "serve_availability:availability:tfos_serve_requests_total@99")

KINDS = ("latency", "availability")


class Objective:
    """One parsed SLO entry (see module docstring for the grammar)."""

    __slots__ = ("name", "kind", "metric", "threshold_ms", "target")

    def __init__(self, name, kind, metric, threshold_ms, target):
        self.name = name
        self.kind = kind
        self.metric = metric
        self.threshold_ms = threshold_ms  # None for availability
        self.target = target              # fraction of GOOD outcomes, 0..1

    def __repr__(self):
        pct = f"{self.target * 100:g}"
        if self.kind == "latency":
            return (f"{self.name}:latency:{self.metric}"
                    f"<{self.threshold_ms:g}@{pct}")
        return f"{self.name}:availability:{self.metric}@{pct}"


def parse_spec(spec):
    """``TFOS_SLO`` string -> list of :class:`Objective`.

    Raises ``ValueError`` on any malformed entry."""
    objectives = []
    for raw in str(spec or "").split(";"):
        entry = raw.strip()
        if not entry:
            continue
        parts = entry.split(":")
        if len(parts) != 3:
            raise ValueError(
                f"slo entry {entry!r}: expected name:kind:spec")
        name, kind, rest = (p.strip() for p in parts)
        if not name:
            raise ValueError(f"slo entry {entry!r}: empty name")
        if kind not in KINDS:
            raise ValueError(f"slo entry {entry!r}: unknown kind {kind!r} "
                             f"(valid: {', '.join(KINDS)})")
        rest, sep, pct_s = rest.partition("@")
        if not sep:
            raise ValueError(f"slo entry {entry!r}: missing @good_pct")
        try:
            pct = float(pct_s)
        except ValueError:
            raise ValueError(
                f"slo entry {entry!r}: non-numeric target {pct_s!r}"
            ) from None
        if not 0.0 < pct < 100.0:
            raise ValueError(
                f"slo entry {entry!r}: target must be in (0, 100)")
        threshold = None
        metric = rest.strip()
        if kind == "latency":
            metric, sep, thr_s = metric.partition("<")
            if not sep:
                raise ValueError(
                    f"slo entry {entry!r}: latency needs metric<threshold_ms")
            try:
                threshold = float(thr_s)
            except ValueError:
                raise ValueError(
                    f"slo entry {entry!r}: non-numeric threshold {thr_s!r}"
                ) from None
            metric = metric.strip()
        if not metric:
            raise ValueError(f"slo entry {entry!r}: empty metric name")
        objectives.append(Objective(name, kind, metric, threshold,
                                    pct / 100.0))
    return objectives


# -- snapshot math ---------------------------------------------------------


def merge_histogram(snaps, metric):
    """Sum one histogram metric's series across node snapshots into a
    single series dict (the ``quantile`` input shape).  Series whose
    bucket bounds differ from the first one seen are skipped — mixing
    incompatible bucketings would silently corrupt the tail.  Returns
    None when no snapshot carries the metric."""
    merged = None
    for snap in snaps:
        ent = (snap or {}).get(metric)
        for s in (ent or {}).get("series", ()):
            if "count" not in s:
                continue
            bounds = list(s.get("bounds", ()))
            if merged is None:
                merged = {"bounds": bounds,
                          "counts": list(s.get("counts", ())),
                          "sum": float(s.get("sum", 0.0)),
                          "count": int(s.get("count", 0))}
                continue
            if bounds != merged["bounds"]:
                logger.debug("slo: %s series with mismatched buckets "
                             "skipped", metric)
                continue
            for i, c in enumerate(s.get("counts", ())):
                if i < len(merged["counts"]):
                    merged["counts"][i] += c
            merged["sum"] += float(s.get("sum", 0.0))
            merged["count"] += int(s.get("count", 0))
    return merged


def fraction_over(series, threshold):
    """Estimated fraction of a histogram's observations ABOVE
    ``threshold`` (linear interpolation inside the containing bucket,
    mirroring ``metrics_registry.quantile``).  The +Inf bucket counts
    entirely as over.  None for an empty series."""
    count = series.get("count", 0) if series else 0
    if not count:
        return None
    bounds = list(series.get("bounds", ()))
    counts = list(series.get("counts", ()))
    cum = 0.0
    lo = 0.0
    for i, c in enumerate(counts):
        hi = bounds[i] if i < len(bounds) else math.inf
        if threshold <= hi:
            if hi == math.inf or hi <= lo:
                under = cum  # whole open-ended bucket counts as over
            else:
                under = cum + c * (threshold - lo) / (hi - lo)
            return max(0.0, min(1.0, (count - under) / count))
        cum += c
        lo = hi
    return 0.0


def counter_outcomes(snaps, metric):
    """(good, total) across every node's series of one status-labelled
    counter: ``status="ok"`` (or an unlabelled series) is good."""
    good = total = 0.0
    for snap in snaps:
        ent = (snap or {}).get(metric)
        for s in (ent or {}).get("series", ()):
            if "value" not in s:
                continue
            v = float(s.get("value", 0.0))
            total += v
            if s.get("labels", {}).get("status", "ok") == "ok":
                good += v
    return good, total


def evaluate(objective, snaps):
    """One objective against a list of registry snapshots -> report row.

    ``burn``/``current`` are None until the metric has samples (an SLO
    with no traffic is not breaching, it is unmeasured)."""
    allowed = max(1e-9, 1.0 - objective.target)
    row = {"name": objective.name, "kind": objective.kind,
           "metric": objective.metric,
           "target_pct": round(objective.target * 100.0, 4),
           "current": None, "burn": None, "breaching": False,
           "samples": 0}
    if objective.kind == "latency":
        row["threshold_ms"] = objective.threshold_ms
        hist = merge_histogram(snaps, objective.metric)
        over = fraction_over(hist, objective.threshold_ms)
        if over is None:
            return row
        row["samples"] = hist["count"]
        q = metrics_registry.quantile(hist, objective.target)
        row["current"] = None if q is None else round(q, 3)
        row["burn"] = round(over / allowed, 4)
    else:
        good, total = counter_outcomes(snaps, objective.metric)
        if not total:
            return row
        row["samples"] = int(total)
        row["current"] = round(good / total, 6)
        row["burn"] = round((1.0 - good / total) / allowed, 4)
    row["breaching"] = bool(row["burn"] is not None and row["burn"] > 1.0)
    return row


class Engine:
    """Holds the parsed objectives + breach edge state; one per
    ObsServer.  ``step`` evaluates every objective against the given
    snapshots, publishes the ``tfos_slo_*`` series into this process's
    registry, and caches the report for ``/statusz`` and ``/slo``."""

    def __init__(self, spec=None):
        if spec is None:
            spec = os.environ.get(SPEC_ENV, DEFAULT_SPEC)
        try:
            self.objectives = parse_spec(spec)
        except ValueError:
            logger.exception("invalid %s=%r; slo engine disabled",
                             SPEC_ENV, spec)
            self.objectives = []
        self._breaching = {}
        self._report = {"ts": None, "objectives": []}

    def step(self, snaps, emit=True):
        rows = [evaluate(o, snaps) for o in self.objectives]
        if emit:
            for row in rows:
                if row["burn"] is None:
                    continue
                metrics_registry.set_gauge("tfos_slo_burn_rate",
                                           row["burn"],
                                           objective=row["name"])
                if row["current"] is not None:
                    metrics_registry.set_gauge("tfos_slo_current",
                                               row["current"],
                                               objective=row["name"])
                was = self._breaching.get(row["name"], False)
                if row["breaching"] and not was:
                    metrics_registry.inc("tfos_slo_breaches_total",
                                         objective=row["name"])
                self._breaching[row["name"]] = row["breaching"]
        self._report = {"ts": time.time(), "objectives": rows}
        return self._report

    def report(self):
        """The last computed report (never None; empty before the first
        ``step``)."""
        return self._report
