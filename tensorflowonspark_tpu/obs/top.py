"""``tfos-top`` — live cluster view over the /statusz endpoint.

A ``top(1)``-style terminal view of a running cluster (no reference
equivalent; the reference's only runtime surface is driver log lines,
reference ``TFCluster.py:343-344``).  Polls ``/statusz`` from the
driver's ``ObsServer`` (``obs/http.py``) and renders one row per node:
role, liveness, step rate, queue depth, stall %, respawns, serving SLO
percentiles.  Plain ANSI redraw (clear + reprint) rather than curses —
it works over ssh, inside ``watch``, and in CI logs; ``--once`` prints
a single snapshot and exits (the form the fast-lane test drives).

Usage::

    tfos-top [--url http://127.0.0.1:9090] [--interval 2] [--once]
             [--slo] [--health] [--deploy] [--pods]

``--url`` defaults to ``http://127.0.0.1:$TFOS_OBS_PORT``.  ``--slo``
appends the SLO pane (one row per objective from the ``slo`` section of
``/statusz``: tracked value, burn rate, breach flag — ``obs/slo.py``).
``--health`` appends the watchtower pane: per-node health state and
anomaly counts plus the driver's straggler table (``obs/health.py``).
``--pods`` appends the serving-fabric pane: one row per fabric host
from the ``pods`` section of ``/statusz`` (``serving/fabric/``).
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time
import urllib.error
import urllib.request

from tensorflowonspark_tpu.utils import metrics_registry

CLEAR = "\x1b[H\x1b[2J"

COLUMNS = (
    # (header, width, extractor) over a /statusz node entry
    ("NODE", 14, lambda nid, e: nid),
    ("ROLE", 9, lambda nid, e: e.get("role") or "?"),
    ("UP", 4, lambda nid, e: "yes" if e.get("alive") else "DOWN"),
    ("SEEN", 6, lambda nid, e: _secs(e.get("last_seen_age_s"))),
    ("STEPS", 7, lambda nid, e: _num(_s(e).get("steps"))),
    ("STEP-MS", 8, lambda nid, e: _num(_s(e).get("step_ms_p50"))),
    ("ITEMS/S", 8, lambda nid, e: _num(_s(e).get("items_per_sec"))),
    ("MFU%", 6, lambda nid, e: _pct(_s(e).get("mfu"))),
    ("STALL%", 7, lambda nid, e: _pct(_s(e).get("stall_frac"))),
    ("QDEPTH", 7, lambda nid, e: _num(_s(e).get("queue_depth"))),
    ("RSPWN", 6, lambda nid, e: _num(_s(e).get("respawns"))),
    ("P50/P99", 12, lambda nid, e: _slo(_s(e))),
)


def _s(entry):
    return entry.get("summary") or {}


def _num(v):
    if v is None:
        return "-"
    f = float(v)
    if f >= 10000:
        return f"{f / 1000.0:.1f}k"
    return str(int(f)) if f == int(f) else f"{f:.1f}"


def _pct(v):
    return "-" if v is None else f"{100.0 * float(v):.1f}"


def _secs(v):
    return "-" if v is None else f"{float(v):.1f}s"


def _slo(summary):
    p50, p99 = summary.get("serve_p50_ms"), summary.get("serve_p99_ms")
    if p50 is None and p99 is None:
        return "-"
    return f"{_num(p50)}/{_num(p99)}"


SLO_COLUMNS = (
    # (header, width, extractor) over one /statusz "slo" report row
    ("OBJECTIVE", 20, lambda r: r.get("name", "?")),
    ("KIND", 13, lambda r: r.get("kind", "?")),
    ("TARGET", 8, lambda r: _slo_target(r)),
    ("CURRENT", 10, lambda r: _slo_current(r)),
    ("BURN", 7, lambda r: _num(r.get("burn"))),
    ("STATE", 9, lambda r: _slo_state(r)),
)


def _slo_target(row):
    pct = row.get("target_pct")
    if pct is None:
        return "-"
    thr = row.get("threshold_ms")
    return f"<{_num(thr)}ms" if thr is not None else f"{pct:g}%"


def _slo_current(row):
    cur = row.get("current")
    if cur is None:
        return "-"
    if row.get("kind") == "latency":
        return f"{_num(cur)}ms"
    return _pct(cur)


def _slo_state(row):
    if row.get("burn") is None:
        return "no-data"
    return "BREACH" if row.get("breaching") else "ok"


def render_slo(status):
    """The --slo pane text: one row per objective, or a placeholder
    when the driver has no SLO engine report yet."""
    rows = status.get("slo") or []
    lines = ["", "slo burn (obs/slo.py):"]
    if not rows:
        lines.append("  (no objectives reported)")
        return "\n".join(lines) + "\n"
    lines.append(" ".join(h.ljust(w) for h, w, _ in SLO_COLUMNS).rstrip())
    for row in rows:
        lines.append(" ".join(
            str(fn(row))[:w].ljust(w) for _, w, fn in SLO_COLUMNS).rstrip())
    return "\n".join(lines) + "\n"


HEALTH_COLUMNS = (
    # (header, width, extractor) over a /statusz node entry
    ("NODE", 14, lambda nid, e: nid),
    ("HEALTH", 9, lambda nid, e: _s(e).get("health") or "-"),
    ("ANOMALIES", 10, lambda nid, e: _num(_s(e).get("health_anomalies"))),
    ("GRAD-NORM", 10, lambda nid, e: _num(_s(e).get("grad_norm"))),
)

STRAGGLER_COLUMNS = (
    # (header, width, extractor) over one stragglers "nodes" row
    ("NODE", 14, lambda r: r.get("node", "?")),
    ("P50-MS", 8, lambda r: _num(r.get("p50_ms"))),
    ("STEPS", 7, lambda r: _num(r.get("steps"))),
    ("REL", 6, lambda r: _rel(r.get("rel"))),
)


def _rel(v):
    return "-" if v is None else f"{float(v):.2f}x"


def render_health(status):
    """The --health pane text: per-node watchtower state plus the
    driver's straggler report (obs/health.py, docs/observability.md)."""
    lines = ["", "health (obs/health.py):"]
    nodes = status.get("nodes") or {}
    rows = [(nid, ent) for nid, ent in sorted(nodes.items())
            if _s(ent).get("health") is not None]
    if rows:
        lines.append(" ".join(
            h.ljust(w) for h, w, _ in HEALTH_COLUMNS).rstrip())
        for nid, ent in rows:
            lines.append(" ".join(
                str(fn(nid, ent))[:w].ljust(w)
                for _, w, fn in HEALTH_COLUMNS).rstrip())
    else:
        lines.append("  (no health reports)")
    st = status.get("stragglers")
    if st:
        lines.append(
            f"stragglers: skew={_rel(st.get('skew'))} "
            f"slowest={st.get('slowest', '?')} "
            f"fastest={st.get('fastest', '?')}")
        lines.append(" ".join(
            h.ljust(w) for h, w, _ in STRAGGLER_COLUMNS).rstrip())
        for row in st.get("nodes") or []:
            lines.append(" ".join(
                str(fn(row))[:w].ljust(w)
                for _, w, fn in STRAGGLER_COLUMNS).rstrip())
    else:
        lines.append("stragglers: (not enough per-node step data)")
    return "\n".join(lines) + "\n"


PODS_COLUMNS = (
    # (header, width, extractor) over one /statusz "pods" row (a fabric
    # host, serving/fabric/router.py describe())
    ("HOST", 6, lambda r: f"{r.get('router', 0)}/{r.get('host', '?')}"),
    ("UP", 4, lambda r: "yes" if r.get("alive") else "DOWN"),
    ("PID", 8, lambda r: _num(r.get("pid"))),
    ("REPLICAS", 9, lambda r: _num(r.get("replicas"))),
    ("QDEPTH", 7, lambda r: _num(r.get("queue_depth"))),
    ("VERSION", 8, lambda r: _num(r.get("version"))),
    ("AFF-HIT%", 9, lambda r: _pct(r.get("affinity_hit_rate"))),
)


def render_pods(status):
    """The --pods pane text: one row per serving-fabric host from the
    ``/statusz`` pods section (serving/fabric/, docs/serving.md
    "Pod-scale fabric")."""
    lines = ["", "pods (serving/fabric/):"]
    rows = status.get("pods") or []
    if not rows:
        lines.append("  (no fabric routers)")
        return "\n".join(lines) + "\n"
    lines.append(" ".join(h.ljust(w) for h, w, _ in PODS_COLUMNS).rstrip())
    for row in rows:
        lines.append(" ".join(
            str(fn(row))[:w].ljust(w)
            for _, w, fn in PODS_COLUMNS).rstrip())
    return "\n".join(lines) + "\n"


def render_deploy(status):
    """The --deploy pane text: per-loop rollout state from the
    ``/statusz`` deploy section (workloads/deploy_loop.py,
    docs/deployment.md)."""
    lines = ["", "deploy (workloads/deploy_loop.py):"]
    rows = status.get("deploy") or []
    if not rows:
        lines.append("  (no deployment loops)")
        return "\n".join(lines) + "\n"
    for row in rows:
        canary = row.get("canary") or {}
        head = (f"  {row.get('ckpt_dir', '?')}: {row.get('state', '?')} "
                f"wm={row.get('watermark', '-')} "
                f"cand={row.get('candidate', '-')} "
                f"promoted={row.get('promotions', 0)} "
                f"rolled_back={row.get('rollbacks', 0)}")
        if canary:
            head += (f" arm={canary.get('replicas')}"
                     f"@{canary.get('pct', '?')}%")
        if row.get("burn_remaining_s") is not None:
            head += f" burn={row['burn_remaining_s']}s"
        lines.append(head)
        for arm, st in sorted((row.get("stats") or {}).items()):
            lines.append(
                f"    {arm}: n={st.get('n', 0)} "
                f"errors={st.get('errors', 0)} "
                f"p50={_num(st.get('p50_ms'))}ms "
                f"p95={_num(st.get('p95_ms'))}ms")
        last = row.get("last_verdict")
        if last:
            why = "; ".join(last.get("reasons") or []) or "clean"
            lines.append(f"    last: {last.get('verdict', '?')} "
                         f"step={last.get('step', '?')} ({why})")
    return "\n".join(lines) + "\n"


def fetch_statusz(url, timeout=5):
    """GET <url>/statusz and parse it; raises URLError/ValueError."""
    with urllib.request.urlopen(url.rstrip("/") + "/statusz",
                                timeout=timeout) as r:
        return json.loads(r.read().decode("utf-8"))


def render(status):
    """One snapshot -> the table text (no ANSI; the live loop adds the
    clear sequence)."""
    lines = []
    cl = status.get("cluster") or {}
    head = (f"tfos-top — cluster {cl.get('id', '?')} "
            f"epoch={cl.get('epoch', '?')} "
            f"restarts={cl.get('restarts_used', 0)}/{cl.get('restarts', 0)} "
            f"nodes={len(status.get('nodes') or {})}")
    lines.append(head)
    feeds = status.get("feeds") or {}
    if feeds:
        prog = " ".join(f"{f}:{n}" for f, n in sorted(feeds.items()))
        lines.append(f"feed ledger: {prog}")
    lines.append("")
    lines.append(" ".join(h.ljust(w) for h, w, _ in COLUMNS).rstrip())
    for nid, ent in sorted((status.get("nodes") or {}).items()):
        row = " ".join(
            str(fn(nid, ent))[:w].ljust(w) for _, w, fn in COLUMNS)
        lines.append(row.rstrip())
    return "\n".join(lines) + "\n"


def build_parser():
    p = argparse.ArgumentParser(
        prog="tfos-top",
        description="live per-node view of a TFOS cluster's /statusz")
    port = os.environ.get(metrics_registry.PORT_ENV)
    p.add_argument("--url",
                   default=f"http://127.0.0.1:{port}" if port else None,
                   help="obs endpoint base URL "
                        "(default: http://127.0.0.1:$TFOS_OBS_PORT)")
    p.add_argument("--interval", type=float, default=2.0,
                   help="refresh period, seconds (default 2)")
    p.add_argument("--once", action="store_true",
                   help="print one snapshot and exit")
    p.add_argument("--slo", action="store_true",
                   help="append the SLO pane (objective, current, burn)")
    p.add_argument("--health", action="store_true",
                   help="append the health pane (anomalies, stragglers)")
    p.add_argument("--deploy", action="store_true",
                   help="append the deploy pane (rollout state, canary "
                        "arms, verdicts)")
    p.add_argument("--pods", action="store_true",
                   help="append the pods pane (serving-fabric hosts: "
                        "replicas, queue depth, affinity hit rate)")
    return p


def main(argv=None, out=None):
    out = out or sys.stdout
    args = build_parser().parse_args(argv)
    if not args.url:
        print("tfos-top: no --url and TFOS_OBS_PORT is unset",
              file=sys.stderr)
        return 2
    while True:
        try:
            status = fetch_statusz(args.url)
        except (urllib.error.URLError, OSError, ValueError) as e:
            if args.once:
                print(f"tfos-top: {args.url} unreachable: {e}",
                      file=sys.stderr)
                return 2
            out.write(f"{CLEAR}tfos-top: {args.url} unreachable "
                      f"({e}); retrying...\n")
            out.flush()
            time.sleep(args.interval)
            continue
        text = render(status)
        if args.slo:
            text += render_slo(status)
        if args.health:
            text += render_health(status)
        if args.deploy:
            text += render_deploy(status)
        if args.pods:
            text += render_pods(status)
        if args.once:
            out.write(text)
            out.flush()
            return 0
        out.write(CLEAR + text)
        out.flush()
        try:
            time.sleep(args.interval)
        except KeyboardInterrupt:
            return 0


if __name__ == "__main__":
    sys.exit(main())
