"""Driver-side observability endpoint: /metrics /healthz /statusz /slo.

The driver half of the live metrics plane (node half:
``obs/publish.py``).  ``ObsServer`` polls every cluster node's manager
KV for published registry snapshots (``manager.TFManager.obs_snapshots``)
and the heartbeat key (``manager.heartbeat_age``), merges them with the
driver's own registry, and serves:

- ``/metrics``  Prometheus text exposition; every series carries a
  ``node`` label (``driver`` for driver-process metrics).
- ``/healthz``  JSON liveness: a node is dead only when its heartbeat
  age exceeds ``manager.stale_after()``; 200 when every node is live,
  503 otherwise (load-balancer semantics).
- ``/statusz``  JSON cluster snapshot: epoch, restart budget/used,
  feed-ledger progress, a per-node summary (last-seen, step rate,
  queue depth, stall %, SLO percentiles), the straggler table
  (``obs/health.py`` skew analysis) and the SLO engine's last
  report — what ``tfos-top`` renders.
- ``/slo``      JSON burn-rate report, re-evaluated per request
  (``obs/slo.py``): objective, current value, burn, breaching.
- ``POST /profilez?node=&ms=``  on-demand profiling control plane:
  writes a capture directive into the named node's manager KV, waits
  for its publish daemon to run ``utils.profiler.trace`` for the
  window, and returns the spooled-back capture path (202 when the ack
  hasn't landed inside the wait window — poll again with the same
  node).  ``POST /flightz?node=`` does the same for an on-demand
  flight-recorder dump.

``/healthz`` additionally reports ``degraded`` (still 503 — don't route
work at a sick cluster) when any node's published metrics carry health
anomalies (``obs/health.py`` detectors), even while every heartbeat is
live.

Gated on ``TFOS_OBS_PORT`` (no server, no threads, no polling when
unset); port 0 binds an ephemeral port, exposed as ``server.port``.
Transport/auth note: binds loopback by default (``TFOS_OBS_HOST`` to
widen); GETs are read-only, the POST control verbs only trigger
capture-to-disk on the target node (nothing is mutated in the run).
"""

from __future__ import annotations

import json
import logging
import socket as _socket
import threading
import time
import urllib.parse
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

from tensorflowonspark_tpu import manager as tfmanager
from tensorflowonspark_tpu.obs import health as _health
from tensorflowonspark_tpu.obs import slo as _slo
from tensorflowonspark_tpu.utils import metrics_registry

logger = logging.getLogger(__name__)

HOST_ENV = "TFOS_OBS_HOST"


def _metric_total(snap, name):
    """Sum of a counter's series values, or None when absent."""
    ent = (snap or {}).get(name)
    if not ent:
        return None
    return sum(s.get("value", 0.0) for s in ent.get("series", ()))


def _metric_gauge(snap, name):
    """First series value of a gauge, or None when absent."""
    ent = (snap or {}).get(name)
    if not ent or not ent.get("series"):
        return None
    return ent["series"][0].get("value")


def _metric_hist(snap, name):
    """First histogram series dict, or None when absent."""
    ent = (snap or {}).get(name)
    if not ent or not ent.get("series"):
        return None
    s = ent["series"][0]
    return s if "count" in s else None


def _round(v, nd=3):
    return None if v is None else round(float(v), nd)


def node_summary(snap):
    """The per-node key-metric extraction ``/statusz`` ships and
    ``tfos-top`` renders; every field is None when the node hasn't
    reported that subsystem."""
    out = {}
    out["steps"] = _metric_total(snap, "tfos_train_steps_total")
    h = _metric_hist(snap, "tfos_train_step_ms")
    if h:
        out["step_ms_p50"] = _round(metrics_registry.quantile(h, 0.5))
        out["step_ms_p99"] = _round(metrics_registry.quantile(h, 0.99))
    out["items_per_sec"] = _round(
        _metric_gauge(snap, "tfos_train_items_per_sec"))
    out["mfu"] = _round(_metric_gauge(snap, "tfos_train_mfu"), 4)
    out["stall_frac"] = _round(
        _metric_gauge(snap, "tfos_train_infeed_stall_frac"), 4)
    ring = _metric_gauge(snap, "tfos_feed_ring_bytes")
    out["queue_depth"] = (
        ring if ring is not None
        else _metric_gauge(snap, "tfos_feed_queue_depth"))
    out["records"] = (
        _metric_total(snap, "tfos_feed_records_total")
        or _metric_total(snap, "tfos_data_records_total"))
    out["respawns"] = _metric_total(snap, "tfos_engine_respawns_total")
    sh = _metric_hist(snap, "tfos_serve_request_ms")
    if sh:
        out["serve_p50_ms"] = _round(metrics_registry.quantile(sh, 0.5))
        out["serve_p99_ms"] = _round(metrics_registry.quantile(sh, 0.99))
        sq = _metric_gauge(snap, "tfos_serve_queue_depth")
        if sq is not None:
            out["queue_depth"] = sq
    gen = _metric_gauge(snap, "tfos_serve_pool_generation")
    if gen is not None:
        out["pool_generation"] = gen
        out["pool_degraded"] = _metric_gauge(
            snap, "tfos_serve_pool_degraded")
        rh = _metric_hist(snap, "tfos_serve_resize_seconds")
        if rh:
            out["resize_p99_s"] = _round(
                metrics_registry.quantile(rh, 0.99), 4)
    ha = _metric_total(snap, "tfos_health_anomalies_total")
    if ha:
        out["health_anomalies"] = ha
    hs = _metric_gauge(snap, "tfos_health_status")
    if hs is not None:
        out["health"] = "degraded" if hs else "ok"
    gn = _metric_gauge(snap, "tfos_health_grad_norm")
    if gn is not None:
        out["grad_norm"] = _round(gn, 4)
    skew = _metric_gauge(snap, "tfos_node_skew")
    if skew is not None:
        out["node_skew"] = _round(skew, 3)
    dh = _metric_hist(snap, "tfos_decode_ttft_ms")
    if dh:
        out["decode_ttft_p99_ms"] = _round(
            metrics_registry.quantile(dh, 0.99))
        out["decode_tokens"] = _metric_total(
            snap, "tfos_decode_tokens_total")
        occ = _metric_gauge(snap, "tfos_decode_slot_occupancy")
        if occ is not None:
            out["decode_slots_busy"] = occ
        hits = _metric_total(snap, "tfos_decode_prefix_hits")
        if hits:
            out["decode_prefix_hits"] = hits
        blocks = _metric_gauge(snap, "tfos_decode_blocks_in_use")
        if blocks is not None:
            out["decode_blocks_in_use"] = blocks
        acc = _metric_gauge(snap, "tfos_decode_spec_accept")
        if acc is not None:
            out["decode_spec_accept"] = _round(acc, 4)
    return {k: v for k, v in out.items() if v is not None}


# Dynamic-split dispatch rollup (/statusz "data" section).  The split
# lifecycle is spread across processes — the provider actor posts and
# requeues, data workers claim and serve, trainers count dup-dropped
# chunks, the autoscaler owns the worker gauge — so counters are summed
# across every snapshot and gauges take the largest reporter (one
# provider / one autoscaler in practice; workers' cache gauges sum).
_DATA_COUNTERS = (
    ("splits_posted", "tfos_data_splits_posted_total"),
    ("splits_claimed", "tfos_data_splits_claimed_total"),
    ("splits_served", "tfos_data_splits_served_total"),
    ("splits_requeued", "tfos_data_splits_requeued_total"),
    ("dup_chunks", "tfos_data_split_dup_chunks_total"),
    ("records", "tfos_data_records_total"),
    ("cache_hits", "tfos_data_cache_hits_total"),
    ("cache_misses", "tfos_data_cache_misses_total"),
    ("cache_spilled", "tfos_data_cache_spilled_total"),
)

_DATA_SUM_GAUGES = (
    ("cache_blocks", "tfos_data_cache_blocks"),
    ("cache_bytes", "tfos_data_cache_bytes"),
)

_DATA_MAX_GAUGES = (
    ("split_queue_depth", "tfos_data_split_queue_depth"),
    ("workers", "tfos_data_workers"),
)


def data_summary(snaps):
    """Cross-process dynamic-split rollup, or None when no process
    reported a split/cache/worker metric (static-shard runs keep
    /statusz unchanged)."""
    out = {}
    for key, name in _DATA_COUNTERS:
        vals = [v for v in (_metric_total(s, name) for s in snaps)
                if v is not None]
        if vals:
            out[key] = sum(vals)
    for key, name in _DATA_SUM_GAUGES:
        vals = [v for v in (_metric_gauge(s, name) for s in snaps)
                if v is not None]
        if vals:
            out[key] = sum(vals)
    for key, name in _DATA_MAX_GAUGES:
        vals = [v for v in (_metric_gauge(s, name) for s in snaps)
                if v is not None]
        if vals:
            out[key] = max(vals)
    # the headline trainer-facing number only matters on dynamic runs;
    # records alone (also counted by the static service) doesn't rate a
    # section of its own
    if set(out) <= {"records"}:
        return None
    return out or None


class ObsServer:
    """See module docstring.  ``cluster`` is a ``TFCluster`` (may be
    None for a driver-only / serving-only endpoint)."""

    def __init__(self, cluster=None, port=None, host=None, interval=None):
        import os

        self.cluster = cluster
        if port is None:
            port = int(os.environ.get(metrics_registry.PORT_ENV, "0") or 0)
        self._req_port = int(port)
        self.host = host or os.environ.get(HOST_ENV) or "127.0.0.1"
        self.interval = (metrics_registry.interval()
                         if interval is None else float(interval))
        self._stop = threading.Event()
        self._lock = threading.Lock()
        self._nodes = {}   # node_id -> payload + poll bookkeeping
        self._mgrs = {}    # (host, executor_id) -> manager proxy
        self._httpd = None
        self._threads = []
        self._ctl_seq = 0  # control-directive sequence (under _lock)
        self.slo = _slo.Engine()

    # -- lifecycle -----------------------------------------------------

    def start(self):
        httpd = ThreadingHTTPServer((self.host, self._req_port), _Handler)
        httpd.daemon_threads = True
        httpd.obs = self
        self._httpd = httpd
        t = threading.Thread(target=httpd.serve_forever,
                             name="tfos-obs-http", daemon=True)
        t.start()
        self._threads.append(t)
        p = threading.Thread(target=self._poll_loop,
                             name="tfos-obs-poll", daemon=True)
        p.start()
        self._threads.append(p)
        logger.info("obs: serving /metrics /healthz /statusz /slo "
                    "(+POST /profilez /flightz) on %s", self.url)
        return self

    @property
    def port(self):
        return self._httpd.server_address[1] if self._httpd else None

    @property
    def url(self):
        return f"http://{self.host}:{self.port}"

    def stop(self):
        self._stop.set()
        if self._httpd is not None:
            try:
                self._httpd.shutdown()
                self._httpd.server_close()
            except Exception:  # noqa: BLE001 - teardown
                pass
        for t in self._threads:
            t.join(timeout=5)
        self._mgrs.clear()

    # -- node polling --------------------------------------------------

    def _manager_for(self, meta):
        key = (meta["host"], meta["executor_id"])
        mgr = self._mgrs.get(key)
        if mgr is not None:
            return mgr
        addr = tuple(meta["addr"])
        candidates = [addr]
        if addr[0] not in ("127.0.0.1", "localhost"):
            candidates.append(("127.0.0.1", addr[1]))
        old = _socket.getdefaulttimeout()
        _socket.setdefaulttimeout(5)
        try:
            for cand in candidates:
                try:
                    mgr = tfmanager.connect(
                        cand, bytes.fromhex(meta["authkey"]))
                    self._mgrs[key] = mgr
                    return mgr
                except Exception:  # noqa: BLE001 - try next candidate
                    continue
        finally:
            _socket.setdefaulttimeout(old)
        return None

    def _poll_node(self, meta):
        node_id = f"{meta['job_name']}-{meta['task_index']}"
        mgr = self._manager_for(meta)
        if mgr is None:
            return
        try:
            payloads = mgr.obs_snapshots()
            hb_age = tfmanager.heartbeat_age(mgr)
        except Exception:  # noqa: BLE001 - reconnect next round
            self._mgrs.pop((meta["host"], meta["executor_id"]), None)
            return
        now = time.time()
        with self._lock:
            # the cluster node itself (heartbeat owner) ...
            ent = self._nodes.setdefault(node_id, {"node_id": node_id})
            ent.update(role=meta["job_name"],
                       executor_id=meta["executor_id"],
                       host=meta["host"], heartbeat_age_s=hb_age,
                       polled_ts=now)
            # ... plus every publisher reachable through its manager
            # (trainer, data workers, feeders) keyed by published id
            for nid, payload in payloads.items():
                if not isinstance(payload, dict):
                    continue
                e = self._nodes.setdefault(nid, {"node_id": nid})
                e.update(role=payload.get("role", e.get("role")),
                         last_seen=payload.get("ts"),
                         metrics=payload.get("metrics"),
                         polled_ts=now)
                e.setdefault("executor_id", meta["executor_id"])
                e.setdefault("host", meta["host"])
                if nid == node_id:
                    e["heartbeat_age_s"] = hb_age

    def poll_once(self):
        """One sweep over the cluster's nodes, then one SLO evaluation
        over everything the sweep (plus the driver registry) can see,
        then one straggler analysis over the per-node step-time
        histograms (the poll thread's body; callable directly in
        tests)."""
        cluster = self.cluster
        if cluster is not None:
            for meta in list(getattr(cluster, "cluster_info", ()) or ()):
                if self._stop.is_set():
                    return
                self._poll_node(meta)
        self.slo.step(self._all_snapshots())
        # emits the tfos_node_skew gauge into the driver registry (and
        # the process_summary cache bench.py reads); /statusz recomputes
        # per request so a probe never sees a stale table
        _health.straggler_report(self._node_entries())

    def _all_snapshots(self):
        """Every registry snapshot in view: the driver's own plus each
        polled node's last published one (the SLO evaluation input)."""
        snaps = [metrics_registry.snapshot()]
        for ent in self._node_entries().values():
            if ent.get("metrics"):
                snaps.append(ent["metrics"])
        return [s for s in snaps if s]

    def _poll_loop(self):
        while not self._stop.is_set():
            try:
                self.poll_once()
            except Exception as e:  # noqa: BLE001 - keep serving
                logger.debug("obs poll error: %s", e)
            self._stop.wait(self.interval)

    # -- on-demand control plane ---------------------------------------

    def _meta_for_node(self, node_id):
        """The cluster_info meta whose manager can reach ``node_id``:
        the node's own executor for cluster nodes, else the executor
        that last published under that id (data workers, feeders)."""
        metas = list(getattr(self.cluster, "cluster_info", ()) or ())
        for meta in metas:
            if f"{meta['job_name']}-{meta['task_index']}" == str(node_id):
                return meta
        ent = self._node_entries().get(str(node_id))
        if ent is not None and ent.get("executor_id") is not None:
            for meta in metas:
                if meta["executor_id"] == ent["executor_id"]:
                    return meta
        return None

    def request_control(self, node_id, directive, wait_s=None):
        """Round-trip one control directive to a node: post it under the
        node's ``obsctl:`` KV slot, then poll the ``obsack:`` slot until
        the node's publish daemon acks with the same sequence number.

        Returns the ack dict plus a ``code`` hint for the HTTP layer:
        200 on a completed round-trip (``ok`` False inside means the
        node executed but degraded, e.g. no profiler backend), 202 when
        the window expired with the directive still posted (slow node;
        it will still execute and a later request sees the ack), 404/502
        for unknown node / unreachable manager."""
        node_id = str(node_id)
        meta = self._meta_for_node(node_id)
        if meta is None:
            return {"ok": False, "code": 404, "node": node_id,
                    "error": f"unknown node {node_id!r}"}
        mgr = self._manager_for(meta)
        if mgr is None:
            return {"ok": False, "code": 502, "node": node_id,
                    "error": "node manager unreachable"}
        with self._lock:
            self._ctl_seq += 1
            seq = self._ctl_seq
        directive = dict(directive, seq=seq, ts=time.time())
        try:
            mgr.obs_control_post(node_id, directive)
        except Exception as e:  # noqa: BLE001 - manager died mid-post
            self._mgrs.pop((meta["host"], meta["executor_id"]), None)
            return {"ok": False, "code": 502, "node": node_id,
                    "error": f"directive post failed: {e}"}
        if wait_s is None:
            # directives are served once per publish tick; two ticks plus
            # the capture window bounds a healthy round trip — floored at
            # 15s because a profile's first capture cold-imports jax in
            # the publish daemon (measured ~4-5s on CPU, worse on TPU)
            wait_s = min(max(2.0 * self.interval + 3.0
                             + float(directive.get("ms") or 0) / 1000.0,
                             15.0), 75.0)
        deadline = time.time() + max(float(wait_s), 0.0)
        while time.time() < deadline and not self._stop.is_set():
            try:
                ack = mgr.obs_control_result(node_id)
            except Exception:  # noqa: BLE001 - retry until deadline
                ack = None
            if isinstance(ack, dict) and ack.get("seq") == seq:
                return dict(ack, code=200)
            time.sleep(min(0.05, self.interval))
        return {"ok": None, "code": 202, "node": node_id, "seq": seq,
                "accepted": True,
                "error": f"no ack within {wait_s:.1f}s (directive still "
                         f"queued; the node serves it on its next tick)"}

    # -- endpoint bodies -----------------------------------------------

    def _node_entries(self):
        with self._lock:
            return {nid: dict(e) for nid, e in self._nodes.items()}

    def render_metrics(self):
        pairs = []
        driver = metrics_registry.snapshot()
        if driver:
            pairs.append(({"node": "driver"}, driver))
        for nid, ent in sorted(self._node_entries().items()):
            if ent.get("metrics"):
                pairs.append(({"node": nid}, ent["metrics"]))
        return metrics_registry.render_text(pairs)

    def render_healthz(self):
        stale = tfmanager.stale_after()
        now = time.time()
        nodes = {}
        healthy = True
        degraded = False
        for nid, ent in sorted(self._node_entries().items()):
            hb = ent.get("heartbeat_age_s")
            seen = ent.get("last_seen")
            alive = hb is None or hb < stale
            if not alive:
                healthy = False
            nodes[nid] = {
                "alive": alive,
                "heartbeat_age_s": _round(hb),
                "publish_age_s": _round(now - seen) if seen else None,
            }
            anomalies = _health.snapshot_anomaly_total(ent.get("metrics"))
            if anomalies:
                degraded = True
                nodes[nid]["anomalies"] = anomalies
        # the driver's own registry too: an in-process monitor (bench,
        # driver-side trainer) degrades /healthz without a publish hop
        own = _health.snapshot_anomaly_total(metrics_registry.snapshot())
        if own:
            degraded = True
        status = ("unhealthy" if not healthy
                  else "degraded" if degraded else "ok")
        return {"status": status, "nodes": nodes}

    def render_statusz(self):
        cluster = self.cluster
        now = time.time()
        out = {"ts": now, "url": self.url}
        if cluster is not None:
            meta = getattr(cluster, "meta", {}) or {}
            cid = meta.get("id")
            out["cluster"] = {
                "id": f"{cid & 0xffffffff:x}" if cid is not None else None,
                "epoch": meta.get("epoch"),
                "num_executors": meta.get("num_executors"),
                "restarts": getattr(cluster, "restarts", None),
                "restarts_used": getattr(cluster, "_restarts_used", None),
            }
            feeds = getattr(getattr(cluster, "server", None), "_feeds", None)
            if feeds:
                out["feeds"] = {f: len(parts)
                                for f, parts in sorted(feeds.items())}
        nodes = {}
        for nid, ent in sorted(self._node_entries().items()):
            seen = ent.get("last_seen")
            hb = ent.get("heartbeat_age_s")
            nodes[nid] = {
                "role": ent.get("role"),
                "executor_id": ent.get("executor_id"),
                "host": ent.get("host"),
                "alive": hb is None or hb < tfmanager.stale_after(),
                "heartbeat_age_s": _round(hb),
                "last_seen_age_s": _round(now - seen) if seen else None,
                "summary": node_summary(ent.get("metrics")),
            }
        driver = metrics_registry.snapshot()
        if driver:
            nodes["driver"] = {
                "role": "driver", "alive": True,
                "summary": node_summary(driver),
            }
        out["nodes"] = nodes
        # cross-node step-time skew: who is slow, and by how much
        # (obs/health.py; recomputed per request, emit only on the poll
        # thread so request traffic never mutates the driver registry)
        strag = _health.straggler_report(self._node_entries(), emit=False)
        if strag:
            out["stragglers"] = strag
        rep = self.slo.report()
        if rep.get("objectives"):
            out["slo"] = rep["objectives"]
        # Supervised-actor table: one row per member of every live
        # ActorSystem in this process (lazy import: obs has no actor
        # dependency unless someone spawned one).
        try:
            from tensorflowonspark_tpu.actors.runtime import actor_table

            rows = actor_table()
        except Exception:  # noqa: BLE001 - actors tearing down
            rows = []
        if rows:
            out["actors"] = rows
        # Elastic serving pools: generation, capacity, assignments —
        # the degrade-by-resize state (same lazy pattern as actors).
        try:
            from tensorflowonspark_tpu.serving.elastic import pool_table

            pools = pool_table()
        except Exception:  # noqa: BLE001 - pools tearing down
            pools = []
        if pools:
            out["pools"] = pools
        # Pod-scale serving fabric: one row per fabric host (replicas,
        # queue depth, affinity hit rate) — the tfos-top --pods pane
        # (same lazy pattern as actors/pools).
        try:
            from tensorflowonspark_tpu.serving.fabric import fabric_table

            pods = fabric_table()
        except Exception:  # noqa: BLE001 - routers tearing down
            pods = []
        if pods:
            out["pods"] = pods
        # Blessed-checkpoint deployment loops: rollout state, watermark,
        # per-arm canary evidence (same lazy pattern as actors/pools).
        try:
            from tensorflowonspark_tpu.workloads.deploy_loop import (
                deploy_table,
            )

            deploys = deploy_table()
        except Exception:  # noqa: BLE001 - loops tearing down
            deploys = []
        if deploys:
            out["deploy"] = deploys
        # Dynamic-split dispatch: split lifecycle counters and dispatch
        # gauges rolled up across every reporting process (data/).
        snaps = [ent.get("metrics")
                 for ent in self._node_entries().values()]
        if driver:
            snaps.append(driver)
        data = data_summary(snaps)
        if data:
            out["data"] = data
        return out

    def render_slo(self):
        """Fresh objective evaluation over everything in view (the
        ``/slo`` body) — re-evaluated per request so a probe sees
        current burn without waiting a poll interval."""
        return self.slo.step(self._all_snapshots())


class _Handler(BaseHTTPRequestHandler):
    server_version = "tfos-obs/1"

    def log_message(self, fmt, *args):  # quiet: scrape traffic
        logger.debug("obs http: " + fmt, *args)

    def _reply(self, code, body, ctype):
        data = body.encode("utf-8")
        self.send_response(code)
        self.send_header("Content-Type", ctype)
        self.send_header("Content-Length", str(len(data)))
        self.end_headers()
        self.wfile.write(data)

    def do_GET(self):  # noqa: N802 - http.server API
        obs = self.server.obs
        path = self.path.split("?", 1)[0]
        try:
            if path == "/metrics":
                self._reply(200, obs.render_metrics(),
                            "text/plain; version=0.0.4; charset=utf-8")
            elif path == "/healthz":
                h = obs.render_healthz()
                code = 200 if h["status"] == "ok" else 503
                self._reply(code, json.dumps(h, indent=1),
                            "application/json")
            elif path == "/statusz":
                self._reply(200, json.dumps(obs.render_statusz(), indent=1),
                            "application/json")
            elif path == "/slo":
                self._reply(200, json.dumps(obs.render_slo(), indent=1),
                            "application/json")
            elif path in ("/profilez", "/flightz"):
                self._reply(405, "profilez/flightz are POST verbs "
                                 "(POST /profilez?node=<id>&ms=<window>)",
                            "text/plain")
            else:
                self._reply(404, "not found: try /metrics /healthz "
                                 "/statusz /slo (POST /profilez /flightz)",
                            "text/plain")
        except Exception as e:  # noqa: BLE001 - never kill the server
            self._reply(500, f"obs error: {e}", "text/plain")

    def do_POST(self):  # noqa: N802 - http.server API
        obs = self.server.obs
        path, _, query = self.path.partition("?")
        params = urllib.parse.parse_qs(query)

        def q(name, default=None):
            return (params.get(name) or [default])[0]

        try:
            if path not in ("/profilez", "/flightz"):
                self._reply(404, "not found: POST /profilez /flightz",
                            "text/plain")
                return
            node = q("node")
            if not node:
                self._reply(400, "missing ?node=<node_id> "
                                 "(ids as shown on /statusz)",
                            "text/plain")
                return
            wait_raw = q("wait_s")
            wait_s = float(wait_raw) if wait_raw else None
            if path == "/profilez":
                directive = {"cmd": "profile", "ms": int(q("ms", "1000"))}
            else:
                directive = {"cmd": "flight", "reason": q("reason")}
            res = obs.request_control(node, directive, wait_s=wait_s)
            code = res.pop("code", 200)
            self._reply(code, json.dumps(res, indent=1),
                        "application/json")
        except ValueError as e:
            self._reply(400, f"bad parameter: {e}", "text/plain")
        except Exception as e:  # noqa: BLE001 - never kill the server
            self._reply(500, f"obs error: {e}", "text/plain")


def start_for_cluster(cluster):
    """Start the driver endpoint for a cluster when ``TFOS_OBS_PORT``
    is set; returns the running ObsServer or None (disabled)."""
    import os

    raw = os.environ.get(metrics_registry.PORT_ENV)
    if raw is None:
        return None
    try:
        port = int(raw)
    except ValueError:
        logger.warning("obs: %s=%r is not a port; metrics plane disabled",
                       metrics_registry.PORT_ENV, raw)
        return None
    try:
        return ObsServer(cluster, port=port).start()
    except OSError as e:
        logger.warning("obs: could not bind %s:%s (%s); metrics plane off",
                       os.environ.get(HOST_ENV, "127.0.0.1"), port, e)
        return None
