"""TensorFlowOnSpark-TPU: a TPU-native cluster-federation framework.

A ground-up re-design of the capabilities of TensorFlowOnSpark
(reference: /root/reference, Yahoo TFoS v2.2.1) for TPU hardware and the
JAX/XLA programming model:

- A data-engine scheduler (Spark, or the built-in local engine) schedules
  one framework node per executor.
- A rendezvous server (``rendezvous.py``, parity: reference
  ``tensorflowonspark/reservation.py``) assembles the cluster spec and the
  JAX distributed coordinator address instead of a TF_CONFIG.
- Data-parallel / model-parallel compute runs as SPMD JAX over a
  ``jax.sharding.Mesh``; collectives ride ICI via XLA (no NCCL/gRPC ring).
- Spark partitions stream into the accelerator through a batched
  shared-queue feed (``feed.DataFeed``, parity: reference ``TFNode.py``)
  rather than per-record pickle IPC.

Public API (mirrors the reference's import surface so users can switch):

    from tensorflowonspark_tpu import TFCluster, TFNode, InputMode
    cluster = TFCluster.run(sc, main_fun, args, num_executors, ...)
    cluster.train(dataRDD); cluster.shutdown()
"""

import logging

__version__ = "0.1.0"

# Library-polite logging: a NullHandler on our namespace; applications (and
# the example drivers) opt in to the reference's root format by calling
# configure_logging() (parity intent: reference __init__.py:1-5, which did
# basicConfig at import time — deliberately not reproduced).
logging.getLogger(__name__).addHandler(logging.NullHandler())


def configure_logging(level=logging.INFO):
    logging.basicConfig(
        level=level,
        format="%(asctime)s %(levelname)s (%(threadName)s-%(process)d) %(message)s",
    )

_LAZY = {
    "InputMode": ("tensorflowonspark_tpu.cluster", "InputMode"),
    # the reference exposes TFCluster as a MODULE (TFCluster.run(...)):
    # keep that exact import surface
    "TFCluster": ("tensorflowonspark_tpu.cluster", None),
    "TFNode": ("tensorflowonspark_tpu.feed", None),
    "TFNodeContext": ("tensorflowonspark_tpu.node", "TFNodeContext"),
    "TFParallel": ("tensorflowonspark_tpu.parallel_run", None),
    "compat": ("tensorflowonspark_tpu.compat", None),
    "dfutil": ("tensorflowonspark_tpu.dfutil", None),
    "infeed": ("tensorflowonspark_tpu.infeed", None),
    "pipeline": ("tensorflowonspark_tpu.pipeline", None),
    "serving": ("tensorflowonspark_tpu.serving", None),
}


def __getattr__(name):
    if name in _LAZY:
        import importlib

        module, attr = _LAZY[name]
        mod = importlib.import_module(module)
        return getattr(mod, attr) if attr else mod
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")
