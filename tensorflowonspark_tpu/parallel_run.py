"""Embarrassingly-parallel mode: N independent single-node jobs
(parity: reference tensorflowonspark/TFParallel.py:17-64).

No cluster is formed — no rendezvous, no coordinator, no collectives.
Each engine executor runs ``map_fn(tf_args, ctx)`` against its own local
accelerators, the pattern for batch inference over many hosts
(reference examples/mnist/keras/mnist_inference.py:79).

The reference uses Spark *barrier execution* so every task starts
together and can see its peers' addresses (``BarrierTaskContext
.getTaskInfos()``, TFParallel.py:43-45); peer visibility feeds the
same-host worker index used to partition GPUs among co-hosted executors
(util.single_node_env, TFParallel.py:49).  Here the same placement logic
partitions *TPU chips* (tpu_info.set_visible_chips) — each co-hosted
process gets a disjoint chip block before its JAX runtime initializes.

Unlike the reference (which returns None), ``run`` returns the collected
``map_fn`` results, one per worker.
"""

from __future__ import annotations

import logging
import os

from tensorflowonspark_tpu import engine as engine_mod
from tensorflowonspark_tpu.utils import get_ip_address, single_node_env

logger = logging.getLogger(__name__)


def _barrier_placement(executor_id, num_workers):
    """(peer_hosts, same_host_index, worker_num) for this task.

    Inside a Spark barrier task, peers come from
    ``BarrierTaskContext.getTaskInfos()`` (TFParallel.py:43-45).  On the
    built-in engine every executor is a co-hosted process, so the
    executor index doubles as the same-host index.
    """
    try:
        from pyspark import BarrierTaskContext

        tc = BarrierTaskContext.get()
        if tc is not None:
            addrs = [info.address.split(":")[0] for info in tc.getTaskInfos()]
            worker_num = tc.partitionId()
            same_host = sum(1 for a in addrs[:worker_num] if a == addrs[worker_num])
            return addrs, same_host, worker_num
    except Exception:  # noqa: BLE001 - not a barrier task / no pyspark
        pass
    # LocalEngine path: every executor IS a co-hosted process of this host,
    # so the executor index doubles as the same-host index.  (Spark tasks
    # never reach here — the Spark path always runs under a barrier.)
    idx = int(os.environ.get("TFOS_EXECUTOR_INDEX", executor_id))
    return [get_ip_address()] * num_workers, idx, executor_id


def _make_closure(map_fn, tf_args, meta, num_workers):
    def _run(iterator):
        from tensorflowonspark_tpu.node import TFNodeContext

        executor_id = 0
        for item in iterator:  # one id per spread partition
            executor_id = item

        peers, same_host_index, worker_num = _barrier_placement(
            executor_id, num_workers
        )
        single_node_env(meta["num_chips"], same_host_index)

        cluster_info = [
            {
                "executor_id": i,
                "host": h,
                "job_name": "worker",
                "task_index": i,
                "port": None,
            }
            for i, h in enumerate(peers)
        ]
        ctx = TFNodeContext(
            executor_id=worker_num,
            job_name="worker",
            task_index=worker_num,
            cluster_spec={"worker": cluster_info},
            default_fs=meta["default_fs"],
            working_dir=meta["working_dir"],
            mgr=None,
            cluster_info=cluster_info,
        )
        logger.info("parallel worker %d/%d starting", worker_num, num_workers)
        return [map_fn(tf_args, ctx)]

    return _run


def run(sc, map_fn, tf_args, num_executors=None, num_chips=0):
    """Run ``map_fn(tf_args, ctx)`` as N independent single-node jobs.

    ``sc`` is a SparkContext or LocalEngine (anything ``as_engine``
    accepts).  Returns the list of per-worker results.
    """
    eng = engine_mod.as_engine(sc)
    n = int(num_executors or eng.num_executors)
    meta = {
        "default_fs": eng.default_fs,
        "working_dir": os.getcwd(),
        "num_chips": num_chips,
    }
    closure = _make_closure(map_fn, tf_args, meta, n)

    if isinstance(eng, engine_mod.SparkEngine):
        # Barrier-only, like the reference (TFParallel.py:63): if the
        # cluster cannot schedule all n tasks together, the job should
        # fail loudly rather than run workers serially.
        rdd = eng.sc.parallelize(range(n), n)
        return rdd.barrier().mapPartitions(closure).collect()

    # Built-in engine: spread pins one task per executor, which is the
    # barrier guarantee the reference needs (concurrent, one per slot).
    # More tasks than slots would serialize behind each other (and claim
    # overlapping chip blocks), silently breaking that guarantee.
    if n > eng.num_executors:
        raise ValueError(
            f"parallel run of {n} workers requires {n} executors; "
            f"engine has {eng.num_executors}"
        )
    ds = eng.parallelize(range(n), n).map_partitions(closure)
    return ds.collect(spread=True)
