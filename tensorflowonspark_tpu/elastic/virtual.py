"""Virtual-device layer: a logical SPMD mesh decoupled from hardware.

New-build capability beyond reference parity (SURVEY.md §2.3/§2.4: the
reference could only ever run the cluster shape it was launched with —
strategy choice and TF_CONFIG froze the topology at startup).  Here one
``TrainSpec``'s *logical* mesh — e.g. ``data=8, fsdp=4`` = 32 virtual
devices — runs unchanged on any physical device count that divides it
(VirtualFlow, arXiv 2009.09523): the surplus factor folds out of the
accumulation axis and is made up with per-virtual-node gradient
accumulation (``utils/train.accumulated_value_and_grad``), so the
optimizer sees the same global batch and the same mean gradient on
1 chip or an N-chip slice.

The algebra, for ``n_virtual = prod(logical)`` and ``n_physical``
devices:

- ``n_virtual % n_physical == 0`` (divisor topologies only — anything
  else would change the per-virtual-node batch);
- ``factor = n_virtual // n_physical`` divides the accumulation axis
  (default ``data``), giving ``physical[accum] = logical[accum]/factor``
  and ``accum_steps = factor``;
- all other axes (``fsdp``/``model``/``seq``/``pp``/``ep``) keep their
  logical size: collective-bearing axes never silently shrink, so a
  layout that fits in HBM on the logical shape still fits after a
  resize (the per-step microbatch shrinks instead).
"""

from __future__ import annotations

import logging
import math
from dataclasses import dataclass, field

from tensorflowonspark_tpu.parallel.mesh import canonical_axes, make_mesh

logger = logging.getLogger(__name__)

DEFAULT_ACCUM_AXIS = "data"


@dataclass(frozen=True)
class VirtualLayout:
    """One resolved placement of a logical mesh onto physical devices.

    ``logical`` is the stable shape a ``TrainSpec`` names; ``physical``
    is what this incarnation's devices support; ``accum_steps`` bridges
    the two (``prod(logical) == prod(physical) * accum_steps``).
    ``mesh`` is the live ``jax.sharding.Mesh`` over ``physical``.
    """

    logical: dict = field(default_factory=dict)
    physical: dict = field(default_factory=dict)
    accum_axis: str = DEFAULT_ACCUM_AXIS
    accum_steps: int = 1
    mesh: object = None

    @property
    def n_virtual(self):
        return math.prod(self.logical.values()) if self.logical else 1

    @property
    def n_physical(self):
        return math.prod(self.physical.values()) if self.physical else 1

    # -- sharding helpers (thin delegates so callers never need to know
    # whether they are on the logical or a folded physical shape) -------

    def batch_sharding(self, axes=("data", "fsdp")):
        from tensorflowonspark_tpu.parallel import batch_sharding

        return batch_sharding(self.mesh, axes=axes)

    def fsdp_sharding(self, tree, axis="fsdp"):
        from tensorflowonspark_tpu.parallel import fsdp_sharding

        return fsdp_sharding(self.mesh, tree, axis)

    def replicated(self):
        from tensorflowonspark_tpu.parallel import replicated

        return replicated(self.mesh)

    def shard_train_state(self, params, state, opt_state, fsdp_axis="fsdp"):
        from tensorflowonspark_tpu.parallel import shard_train_state

        return shard_train_state(self.mesh, params, state, opt_state,
                                 fsdp_axis=fsdp_axis)

    def value_and_grad(self, loss_fn, has_aux=False, carry_aux=False):
        """``jax.value_and_grad`` at this layout's accumulation depth:
        the returned function consumes the full logical-mesh global
        batch and replays it in ``accum_steps`` microbatches, so loss
        and mean gradient match the logical shape exactly
        (``utils/train.accumulated_value_and_grad``)."""
        from tensorflowonspark_tpu.utils.train import (
            accumulated_value_and_grad,
        )

        return accumulated_value_and_grad(
            loss_fn, self.accum_steps, has_aux=has_aux, carry_aux=carry_aux)

    def microbatch(self, global_batch):
        """Per-dispatch batch after accumulation folding: the physical
        step consumes this many rows ``accum_steps`` times per optimizer
        update."""
        if global_batch % self.accum_steps:
            raise ValueError(
                f"global batch {global_batch} not divisible by "
                f"accum_steps={self.accum_steps}")
        return global_batch // self.accum_steps

    def describe(self):
        return (f"logical={self.logical} physical={self.physical} "
                f"accum={self.accum_steps}x{self.accum_axis} "
                f"devices={self.n_physical}")


def virtualize(logical_axes, devices, accum_axis=DEFAULT_ACCUM_AXIS):
    """Place ``logical_axes`` (fully-specified virtual mesh shape) onto
    ``devices``, folding any surplus through gradient accumulation.

    Raises ``ValueError`` when the device count is not a divisor of the
    virtual device count, when the surplus does not divide the
    accumulation axis, or when the logical shape contains ``-1`` (a
    virtual shape is the stable contract — it cannot absorb a device
    count that changes under it).
    """
    logical = canonical_axes(dict(logical_axes))
    if any(v == -1 for v in logical.values()):
        raise ValueError(
            f"virtual mesh shape must be fully specified, got {logical} "
            "(-1 absorption is only meaningful against a fixed device "
            "count; see parallel.mesh.MeshSpec)")
    if any(v < 1 for v in logical.values()):
        raise ValueError(f"virtual mesh axis sizes must be >= 1: {logical}")
    accum_axis = canonical_axes({accum_axis: 1}).popitem()[0]
    devices = list(devices)
    n_virtual = math.prod(logical.values()) if logical else 1
    n_physical = len(devices)
    if n_physical < 1:
        raise ValueError("virtualize: empty device list")
    if n_virtual % n_physical:
        raise ValueError(
            f"{n_physical} devices is not a divisor topology of the "
            f"virtual mesh {logical} ({n_virtual} virtual devices)")
    factor = n_virtual // n_physical
    physical = dict(logical)
    if factor > 1:
        if accum_axis not in logical:
            raise ValueError(
                f"virtual mesh {logical} has no '{accum_axis}' axis to "
                f"fold the {factor}x device deficit into")
        if logical[accum_axis] % factor:
            raise ValueError(
                f"cannot fold {factor}x into axis '{accum_axis}' of size "
                f"{logical[accum_axis]} (virtual {logical} on "
                f"{n_physical} devices)")
        physical[accum_axis] = logical[accum_axis] // factor
    mesh = make_mesh(physical, devices=devices)
    layout = VirtualLayout(logical=logical, physical=physical,
                           accum_axis=accum_axis, accum_steps=factor,
                           mesh=mesh)
    logger.info("virtualize: %s", layout.describe())
    return layout
