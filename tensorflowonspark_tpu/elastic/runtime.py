"""Elastic SPMD runtime: one ``TrainSpec``, any divisor topology.

The promotion of ``__graft_entry__.dryrun_multichip``'s hand-built
meshes into a first-class runtime (ROADMAP item 1; in-framework mesh
construction in the spirit of TF-Replicator, arXiv 1902.00465 — no
reference equivalent: the reference delegated every collective to TF
and froze the cluster shape in TF_CONFIG, SURVEY.md §2.4).

A ``TrainSpec`` names the *logical* mesh a model is configured for
(axis convention: ``parallel/mesh.AXIS_ORDER``).  ``ElasticRuntime``
resolves it against whatever devices this incarnation actually has
(``elastic/virtual.virtualize``), hands out shardings, and — when the
cluster shrinks or re-grows under ``cluster.run(restarts=N,
min_executors=k)`` supervision — ``resize()`` re-forms the mesh over
the surviving devices and ``reshard`` / ``restore`` re-place the train
state under it (``elastic/reshard.py``).

Observability: every build/resize sets the mesh-shape gauges
(``tfos_elastic_mesh_devices`` / ``tfos_elastic_virtual_devices`` /
``tfos_elastic_accum_steps``) and resizes bump
``tfos_elastic_resizes_total`` (docs/observability.md).
"""

from __future__ import annotations

import logging
from dataclasses import dataclass, field

import tensorflowonspark_tpu.elastic.virtual as _virtual
# function imports, not the module: the package __init__ re-exports the
# reshard() function under the same attribute name as the reshard module
from tensorflowonspark_tpu.elastic.reshard import (
    reshard as _reshard_tree,
    reshard_train_state as _reshard_train_state,
)
from tensorflowonspark_tpu.utils import metrics_registry, telemetry

logger = logging.getLogger(__name__)


@dataclass
class TrainSpec:
    """The topology-stable half of a training config.

    ``mesh_axes``: fully-specified logical mesh, e.g.
    ``{"data": 8, "fsdp": 4}`` (aliases ``pipe``/``expert`` accepted).
    ``global_batch``: optimizer-visible batch size; 0 = caller manages
    batching itself.  ``accum_axis``: which axis absorbs a device
    deficit through gradient accumulation (default ``data``).
    """

    mesh_axes: dict = field(default_factory=dict)
    global_batch: int = 0
    accum_axis: str = _virtual.DEFAULT_ACCUM_AXIS


class ElasticRuntime:
    """Live mesh state for one training job: build once, resize on
    topology change, reshard/restore train state under the current
    layout.

    ::

        rt = ElasticRuntime(TrainSpec({"data": 8, "fsdp": 2}), devices)
        (params, state, opt_state), shardings = rt.shard_train_state(...)
        ...                      # executor lost; recovery re-formed us
        rt.resize(jax.devices())             # 16 virtual -> 8 physical
        (params, ...), shardings = rt.reshard_train_state(params, ...)
    """

    def __init__(self, spec, devices=None):
        if not isinstance(spec, TrainSpec):
            spec = TrainSpec(dict(spec))
        self.spec = spec
        self.generation = 0
        self.layout = None
        self._build(devices, event="elastic/build")

    # -- topology -------------------------------------------------------

    def _build(self, devices, event):
        if devices is None:
            import jax

            devices = jax.devices()
        layout = _virtual.virtualize(
            self.spec.mesh_axes, devices, accum_axis=self.spec.accum_axis)
        self.layout = layout
        telemetry.event(event, generation=self.generation,
                        logical=dict(layout.logical),
                        physical=dict(layout.physical),
                        accum_steps=layout.accum_steps,
                        devices=layout.n_physical)
        metrics_registry.set_gauge("tfos_elastic_mesh_devices",
                                   layout.n_physical)
        metrics_registry.set_gauge("tfos_elastic_virtual_devices",
                                   layout.n_virtual)
        metrics_registry.set_gauge("tfos_elastic_accum_steps",
                                   layout.accum_steps)
        logger.info("elastic runtime gen %d: %s",
                    self.generation, layout.describe())
        return layout

    def resize(self, devices=None):
        """Re-form the mesh over a new device set (smaller after an
        executor loss, larger after the pool re-grew).  The logical
        shape never changes — only the physical fold does.  Existing
        arrays keep their OLD placement; push them through
        ``reshard``/``reshard_train_state`` before stepping again."""
        self.generation += 1
        layout = self._build(devices, event="elastic/resize")
        metrics_registry.inc("tfos_elastic_resizes_total", scope="runtime")
        return layout

    # -- sharding / state placement ------------------------------------

    @property
    def mesh(self):
        return self.layout.mesh

    def batch_sharding(self, axes=("data", "fsdp")):
        return self.layout.batch_sharding(axes=axes)

    def fsdp_sharding(self, tree, axis="fsdp"):
        return self.layout.fsdp_sharding(tree, axis=axis)

    def shard_train_state(self, params, state, opt_state, fsdp_axis="fsdp"):
        return self.layout.shard_train_state(params, state, opt_state,
                                             fsdp_axis=fsdp_axis)

    def reshard(self, tree, shardings=None):
        """Re-place any pytree under the CURRENT layout (host
        round-trip).  Default shardings: fsdp rules over the tree."""
        if shardings is None:
            shardings = self.layout.fsdp_sharding(tree)
        return _reshard_tree(tree, shardings)

    def reshard_train_state(self, params, state, opt_state,
                            fsdp_axis="fsdp"):
        return _reshard_train_state(
            self.layout, params, state, opt_state, fsdp_axis=fsdp_axis)

    def value_and_grad(self, loss_fn, has_aux=False, carry_aux=False):
        return self.layout.value_and_grad(loss_fn, has_aux=has_aux,
                                          carry_aux=carry_aux)

    def restore(self, ckpt_dir, shardings=None):
        """(tree, step) from the newest checkpoint in ``ckpt_dir``,
        re-placed under the current layout — the resize-aware resume
        path.  ``shardings``: explicit sharding pytree or callable;
        default fsdp rules over the restored tree."""
        from tensorflowonspark_tpu.utils import checkpoint as _ckpt

        if shardings is None:
            def shardings(tree):
                return self.layout.fsdp_sharding(tree)
        return _ckpt.restore_any(ckpt_dir, target_shardings=shardings)

    # -- batch schedule -------------------------------------------------

    def batch_schedule(self):
        """How ``spec.global_batch`` lands on the current layout:
        ``{"global", "microbatch", "per_device", "accum_steps"}``.
        The global batch (and so the optimizer trajectory) is
        topology-invariant; only the per-dispatch slice moves."""
        gb = int(self.spec.global_batch)
        if gb <= 0:
            raise ValueError("TrainSpec.global_batch not set")
        layout = self.layout
        micro = layout.microbatch(gb)
        data_shards = 1
        for a in ("data", "fsdp"):
            data_shards *= layout.physical.get(a, 1)
        if micro % data_shards:
            raise ValueError(
                f"microbatch {micro} not divisible by {data_shards} "
                f"batch shards (layout {layout.describe()})")
        return {"global": gb, "microbatch": micro,
                "per_device": micro // data_shards,
                "accum_steps": layout.accum_steps}


def from_context(ctx, spec, devices=None):
    """Build an :class:`ElasticRuntime` inside a cluster node: the
    rendezvous output (``ctx.cluster_info``) has already sized the JAX
    job (``ctx.jax_initialize``), so the global device view IS the
    cluster spec made concrete; the logical shape comes from the
    caller's ``TrainSpec``.  Stamped with the node's cluster epoch so
    resize generations line up with cluster incarnations in the merged
    trace."""
    rt = ElasticRuntime(spec, devices=devices)
    telemetry.event("elastic/from_context",
                    epoch=getattr(ctx, "epoch", 0),
                    job=getattr(ctx, "job_name", None),
                    task=getattr(ctx, "task_index", None),
                    devices=rt.layout.n_physical)
    return rt
