"""Resize-time resharding: re-place train state under a new mesh.

The recovery half of the elastic runtime (SURVEY.md §5 grows beyond
"restart job from checkpoint"): a checkpoint written under one mesh
shape restores under another.  The contract is deliberately a **host
round-trip** — every leaf is fetched to host memory first, then
``jax.device_put`` lays it out under the target sharding — because at
resize time the source placement is unusable by construction: the old
mesh may reference devices that no longer exist (a lost executor's
chips), and checkpoint restores arrive as host numpy anyway.  Values
never change; only placement does.  Optimizer moments travel with their
parameter's layout (``parallel/sharding.shard_train_state``), which is
the whole resize story for mean-reduced losses: the global batch and
the mean gradient are topology-invariant under the virtual layer, so
moments need re-placement, not re-scaling.
"""

from __future__ import annotations

import logging
import time

import numpy as np

from tensorflowonspark_tpu.utils import metrics_registry, telemetry

logger = logging.getLogger(__name__)


def host_fetch(tree):
    """Every leaf as host numpy (works for leaves placed under a dead or
    foreign mesh: fetching is per-shard reads, not a collective)."""
    import jax

    return jax.tree_util.tree_map(lambda x: np.asarray(x), tree)


def reshard(tree, target_shardings):
    """Re-place ``tree`` under ``target_shardings`` via the host.

    ``target_shardings`` is either a pytree of ``Sharding`` matching
    ``tree`` (a prefix tree works, as with ``jax.device_put``) or a
    callable ``tree -> shardings`` — the callable form lets callers
    derive shardings from the restored structure itself (e.g.
    ``lambda t: fsdp_sharding(new_mesh, t)``), which is what
    ``utils/checkpoint.restore_any(target_shardings=...)`` passes
    through.
    """
    import jax

    if callable(target_shardings):
        target_shardings = target_shardings(tree)
    t0 = time.perf_counter()
    with telemetry.span("elastic/reshard"):
        placed = jax.device_put(host_fetch(tree), target_shardings)
    metrics_registry.observe("tfos_elastic_reshard_ms",
                             (time.perf_counter() - t0) * 1000.0)
    return placed


def reshard_train_state(layout, params, state, opt_state, fsdp_axis="fsdp"):
    """Re-place a full train state under ``layout``'s mesh: fsdp for
    params and optimizer moments, replicated model state — the same
    rules as first placement (``shard_train_state``), applied through
    the host round-trip so it works across a resize.

    Returns ``((params, state, opt_state), (p_sh, s_sh, o_sh))`` like
    ``shard_train_state``; the shardings feed the re-jit of the train
    step under the new mesh.
    """
    t0 = time.perf_counter()
    with telemetry.span("elastic/reshard_train_state"):
        out = layout.shard_train_state(
            host_fetch(params), host_fetch(state), host_fetch(opt_state),
            fsdp_axis=fsdp_axis)
    metrics_registry.observe("tfos_elastic_reshard_ms",
                             (time.perf_counter() - t0) * 1000.0)
    return out
