"""Elastic SPMD runtime: virtual-device meshes with resize-and-reshard.

No reference equivalent (SURVEY.md §2.3/§2.4: the reference delegated
collectives to TF and could only restart a fixed-size cluster).  This
package decouples the logical mesh a model is configured for from the
physical devices an incarnation happens to have (``virtual.py``),
resolves rendezvous cluster specs into live meshes (``runtime.py``),
and re-places train state when the topology changes under supervision
(``reshard.py``).  Walkthrough: docs/elastic.md.
"""

from tensorflowonspark_tpu.elastic.reshard import (  # noqa: F401
    host_fetch,
    reshard,
    reshard_train_state,
)
from tensorflowonspark_tpu.elastic.runtime import (  # noqa: F401
    ElasticRuntime,
    TrainSpec,
    from_context,
)
from tensorflowonspark_tpu.elastic.virtual import (  # noqa: F401
    VirtualLayout,
    virtualize,
)
