"""Executor-side node runtime (parity: reference TFSparkNode.py).

One framework node per engine executor.  The node task:

1. claims TPU chips for this process (tpu_info, parity: _get_gpus),
2. derives its job/task from the cluster template,
3. starts the per-executor IPC manager (manager.py),
4. registers with the driver's rendezvous server and awaits the full
   cluster (rendezvous.py),
5. exports the JAX-distributed bootstrap env (coordinator address +
   process id — the TF_CONFIG equivalent, TFSparkNode.py:366-374),
6. runs the user ``main_fun(args, ctx)`` — foreground for direct-read
   workers, background process for InputMode.SPARK workers so the executor
   slot frees up for feeder tasks, control-queue wait loop for
   ps/evaluator (TFSparkNode.py:411-443).

The feeder/inference/shutdown closures at the bottom reattach to the
node's manager through the executor-id file (util.py:77-94 pattern) and
move data in **chunks** (lists of records), not per-record.
"""

from __future__ import annotations

import json
import logging
import multiprocessing
import os
import signal
import socket
import sys
import tempfile
import threading
import time
import traceback

from tensorflowonspark_tpu import manager as tfmanager
from tensorflowonspark_tpu import marker, rendezvous, tpu_info
from tensorflowonspark_tpu.utils import (
    faults,
    get_ip_address,
    read_executor_id,
    reap_child,
    telemetry,
    track_child_pid,
    write_executor_id,
)

logger = logging.getLogger(__name__)

# Records per queue chunk on the feed path; one IPC hop per chunk.
FEED_CHUNK_RECORDS = int(os.environ.get("TFOS_FEED_CHUNK", "1024"))


def _feed_chunk_records():
    """Chunk size resolved where the feeder RUNS, not where it was pickled.

    The feeder closures are cloudpickled by value, which snapshots module
    globals from the driver — so :data:`FEED_CHUNK_RECORDS` as seen by an
    executor would silently be the *driver's* import-time value.  Reading
    the env at call time lets per-executor overrides (``LocalEngine(env=
    {"TFOS_FEED_CHUNK": ...})``) actually pace the feed."""
    try:
        return int(os.environ.get("TFOS_FEED_CHUNK", "")) or FEED_CHUNK_RECORDS
    except ValueError:
        return FEED_CHUNK_RECORDS

COMPUTE_JOBS = ("chief", "master", "worker")


class _NodeState:
    """Per-executor-process globals (parity: TFSparkNode class attrs)."""

    mgr = None
    cluster_id = None
    epoch = 0  # cluster incarnation this node belongs to
    ring = None  # shm feed ring (creator side), kept alive for the cluster
    tb_proc = None  # TensorBoard child of the dashboard node


def _teardown_node_state():
    """Dismantle this executor's node incarnation — background trainer,
    IPC manager, shm ring, TensorBoard — so a retried node task or a new
    cluster epoch can boot clean on the same executor.  Best-effort
    throughout: the incarnation being torn down may already be half dead."""
    mgr = _NodeState.mgr
    if mgr is not None:
        try:
            bg = mgr.get("bg_pid")
            if bg:
                reap_child(int(str(bg)), timeout=0.2, term_first=False)
        except Exception:  # noqa: BLE001
            pass
        try:
            mgr.shutdown()
        except Exception:  # noqa: BLE001
            pass
    if _NodeState.ring is not None:
        try:
            _NodeState.ring.close()
        except Exception:  # noqa: BLE001
            pass
    if _NodeState.tb_proc is not None:
        try:
            _NodeState.tb_proc.kill()
        except Exception:  # noqa: BLE001
            pass
    _NodeState.mgr = None
    _NodeState.cluster_id = None
    _NodeState.ring = None
    _NodeState.tb_proc = None


def _get_cluster_spec(cluster_info):
    """{job: [node_meta sorted by task_index]} (TFSparkNode.py:43-56)."""
    spec = {}
    for meta in sorted(cluster_info, key=lambda m: m["executor_id"]):
        spec.setdefault(meta["job_name"], []).append(meta)
    for job, nodes in spec.items():
        seen = {}
        for n in nodes:
            if n["task_index"] in seen:
                raise RuntimeError(
                    f"duplicate task_index {n['task_index']} in job {job}: "
                    f"{n} vs {seen[n['task_index']]}"
                )
            seen[n["task_index"]] = n
    return spec


def _distributed_env(cluster_info):
    """Bootstrap info for jax.distributed (the TF_CONFIG replacement).

    Compute processes (chief/master/worker) get contiguous process ids
    with the chief first; the coordinator is process 0's reserved
    host:port.  ps/evaluator nodes are *not* part of the SPMD job.
    """
    compute = [m for m in cluster_info if m["job_name"] in COMPUTE_JOBS]
    compute.sort(key=lambda m: (m["job_name"] not in ("chief", "master"), m["executor_id"]))
    ids = {m["executor_id"]: i for i, m in enumerate(compute)}
    coordinator = f"{compute[0]['host']}:{compute[0]['port']}" if compute else None
    return {
        "coordinator_address": coordinator,
        "num_processes": len(compute),
        "process_ids": ids,
    }


class TFNodeContext:
    """Node metadata handed to user code (parity: TFSparkNode.py:59-99)."""

    def __init__(
        self,
        executor_id,
        job_name,
        task_index,
        cluster_spec,
        default_fs,
        working_dir,
        mgr,
        cluster_info=None,
        epoch=0,
    ):
        self.executor_id = executor_id
        self.job_name = job_name
        self.task_index = task_index
        self.cluster_spec = cluster_spec
        self.default_fs = default_fs
        self.working_dir = working_dir
        self.mgr = mgr
        self.cluster_info = cluster_info or []
        self.epoch = epoch  # cluster incarnation (bumped by recovery)

    @property
    def num_workers(self):
        return sum(len(v) for k, v in self.cluster_spec.items() if k in COMPUTE_JOBS)

    def absolute_path(self, path):
        from tensorflowonspark_tpu import feed

        return feed.hdfs_path(self, path)

    def get_data_feed(
        self, train_mode=True, qname_in="input", qname_out="output",
        input_mapping=None, metrics=None,
    ):
        from tensorflowonspark_tpu.feed import DataFeed

        return DataFeed(
            self.mgr, train_mode, qname_in, qname_out, input_mapping, metrics
        )

    def restore_latest(self, ckpt_dir, target_shardings=None):
        """(tree, start_step) from the newest checkpoint in ``ckpt_dir``
        regardless of who wrote it (npz or orbax layouts; (None, 0) when
        empty) — the auto-resume half of ``cluster.run(restarts=N)``:
        training mains call this at startup, so a relaunched incarnation
        continues from where the dead one last saved.

        ``target_shardings`` (pytree of ``Sharding`` or callable
        ``tree -> shardings``) re-places the restored leaves under this
        incarnation's mesh — required after an elastic resize, where the
        checkpoint was written under a different topology
        (``utils/checkpoint.restore_any``, docs/elastic.md)."""
        from tensorflowonspark_tpu.utils import checkpoint as _ckpt

        tree, step = _ckpt.restore_any(ckpt_dir,
                                       target_shardings=target_shardings)
        telemetry.event("node/resume", step=step, epoch=self.epoch,
                        found=tree is not None,
                        resharded=target_shardings is not None)
        if tree is not None:
            logger.info("node %s:%s resuming from step %d (epoch %d)",
                        self.job_name, self.task_index, step, self.epoch)
        return tree, step

    def elastic_runtime(self, mesh_axes, devices=None, global_batch=0,
                        accum_axis="data"):
        """An :class:`elastic.ElasticRuntime` for this node: the logical
        mesh shape ``mesh_axes`` resolved over this incarnation's
        devices (default: all devices visible after
        ``jax_initialize``).  A relaunched node on a shrunken cluster
        gets a smaller physical mesh for the SAME logical shape, with
        gradient accumulation making up the difference
        (docs/elastic.md)."""
        from tensorflowonspark_tpu import elastic

        return elastic.from_context(
            self,
            elastic.TrainSpec(mesh_axes=dict(mesh_axes),
                              global_batch=int(global_batch),
                              accum_axis=accum_axis),
            devices=devices)

    def distributed_env(self):
        env = _distributed_env(self.cluster_info)
        return {
            "coordinator_address": env["coordinator_address"],
            "num_processes": env["num_processes"],
            "process_id": env["process_ids"].get(self.executor_id),
        }

    def jax_initialize(self):
        """Join the multi-controller JAX job (TF_CONFIG/MWMS replacement).

        No-op for ps/evaluator roles (they own no chips).  Single-process
        jobs skip jax.distributed but still run the slice health check —
        the silent libtpu-fallback (training on host CPU) is most common
        exactly there.
        """
        env = self.distributed_env()
        if env["process_id"] is None:  # ps/evaluator: no accelerator claim
            return env
        if env["num_processes"] > 1:
            import jax

            plat = (os.environ.get("JAX_PLATFORMS")
                    or str(getattr(jax.config, "jax_platforms", None) or ""))
            if plat.split(",")[0].strip() == "cpu":
                # multi-process SPMD on the CPU backend needs the gloo
                # cross-process collectives; without them every sharded
                # computation fails with "Multiprocess computations
                # aren't implemented on the CPU backend".  Must be set
                # before the backend initializes.
                try:
                    jax.config.update(
                        "jax_cpu_collectives_implementation", "gloo")
                except Exception:  # noqa: BLE001 - option may move/vanish
                    logger.warning("could not enable gloo cpu collectives",
                                   exc_info=True)
            jax.distributed.initialize(
                coordinator_address=env["coordinator_address"],
                num_processes=env["num_processes"],
                process_id=env["process_id"],
            )
            self._jax_distributed = True
        # slice health at bring-up (SURVEY.md §5): a process that joined
        # the job but sees a wedged chip or a short device count should
        # say so here, where the error queue still reaches the driver,
        # not via a hang in the first collective
        from tensorflowonspark_tpu import tpu_info

        health = tpu_info.slice_health(
            expected_processes=env["num_processes"])
        env["slice_health"] = health
        if not health["healthy"]:
            logger.error("slice health check failed: %s", health["errors"])
            # TFOS_SLICE_HEALTH modes:
            #   lenient (default) — definite findings (wrong device
            #     counts, CPU fallback, smoke failure) are fatal; a probe
            #     that merely TIMED OUT with nothing else found is
            #     warn-only, because first TPU contact through a slow
            #     pool/tunnel can exceed any fixed window (widen via
            #     TFOS_SLICE_HEALTH_TIMEOUT).
            #   strict — everything fatal, including probe timeouts:
            #     fail-fast for deployments that prefer a bring-up error
            #     over a possible hang in the first collective.
            #   warn — log only, never fatal.
            mode = os.environ.get(
                "TFOS_SLICE_HEALTH", "lenient").strip().lower()
            if mode not in ("strict", "lenient", "warn"):
                logger.warning(
                    "unknown TFOS_SLICE_HEALTH=%r; treating as 'lenient' "
                    "(valid: strict|lenient|warn)", mode)
                mode = "lenient"
            only_timeout = health.get("bare_timeout", False)
            # raising here routes through the node wrapper's exception
            # path onto the error queue, which the feeder/driver observe
            if mode != "warn" and not (only_timeout and mode == "lenient"):
                raise RuntimeError(
                    f"unhealthy accelerator slice: {health['errors']}")
        else:
            logger.info(
                "slice healthy: %d local / %d global devices (%s)",
                health["local_devices"], health["global_devices"],
                health["platform"])
        return env

    def sync_exit_barrier(self):
        """Cross-process barrier run by the node wrapper after user code
        returns: every process drains its async dispatch queue and waits
        for its peers before tearing down its collective endpoints.

        Without this, a worker that finishes feeding first exits while a
        peer's final all-reduce is still in flight and resets the
        connection mid-collective (the TPU-native analogue of the
        reference's grace_secs-before-export contract, TFCluster.py:125).
        """
        if not getattr(self, "_jax_distributed", False):
            return
        try:
            from jax.experimental import multihost_utils

            # blocks until every process reaches it, and its collective is
            # ordered after all previously dispatched collectives on every
            # participant
            multihost_utils.sync_global_devices("tfos_node_exit")
        except Exception as e:  # noqa: BLE001 - best-effort on teardown
            logger.warning("exit barrier failed: %s", e)

    def export_env(self):
        """Export bootstrap env vars for subprocesses (TF_CONFIG parity)."""
        env = self.distributed_env()
        os.environ["TFOS_COORDINATOR"] = env["coordinator_address"] or ""
        os.environ["TFOS_NUM_PROCESSES"] = str(env["num_processes"])
        os.environ["TFOS_PROCESS_ID"] = str(
            env["process_id"] if env["process_id"] is not None else -1
        )
        os.environ["TFOS_CLUSTER_SPEC"] = json.dumps(
            {k: [f"{m['host']}:{m['port']}" for m in v] for k, v in self.cluster_spec.items()}
        )


def _job_for_executor(cluster_template, executor_id):
    for job, ids in cluster_template.items():
        if executor_id in ids:
            return job, sorted(ids).index(executor_id)
    raise RuntimeError(f"executor {executor_id} not in template {cluster_template}")


def run(fn, tf_args, cluster_meta, tensorboard=False, log_dir=None,
        queues=None, background=False, num_chips=0):
    """Build the node-startup closure (parity: TFSparkNode.run :149-445)."""
    queues = queues or ["input", "output", "error", "control"]

    def _mapfn(iterator):
        boot_t0 = time.perf_counter()
        executor_id = None
        for item in iterator:  # one element per spread partition
            executor_id = item
        assert executor_id is not None, "empty node partition"

        # (1) claim TPU chips before any jax/XLA initialization —
        # scheduler (Spark-3 resources API) first, host scan second
        # (decision table: tpu_info.claim_chips, ref TFSparkNode.py:170-229)
        tpu_info.claim_chips(num_chips, _same_host_index(executor_id))

        # (2) role from template
        job_name, task_index = _job_for_executor(
            cluster_meta["cluster_template"], executor_id
        )

        # Pin telemetry identity + node-local spool for this process AND
        # its fork children (trainer), via the env channel.  In-process
        # engines (sparkstub) may run this in the driver itself — never
        # relabel the driver's recorder there.  The spool must live
        # OUTSIDE the engine scratch cwd: engine.stop() rmtree's the
        # scratch root, and flight dumps (*.json) are not part of the
        # *.jsonl drain — a dump written moments before a crash has to
        # survive engine teardown.  Non-dot dir name on purpose:
        # postmortem's recursive glob skips dotdirs.
        if os.environ.get(telemetry.ROLE_ENV) != "driver":
            base = os.environ.get(telemetry.DIR_ENV) or os.path.join(
                tempfile.gettempdir(), ".tfos_telemetry")
            cid = cluster_meta["id"] & 0xffffffff
            telemetry.configure(
                node_id=f"{job_name}-{task_index}",
                role=job_name,
                spool=os.path.join(
                    os.path.abspath(base),
                    f"spool-{cid:x}-{job_name}-{task_index}"),
            )

        faults.check("node.boot", executor=executor_id, job=job_name)

        # (3) idempotency/retry guard (TFSparkNode.py:249-255), epoch-aware:
        # a live manager from the SAME cluster AND epoch means a duplicate
        # placement — raise so the engine/Spark retries this task elsewhere.
        # A node from a PREVIOUS epoch (cluster recovery relaunched us on a
        # surviving executor) is stale: tear it down and boot fresh.
        epoch = int(cluster_meta.get("epoch", 0))
        if (_NodeState.mgr is not None
                and _NodeState.cluster_id == cluster_meta["id"]):
            try:
                state = str(_NodeState.mgr.get("state"))
            except Exception:  # noqa: BLE001 - manager server already dead
                state = None
            if (_NodeState.epoch == epoch
                    and state in ("running", "terminating")):
                raise RuntimeError(
                    f"executor already hosts a node of cluster "
                    f"{cluster_meta['id']}"
                )
            logger.info(
                "tearing down stale node incarnation (epoch %d state %s) "
                "before booting epoch %d", _NodeState.epoch, state, epoch)
            _teardown_node_state()

        authkey = bytes.fromhex(cluster_meta["authkey"])
        mode = "remote" if job_name in ("ps", "evaluator") else "local"
        mgr = tfmanager.start(authkey, queues, mode)
        _NodeState.mgr = mgr
        _NodeState.cluster_id = cluster_meta["id"]
        _NodeState.epoch = epoch
        write_executor_id(executor_id)

        # Everything up to execution is boot: a failure here (rendezvous
        # rejection, injected fault, dead ring) must release this
        # executor's node identity — manager, ring, children — so an
        # engine-level retry of the SAME task can boot clean instead of
        # tripping the duplicate-placement guard forever.
        try:
            # Fast same-host feed transport: a shared-memory ring for the
            # 'input' stream (native/shmqueue.cpp).  The manager keeps
            # control/error/output and the state machine; the ring carries
            # the bulk record chunks with no per-chunk manager RPC.
            if os.environ.get("TFOS_SHM_FEED", "1") != "0":
                try:
                    from tensorflowonspark_tpu.recordio import shm as shmq

                    if shmq.available():
                        # epoch in the name: a recovered cluster's fresh ring
                        # must never collide with a dead incarnation's shm
                        # segment that a wedged orphan still maps
                        ring_name = (
                            f"/tfos-{cluster_meta['id'] & 0xffffffff:x}"
                            f"{'' if not epoch else f'-e{epoch}'}"
                            f"-{executor_id}")
                        cap = int(os.environ.get("TFOS_SHM_FEED_BYTES", str(256 << 20)))
                        _NodeState.ring = shmq.ShmQueue(ring_name, cap, create=True)
                        mgr.set("shm_input", ring_name)
                except Exception as e:  # noqa: BLE001 - optional acceleration
                    logger.warning("shm feed unavailable: %s", e)

            # (4) rendezvous: reserve a port for the coordinator service (the
            # free-port trick, TFSparkNode.py:337-342), then register.
            client = rendezvous.Client(cluster_meta["server_addr"])
            host = get_ip_address()
            tmp_sock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
            tmp_sock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
            port_env = os.environ.get("TFOS_NODE_PORT")
            tmp_sock.bind(("", int(port_env) if port_env else 0))
            port = tmp_sock.getsockname()[1]
            maddr = list(mgr.address)
            if mode == "remote" and maddr[0] in ("", "0.0.0.0"):
                maddr[0] = host  # advertise a dialable address to the driver
            node_meta = {
                "executor_id": executor_id,
                "host": host,
                "job_name": job_name,
                "task_index": task_index,
                "port": port,
                "addr": maddr,
                "authkey": cluster_meta["authkey"],
            }

            # dashboard node: spawn TensorBoard before registering so its
            # port travels with the reservation (TFSparkNode.py:282-319)
            if (
                tensorboard
                and task_index == 0
                and job_name in ("chief", "master", "worker")
                and ("chief" not in cluster_meta["cluster_template"]
                     and "master" not in cluster_meta["cluster_template"]
                     or job_name in ("chief", "master"))
            ):
                from tensorflowonspark_tpu.utils import profiler as _profiler

                tb_dir = log_dir or os.path.join(
                    cluster_meta["working_dir"], "tensorboard",
                    f"cluster-{cluster_meta['id'] & 0xffffffff:x}",
                )
                _NodeState.tb_proc, tb_port = _profiler.launch_tensorboard(tb_dir)
                if tb_port:
                    node_meta["tb_port"] = tb_port
                    # pid in the manager KV so the shutdown closure (which
                    # may run in a different python worker) can kill the
                    # child
                    mgr.set("tb_pid", _NodeState.tb_proc.pid)
                    telemetry.event("node/tb_spawn", port=tb_port,
                                    pid=_NodeState.tb_proc.pid)

            client.register(node_meta, epoch=epoch)
            cluster_info = client.await_reservations(
                timeout=cluster_meta.get("reservation_timeout", 600)
            )
            client.close()
            logger.info("node %d: cluster complete (%d nodes)", executor_id, len(cluster_info))

            # (5) context + bootstrap env
            cluster_spec = _get_cluster_spec(cluster_info)
            ctx = TFNodeContext(
                executor_id,
                job_name,
                task_index,
                cluster_spec,
                cluster_meta["default_fs"],
                cluster_meta["working_dir"],
                mgr,
                cluster_info,
                epoch=epoch,
            )
            ctx.export_env()

            # release the reserved port as late as possible
            tmp_sock.close()

            # Boot complete: chips claimed, manager up, rendezvous done.
            # The spool dir is advertised in the manager KV so the driver
            # drain (cluster.shutdown -> drain_telemetry) can find every
            # node file.
            telemetry.register_with(mgr)
            telemetry.record_span(
                "node/boot", time.perf_counter() - boot_t0,
                executor=executor_id, nodes=len(cluster_info))
        except BaseException:
            telemetry.flush()
            _teardown_node_state()
            raise

        def wrapper_fn(args, context):
            if isinstance(args, list):
                sys.argv = args
            # liveness beacon for the feeder: a trainer that stops beating
            # is DEAD, one that beats while busy is merely SLOW
            hb = tfmanager.start_heartbeat(mgr)
            # live metrics plane: snapshot this process's registry into
            # the manager KV every TFOS_OBS_INTERVAL (None when disabled)
            from tensorflowonspark_tpu.obs import publish as obs_publish

            obs_id = f"{context.job_name}-{context.task_index}"
            pub = obs_publish.start_publisher(mgr, obs_id,
                                              role=context.job_name)
            from tensorflowonspark_tpu.obs.health import HealthHalt

            try:
                with telemetry.span("node/main", job=context.job_name,
                                    task=context.task_index):
                    faults.check("node.main", job=context.job_name,
                                 task=context.task_index)
                    fn(args, context)
                # all processes leave together (see sync_exit_barrier
                # docstring)
                context.sync_exit_barrier()
            except HealthHalt as e:
                # a health reaction (TFOS_HEALTH_ACTION=halt) already
                # checkpointed at the last finite step; stop this node
                # cleanly — no exit barrier (peers halting on the same
                # anomaly stop on their own; waiting on a diverged run
                # would burn exactly the chip hours halt exists to save)
                logger.warning("node %s:%d health halt: %s",
                               context.job_name, context.task_index, e)
                telemetry.event("health/halt", job=context.job_name,
                                task=context.task_index, reason=str(e))
                try:
                    mgr.set("state", "terminating")  # feeders drain
                except Exception:  # noqa: BLE001 - manager tearing down
                    pass
            finally:
                hb.set()
                if pub is not None:
                    pub.set()
                    # the thread's final publish races process exit; land
                    # the tail counts synchronously
                    obs_publish.publish_once(mgr, obs_id,
                                             role=context.job_name)
                telemetry.flush()

        def wrapper_fn_background(args, context):
            # fork child: the pid-keyed recorder opens its own sink file;
            # advertise it for the shutdown drain
            telemetry.register_with(mgr)
            errq = mgr.get_queue("error")
            try:
                wrapper_fn(args, context)
            except Exception:  # noqa: BLE001 - forwarded via error queue
                errq.put(traceback.format_exc())

        # (6) execute (TFSparkNode.py:411-443)
        if job_name in ("ps", "evaluator") or background:
            logger.info(
                "starting %s:%d on executor %d in background process",
                job_name, task_index, executor_id,
            )
            fork = multiprocessing.get_context("fork")
            p = fork.Process(target=wrapper_fn_background, args=(tf_args, ctx))
            p.daemon = job_name in ("ps", "evaluator")
            p.start()
            # Reapability contract: the shutdown closure (manager KV) and the
            # engine's teardown (pid file) must both be able to find this
            # child — a crashed run must never leave an orphaned trainer
            # wedging interpreter exit on the resource-tracker pipe.
            mgr.set("bg_pid", p.pid)
            track_child_pid(p.pid)
            if job_name in ("ps", "evaluator"):
                _control_wait_loop(mgr, job_name)
        else:
            logger.info(
                "starting %s:%d on executor %d in foreground",
                job_name, task_index, executor_id,
            )
            wrapper_fn(tf_args, ctx)
            logger.info("finished %s:%d on executor %d", job_name, task_index, executor_id)

    return _mapfn


def _same_host_index(executor_id):
    """Worker index among same-host peers for chip partitioning."""
    try:
        return int(os.environ.get("TFOS_EXECUTOR_INDEX", executor_id))
    except (TypeError, ValueError):
        return executor_id


def _control_wait_loop(mgr, job_name):
    """Block a ps/evaluator slot until the driver sends None
    (TFSparkNode.py:420-438)."""
    queue = mgr.get_queue("control")
    equeue = mgr.get_queue("error")
    while True:
        while queue.empty() and equeue.empty():
            time.sleep(1)
        if not equeue.empty():
            e_str = equeue.get()
            equeue.task_done()
            raise RuntimeError(f"exception in {job_name}:\n{e_str}")
        msg = queue.get(block=True)
        queue.task_done()
        logger.info("%s got control msg: %s", job_name, msg)
        if msg is None:
            logger.info("terminating %s", job_name)
            mgr.set("state", "stopped")
            return


def _get_manager(cluster_info, host, executor_id):
    """Reattach to this executor's manager (TFSparkNode.py:119-146)."""
    for meta in cluster_info:
        if meta["executor_id"] == executor_id:
            addr = tuple(meta["addr"])
            authkey = bytes.fromhex(meta["authkey"])
            return tfmanager.connect(addr, authkey)
    raise RuntimeError(
        f"no node of this cluster on executor {executor_id} (host {host}); "
        f"cluster_info={[(m['host'], m['executor_id']) for m in cluster_info]}"
    )


def _open_feed_ring(mgr, qname, producer_nonblock=False):
    """Producer-side handle on the shared transport handshake (feed.py)."""
    from tensorflowonspark_tpu.feed import open_feed_ring

    return open_feed_ring(mgr, qname, producer=True,
                          producer_nonblock=producer_nonblock)


def _raise_if_consumer_lost(mgr, equeue):
    """Fail the feeder fast when the consumer errored or died.

    The error queue is PEEKED — get, then put back — so an engine/Spark
    retry of this feeder task still observes a persistent worker failure
    (a consuming read would make the retry hang on an empty queue until
    feed_timeout).  Heartbeat age (manager.py) distinguishes DEAD from
    SLOW: a busy trainer keeps beating, a killed one goes stale; no beat
    ever recorded means 'unknown', never 'dead'."""
    if not equeue.empty():
        e_str = equeue.get()
        equeue.task_done()
        equeue.put(e_str)
        raise RuntimeError(f"exception in worker:\n{e_str}")
    age = tfmanager.heartbeat_age(mgr)
    if age is not None and age > tfmanager.stale_after():
        raise RuntimeError(
            f"consumer appears dead: no heartbeat for {age:.0f}s "
            f"(stale after {tfmanager.stale_after():.0f}s, "
            f"TFOS_HEARTBEAT_STALE)")


def _await_consumption(mgr, waiter, feed_timeout, poll=1.0):
    """Wait for the consumer to drain what we queued, polling the error
    queue and the consumer heartbeat (parity: TFSparkNode.py:484-497).
    ``waiter()`` returns True while data is still outstanding."""
    equeue = mgr.get_queue("error")
    timeout = feed_timeout
    while waiter():
        _raise_if_consumer_lost(mgr, equeue)
        time.sleep(poll)
        timeout -= poll
        if timeout <= 0:
            raise TimeoutError("timed out waiting for consumption of partition")


def _make_chunk_encoder():
    """Per-partition chunk encoder: all-numeric row chunks go columnar
    (marker.ColumnChunk via marshal.rows_to_columns — ~10x cheaper to
    serialize, ~2x smaller on the wire than pickled row lists); chunks
    with string/object/ragged columns stay as plain row lists.

    n-D ndarray fields (images: [H, W, C] uint8) are flattened to width
    H*W*C columns — reshape VIEWS, no copy — with the original trailing
    shape carried in ``ColumnChunk.shapes`` so the consumer can slice
    dense ``[n, H, W, C]`` batches with zero per-record python work
    (``DataFeed.next_batch_columns``)."""
    if os.environ.get("TFOS_COLUMNAR_FEED", "1") == "0":
        return lambda chunk: chunk
    import numpy as np

    from tensorflowonspark_tpu.recordio import marshal

    state = {"spec": None, "off": False, "shapes": None}

    def flatten(row):
        shapes = state["shapes"]
        out = []
        for i, v in enumerate(row):
            if shapes[i] is not None:
                if not (isinstance(v, np.ndarray) and v.shape == shapes[i]):
                    raise TypeError(
                        f"field {i} shape drift: expected {shapes[i]}, "
                        f"got {getattr(v, 'shape', type(v).__name__)}")
                v = v.reshape(-1)
            out.append(v)
        return tuple(out)

    def encode(chunk):
        if state["off"]:
            return chunk
        try:
            if state["spec"] is None:
                row = chunk[0]
                if not isinstance(row, (tuple, list)):
                    raise TypeError("non-tuple row")
                shapes = tuple(
                    v.shape if isinstance(v, np.ndarray) and v.ndim > 1
                    else None
                    for v in row)
                state["shapes"] = (shapes if any(s is not None
                                                 for s in shapes) else None)
                if state["shapes"] is not None:
                    row = flatten(row)
                spec = marshal.infer_spec(row)
                if any(c == "O" for c, _ in spec):
                    raise TypeError("object column")
                state["spec"] = spec
            rows = (chunk if state["shapes"] is None
                    else [flatten(r) for r in chunk])
            return marker.ColumnChunk(
                state["spec"],
                marshal.rows_to_columns(rows, state["spec"]),
                shapes=state["shapes"],
            )
        except Exception as e:  # noqa: BLE001 - heterogeneous data: row path
            state["off"] = True
            logger.info(
                "feed: row-chunk path (columnar not applicable: %s)", e
            )
            return chunk

    return encode


def _partition_index():
    """This feed task's partition id: Spark TaskContext under real
    pyspark, else the engine-exported TFOS_PARTITION_INDEX; -1 when
    neither is known (feed-consumption accounting is then disabled)."""
    try:
        from pyspark import TaskContext

        tc = TaskContext.get()
        if tc is not None:
            return int(tc.partitionId())
    except Exception:  # noqa: BLE001 - no spark on this path
        pass
    try:
        return int(os.environ.get("TFOS_PARTITION_INDEX", "-1"))
    except (TypeError, ValueError):
        return -1


def train(cluster_info, cluster_meta, feed_timeout=600, qname="input",
          skip=None):
    """Feeder closure: push partition records as chunks over the shm ring
    (fast path) or the manager queue (parity: TFSparkNode.train :448-515).

    ``skip`` is a set of partition indices already fully consumed in a
    previous cluster incarnation (rendezvous feed ledger): a relaunched
    feed job drains those partitions without re-feeding, so auto-resumed
    training never sees the same record twice."""
    skip = frozenset(skip or ())

    def _train(iterator):
        pidx = _partition_index()
        if pidx >= 0 and pidx in skip:
            count = sum(1 for _ in iterator)
            logger.info("feeder: partition %d already consumed before "
                        "recovery, skipping %d records", pidx, count)
            telemetry.event("feed/partition_skipped", part=pidx,
                            records=count)
            return
        mgr = _get_manager(cluster_info, get_ip_address(), read_executor_id())
        telemetry.register_with(mgr)
        state = str(mgr.get("state"))
        if state in ("terminating", "stopped"):
            logger.info("feeder: state=%s, skipping/draining partition", state)
            count = sum(1 for _ in iterator)
            logger.info("feeder: discarded %d records", count)
            return
        ring = _open_feed_ring(mgr, qname)
        queue = None if ring is not None else mgr.get_queue(qname)
        equeue = mgr.get_queue("error")
        encode = _make_chunk_encoder()

        def put(chunk):
            """False once the consumer requested termination mid-feed: a
            put blocked on a full ring re-checks state each second, so a
            feeder never deadlocks against a consumer that stopped
            draining (and fails fast when the consumer errored or its
            heartbeat went stale)."""
            faults.check("feed.put", part=pidx)
            chunk = encode(chunk)
            if ring is not None:
                while True:
                    try:
                        ring.put(chunk, timeout_ms=1000)
                        return True
                    except TimeoutError:
                        if str(mgr.get("state")) == "terminating":
                            return False
                        _raise_if_consumer_lost(mgr, equeue)
            else:
                queue.put(chunk, block=True)
                return True

        total = 0
        terminated = False
        chunk = []
        chunk_records = _feed_chunk_records()
        for item in iterator:
            chunk.append(item)
            if len(chunk) >= chunk_records:
                if not put(chunk):
                    terminated = True
                    break
                total += len(chunk)
                chunk = []
        if chunk and not terminated:
            if put(chunk):
                total += len(chunk)
            else:
                terminated = True
        # a feeder that passed the entry state check before terminate()
        # set the flag may have queued its whole (small) partition without
        # any put ever blocking — re-check here so it never waits on a
        # consumer that already stopped draining
        if not terminated and str(mgr.get("state")) == "terminating":
            terminated = True
        if terminated:
            discarded = sum(1 for _ in iterator)
            logger.info("feeder: termination mid-feed, discarded %d records",
                        discarded + len(chunk))
        logger.info("feeder: queued %d records (%s path)", total,
                    "shm" if ring is not None else "manager")
        telemetry.event("feed/partition_queued", part=pidx, records=total,
                        path="shm" if ring is not None else "manager",
                        terminated=terminated)

        if ring is not None:
            if not terminated:
                # terminate()'s drain loop keeps reading while we hold the
                # producer flock, so outstanding bytes always reach zero
                _await_consumption(
                    mgr, lambda: ring.qsize_bytes() > 0, feed_timeout, poll=0.2
                )
            ring.close()
        else:
            joining = threading.Thread(target=queue.join, daemon=True)
            joining.start()
            _await_consumption(mgr, joining.is_alive, feed_timeout)

        # fully consumed, not cut short: record it in the driver's feed
        # ledger so a post-recovery relaunch of this feed job skips it.
        # Best-effort — standalone tests feed against a placeholder
        # server_addr with no rendezvous listening.
        if not terminated and pidx >= 0:
            try:
                client = rendezvous.Client(cluster_meta["server_addr"])
                client.partition_done(qname, pidx)
                client.close()
            except Exception as e:  # noqa: BLE001 - accounting only
                logger.warning(
                    "feeder: could not record partition %d consumed: %s",
                    pidx, e)

        if str(mgr.get("state")) == "terminating":
            logger.info("feeder: consumer requested termination")
            client = rendezvous.Client(cluster_meta["server_addr"])
            client.request_stop()

    return _train


def inference(cluster_info, cluster_meta, feed_timeout=600, qname="input"):
    """Inference closure: feed a partition, collect exactly as many results
    (parity: TFSparkNode.inference :518-579)."""

    def _inference(iterator):
        mgr = _get_manager(cluster_info, get_ip_address(), read_executor_id())
        telemetry.register_with(mgr)
        ring = _open_feed_ring(mgr, qname)
        queue = None if ring is not None else mgr.get_queue(qname)
        encode = _make_chunk_encoder()

        def put(item):
            if isinstance(item, list):
                item = encode(item)
            if ring is not None:
                ring.put(item)
            else:
                queue.put(item, block=True)

        count = 0
        chunk = []
        chunk_records = _feed_chunk_records()
        for item in iterator:
            chunk.append(item)
            if len(chunk) >= chunk_records:
                put(chunk)
                count += len(chunk)
                chunk = []
        if chunk:
            put(chunk)
            count += len(chunk)
        put(marker.EndPartition())

        # await consumption with error polling
        if ring is not None:
            _await_consumption(
                mgr, lambda: ring.qsize_bytes() > 0, feed_timeout, poll=0.1
            )
            ring.close()
        else:
            joining = threading.Thread(target=queue.join, daemon=True)
            joining.start()
            _await_consumption(mgr, joining.is_alive, feed_timeout, poll=0.2)
        if count == 0:
            return []  # empty partition: nothing to collect

        # collect exactly `count` results (results arrive as chunks)
        results = []
        out_q = mgr.get_queue("output")
        while len(results) < count:
            got = out_q.get(block=True)
            out_q.task_done()
            if isinstance(got, list):
                results.extend(got)
            else:
                results.append(got)
        logger.info("inference: partition yielded %d results", len(results))
        return results

    return _inference


def shutdown(cluster_info, queues, cluster_id, grace_secs=0):
    """Worker-shutdown closure (parity: TFSparkNode.shutdown :582-636)."""

    def _shutdown(iterator):
        list(iterator)
        executor_id = read_executor_id()
        mgr = _get_manager(cluster_info, get_ip_address(), executor_id)
        logger.info("shutdown: signalling end-of-feed on executor %s", executor_id)
        tb_pid = mgr.get("tb_pid")  # kill TB child (TFSparkNode.py:599-605)
        if tb_pid:
            try:
                os.kill(int(str(tb_pid)), signal.SIGKILL)
            except (OSError, ValueError):
                pass
            try:  # reap when this worker happens to be the spawning parent
                os.waitpid(int(str(tb_pid)), 0)
            except (ChildProcessError, OSError, ValueError):
                pass
            mgr.set("tb_pid", None)
        ring = _open_feed_ring(mgr, "input")
        for qname in queues:
            if qname in ("error", "control"):
                continue  # end-of-feed applies to data queues only
            try:
                if qname == "input" and ring is not None:
                    ring.put(None)
                else:
                    mgr.get_queue(qname).put(None, block=True)
            except Exception as e:  # noqa: BLE001
                logger.warning("shutdown: queue %s: %s", qname, e)
        if ring is not None:
            ring.close()
        if grace_secs:
            time.sleep(grace_secs)
        # PEEK the error queue — get and put back — so an engine/Spark task
        # retry still observes the failure (TFSparkNode.py:624-630).
        equeue = mgr.get_queue("error")
        err = None
        if not equeue.empty():
            err = equeue.get()
            equeue.put(err)
        # Reap the background trainer: it received end-of-feed above and
        # must exit on its own; a worker still alive past the bound is
        # stuck (e.g. crashed feed left it blocked on the ring) and gets
        # killed so no orphan survives the cluster.  The healthy-path
        # budget is deliberately long (feed_timeout scale): the trainer
        # may still be consuming queued batches, compiling, or writing a
        # final checkpoint, and killing working user code loses data — an
        # already-errored worker is reaped fast instead.
        bg_pid = mgr.get("bg_pid")
        if bg_pid:
            budget = (5.0 if err is not None else max(
                grace_secs, float(os.environ.get("TFOS_REAP_TIMEOUT", "600"))
            ))
            exited = reap_child(int(str(bg_pid)), timeout=budget)
            if not exited:
                logger.warning("shutdown: background worker %s did not exit "
                               "cleanly and was killed", bg_pid)
            mgr.set("bg_pid", None)
        if err is not None:
            raise RuntimeError(f"exception in worker:\n{err}")
        mgr.set("state", "stopped")

    return _shutdown


def drain_telemetry(cluster_info):
    """Executor-side telemetry drain closure: flush this process, then
    read every spool dir the node's processes advertised in the manager
    KV (telemetry.register_with) and return the raw JSONL so the driver
    can write one run directory.  Best-effort throughout — a drain
    failure must never turn a clean shutdown into an error."""

    def _drain(iterator):
        list(iterator)
        telemetry.flush()
        out = []
        try:
            executor_id = read_executor_id()
            mgr = _get_manager(cluster_info, get_ip_address(), executor_id)
            spools = mgr.telemetry_spools()
        except Exception as e:  # noqa: BLE001 - drain is best-effort
            logger.warning("telemetry drain: no manager/spools: %s", e)
            return out
        for spool in spools:
            for name, text in telemetry.read_spool(spool):
                out.append((executor_id, name, text))
        return out

    return _drain
