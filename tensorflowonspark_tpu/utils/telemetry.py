"""Cluster-wide structured telemetry: per-node event spans on one schema.

Parity target: the reference's observability is *log lines only* —
``logging.basicConfig`` at import (reference ``__init__.py:1-5``) plus
free-text records for cluster_info (``TFCluster.py:343-344``), node
registrations (``TFSparkNode.py:356``) and feed counts
(``TFSparkNode.py:497``); no metrics, no counters, no timeline
(SURVEY.md §5).  This module replaces those log lines with structured
spans so a whole federated run (reservation → rendezvous → compile →
steps → shutdown) lands on ONE timeline that
``scripts/trace_merge.py`` renders as a Perfetto-loadable Chrome trace
and a stall-attribution summary.

Design constraints (all load-bearing):

- **Zero-dep / stdlib-only** — imported by engine executors, feeder
  tasks, forked trainers and the driver; must never pull jax/numpy.
- **Opt-in via env** — enabled iff ``TFOS_TELEMETRY_DIR`` is set; when
  unset every call is a cached no-op (no files, no measurable cost).
- **Monotonic durations** — ``dur_ms`` comes from ``perf_counter``
  deltas; ``ts`` is wall-clock (``time.time``) only to *anchor* spans
  on a shared timeline across processes of one host/run.
- **Bounded ring buffer** — records buffer in a ``deque(maxlen=...)``
  between flushes, so an unwritable sink degrades to dropped telemetry
  (counted), never to unbounded memory or a crashed trainer.
- **Safe under spawn/fork** — the recorder is keyed by pid: a fork or
  spawn child lazily opens its OWN ``<node>-<pid>.jsonl`` sink, and a
  ``multiprocessing.util.Finalize`` hook (multiprocessing children skip
  ``atexit``) flushes it at child exit.

One record per line (JSONL), one schema everywhere::

    {"ts": <epoch s>, "node_id": "worker-0", "role": "worker",
     "kind": "span"|"event", "name": "train/step",
     "dur_ms": <float>|null, "attrs": {...}}

Env vars:
  ``TFOS_TELEMETRY_DIR``    master switch + driver-side sink/run dir.
  ``TFOS_TELEMETRY_SPOOL``  node-local spool dir override (node.py sets
                            it per executor; the driver drain collects
                            spools into ``<dir>/run-<id>/``).
  ``TFOS_TELEMETRY_NODE``/``TFOS_TELEMETRY_ROLE``  identity defaults,
                            inherited by forked/spawned children.
  ``TFOS_TELEMETRY_BUFFER`` ring capacity (default 4096 records).
  ``TFOS_TELEMETRY_FLUSH``  flush threshold (default 128 records).
  ``TFOS_TRACE_PARENT``     W3C-traceparent-shaped causal parent, the
                            env channel by which spawned/forked children
                            join the minting process's request trace.
  ``TFOS_FLIGHT_RING``      flight-recorder ring capacity (default 512
                            records; see obs/flight.py).
"""

from __future__ import annotations

import atexit
import collections
import json
import logging
import os
import re
import socket
import threading
import time

logger = logging.getLogger(__name__)

DIR_ENV = "TFOS_TELEMETRY_DIR"
SPOOL_ENV = "TFOS_TELEMETRY_SPOOL"
NODE_ENV = "TFOS_TELEMETRY_NODE"
ROLE_ENV = "TFOS_TELEMETRY_ROLE"
BUFFER_ENV = "TFOS_TELEMETRY_BUFFER"
FLUSH_ENV = "TFOS_TELEMETRY_FLUSH"
TRACE_ENV = "TFOS_TRACE_PARENT"
RING_ENV = "TFOS_FLIGHT_RING"

SCHEMA_KEYS = ("ts", "node_id", "role", "kind", "name", "dur_ms", "attrs")

# -- serving SLO metric names (docs/serving.md) ----------------------------
# One span per served request with queue_ms / batch_ms / device_ms /
# batch / bucket attrs; one event per load-shed rejection.  trace_merge
# summarizes them into p50/p95/p99 and shed-rate.
SERVE_REQUEST = "serve/request"
SERVE_SHED = "serve/shed"
SERVE_BATCH = "serve/replica_batch"   # replica-side device batch span
SERVE_RELOAD = "serve/reload"         # hot-reload broadcast event
DECODE_SESSION = "decode/session"     # one autoregressive decode session
DECODE_SHED = "decode/shed"           # decode admission-control rejection
ACTOR_MESSAGE = "actor/message"       # one actor envelope handled
EVAL_RUN = "eval/run"                 # one eval-sidecar evaluation
SERVE_GENERATE = "serve/generate"     # request-root span, /v1/generate
SERVE_PREDICT = "serve/predict"       # request-root span, /v1/predict
DECODE_ADMIT = "decode/admit"         # replica-side session admission
DECODE_RETIRE = "decode/retire"       # replica-side session retirement
BENCH_REQUEST = "bench/request"       # loadgen per-request root span
CLUSTER_RUN = "cluster/run"           # cluster root-trace anchor
DATA_UNIT = "data/unit"               # one exactly-once data unit served
DEPLOY_BLESS = "deploy/bless"         # checkpoint passed gate, manifest out
DEPLOY_CANARY = "deploy/canary"       # canary arm opened on a candidate
DEPLOY_PROMOTE = "deploy/promote"     # candidate promoted fleet-wide
DEPLOY_ROLLBACK = "deploy/rollback"   # candidate rejected, blessed re-pinned


# -- causal trace context (W3C-traceparent-shaped) -------------------------
# A TraceContext links spans ACROSS processes: the string form
# ``00-<32 hex trace_id>-<16 hex span_id>-01`` rides HTTP headers,
# dispatch blobs, actor envelopes and the TFOS_TRACE_PARENT env var;
# span records under an active context carry ``trace_id`` / ``span_id``
# / ``parent_id`` inside ``attrs`` (the 7-key record schema above never
# changes).  With no active context, attrs are left untouched.
_TRACEPARENT_RE = re.compile(
    r"^00-([0-9a-f]{32})-([0-9a-f]{16})-[0-9a-f]{2}$")


class TraceContext:
    """One node of a causal request tree.

    ``span_id`` names the span that new child records parent to;
    ``parent_id`` is where THIS context's own span (if any) links
    upward (None at the root).  Wire form is ``to_header()``; a parsed
    header yields a context whose ``span_id`` is the remote sender's
    span, so children recorded under it link across the process
    boundary."""

    __slots__ = ("trace_id", "span_id", "parent_id")

    def __init__(self, trace_id=None, span_id=None, parent_id=None):
        self.trace_id = trace_id or os.urandom(16).hex()
        self.span_id = span_id or os.urandom(8).hex()
        self.parent_id = parent_id

    def child(self):
        """A fresh context one level down (new span_id, parented here)."""
        return TraceContext(self.trace_id, None, self.span_id)

    def to_header(self):
        """W3C-traceparent-shaped string form for wires and env vars."""
        return f"00-{self.trace_id}-{self.span_id}-01"

    @classmethod
    def from_header(cls, header):
        """Parse a traceparent string; None on anything malformed."""
        if isinstance(header, TraceContext):
            return header
        m = _TRACEPARENT_RE.match(str(header or "").strip())
        if not m:
            return None
        return cls(m.group(1), m.group(2))

    def __repr__(self):
        return (f"TraceContext({self.trace_id[:8]}…, span={self.span_id}, "
                f"parent={self.parent_id})")


_TRACE_TLS = threading.local()
# env-channel parse cache: (raw header string, parsed ctx)
_ENV_PARENT = {"raw": None, "ctx": None}


def current():
    """The active TraceContext of this thread: the innermost activated
    /traced span, else the ``TFOS_TRACE_PARENT`` env channel (how
    spawned children inherit their parent), else None."""
    stack = getattr(_TRACE_TLS, "stack", None)
    if stack:
        return stack[-1]
    raw = os.environ.get(TRACE_ENV)
    if not raw:
        return None
    if _ENV_PARENT["raw"] != raw:
        _ENV_PARENT["ctx"] = TraceContext.from_header(raw)
        _ENV_PARENT["raw"] = raw
    return _ENV_PARENT["ctx"]


def _push(ctx):
    stack = getattr(_TRACE_TLS, "stack", None)
    if stack is None:
        stack = _TRACE_TLS.stack = []
    stack.append(ctx)


def _pop(ctx):
    stack = getattr(_TRACE_TLS, "stack", None)
    if stack and stack[-1] is ctx:
        stack.pop()


class _Activation:
    """CM scoping an existing context onto this thread (wire receive)."""

    __slots__ = ("_ctx",)

    def __init__(self, ctx):
        self._ctx = ctx

    def __enter__(self):
        if self._ctx is not None:
            _push(self._ctx)
        return self._ctx

    def __exit__(self, *exc):
        if self._ctx is not None:
            _pop(self._ctx)
        return False


def activate(ctx):
    """``with telemetry.activate(ctx_or_header):`` — make a context
    received over a wire (dispatch blob, envelope, queue dict) the
    active parent for spans/events in the body.  Accepts a
    TraceContext, a traceparent string, or None (no-op); also a no-op
    when telemetry is disabled."""
    if ctx is None or _get() is None:
        return _Activation(None)
    if not isinstance(ctx, TraceContext):
        ctx = TraceContext.from_header(ctx)
    return _Activation(ctx)


class Recorder:
    """Per-process span/event sink: bounded buffer -> one JSONL file."""

    def __init__(self, sink_dir, node_id=None, role=None):
        self.sink_dir = sink_dir
        self.pid = os.getpid()
        self.node_id = (node_id or os.environ.get(NODE_ENV)
                        or f"{socket.gethostname()}-{self.pid}")
        self.role = role or os.environ.get(ROLE_ENV) or "proc"
        self.path = os.path.join(
            sink_dir, f"{_safe(self.node_id)}-{self.pid}.jsonl")
        cap = int(os.environ.get(BUFFER_ENV, "4096"))
        self._flush_every = int(os.environ.get(FLUSH_ENV, "128"))
        self._buf = collections.deque(maxlen=max(cap, 1))
        # flight ring: the last N records, NOT drained by flush — the
        # black-box window obs/flight.py snapshots on supervision events
        self.ring = collections.deque(
            maxlen=max(int(os.environ.get(RING_ENV, "512")), 1))
        self._lock = threading.Lock()
        self._last_flush = time.monotonic()
        self._sink_warned = False
        self.dropped = 0
        # atexit covers plain interpreters; multiprocessing children
        # exit via os._exit in Process._bootstrap and run only the
        # util.Finalize registry — register with both so a spawned or
        # forked trainer's tail records always reach the file.
        atexit.register(self.flush)
        try:
            from multiprocessing import util as _mputil

            _mputil.Finalize(self, Recorder.flush, args=(self,),
                             exitpriority=100)
        except Exception:  # noqa: BLE001 - atexit alone is acceptable
            pass

    def record(self, kind, name, ts, dur_ms, attrs):
        rec = {
            "ts": ts,
            "node_id": self.node_id,
            "role": self.role,
            "kind": kind,
            "name": name,
            "dur_ms": dur_ms,
            "attrs": attrs or {},
        }
        with self._lock:
            if len(self._buf) == self._buf.maxlen:
                self.dropped += 1
            self._buf.append(rec)
            self.ring.append(rec)
            need = (len(self._buf) >= self._flush_every
                    or time.monotonic() - self._last_flush > 1.0)
        if need:
            self.flush()

    def flush(self):
        if os.getpid() != self.pid:
            # A fork child inherits the parent's atexit/Finalize entries
            # (and any buffered records): flushing here would duplicate
            # the parent's records under the parent's filename.
            return
        with self._lock:
            if not self._buf:
                return
            recs = list(self._buf)
            self._buf.clear()
            dropped, self.dropped = self.dropped, 0
            self._last_flush = time.monotonic()
        if dropped:
            recs.insert(0, {
                "ts": time.time(), "node_id": self.node_id,
                "role": self.role, "kind": "event",
                "name": "telemetry/dropped", "dur_ms": None,
                "attrs": {"count": dropped},
            })
        try:
            os.makedirs(self.sink_dir, exist_ok=True)
            data = "".join(
                json.dumps(r, default=str) + "\n" for r in recs)
            with open(self.path, "a", encoding="utf-8") as f:
                f.write(data)
        except OSError as e:
            if not self._sink_warned:  # degrade quietly, never crash
                self._sink_warned = True
                logger.warning("telemetry sink unwritable (%s): %s",
                               self.path, e)


def _safe(name):
    return "".join(c if (c.isalnum() or c in "-_.") else "_"
                   for c in str(name)) or "node"


# Cached per (pid, dir, spool, node, role): a fork/spawn child or an env
# change (tests, node_configure) transparently gets a fresh recorder.
_STATE = {"key": None, "rec": None}
_STATE_LOCK = threading.Lock()


def _get():
    key = (os.getpid(), os.environ.get(DIR_ENV),
           os.environ.get(SPOOL_ENV), os.environ.get(NODE_ENV),
           os.environ.get(ROLE_ENV))
    if _STATE["key"] == key:
        return _STATE["rec"]
    with _STATE_LOCK:
        if _STATE["key"] == key:
            return _STATE["rec"]
        old = _STATE["rec"]
        if old is not None and old.pid == os.getpid():
            old.flush()  # reconfigure in-process: don't strand records
        base = key[1]
        rec = Recorder(key[2] or base) if base else None
        _STATE["rec"] = rec
        _STATE["key"] = key
        return rec


def enabled():
    """True when telemetry is recording in this process."""
    return _get() is not None


def sink_path():
    """This process's JSONL sink path, or None when disabled."""
    rec = _get()
    return rec.path if rec is not None else None


def configure(node_id=None, role=None, spool=None):
    """Pin identity/sink via the env channel so forked and spawned
    children inherit them; returns the active recorder (or None)."""
    if node_id is not None:
        os.environ[NODE_ENV] = str(node_id)
    if role is not None:
        os.environ[ROLE_ENV] = str(role)
    if spool is not None:
        os.environ[SPOOL_ENV] = str(spool)
    return _get()


class _NullSpan:
    """Shared no-op span: the disabled path allocates nothing."""

    __slots__ = ()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False

    def add(self, **attrs):
        return self


_NULL = _NullSpan()


class Span:
    """Context manager measuring one span on the monotonic clock.

    Under an active :class:`TraceContext` the span joins the causal
    tree: it derives (or is handed) a child context, becomes the active
    parent for its body, and stamps ``trace_id``/``span_id``/
    ``parent_id`` into its attrs on exit.  With no active context the
    record is byte-identical to the pre-trace schema (attrs
    untouched)."""

    __slots__ = ("_rec", "name", "attrs", "_ts", "_t0", "_ctx")

    def __init__(self, rec, name, attrs, ctx=None):
        self._rec = rec
        self.name = name
        self.attrs = attrs
        self._ctx = ctx

    def __enter__(self):
        self._ts = time.time()
        self._t0 = time.perf_counter()
        if self._ctx is None:
            parent = current()
            if parent is not None:
                self._ctx = parent.child()
        if self._ctx is not None:
            _push(self._ctx)
        return self

    def add(self, **attrs):
        self.attrs.update(attrs)
        return self

    @property
    def ctx(self):
        """This span's TraceContext (None outside any trace)."""
        return self._ctx

    def __exit__(self, exc_type, exc, tb):
        dur_ms = (time.perf_counter() - self._t0) * 1000.0
        if self._ctx is not None:
            _pop(self._ctx)
            self.attrs.setdefault("trace_id", self._ctx.trace_id)
            self.attrs.setdefault("span_id", self._ctx.span_id)
            self.attrs.setdefault("parent_id", self._ctx.parent_id)
        if exc_type is not None:
            self.attrs.setdefault("error", repr(exc)[:200])
        self._rec.record("span", self.name, self._ts, dur_ms, self.attrs)
        return False


def span(name, **attrs):
    """``with telemetry.span("phase/name", k=v) as s: ...`` — records a
    span on exit (exceptions annotate ``attrs.error`` and propagate)."""
    rec = _get()
    if rec is None:
        return _NULL
    return Span(rec, name, attrs)


def trace_span(name, header=None, **attrs):
    """Entry-point span: like :func:`span` but ALWAYS traced — it
    continues the trace in ``header`` (traceparent string or
    TraceContext) when given, else the thread's active context, else
    mints a fresh root.  Returns the no-op span when telemetry is
    disabled (the overhead contract)."""
    rec = _get()
    if rec is None:
        return _NULL
    parent = TraceContext.from_header(header) if header else current()
    ctx = parent.child() if parent is not None else TraceContext()
    return Span(rec, name, attrs, ctx=ctx)


def event(name, **attrs):
    """Record an instant event (``dur_ms`` null).  Under an active
    trace the event is stamped as a leaf of the current span."""
    rec = _get()
    if rec is not None:
        ctx = current()
        if ctx is not None:
            attrs.setdefault("trace_id", ctx.trace_id)
            attrs.setdefault("parent_id", ctx.span_id)
        rec.record("event", name, time.time(), None, attrs)


def record_span(name, dur_s, **attrs):
    """Record an already-measured duration as a span whose start is
    back-dated by ``dur_s`` — for call sites that time themselves (the
    feed wait path, TrainMetrics.step) so telemetry and the counters
    report the SAME number."""
    rec = _get()
    if rec is not None:
        ctx = current()
        if ctx is not None:
            attrs.setdefault("trace_id", ctx.trace_id)
            attrs.setdefault("span_id", os.urandom(8).hex())
            attrs.setdefault("parent_id", ctx.span_id)
        rec.record("span", name, time.time() - dur_s, dur_s * 1000.0,
                   attrs)


def trace_root(name, export=True, **attrs):
    """Mint a root TraceContext for a long-lived scope (``cluster.run``)
    and record an instant anchor span for it so every later child's
    ``parent_id`` resolves.  ``export=True`` additionally publishes the
    context on ``TFOS_TRACE_PARENT`` so this process's later spans AND
    spawned children inherit it.  Returns the context (None when
    telemetry is disabled)."""
    rec = _get()
    if rec is None:
        return None
    ctx = TraceContext()
    attrs.setdefault("trace_id", ctx.trace_id)
    attrs.setdefault("span_id", ctx.span_id)
    attrs.setdefault("parent_id", None)
    rec.record("span", name, time.time(), 0.0, attrs)
    if export:
        os.environ[TRACE_ENV] = ctx.to_header()
    return ctx


def recent(window_s=None):
    """The flight ring: this process's last recorded spans/events (most
    recent last), optionally clipped to the trailing ``window_s``
    seconds.  Empty when telemetry is disabled."""
    rec = _get()
    if rec is None:
        return []
    with rec._lock:
        records = list(rec.ring)
    if window_s is not None:
        cutoff = time.time() - float(window_s)
        records = [r for r in records if r.get("ts", 0) >= cutoff]
    return records


def flush():
    """Flush this process's buffered records to the JSONL sink."""
    rec = _get()
    if rec is not None:
        rec.flush()


def run_dir(cluster_id):
    """The per-run collection directory under TFOS_TELEMETRY_DIR that
    the driver drain fills at shutdown, or None when disabled."""
    base = os.environ.get(DIR_ENV)
    if not base:
        return None
    return os.path.join(base, f"run-{int(cluster_id) & 0xffffffff:x}")


def register_with(mgr):
    """Advertise this process's spool dir in the executor manager's KV
    (the telemetry drain channel, manager.py) so the driver-side drain
    can collect every node file at shutdown.  Best-effort: telemetry
    must never take a worker down."""
    rec = _get()
    if rec is None:
        return
    try:
        mgr.telemetry_register(os.path.abspath(rec.sink_dir))
    except Exception as e:  # noqa: BLE001 - drain is best-effort
        logger.debug("telemetry spool registration failed: %s", e)


def read_spool(spool_dir):
    """[(filename, jsonl_text), ...] for every record file in a spool —
    the executor-side half of the drain (see node.drain_telemetry).

    Hardened against SIGKILLed writers: a process killed mid-``write``
    leaves a truncated (or garbage) trailing line; such lines are
    dropped and counted (one warning per file) instead of poisoning the
    merged run directory — and this function never raises, because the
    drain runs on live executors whose telemetry must not take them
    down."""
    out = []
    try:
        names = sorted(os.listdir(spool_dir))
    except OSError:
        return out
    for name in names:
        if not name.endswith(".jsonl"):
            continue
        try:
            # errors="replace": a record cut inside a multi-byte UTF-8
            # sequence must not abort the whole file
            with open(os.path.join(spool_dir, name),
                      encoding="utf-8", errors="replace") as f:
                raw = f.read()
        except OSError as e:
            logger.warning("telemetry drain: unreadable %s: %s", name, e)
            continue
        kept, skipped = [], 0
        for line in raw.splitlines():
            if not line.strip():
                continue
            try:
                json.loads(line)
            except ValueError:
                skipped += 1
                continue
            kept.append(line)
        if skipped:
            logger.warning(
                "telemetry drain: skipped %d truncated/corrupt line(s) "
                "in %s", skipped, name)
        if kept:
            out.append((name, "\n".join(kept) + "\n"))
    return out
