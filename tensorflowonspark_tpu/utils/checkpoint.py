"""Checkpoint / export utilities.

Parity intent: the reference delegates checkpointing to TF and contributes
the *contract* — model_dir plumbing, chief-only SavedModel export with
non-chief no-op (reference compat.py:10-17), grace-period export after
feeding stops.  Here:

- ``save_checkpoint``/``load_checkpoint``: a dependency-free npz format
  for plain pytrees (always available, used by CI tests);
- ``export_model``: the chief-only export gate;
- ``async_checkpointer``: orbax-backed async checkpointing for real runs
  (GCS-capable), import-gated;
- blessing manifests (``bless_checkpoint``/``verify_manifest``/
  ``tombstone_checkpoint``): the deployment loop's integrity contract
  (workloads/deploy_loop.py, docs/deployment.md).  No reference
  counterpart — the reference hands checkpoints to TF Serving unsigned
  and unverified (SURVEY §1 L7); here a promoted checkpoint carries
  per-file sha256 digests + the eval score that gated it, and restore
  paths skip tombstoned/corrupt steps instead of crashing on them.
"""

from __future__ import annotations

import hashlib
import io
import json
import logging
import os
import time

import numpy as np

from tensorflowonspark_tpu.recordio import fs as _fs
from tensorflowonspark_tpu.utils import faults, metrics_registry, telemetry

logger = logging.getLogger(__name__)


def _flatten(tree, prefix=""):
    out = {}
    if isinstance(tree, dict):
        for k in sorted(tree):
            out.update(_flatten(tree[k], f"{prefix}{k}/"))
    else:
        out[prefix[:-1]] = np.asarray(tree)
    return out


def _unflatten(flat):
    tree = {}
    for key, value in flat.items():
        parts = key.split("/")
        node = tree
        for p in parts[:-1]:
            node = node.setdefault(p, {})
        node[parts[-1]] = value
    return tree


def save_checkpoint(ckpt_dir, params, step, keep=3):
    """Write step-stamped npz checkpoint to any filesystem (local,
    gs://, hdfs://, ... via fsspec); prune old ones."""
    t0 = time.perf_counter()
    with telemetry.span("checkpoint/save", step=step):
        faults.check("checkpoint.save", step=step)
        _fs.makedirs(ckpt_dir)
        flat = _flatten(_to_host(params))
        path = _fs.join(ckpt_dir, f"ckpt-{step:08d}.npz")
        if _fs.is_local(ckpt_dir):
            lp = _fs.local_path(path)
            # pid-unique tmp: concurrent writers (several workers sharing
            # one filesystem) must not clobber each other's in-flight file
            tmp = f"{lp}.{os.getpid()}.tmp"
            with open(tmp, "wb") as f:
                np.savez(f, **flat)
            os.replace(tmp, lp)  # atomic publish
        else:
            buf = io.BytesIO()  # object stores publish atomically on PUT
            np.savez(buf, **flat)
            _fs.write_bytes(path, buf.getvalue())
        logger.info("saved checkpoint %s", path)
        ckpts = sorted(
            p for p in _fs.listdir(ckpt_dir)
            if p.startswith("ckpt-") and p.endswith(".npz")
        )
        for old in ckpts[:-keep]:
            _fs.remove(_fs.join(ckpt_dir, old))
        metrics_registry.inc("tfos_checkpoint_saves_total")
        metrics_registry.observe("tfos_checkpoint_save_ms",
                                 (time.perf_counter() - t0) * 1000.0)
        return path


def latest_checkpoint(ckpt_dir):
    """Path of the newest *restorable* npz checkpoint, or None.

    Integrity-hardened (deploy-loop satellite): steps that are
    tombstoned, fail their blessing manifest, or are visibly truncated
    are skipped with a warning and the previous step wins — a torn
    write must cost one checkpoint interval, not the whole resume."""
    for step in sorted(_steps_by_format(ckpt_dir)["npz"], reverse=True):
        ok, reason = _restorable(ckpt_dir, step, "npz")
        if ok:
            return _fs.join(ckpt_dir, f"ckpt-{step:08d}.npz")
        logger.warning("skipping checkpoint step %d: %s", step, reason)
    return None


def load_checkpoint(path):
    t0 = time.perf_counter()
    with telemetry.span("checkpoint/restore", path=os.path.basename(path)):
        with _fs.open_file(path, "rb") as f, np.load(f) as z:
            out = _unflatten({k: z[k] for k in z.files})
        metrics_registry.inc("tfos_checkpoint_restores_total")
        metrics_registry.observe("tfos_checkpoint_restore_ms",
                                 (time.perf_counter() - t0) * 1000.0)
        return out


def export_model(export_dir, params, ctx=None, metadata=None):
    """Chief-only model export (parity: reference compat.py:10-17 —
    non-chief workers write nothing instead of a dummy dir)."""
    if ctx is not None and not is_chief(ctx):
        logger.info("export_model: not chief (%s:%s), skipping",
                    ctx.job_name, ctx.task_index)
        return None
    with telemetry.span("checkpoint/export"):
        _fs.makedirs(export_dir)
        flat = _flatten(_to_host(params))
        buf = io.BytesIO()
        np.savez(buf, **flat)
        _fs.write_bytes(_fs.join(export_dir, "params.npz"), buf.getvalue())
        meta = {"format": "tfos-tpu-export-v1"}
        meta.update(metadata or {})
        _fs.write_bytes(_fs.join(export_dir, "export.json"),
                        json.dumps(meta).encode())
        logger.info("exported model to %s", export_dir)
        return export_dir


def load_exported(export_dir):
    with _fs.open_file(_fs.join(export_dir, "params.npz"), "rb") as f, \
            np.load(f) as z:
        params = _unflatten({k: z[k] for k in z.files})
    return params, load_export_meta(export_dir)


def load_export_meta(export_dir):
    """Export metadata alone, no params read: the elastic adopt path
    (serving/elastic.py) resolves the predict symbol from it while the
    params arrive live from a surviving replica."""
    return json.loads(_fs.read_bytes(_fs.join(export_dir, "export.json")))


def is_chief(ctx):
    """process 0 duties: chief/master role, else worker:0
    (reference ctx.job_name=='chief' convention)."""
    if ctx.job_name in ("chief", "master"):
        return True
    has_chief = any(j in ctx.cluster_spec for j in ("chief", "master"))
    return not has_chief and ctx.job_name == "worker" and ctx.task_index == 0


def _to_host(params):
    import jax

    return jax.tree_util.tree_map(lambda x: np.asarray(x), params)


def pack_pytree(tree):
    """Arbitrary pytree (optax states, namedtuples, ...) -> flat
    {index: ndarray} dict storable by save_checkpoint (npz holds flat
    arrays; the structure is re-imposed by unpack_pytree at load)."""
    import jax

    return {
        f"{i:05d}": np.asarray(x)
        for i, x in enumerate(jax.tree_util.tree_leaves(tree))
    }


def unpack_pytree(flat, like):
    """Rebuild a pytree with the structure of ``like`` from pack_pytree
    output (leaf order is jax's canonical tree order)."""
    import jax

    leaves = [flat[k] for k in sorted(flat)]
    return jax.tree_util.tree_unflatten(
        jax.tree_util.tree_structure(like), leaves
    )


def step_of(ckpt_path):
    """Step number encoded in a ``ckpt-<step>.npz`` path."""
    name = os.path.basename(ckpt_path)
    return int(name[len("ckpt-"):-len(".npz")])


# --------------------------------------------------------------------------
# Blessing manifests (deployment-loop integrity contract).
#
# A manifest is one JSON file ``bless-<step>.json`` next to the checkpoint
# it covers: per-file sha256 + byte count, the step, and the eval score
# that gated promotion.  ``verify_manifest`` re-digests the files; a
# ``tombstone`` entry quarantines a checkpoint that regressed in canary
# (workloads/deploy_loop.py rollback path) so no restore path — trainer
# resume, serving reload, elastic adopt — ever picks it again.

MANIFEST_FORMAT = "tfos-bless-v1"


def manifest_path(ckpt_dir, step):
    return _fs.join(ckpt_dir, f"bless-{step:08d}.json")


def _step_files(ckpt_dir, step):
    """Relative paths of every file making up checkpoint ``step``
    (the npz file, or the orbax digit-dir walked recursively)."""
    names = []
    npz = f"ckpt-{step:08d}.npz"
    if _fs.exists(_fs.join(ckpt_dir, npz)):
        names.append(npz)
    odir = _fs.join(ckpt_dir, str(step))
    if _fs.isdir(odir):
        if _fs.is_local(odir):
            root = _fs.local_path(odir)
            for dirpath, _dirs, files in os.walk(root):
                rel = os.path.relpath(dirpath, _fs.local_path(ckpt_dir))
                names.extend(os.path.join(rel, f) for f in sorted(files))
        else:
            names.extend(f"{step}/{n}" for n in sorted(_fs.listdir(odir))
                         if not n.endswith("/"))
    return names


def _digest(path):
    """(sha256-hex, byte count) of one checkpoint file, streamed."""
    h = hashlib.sha256()
    n = 0
    with _fs.open_file(path, "rb") as f:
        while True:
            chunk = f.read(1 << 20)
            if not chunk:
                break
            h.update(chunk)
            n += len(chunk)
    return h.hexdigest(), n


def _write_manifest(ckpt_dir, step, manifest):
    blob = json.dumps(manifest, sort_keys=True).encode()
    path = manifest_path(ckpt_dir, step)
    if _fs.is_local(ckpt_dir):
        lp = _fs.local_path(path)
        tmp = f"{lp}.{os.getpid()}.tmp"
        with open(tmp, "wb") as f:
            f.write(blob)
        os.replace(tmp, lp)  # atomic publish, same as save_checkpoint
    else:
        _fs.write_bytes(path, blob)
    return path


def bless_checkpoint(ckpt_dir, step, score=None, eval_metrics=None):
    """Write the integrity manifest that marks ``step`` *blessed*.

    Called by the promotion controller after the eval gate passes:
    digests every file of the checkpoint so later restores can prove
    the bytes they read are the bytes that were evaluated.  Returns the
    manifest path.  Raises ``FileNotFoundError`` when the step has no
    files — blessing nothing must fail loudly."""
    files = _step_files(ckpt_dir, step)
    if not files:
        raise FileNotFoundError(
            f"bless_checkpoint: no checkpoint files for step {step} "
            f"in {ckpt_dir}")
    manifest = {
        "format": MANIFEST_FORMAT,
        "step": int(step),
        "score": None if score is None else float(score),
        "eval": dict(eval_metrics or {}),
        "files": {},
        "blessed_ts": time.time(),
        "tombstone": None,
    }
    for rel in files:
        digest, nbytes = _digest(_fs.join(ckpt_dir, rel))
        manifest["files"][rel.replace(os.sep, "/")] = {
            "sha256": digest, "bytes": nbytes}
    path = _write_manifest(ckpt_dir, step, manifest)
    telemetry.event(telemetry.DEPLOY_BLESS, step=int(step),
                    score=manifest["score"], files=len(files))
    metrics_registry.set_gauge("tfos_deploy_blessed_step", int(step))
    logger.info("blessed checkpoint step %d (%d files) -> %s",
                step, len(files), path)
    return path


def read_manifest(ckpt_dir, step):
    """Parsed manifest dict for ``step``, or None (absent/unparseable)."""
    path = manifest_path(ckpt_dir, step)
    if not _fs.exists(path):
        return None
    try:
        manifest = json.loads(_fs.read_bytes(path))
    except (OSError, ValueError) as e:
        logger.warning("unreadable manifest %s: %s", path, e)
        return None
    return manifest if isinstance(manifest, dict) else None


def verify_manifest(ckpt_dir, step):
    """(ok, reason) for the blessing manifest of ``step``.

    ``(False, "unblessed")`` when no manifest exists — the caller
    decides whether blessing is required (serving reload) or optional
    (trainer resume, see :func:`restore_any`)."""
    manifest = read_manifest(ckpt_dir, step)
    if manifest is None:
        return False, "unblessed"
    if manifest.get("tombstone"):
        reason = (manifest["tombstone"] or {}).get("reason", "")
        return False, f"tombstoned ({reason})"
    files = manifest.get("files") or {}
    if not files:
        return False, "empty manifest"
    for rel, info in sorted(files.items()):
        path = _fs.join(ckpt_dir, rel)
        if not _fs.exists(path):
            return False, f"missing file {rel}"
        try:
            digest, nbytes = _digest(path)
        except OSError as e:
            return False, f"unreadable file {rel}: {e}"
        if nbytes != info.get("bytes"):
            return False, (f"size mismatch {rel}: "
                           f"{nbytes} != {info.get('bytes')}")
        if digest != info.get("sha256"):
            return False, f"digest mismatch {rel}"
    return True, "ok"


def tombstone_checkpoint(ckpt_dir, step, reason):
    """Quarantine ``step``: mark its manifest (created if absent) with a
    tombstone so every restore path skips it.  The rollback half of the
    deployment loop — a checkpoint that regressed in canary must never
    be served, resumed from, or adopted by a regrown replica again."""
    manifest = read_manifest(ckpt_dir, step) or {
        "format": MANIFEST_FORMAT, "step": int(step), "score": None,
        "eval": {}, "files": {}, "blessed_ts": None,
    }
    manifest["tombstone"] = {"reason": str(reason), "ts": time.time()}
    path = _write_manifest(ckpt_dir, step, manifest)
    metrics_registry.inc("tfos_deploy_tombstones_total")
    logger.warning("tombstoned checkpoint step %d: %s", step, reason)
    return path


def blessed_steps(ckpt_dir):
    """Sorted steps with a live (non-tombstoned) blessing manifest."""
    if not _fs.isdir(ckpt_dir):
        return []
    steps = []
    for name in _fs.listdir(ckpt_dir):
        name = name.rstrip("/")
        if not (name.startswith("bless-") and name.endswith(".json")):
            continue
        try:
            step = int(name[len("bless-"):-len(".json")])
        except ValueError:
            continue
        manifest = read_manifest(ckpt_dir, step)
        if manifest is not None and not manifest.get("tombstone"):
            steps.append(step)
    return sorted(steps)


def latest_blessed(ckpt_dir):
    """(step, path) of the newest blessed checkpoint whose manifest
    verifies, or (None, None).  The rollback target resolver."""
    for step in sorted(blessed_steps(ckpt_dir), reverse=True):
        ok, reason = verify_manifest(ckpt_dir, step)
        if not ok:
            logger.warning("blessed step %d fails verify: %s", step, reason)
            continue
        npz = _fs.join(ckpt_dir, f"ckpt-{step:08d}.npz")
        if _fs.exists(npz):
            return step, npz
        return step, _fs.join(ckpt_dir, str(step))
    return None, None


def _npz_intact(path):
    """Cheap truncation check: an npz is a zip, and truncation destroys
    the central directory at the tail.  Local paths only (remote reads
    would defeat 'cheap'); non-local returns True and the load attempt
    is the arbiter."""
    if not _fs.is_local(path):
        return True
    import zipfile

    try:
        with zipfile.ZipFile(_fs.local_path(path)) as z:
            z.namelist()
        return True
    except Exception:  # noqa: BLE001 - any unzip failure means torn
        return False


def _restorable(ckpt_dir, step, fmt, blessed_only=False):
    """(ok, reason): should a restore path attempt ``step``?

    Manifest-present steps must verify (tombstones and digest drift are
    hard skips); manifest-absent steps pass unless ``blessed_only``
    (serving reloads demand blessing, trainer resume does not).  npz
    steps additionally get the cheap truncation probe."""
    manifest = read_manifest(ckpt_dir, step)
    if manifest is not None:
        ok, reason = verify_manifest(ckpt_dir, step)
        if not ok:
            return False, reason
    elif blessed_only:
        return False, "unblessed"
    if fmt == "npz":
        path = _fs.join(ckpt_dir, f"ckpt-{step:08d}.npz")
        if not _fs.exists(path):
            return False, "missing npz"
        if not _npz_intact(path):
            return False, "truncated npz"
    return True, "ok"


def restore_step(ckpt_dir, step):
    """Params tree of checkpoint ``step`` exactly, whichever format holds
    it.  The pinned-reload path: canary replicas load the candidate,
    rollback re-pins the blessed step (serving/replicas.py
    ``_maybe_reload``)."""
    npz = _fs.join(ckpt_dir, f"ckpt-{step:08d}.npz")
    if _fs.exists(npz):
        return load_checkpoint(npz)
    if _fs.isdir(_fs.join(ckpt_dir, str(step))):
        ckpt = AsyncCheckpointer(ckpt_dir)
        try:
            return ckpt.restore_at(step)
        finally:
            ckpt.close()
    raise FileNotFoundError(
        f"restore_step: no checkpoint for step {step} in {ckpt_dir}")


def restore_latest(ckpt_dir):
    """(params, step) from the newest restorable checkpoint, or (None, 0).

    The resume half of the recovery contract (SURVEY.md §5: recovery is
    "restart job from checkpoint"): training mains call this at startup
    and begin from the returned step.  Hardened like
    :func:`latest_checkpoint`: a torn/tombstoned newest step falls back
    to the previous one with a warning.
    """
    for step in sorted(_steps_by_format(ckpt_dir)["npz"], reverse=True):
        ok, reason = _restorable(ckpt_dir, step, "npz")
        if not ok:
            logger.warning("skipping checkpoint step %d: %s", step, reason)
            continue
        path = _fs.join(ckpt_dir, f"ckpt-{step:08d}.npz")
        try:
            tree = load_checkpoint(path)
        except Exception as e:  # noqa: BLE001 - torn file past the probe
            logger.warning("checkpoint %s unreadable: %s", path, e)
            continue
        logger.info("resuming from %s", path)
        return tree, step
    return None, 0


def _steps_by_format(ckpt_dir):
    """{'npz': [steps...], 'orbax': [steps...]} found in ``ckpt_dir``.

    npz checkpoints are ``ckpt-<step>.npz`` files; orbax CheckpointManager
    step dirs are all-digit directory names.  Listing is format-blind so
    auto-resume works whichever writer the dead incarnation used."""
    out = {"npz": [], "orbax": []}
    if not _fs.isdir(ckpt_dir):
        return out
    for name in _fs.listdir(ckpt_dir):
        name = name.rstrip("/")
        if name.startswith("ckpt-") and name.endswith(".npz"):
            try:
                out["npz"].append(step_of(name))
            except ValueError:
                pass
        elif name.isdigit():
            out["orbax"].append(int(name))
    return out


def latest_step(ckpt_dir):
    """Newest checkpoint step in ``ckpt_dir`` across BOTH formats (npz
    and orbax), or None when the dir is absent/empty."""
    steps = _steps_by_format(ckpt_dir)
    every = steps["npz"] + steps["orbax"]
    return max(every) if every else None


def latest(ckpt_dir):
    """(step, path) of the newest checkpoint across BOTH formats, or
    (None, None).  The serving hot-reload watcher
    (serving/replicas.ReplicaPool) polls this cheaply — it is a listing,
    never a restore; ``restore_any`` does the actual load."""
    steps = _steps_by_format(ckpt_dir)
    best_npz = max(steps["npz"]) if steps["npz"] else -1
    best_orbax = max(steps["orbax"]) if steps["orbax"] else -1
    if best_orbax < 0 and best_npz < 0:
        return None, None
    if best_orbax >= best_npz:
        return best_orbax, _fs.join(ckpt_dir, str(best_orbax))
    return best_npz, _fs.join(ckpt_dir, f"ckpt-{best_npz:08d}.npz")


def restore_any(ckpt_dir, target_shardings=None, blessed_only=False):
    """(tree, step) from the newest restorable checkpoint regardless of
    format, or (None, 0).  The auto-resume entry point (``TFNodeContext
    .restore_latest``): a relaunched node must continue from whatever its
    dead predecessor last published, whether it saved via
    ``save_checkpoint`` (npz) or :class:`AsyncCheckpointer` (orbax).

    Candidates are tried newest-first; steps that are tombstoned, fail
    their blessing manifest, are truncated, or raise on load are skipped
    with a warning and the previous step is tried (deploy-loop
    satellite: a bad newest checkpoint costs one interval, not the
    resume).  ``blessed_only=True`` additionally requires a verified
    blessing manifest — the serving-reload contract (only promoted
    checkpoints may serve traffic).

    Without ``target_shardings`` leaves restore as host numpy with NO
    placement contract — fine for single-device resumes, wrong for a
    mesh.  ``target_shardings`` makes placement explicit (the reshard
    step of elastic recovery, docs/elastic.md): a pytree of ``Sharding``
    matching the restored tree, or a callable ``tree -> shardings``
    derived from the restored structure (e.g. ``lambda t:
    fsdp_sharding(mesh, t)``).  The checkpoint may have been written
    under a DIFFERENT mesh shape: restore is host-side either way, so
    re-placement works across topologies (``elastic/reshard.py``)."""
    steps = _steps_by_format(ckpt_dir)
    # newest first; orbax wins a step tie (matches the historical
    # best_orbax >= best_npz preference)
    cands = sorted(
        [(s, "npz") for s in steps["npz"]]
        + [(s, "orbax") for s in steps["orbax"]],
        key=lambda c: (c[0], c[1] == "orbax"), reverse=True)
    tree, step = None, 0
    for s, fmt in cands:
        ok, reason = _restorable(ckpt_dir, s, fmt, blessed_only=blessed_only)
        if not ok:
            logger.warning("skipping checkpoint step %d (%s): %s",
                           s, fmt, reason)
            continue
        try:
            if fmt == "npz":
                tree = load_checkpoint(
                    _fs.join(ckpt_dir, f"ckpt-{s:08d}.npz"))
            else:
                ckpt = AsyncCheckpointer(ckpt_dir)
                try:
                    tree = ckpt.restore_at(s)
                finally:
                    ckpt.close()
            step = s
            break
        except Exception as e:  # noqa: BLE001 - torn past the probe
            logger.warning("checkpoint step %d (%s) unreadable: %s",
                           s, fmt, e)
            tree = None
    if tree is None:
        return None, 0
    if target_shardings is not None:
        # function import: the elastic package re-exports reshard() the
        # function over the reshard module attribute
        from tensorflowonspark_tpu.elastic.reshard import reshard

        tree = reshard(tree, target_shardings)
    return tree, step


class AsyncCheckpointer:
    """Orbax-backed async checkpointing (GCS-capable) behind the same
    save/restore contract as the npz functions: device-to-host copy and
    serialization overlap training instead of blocking the step loop.

    Usage::

        ckpt = AsyncCheckpointer(model_dir)
        params, start = ckpt.restore_latest()
        for step in range(start, steps):
            ...
            if step % save_every == 0:
                ckpt.save(step, params)   # returns immediately
        ckpt.close()                      # waits for in-flight saves
    """

    def __init__(self, ckpt_dir, keep=3):
        import orbax.checkpoint as ocp

        # URLs (gs://...) go to orbax/tensorstore verbatim; only plain
        # local paths are absolutized (os.path.abspath would mangle a URL)
        if _fs.is_local(ckpt_dir):
            ckpt_dir = os.path.abspath(_fs.local_path(ckpt_dir))
        self._ocp = ocp
        self._mngr = ocp.CheckpointManager(
            ckpt_dir,
            options=ocp.CheckpointManagerOptions(
                max_to_keep=keep, enable_async_checkpointing=True
            ),
        )

    def save(self, step, tree):
        """Queue an async save of ``tree`` at ``step`` (non-blocking)."""
        import jax

        faults.check("checkpoint.save", step=step)

        # orbax's StandardSave rejects numpy scalar leaves (np.float32);
        # promote them to 0-d arrays, which round-trip identically
        tree = jax.tree.map(
            lambda x: np.asarray(x) if isinstance(x, np.generic) else x,
            tree)
        self._mngr.save(step, args=self._ocp.args.StandardSave(tree))

    def latest_step(self):
        return self._mngr.latest_step()

    def restore_latest(self):
        """(tree, next_step) — (None, 0) when no checkpoint exists."""
        step = self._mngr.latest_step()
        if step is None:
            return None, 0
        # explicit StandardRestore: a fresh manager over an existing dir
        # has no registered handler yet and raises KeyError without it
        return self._mngr.restore(
            step, args=self._ocp.args.StandardRestore()), step

    def restore_at(self, step):
        """Tree of one specific step (the pinned-reload/rollback path)."""
        return self._mngr.restore(
            step, args=self._ocp.args.StandardRestore())

    def wait(self):
        self._mngr.wait_until_finished()

    def close(self):
        self._mngr.wait_until_finished()
        self._mngr.close()


def async_checkpointer(ckpt_dir, keep=3):
    """Back-compat constructor for :class:`AsyncCheckpointer`."""
    return AsyncCheckpointer(ckpt_dir, keep=keep)
