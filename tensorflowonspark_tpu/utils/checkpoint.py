"""Checkpoint / export utilities.

Parity intent: the reference delegates checkpointing to TF and contributes
the *contract* — model_dir plumbing, chief-only SavedModel export with
non-chief no-op (reference compat.py:10-17), grace-period export after
feeding stops.  Here:

- ``save_checkpoint``/``load_checkpoint``: a dependency-free npz format
  for plain pytrees (always available, used by CI tests);
- ``export_model``: the chief-only export gate;
- ``async_checkpointer``: orbax-backed async checkpointing for real runs
  (GCS-capable), import-gated.
"""

from __future__ import annotations

import io
import json
import logging
import os
import time

import numpy as np

from tensorflowonspark_tpu.recordio import fs as _fs
from tensorflowonspark_tpu.utils import faults, metrics_registry, telemetry

logger = logging.getLogger(__name__)


def _flatten(tree, prefix=""):
    out = {}
    if isinstance(tree, dict):
        for k in sorted(tree):
            out.update(_flatten(tree[k], f"{prefix}{k}/"))
    else:
        out[prefix[:-1]] = np.asarray(tree)
    return out


def _unflatten(flat):
    tree = {}
    for key, value in flat.items():
        parts = key.split("/")
        node = tree
        for p in parts[:-1]:
            node = node.setdefault(p, {})
        node[parts[-1]] = value
    return tree


def save_checkpoint(ckpt_dir, params, step, keep=3):
    """Write step-stamped npz checkpoint to any filesystem (local,
    gs://, hdfs://, ... via fsspec); prune old ones."""
    t0 = time.perf_counter()
    with telemetry.span("checkpoint/save", step=step):
        faults.check("checkpoint.save", step=step)
        _fs.makedirs(ckpt_dir)
        flat = _flatten(_to_host(params))
        path = _fs.join(ckpt_dir, f"ckpt-{step:08d}.npz")
        if _fs.is_local(ckpt_dir):
            lp = _fs.local_path(path)
            # pid-unique tmp: concurrent writers (several workers sharing
            # one filesystem) must not clobber each other's in-flight file
            tmp = f"{lp}.{os.getpid()}.tmp"
            with open(tmp, "wb") as f:
                np.savez(f, **flat)
            os.replace(tmp, lp)  # atomic publish
        else:
            buf = io.BytesIO()  # object stores publish atomically on PUT
            np.savez(buf, **flat)
            _fs.write_bytes(path, buf.getvalue())
        logger.info("saved checkpoint %s", path)
        ckpts = sorted(
            p for p in _fs.listdir(ckpt_dir)
            if p.startswith("ckpt-") and p.endswith(".npz")
        )
        for old in ckpts[:-keep]:
            _fs.remove(_fs.join(ckpt_dir, old))
        metrics_registry.inc("tfos_checkpoint_saves_total")
        metrics_registry.observe("tfos_checkpoint_save_ms",
                                 (time.perf_counter() - t0) * 1000.0)
        return path


def latest_checkpoint(ckpt_dir):
    if not _fs.isdir(ckpt_dir):
        return None
    ckpts = sorted(
        p for p in _fs.listdir(ckpt_dir)
        if p.startswith("ckpt-") and p.endswith(".npz")
    )
    return _fs.join(ckpt_dir, ckpts[-1]) if ckpts else None


def load_checkpoint(path):
    t0 = time.perf_counter()
    with telemetry.span("checkpoint/restore", path=os.path.basename(path)):
        with _fs.open_file(path, "rb") as f, np.load(f) as z:
            out = _unflatten({k: z[k] for k in z.files})
        metrics_registry.inc("tfos_checkpoint_restores_total")
        metrics_registry.observe("tfos_checkpoint_restore_ms",
                                 (time.perf_counter() - t0) * 1000.0)
        return out


def export_model(export_dir, params, ctx=None, metadata=None):
    """Chief-only model export (parity: reference compat.py:10-17 —
    non-chief workers write nothing instead of a dummy dir)."""
    if ctx is not None and not is_chief(ctx):
        logger.info("export_model: not chief (%s:%s), skipping",
                    ctx.job_name, ctx.task_index)
        return None
    with telemetry.span("checkpoint/export"):
        _fs.makedirs(export_dir)
        flat = _flatten(_to_host(params))
        buf = io.BytesIO()
        np.savez(buf, **flat)
        _fs.write_bytes(_fs.join(export_dir, "params.npz"), buf.getvalue())
        meta = {"format": "tfos-tpu-export-v1"}
        meta.update(metadata or {})
        _fs.write_bytes(_fs.join(export_dir, "export.json"),
                        json.dumps(meta).encode())
        logger.info("exported model to %s", export_dir)
        return export_dir


def load_exported(export_dir):
    with _fs.open_file(_fs.join(export_dir, "params.npz"), "rb") as f, \
            np.load(f) as z:
        params = _unflatten({k: z[k] for k in z.files})
    return params, load_export_meta(export_dir)


def load_export_meta(export_dir):
    """Export metadata alone, no params read: the elastic adopt path
    (serving/elastic.py) resolves the predict symbol from it while the
    params arrive live from a surviving replica."""
    return json.loads(_fs.read_bytes(_fs.join(export_dir, "export.json")))


def is_chief(ctx):
    """process 0 duties: chief/master role, else worker:0
    (reference ctx.job_name=='chief' convention)."""
    if ctx.job_name in ("chief", "master"):
        return True
    has_chief = any(j in ctx.cluster_spec for j in ("chief", "master"))
    return not has_chief and ctx.job_name == "worker" and ctx.task_index == 0


def _to_host(params):
    import jax

    return jax.tree_util.tree_map(lambda x: np.asarray(x), params)


def pack_pytree(tree):
    """Arbitrary pytree (optax states, namedtuples, ...) -> flat
    {index: ndarray} dict storable by save_checkpoint (npz holds flat
    arrays; the structure is re-imposed by unpack_pytree at load)."""
    import jax

    return {
        f"{i:05d}": np.asarray(x)
        for i, x in enumerate(jax.tree_util.tree_leaves(tree))
    }


def unpack_pytree(flat, like):
    """Rebuild a pytree with the structure of ``like`` from pack_pytree
    output (leaf order is jax's canonical tree order)."""
    import jax

    leaves = [flat[k] for k in sorted(flat)]
    return jax.tree_util.tree_unflatten(
        jax.tree_util.tree_structure(like), leaves
    )


def step_of(ckpt_path):
    """Step number encoded in a ``ckpt-<step>.npz`` path."""
    name = os.path.basename(ckpt_path)
    return int(name[len("ckpt-"):-len(".npz")])


def restore_latest(ckpt_dir):
    """(params, step) from the newest checkpoint, or (None, 0).

    The resume half of the recovery contract (SURVEY.md §5: recovery is
    "restart job from checkpoint"): training mains call this at startup
    and begin from the returned step.
    """
    path = latest_checkpoint(ckpt_dir)
    if path is None:
        return None, 0
    logger.info("resuming from %s", path)
    return load_checkpoint(path), step_of(path)


def _steps_by_format(ckpt_dir):
    """{'npz': [steps...], 'orbax': [steps...]} found in ``ckpt_dir``.

    npz checkpoints are ``ckpt-<step>.npz`` files; orbax CheckpointManager
    step dirs are all-digit directory names.  Listing is format-blind so
    auto-resume works whichever writer the dead incarnation used."""
    out = {"npz": [], "orbax": []}
    if not _fs.isdir(ckpt_dir):
        return out
    for name in _fs.listdir(ckpt_dir):
        name = name.rstrip("/")
        if name.startswith("ckpt-") and name.endswith(".npz"):
            try:
                out["npz"].append(step_of(name))
            except ValueError:
                pass
        elif name.isdigit():
            out["orbax"].append(int(name))
    return out


def latest_step(ckpt_dir):
    """Newest checkpoint step in ``ckpt_dir`` across BOTH formats (npz
    and orbax), or None when the dir is absent/empty."""
    steps = _steps_by_format(ckpt_dir)
    every = steps["npz"] + steps["orbax"]
    return max(every) if every else None


def latest(ckpt_dir):
    """(step, path) of the newest checkpoint across BOTH formats, or
    (None, None).  The serving hot-reload watcher
    (serving/replicas.ReplicaPool) polls this cheaply — it is a listing,
    never a restore; ``restore_any`` does the actual load."""
    steps = _steps_by_format(ckpt_dir)
    best_npz = max(steps["npz"]) if steps["npz"] else -1
    best_orbax = max(steps["orbax"]) if steps["orbax"] else -1
    if best_orbax < 0 and best_npz < 0:
        return None, None
    if best_orbax >= best_npz:
        return best_orbax, _fs.join(ckpt_dir, str(best_orbax))
    return best_npz, _fs.join(ckpt_dir, f"ckpt-{best_npz:08d}.npz")


def restore_any(ckpt_dir, target_shardings=None):
    """(tree, step) from the newest checkpoint regardless of format, or
    (None, 0).  The auto-resume entry point (``TFNodeContext
    .restore_latest``): a relaunched node must continue from whatever its
    dead predecessor last published, whether it saved via
    ``save_checkpoint`` (npz) or :class:`AsyncCheckpointer` (orbax).

    Without ``target_shardings`` leaves restore as host numpy with NO
    placement contract — fine for single-device resumes, wrong for a
    mesh.  ``target_shardings`` makes placement explicit (the reshard
    step of elastic recovery, docs/elastic.md): a pytree of ``Sharding``
    matching the restored tree, or a callable ``tree -> shardings``
    derived from the restored structure (e.g. ``lambda t:
    fsdp_sharding(mesh, t)``).  The checkpoint may have been written
    under a DIFFERENT mesh shape: restore is host-side either way, so
    re-placement works across topologies (``elastic/reshard.py``)."""
    steps = _steps_by_format(ckpt_dir)
    best_npz = max(steps["npz"]) if steps["npz"] else -1
    best_orbax = max(steps["orbax"]) if steps["orbax"] else -1
    if best_orbax < 0 and best_npz < 0:
        return None, 0
    if best_orbax >= best_npz:
        ckpt = AsyncCheckpointer(ckpt_dir)
        try:
            tree, step = ckpt.restore_latest()
        finally:
            ckpt.close()
    else:
        tree, step = restore_latest(ckpt_dir)
    if tree is not None and target_shardings is not None:
        # function import: the elastic package re-exports reshard() the
        # function over the reshard module attribute
        from tensorflowonspark_tpu.elastic.reshard import reshard

        tree = reshard(tree, target_shardings)
    return tree, step


class AsyncCheckpointer:
    """Orbax-backed async checkpointing (GCS-capable) behind the same
    save/restore contract as the npz functions: device-to-host copy and
    serialization overlap training instead of blocking the step loop.

    Usage::

        ckpt = AsyncCheckpointer(model_dir)
        params, start = ckpt.restore_latest()
        for step in range(start, steps):
            ...
            if step % save_every == 0:
                ckpt.save(step, params)   # returns immediately
        ckpt.close()                      # waits for in-flight saves
    """

    def __init__(self, ckpt_dir, keep=3):
        import orbax.checkpoint as ocp

        # URLs (gs://...) go to orbax/tensorstore verbatim; only plain
        # local paths are absolutized (os.path.abspath would mangle a URL)
        if _fs.is_local(ckpt_dir):
            ckpt_dir = os.path.abspath(_fs.local_path(ckpt_dir))
        self._ocp = ocp
        self._mngr = ocp.CheckpointManager(
            ckpt_dir,
            options=ocp.CheckpointManagerOptions(
                max_to_keep=keep, enable_async_checkpointing=True
            ),
        )

    def save(self, step, tree):
        """Queue an async save of ``tree`` at ``step`` (non-blocking)."""
        import jax

        faults.check("checkpoint.save", step=step)

        # orbax's StandardSave rejects numpy scalar leaves (np.float32);
        # promote them to 0-d arrays, which round-trip identically
        tree = jax.tree.map(
            lambda x: np.asarray(x) if isinstance(x, np.generic) else x,
            tree)
        self._mngr.save(step, args=self._ocp.args.StandardSave(tree))

    def latest_step(self):
        return self._mngr.latest_step()

    def restore_latest(self):
        """(tree, next_step) — (None, 0) when no checkpoint exists."""
        step = self._mngr.latest_step()
        if step is None:
            return None, 0
        # explicit StandardRestore: a fresh manager over an existing dir
        # has no registered handler yet and raises KeyError without it
        return self._mngr.restore(
            step, args=self._ocp.args.StandardRestore()), step

    def wait(self):
        self._mngr.wait_until_finished()

    def close(self):
        self._mngr.wait_until_finished()
        self._mngr.close()


def async_checkpointer(ckpt_dir, keep=3):
    """Back-compat constructor for :class:`AsyncCheckpointer`."""
    return AsyncCheckpointer(ckpt_dir, keep=keep)
