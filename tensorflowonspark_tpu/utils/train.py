"""Training-loop building blocks shared by the model families.

New-build capability beyond reference parity (the reference delegated
all training mechanics to TensorFlow): gradient accumulation lets one
chip train at an effective batch larger than HBM allows — the single
optimizer update sees the mean gradient over ``accum_steps``
microbatches, computed under one jit with a ``lax.scan`` (constant
memory in the number of microbatches).
"""

from __future__ import annotations

import os

import jax
import jax.numpy as jnp
from jax import lax

GRADNORM_ENV = "TFOS_HEALTH_GRADNORM"


def gradnorm_enabled():
    """True when ``TFOS_HEALTH_GRADNORM`` asks for the device-side health
    probe.  Read at trace time: the fold into the jitted step happens (or
    not) when the step is built, so the off path costs literally zero."""
    return os.environ.get(GRADNORM_ENV, "").strip().lower() in (
        "1", "true", "on", "yes")


def global_norm(tree):
    """Global L2 norm over a gradient pytree, accumulated in float32 —
    one scalar, cheap next to the backward pass that produced the tree."""
    leaves = jax.tree.leaves(tree)
    if not leaves:
        return jnp.zeros((), jnp.float32)
    sq = sum(jnp.sum(jnp.square(x.astype(jnp.float32))) for x in leaves)
    return jnp.sqrt(sq)


def health_probe(loss, grads):
    """Device-computed health scalars for the watchtower (obs/health.py),
    folded into the train step behind ``TFOS_HEALTH_GRADNORM``.

    Returns ``{"grad_norm", "finite"}`` (a float32 scalar and a bool
    scalar: ``isfinite(loss) & isfinite(grad_norm)``) to return alongside
    the step outputs and forward into ``TrainMetrics.step(grad_norm=...,
    grad_finite=...)`` — or None when the gate is off, so callers can
    write ``probe = train.health_probe(loss, grads)`` unconditionally
    inside the jitted step and pay nothing unless enabled."""
    if not gradnorm_enabled():
        return None
    gn = global_norm(grads)
    finite = jnp.logical_and(
        jnp.all(jnp.isfinite(jnp.asarray(loss, jnp.float32))),
        jnp.isfinite(gn))
    return {"grad_norm": gn, "finite": finite}


def split_microbatches(batch, accum_steps):
    """Reshape every leaf of ``batch`` (a tuple/pytree of arrays with a
    shared leading batch dim) to ``[accum_steps, b/accum_steps, ...]``
    for a ``lax.scan`` over microbatches.  Raises when the leading dim
    is not divisible — the elastic virtual layer sizes global batches so
    this always divides on any divisor topology (docs/elastic.md)."""

    def split(x):
        if x.shape[0] % accum_steps:
            raise ValueError(
                f"batch dim {x.shape[0]} not divisible by "
                f"accum_steps={accum_steps}")
        return x.reshape(accum_steps, x.shape[0] // accum_steps,
                         *x.shape[1:])

    return jax.tree.map(split, batch)


def accumulated_value_and_grad(loss_fn, accum_steps, has_aux=False,
                               carry_aux=False):
    """``jax.value_and_grad`` with microbatch accumulation.

    ``loss_fn(params, *batch) -> loss`` (or ``(loss, aux)`` with
    ``has_aux=True``).  Returns ``vg(params, *batch)`` ->
    ``(loss, grads)`` (or ``((loss, aux), grads)``) where every batch
    leaf's leading dimension must be divisible by ``accum_steps``; the
    loss and gradients are the mean over microbatches (identical to one
    big batch for mean-reduced losses).

    ``carry_aux=True`` (requires ``has_aux``) threads the aux through
    the microbatch chain — ``loss_fn(params, aux_prev, *mb)`` — so
    stateful aux (e.g. BatchNorm running statistics) advances once per
    MICROBATCH, exactly like a sequential small-batch loop; the caller
    passes the incoming state as ``vg(params, *batch, init_aux=state)``.
    Without it, aux is simply the last microbatch's output.

    ``accum_steps=1`` returns plain ``jax.value_and_grad`` — zero
    overhead on the common path.
    """
    if accum_steps < 1:
        raise ValueError(f"accum_steps must be >= 1, got {accum_steps}")
    if carry_aux and not has_aux:
        raise ValueError("carry_aux requires has_aux=True")
    base = jax.value_and_grad(loss_fn, has_aux=has_aux)
    if accum_steps == 1 and not carry_aux:
        return base

    def vg(params, *batch, init_aux=None):
        if carry_aux and init_aux is None:
            raise ValueError("carry_aux=True requires init_aux=...")

        micro = split_microbatches(batch, accum_steps)

        def body(carry, mb):
            loss_sum, aux_prev, grad_sum = carry
            if carry_aux:
                (loss, aux), grads = base(params, aux_prev, *mb)
            else:
                out, grads = base(params, *mb)
                loss, aux = out if has_aux else (out, aux_prev)
            return (loss_sum + loss, aux,
                    jax.tree.map(jnp.add, grad_sum, grads)), None

        if carry_aux:
            aux0 = init_aux
        elif has_aux:
            # structure-only init (never read — body overwrites it at
            # iteration 0): eval_shape costs zero compute, unlike a real
            # extra forward pass
            _, aux_shape = jax.eval_shape(
                loss_fn, params, *jax.tree.map(lambda x: x[0], micro))
            aux0 = jax.tree.map(
                lambda s: jnp.zeros(s.shape, s.dtype), aux_shape)
        else:
            aux0 = 0.0
        zeros = jax.tree.map(jnp.zeros_like, params)
        (loss_sum, aux, grad_sum), _ = lax.scan(
            body, (jnp.zeros(()), aux0, zeros), micro)
        loss = loss_sum / accum_steps
        grads = jax.tree.map(lambda g: g / accum_steps, grad_sum)
        return ((loss, aux), grads) if has_aux else (loss, grads)

    return vg
