"""Host discovery, env setup, executor-id persistence.

Parity: reference tensorflowonspark/util.py:21-94.  The executor-id file is
the key that lets a *feeder* task, scheduled later onto the same executor,
reattach to the manager started by the node task (SURVEY.md §3.2).
"""

from __future__ import annotations

import logging
import os
import socket

logger = logging.getLogger(__name__)

_EXECUTOR_ID_FILE = "executor_id"


def get_ip_address():
    """This host's primary IP via the UDP-connect trick (util.py:52-65)."""
    s = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
    try:
        s.connect(("8.8.8.8", 80))
        return s.getsockname()[0]
    except OSError:
        return "127.0.0.1"
    finally:
        s.close()


def find_in_path(path, file_name):
    """Find file_name in the os.pathsep-separated path (util.py:68-74)."""
    for p in path.split(os.pathsep):
        candidate = os.path.join(p, file_name)
        if os.path.exists(candidate) and os.path.isfile(candidate):
            return candidate
    return False


def write_executor_id(num, cwd=None):
    """Persist this executor's id in its working dir (util.py:77-85)."""
    path = os.path.join(cwd or os.getcwd(), _EXECUTOR_ID_FILE)
    with open(path, "w") as f:
        f.write(str(num))
    return path


def read_executor_id(cwd=None):
    """Read back the executor id; None if the node task never ran here."""
    path = os.path.join(cwd or os.getcwd(), _EXECUTOR_ID_FILE)
    if not os.path.exists(path):
        return None
    with open(path) as f:
        return int(f.read())


_CHILD_PIDS_FILE = "tfos_child_pids"
CHILD_PIDS_DIR_ENV = "TFOS_CHILD_PIDS_DIR"


def child_pids_dir():
    """Default directory of this process's child-pid ledger.

    Executor processes (``TFOS_EXECUTOR_INDEX`` set) keep the original
    contract — their ledger lives in the executor working dir, where the
    engine's respawn/stop paths read it.  Any other process (the driver,
    a serving pool, a test) gets a per-process tempdir instead of its
    CWD: a driver-side ``manager.start`` used to drop ``tfos_child_pids``
    into whatever directory the user launched from (the repo root,
    typically).  ``TFOS_CHILD_PIDS_DIR`` overrides both.
    """
    override = os.environ.get(CHILD_PIDS_DIR_ENV)
    if override:
        return override
    if "TFOS_EXECUTOR_INDEX" in os.environ:
        return os.getcwd()
    import tempfile

    return os.path.join(tempfile.gettempdir(), f"tfos-pids-{os.getpid()}")


def track_child_pid(pid, cwd=None):
    """Record a forked/spawned long-lived child of this executor process.

    The node task forks the background trainer and the IPC-manager server
    inside the executor; if the executor is later killed un-gracefully
    (engine teardown after a crashed run), those children re-parent to
    init and outlive the job.  The pid file lets the engine's ``stop()``
    kill survivors it can no longer reach through a manager.
    """
    base = cwd or child_pids_dir()
    path = os.path.join(base, _CHILD_PIDS_FILE)
    try:
        os.makedirs(base, exist_ok=True)
        with open(path, "a") as f:
            f.write(f"{pid}\n")
    except OSError as e:  # best-effort bookkeeping only
        logger.warning("could not record child pid %s: %s", pid, e)
    return path


def read_child_pids(cwd=None):
    """Pids recorded by track_child_pid in the given working dir."""
    path = os.path.join(cwd or child_pids_dir(), _CHILD_PIDS_FILE)
    if not os.path.exists(path):
        return []
    try:
        with open(path) as f:
            return [int(line) for line in f.read().split()]
    except (OSError, ValueError):
        return []


def clear_child_pids(cwd=None):
    """Forget the child pids recorded for ``cwd``.  Called after an
    executor respawn has reaped the dead incarnation's children (and by
    engine stop after its final sweep), so the next incarnation's pid
    file starts clean."""
    path = os.path.join(cwd or child_pids_dir(), _CHILD_PIDS_FILE)
    try:
        os.remove(path)
    except OSError:
        pass


def kill_pid(pid, sig=None):
    """Send ``sig`` (default SIGKILL) to pid; True if the signal was sent."""
    import signal as _signal

    try:
        os.kill(pid, _signal.SIGKILL if sig is None else sig)
        return True
    except (OSError, ProcessLookupError):
        return False


def reap_child(pid, timeout=5.0, term_first=True):
    """Make a direct child exit and collect it: wait, then SIGTERM, then
    SIGKILL; swallows 'not my child' so callers can use it opportunistically
    from whichever process the shutdown closure happens to land in."""
    import signal as _signal
    import time as _time

    deadline = _time.time() + timeout

    def _gone():
        try:
            done, _ = os.waitpid(pid, os.WNOHANG)
            if done == pid:
                return True
        except ChildProcessError:
            # not our child (or already reaped): alive-check via signal 0
            return not kill_pid(pid, 0)
        except OSError:
            return True
        return False

    while _time.time() < deadline:
        if _gone():
            return True
        _time.sleep(0.1)
    if term_first:
        kill_pid(pid, _signal.SIGTERM)
        grace = _time.time() + 2.0
        while _time.time() < grace:
            if _gone():
                return True
            _time.sleep(0.1)
    kill_pid(pid)
    final = _time.time() + 2.0
    while _time.time() < final:
        if _gone():
            return True
        _time.sleep(0.1)
    return False


def single_node_env(num_chips=0, worker_index=-1):
    """Set up a single-node environment (util.py:21-49 equivalent).

    The reference expands Hadoop classpath globs and claims GPUs via
    nvidia-smi; here the device substrate is the TPU runtime, so this
    partitions visible TPU chips for multi-process-per-host placement.
    """
    from tensorflowonspark_tpu import tpu_info

    if num_chips > 0:
        tpu_info.set_visible_chips(num_chips, worker_index)
    # Expand any HADOOP classpath for HDFS-backed checkpoint paths, once.
    if "HADOOP_PREFIX" in os.environ and "TFOS_CLASSPATH_UPDATED" not in os.environ:
        classpath = os.environ.get("CLASSPATH", "")
        hadoop_path = os.path.join(os.environ["HADOOP_PREFIX"], "bin", "hadoop")
        if os.path.exists(hadoop_path):
            import subprocess

            hadoop_classpath = subprocess.check_output(
                [hadoop_path, "classpath", "--glob"]
            ).decode()
            os.environ["CLASSPATH"] = classpath + os.pathsep + hadoop_classpath
        os.environ["TFOS_CLASSPATH_UPDATED"] = "1"
