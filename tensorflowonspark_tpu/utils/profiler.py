"""Dashboard + device profiler (parity: the TensorBoard subprocess spawn
of reference TFSparkNode.py:282-319, plus the XLA/TPU profiler capture
the reference lacked — SURVEY.md §5 "Tracing: new build adds native
XLA/TPU profiler capture").

``launch_tensorboard`` mirrors the reference's behavior: port from
``TENSORBOARD_PORT`` or ephemeral, binary found next to the python
executable / on PATH / via PYTHONPATH module fallback, child killed at
node shutdown.  ``trace``/``start_trace``/``stop_trace`` wrap
``jax.profiler`` so each worker can drop a device trace (HLO timelines,
MXU utilization) into the same log_dir TensorBoard serves.
"""

from __future__ import annotations

import contextlib
import logging
import os
import socket
import subprocess
import sys
import time

logger = logging.getLogger(__name__)


def _find_tensorboard():
    """Locate a tensorboard executable (TFSparkNode.py:299-311 order:
    python bin dir, then PATH)."""
    candidates = [
        os.path.join(os.path.dirname(sys.executable), "tensorboard"),
    ]
    from tensorflowonspark_tpu.utils.hostinfo import find_in_path

    on_path = find_in_path(os.environ.get("PATH", ""), "tensorboard")
    if on_path:
        candidates.append(on_path)
    for c in candidates:
        if c and os.path.isfile(c) and os.access(c, os.X_OK):
            return [c]
    try:  # module fallback (no console script installed)
        import tensorboard  # noqa: F401

        return [sys.executable, "-m", "tensorboard.main"]
    except ImportError:
        return None


def launch_tensorboard(log_dir, port=None):
    """Spawn TensorBoard on ``log_dir``; returns (process, port) or
    (None, None) when no tensorboard is installed (logged, not fatal)."""
    cmd = _find_tensorboard()
    if not cmd:
        logger.warning("tensorboard not found; dashboard disabled")
        return None, None
    if port is None:
        if os.environ.get("TENSORBOARD_PORT"):
            port = int(os.environ["TENSORBOARD_PORT"])
        else:
            with socket.socket() as s:  # ephemeral pick
                s.bind(("", 0))
                port = s.getsockname()[1]
    os.makedirs(log_dir, exist_ok=True)
    tb_log = os.path.join(log_dir, "tensorboard.log")
    with open(tb_log, "ab") as sink:
        proc = subprocess.Popen(
            cmd + ["--logdir", log_dir, "--port", str(port), "--bind_all"],
            stdout=sink,
            stderr=sink,
        )
    # liveness check: an ephemeral port can be stolen between release and
    # the child's bind, and a bad install dies instantly — don't advertise
    # a dashboard that isn't running
    time.sleep(1.0)
    if proc.poll() is not None:
        logger.warning(
            "tensorboard exited immediately (rc=%s); see %s",
            proc.returncode, tb_log,
        )
        return None, None
    logger.info("TensorBoard pid=%d port=%d logdir=%s", proc.pid, port, log_dir)
    return proc, port


def stop_tensorboard(proc):
    if proc is not None and proc.poll() is None:
        proc.kill()
        proc.wait(timeout=10)


# Capture degrades to a no-op on images where jax.profiler can't start a
# trace (no jax, no profiler plugin, CPU-only builds without the capture
# backend).  A missing profiler must never take down the run — or the
# obs control plane asking a worker for an on-demand capture — so every
# entry point warns once and reports success as a boolean.
_degraded_warned = False


def _warn_unavailable(err):
    global _degraded_warned
    if not _degraded_warned:
        logger.warning(
            "jax profiler capture unavailable (%s); trace is a no-op", err)
        _degraded_warned = True
    else:
        logger.debug("jax profiler capture unavailable: %s", err)


def start_trace(log_dir):
    """Begin an XLA device trace (viewable in TensorBoard's profile tab).

    Returns True when a capture actually started; False when capture is
    unavailable in this build (warned once, never raises)."""
    try:
        import jax

        jax.profiler.start_trace(log_dir)
        return True
    except Exception as e:  # noqa: BLE001 - capture is best-effort
        _warn_unavailable(e)
        return False


def stop_trace():
    """End the running trace; returns True on success (never raises)."""
    try:
        import jax

        jax.profiler.stop_trace()
        return True
    except Exception as e:  # noqa: BLE001 - capture is best-effort
        _warn_unavailable(e)
        return False


@contextlib.contextmanager
def trace(log_dir, enabled=True):
    """``with profiler.trace(log_dir): step(...)`` around hot steps.

    Degrades to a plain passthrough when capture is unavailable (the
    body always runs; only the trace is skipped)."""
    if not enabled:
        yield
        return
    started = start_trace(log_dir)
    try:
        yield
    finally:
        if started:
            stop_trace()
