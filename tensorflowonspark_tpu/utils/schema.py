"""Schema-string parser (parity: src/main/scala SimpleTypeParser.scala).

The reference's JVM inference CLI takes a ``schema_hint`` in Spark's
``StructType.simpleString`` grammar — ``struct<name:type,...>`` over base
types and 1-D arrays (SimpleTypeParser.scala:34-64).  The same grammar is
accepted here and mapped onto dfutil's ``{name: (kind, is_array)}``
schema dicts (kinds: int64 / float / string / bytes).
"""

from __future__ import annotations

import re

# simpleString base type -> dfutil kind (the reference's widening rules:
# DFUtilTest.scala:95-132 — bool widens to long, binary is bytes)
_BASE_TYPES = {
    "boolean": "int64",
    "tinyint": "int64",
    "smallint": "int64",
    "int": "int64",
    "bigint": "int64",
    "long": "int64",
    "float": "float",
    "double": "float",
    "string": "string",
    "binary": "bytes",
}

_KIND_TO_TYPE = {
    "int64": "bigint",
    "float": "float",
    "string": "string",
    "bytes": "binary",
}

_FIELD_RE = re.compile(
    r"^(?P<name>[A-Za-z_][A-Za-z0-9_]*):"
    r"(?:(?P<array>array<(?P<elem>[a-z]+)>)|(?P<base>[a-z]+))$"
)


class SchemaParseError(ValueError):
    pass


def _split_fields(body):
    """Split on commas at nesting depth 0 (array<...> commas don't occur
    in the 1-D grammar, but be robust to them anyway)."""
    fields, depth, cur = [], 0, []
    for ch in body:
        if ch == "<":
            depth += 1
        elif ch == ">":
            depth -= 1
        if ch == "," and depth == 0:
            fields.append("".join(cur))
            cur = []
        else:
            cur.append(ch)
    if cur:
        fields.append("".join(cur))
    return fields


def parse_schema(text):
    """``struct<name:type,...>`` -> {name: (kind, is_array)}.

    Accepts the bare field list too (``name:type,...``), matching how the
    reference CLI users pass hints on the command line.
    """
    s = text.strip()
    if s.startswith("struct<"):
        if not s.endswith(">"):
            raise SchemaParseError(f"unbalanced struct<...>: {text!r}")
        s = s[len("struct<"):-1]
    schema = {}
    if not s:
        return schema
    for field in _split_fields(s):
        m = _FIELD_RE.match(field.strip())
        if not m:
            raise SchemaParseError(f"cannot parse field {field!r} in {text!r}")
        base = m.group("elem") or m.group("base")
        if base not in _BASE_TYPES:
            raise SchemaParseError(
                f"unknown type {base!r} in {field!r}; "
                f"expected one of {sorted(_BASE_TYPES)}"
            )
        schema[m.group("name")] = (
            _BASE_TYPES[base], m.group("array") is not None
        )
    return schema


def format_schema(schema):
    """{name: (kind, is_array)} -> ``struct<...>`` simpleString."""
    parts = []
    for name, (kind, is_array) in schema.items():
        t = _KIND_TO_TYPE[kind]
        parts.append(f"{name}:array<{t}>" if is_array else f"{name}:{t}")
    return f"struct<{','.join(parts)}>"


def merge_schemas(inferred, hint):
    """Partial-hint semantics (parity: DFUtil.inferSchema's schemaHint
    :67-110): hinted fields override the inferred kinds; unhinted fields
    keep the inference."""
    merged = dict(inferred)
    merged.update(hint)
    return merged
