"""Live in-process metrics registry: counters / gauges / histograms.

Parity target: none — the reference's observability is log lines only
(reference ``TFCluster.py:343-344``, SURVEY.md §5) and our telemetry
layer (``utils/telemetry.py``) is post-hoc: spools are drained at run
end and merged offline.  This registry is the *in-flight* half of the
observability plane: hot subsystems bump counters here, a per-node
publisher (``obs/publish.py``) snapshots the registry into the manager
KV, and the driver's ``obs/http.py`` server renders the merged cluster
state as Prometheus text exposition at ``/metrics``.

Design constraints (same discipline as the span recorder):

- **Zero-dep / stdlib-only** — imported by engine executors, feeder
  tasks, forked trainers and the driver; must never pull jax/numpy.
- **Opt-in via env** — enabled iff ``TFOS_OBS_PORT`` is set (the driver
  sets it; spawned/forked children inherit it through the environment).
  When unset every call is a cached no-op: no registry object, no
  locks taken, no threads, no measurable cost on the hot path.
- **Safe under spawn/fork** — the registry is keyed by pid, so a child
  process transparently gets its OWN empty registry instead of a
  handle into the parent's (counts never alias across processes; each
  process publishes its own snapshot under its node id).
- **Never crash the host** — malformed label values are coerced to
  strings; rendering and snapshotting take one lock briefly and touch
  no I/O.

Metric names follow Prometheus conventions (``tfos_`` prefix, unit
suffix on histograms).  Every name used by the instrumentation MUST be
listed in ``CATALOG`` below — ``docs/observability.md`` mirrors that
table and ``tests/test_obs.py`` lints code, catalog and docs against
each other (the span-table convention from ``docs/telemetry.md``).

Env vars:
  ``TFOS_OBS_PORT``      master switch + driver HTTP port (0 = bind an
                         ephemeral port; the bound port is exposed on
                         the server handle).
  ``TFOS_OBS_INTERVAL``  node publish / driver poll period, seconds
                         (default 2; tests shrink it).
"""

from __future__ import annotations

import math
import os
import threading

PORT_ENV = "TFOS_OBS_PORT"
INTERVAL_ENV = "TFOS_OBS_INTERVAL"

DEFAULT_INTERVAL = 2.0

# Default histogram bucket upper bounds, milliseconds: spans feed-chunk
# waits (~1ms) through cold TPU compiles (~minutes).
DEFAULT_BUCKETS_MS = (
    1.0, 2.5, 5.0, 10.0, 25.0, 50.0, 100.0, 250.0, 500.0,
    1000.0, 2500.0, 5000.0, 10000.0, 30000.0, 60000.0,
)

# -- metric catalog --------------------------------------------------------
# name -> (type, help).  docs/observability.md carries the same table
# with labels and call sites; tests/test_obs.py asserts (a) every
# ``tfos_*`` literal in the package appears here and (b) every name here
# appears in the docs — so the catalog can't silently rot.
CATALOG = {
    # engine (driver process)
    "tfos_engine_jobs_total": (
        "counter", "Engine jobs completed, by status (ok|error)."),
    "tfos_engine_tasks_total": (
        "counter", "Engine tasks completed, by status (ok|error)."),
    "tfos_engine_task_retries_total": (
        "counter", "Task attempts re-scheduled after a retryable failure."),
    "tfos_engine_respawns_total": (
        "counter", "Executor processes respawned after death."),
    "tfos_engine_executors": (
        "gauge", "Executor processes currently alive."),
    # feed / data ring (trainer process)
    "tfos_feed_chunks_total": (
        "counter", "Chunks pulled off the feed transport."),
    "tfos_feed_records_total": (
        "counter", "Records pulled off the feed transport."),
    "tfos_feed_wait_seconds_total": (
        "counter", "Cumulative seconds the consumer blocked on the feed."),
    "tfos_feed_ring_bytes": (
        "gauge", "Bytes resident in the shm feed ring after a pull."),
    "tfos_feed_queue_depth": (
        "gauge", "Chunks resident in the manager feed queue after a pull."),
    # train step (trainer process, utils/metrics.py)
    "tfos_train_steps_total": (
        "counter", "Timed train steps completed."),
    "tfos_train_step_ms": (
        "histogram", "Train step wall time, milliseconds."),
    "tfos_train_items_per_sec": (
        "gauge", "Training throughput over the metrics window."),
    "tfos_train_infeed_stall_frac": (
        "gauge", "Fraction of step time spent waiting on the feed."),
    "tfos_train_mfu": (
        "gauge", "Model FLOPs utilization (2 FLOPs/MAC convention)."),
    # data service (data-worker process)
    "tfos_data_records_total": (
        "counter", "Records pushed to trainers, by trainer rank."),
    "tfos_data_units_total": (
        "counter", "Exactly-once ledger units recorded done."),
    "tfos_data_resumes_total": (
        "counter", "Shard-cursor resumes after a worker respawn."),
    # dynamic split dispatch (data/splits.py provider + dynamic workers)
    "tfos_data_splits_posted_total": (
        "counter", "Split ids posted to the FCFS queue by the provider."),
    "tfos_data_splits_claimed_total": (
        "counter", "Splits claimed off the queue by this worker."),
    "tfos_data_splits_served_total": (
        "counter", "Splits recorded consumption-safe in the ledger."),
    "tfos_data_splits_requeued_total": (
        "counter", "Splits of dead claimants returned to the queue."),
    "tfos_data_split_dup_chunks_total": (
        "counter", "Re-served split chunks dropped by consumer dedup."),
    "tfos_data_split_queue_depth": (
        "gauge", "Split ids waiting in the shared FCFS queue."),
    "tfos_data_workers": (
        "gauge", "Dynamic data workers in the active plan (autoscaler)."),
    # shared epoch cache (data/cache.py)
    "tfos_data_cache_hits_total": (
        "counter", "Shared-cache registry lookups that reused a cache."),
    "tfos_data_cache_misses_total": (
        "counter", "Shared-cache registry lookups that built a cache."),
    "tfos_data_cache_spilled_total": (
        "counter", "Cached blocks written to the disk spill."),
    "tfos_data_cache_blocks": (
        "gauge", "Blocks materialized in the epoch cache."),
    "tfos_data_cache_bytes": (
        "gauge", "Bytes resident in the epoch cache memory tier."),
    # serving (server process)
    "tfos_serve_requests_total": (
        "counter", "Serving requests, by status (ok|error|shed)."),
    "tfos_serve_request_ms": (
        "histogram", "End-to-end served request latency, milliseconds."),
    "tfos_serve_queue_depth": (
        "gauge", "Micro-batcher queue depth at last admission."),
    "tfos_serve_batches_total": (
        "counter", "Device batches dispatched by the micro-batcher."),
    "tfos_serve_batch_rows_total": (
        "counter", "Real (non-padding) rows in dispatched batches."),
    "tfos_serve_reloads_total": (
        "counter", "Checkpoint hot-reload broadcasts."),
    "tfos_serve_pool_generation": (
        "gauge", "Elastic pool generation (bumps on every resize; "
                 "epoch-fences stale resize acks)."),
    "tfos_serve_pool_degraded": (
        "gauge", "1 while the elastic pool serves below its logical "
                 "capacity, else 0."),
    "tfos_serve_resize_seconds": (
        "histogram", "Elastic pool resize duration (generation bump to "
                     "last replica reshard ack), seconds."),
    # serving fabric (serving/fabric/ — driver process)
    "tfos_fabric_hosts": (
        "gauge", "Live fabric host processes."),
    "tfos_fabric_replicas": (
        "gauge", "Replica workers across live fabric hosts."),
    "tfos_fabric_queue_depth": (
        "gauge", "In-flight fabric dispatches (batches + sessions)."),
    "tfos_fabric_dispatches_total": (
        "counter", "Fabric dispatches, by kind (batch|gen)."),
    "tfos_fabric_affinity_total": (
        "counter", "Fabric session routing decisions, by outcome "
                   "(hit|miss|fallback)."),
    "tfos_fabric_redispatches_total": (
        "counter", "In-flight work resent after a fabric host died, "
                   "by kind (batch|gen)."),
    "tfos_fabric_scale_events_total": (
        "counter", "Autoscale plans actuated by the fabric router, by "
                   "direction (up|down)."),
    # decode (serving/decode/ — server process + replica engines)
    "tfos_decode_sessions_total": (
        "counter", "Decode sessions, by status (ok|error|shed)."),
    "tfos_decode_tokens_total": (
        "counter", "Tokens generated by completed decode sessions."),
    "tfos_decode_ttft_ms": (
        "histogram", "Decode time-to-first-token, milliseconds."),
    "tfos_decode_token_ms": (
        "histogram", "Decode per-token gap (inter-token latency), "
                     "milliseconds."),
    "tfos_decode_slot_occupancy": (
        "gauge", "KV-cache slots occupied after the last engine "
                 "iteration."),
    "tfos_decode_retired_total": (
        "counter", "Decode sessions retired (EOS or max_tokens)."),
    "tfos_decode_prefix_hits": (
        "counter", "Admissions that mapped trie-matched prompt-prefix "
                   "blocks instead of re-prefilling them."),
    "tfos_decode_blocks_in_use": (
        "gauge", "Paged-KV blocks referenced by live sessions or the "
                 "prefix trie (sentinel excluded)."),
    "tfos_decode_spec_accept": (
        "gauge", "Speculative-decode draft acceptance rate (accepted / "
                 "proposed, cumulative)."),
    # checkpoint (any process)
    "tfos_checkpoint_saves_total": (
        "counter", "Checkpoint saves completed."),
    "tfos_checkpoint_restores_total": (
        "counter", "Checkpoint restores completed."),
    "tfos_checkpoint_save_ms": (
        "histogram", "Checkpoint save latency, milliseconds."),
    "tfos_checkpoint_restore_ms": (
        "histogram", "Checkpoint restore latency, milliseconds."),
    # elastic SPMD runtime (elastic/)
    "tfos_elastic_resizes_total": (
        "counter", "Mesh/cluster elastic resizes, by scope "
                   "(runtime|cluster)."),
    "tfos_elastic_mesh_devices": (
        "gauge", "Physical devices in the current elastic mesh."),
    "tfos_elastic_virtual_devices": (
        "gauge", "Virtual devices (logical mesh size) of the TrainSpec."),
    "tfos_elastic_accum_steps": (
        "gauge", "Gradient-accumulation steps folding virtual onto "
                 "physical devices."),
    "tfos_elastic_reshard_ms": (
        "histogram", "Train-state reshard latency (host round-trip), "
                     "milliseconds."),
    # actor substrate (actors/ — driver process)
    "tfos_actor_spawns_total": (
        "counter", "Actor member incarnations registered, by group."),
    "tfos_actor_respawns_total": (
        "counter", "Actor members respawned after death, by group."),
    "tfos_actor_mailbox_depth": (
        "gauge", "Mailbox depth observed at the last send, by group."),
    "tfos_actor_heartbeat_age_s": (
        "gauge", "Oldest live-member heartbeat age, seconds, by group."),
    # workloads (workloads/ — actor processes)
    "tfos_eval_runs_total": (
        "counter", "Eval-sidecar evaluations completed."),
    "tfos_eval_last_step": (
        "gauge", "Checkpoint step of the last completed evaluation."),
    # blessed-checkpoint deployment loop (utils/checkpoint.py manifests,
    # serving/replicas.py canary arms, workloads/deploy_loop.py controller)
    "tfos_deploy_blessed_step": (
        "gauge", "Newest checkpoint step with a blessing manifest (the "
                 "rollback target)."),
    "tfos_deploy_tombstones_total": (
        "counter", "Checkpoints quarantined by a rollback tombstone."),
    "tfos_deploy_canary_step": (
        "gauge", "Candidate checkpoint step the open canary arm serves."),
    "tfos_deploy_requests_total": (
        "counter", "Requests resolved under a canary split, by arm "
                   "(canary|baseline) and status (ok|error)."),
    "tfos_deploy_request_ms": (
        "histogram", "End-to-end request latency under a canary split, "
                     "by arm."),
    "tfos_deploy_promotions_total": (
        "counter", "Canary candidates promoted to the full pool "
                   "(bootstrap pins included)."),
    "tfos_deploy_rollbacks_total": (
        "counter", "Canary candidates auto-rolled back and tombstoned."),
    # SLO engine (obs/slo.py — driver process)
    "tfos_slo_burn_rate": (
        "gauge", "Error-budget burn rate per objective (1.0 spends the "
                 "budget exactly; >1 is a breach in progress)."),
    "tfos_slo_current": (
        "gauge", "Current tracked value per objective (latency: the "
                 "target-quantile milliseconds; availability: the good "
                 "fraction)."),
    "tfos_slo_breaches_total": (
        "counter", "Objective transitions into breach (burn crossing "
                   "above 1), by objective."),
    # training-health watchtower (obs/health.py — trainer process;
    # tfos_node_skew on the driver)
    "tfos_health_anomalies_total": (
        "counter", "Edge-triggered training anomalies, by kind "
                   "(nan|loss_spike|slow_step|infeed_stall)."),
    "tfos_health_status": (
        "gauge", "Health of this process's training loop: 0 ok, "
                 "1 degraded (an anomaly fired and has not cleared)."),
    "tfos_health_last_anomaly_step": (
        "gauge", "Step index of the most recent anomaly, by kind."),
    "tfos_health_grad_norm": (
        "gauge", "Device-computed global gradient norm from the last "
                 "step (only under TFOS_HEALTH_GRADNORM=1)."),
    "tfos_health_captures_total": (
        "counter", "On-demand captures served by the publish daemon, "
                   "by kind (profile|flight) and status (ok|degraded)."),
    "tfos_node_skew": (
        "gauge", "Driver-side straggler skew: slowest node's median "
                 "step time over the fastest node's (1.0 = balanced)."),
}


def interval():
    """Publish/poll period in seconds (``TFOS_OBS_INTERVAL``)."""
    try:
        return max(0.05, float(os.environ.get(INTERVAL_ENV,
                                              str(DEFAULT_INTERVAL))))
    except ValueError:
        return DEFAULT_INTERVAL


class _Hist:
    __slots__ = ("bounds", "counts", "sum", "count")

    def __init__(self, bounds):
        self.bounds = tuple(float(b) for b in bounds)
        self.counts = [0] * (len(self.bounds) + 1)  # last bin = +Inf
        self.sum = 0.0
        self.count = 0

    def observe(self, value):
        v = float(value)
        self.sum += v
        self.count += 1
        for i, b in enumerate(self.bounds):
            if v <= b:
                self.counts[i] += 1
                return
        self.counts[-1] += 1


class Registry:
    """One process's metric store.  All mutation under one lock — the
    critical sections are a few dict ops, far below transport costs on
    every instrumented path."""

    def __init__(self):
        self._lock = threading.Lock()
        # name -> {"type", "help", "series": {labels_tuple: value|_Hist}}
        self._metrics = {}

    def _series(self, name, mtype, labels, default):
        ent = self._metrics.get(name)
        if ent is None:
            mhelp = CATALOG.get(name, (mtype, ""))[1]
            ent = {"type": mtype, "help": mhelp, "series": {}}
            self._metrics[name] = ent
        key = tuple(sorted((str(k), str(v)) for k, v in labels.items()))
        if key not in ent["series"]:
            ent["series"][key] = default()
        return ent["series"], key

    def inc(self, name, value=1.0, **labels):
        with self._lock:
            series, key = self._series(name, "counter", labels, float)
            series[key] += float(value)

    def set(self, name, value, **labels):
        with self._lock:
            series, key = self._series(name, "gauge", labels, float)
            series[key] = float(value)

    def observe(self, name, value, buckets=None, **labels):
        with self._lock:
            series, key = self._series(
                name, "histogram", labels,
                lambda: _Hist(buckets or DEFAULT_BUCKETS_MS))
            series[key].observe(value)

    def snapshot(self):
        """Plain-data (picklable / JSON-able) copy of every series —
        the payload the node publisher ships over the manager KV."""
        out = {}
        with self._lock:
            for name, ent in self._metrics.items():
                series = []
                for key, val in ent["series"].items():
                    s = {"labels": dict(key)}
                    if isinstance(val, _Hist):
                        s.update(bounds=list(val.bounds),
                                 counts=list(val.counts),
                                 sum=val.sum, count=val.count)
                    else:
                        s["value"] = val
                    series.append(s)
                out[name] = {"type": ent["type"], "help": ent["help"],
                             "series": series}
        return out


# Cached per (pid, gate): a fork/spawn child or an env change (tests)
# transparently gets a fresh registry — same pattern as telemetry._get.
_STATE = {"key": None, "reg": None}
_STATE_LOCK = threading.Lock()


def _get():
    key = (os.getpid(), os.environ.get(PORT_ENV))
    if _STATE["key"] == key:
        return _STATE["reg"]
    with _STATE_LOCK:
        if _STATE["key"] != key:
            _STATE["reg"] = Registry() if key[1] is not None else None
            _STATE["key"] = key
        return _STATE["reg"]


def enabled():
    """True when the live metrics plane is recording in this process."""
    return _get() is not None


def reset():
    """Drop this process's registry (tests: isolate series between
    cases that share one ``TFOS_OBS_PORT`` value)."""
    with _STATE_LOCK:
        _STATE["key"] = None
        _STATE["reg"] = None


def inc(name, value=1.0, **labels):
    """Add ``value`` to a counter series (no-op when disabled)."""
    reg = _get()
    if reg is not None:
        reg.inc(name, value, **labels)


def set_gauge(name, value, **labels):
    """Set a gauge series to ``value`` (no-op when disabled)."""
    reg = _get()
    if reg is not None:
        reg.set(name, value, **labels)


def observe(name, value, buckets=None, **labels):
    """Record one histogram observation (no-op when disabled)."""
    reg = _get()
    if reg is not None:
        reg.observe(name, value, buckets=buckets, **labels)


def snapshot():
    """This process's registry snapshot, or None when disabled."""
    reg = _get()
    return reg.snapshot() if reg is not None else None


# -- rendering -------------------------------------------------------------


def _escape(v):
    return (str(v).replace("\\", "\\\\").replace("\n", "\\n")
            .replace('"', '\\"'))


def _labelstr(labels):
    if not labels:
        return ""
    inner = ",".join(f'{k}="{_escape(v)}"'
                     for k, v in sorted(labels.items()))
    return "{" + inner + "}"


def _fmt(v):
    if v == math.inf:
        return "+Inf"
    f = float(v)
    return str(int(f)) if f == int(f) else repr(f)


def render_text(snapshots):
    """Prometheus text exposition for ``[(extra_labels, snapshot)]``
    pairs (one pair per node; ``extra_labels`` typically
    ``{"node": node_id}``).  Series from every node merge under one
    ``# HELP``/``# TYPE`` header per metric name."""
    merged = {}  # name -> (type, help, [(labels, series_dict)])
    for extra, snap in snapshots:
        for name, ent in (snap or {}).items():
            slot = merged.setdefault(
                name, (ent.get("type", "gauge"), ent.get("help", ""), []))
            for s in ent.get("series", ()):
                labels = dict(s.get("labels", {}))
                labels.update(extra or {})
                slot[2].append((labels, s))
    lines = []
    for name in sorted(merged):
        mtype, mhelp, series = merged[name]
        if mhelp:
            lines.append(f"# HELP {name} {mhelp}")
        lines.append(f"# TYPE {name} {mtype}")
        for labels, s in series:
            if mtype == "histogram":
                cum = 0
                bounds = list(s.get("bounds", ())) + [math.inf]
                for b, c in zip(bounds, s.get("counts", ())):
                    cum += c
                    bl = dict(labels, le=_fmt(b))
                    lines.append(f"{name}_bucket{_labelstr(bl)} {cum}")
                lines.append(
                    f"{name}_sum{_labelstr(labels)} {_fmt(s.get('sum', 0))}")
                lines.append(
                    f"{name}_count{_labelstr(labels)} "
                    f"{_fmt(s.get('count', 0))}")
            else:
                lines.append(
                    f"{name}{_labelstr(labels)} {_fmt(s.get('value', 0))}")
    return "\n".join(lines) + ("\n" if lines else "")


def quantile(series, q):
    """Estimate quantile ``q`` (0..1) from one histogram series dict
    (snapshot format: bounds/counts/count) by linear interpolation
    inside the target bucket.  The +Inf bucket clamps to the last
    finite bound.  Returns None for an empty series."""
    count = series.get("count", 0)
    if not count:
        return None
    bounds = list(series.get("bounds", ()))
    counts = list(series.get("counts", ()))
    target = q * count
    cum = 0.0
    lo = 0.0
    for i, c in enumerate(counts):
        nxt = cum + c
        if nxt >= target and c:
            hi = bounds[i] if i < len(bounds) else (
                bounds[-1] if bounds else lo)
            if i >= len(bounds):  # +Inf bucket: clamp
                return float(hi)
            frac = (target - cum) / c
            return float(lo + (hi - lo) * frac)
        cum = nxt
        lo = bounds[i] if i < len(bounds) else lo
    return float(bounds[-1]) if bounds else None
