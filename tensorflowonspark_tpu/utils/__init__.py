"""Small host-side utilities (parity: reference tensorflowonspark/util.py)."""

from tensorflowonspark_tpu.utils.hostinfo import (  # noqa: F401
    find_in_path,
    get_ip_address,
    read_executor_id,
    single_node_env,
    write_executor_id,
)
