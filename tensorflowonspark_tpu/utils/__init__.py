"""Small host-side utilities (parity: reference tensorflowonspark/util.py)."""

from tensorflowonspark_tpu.utils.hostinfo import (  # noqa: F401
    child_pids_dir,
    clear_child_pids,
    find_in_path,
    get_ip_address,
    kill_pid,
    read_child_pids,
    read_executor_id,
    reap_child,
    single_node_env,
    track_child_pid,
    write_executor_id,
)
