"""Shape-exact model-FLOPs counting from the traced jaxpr.

The MFU north star needs an honest denominator for every benched model
(VERDICT r4 weak #6: segmentation/inference reported bare rates nobody
could regress-gate).  Instead of one hand-derived table per family
(models/resnet.py:233 carries the published-MACs table), this walks the
program jax actually traces and counts multiply-accumulates where the
FLOPs are: ``dot_general`` and ``conv_general_dilated``.  Elementwise
and reduction work is excluded, matching the PaLM appendix-B convention
every other denominator in this repo uses (2 FLOPs per MAC;
CLAUDE.md "MFU convention").

The reference has no FLOPs accounting at all (SURVEY.md §5 —
observability is log lines); this is green-field infrastructure shared
by bench.py's segmentation/inference lanes and any future model family.

Counting conventions:
- ``dot_general``: 2 x batch x M x N x K.
- ``conv_general_dilated``: 2 x output positions x kernel taps x
  (in_ch / feature_group_count), divided by ``lhs_dilation`` — a
  transposed conv's zero-inserted positions are not algorithmically
  required work, same honesty rule as the causal attention denominator
  (utils.metrics.transformer_flops_per_token(causal=True)).
- ``scan`` bodies multiply by trip count; ``cond`` branches count the
  most expensive branch; ``while`` bodies count ONCE and set
  ``"while_underestimate"`` in the report (trip counts are unknowable
  statically — refuse to guess).
"""

from __future__ import annotations

import math


def _is_jaxpr(obj):
    # ClosedJaxpr in every modern jax; accept raw Jaxpr defensively
    return hasattr(obj, "eqns") or hasattr(obj, "jaxpr")


def _inner(obj):
    return obj.jaxpr if hasattr(obj, "jaxpr") else obj


def _dot_macs(eqn):
    (lc, rc), (lb, rb) = eqn.params["dimension_numbers"]
    lhs, rhs = (v.aval.shape for v in eqn.invars[:2])
    batch = math.prod(lhs[i] for i in lb)
    contract = math.prod(lhs[i] for i in lc)
    m = math.prod(d for i, d in enumerate(lhs) if i not in set(lb) | set(lc))
    n = math.prod(d for i, d in enumerate(rhs) if i not in set(rb) | set(rc))
    return batch * m * n * contract


def _conv_macs(eqn):
    p = eqn.params
    dn = p["dimension_numbers"]
    lhs = eqn.invars[0].aval.shape
    rhs = eqn.invars[1].aval.shape
    out = eqn.outvars[0].aval.shape
    taps = math.prod(rhs[i] for i in dn.rhs_spec[2:])
    in_ch = lhs[dn.lhs_spec[1]]
    # only feature groups shrink the per-output contraction (each output
    # channel sees in_ch/feature_groups inputs).  batch groups shrink the
    # OUTPUT batch dim instead — already reflected in prod(out) — so
    # dividing by batch_group_count double-counted the reduction.
    groups = p.get("feature_group_count", 1)
    dil = math.prod(p.get("lhs_dilation") or (1,))
    return math.prod(out) * taps * in_ch // groups // dil


def _count(jaxpr, report):
    macs = 0
    for eqn in jaxpr.eqns:
        name = eqn.primitive.name
        if name == "dot_general":
            macs += _dot_macs(eqn)
        elif name == "conv_general_dilated":
            macs += _conv_macs(eqn)
        elif name == "scan":
            macs += eqn.params["length"] * _count(
                _inner(eqn.params["jaxpr"]), report)
        elif name == "cond":
            macs += max((_count(_inner(b), report)
                         for b in eqn.params["branches"]), default=0)
        elif name == "while":
            report["while_underestimate"] = True
            macs += _count(_inner(eqn.params["body_jaxpr"]), report)
        else:
            # recurse into any sub-jaxpr (pjit, remat, custom_vjp, ...)
            for v in eqn.params.values():
                if _is_jaxpr(v):
                    macs += _count(_inner(v), report)
                elif isinstance(v, (tuple, list)):
                    macs += sum(_count(_inner(b), report)
                                for b in v if _is_jaxpr(b))
    return macs


def count_flops(fn, *args, **kwargs):
    """Trace ``fn(*args, **kwargs)`` (no execution) and return
    ``{"macs", "flops", ...}`` with flops = 2 x MACs over the matmul/conv
    primitives.  Tracing is cheap (no compile, no device) so this is
    safe to call at bench setup on full-size shapes."""
    import jax

    jaxpr = jax.make_jaxpr(fn)(*args, **kwargs)
    report = {}
    report["macs"] = _count(jaxpr.jaxpr, report)
    report["flops"] = 2 * report["macs"]
    return report
