"""Deterministic fault injection for the fault-tolerant runtime.

Parity intent: the reference has NO injection layer — its failure tests
kill Spark executors from the outside (test/test_TFCluster.py relies on
task retries).  Here failures are first-class: the supervision stack
(engine retry/respawn, cluster.run(restarts=N) recovery, heartbeat
liveness) is only trustworthy if every failure mode can be reproduced
deterministically, so the injection points live in the production code
paths and are driven entirely by environment variables — which makes
them *spawn-safe*: executor processes and their fork children inherit
the plan with no extra plumbing.

Plan grammar (``TFOS_FAULT_PLAN``)::

    plan  := entry ("," entry)*
    entry := site ":" kind ["(" arg ")"] ["@" hits]
    kind  := "exc" | "kill" | "hang" | "delay" | "nan"
    hits  := N      -- fire on exactly the N-th check of this site (1-based)
           | N "+"  -- fire on the N-th and every later check
           | "*"    -- fire on every check

``hits`` defaults to ``1``.  Kinds:

- ``exc``          raise :class:`FaultInjected`
- ``kill``         ``SIGKILL`` the calling process (an un-catchable crash,
                   the executor-loss case)
- ``hang(secs)``   sleep (default 3600s — "forever" at test scale); models
                   a wedged node that only heartbeat staleness can detect
- ``delay(secs)``  sleep briefly (default 1s) then continue; models slow,
                   not dead
- ``nan``          value poison: :func:`poison` returns NaN in place of the
                   value it was handed (a silent numeric corruption, the
                   diverged-training case).  Only :func:`poison` call sites
                   honor it — :func:`check` ignores ``nan`` entries, and the
                   two keep separate hit counters, so ``train.step:nan@5``
                   poisons exactly the 5th step regardless of how many
                   ``check`` kinds share the site.

Hit counters are **per process, per site**: a respawned executor or a
relaunched trainer starts from zero, which is exactly the semantics a
retry/restart test needs ("fail the first boot, succeed the second").

Scoping: ``TFOS_FAULT_EXECUTOR=<n>`` restricts firing to processes whose
``TFOS_EXECUTOR_INDEX`` equals ``n`` (fork children inherit the index),
so a plan can target one executor of a pool deterministically.

Every fault that fires emits a ``fault/injected`` telemetry event (and
flushes, so even a ``kill`` leaves its event on disk).
"""

from __future__ import annotations

import logging
import os
import random as _random
import signal
import time

from tensorflowonspark_tpu.utils import telemetry

logger = logging.getLogger(__name__)

PLAN_ENV = "TFOS_FAULT_PLAN"
EXECUTOR_ENV = "TFOS_FAULT_EXECUTOR"

KINDS = ("exc", "kill", "hang", "delay", "nan")

#: Injection points wired into the runtime (site -> where it fires).
SITES = (
    "engine.task",          # engine.py executor loop, before running a task
    "node.boot",            # node.py _mapfn, before the manager starts
    "node.main",            # node.py wrapper_fn, before user main_fun
    "train.step",           # utils/metrics.py TrainMetrics.step, per step
    "feed.put",             # node.py feeder, before each chunk put
    "feed.get",             # feed.py DataFeed, after each chunk pop
    "data.serve",           # data/service.py worker, before each unit
    "data.split_claim",     # data/service.py dynamic worker, after a claim
    "data.split_serve",     # data/service.py dynamic worker, per chunk
    "rendezvous.register",  # rendezvous.py Client.register
    "rendezvous.query",     # rendezvous.py Client.await_reservations polls
    "checkpoint.save",      # utils/checkpoint.py save paths
    "actor.spawn",          # actors/runtime.py member boot, before on_start
    "actor.receive",        # actors/runtime.py, before handling an envelope
    "actor.tick",           # actors/runtime.py idle tick, before on_tick
    "serve.dispatch",       # serving/replicas.py, before routing a request
    "serve.resize",         # serving/elastic.py, before a pool resize
    "serve.fabric_dispatch",  # serving/fabric/router.py, before a dispatch
    "serve.fabric_route",   # serving/fabric/router.py, affinity route pick
    "decode.step",          # serving/decode/scheduler.py engine loop body
    "deploy.canary",        # workloads/deploy_loop.py, before opening canary
    "deploy.promote",       # workloads/deploy_loop.py, before promote commit
    "deploy.rollback",      # workloads/deploy_loop.py, before rollback commit
)

#: Sites whose hit counters live in long-lived executor processes, so a
#: consumed occurrence stays consumed across engine retries — safe for
#: randomized chaos runs that must eventually make progress.  Trainer-side
#: sites (feed.get, node.main, checkpoint.save) restart their counters in
#: every relaunched fork child and would re-fire forever.
CHAOS_SITES = ("engine.task", "node.boot", "feed.put", "rendezvous.query")

#: Serving-tier counterpart for the elastic-pool chaos smoke: dispatch
#: faults surface as explicit client errors (batcher fails the batch),
#: resize faults are retried by the next supervisor tick, decode faults
#: fail the cohort and rebuild the caches — all recoverable, so a
#: randomized plan over these must leave the pool serving.
SERVE_CHAOS_SITES = ("serve.dispatch", "serve.resize", "decode.step")

#: Deployment-loop counterpart: canary/promote/rollback faults raise in
#: the promotion controller's decision path, which re-arms and retries on
#: the next pump — recoverable by construction, so a randomized plan over
#: these must leave the loop converging (and the pool serving).
DEPLOY_CHAOS_SITES = ("deploy.canary", "deploy.promote", "deploy.rollback")


class FaultInjected(RuntimeError):
    """An exception raised by an injected ``exc`` fault."""


class _Fault:
    __slots__ = ("site", "kind", "arg", "first", "last")

    def __init__(self, site, kind, arg, first, last):
        self.site = site
        self.kind = kind
        self.arg = arg
        self.first = first  # 1-based hit the fault starts firing on
        self.last = last    # last firing hit (None = open-ended)

    def matches(self, hit):
        if hit < self.first:
            return False
        return self.last is None or hit <= self.last

    def __repr__(self):
        hits = ("*" if (self.first, self.last) == (1, None)
                else f"{self.first}+" if self.last is None
                else str(self.first))
        arg = f"({self.arg:g})" if self.arg is not None else ""
        return f"{self.site}:{self.kind}{arg}@{hits}"


def parse_plan(plan):
    """``TFOS_FAULT_PLAN`` string -> list of :class:`_Fault`.

    Raises ``ValueError`` on malformed entries — a typo'd plan must fail
    loudly, not silently inject nothing.
    """
    faults = []
    for raw in str(plan or "").split(","):
        entry = raw.strip()
        if not entry:
            continue
        site, sep, rest = entry.partition(":")
        site = site.strip()
        if not sep or not site:
            raise ValueError(f"fault entry {entry!r}: expected site:kind")
        if site not in SITES:
            raise ValueError(
                f"fault entry {entry!r}: unknown site {site!r} "
                f"(valid: {', '.join(SITES)})")
        rest, _, hits_s = rest.partition("@")
        kind, arg = rest.strip(), None
        if "(" in kind:
            if not kind.endswith(")"):
                raise ValueError(f"fault entry {entry!r}: unclosed arg")
            kind, arg_s = kind[:-1].split("(", 1)
            try:
                arg = float(arg_s)
            except ValueError:
                raise ValueError(
                    f"fault entry {entry!r}: non-numeric arg {arg_s!r}"
                ) from None
        if kind not in KINDS:
            raise ValueError(
                f"fault entry {entry!r}: unknown kind {kind!r} "
                f"(valid: {', '.join(KINDS)})")
        hits_s = hits_s.strip() or "1"
        if hits_s == "*":
            first, last = 1, None
        elif hits_s.endswith("+"):
            first, last = int(hits_s[:-1]), None
        else:
            first = int(hits_s)
            last = first
        if first < 1:
            raise ValueError(f"fault entry {entry!r}: hits are 1-based")
        faults.append(_Fault(site, kind, arg, first, last))
    return faults


# Per-process parse cache + hit counters.  Keyed by pid: a fork child
# inherits the parent's dict but must count its own hits from zero.
_state = {"pid": None, "plan": None, "faults": (), "hits": {}}


def _faults_for_this_process():
    plan = os.environ.get(PLAN_ENV, "")
    if _state["pid"] != os.getpid() or _state["plan"] != plan:
        _state["pid"] = os.getpid()
        _state["plan"] = plan
        _state["hits"] = {}
        try:
            _state["faults"] = tuple(parse_plan(plan))
        except ValueError:
            logger.exception("invalid %s=%r; injecting nothing", PLAN_ENV, plan)
            _state["faults"] = ()
    return _state["faults"]


def _scoped_out():
    """True when TFOS_FAULT_EXECUTOR is set and this process (or its
    executor ancestor) is a different executor."""
    want = os.environ.get(EXECUTOR_ENV, "").strip()
    if not want:
        return False
    return os.environ.get("TFOS_EXECUTOR_INDEX", "").strip() != want


def check(site, **attrs):
    """Injection point: count a hit on ``site`` and fire any planned fault.

    Free when no plan is set (one env read + dict lookup).  Call it at
    the top of the operation it guards; ``attrs`` travel into the
    ``fault/injected`` telemetry event for the recovery timeline.
    """
    faults = _faults_for_this_process()
    if not faults:
        return
    # nan entries are value poison, consumed by poison() with its own
    # counter — a check at the same site must neither fire nor count them
    armed = [f for f in faults if f.site == site and f.kind != "nan"]
    if not armed or _scoped_out():
        return
    hit = _state["hits"].get(site, 0) + 1
    _state["hits"][site] = hit
    for f in armed:
        if not f.matches(hit):
            continue
        logger.warning("fault injection: %r firing at hit %d of %s (pid %d)",
                       f, hit, site, os.getpid())
        telemetry.event("fault/injected", site=site, kind=f.kind, hit=hit,
                        pid=os.getpid(), **attrs)
        if f.kind in ("kill", "hang"):
            # The victim's own black box: freeze the span ring NOW —
            # after the SIGKILL nothing of this process survives but
            # what is already on disk (obs/flight.py).
            try:
                from tensorflowonspark_tpu.obs import flight as _flight

                _flight.snapshot("fault/injected", node=None,
                                 reason=f"{f.kind}@{site} hit {hit}")
            except Exception:  # noqa: BLE001 - injection must still fire
                logger.debug("flight snapshot failed", exc_info=True)
        # a kill/hang never returns: the event must already be on disk
        telemetry.flush()
        if f.kind == "exc":
            raise FaultInjected(
                f"injected fault at {site} (hit {hit}, plan {f!r})")
        if f.kind == "kill":
            os.kill(os.getpid(), signal.SIGKILL)
            time.sleep(60)  # pending-signal window; never reached
        if f.kind == "hang":
            time.sleep(3600.0 if f.arg is None else f.arg)
            raise FaultInjected(
                f"injected hang at {site} expired (hit {hit}, plan {f!r})")
        if f.kind == "delay":
            time.sleep(1.0 if f.arg is None else f.arg)
        return


def poison(site, value):
    """Value-poison injection point: return ``value``, or ``float('nan')``
    when a planned ``nan`` fault fires on this hit.

    The counterpart of :func:`check` for corruptions that travel *through*
    a value instead of control flow — the health watchtower's NaN-gate
    e2e seeds ``train.step:nan@N`` and the N-th recorded loss goes NaN
    deterministically.  Hits are counted per process per site under a
    separate ``nan`` counter (see the module docstring), and the firing
    leaves the same ``fault/injected`` event as every other kind."""
    faults = _faults_for_this_process()
    if not faults:
        return value
    armed = [f for f in faults if f.site == site and f.kind == "nan"]
    if not armed or _scoped_out():
        return value
    key = site + "#nan"
    hit = _state["hits"].get(key, 0) + 1
    _state["hits"][key] = hit
    for f in armed:
        if not f.matches(hit):
            continue
        logger.warning("fault injection: %r poisoning hit %d of %s (pid %d)",
                       f, hit, site, os.getpid())
        telemetry.event("fault/injected", site=site, kind="nan", hit=hit,
                        pid=os.getpid())
        return float("nan")
    return value


def random_plan(seed, max_faults=2, sites=CHAOS_SITES):
    """A reproducible chaos plan: same seed, same plan, always parseable.

    Restricted to :data:`CHAOS_SITES` by default (see its docstring) and
    to ``exc`` faults — ``kill``/``hang`` scenarios are exercised by the
    deterministic tests; the chaos smoke's job is breadth under the
    retry/restart machinery, and it must terminate.
    """
    rng = _random.Random(int(seed))
    n = rng.randint(1, max_faults)
    entries = []
    for _ in range(n):
        site = rng.choice(list(sites))
        hit = rng.randint(1, 3)
        entries.append(f"{site}:exc@{hit}")
    plan = ",".join(entries)
    parse_plan(plan)  # a generator bug must fail here, not mid-chaos-run
    return plan


def _reset_for_tests():
    """Forget cached plan + hit counters (unit tests only)."""
    _state.update(pid=None, plan=None, faults=(), hits={})
