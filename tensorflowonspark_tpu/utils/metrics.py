"""Step-time / throughput / MFU / infeed-stall counters.

The reference has no metrics at all (SURVEY.md §5 "Observability = log
lines"); the ≥50% MFU north star needs them.  One lightweight
``TrainMetrics`` aggregator per worker: time steps with ``step()``,
account feed-wait with ``infeed_wait()`` (DataFeed calls this
internally when handed a metrics object), read a structured summary with
``report()``.

MFU convention: model FLOPs per step / (step time x peak FLOPs), peak
resolved from the device kind like bench.py.  FLOPs estimators for the
zoo's families are provided (6ND for transformers, 2 x MACs for convs is
the caller's number).
"""

from __future__ import annotations

import functools
import logging
import os
import time

from tensorflowonspark_tpu.utils import faults, metrics_registry, telemetry

logger = logging.getLogger(__name__)

# bf16 peak FLOP/s per chip by device-kind substring (same table as bench.py)
PEAK_FLOPS = {
    "v5 lite": 197e12,
    "v5e": 197e12,
    "v4": 275e12,
    "v5p": 459e12,
    "v6": 918e12,
}


def peak_flops(device=None):
    env = os.environ.get("TFOS_PEAK_FLOPS")
    if env:
        return float(env)
    if device is None:
        import jax

        device = jax.devices()[0]
    kind = getattr(device, "device_kind", "").lower()
    for k, v in PEAK_FLOPS.items():
        if k in kind:
            return v
    return None  # unknown (CPU): MFU not reported


def transformer_flops_per_token(cfg, causal=False):
    """~6N FLOPs/token (fwd+bwd) + attention term, from the config.

    Default is the PaLM appendix-B convention: the attention matmuls are
    counted dense (12·L·d·S per token) even for causal models — the
    convention most published MFU numbers use.  ``causal=True`` halves
    the attention term to count only the algorithmically required work,
    the honest denominator for kernels that skip the non-causal half
    (e.g. the pallas flash path with causal block skipping)."""
    n_params = (
        cfg.vocab_size * cfg.dim * 2
        + cfg.n_layers * (cfg.dim * cfg.dim * 4 + cfg.dim * cfg.dim * cfg.mlp_ratio * 2)
    )
    attn = 12 * cfg.n_layers * cfg.dim * cfg.max_seq  # 2*2*3 * L * d * S
    if causal:
        attn //= 2
    return 6 * n_params + attn


def segmentation_flops_per_image(image_size=256, num_classes=21, width=1.0):
    """Forward-pass FLOPs per image for models/segmentation.py, counted
    shape-exactly from the traced program (utils.flops walks the jaxpr;
    2 FLOPs/MAC, transposed-conv zero positions excluded).  Multiply by
    3 for the train step like resnet.flops_per_image's callers.  Tracing
    is abstract (eval_shape) — no device compute, safe pre-backend."""
    return _seg_flops_cached(int(image_size), int(num_classes), float(width))


@functools.lru_cache(maxsize=8)
def _seg_flops_cached(image_size, num_classes, width):
    import jax

    from tensorflowonspark_tpu.models import segmentation
    from tensorflowonspark_tpu.utils import flops as F

    ps, ss = jax.eval_shape(
        lambda k: segmentation.init(k, num_classes=num_classes, width=width),
        jax.random.PRNGKey(0))
    img = jax.ShapeDtypeStruct((1, image_size, image_size, 3), "float32")
    return F.count_flops(
        lambda p, s, x: segmentation.apply(p, s, x, train=True)[0],
        ps, ss, img)["flops"]


@functools.lru_cache(maxsize=1)
def mnist_inference_flops_per_row():
    """Forward-pass FLOPs per row for the MNIST export model that
    BASELINE config #5 (batch inference) serves — the jittable core
    ``mnist.apply``, counted like segmentation_flops_per_image."""
    import jax

    from tensorflowonspark_tpu.models import mnist
    from tensorflowonspark_tpu.utils import flops as F

    params = jax.eval_shape(mnist.init_params, jax.random.PRNGKey(0))
    x = jax.ShapeDtypeStruct((1, 28, 28, 1), "float32")
    return F.count_flops(mnist.apply, params, x)["flops"]


class TrainMetrics:
    """Windowed counters; cheap enough for the hot loop.

    Also the feed point of the training-health watchtower
    (``obs/health.py``): by default a :class:`~.health.HealthMonitor`
    rides along (``TFOS_HEALTH=0`` disables it; pass ``health=False`` to
    opt one instance out, or your own monitor to wire a ``checkpoint_fn``
    for the ``TFOS_HEALTH_ACTION`` reactions) and every ``step()`` hands
    it the step duration, the infeed-stall fraction, and — when the
    caller supplies them — the loss and the device-computed grad-norm
    probe (``utils.train.health_probe``)."""

    def __init__(self, flops_per_item=None, device=None, window=50,
                 health=None):
        self.flops_per_item = flops_per_item
        self.window = window
        self._peak = peak_flops(device) if flops_per_item else None
        if health is None:
            from tensorflowonspark_tpu.obs import health as _health

            self.health = _health.monitor_from_env()
        else:
            self.health = health or None  # health=False opts out
        self.reset()

    def reset(self):
        self.steps = 0
        self.items = 0
        self.step_time = 0.0
        self.infeed_time = 0.0
        self._last = None

    # -- recording ----------------------------------------------------------

    def infeed_wait(self, seconds):
        self.infeed_time += seconds

    def step(self, items=0, loss=None, grad_norm=None, grad_finite=None):
        """Call once per completed train step with the item count.

        The first call only arms the timer; its items are NOT counted, so
        rates divide N timed steps' items by N timed steps' time.

        ``loss`` (optional) feeds the health monitor's NaN gate and
        loss-spike detector — pass the step's scalar loss (the float()
        here is the same value fetch the timing convention already
        requires, PERF.md r4).  ``grad_norm``/``grad_finite`` forward
        the ``utils.train.health_probe`` outputs.  A configured
        ``TFOS_HEALTH_ACTION=halt`` propagates :class:`HealthHalt` out
        of this call on a numeric anomaly."""
        # injection point: ``train.step`` — check() serves delay/exc
        # (seeded stragglers), poison() the deterministic NaN e2e.  Both
        # sit before the clock read so an injected delay lands in this
        # step's measured duration like a real slowdown would.
        faults.check("train.step")
        if loss is not None:
            loss = faults.poison("train.step", loss)
        now = time.perf_counter()
        dur = None
        if self._last is not None:
            dur = now - self._last
            self.step_time += dur
            self.items += items
            if telemetry.enabled():
                # same measured duration as the counter above, so the
                # trace-merge percentiles and report() agree exactly
                attrs = {"items": items}
                if self.flops_per_item:
                    attrs["flops_per_item"] = self.flops_per_item
                if self._peak:
                    attrs["peak_flops"] = self._peak
                telemetry.record_span("train/step", dur, **attrs)
            if metrics_registry.enabled():
                # live plane: the same windowed numbers report() derives,
                # published mid-run by obs/publish.py
                metrics_registry.inc("tfos_train_steps_total")
                metrics_registry.observe("tfos_train_step_ms", dur * 1000.0)
                if self.step_time:
                    metrics_registry.set_gauge(
                        "tfos_train_items_per_sec",
                        self.items / self.step_time)
                    metrics_registry.set_gauge(
                        "tfos_train_infeed_stall_frac",
                        min(self.infeed_time / self.step_time, 1.0))
                    if self.flops_per_item and self._peak:
                        metrics_registry.set_gauge(
                            "tfos_train_mfu",
                            self.items * self.flops_per_item
                            / self.step_time / self._peak)
        self._last = now
        self.steps += 1
        if self.health is not None:
            self.health.observe_step(
                loss=None if loss is None else float(loss),
                step_time_s=dur,
                infeed_frac=(min(self.infeed_time / self.step_time, 1.0)
                             if self.step_time else None),
                grad_norm=(None if grad_norm is None else float(grad_norm)),
                grad_finite=(None if grad_finite is None
                             else bool(grad_finite)),
                step=self.steps)

    # -- reading ------------------------------------------------------------

    def report(self):
        """Summary dict over the window since reset(); rates need >=2
        step() calls (the first call only arms the timer)."""
        out = {
            "steps": self.steps,
            "items": self.items,
            "step_time_avg_s": self.step_time / max(self.steps - 1, 1),
            "infeed_wait_s": self.infeed_time,
            "infeed_stall_frac": (
                self.infeed_time / self.step_time if self.step_time else 0.0
            ),
        }
        if self.step_time:
            out["items_per_sec"] = self.items / self.step_time
            if self.flops_per_item and self._peak:
                out["mfu"] = (
                    self.items * self.flops_per_item
                    / self.step_time / self._peak
                )
        return out

    def maybe_log(self, prefix=""):
        if self.steps and self.steps % self.window == 0:
            logger.info("%smetrics: %s", prefix, self.report())
