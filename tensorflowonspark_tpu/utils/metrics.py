"""Step-time / throughput / MFU / infeed-stall counters.

The reference has no metrics at all (SURVEY.md §5 "Observability = log
lines"); the ≥50% MFU north star needs them.  One lightweight
``TrainMetrics`` aggregator per worker: time steps with ``step()``,
account feed-wait with ``infeed_wait()`` (DataFeed calls this
internally when handed a metrics object), read a structured summary with
``report()``.

MFU convention: model FLOPs per step / (step time x peak FLOPs), peak
resolved from the device kind like bench.py.  FLOPs estimators for the
zoo's families are provided (6ND for transformers, 2 x MACs for convs is
the caller's number).
"""

from __future__ import annotations

import logging
import os
import time

logger = logging.getLogger(__name__)

# bf16 peak FLOP/s per chip by device-kind substring (same table as bench.py)
PEAK_FLOPS = {
    "v5 lite": 197e12,
    "v5e": 197e12,
    "v4": 275e12,
    "v5p": 459e12,
    "v6": 918e12,
}


def peak_flops(device=None):
    env = os.environ.get("TFOS_PEAK_FLOPS")
    if env:
        return float(env)
    if device is None:
        import jax

        device = jax.devices()[0]
    kind = getattr(device, "device_kind", "").lower()
    for k, v in PEAK_FLOPS.items():
        if k in kind:
            return v
    return None  # unknown (CPU): MFU not reported


def transformer_flops_per_token(cfg, causal=False):
    """~6N FLOPs/token (fwd+bwd) + attention term, from the config.

    Default is the PaLM appendix-B convention: the attention matmuls are
    counted dense (12·L·d·S per token) even for causal models — the
    convention most published MFU numbers use.  ``causal=True`` halves
    the attention term to count only the algorithmically required work,
    the honest denominator for kernels that skip the non-causal half
    (e.g. the pallas flash path with causal block skipping)."""
    n_params = (
        cfg.vocab_size * cfg.dim * 2
        + cfg.n_layers * (cfg.dim * cfg.dim * 4 + cfg.dim * cfg.dim * cfg.mlp_ratio * 2)
    )
    attn = 12 * cfg.n_layers * cfg.dim * cfg.max_seq  # 2*2*3 * L * d * S
    if causal:
        attn //= 2
    return 6 * n_params + attn


class TrainMetrics:
    """Windowed counters; cheap enough for the hot loop."""

    def __init__(self, flops_per_item=None, device=None, window=50):
        self.flops_per_item = flops_per_item
        self.window = window
        self._peak = peak_flops(device) if flops_per_item else None
        self.reset()

    def reset(self):
        self.steps = 0
        self.items = 0
        self.step_time = 0.0
        self.infeed_time = 0.0
        self._last = None

    # -- recording ----------------------------------------------------------

    def infeed_wait(self, seconds):
        self.infeed_time += seconds

    def step(self, items=0):
        """Call once per completed train step with the item count.

        The first call only arms the timer; its items are NOT counted, so
        rates divide N timed steps' items by N timed steps' time."""
        now = time.perf_counter()
        if self._last is not None:
            self.step_time += now - self._last
            self.items += items
        self._last = now
        self.steps += 1

    # -- reading ------------------------------------------------------------

    def report(self):
        """Summary dict over the window since reset(); rates need >=2
        step() calls (the first call only arms the timer)."""
        out = {
            "steps": self.steps,
            "items": self.items,
            "step_time_avg_s": self.step_time / max(self.steps - 1, 1),
            "infeed_wait_s": self.infeed_time,
            "infeed_stall_frac": (
                self.infeed_time / self.step_time if self.step_time else 0.0
            ),
        }
        if self.step_time:
            out["items_per_sec"] = self.items / self.step_time
            if self.flops_per_item and self._peak:
                out["mfu"] = (
                    self.items * self.flops_per_item
                    / self.step_time / self._peak
                )
        return out

    def maybe_log(self, prefix=""):
        if self.steps and self.steps % self.window == 0:
            logger.info("%smetrics: %s", prefix, self.report())
