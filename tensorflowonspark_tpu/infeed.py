"""Infeed pipelining: overlap host feed/conversion with device compute.

SURVEY.md §7 step 10's perf work ("infeed pipelining, double-buffering,
per-host sharded feeding"): the naive InputMode.SPARK loop is
  next_batch (host) -> np.stack (host) -> device_put -> step (device)
with the device idle during the host phases.  ``prefetch_to_device``
runs those host phases on a background thread ``depth`` batches ahead,
so the accelerator consumes batch t while t+1..t+depth are already
staged in HBM — the TPU-native analogue of the reference's
tf.data prefetch between DataFeed and model.fit
(examples/mnist/keras/mnist_spark.py:33-66).
"""

from __future__ import annotations

import logging
import queue as _queue
import threading

logger = logging.getLogger(__name__)

_END = object()


def batch_iterator(feed, batch_size, collate=None, min_batch=None):
    """DataFeed -> iterator of collated host batches.

    ``collate(records) -> pytree of np arrays`` (default: identity);
    short tails below ``min_batch`` (default: batch_size) are dropped,
    matching the examples' skip-short-batch convention so SPMD steps
    always see full shapes (no recompilation, no ragged collectives).
    """
    min_batch = batch_size if min_batch is None else min_batch
    while not feed.should_stop():
        records = feed.next_batch(batch_size)
        n = len(next(iter(records.values()))) if isinstance(records, dict) \
            else len(records)
        if n < min_batch:
            continue
        yield collate(records) if collate is not None else records


def prefetch_to_device(it, depth=2, placement=None):
    """Stage ``it``'s batches onto devices ``depth`` ahead.

    placement: None (default device_put), a Sharding, or a callable
    pytree->pytree (e.g. ``lambda b: local_to_global(mesh, b)`` for
    multi-host global arrays).  Exceptions on the worker thread re-raise
    at the consuming iteration.
    """
    import jax

    if placement is None or not callable(placement):
        sharding = placement

        def place(batch):
            return jax.device_put(batch, sharding)
    else:
        place = placement

    q = _queue.Queue(maxsize=depth)

    def worker():
        try:
            for batch in it:
                q.put(place(batch))
        except Exception as e:  # noqa: BLE001 - forwarded to consumer
            q.put(("__prefetch_error__", e))
        finally:
            q.put(_END)

    t = threading.Thread(target=worker, daemon=True, name="tfos-prefetch")
    t.start()

    while True:
        item = q.get()
        if item is _END:
            return
        if isinstance(item, tuple) and len(item) == 2 \
                and item[0] == "__prefetch_error__":
            raise item[1]
        yield item


def device_feed(feed, batch_size, *, collate=None, depth=2, placement=None,
                min_batch=None):
    """The composed fast path: DataFeed -> collate -> double-buffered
    device staging.  Drop-in for the examples' while-loop:

        for batch in device_feed(ctx.get_data_feed(), per_proc,
                                 collate=my_collate,
                                 placement=lambda b: local_to_global(mesh, b)):
            params, ... = step_fn(params, ..., *batch)
    """
    return prefetch_to_device(
        batch_iterator(feed, batch_size, collate, min_batch),
        depth=depth,
        placement=placement,
    )
