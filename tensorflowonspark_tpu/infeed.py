"""Infeed pipelining: overlap host feed/conversion with device compute.

SURVEY.md §7 step 10's perf work ("infeed pipelining, double-buffering,
per-host sharded feeding"): the naive InputMode.SPARK loop is
  next_batch (host) -> np.stack (host) -> device_put -> step (device)
with the device idle during the host phases.  ``prefetch_to_device``
runs those host phases on a background thread ``depth`` batches ahead,
so the accelerator consumes batch t while t+1..t+depth are already
staged in HBM — the TPU-native analogue of the reference's
tf.data prefetch between DataFeed and model.fit
(examples/mnist/keras/mnist_spark.py:33-66).
"""

from __future__ import annotations

import logging
import queue as _queue
import threading
import time as _time

logger = logging.getLogger(__name__)

_END = object()


def batch_iterator(feed, batch_size, collate=None, min_batch=None,
                   columnar=False):
    """DataFeed -> iterator of collated host batches.

    ``collate(records) -> pytree of np arrays`` (default: identity);
    short tails below ``min_batch`` (default: batch_size) are dropped,
    matching the examples' skip-short-batch convention so SPMD steps
    always see full shapes (no recompilation, no ragged collectives).

    ``columnar=True`` pulls via ``feed.next_batch_columns`` — collate
    receives ``{tensor: dense ndarray[n, ...]}`` instead of per-tensor
    python lists, skipping the per-record loop + np.stack on the
    consumer hot path (requires the feed's input_mapping).
    """
    min_batch = batch_size if min_batch is None else min_batch
    pull = feed.next_batch_columns if columnar else feed.next_batch
    while not feed.should_stop():
        records = pull(batch_size)
        n = len(next(iter(records.values()))) if isinstance(records, dict) \
            else len(records)
        if n < min_batch:
            continue
        yield collate(records) if collate is not None else records


def prefetch_to_device(it, depth=2, placement=None, on_abandon=None):
    """Stage ``it``'s batches onto devices ``depth`` ahead.

    placement: None (default device_put), a Sharding, or a callable
    pytree->pytree (e.g. ``lambda b: local_to_global(mesh, b)`` for
    multi-host global arrays).  Exceptions on the worker thread re-raise
    at the consuming iteration.

    on_abandon: called once if the consumer abandons the stream while the
    worker is still running (early ``break`` / ``close()``) — its job is
    to make the source iterator return promptly (device_feed passes the
    DataFeed's ``poison``).  Without it, a worker blocked in the source
    cannot be interrupted and is left as a daemon.
    """
    import jax

    if placement is None or not callable(placement):
        sharding = placement

        def place(batch):
            return jax.device_put(batch, sharding)
    else:
        place = placement

    q = _queue.Queue(maxsize=depth)
    cancelled = threading.Event()

    def worker():
        try:
            for batch in it:
                # check before place(): a cancelled worker must not stage
                # one more batch into HBM just for the drain to discard it
                if cancelled.is_set():
                    break
                staged = place(batch)
                # re-check after place(): the consumer may have abandoned
                # the stream during a long transfer — dropping the local
                # reference frees the device buffer, whereas enqueueing it
                # into the abandoned queue would pin HBM indefinitely
                if cancelled.is_set():
                    del staged
                    break
                q.put(staged)
        except Exception as e:  # noqa: BLE001 - forwarded to consumer
            q.put(("__prefetch_error__", e))
        finally:
            q.put(_END)

    t = threading.Thread(target=worker, daemon=True, name="tfos-prefetch")
    t.start()

    finished = False
    try:
        while True:
            item = q.get()
            if item is _END:
                finished = True
                return
            if isinstance(item, tuple) and len(item) == 2 \
                    and item[0] == "__prefetch_error__":
                raise item[1]
            yield item
    finally:
        cancelled.set()
        if not finished:
            # abandoned mid-stream (or error raised): ask the source to
            # unblock, release a worker blocked on the full queue, and
            # drop staged batches so they don't pin device memory
            if on_abandon is not None:
                try:
                    on_abandon()
                except Exception:  # noqa: BLE001 - cleanup must not mask
                    logger.exception("prefetch on_abandon hook failed")
            deadline = _time.monotonic() + 3
            idle_polls = 0
            while _time.monotonic() < deadline:
                try:
                    item = q.get(timeout=0.2)
                except _queue.Empty:
                    if not t.is_alive():
                        break
                    # a live-but-idle worker is blocked in the source and
                    # will never produce once cancelled: stop burning time.
                    # With an on_abandon hook give the source one extra
                    # poll to unblock (poison slices are not instant), but
                    # never pay the full drain deadline on an idle worker —
                    # the join + daemon warning below covers a stuck one
                    idle_polls += 1
                    if idle_polls >= (3 if on_abandon is not None else 2):
                        break
                    continue
                idle_polls = 0
                if item is _END:
                    break
        t.join(timeout=2)
        if t.is_alive():
            logger.warning("prefetch worker did not exit (blocked in the "
                           "source iterator or mid-transfer); left as daemon")
        # final sweep: drop anything enqueued between the drain loop's
        # last poll and the worker's exit so it doesn't pin device memory
        while True:
            try:
                q.get_nowait()
            except _queue.Empty:
                break


def synchronized(it, feed=None):
    """Yield from ``it`` only while EVERY process still has a next item.

    The principled global-stop for ragged end-of-feed tails under
    synchronous collectives (SURVEY.md §7 hard parts): after end-of-feed,
    workers are left with DIFFERENT numbers of residual full batches, and
    a worker stepping one extra time would strand its peers' all-reduce —
    the reference's workaround was "train only 90% of the steps"
    (reference examples/mnist/keras/mnist_spark.py:58-66).  Here every
    process all-gathers a has-data flag before stepping, so all processes
    stop on exactly the same step.  The exchange is once per item,
    unconditionally — amortizing it would reintroduce the hang it
    prevents (a process that runs dry mid-window cannot participate in
    peers' device collectives).

    Pass ``feed`` (the DataFeed backing ``it``) so a process stopped
    with local batches remaining drains them (``feed.terminate()``),
    keeping the feeder-side consumption protocol intact.

    Scope: this aligns the *end-of-feed* tail — the signal that a feed is
    dry is its end-of-feed marker.  A worker starved MID-train (its
    partitions exhausted while peers keep receiving data, beyond what the
    prefetch/ring buffers absorb) blocks waiting for data before it can
    reach the flag exchange; keep per-worker record counts roughly
    balanced during feeding, as the engine's partitioning does (and as
    the reference equally required).

    Single-process: a plain passthrough with zero collectives.
    """
    import jax

    if jax.process_count() <= 1:
        yield from it
        return

    import numpy as np
    from jax.experimental import multihost_utils

    while True:
        item = next(it, None)
        mine = item is not None
        flags = multihost_utils.process_allgather(np.asarray(mine))
        if not bool(np.asarray(flags).all()):
            if mine:
                logger.info(
                    "synchronized: a peer's feed ended; draining local "
                    "remainder"
                )
                if feed is not None:
                    feed.terminate()  # unblocks + ends the batch stream
                close = getattr(it, "close", None)
                if close is not None:
                    close()  # reap the prefetch thread + staged batches
            return
        yield item


def tfrecord_device_feed(source, batch_size, *, collate=None, depth=2,
                         placement=None, drop_remainder=True):
    """InputMode.TENSORFLOW fast path: stream TFRecord shards as dense
    column batches (``dfutil.iter_tfrecords_columnar`` — one shard
    resident at a time) straight into double-buffered device staging.

        for x, y in tfrecord_device_feed(files, per_proc,
                                         collate=my_collate):
            params, ... = step_fn(params, ..., x, y)

    ``collate({name: column_batch}) -> pytree`` (default: the dict as
    is); ``drop_remainder`` defaults True so SPMD steps always see full
    shapes.  ``source`` is a dir, file, or this worker's shard subset.
    """
    from tensorflowonspark_tpu import dfutil
    from tensorflowonspark_tpu.utils import telemetry

    it = dfutil.iter_tfrecords_columnar(source, batch_size,
                                        drop_remainder=drop_remainder)
    if telemetry.enabled():
        # per-batch data/stage spans (stage tfrecord_read): decode/IO
        # cost of this hot path lands in trace_merge's -- data -- stall
        # table next to the pipeline stages (docs/data.md)
        from tensorflowonspark_tpu.data.pipeline import _instrumented

        it = _instrumented("tfrecord_read", it)
    if collate is not None:
        it = map(collate, it)
    return prefetch_to_device(it, depth=depth, placement=placement)


def device_feed(feed, batch_size, *, collate=None, depth=2, placement=None,
                min_batch=None, columnar=False):
    """The composed fast path: DataFeed -> collate -> double-buffered
    device staging.  Drop-in for the examples' while-loop:

        for batch in device_feed(ctx.get_data_feed(), per_proc,
                                 collate=my_collate,
                                 placement=lambda b: local_to_global(mesh, b)):
            params, ... = step_fn(params, ..., *batch)

    ``columnar=True``: collate sees dense per-tensor arrays (see
    ``batch_iterator``) — the preferred consumer for columnar feeds.
    """
    return prefetch_to_device(
        batch_iterator(feed, batch_size, collate, min_batch, columnar),
        depth=depth,
        placement=placement,
        # abandoning the stream (early break / close) poisons the feed so
        # the prefetch worker exits instead of polling the ring forever;
        # call feed.terminate() afterwards for the producer-drain handshake
        on_abandon=getattr(feed, "poison", None),
    )
