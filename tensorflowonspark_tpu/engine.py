"""Execution-engine substrate: the scheduler the framework federates.

The reference federates Apache Spark: executors are long-lived *processes*,
tasks are serialized closures shipped to them, and data is partitioned
RDDs (SURVEY.md §1 L1).  This module provides the same substrate contract
behind a small interface so the rest of the framework is
scheduler-agnostic:

- ``LocalEngine`` — a built-in multi-process executor pool.  This is both
  the test fixture (parity: reference test/run_tests.sh's 2-worker local
  Spark Standalone cluster — "Local mode is explicitly insufficient;
  executors must be separate processes", test/README.md:10) and a real
  single-host runtime for TPU VMs without a Spark installation.
- ``SparkEngine`` — a thin adapter over a live ``pyspark.SparkContext``
  (import-gated; pyspark is optional).

Engine contract used by cluster.py / node.py:
  ``parallelize(seq, n)`` → Dataset with ``foreach_partition`` /
  ``map_partitions`` / ``collect`` / ``union`` / ``num_partitions``;
  ``cancel_all_jobs()``; ``default_fs``; ``num_executors``.

Scheduling model of ``LocalEngine`` (matches how Spark behaves under the
reference's usage):

- Node-placement jobs run ``spread=True``: task *i* goes to executor *i*'s
  private inbox — one node per executor, like ``nodeRDD =
  sc.parallelize(range(N), N)`` spreading over N single-slot workers.
- Data/feeder jobs go to a shared work-stealing queue: only executors
  whose slot is free pull them.  A ps/evaluator node task that blocks its
  slot (reference TFSparkNode.py:411-438) therefore never receives feeder
  partitions — exactly the emergent Spark behavior the reference relies
  on.
"""

from __future__ import annotations

import atexit
import contextlib
import logging
import os
import queue as _queue
import shutil
import tempfile
import threading
import time
import traceback
import multiprocessing as mp

import cloudpickle

from tensorflowonspark_tpu.actors import supervise as _supervise
from tensorflowonspark_tpu.utils import faults, metrics_registry, telemetry

logger = logging.getLogger(__name__)


class TaskError(RuntimeError):
    """A task raised on an executor; carries the remote traceback."""


class ResultPumpError(TaskError):
    """The result transport itself failed (corrupt stream, undeliverable
    payload) — not attributable to any one task's user code."""


def _row_bytes(row, _depth=0):
    """Approximate in-memory payload size of one row (bytes/ndarray-aware,
    two levels deep into containers — enough for (image, label) tuples and
    feature dicts without walking arbitrary object graphs)."""
    if isinstance(row, (bytes, bytearray, str)):
        return len(row)
    nbytes = getattr(row, "nbytes", None)
    if nbytes is not None:
        return int(nbytes)
    if _depth < 2 and isinstance(row, (list, tuple, dict)):
        vals = row.values() if isinstance(row, dict) else row
        return 64 + sum(_row_bytes(v, _depth + 1) for v in vals)
    import sys

    try:
        return sys.getsizeof(row)
    except TypeError:
        return 64


def _approx_bytes(rows, sample=200):
    """Estimated total payload bytes of ``rows`` from a strided sample."""
    if not rows:
        return 0
    k = min(sample, len(rows))
    stride = len(rows) // k
    sampled = sum(_row_bytes(rows[i * stride]) for i in range(k))
    return int(sampled * len(rows) / k)


def _fmt_bytes(n):
    for unit in ("B", "KiB", "MiB", "GiB"):
        if n < 1024 or unit == "GiB":
            return f"{n:.1f} {unit}" if unit != "B" else f"{n} B"
        n /= 1024
    return f"{n:.1f} GiB"


# ----------------------------------------------------------------------------
# Executor worker process
# ----------------------------------------------------------------------------

def _executor_main(index, workdir, shared_inbox, own_inbox, results):
    """Executor process loop: pull a task, run it, report the result."""
    os.chdir(workdir)
    os.environ["TFOS_EXECUTOR_INDEX"] = str(index)
    # Executors are never the driver: shed any inherited driver telemetry
    # identity so a node task can label this process for its cluster.
    os.environ.pop(telemetry.NODE_ENV, None)
    os.environ[telemetry.ROLE_ENV] = "executor"
    try:
        while True:
            msg = None
            # Prefer directly-assigned tasks; otherwise steal from the pool.
            try:
                msg = own_inbox.get(timeout=0.02)
            except _queue.Empty:
                try:
                    msg = shared_inbox.get(timeout=0.02)
                except _queue.Empty:
                    continue
            if msg[0] == "stop":
                break
            _, job_id, task_id, blob = msg
            # Start-ack BEFORE execution: the driver uses it to know which
            # tasks were in flight on an executor that dies, so exactly
            # those can be re-dispatched after a respawn.
            results.put(("start", job_id, task_id, index, None))
            # The feeder closures recover their partition number from this
            # (engine analogue of pyspark TaskContext.partitionId()).
            os.environ["TFOS_PARTITION_INDEX"] = str(task_id)
            try:
                faults.check("engine.task", job=job_id, task=task_id)
                fn, items, collect, trace = _unpack_task(blob)
                # Export the dispatcher's trace context on the env
                # channel for the task's lifetime so processes the task
                # forks/spawns (trainers, feeders) inherit it.
                prev_trace = os.environ.get(telemetry.TRACE_ENV)
                if trace is not None:
                    os.environ[telemetry.TRACE_ENV] = str(trace)
                try:
                    with telemetry.activate(trace), \
                            telemetry.span("engine/task", job=job_id,
                                           task=task_id):
                        out = fn(iter(items))
                        result = (list(out)
                                  if (collect and out is not None)
                                  else None)
                finally:
                    if trace is not None:
                        if prev_trace is None:
                            os.environ.pop(telemetry.TRACE_ENV, None)
                        else:
                            os.environ[telemetry.TRACE_ENV] = prev_trace
                # Serialize the payload HERE: an unpicklable result then
                # fails only this task (below) instead of poisoning the
                # shared results pipe for every in-flight job.
                payload = (None if result is None
                           else cloudpickle.dumps(result))
                results.put(("ok", job_id, task_id, index, payload))
            except BaseException:  # noqa: BLE001 - must report any task failure
                results.put(("error", job_id, task_id, index, traceback.format_exc()))
    finally:
        telemetry.flush()
        _reap_executor_children()


def _unpack_task(blob):
    """Unpack a task blob: ``(fn, items, collect)`` plus an optional
    trailing traceparent header (older 3-tuple blobs — e.g. kept for
    byte-identical retry re-dispatch — stay valid)."""
    parts = cloudpickle.loads(blob)
    trace = parts[3] if len(parts) > 3 else None
    return parts[0], parts[1], parts[2], trace


def _reap_executor_children():
    """Terminate and collect every live child of this executor before the
    interpreter exits.  A background trainer left behind by a crashed run
    would otherwise (a) block multiprocessing's atexit join forever (it is
    non-daemonic) and (b) hold the resource-tracker pipe open, wedging the
    *driver* interpreter's exit too."""
    for child in mp.active_children():
        try:
            child.terminate()
            child.join(timeout=3)
            if child.is_alive():
                child.kill()
                child.join(timeout=2)
        except (OSError, ValueError, AssertionError):
            pass


# ----------------------------------------------------------------------------
# Dataset (RDD parity surface)
# ----------------------------------------------------------------------------

def _compose(parent_fn, fn):
    if parent_fn is None:
        return fn

    def composed(it, _pf=parent_fn, _f=fn):
        return _f(iter(list(_pf(it))))

    return composed


class LocalDataset:
    """Partitioned dataset with a lazy map_partitions lineage (RDD parity).

    Internally a dataset resolves to *tasks*: one ``(items, fn|None)``
    pair per partition, so unions of differently-derived datasets (e.g.
    the epoch-union of a column projection, TFCluster.train parity) keep
    each branch's transform chain."""

    def __init__(self, engine, partitions, lineage=None, tasks=None):
        self._engine = engine
        self._partitions = partitions  # list[list] or None when derived
        self._lineage = lineage        # (parent: LocalDataset, fn)
        self._tasks_cache = tasks      # list[(items, fn|None)] (union result)

    # -- lineage resolution ---------------------------------------------------
    def _tasks(self):
        """Resolve to per-partition (items, composed_fn|None) tasks."""
        if self._tasks_cache is not None:
            return list(self._tasks_cache)
        if self._lineage is None:
            return [(p, None) for p in self._partitions]
        parent, fn = self._lineage
        return [(items, _compose(pfn, fn)) for items, pfn in parent._tasks()]

    # -- RDD-like API ---------------------------------------------------------
    @property
    def num_partitions(self):
        return len(self._tasks())

    def map_partitions(self, fn):
        return LocalDataset(self._engine, None, lineage=(self, fn))

    def foreach_partition(self, fn, spread=False, placement=None,
                          retryable=False, max_retries=None):
        """Run fn over partitions.  ``placement`` pins task i to executor
        placement[i] (used so shutdown signals reach the executor that owns
        each node's manager — Spark gets this from locality).

        ``retryable=True`` declares every task idempotent: a failed task
        is retried with exponential backoff (budget ``max_retries``,
        default TFOS_TASK_RETRIES) and a dead executor is respawned with
        its lost tasks re-dispatched, instead of failing the job.  Only
        the node-placement and feeder closures qualify — arbitrary user
        jobs keep fail-fast semantics."""

        def run(fn_, chain):
            def _run(it, _c=chain, _f=fn_):
                _f(iter(list(_c(it))) if _c is not None else it)
                return None

            return _run

        tasks = [(items, run(fn, chain)) for items, chain in self._tasks()]
        self._engine._run_job(tasks, collect=False, spread=spread,
                              placement=placement, retryable=retryable,
                              max_retries=max_retries)

    def collect(self, spread=False, retryable=False, max_retries=None):
        """Materialize all partitions.  ``spread=True`` pins task i to
        executor i (one concurrent task per slot — the barrier-execution
        guarantee TFParallel-style jobs need).  ``retryable`` as in
        :meth:`foreach_partition` — only for idempotent lineages."""
        tasks = [
            (items, chain if chain is not None else (lambda it: list(it)))
            for items, chain in self._tasks()
        ]
        parts = self._engine._run_job(
            tasks, collect=True, spread=spread, placement=None,
            retryable=retryable, max_retries=max_retries
        )
        out = []
        for p in parts:
            out.extend(p or [])
        return out

    def union(self, *others):
        tasks = self._tasks()
        for o in others:
            tasks.extend(o._tasks())
        return LocalDataset(self._engine, None, tasks=tasks)

    def repartition(self, num_partitions):
        """Rebalance into ``num_partitions`` round-robin partitions (RDD
        ``repartition`` parity).  Needed when a feed source has fewer
        partitions than executors — InputMode.SPARK feeds one partition
        per feeder task, so a starved worker would trigger the
        synchronized global-stop at step 0.

        Local engine: MATERIALIZES the whole dataset through the driver
        (executor tasks still run the lineage), so the byte volume is
        measured and logged — a dataset that was too big per partition
        will collapse driver memory here.  For TFRecord sources use
        ``dfutil.load_tfrecords(..., min_partitions=N)`` instead: it
        stripes the shard FILES across partitions with no driver
        materialization.  Production-scale data should be written with
        >= num_executors shards in the first place."""
        rows = self.collect()
        n = max(1, min(num_partitions, max(len(rows), 1)))
        approx = _approx_bytes(rows)
        msg = ("repartition(%d) materialized %d rows (~%s) through the "
               "driver")
        if approx > 256 * 1024 * 1024:
            logger.warning(
                msg + " — for TFRecords use load_tfrecords(..., "
                "min_partitions=N) to stripe shards without driver "
                "materialization", n, len(rows), _fmt_bytes(approx))
        else:
            logger.info(msg, n, len(rows), _fmt_bytes(approx))
        parts = [rows[i::n] for i in range(n)]
        return LocalDataset(self._engine, parts)


# ----------------------------------------------------------------------------
# Local engine
# ----------------------------------------------------------------------------

@contextlib.contextmanager
def _patched_env(env):
    """Apply env overrides around a spawn; a value of None removes the
    variable.  Restores os.environ on exit."""
    saved = {}
    for k, v in env.items():
        saved[k] = os.environ.get(k)
        if v is None:
            os.environ.pop(k, None)
        else:
            os.environ[k] = str(v)
    try:
        yield
    finally:
        for k, old in saved.items():
            if old is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = old


class LocalEngine:
    """Multi-process executor pool: the built-in scheduler substrate."""

    def __init__(self, num_executors, workdir=None, start_method="spawn", env=None):
        """``env``: environment overrides for executor processes (set at
        spawn time so they apply before the child interpreter boots —
        required for platform-selection vars like JAX_PLATFORMS).  A value
        of None removes the variable.  Construction briefly mutates
        os.environ, so construct engines from the driver main thread
        before launching other threads/subprocesses."""
        self.num_executors = int(num_executors)
        self._ctx = mp.get_context(start_method)
        self._env = dict(env) if env else {}
        self._root = workdir or tempfile.mkdtemp(prefix="tfos_engine_")
        self._owns_root = workdir is None
        self._shared_inbox = self._ctx.Queue()
        self._results = self._ctx.Queue()
        self._own_inboxes = []
        self._procs = []
        self._job_counter = 0
        self._job_lock = threading.Lock()
        self._job_queues = {}  # job_id -> local queue (results demux)
        self._cancelled = False
        self.executor_dirs = []
        # supervision knobs (foreach_partition(retryable=True) path);
        # the mechanisms live in actors.supervise — the engine is policy
        self._max_retries = int(os.environ.get("TFOS_TASK_RETRIES", "2"))
        self._retry_backoff = float(os.environ.get("TFOS_RETRY_BACKOFF", "0.25"))
        self._budget = _supervise.RespawnBudget(
            int(os.environ.get("TFOS_EXECUTOR_RESPAWNS", "8")),
            what="executor", env_name="TFOS_EXECUTOR_RESPAWNS",
            error_cls=TaskError)
        self._retired = set()  # slots removed by an elastic cluster shrink
        self._spawn_lock = threading.Lock()
        with _patched_env(self._env):
            for i in range(self.num_executors):
                d = os.path.join(self._root, f"executor-{i}")
                os.makedirs(d, exist_ok=True)
                self.executor_dirs.append(d)
                self._own_inboxes.append(self._ctx.Queue())
                self._procs.append(self._spawn_executor(i))
        # Concurrent jobs (e.g. the node-launcher thread and a feeder) share
        # one results pipe; this pump demultiplexes per job so one job's
        # wait loop can never swallow another's completions.
        self._pump = threading.Thread(
            target=self._pump_results, name="tfos-result-pump", daemon=True
        )
        self._pump.start()
        atexit.register(self.stop)
        metrics_registry.set_gauge("tfos_engine_executors",
                                   self.num_executors)
        logger.info(
            "LocalEngine started %d executors under %s", self.num_executors, self._root
        )

    def _spawn_executor(self, index):
        """Start the executor-``index`` process (reusing its inbox and
        working dir, so queued-but-unconsumed tasks survive a respawn).
        NOT daemonic: executors must be able to fork the background
        training process and the IPC manager (Spark executors can)."""
        p = self._ctx.Process(
            target=_executor_main,
            args=(index, self.executor_dirs[index], self._shared_inbox,
                  self._own_inboxes[index], self._results),
            name=f"tfos-executor-{index}",
            daemon=False,
        )
        p.start()
        return p

    # -- supervision ----------------------------------------------------------
    @property
    def _respawns(self):
        """Respawns consumed so far (budget bookkeeping in supervise)."""
        return self._budget.used

    def _respawn_executor(self, index):
        """Replace a dead executor process; True if a respawn happened.

        The dead incarnation's forked children (IPC-manager server,
        background trainer) are part of its failure domain:
        ``supervise.reap_orphans`` kills them via the executor dir's pid
        file before the replacement starts, so a relaunched node never
        fights a half-dead twin for the executor's identity."""
        with self._spawn_lock:
            if self._procs[index].is_alive():
                return False
            if index in self._retired:
                raise TaskError(
                    f"executor {index} is retired (elastic cluster shrink); "
                    "its slot is no longer part of the dispatch pool")
            self._budget.consume(index)
            _supervise.reap_orphans([self.executor_dirs[index]],
                                    what=f"child of dead executor {index}")
            with _patched_env(self._env):
                self._procs[index] = self._spawn_executor(index)
        telemetry.event("engine/executor_respawn", executor=index,
                        respawns=self._budget.used)
        try:  # black-box flight dump (docs/telemetry.md)
            from tensorflowonspark_tpu.obs import flight as _flight

            _flight.snapshot(
                "engine/executor_respawn", node=f"executor-{index}",
                reason=f"respawn {self._budget.used}/{self._budget.budget}")
        except Exception:  # noqa: BLE001 - never block a respawn
            logger.debug("flight snapshot failed", exc_info=True)
        metrics_registry.inc("tfos_engine_respawns_total")
        if metrics_registry.enabled():
            metrics_registry.set_gauge(
                "tfos_engine_executors",
                sum(1 for p in self._procs if p.is_alive()))
        logger.warning("respawned executor %d (%d/%d respawns used)",
                       index, self._budget.used, self._budget.budget)
        return True

    def ensure_executors(self):
        """Respawn every dead executor; returns the respawned indices.
        Used by cluster recovery to heal the pool before relaunching
        nodes.  Raises ``TaskError`` when the respawn budget is
        exhausted — elastic recovery (``cluster.run(min_executors=k)``)
        catches it and re-forms the cluster over ``alive_executors()``
        instead."""
        respawned = []
        for i, p in enumerate(self._procs):
            if i in self._retired:
                continue
            if not p.is_alive() and self._respawn_executor(i):
                respawned.append(i)
        return respawned

    def alive_executors(self):
        """Sorted indices of executor processes currently alive — the
        surviving pool an elastic recovery re-forms the cluster over."""
        return sorted(i for i, p in enumerate(self._procs) if p.is_alive())

    def retire_executors(self, indices):
        """Replace the set of slots excluded from the dispatch pool
        (elastic cluster shrink: ``cluster._resize_cluster``).  Retired
        slots are skipped by spread dispatch and never respawned; a
        later ``retire_executors([])`` — the pool healed and the
        cluster re-grew — restores them."""
        self._retired = {int(i) for i in indices}
        telemetry.event("engine/retire", retired=sorted(self._retired))
        if self._retired:
            logger.warning("engine: retired executor slot(s) %s",
                           sorted(self._retired))

    # -- engine contract ------------------------------------------------------
    @property
    def default_fs(self):
        return "file://"

    def parallelize(self, seq, num_partitions=None):
        items = list(seq)
        n = num_partitions or self.num_executors
        n = max(1, min(n, max(len(items), 1)))
        parts = [[] for _ in range(n)]
        for i, item in enumerate(items):
            parts[i * n // max(len(items), 1)].append(item)
        return LocalDataset(self, parts)

    def from_partitions(self, partitions):
        return LocalDataset(self, [list(p) for p in partitions])

    def cancel_all_jobs(self):
        """Abort everything (parity: sc.cancelAllJobs before driver exit)."""
        self._cancelled = True

    def _pump_results(self):
        """Drain the shared results pipe into per-job local queues."""
        while not getattr(self, "_stopped", False):
            try:
                item = self._results.get(timeout=0.2)
            except _queue.Empty:
                continue
            except (OSError, EOFError, ValueError):
                break
            except Exception as e:  # noqa: BLE001 - transport corruption
                # Task results are serialized child-side (so a bad payload
                # fails only its own task); reaching here means the results
                # PIPE itself is corrupt.  That must not silently kill the
                # pump (every job would hang); broadcast a typed transport
                # error to all in-flight jobs instead.
                logger.exception("result pump error")
                with self._job_lock:
                    queues = list(self._job_queues.values())
                for q in queues:
                    q.put(("pump_error", None, -1, -1,
                           f"result pump transport error: {e!r}"))
                continue
            with self._job_lock:
                q = self._job_queues.get(item[1])
            if q is not None:
                q.put(item)
            # results for finished/cancelled jobs are dropped

    def _run_job(self, tasks, collect, spread, placement=None,
                 retryable=False, max_retries=None):
        """Dispatch one (items, fn) task per partition; block until done."""
        if self._cancelled:
            raise TaskError("engine cancelled")
        with self._job_lock:
            self._job_counter += 1
            job_id = self._job_counter
            my_results = _queue.Queue()
            self._job_queues[job_id] = my_results
        with telemetry.span("engine/job", job=job_id, tasks=len(tasks),
                            spread=bool(spread or placement is not None),
                            retryable=bool(retryable)):
            try:
                out = self._run_job_inner(
                    tasks, collect, spread, placement, job_id, my_results,
                    retryable, max_retries)
            except BaseException:
                metrics_registry.inc("tfos_engine_jobs_total",
                                     status="error")
                raise
            metrics_registry.inc("tfos_engine_jobs_total", status="ok")
            return out

    def _run_job_inner(self, tasks, collect, spread, placement, job_id,
                       my_results, retryable=False, max_retries=None):
        # Only executors that die DURING this job abort it; one already lost
        # to an earlier job must not fail work the survivors can finish.
        dead_at_start = {i for i, p in enumerate(self._procs) if not p.is_alive()}
        ntasks = len(tasks)
        if max_retries is None:
            max_retries = self._max_retries
        if not retryable:
            max_retries = 0
        # Blobs are kept for the job's lifetime when retryable so a failed
        # or lost task can be re-dispatched byte-identically.
        # The active trace context (the engine/job span's, when a trace
        # is live) rides each blob so executor-side task spans join the
        # dispatching request's tree.
        ctx = telemetry.current()
        trace_hdr = ctx.to_header() if ctx is not None else None
        blobs = [cloudpickle.dumps((fn, list(part), collect, trace_hdr))
                 for part, fn in tasks]

        def _dispatch(task_id):
            msg = ("task", job_id, task_id, blobs[task_id])
            if placement is not None and task_id < len(placement):
                target = placement[task_id] % self.num_executors
            elif spread:
                # retired slots (elastic shrink) are out of the pool
                pool = [i for i in range(self.num_executors)
                        if i not in self._retired]
                if not pool:
                    raise TaskError("all executor slots are retired")
                target = pool[task_id % len(pool)]
            else:
                self._shared_inbox.put(msg)
                return
            if not self._procs[target].is_alive():
                if retryable:
                    # heal the slot: the inbox survives, so the respawned
                    # executor picks this message up
                    self._respawn_executor(target)
                    dead_at_start.discard(target)
                elif placement is not None:
                    raise TaskError(
                        f"cannot place task {task_id} on executor "
                        f"{target}: executor process is dead"
                    )
                else:
                    raise TaskError(
                        f"cannot spread task {task_id} to executor "
                        f"{target}: executor process is dead"
                    )
            self._own_inboxes[target].put(msg)

        results = [None] * ntasks
        done = [False] * ntasks
        sched = _supervise.RetrySchedule(max_retries, self._retry_backoff)
        running = {}                  # task_id -> executor (start-acked)
        retry_at = {}                 # task_id -> monotonic re-dispatch time
        ndone = 0

        def _schedule_retry(tid, reason):
            """Count a failed attempt; queue a backoff re-dispatch or fail
            the job once the budget is spent (poison task)."""
            sched.record_failure(tid, reason)
            metrics_registry.inc("tfos_engine_tasks_total", status="error")
            running.pop(tid, None)
            if sched.exhausted(tid):
                if retryable:
                    telemetry.event("engine/task_poison", job=job_id,
                                    task=tid, attempts=sched.attempt(tid) + 1)
                raise TaskError(sched.permanent_error(
                    tid, f"task {tid} failed on executor"))
            delay = sched.next_delay(tid)
            retry_at[tid] = time.monotonic() + delay
            telemetry.event("engine/task_retry", job=job_id, task=tid,
                            attempt=sched.attempt(tid),
                            delay_ms=int(delay * 1000))
            metrics_registry.inc("tfos_engine_task_retries_total")
            logger.warning(
                "task %d of job %d failed (attempt %d of %d); retrying "
                "in %.2fs", tid, job_id, sched.attempt(tid),
                max_retries + 1, delay)

        try:
            for task_id in range(ntasks):
                _dispatch(task_id)
            while ndone < ntasks:
                if self._cancelled:
                    raise TaskError("engine cancelled")
                now = time.monotonic()
                for tid in [t for t, at in retry_at.items() if at <= now]:
                    del retry_at[tid]
                    _dispatch(tid)
                try:
                    status, _jid, tid, idx, payload = my_results.get(timeout=0.25)
                except _queue.Empty:
                    dead = [
                        i
                        for i, p in enumerate(self._procs)
                        if i not in dead_at_start and not p.is_alive()
                    ]
                    if not dead:
                        continue
                    if not retryable:
                        raise TaskError(
                            f"executor(s) {dead} died with tasks in flight "
                            f"(job {job_id}, {ntasks - ndone} pending); driver "
                            "scripts must guard entry with if __name__ == '__main__' "
                            "when using the default spawn start method"
                        )
                    for e in dead:
                        lost = sorted(t for t, ex in running.items() if ex == e)
                        self._respawn_executor(e)
                        dead_at_start.discard(e)
                        for t in lost:
                            _schedule_retry(
                                t, f"executor {e} died while running task {t} "
                                   "(process loss)")
                    continue
                if status == "start":
                    running[tid] = idx
                    continue
                if status == "pump_error":
                    raise ResultPumpError(payload)
                if done[tid]:
                    continue  # late duplicate from a superseded attempt
                if status == "error":
                    # max_retries == 0 (non-retryable jobs) is exhausted on
                    # the first failure, so this fails fast with the same
                    # single-attempt message as before
                    _schedule_retry(tid, payload)
                    continue
                # status == "ok"; payloads are serialized child-side
                running.pop(tid, None)
                if payload is not None:
                    try:
                        results[tid] = cloudpickle.loads(payload)
                    except Exception as e:
                        raise ResultPumpError(
                            f"result of task {tid} (job {job_id}) could not "
                            f"be deserialized: {e!r}") from e
                done[tid] = True
                ndone += 1
                metrics_registry.inc("tfos_engine_tasks_total", status="ok")
            return results
        finally:
            with self._job_lock:
                self._job_queues.pop(job_id, None)

    def stop(self):
        if getattr(self, "_stopped", False):
            return
        self._stopped = True
        for inbox in self._own_inboxes:
            try:
                inbox.put(("stop",))
            except (OSError, ValueError):
                pass
        # A dead executor never drains its inbox; if an undelivered task
        # blob exceeds the pipe buffer, the queue's feeder thread blocks
        # in write() forever and multiprocessing's atexit join would hang
        # interpreter exit on it.  The engine is going away — never wait
        # for a flush to a reader that may not exist.
        for q in (self._shared_inbox, self._results, *self._own_inboxes):
            try:
                q.cancel_join_thread()
            except (OSError, ValueError):
                pass
        deadline = time.time() + 5
        for p in self._procs:
            p.join(timeout=max(0.1, deadline - time.time()))
            if p.is_alive():
                p.terminate()
                p.join(timeout=2)
            if p.is_alive():
                p.kill()
                p.join(timeout=2)
        # Executors killed un-gracefully may leave their forked children
        # (background trainer, IPC-manager server) re-parented to init;
        # each executor recorded those pids in its working dir — kill any
        # survivor so nothing outlives the engine (and nothing keeps the
        # resource-tracker pipe open past interpreter exit).  The pid
        # ledger is cleared once swept, so a caller-provided workdir is
        # not left with pid droppings.
        _supervise.reap_orphans(self.executor_dirs, what="leftover child")
        if self._owns_root:
            shutil.rmtree(self._root, ignore_errors=True)


# ----------------------------------------------------------------------------
# Spark adapter (optional)
# ----------------------------------------------------------------------------

class SparkDataset:
    """RDD wrapper exposing the Dataset contract.

    ``spread``/``placement`` map onto Spark **barrier execution**
    (``rdd.barrier()``): all partitions are scheduled concurrently, one
    per free slot — the strongest placement guarantee Spark offers.
    True executor *pinning* does not exist on Spark; node identity is
    recovered the reference's way instead, by executor-id-file
    reattachment on whichever executor a task lands
    (TFSparkNode.py:119-146), so barrier's distinct-slot guarantee is
    exactly what the node-launch and shutdown closures need.
    """

    def __init__(self, rdd):
        self.rdd = rdd

    @property
    def num_partitions(self):
        return self.rdd.getNumPartitions()

    def map_partitions(self, fn):
        return SparkDataset(self.rdd.mapPartitions(fn))

    def foreach_partition(self, fn, spread=False, placement=None,
                          retryable=False, max_retries=None):
        # retryable/max_retries are accepted for contract parity; Spark's
        # own task retry (spark.task.maxFailures) supervises these jobs.
        del retryable, max_retries
        if spread or placement is not None:
            def _run(it, _fn=fn):
                _fn(it)
                return iter([0])

            self.rdd.barrier().mapPartitions(_run).count()
        else:
            self.rdd.foreachPartition(fn)

    def collect(self, spread=False, retryable=False, max_retries=None):
        del retryable, max_retries  # supervised by spark.task.maxFailures
        if spread:
            def _identity(it):
                return it

            return self.rdd.barrier().mapPartitions(_identity).collect()
        return self.rdd.collect()

    def union(self, *others):
        rdd = self.rdd
        for o in others:
            rdd = rdd.union(o.rdd if isinstance(o, SparkDataset) else o)
        return SparkDataset(rdd)

    def repartition(self, num_partitions):
        return SparkDataset(self.rdd.repartition(num_partitions))


class SparkEngine:
    """Adapter over pyspark.SparkContext (parity: the reference's `sc`)."""

    def __init__(self, sc):
        self.sc = sc
        self.num_executors = int(sc.getConf().get("spark.executor.instances", "1"))
        # the node runtime assumes a fixed executor set for the cluster's
        # lifetime (parity: TFSparkNode.py:138-143 hard-fails the same way)
        if sc.getConf().get(
            "spark.dynamicAllocation.enabled", "false"
        ).strip().lower() == "true":
            raise RuntimeError(
                "TFCluster requires spark.dynamicAllocation.enabled=false: "
                "executors host long-lived framework nodes and must not be "
                "reclaimed mid-job"
            )

    @property
    def default_fs(self):
        return self.sc._jsc.hadoopConfiguration().get("fs.defaultFS")

    def parallelize(self, seq, num_partitions=None):
        return SparkDataset(self.sc.parallelize(seq, num_partitions))

    def cancel_all_jobs(self):
        self.sc.cancelAllJobs()

    def stop(self):
        pass  # caller owns the SparkContext


def as_engine(obj):
    """Coerce a SparkContext / RDD-owner / engine to the Engine contract."""
    if isinstance(obj, (LocalEngine, SparkEngine)):
        return obj
    cls = type(obj)
    if cls.__module__.startswith("pyspark") and cls.__name__ == "SparkContext":
        return SparkEngine(obj)
    raise TypeError(f"not an engine or SparkContext: {obj!r}")


def as_dataset(obj, engine=None):
    """Coerce an RDD or Dataset to the Dataset contract."""
    if isinstance(obj, (LocalDataset, SparkDataset)):
        return obj
    cls = type(obj)
    if cls.__module__.startswith("pyspark") and cls.__name__ == "RDD":
        return SparkDataset(obj)
    raise TypeError(f"not a dataset or RDD: {obj!r}")
