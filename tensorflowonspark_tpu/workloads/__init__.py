"""Workloads composed purely from the actor substrate (``actors/``).

Each module here is an existence proof of ISSUE 10's claim: new
supervised behaviors are actor definitions + policy, with zero bespoke
supervision/respawn/ledger code (the lint test in tests/test_actors.py
enforces this for everything outside ``actors/``).
"""

from tensorflowonspark_tpu.workloads.deploy_loop import (  # noqa: F401
    DeployLoop, PromotionController, deploy_table, run_deploy_loop,
)
from tensorflowonspark_tpu.workloads.eval_sidecar import (  # noqa: F401
    EvalSidecar,
)
from tensorflowonspark_tpu.workloads.sweep import (  # noqa: F401
    TrialActor, successive_halving,
)
