"""Successive-halving hyperparameter sweep as pure actors.

Parity anchor: ``parallel_run.py`` (reference ``TFParallel.py:26-68`` —
one barrier wave of independent instances, all run to completion).  This
module extends that barrier parity with EARLY STOPPING: configs compete
in rungs; after each rung only the top ``1/eta`` survive and the budget
multiplies by ``eta`` (successive halving, the Hyperband inner loop) —
so total work is ~``n * budget * log_eta(n)`` instead of every config
running at full budget.  ROADMAP item 5's named scenario.

Like the eval sidecar, this carries ZERO supervision/respawn/ledger code
(the lint test enforces it): trials run as ``ask``s to an
:class:`~tensorflowonspark_tpu.actors.ActorGroup` of
:class:`TrialActor`s, so a worker SIGKILLed mid-trial is respawned by
the substrate and its trial re-dispatched, with the resolve-once ask
future absorbing any duplicate answer.  Each rung is a barrier — every
surviving config's future resolves before ranking — matching
``parallel_run``'s collect(spread=True) semantics.
"""

from __future__ import annotations

import logging
import math

from tensorflowonspark_tpu.actors import Actor
from tensorflowonspark_tpu.utils import telemetry

logger = logging.getLogger(__name__)


class TrialActor(Actor):
    """Runs one trial per ``ask``: ``trial_fn(config, budget) -> score``
    (higher is better).  State-free between trials by design — any
    member can run any trial, so failover needs no affinity."""

    def __init__(self, trial_fn):
        self.trial_fn = trial_fn

    def on_message(self, ctx, kind, payload):
        if kind != "trial":
            raise NotImplementedError(f"unhandled message kind {kind!r}")
        score = self.trial_fn(payload["config"], payload["budget"])
        return {"trial": payload["trial"], "score": float(score),
                "budget": payload["budget"]}


def successive_halving(trial_fn, configs, budget=1, eta=2, workers=None,
                       system=None, policy=None, env=None, target=None,
                       timeout=600.0, name="sweep"):
    """Run a successive-halving sweep over ``configs``.

    Args:
      trial_fn: ``(config, budget) -> score`` (higher is better); must
        be module-importable in workers (spawn start method) and
        idempotent per (config, budget) — a failover re-runs it.
      configs: list of config objects (anything picklable).
      budget: rung-0 budget passed to ``trial_fn`` (epochs, steps...).
      eta: halving rate — keep ``ceil(n/eta)`` per rung, multiply the
        budget by ``eta``.
      workers: trial actors to spawn (default ``min(len(configs), 4)``).
      system: an existing :class:`~tensorflowonspark_tpu.actors.ActorSystem`
        to spawn into (a fresh one is created and stopped otherwise).
      policy: optional SupervisionPolicy for the trial group.
      env: env overrides for a freshly-created system's executors.
      target: optional early-stop score — the sweep returns as soon as a
        rung's best reaches it.
      timeout: per-rung wait for all trial replies.
      name: actor-group name (unique per system).

    Returns ``{"best": {"trial", "config", "score", "budget"},
    "history": [per-rung dicts]}``.
    """
    configs = list(configs)
    if not configs:
        raise ValueError("successive_halving needs at least one config")
    workers = int(workers or min(len(configs), 4))
    own_system = system is None
    if own_system:
        from tensorflowonspark_tpu.actors import ActorSystem

        system = ActorSystem(workers, env=env)
    try:
        group = system.spawn(TrialActor(trial_fn), name, count=workers,
                             policy=policy)
        survivors = list(enumerate(configs))   # (trial id, config)
        history = []
        best = None
        rung = 0
        while survivors:
            futures = [(tid, cfg,
                        group.ask("trial", {"trial": tid, "config": cfg,
                                            "budget": budget}))
                       for tid, cfg in survivors]
            # rung barrier: every surviving config resolves before
            # ranking (parallel_run collect(spread=True) parity)
            results = [(tid, cfg, f.result(timeout))
                       for tid, cfg, f in futures]
            results.sort(key=lambda r: (-r[2]["score"], r[0]))
            history.append({
                "rung": rung, "budget": budget,
                "scores": {tid: r["score"] for tid, _cfg, r in results},
            })
            tid, cfg, r = results[0]
            best = {"trial": tid, "config": cfg, "score": r["score"],
                    "budget": budget}
            telemetry.event("sweep/rung", rung=rung, budget=budget,
                            survivors=len(results),
                            best_trial=tid, best_score=r["score"])
            if target is not None and best["score"] >= target:
                logger.info("sweep: target %.4g reached at rung %d by "
                            "trial %d", target, rung, tid)
                break
            if len(results) == 1:
                break
            keep = max(1, math.ceil(len(results) / eta))
            survivors = [(t, c) for t, c, _r in results[:keep]]
            budget *= eta
            rung += 1
        return {"best": best, "history": history}
    finally:
        if own_system:
            system.stop()
