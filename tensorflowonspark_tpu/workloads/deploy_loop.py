"""Blessed-checkpoint deployment loop: eval gate -> canary -> verdict.

Parity anchor: none — the reference stops at the SavedModel hand-off
(``TFNode.export_saved_model``, reference ``TFNode.py:159-208``) and
delegates deployment to TF Serving.  Here the loop is closed inside the
stack: the trainer emits checkpoints, the :class:`EvalSidecar` scores
each step exactly once, a supervised :class:`PromotionController` actor
*blesses* gate-passing steps (integrity manifest: per-file sha256 +
step + eval score, ``utils/checkpoint.bless_checkpoint``), and the
driver-side :class:`DeployLoop` stages the rollout against a live
:class:`~tensorflowonspark_tpu.serving.replicas.ReplicaPool`:

1. **canary** — pin an arm of replicas at the candidate and route
   ``TFOS_DEPLOY_CANARY_PCT``% of traffic there (deterministic
   crc32 split, ``replicas.canary_arm``);
2. **burn** — accumulate per-arm outcomes for
   ``TFOS_DEPLOY_BURN_SECS``, exported by the pool in registry-snapshot
   shape (``canary_snapshot``) so the verdict runs the SAME math as the
   live metrics plane (``obs/slo.evaluate``);
3. **verdict** — promote (reload the baseline at the candidate, advance
   the watermark) or auto-rollback (re-pin the arm at the last blessed
   step, quarantine the candidate via manifest tombstone, flight-ring
   snapshot + ``deploy/rollback`` telemetry).

Like every workload, this module carries ZERO supervision code of its
own (the lint test enforces it): the controller rides the actor
substrate, the pool owns all replica mechanics, and the driver pump is
a plain synchronous function.  Durable state is the manifests
themselves — blessed-and-not-tombstoned steps above the watermark ARE
the work queue, so a restarted driver recovers by re-reading them
(``recover()``), and a SIGKILLed controller re-gates nothing (KV
ledger + manifest-existence check).

Chaos contract: ``deploy.canary`` / ``deploy.promote`` /
``deploy.rollback`` fault sites fire BEFORE the matching pool
transition, so an injected fault leaves the state machine unchanged and
the next pump retries — :func:`run_deploy_loop` absorbs the raise.
"""

from __future__ import annotations

import logging
import math
import os
import time
import weakref

from tensorflowonspark_tpu.actors import Actor
from tensorflowonspark_tpu.utils import faults, metrics_registry, telemetry

logger = logging.getLogger(__name__)

#: Live loops, for /statusz introspection (obs/http deploy rows).
_LOOPS = weakref.WeakSet()

GATE_LEDGER = "deploy_gate"

PCT_ENV = "TFOS_DEPLOY_CANARY_PCT"
ARM_ENV = "TFOS_DEPLOY_CANARY_REPLICAS"
BURN_ENV = "TFOS_DEPLOY_BURN_SECS"
MIN_SAMPLES_ENV = "TFOS_DEPLOY_MIN_SAMPLES"
EVAL_TOL_ENV = "TFOS_DEPLOY_EVAL_TOL"
LAT_TOL_ENV = "TFOS_DEPLOY_LAT_TOL"
SLO_ENV = "TFOS_DEPLOY_SLO"
GATE_MAX_ENV = "TFOS_DEPLOY_GATE_MAX"

#: Default burn-window objective: 99% of canary-arm requests must not
#: error.  Same grammar as TFOS_SLO (obs/slo.py); latency is judged
#: RELATIVELY (canary p95 vs baseline p95, ``TFOS_DEPLOY_LAT_TOL``)
#: because an absolute threshold is workload-specific.
DEFAULT_SLO = "deploy_availability:availability:tfos_deploy_requests_total@99"


def _env_float(name, default):
    raw = os.environ.get(name)
    return default if raw in (None, "") else float(raw)


class PromotionController(Actor):
    """Supervised gatekeeper: blesses or quarantines each checkpoint
    step once its eval result is in.

    Runs in the SAME :class:`~tensorflowonspark_tpu.actors.ActorSystem`
    as the :class:`EvalSidecar` (it reads the sidecar's published
    ``eval_result:<step>`` through the shared manager KV).  Gate
    decisions are exactly-once across SIGKILL respawns: the KV ledger
    records judged steps, and a manifest already on disk short-circuits
    a re-judge (bless/tombstone are idempotent, so the at-least-once
    window between effect and record converges).

    ``gate_fn(metrics) -> bool`` overrides the default gate (score
    finite, and ``<= TFOS_DEPLOY_GATE_MAX`` when set).  Messages:

    - ``ask("latest")`` -> last gate decision or None
    - ``ask("judged")`` -> sorted steps already gated
    """

    def __init__(self, ckpt_dir, eval_group="eval", gate_fn=None,
                 score_key="loss"):
        self.ckpt_dir = ckpt_dir
        self.eval_group = eval_group
        self.gate_fn = gate_fn
        self.score_key = score_key
        self.last = None

    def _gate(self, metrics):
        score = metrics.get(self.score_key)
        score = None if score is None else float(score)
        if self.gate_fn is not None:
            return bool(self.gate_fn(metrics)), score, "gate_fn"
        if score is None or not math.isfinite(score):
            return False, score, f"{self.score_key}={score} not finite"
        gate_max = os.environ.get(GATE_MAX_ENV)
        if gate_max not in (None, "") and score > float(gate_max):
            return False, score, (f"{self.score_key}={score:g} over "
                                  f"gate max {float(gate_max):g}")
        return True, score, "pass"

    def on_tick(self, ctx):
        from tensorflowonspark_tpu.utils import checkpoint as ckpt

        try:
            step, _path = ckpt.latest(self.ckpt_dir)
        except Exception:  # noqa: BLE001 - transient fs error
            return
        if step is None or ctx.ledger.done(GATE_LEDGER, step):
            return
        if ckpt.read_manifest(self.ckpt_dir, step) is not None:
            # a prior incarnation judged it between effect and record
            ctx.ledger.record(GATE_LEDGER, step)
            return
        result = ctx.mgr.get(
            f"actor_kv:{self.eval_group}:eval_result:{step}")
        if result is None:
            return  # the sidecar hasn't scored this step yet
        metrics = dict(result.get("metrics") or {})
        ok, score, why = self._gate(metrics)
        if ok:
            ckpt.bless_checkpoint(self.ckpt_dir, step, score=score,
                                  eval_metrics=metrics)
        else:
            ckpt.tombstone_checkpoint(self.ckpt_dir, step,
                                      reason=f"eval gate: {why}")
        ctx.ledger.record(GATE_LEDGER, step)
        self.last = {"step": step, "blessed": ok, "score": score,
                     "why": why}
        ctx.kv_set(f"deploy_gate:{step}", self.last)
        ctx.emit("deploy/gate", self.last)
        logger.info("promotion gate: step %d %s (%s)", step,
                    "blessed" if ok else "quarantined", why)

    def on_message(self, ctx, kind, payload):
        if kind == "latest":
            return self.last
        if kind == "judged":
            return ctx.ledger.done_units(GATE_LEDGER)
        raise NotImplementedError(f"unhandled message kind {kind!r}")


class DeployLoop:
    """Driver-side staged-rollout state machine over one pool + one
    checkpoint dir.  Synchronous by design: ``pump()`` attempts at most
    one transition and returns a status row; the caller owns cadence
    (:func:`run_deploy_loop` is the batteries-included driver).

    States: ``idle`` (scanning for a blessed candidate above the
    watermark) -> ``burn`` (canary open, evidence accumulating) ->
    back to ``idle`` via promote or rollback.
    """

    def __init__(self, pool, ckpt_dir, pct=None, canary_count=None,
                 burn_secs=None, min_samples=None, eval_tol=None,
                 lat_tol=None, slo_spec=None):
        from tensorflowonspark_tpu.obs import slo as _slo

        self.pool = pool
        self.ckpt_dir = ckpt_dir
        self.pct = _env_float(PCT_ENV, 10.0) if pct is None else float(pct)
        self.canary_count = int(_env_float(ARM_ENV, 1)
                                if canary_count is None else canary_count)
        self.burn_secs = (_env_float(BURN_ENV, 30.0)
                          if burn_secs is None else float(burn_secs))
        self.min_samples = int(_env_float(MIN_SAMPLES_ENV, 10)
                               if min_samples is None else min_samples)
        self.eval_tol = (_env_float(EVAL_TOL_ENV, 0.1)
                         if eval_tol is None else float(eval_tol))
        self.lat_tol = (_env_float(LAT_TOL_ENV, 0.5)
                        if lat_tol is None else float(lat_tol))
        if slo_spec is None:
            slo_spec = os.environ.get(SLO_ENV, DEFAULT_SLO)
        self.objectives = _slo.parse_spec(slo_spec)
        self.state = "idle"
        self.candidate = None
        self.promotions = 0
        self.rollbacks = 0
        self.last_verdict = None
        self.history = []
        self._burn_deadline = None
        _LOOPS.add(self)

    # -- candidate discovery --------------------------------------------------
    def recover(self):
        """Re-pin the pool from durable state: the newest VERIFYING
        blessed manifest.  A fresh loop (or a restarted driver) calls
        this before pumping so rollout decisions always have a blessed
        baseline to fall back to."""
        from tensorflowonspark_tpu.utils import checkpoint as ckpt

        if self.pool.watermark() is not None:
            return self.pool.watermark()
        step, _path = ckpt.latest_blessed(self.ckpt_dir)
        if step is not None:
            self.pool.pin_version(step)
            logger.info("deploy loop: recovered watermark at step %d", step)
        return step

    def _next_candidate(self):
        from tensorflowonspark_tpu.utils import checkpoint as ckpt

        wm = self.pool.watermark()
        steps = [s for s in ckpt.blessed_steps(self.ckpt_dir)
                 if wm is None or s > wm]
        if not steps:
            return None
        cand = max(steps)  # newest blessed wins; stale siblings skipped
        ok, reason = ckpt.verify_manifest(self.ckpt_dir, cand)
        if not ok:
            logger.warning("deploy loop: candidate %d fails verify (%s); "
                           "skipped", cand, reason)
            return None
        return cand

    def _pick_arm(self):
        live = sorted(self.pool.live_replicas())
        count = max(1, min(self.canary_count, len(live) - 1))
        return live[:count]

    # -- the pump -------------------------------------------------------------
    def pump(self, now=None):
        """One synchronous transition attempt.  Raises on injected
        faults (state unchanged — the next pump retries); returns a
        status row either way on the normal path."""
        now = time.monotonic() if now is None else now
        if self.state == "idle":
            cand = self._next_candidate()
            if cand is not None:
                if self.pool.watermark() is None:
                    self._bootstrap(cand)
                else:
                    self._open_canary(cand, now)
        elif self.state == "burn" and now >= self._burn_deadline:
            ok, reasons = self._judge()
            if ok:
                self._promote()
            else:
                self._rollback(reasons)
        return self.status()

    def _bootstrap(self, step):
        """First blessed checkpoint: nothing to canary against, so the
        whole pool pins to it (still a promote commit — the fault site
        and the telemetry say so)."""
        faults.check("deploy.promote", step=step, bootstrap=True)
        self.pool.pin_version(step)
        self.promotions += 1
        self.last_verdict = {"step": step, "verdict": "promote",
                             "reasons": ["bootstrap"]}
        self.history.append(self.last_verdict)
        metrics_registry.inc("tfos_deploy_promotions_total")
        telemetry.event(telemetry.DEPLOY_PROMOTE, step=step,
                        bootstrap=True)
        logger.info("deploy loop: bootstrap promote to step %d", step)

    def _open_canary(self, cand, now):
        faults.check("deploy.canary", step=cand)
        arm = self._pick_arm()
        self.pool.set_canary(arm, cand, self.pct)
        self.candidate = cand
        self._burn_deadline = now + self.burn_secs
        self.state = "burn"

    def _promote(self):
        faults.check("deploy.promote", step=self.candidate)
        step = self.pool.promote_canary()
        self.promotions += 1
        self.last_verdict = {"step": step, "verdict": "promote",
                             "reasons": []}
        self.history.append(self.last_verdict)
        self.state, self.candidate = "idle", None
        metrics_registry.inc("tfos_deploy_promotions_total")
        telemetry.event(telemetry.DEPLOY_PROMOTE, step=step)
        logger.info("deploy loop: promoted step %d", step)

    def _rollback(self, reasons):
        from tensorflowonspark_tpu.obs import flight
        from tensorflowonspark_tpu.utils import checkpoint as ckpt

        cand = self.candidate
        faults.check("deploy.rollback", step=cand)
        target = self.pool.rollback_canary()
        ckpt.tombstone_checkpoint(self.ckpt_dir, cand,
                                  reason="; ".join(reasons) or "rollback")
        # the last telemetry window around the regression, preserved
        # before traffic converges back to baseline
        flight.snapshot(telemetry.DEPLOY_ROLLBACK,
                        node=f"deploy:{os.path.basename(self.ckpt_dir)}",
                        reason="; ".join(reasons))
        self.rollbacks += 1
        self.last_verdict = {"step": cand, "verdict": "rollback",
                             "target": target, "reasons": list(reasons)}
        self.history.append(self.last_verdict)
        self.state, self.candidate = "idle", None
        metrics_registry.inc("tfos_deploy_rollbacks_total")
        telemetry.event(telemetry.DEPLOY_ROLLBACK, step=cand,
                        target=target, reasons=list(reasons))
        logger.warning("deploy loop: rolled back step %s to %s (%s)",
                       cand, target, "; ".join(reasons))

    # -- the verdict ----------------------------------------------------------
    def _judge(self):
        """Burn-window verdict: (ok, reasons).  Fail-safe — a canary
        that produced no judgeable evidence does not promote."""
        from tensorflowonspark_tpu.obs import slo as _slo
        from tensorflowonspark_tpu.utils import checkpoint as ckpt

        reasons = []
        snap = self.pool.canary_snapshot()
        stats = self.pool.canary_stats()
        can, base = stats.get("canary"), stats.get("baseline")
        if not can or can["n"] < self.min_samples:
            n = can["n"] if can else 0
            reasons.append(f"insufficient canary traffic "
                           f"({n}/{self.min_samples})")
        # eval-score regression vs the blessed baseline (lower = better;
        # the manifests are the durable record of both scores)
        cand_man = ckpt.read_manifest(self.ckpt_dir, self.candidate) or {}
        base_man = (ckpt.read_manifest(self.ckpt_dir,
                                       self.pool.watermark() or -1) or {})
        c_score, b_score = cand_man.get("score"), base_man.get("score")
        if c_score is not None and not math.isfinite(float(c_score)):
            reasons.append(f"candidate eval score {c_score} not finite")
        elif (c_score is not None and b_score is not None
                and float(c_score) > float(b_score) * (1 + self.eval_tol)
                + 1e-12):
            reasons.append(f"eval regression: {float(c_score):g} vs "
                           f"blessed {float(b_score):g} "
                           f"(tol {self.eval_tol:g})")
        # SLO objectives per arm: the canary must not breach an
        # objective the baseline holds
        for obj in self.objectives:
            c_row = _slo.evaluate(obj, [_arm_view(snap, "canary")])
            b_row = _slo.evaluate(obj, [_arm_view(snap, "baseline")])
            if c_row["breaching"] and not b_row["breaching"]:
                reasons.append(
                    f"slo {obj.name}: canary burn {c_row['burn']} "
                    f"(baseline {b_row['burn']})")
        # relative latency guard: canary p95 within lat_tol of baseline
        if (can and base and can.get("p95_ms") is not None
                and base.get("p95_ms") and base["p95_ms"] > 0
                and can["p95_ms"] > base["p95_ms"] * (1 + self.lat_tol)):
            reasons.append(f"latency regression: canary p95 "
                           f"{can['p95_ms']:.1f}ms vs baseline "
                           f"{base['p95_ms']:.1f}ms (tol {self.lat_tol:g})")
        return not reasons, reasons

    # -- introspection --------------------------------------------------------
    def status(self):
        """One ``/statusz`` row (see :func:`deploy_table`)."""
        row = {
            "ckpt_dir": self.ckpt_dir,
            "state": self.state,
            "watermark": self.pool.watermark(),
            "candidate": self.candidate,
            "canary": self.pool.canary(),
            "stats": self.pool.canary_stats(),
            "promotions": self.promotions,
            "rollbacks": self.rollbacks,
            "last_verdict": self.last_verdict,
        }
        if self.state == "burn" and self._burn_deadline is not None:
            row["burn_remaining_s"] = round(
                max(0.0, self._burn_deadline - time.monotonic()), 1)
        return row

    def summary(self):
        return {
            "watermark": self.pool.watermark(),
            "promotions": self.promotions,
            "rollbacks": self.rollbacks,
            "history": list(self.history),
        }


def _arm_view(snap, arm):
    """Filter a registry-shaped snapshot down to one arm's series (the
    per-arm input ``obs/slo.evaluate`` judges — its merge helpers do not
    filter by label themselves)."""
    out = {}
    for name, ent in (snap or {}).items():
        series = [s for s in ent.get("series", ())
                  if s.get("labels", {}).get("arm") == arm]
        if series:
            out[name] = {"series": series}
    return out


def run_deploy_loop(pool, ckpt_dir, eval_fn, duration=60.0, poll_secs=0.5,
                    system=None, policy=None, env=None, eval_group="eval",
                    controller_group="deploy", gate_fn=None,
                    score_key="loss", stop_when=None, **knobs):
    """Drive the full loop for ``duration`` seconds: spawn the eval
    sidecar + promotion controller (into ``system``, or an own
    2-slot :class:`~tensorflowonspark_tpu.actors.ActorSystem`), recover
    the watermark, then pump synchronously.

    Injected deploy-site faults and transient pump errors are absorbed
    (logged, retried next pump) — the chaos contract.  ``stop_when``
    (``loop -> bool``) ends the run early.  Returns
    :meth:`DeployLoop.summary`.
    """
    from tensorflowonspark_tpu.workloads.eval_sidecar import EvalSidecar

    own_system = system is None
    if own_system:
        from tensorflowonspark_tpu.actors import ActorSystem

        system = ActorSystem(2, env=env)
    try:
        system.spawn(EvalSidecar(ckpt_dir, eval_fn), eval_group,
                     policy=policy)
        system.spawn(
            PromotionController(ckpt_dir, eval_group=eval_group,
                                gate_fn=gate_fn, score_key=score_key),
            controller_group, policy=policy)
        loop = DeployLoop(pool, ckpt_dir, **knobs)
        loop.recover()
        deadline = time.monotonic() + duration
        while time.monotonic() < deadline:
            try:
                loop.pump()
            except faults.FaultInjected as e:
                logger.warning("deploy loop: injected fault (%s); "
                               "retrying next pump", e)
            except Exception:  # noqa: BLE001 - transient (pool
                # resizing, manager hiccup): the loop must outlive it
                logger.exception("deploy loop: pump failed; retrying")
            if stop_when is not None and stop_when(loop):
                break
            time.sleep(poll_secs)
        return loop.summary()
    finally:
        if own_system:
            system.stop()


def deploy_table():
    """Status rows for every live :class:`DeployLoop` (the ``/statusz``
    deploy section and the ``tfos-top`` health pane)."""
    rows = []
    for loop in list(_LOOPS):
        try:
            rows.append(loop.status())
        except Exception:  # noqa: BLE001 - pool tearing down
            continue
    return sorted(rows, key=lambda r: r["ckpt_dir"])
