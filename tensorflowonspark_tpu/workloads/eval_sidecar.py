"""Async eval sidecar: checkpoint-watching evaluation as a pure actor.

Parity anchor: the reference's evaluator is a dedicated cluster node
(``TFNode``/``job_name='evaluator'``, reference ``TFCluster.py:109-117``
spawns it like any worker) whose liveness and restart are Spark's
problem.  Here it is an :class:`~tensorflowonspark_tpu.actors.Actor` —
ZERO supervision, respawn or ledger code of its own (ISSUE 10
acceptance; the lint test enforces it): the substrate supervises, and
``ctx.ledger`` provides the exactly-once "each checkpoint evaluated
once" guarantee across SIGKILL respawns.

Behavior: every idle tick the sidecar polls ``checkpoint.latest`` on its
``ckpt_dir``.  A step not yet in the ledger is restored off the training
path (``checkpoint.restore_any``), run through the user's ``eval_fn``,
recorded in the ledger, published under the manager KV
(``eval_result:<step>``) and emitted as an ``eval/result`` event with an
``eval/run`` telemetry span and ``tfos_eval_*`` metrics.  A respawned
incarnation re-polls, finds the step in the (driver-held KV) ledger, and
skips it — evaluation is exactly-once per checkpoint step.
"""

from __future__ import annotations

import logging
import time

from tensorflowonspark_tpu.actors import Actor
from tensorflowonspark_tpu.utils import metrics_registry, telemetry

logger = logging.getLogger(__name__)

LEDGER_FEED = "eval"


class EvalSidecar(Actor):
    """Watches ``ckpt_dir``; evaluates each new checkpoint step once.

    ``eval_fn(tree, step) -> dict`` runs in the sidecar's process —
    off the training path by construction.  Messages:

    - ``ask("latest")`` -> ``{"step": int, "metrics": dict}`` or None
    - ``ask("evaluated")`` -> sorted steps already recorded
    """

    def __init__(self, ckpt_dir, eval_fn):
        self.ckpt_dir = ckpt_dir
        self.eval_fn = eval_fn
        self.last = None

    def on_tick(self, ctx):
        from tensorflowonspark_tpu.utils import checkpoint as ckpt

        try:
            step, _path = ckpt.latest(self.ckpt_dir)
        except Exception:  # noqa: BLE001 - transient fs error
            return
        if step is None or ctx.ledger.done(LEDGER_FEED, step):
            return
        tree, step = ckpt.restore_any(self.ckpt_dir)
        if tree is None:
            return
        t0 = time.perf_counter()
        results = self.eval_fn(tree, step)
        telemetry.record_span(telemetry.EVAL_RUN,
                              time.perf_counter() - t0, step=step)
        if not ctx.ledger.record(LEDGER_FEED, step):
            return  # a twin incarnation won the race; its result stands
        self.last = {"step": step, "metrics": results}
        ctx.kv_set(f"eval_result:{step}", self.last)
        ctx.emit("eval/result", self.last)
        metrics_registry.inc("tfos_eval_runs_total")
        metrics_registry.set_gauge("tfos_eval_last_step", step)
        logger.info("eval sidecar: step %d -> %s", step, results)

    def on_message(self, ctx, kind, payload):
        if kind == "latest":
            return self.last
        if kind == "evaluated":
            return ctx.ledger.done_units(LEDGER_FEED)
        raise NotImplementedError(f"unhandled message kind {kind!r}")
