"""Compatibility shims (parity: reference tensorflowonspark/compat.py:10-31).

The reference smooths TF1/TF2 API differences; here the shims keep the
reference's *call sites* working over the TPU-native substrate:

- ``export_saved_model(model, export_dir, ctx)``: chief-only export.  The
  reference has non-chief workers export to a dummy path (compat.py:12-17,
  a MultiWorkerMirroredStrategy quirk); TPU-native export simply no-ops on
  non-chief nodes (utils/checkpoint.py behavior) — no dummy dirs to clean.
- ``disable_auto_shard(options)``: accepted and ignored.  Auto-sharding is
  a tf.data concept; the framework's feed already delivers each node its
  own partitions, and direct-read pipelines shard by process index.
- ``is_gpu_available()``: truthful accelerator check for the hardware this
  framework targets (TPU chips), name kept for drop-in compatibility.
"""

from __future__ import annotations

import logging

from tensorflowonspark_tpu import tpu_info
from tensorflowonspark_tpu.utils import checkpoint as _checkpoint

logger = logging.getLogger(__name__)


def export_saved_model(model, export_dir, ctx=None, metadata=None):
    """Export ``model`` (a params pytree, or an object with a ``params``
    attribute) from the chief only (compat.py:10-17 parity)."""
    params = getattr(model, "params", model)
    return _checkpoint.export_model(export_dir, params, ctx, metadata=metadata)


def disable_auto_shard(options):
    """No-op (compat.py:20-24): partition feeds are already per-node."""
    logger.debug("disable_auto_shard: no-op on the TPU-native feed")
    return options


def is_gpu_available():
    """Accelerator availability (compat.py:27-31); checks TPU chips."""
    return tpu_info.is_tpu_available()


# honest alias for new code
is_tpu_available = tpu_info.is_tpu_available
