"""Cluster rendezvous: TCP registry that assembles the TPU job topology.

Parity target: reference ``tensorflowonspark/reservation.py`` (Server/Client
with REG/QUERY/QINFO/STOP messages, 1s client polling, env-pinned host/port
with port ranges, retry logic).  Differences, by design:

- Messages are length-prefixed **JSON**, not pickle (reservation.py:68-97
  frames pickled dicts; pickle over TCP is an RCE hazard, and node metadata
  is plain data anyway).
- What the registry *produces* is not a TF_CONFIG host:port cluster spec but
  the inputs for ``jax.distributed.initialize``: a coordinator address
  (process 0), ``num_processes`` and a deterministic ``process_id`` per node
  (sorted by executor_id, like reservation-sorted cluster specs at reference
  TFSparkNode.py:43-56).

Env overrides (parity: reservation.py:25-26,190-206):
  ``TFOS_SERVER_HOST``  — bind/advertise host for the server.
  ``TFOS_SERVER_PORT``  — port, comma list, and/or ``lo-hi`` ranges.
"""

from __future__ import annotations

import json
import logging
import os
import random
import select
import socket
import struct
import threading
import time

from tensorflowonspark_tpu.actors.ledger import DeliveryLedger
from tensorflowonspark_tpu.utils import faults, telemetry

logger = logging.getLogger(__name__)

TFOS_SERVER_HOST = "TFOS_SERVER_HOST"
TFOS_SERVER_PORT = "TFOS_SERVER_PORT"

MAX_RETRIES = 3          # client connect retries (parity: reservation.py:28)
POLL_SECS = 1.0          # client await poll interval
DEFAULT_TIMEOUT = 600    # driver-side await timeout (parity: TFCluster.py:231)

_HEADER = struct.Struct(">I")


def _candidate_ports():
    """Yield candidate ports from TFOS_SERVER_PORT ('p', 'p1,p2', 'lo-hi')."""
    spec = os.environ.get(TFOS_SERVER_PORT)
    if not spec:
        yield 0
        return
    for part in str(spec).split(","):
        part = part.strip()
        if "-" in part:
            lo, hi = part.split("-", 1)
            for p in range(int(lo), int(hi) + 1):
                yield p
        elif part:
            yield int(part)


class Reservations:
    """Thread-safe store of node registrations (parity: reservation.py:31-65)."""

    def __init__(self, required):
        self.required = int(required)
        self._lock = threading.RLock()
        self._reservations = []

    def add(self, meta):
        with self._lock:
            eid = meta.get("executor_id") if isinstance(meta, dict) else None
            if eid is not None:
                for i, m in enumerate(self._reservations):
                    if isinstance(m, dict) and m.get("executor_id") == eid:
                        # a respawned node re-registering within the epoch
                        # replaces its stale reservation instead of
                        # corrupting the frozen spec with a duplicate
                        logger.info(
                            "replacing reservation of executor %s", eid)
                        self._reservations[i] = meta
                        return
            self._reservations.append(meta)

    def reset(self):
        with self._lock:
            self._reservations = []

    def resize(self, required):
        """Change how many registrations complete the cluster (elastic
        recovery re-forms a smaller — or re-grown — incarnation over the
        surviving executors; cluster.py:_resize_cluster)."""
        with self._lock:
            self.required = int(required)

    def done(self):
        with self._lock:
            return len(self._reservations) >= self.required

    def get(self):
        with self._lock:
            return list(self._reservations)

    def remaining(self):
        with self._lock:
            return self.required - len(self._reservations)


class MessageSocket:
    """Length-prefixed JSON datagrams over a stream socket."""

    # a corrupt or hostile length prefix must not make either end buffer
    # up to 4GB from one connection.  64MB leaves orders of magnitude of
    # headroom over the largest legitimate frame (the QINFO reservations
    # list: ~100 bytes/node, so ~640k nodes) while bounding the damage.
    MAX_FRAME = 64 << 20

    def receive(self, sock):
        header = self._recv_exact(sock, _HEADER.size)
        if header is None:
            return None
        (length,) = _HEADER.unpack(header)
        if length > self.MAX_FRAME:
            logger.warning(
                "dropping connection: frame length %d exceeds %d "
                "(corrupt or hostile peer)", length, self.MAX_FRAME)
            return None
        payload = self._recv_exact(sock, length)
        if payload is None:
            return None
        return json.loads(payload.decode("utf-8"))

    def send(self, sock, msg):
        payload = json.dumps(msg).encode("utf-8")
        sock.sendall(_HEADER.pack(len(payload)) + payload)

    @staticmethod
    def _recv_exact(sock, n):
        buf = b""
        while len(buf) < n:
            chunk = sock.recv(n - len(buf))
            if not chunk:
                return None
            buf += chunk
        return buf


class Server(MessageSocket):
    """Rendezvous server run on the driver (parity: reservation.py:100-231)."""

    def __init__(self, count):
        self.reservations = Reservations(count)
        # ``done`` is the application-level STOP signal (streaming feeds
        # watch it); the server keeps *serving* until stop() so late
        # QUERY/QINFO polls from still-registering nodes never hit a dead
        # socket.
        self.done = threading.Event()
        self._closing = threading.Event()
        self._listener = None
        self._thread = None
        # Epoch fence: cluster.run(restarts=N) recovery bumps this via
        # reset(); REG messages stamped with an older epoch are rejected,
        # so a node task from the previous incarnation (e.g. an engine
        # retry racing the relaunch) can never pollute the new spec.
        self.epoch = 0
        # Feed-replay ledger: feeders report fully-consumed partitions
        # (PDONE) per feed qname; after a recovery the driver re-feeds
        # only what is NOT in the ledger.
        self._feeds = DeliveryLedger()

    def reset(self, epoch):
        """Fence a new cluster incarnation: drop all reservations and the
        STOP flag, and reject REG messages from older epochs from now on.
        The feed ledger deliberately survives (it is what makes re-feeding
        skip already-consumed partitions)."""
        self.epoch = int(epoch)
        self.reservations.reset()
        self.done.clear()
        telemetry.event("rendezvous/epoch_reset", epoch=self.epoch)
        logger.info("rendezvous: reset to epoch %d", self.epoch)

    def resize(self, required):
        """Elastic recovery: the next incarnation completes with
        ``required`` registrations (fewer after an unhealable executor
        loss, back to full strength after the pool re-grew).  Call
        before ``reset(epoch)`` relaunches the nodes."""
        old = self.reservations.required
        self.reservations.resize(required)
        telemetry.event("rendezvous/resize", from_required=old,
                        to_required=int(required))
        logger.info("rendezvous: required registrations %d -> %d",
                    old, int(required))

    def fed_partitions(self, feed="input"):
        """Sorted partition indices recorded as fully consumed for ``feed``."""
        return self._feeds.done_units(feed)

    def reset_feed(self, feed="input"):
        """Clear the consumption ledger for ``feed`` (start of a train
        call: each train() owns one replay scope)."""
        self._feeds.reset(feed)

    def start(self):
        """Bind, spawn the select() loop thread, return (host, port)."""
        listener = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        listener.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        host = os.environ.get(TFOS_SERVER_HOST) or ""
        last_err = None
        for port in _candidate_ports():
            try:
                listener.bind((host, port))
                break
            except OSError as e:  # try next candidate port
                last_err = e
        else:
            listener.close()
            raise OSError(f"no usable port from {TFOS_SERVER_PORT}: {last_err}")
        listener.listen(64)
        bound_host, bound_port = listener.getsockname()[:2]
        advertise = os.environ.get(TFOS_SERVER_HOST) or _local_ip()
        self._listener = listener
        self._thread = threading.Thread(
            target=self._serve, name="rendezvous-server", daemon=True
        )
        self._thread.start()
        addr = (advertise, bound_port)
        logger.info("rendezvous server listening on %s", addr)
        return addr

    def _serve(self):
        conns = [self._listener]
        while not self._closing.is_set():
            try:
                readable, _, _ = select.select(conns, [], [], 0.25)
            except OSError:
                break
            for sock in readable:
                if sock is self._listener:
                    try:
                        conn, _ = self._listener.accept()
                        # A stalled/fragmented client must not freeze the
                        # whole select loop in a blocking recv.
                        conn.settimeout(10.0)
                        conns.append(conn)
                    except OSError:
                        pass
                    continue
                try:
                    msg = self.receive(sock)
                except (OSError, TimeoutError, ValueError):
                    msg = None
                if msg is None:
                    conns.remove(sock)
                    sock.close()
                    continue
                self._handle_message(sock, msg)
        for sock in conns:
            try:
                sock.close()
            except OSError:
                pass

    def _handle_message(self, sock, msg):
        """REG/QUERY/QINFO/QNUM/PDONE/PQUERY/STOP
        (parity: reservation.py:130-146; PDONE/PQUERY and the epoch stamp
        are fault-tolerance extensions)."""
        kind = msg.get("type")
        if kind == "REG":
            epoch = int(msg.get("epoch", 0))
            if epoch != self.epoch:
                logger.warning(
                    "rejecting registration from epoch %d (current %d): %s",
                    epoch, self.epoch, msg.get("data"))
                self.send(sock, {"type": "REJECT",
                                 "data": {"epoch": self.epoch}})
                return
            self.reservations.add(msg["data"])
            self.send(sock, {"type": "OK"})
        elif kind == "PDONE":
            self._feeds.record(msg.get("feed", "input"), int(msg["part"]))
            self.send(sock, {"type": "OK"})
        elif kind == "PQUERY":
            self.send(sock, {
                "type": "PQUERY",
                "data": self.fed_partitions(msg.get("feed", "input")),
            })
        elif kind == "QUERY":
            self.send(sock, {"type": "QUERY", "data": self.reservations.done()})
        elif kind == "QINFO":
            self.send(sock, {"type": "QINFO", "data": self.reservations.get()})
        elif kind == "QNUM":
            self.send(sock, {"type": "QNUM", "data": self.reservations.remaining()})
        elif kind == "STOP":
            self.send(sock, {"type": "OK"})
            self.done.set()
        else:
            self.send(sock, {"type": "ERR", "data": f"unknown message {kind!r}"})

    def await_reservations(self, status=None, timeout=DEFAULT_TIMEOUT):
        """Block until every node registered (parity: reservation.py:113-128).

        ``status`` is the shared driver-side dict; an 'error' key set by the
        launcher thread aborts the wait (parity: TFCluster.py tf_status).
        """
        with telemetry.span("rendezvous/await_reservations",
                            required=self.reservations.required) as sp:
            deadline = time.time() + timeout
            while not self.reservations.done():
                if status and status.get("error"):
                    raise RuntimeError(
                        f"node startup failed: {status['error']}")
                if time.time() > deadline:
                    raise TimeoutError(
                        f"timed out waiting for "
                        f"{self.reservations.remaining()} "
                        f"of {self.reservations.required} reservations"
                    )
                time.sleep(0.1)
            got = self.reservations.get()
            sp.add(registered=len(got))
            return got

    def stop(self):
        self.done.set()
        self._closing.set()
        if self._thread is not None:
            self._thread.join(timeout=5)


class Client(MessageSocket):
    """Node-side rendezvous client (parity: reservation.py:234-301)."""

    def __init__(self, server_addr):
        self.server_addr = (server_addr[0], int(server_addr[1]))
        self._sock = self._connect()

    def _connect(self):
        last = None
        for attempt in range(MAX_RETRIES):
            try:
                return socket.create_connection(self.server_addr, timeout=30)
            except OSError as e:
                last = e
                time.sleep(2 ** attempt)
        raise ConnectionError(
            f"cannot reach rendezvous server at {self.server_addr}: {last}"
        )

    # Pure queries may be replayed on a fresh connection with no
    # server-side effect; REG/STOP/PDONE mutate state and must not be.
    IDEMPOTENT = frozenset({"QUERY", "QINFO", "QNUM", "PQUERY"})

    def _call(self, msg):
        err = None
        try:
            self.send(self._sock, msg)
            reply = self.receive(self._sock)
        except OSError as e:
            reply, err = None, e
        if reply is not None:
            return reply
        if msg.get("type") not in self.IDEMPOTENT:
            raise ConnectionError("rendezvous server closed connection"
                                  + (f" ({err})" if err else ""))
        # one transparent reconnect+replay: a dropped connection under a
        # pure query (driver restarted select loop, transient RST) should
        # not kill a node that is merely polling
        logger.warning("rendezvous connection lost during %s; reconnecting",
                       msg.get("type"))
        self.close()
        self._sock = self._connect()
        try:
            self.send(self._sock, msg)
            reply = self.receive(self._sock)
        except OSError as e:
            raise ConnectionError(
                "rendezvous server closed connection") from e
        if reply is None:
            raise ConnectionError("rendezvous server closed connection")
        return reply

    def register(self, node_meta, epoch=0):
        """Register this node, stamped with its cluster epoch.  A REJECT
        (stale epoch: the cluster recovered past this node's incarnation)
        raises — the hosting task must die so the engine can retry with
        fresh cluster metadata, or give up."""
        with telemetry.span(
                "rendezvous/register",
                job=node_meta.get("job_name") if isinstance(node_meta, dict)
                else None,
                task=node_meta.get("task_index") if isinstance(node_meta, dict)
                else None,
                epoch=epoch):
            faults.check("rendezvous.register")
            reply = self._call(
                {"type": "REG", "data": node_meta, "epoch": int(epoch)})
            if reply.get("type") == "REJECT":
                raise RuntimeError(
                    f"rendezvous registration rejected: node epoch {epoch} "
                    f"!= cluster epoch {reply['data']['epoch']} (stale node "
                    "from a previous cluster incarnation)")
            return reply

    def get_reservations(self):
        return self._call({"type": "QINFO"})["data"]

    def partition_done(self, feed, part):
        """Record partition ``part`` of ``feed`` as fully consumed."""
        return self._call({"type": "PDONE", "feed": str(feed),
                           "part": int(part)})

    def fed_partitions(self, feed="input"):
        return self._call({"type": "PQUERY", "feed": str(feed)})["data"]

    def await_reservations(self, timeout=DEFAULT_TIMEOUT):
        """Poll until the cluster is complete, then return all node metas."""
        with telemetry.span("rendezvous/await_cluster_spec") as sp:
            deadline = time.time() + timeout
            polls = 0
            while True:
                faults.check("rendezvous.query")
                if self._call({"type": "QUERY"})["data"]:
                    break
                polls += 1
                if time.time() > deadline:
                    raise TimeoutError("timed out awaiting cluster completion")
                # jittered poll: N nodes registering together must not hit
                # the server's select loop in lockstep every POLL_SECS
                time.sleep(POLL_SECS * (0.5 + random.random()))
            sp.add(polls=polls)
            return self.get_reservations()

    def request_stop(self):
        try:
            return self._call({"type": "STOP"})
        finally:
            self.close()

    def close(self):
        try:
            self._sock.close()
        except OSError:
            pass


def _local_ip():
    from tensorflowonspark_tpu.utils import get_ip_address

    return get_ip_address()
