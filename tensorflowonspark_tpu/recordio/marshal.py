"""Row-batch ⇄ typed-column marshalling, native when available.

Parity target: the reference's JVM marshalling layer
(TFModel.scala:51-239 batch2tensors/tensors2batch), where the per-dtype
conversion between rows and dense tensors runs in compiled code.  Here
the compiled path is the ``_tfos_marshal`` CPython extension
(native/marshal.c); a numpy fallback implements identical semantics so
behavior does not depend on the native build.

Dtype codes (mirror of the reference's supported SQL type matrix):
  '?' bool  'i' int32  'l' int64  'f' float32  'd' float64  'O' object
A column spec entry is ``(code, width)``: width 0 for scalar columns,
w>0 for fixed-length sequence columns (shape [n, w]).
"""

from __future__ import annotations

import importlib.machinery
import importlib.util
import os

import numpy as np

_ext = None
_ext_tried = False

_CODE_TO_DTYPE = {"?": np.bool_, "i": np.int32, "l": np.int64,
                  "f": np.float32, "d": np.float64,
                  # narrow integer columns (image bytes!): keep the
                  # native dtype on the wire instead of upcasting to
                  # int32 — a 224x224x3 uint8 image must travel as 147KB,
                  # not 588KB
                  "b": np.int8, "B": np.uint8,
                  "h": np.int16, "H": np.uint16}

# codes the C extension's per-element fill loop understands; narrow
# codes deliberately stay on the numpy path — their columns come from
# ndarray rows where one bulk np.asarray copy beats per-element boxing
_EXT_CODES = "?ilfd"

# dtypes the C reconstruction loop (columns_to_rows) can read back —
# exactly the buffer formats its format_code/value_from switch handles
_EXT_OUT_DTYPES = frozenset(
    np.dtype(t) for t in (np.bool_, np.int8, np.int32, np.int64,
                          np.float32, np.float64))


def _load_ext():
    global _ext, _ext_tried
    if _ext_tried:
        return _ext
    _ext_tried = True
    if os.environ.get("TFOS_NATIVE_MARSHAL", "1") == "0":
        return None
    here = os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
    path = os.path.join(here, "native", "_tfos_marshal.so")
    if not os.path.exists(path):
        return None
    try:
        loader = importlib.machinery.ExtensionFileLoader("_tfos_marshal", path)
        spec = importlib.util.spec_from_loader("_tfos_marshal", loader)
        mod = importlib.util.module_from_spec(spec)
        loader.exec_module(mod)
        _ext = mod
    except Exception:  # noqa: BLE001 - fall back to numpy
        _ext = None
    return _ext


def native_available():
    return _load_ext() is not None


def _ndarray_code(dtype):
    """Spec code for a numpy dtype (exact-width for narrow ints so image
    bytes never upcast on the wire; int8 'b' must NOT collide with bool
    '?', uint64 does not fit int64)."""
    if dtype.kind == "b":
        return "?"
    if dtype.kind == "i":
        return {1: "b", 2: "h", 4: "i"}.get(dtype.itemsize, "l")
    if dtype.kind == "u":
        if dtype.itemsize >= 8:
            raise ValueError("uint64 columns do not fit the int64 spec")
        # unsigned widths widen one step only where exactness demands it:
        # uint8 'B' / uint16 'H' are exact; uint32 needs int64
        return {1: "B", 2: "H"}.get(dtype.itemsize, "l")
    if dtype.kind == "f":
        return "f" if dtype.itemsize <= 4 else "d"
    raise ValueError(f"unsupported ndarray dtype {dtype}")


def infer_spec(row):
    """Column spec from one example row (the schema-less path; the CLI's
    schema_hint translates to an explicit spec via schema_to_spec)."""
    spec = []
    for v in row:
        if isinstance(v, (bool, np.bool_)):
            spec.append(("?", 0))
        elif isinstance(v, (int, np.integer)):
            spec.append(("l", 0))
        elif isinstance(v, (float, np.floating)):
            spec.append(("d", 0))
        elif isinstance(v, (bytes, str)):
            spec.append(("O", 0))
        elif isinstance(v, np.ndarray):
            if v.ndim != 1:
                raise ValueError(
                    f"spec supports 1-D array columns, got shape {v.shape}"
                )
            spec.append((_ndarray_code(v.dtype), len(v)))
        elif isinstance(v, (list, tuple)):
            if not v:
                raise ValueError("cannot infer dtype of empty sequence column")
            inner = v[0]
            if isinstance(inner, (bool, np.bool_)):
                spec.append(("?", len(v)))
            elif isinstance(inner, (int, np.integer)):
                spec.append(("l", len(v)))
            elif isinstance(inner, (float, np.floating)):
                spec.append(("d", len(v)))
            elif isinstance(inner, (bytes, str)):
                spec.append(("O", len(v)))
            else:
                raise ValueError(f"unsupported sequence element: {type(inner)}")
        else:
            raise ValueError(f"unsupported column value: {type(v)}")
    return spec


def schema_to_spec(fields, widths=None):
    """(name, dtype_str) pairs (utils.schema parse output) -> spec."""
    m = {"bool": "?", "boolean": "?", "int": "i", "integer": "i",
         "bigint": "l", "long": "l", "float": "f", "double": "d",
         "string": "O", "binary": "O"}
    spec = []
    for i, (name, dt) in enumerate(fields):
        base = dt
        width = 0
        if dt.startswith("array<") and dt.endswith(">"):
            base = dt[6:-1]
            width = (widths or {}).get(name, -1)
        code = m.get(base)
        if code is None:
            raise ValueError(f"unsupported schema type {dt} for {name}")
        spec.append((code, width))
    return spec


def rows_to_columns(rows, spec=None):
    """Batch of row tuples -> tuple of dense per-column arrays.

    Object ('O') columns always take the numpy path (the native layer
    handles the numeric matrix; strings/bytes stay python objects, like
    the reference's byte-string tensors)."""
    rows = list(rows)
    if not rows:
        return ()
    if spec is None:
        spec = infer_spec(rows[0])
    ext = _load_ext()
    if ext is not None and all(c in _EXT_CODES for c, _ in spec):
        return ext.rows_to_columns(rows, [(c, int(w)) for c, w in spec])
    # numpy fallback (identical semantics)
    for i, r in enumerate(rows):
        if len(r) != len(spec):
            raise ValueError(
                f"row {i} has {len(r)} fields, spec has {len(spec)} columns"
            )
    out = []
    for c, (code, width) in enumerate(spec):
        vals = [r[c] for r in rows]
        if code == "O":
            arr = np.empty(len(vals), dtype=object)
            arr[:] = vals
        else:
            if code in "?ilbBhH":
                # a spec inferred from an int first row must not silently
                # truncate floats that appear in later rows — reject the
                # lossy cast so callers fall back to the exact row path
                natural = np.asarray(vals)
                if natural.dtype.kind == "f" or (
                    code == "?" and natural.dtype.kind != "b"
                ):
                    raise ValueError(
                        f"column {c}: {natural.dtype} values under spec "
                        f"{code!r} (lossy cast refused)"
                    )
                target = _CODE_TO_DTYPE[code]
                if code != "?" and natural.dtype != np.dtype(target):
                    # narrowing (or sign-crossing) casts are checked by
                    # VALUE range, like the C fill loop's int32 guard
                    info = np.iinfo(target)
                    if (natural > info.max).any() or (natural < info.min).any():
                        raise ValueError(
                            f"column {c}: values overflow the "
                            f"{np.dtype(target).name} spec"
                        )
                arr = natural.astype(target, copy=False)
            else:
                arr = np.asarray(vals, dtype=_CODE_TO_DTYPE[code])
            if width and arr.shape[1:] != (width,):
                raise ValueError(
                    f"column {c}: shape {arr.shape[1:]} != width {width}"
                )
        out.append(arr)
    return tuple(out)


def columns_to_rows(columns):
    """Dense per-column arrays -> list of row tuples.

    1-D columns yield python scalars; 2-D columns yield python lists
    (parity: tensors2batch's scalar-vs-Seq rule, TFModel.scala:121-239).
    """
    columns = [np.ascontiguousarray(a) for a in columns]
    ext = _load_ext()
    if ext is not None and all(
        a.dtype in _EXT_OUT_DTYPES and a.ndim in (1, 2) for a in columns
    ):
        return ext.columns_to_rows(columns)
    n = len(columns[0]) if columns else 0
    cols = []
    for a in columns:
        if a.ndim <= 1:
            cols.append(a.tolist())
        else:
            # per-row nested lists; ndim>2 keeps its nesting (the ext path
            # only handles ndim<=2, so those arrays always land here)
            cols.append([row.tolist() for row in a])
    return [tuple(col[i] for col in cols) for i in range(n)]
