"""Native JPEG decode for the host input path (ctypes over
``native/jpegdec.c``; PIL fallback).

Re-implements the host-side image decode the reference delegates to
``tf.image.decode_jpeg`` (reference
examples/resnet/imagenet_preprocessing.py:88-118 — JPEG bytes to an
RGB tensor resized for the model).  Two wins over the PIL path
measured in PERF.md (~700 img/s, GIL-bound):

- the C call releases the GIL (ctypes), so ``decode_batch`` scales
  across a thread pool instead of serializing on the interpreter;
- libjpeg DCT scaling decodes directly at 1/2, 1/4 or 1/8 resolution
  when a target size is given — most of ImageNet never gets decoded
  at full resolution at all.

``decode_resized`` matches ``imagenet_records.decode_record``'s
contract: RGB uint8 ``[size, size, 3]``, bilinear.
"""

from __future__ import annotations

import ctypes
import io
import os

import numpy as np

_LIB = None
_TRIED = False


def _load():
    global _LIB, _TRIED
    if _TRIED:
        return _LIB
    _TRIED = True
    if os.environ.get("TFOS_NO_NATIVE") == "1":
        return None
    here = os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    path = os.path.join(here, "native", "libtfos_native.so")
    try:
        lib = ctypes.CDLL(path)
        lib.tfos_jpeg_decode.restype = ctypes.c_int
        lib.tfos_jpeg_decode.argtypes = [
            ctypes.c_char_p, ctypes.c_size_t, ctypes.c_int,
            ctypes.c_void_p, ctypes.c_size_t,
            ctypes.POINTER(ctypes.c_int), ctypes.POINTER(ctypes.c_int)]
        lib.tfos_jpeg_info.restype = ctypes.c_int
        lib.tfos_jpeg_info.argtypes = [
            ctypes.c_char_p, ctypes.c_size_t, ctypes.c_int,
            ctypes.POINTER(ctypes.c_int), ctypes.POINTER(ctypes.c_int)]
        lib.tfos_jpeg_decode_resized.restype = ctypes.c_int
        lib.tfos_jpeg_decode_resized.argtypes = [
            ctypes.c_char_p, ctypes.c_size_t, ctypes.c_int,
            ctypes.c_void_p, ctypes.c_size_t, ctypes.c_void_p]
        _LIB = lib
    except (OSError, AttributeError):
        _LIB = None
    return _LIB


def available():
    return _load() is not None


def _pil_decode(data):
    from PIL import Image

    try:
        img = Image.open(io.BytesIO(data)).convert("RGB")
        return np.asarray(img, np.uint8)
    except Exception as e:  # noqa: BLE001 - normalize PIL's error zoo
        raise ValueError(f"not a decodable JPEG ({e})") from None


def decode_rgb(data, target_min=0):
    """JPEG bytes → RGB uint8 [h, w, 3].  ``target_min > 0`` allows a
    DCT-scaled decode whose min(h, w) is still >= target_min (exact
    final sizing is the caller's job).  Raises ValueError on corrupt
    input.

    The native decoder is STRICT (any libjpeg warning — truncation
    padded with a fake EOI, unsupported color transforms like CMYK —
    fails); failures retry through PIL, so weird-but-valid JPEGs
    degrade to the old path and truly corrupt data still raises."""
    lib = _load()
    if lib is None:
        return _pil_decode(data)
    if not isinstance(data, bytes):
        data = bytes(data)  # ctypes c_char_p rejects bytearray/memoryview
    w = ctypes.c_int()
    h = ctypes.c_int()
    if lib.tfos_jpeg_info(data, len(data), int(target_min),
                          ctypes.byref(w), ctypes.byref(h)) != 0:
        return _pil_decode(data)
    out = np.empty((h.value, w.value, 3), np.uint8)  # scaled-size bound
    rc = lib.tfos_jpeg_decode(
        data, len(data), int(target_min),
        out.ctypes.data_as(ctypes.c_void_p), out.nbytes,
        ctypes.byref(w), ctypes.byref(h))
    if rc != 0:
        return _pil_decode(data)
    if (h.value, w.value) != out.shape[:2]:
        out = out.reshape(-1)[: h.value * w.value * 3]
        out = out.reshape(h.value, w.value, 3)
    return out


def _resize_bilinear(img, size):
    """uint8 [h, w, c] → [size, size, c], half-pixel-center bilinear
    (PIL-convention sampling), vectorized numpy."""
    h, w = img.shape[:2]
    if (h, w) == (size, size):
        return img
    ys = (np.arange(size, dtype=np.float32) + 0.5) * (h / size) - 0.5
    xs = (np.arange(size, dtype=np.float32) + 0.5) * (w / size) - 0.5
    ys = np.clip(ys, 0, h - 1)
    xs = np.clip(xs, 0, w - 1)
    y0 = np.minimum(ys.astype(np.int32), h - 2) if h > 1 else \
        np.zeros(size, np.int32)
    x0 = np.minimum(xs.astype(np.int32), w - 2) if w > 1 else \
        np.zeros(size, np.int32)
    wy = (ys - y0)[:, None, None]
    wx = (xs - x0)[None, :, None]
    f = img.astype(np.float32)
    y1 = np.minimum(y0 + 1, h - 1)
    x1 = np.minimum(x0 + 1, w - 1)
    top = f[y0][:, x0] * (1 - wx) + f[y0][:, x1] * wx
    bot = f[y1][:, x0] * (1 - wx) + f[y1][:, x1] * wx
    return np.clip(top * (1 - wy) + bot * wy + 0.5, 0, 255).astype(np.uint8)


def decode_resized(data, size, _out=None):
    """JPEG bytes → RGB uint8 [size, size, 3] (bilinear).  Native path:
    DCT-scaled decode + C resize in ONE GIL-free call; fallback: PIL
    decode + numpy bilinear."""
    lib = _load()
    if lib is None:
        return _resize_bilinear(_pil_decode(data), size)
    if not isinstance(data, bytes):
        data = bytes(data)  # ctypes c_char_p rejects bytearray/memoryview
    w = ctypes.c_int()
    h = ctypes.c_int()
    out = _out if _out is not None else np.empty((size, size, 3), np.uint8)
    if lib.tfos_jpeg_info(data, len(data), size,
                          ctypes.byref(w), ctypes.byref(h)) != 0:
        out[...] = _resize_bilinear(_pil_decode(data), size)
        return out
    scratch = np.empty((h.value, w.value, 3), np.uint8)  # DCT-scaled size
    rc = lib.tfos_jpeg_decode_resized(
        data, len(data), size,
        scratch.ctypes.data_as(ctypes.c_void_p), scratch.nbytes,
        out.ctypes.data_as(ctypes.c_void_p))
    if rc != 0:
        # strict native failure (warning/truncation/CMYK): arbitrate
        # through PIL — valid-but-odd images decode, corrupt ones raise
        out[...] = _resize_bilinear(_pil_decode(data), size)
    return out


def decode_batch(datas, size, threads=None):
    """Decode many JPEGs concurrently → uint8 [n, size, size, 3].

    The native decode+resize releases the GIL for its whole duration,
    so a thread pool scales with cores (the PIL path stays sequential —
    threads would serialize on the interpreter)."""
    n = len(datas)
    out = np.empty((n, size, size, 3), np.uint8)
    if _load() is None or n <= 1:
        for i, d in enumerate(datas):
            out[i] = decode_resized(d, size)
        return out
    from concurrent.futures import ThreadPoolExecutor

    threads = threads or min(8, os.cpu_count() or 1)

    def work(i):
        decode_resized(datas[i], size, _out=out[i])

    with ThreadPoolExecutor(max_workers=threads) as pool:
        list(pool.map(work, range(n)))
    return out
