"""Filesystem routing for record IO: local fast path + fsspec for the rest.

The reference reads and writes TFRecords on any Hadoop-compatible
filesystem through the tensorflow-hadoop InputFormat/OutputFormat
(reference dfutil.py:39-41,63-65, DFUtil.scala:37-40).  The TPU-native
equivalent routes remote schemes (gs://, hdfs://, s3://, memory://, ...)
through fsspec while plain local paths keep hitting the C library's
fopen-based reader/writer directly.
"""

from __future__ import annotations

import os
import re

_SCHEME_RE = re.compile(r"^([a-zA-Z][a-zA-Z0-9+.-]*)://")


def scheme_of(path) -> str | None:
    """URL scheme of a path, or None for plain local paths.

    Windows drive letters don't appear here (TPU hosts are linux), so a
    single-letter scheme is not special-cased.
    """
    m = _SCHEME_RE.match(str(path))
    return m.group(1).lower() if m else None


def is_local(path) -> bool:
    s = scheme_of(path)
    return s is None or s in ("file", "local")


def local_path(path) -> str:
    """Strip a file:// prefix down to an OS path (reference hdfs_path's
    'file://' row, TFNode.py:40-49)."""
    p = str(path)
    s = scheme_of(p)
    if s in ("file", "local"):
        return p[len(s) + 3:] or "/"
    return p


def get_fs(path):
    """(fsspec filesystem, path-within-fs) for any URL."""
    import fsspec

    return fsspec.core.url_to_fs(str(path))


def open_file(path, mode="rb"):
    """Open local paths with plain open(); remote through fsspec."""
    if is_local(path):
        return open(local_path(path), mode)
    fs, p = get_fs(path)
    return fs.open(p, mode)


def read_bytes(path) -> bytes:
    with open_file(path, "rb") as f:
        return f.read()


def write_bytes(path, data: bytes):
    with open_file(path, "wb") as f:
        f.write(data)


def makedirs(path):
    if is_local(path):
        os.makedirs(local_path(path), exist_ok=True)
    else:
        fs, p = get_fs(path)
        fs.makedirs(p, exist_ok=True)


def isdir(path) -> bool:
    if is_local(path):
        return os.path.isdir(local_path(path))
    fs, p = get_fs(path)
    return fs.isdir(p)


def exists(path) -> bool:
    if is_local(path):
        return os.path.exists(local_path(path))
    fs, p = get_fs(path)
    return fs.exists(p)


def remove(path):
    if is_local(path):
        os.remove(local_path(path))
    else:
        fs, p = get_fs(path)
        fs.rm(p)


def listdir(path):
    """Names (not full paths) of a directory's entries."""
    if is_local(path):
        return os.listdir(local_path(path))
    fs, p = get_fs(path)
    return [name.rstrip("/").rsplit("/", 1)[-1] for name in fs.ls(p, detail=False)]


def join(path, *parts) -> str:
    """Join that preserves the URL scheme (os.path.join would not)."""
    base = str(path).rstrip("/")
    tail = "/".join(str(p).strip("/") for p in parts)
    return f"{base}/{tail}" if tail else base
