"""Pure-Python TFRecord framing + tf.train.Example wire codec.

Fallback for environments without the native library; semantics match
native/tfrecord.cpp exactly (same format, same masked crc32c).
"""

from __future__ import annotations

import struct

# -- crc32c ------------------------------------------------------------------

_TABLE = []


def _crc_table():
    if _TABLE:
        return _TABLE
    poly = 0x82F63B78
    for i in range(256):
        c = i
        for _ in range(8):
            c = (poly ^ (c >> 1)) if c & 1 else (c >> 1)
        _TABLE.append(c)
    return _TABLE


def crc32c(data: bytes) -> int:
    table = _crc_table()
    c = 0xFFFFFFFF
    for b in data:
        c = table[(c ^ b) & 0xFF] ^ (c >> 8)
    return c ^ 0xFFFFFFFF


def masked_crc(data: bytes) -> int:
    crc = crc32c(data)
    return (((crc >> 15) | (crc << 17)) + 0xA282EAD8) & 0xFFFFFFFF


# -- framing -----------------------------------------------------------------

def write_record(f, data: bytes):
    header = struct.pack("<Q", len(data))
    f.write(header)
    f.write(struct.pack("<I", masked_crc(header)))
    f.write(data)
    f.write(struct.pack("<I", masked_crc(data)))


def read_records(f):
    while True:
        header = f.read(12)
        if not header:
            return
        if len(header) != 12:
            raise IOError("truncated TFRecord header")
        (length,) = struct.unpack("<Q", header[:8])
        (lcrc,) = struct.unpack("<I", header[8:])
        if masked_crc(header[:8]) != lcrc:
            raise IOError("corrupt TFRecord length crc")
        data = f.read(length)
        if len(data) != length:
            raise IOError("truncated TFRecord data")
        (dcrc,) = struct.unpack("<I", f.read(4))
        if masked_crc(data) != dcrc:
            raise IOError("corrupt TFRecord data crc")
        yield data


# -- proto wire helpers ------------------------------------------------------

def _varint(v: int) -> bytes:
    out = bytearray()
    while v >= 0x80:
        out.append((v & 0x7F) | 0x80)
        v >>= 7
    out.append(v)
    return bytes(out)


def _read_varint(buf, pos):
    r = 0
    shift = 0
    while True:
        b = buf[pos]
        pos += 1
        r |= (b & 0x7F) << shift
        if not b & 0x80:
            return r, pos
        shift += 7


def _len_delim(field: int, payload: bytes) -> bytes:
    return _varint(field << 3 | 2) + _varint(len(payload)) + payload


# -- Example encode ----------------------------------------------------------

def encode_example(features: dict) -> bytes:
    """features: {name: (kind, values)} with kind in {'bytes','float','int64'}
    and values a list."""
    fmap = b""
    for name in sorted(features):
        kind, values = features[name]
        if kind == "int64":
            packed = b"".join(_varint(v & 0xFFFFFFFFFFFFFFFF) for v in values)
            feature = _len_delim(3, _len_delim(1, packed))
        elif kind == "float":
            packed = struct.pack(f"<{len(values)}f", *values)
            feature = _len_delim(2, _len_delim(1, packed))
        elif kind == "bytes":
            lst = b"".join(_len_delim(1, v) for v in values)
            feature = _len_delim(1, lst)
        else:
            raise ValueError(f"unknown feature kind {kind!r}")
        entry = _len_delim(1, name.encode()) + _len_delim(2, feature)
        fmap += _len_delim(1, entry)
    return _len_delim(1, fmap)


# -- Example decode ----------------------------------------------------------

_KINDS = {1: "bytes", 2: "float", 3: "int64"}


def decode_example(data: bytes) -> dict:
    """Returns {name: (kind, values)}."""
    out = {}
    pos = 0
    end = len(data)
    while pos < end:
        tag, pos = _read_varint(data, pos)
        length, pos = _read_varint(data, pos)
        fend = pos + length
        if tag >> 3 == 1:  # Features
            q = pos
            while q < fend:
                etag, q = _read_varint(data, q)
                elen, q = _read_varint(data, q)
                eend = q + elen
                name, kind, values = None, None, []
                m = q
                while m < eend:
                    mtag, m = _read_varint(data, m)
                    mlen, m = _read_varint(data, m)
                    if mtag >> 3 == 1:
                        name = data[m:m + mlen].decode()
                    elif mtag >> 3 == 2:
                        kind, values = _decode_feature(data[m:m + mlen])
                    m += mlen
                if name is not None:
                    out[name] = (kind, values)
                q = eend
        pos = fend
    return out


def _decode_feature(buf: bytes):
    pos = 0
    end = len(buf)
    kind = None
    values = []
    while pos < end:
        tag, pos = _read_varint(buf, pos)
        field = tag >> 3
        length, pos = _read_varint(buf, pos)
        lend = pos + length
        kind = _KINDS.get(field)
        q = pos
        while q < lend:
            vtag, q = _read_varint(buf, q)
            vwire = vtag & 7
            if field == 1:  # bytes
                blen, q = _read_varint(buf, q)
                values.append(buf[q:q + blen])
                q += blen
            elif field == 2:  # float: packed or single fixed32
                if vwire == 2:
                    blen, q = _read_varint(buf, q)
                    values.extend(struct.unpack(f"<{blen // 4}f", buf[q:q + blen]))
                    q += blen
                else:
                    values.extend(struct.unpack("<f", buf[q:q + 4]))
                    q += 4
            elif field == 3:  # int64: packed or single varint
                if vwire == 2:
                    blen, q = _read_varint(buf, q)
                    vend = q + blen
                    while q < vend:
                        v, q = _read_varint(buf, q)
                        values.append(v - (1 << 64) if v >= 1 << 63 else v)
                else:
                    v, q = _read_varint(buf, q)
                    values.append(v - (1 << 64) if v >= 1 << 63 else v)
        pos = lend
    return kind, values
