"""Record IO: TFRecord files + tf.train.Example codec, native-accelerated.

Component parity (SURVEY.md §2.2 ⚙): the reference vendors the
tensorflow-hadoop jar for record-level TFRecord IO and does Example⇄Row
marshalling in Scala/JNI; here a C++ library (native/tfrecord.cpp) does
framing, crc32c, and Example wire encode/decode, loaded via ctypes with a
pure-Python fallback (pyimpl.py).  No TensorFlow dependency anywhere.

API:
    with TFRecordWriter(path) as w: w.write(b"...")
    for rec in TFRecordReader(path): ...
    encode_example({"x": ("float", [1.0])}) -> bytes
    decode_example(b) -> {"x": ("float", [1.0])}
"""

from __future__ import annotations

import ctypes
import os as _os

import numpy as _np

from tensorflowonspark_tpu.recordio import fs as _fs
from tensorflowonspark_tpu.recordio import native as _native
from tensorflowonspark_tpu.recordio import pyimpl as _py


class TFRecordWriter:
    """Writes TFRecord framing to any filesystem.

    Local paths go straight through the C library's buffered FILE* writer;
    remote URLs (gs://, hdfs://, s3://, memory://) are framed in memory by
    the C codec and flushed to the object store through fsspec on close
    (objects on these stores are immutable — a single terminal PUT is the
    native write pattern, not a defect of this path).
    """

    def __init__(self, path):
        self._lib = _native.load()
        self._h = self._mh = self._f = None
        self._remote_path = None
        if _fs.is_local(path):
            lp = _fs.local_path(path)
            if self._lib is not None:
                self._h = self._lib.tfr_writer_open(str(lp).encode())
                if not self._h:
                    raise IOError(f"cannot open {lp} for writing")
            else:
                self._f = open(lp, "wb")
        elif self._lib is not None and getattr(self._lib, "_tfos_mem_api", False):
            self._mh = self._lib.tfr_mem_writer_new()
            self._remote_path = str(path)
        else:
            self._f = _fs.open_file(path, "wb")

    def write(self, data: bytes):
        if self._h is not None:
            if self._lib.tfr_writer_write(self._h, data, len(data)) != 0:
                raise IOError("TFRecord write failed")
        elif self._mh is not None:
            self._lib.tfr_mem_writer_write(self._mh, data, len(data))
        else:
            _py.write_record(self._f, data)

    def close(self):
        if self._h is not None:
            self._lib.tfr_writer_close(self._h)
            self._h = None
        elif self._mh is not None:
            try:
                n = ctypes.c_uint64()
                p = self._lib.tfr_mem_writer_data(self._mh, ctypes.byref(n))
                _fs.write_bytes(self._remote_path,
                                ctypes.string_at(p, n.value) if n.value else b"")
            finally:
                self._lib.tfr_mem_writer_free(self._mh)
                self._mh = None
        elif self._f is not None:
            self._f.close()
            self._f = None

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()


class TFRecordReader:
    """Iterates raw record bytes from one TFRecord file on any filesystem."""

    def __init__(self, path):
        self._path = path
        self._lib = _native.load()

    def __iter__(self):
        if _fs.is_local(self._path):
            yield from self._iter_local()
        else:
            yield from self._iter_remote()

    def _iter_local(self):
        if self._lib is not None:
            h = self._lib.tfr_reader_open(
                str(_fs.local_path(self._path)).encode()
            )
            if not h:
                raise IOError(f"cannot open {self._path}")
            try:
                buf = ctypes.POINTER(ctypes.c_uint8)()
                while True:
                    n = self._lib.tfr_reader_next(h, ctypes.byref(buf))
                    if n == -1:
                        return  # clean EOF
                    if n < -1:
                        raise IOError(f"corrupt TFRecord ({n}) in {self._path}")
                    yield ctypes.string_at(buf, n) if n else b""
            finally:
                self._lib.tfr_reader_close(h)
        else:
            with open(_fs.local_path(self._path), "rb") as f:
                yield from _py.read_records(f)

    def _iter_remote(self):
        data = _fs.read_bytes(self._path)
        if self._lib is not None and getattr(self._lib, "_tfos_mem_api", False):
            h = self._lib.tfr_mem_reader_new(data, len(data))
            try:
                buf = ctypes.POINTER(ctypes.c_uint8)()
                while True:
                    n = self._lib.tfr_mem_reader_next(h, ctypes.byref(buf))
                    if n == -1:
                        return
                    if n < -1:
                        raise IOError(f"corrupt TFRecord ({n}) in {self._path}")
                    yield ctypes.string_at(buf, n) if n else b""
            finally:
                self._lib.tfr_mem_reader_free(h)
        else:
            import io

            yield from _py.read_records(io.BytesIO(data))


def encode_example(features: dict) -> bytes:
    """{name: (kind, values)} → serialized tf.train.Example."""
    lib = _native.load()
    if lib is None:
        return _py.encode_example(features)
    b = lib.exb_new()
    try:
        for name in sorted(features):
            kind, values = features[name]
            cname = name.encode()
            if kind == "int64":
                arr = (ctypes.c_int64 * len(values))(*values)
                lib.exb_add_int64(b, cname, arr, len(values))
            elif kind == "float":
                arr = (ctypes.c_float * len(values))(*values)
                lib.exb_add_float(b, cname, arr, len(values))
            elif kind == "bytes":
                bufs = (ctypes.c_char_p * len(values))(*values)
                lens = (ctypes.c_uint64 * len(values))(*[len(v) for v in values])
                lib.exb_add_bytes(b, cname, bufs, lens, len(values))
            else:
                raise ValueError(f"unknown feature kind {kind!r}")
        n = ctypes.c_uint64()
        p = lib.exb_serialize(b, ctypes.byref(n))
        return ctypes.string_at(p, n.value)
    finally:
        lib.exb_free(b)


def decode_example(data: bytes) -> dict:
    """Serialized tf.train.Example → {name: (kind, values)}."""
    lib = _native.load()
    if lib is None:
        return _py.decode_example(data)
    d = lib.exd_parse(data, len(data))
    if not d:
        raise ValueError("unparseable tf.train.Example")
    try:
        out = {}
        for i in range(lib.exd_num_features(d)):
            name = lib.exd_name(d, i).decode()
            kind = lib.exd_kind(d, i)
            cnt = lib.exd_value_count(d, i)
            if kind == 2:
                # bulk-copy the C value buffer: per-element ctypes
                # indexing costs ~100ns/value (~80us for a 784-float
                # feature); one string_at + frombuffer + tolist is ~2us
                p = lib.exd_floats(d, i)
                out[name] = ("float", _np.frombuffer(
                    ctypes.string_at(p, cnt * 4), _np.float32).tolist())
            elif kind == 3:
                p = lib.exd_int64s(d, i)
                out[name] = ("int64", _np.frombuffer(
                    ctypes.string_at(p, cnt * 8), _np.int64).tolist())
            elif kind == 1:
                vals = []
                n = ctypes.c_uint64()
                for j in range(cnt):
                    p = lib.exd_bytes(d, i, j, ctypes.byref(n))
                    vals.append(ctypes.string_at(p, n.value))
                out[name] = ("bytes", vals)
            else:
                out[name] = (None, [])
        return out
    finally:
        lib.exd_free(d)


def load_columnar(path):
    """Bulk-load one TFRecord file of tf.train.Examples into dense
    per-feature columns: {name: (kind, column)} where column is an
    ndarray [n] / [n, w] for float/int64 features and a list of bytes
    (or list of lists for multi-value) for bytes features.

    One C pass over the whole file — no per-value Python objects — the
    TPU-shaped replacement for the reference's per-row Example decode
    (DFUtil.scala:119-184): columns are ready for np slicing into device
    batches.  Requires a fixed schema across records (taken from the
    first record); ragged or schema-drifting files fall back to per-row
    ``decode_example`` with identical results.
    """
    if _fs.is_local(path) and _os.path.isdir(_fs.local_path(path)):
        # fopen(dir) "succeeds" with zero reads = silent empty result;
        # a directory here is a caller mix-up (use dfutil's loaders for
        # shard dirs)
        raise IsADirectoryError(
            f"{path} is a directory; pass a shard file (or use "
            "dfutil.load_tfrecords_columnar / iter_tfrecords_columnar "
            "for a shard dir)")
    lib = _native.load()
    if lib is None or not getattr(lib, "_tfos_colb_api", False):
        return _columnar_fallback(path)
    if _fs.is_local(path):
        h = lib.tfr_load_columnar(str(_fs.local_path(path)).encode())
    else:
        data = _fs.read_bytes(path)
        h = lib.tfr_load_columnar_mem(data, len(data))
    if not h:
        raise MemoryError("columnar load allocation failed")
    try:
        if not lib.colb_ok(h):
            err = lib.colb_error(h).decode()
            # IO errors use these exact fixed strings (tfrecord.cpp); all
            # other errors are schema-shaped (ragged/drifting/repeated
            # features, named inside quotes) and take the per-row fallback
            if err == "cannot open file" or err.startswith(
                    "corrupt TFRecord framing"):
                raise IOError(f"{err}: {path}")
            return _columnar_fallback(path)
        n = lib.colb_num_rows(h)
        out = {}
        for i in range(lib.colb_num_features(h)):
            name = lib.colb_name(h, i).decode()
            kind = lib.colb_kind(h, i)
            w = lib.colb_width(h, i)
            if kind == 2:
                if n * w == 0:  # empty column: C buffer may be NULL
                    a = _np.zeros((n, w), _np.float32)
                else:
                    a = _np.ctypeslib.as_array(
                        lib.colb_floats(h, i), (n, w))  # view; one copy below
                out[name] = ("float", a[:, 0].copy() if w == 1 else a.copy())
            elif kind == 3:
                if n * w == 0:
                    a = _np.zeros((n, w), _np.int64)
                else:
                    a = _np.ctypeslib.as_array(lib.colb_int64s(h, i), (n, w))
                out[name] = ("int64", a[:, 0].copy() if w == 1 else a.copy())
            elif kind == 1:
                offs = _np.frombuffer(
                    ctypes.string_at(lib.colb_bytes_offsets(h, i),
                                     (n * w + 1) * 8), _np.uint64)
                blob = ctypes.string_at(lib.colb_bytes_blob(h, i),
                                        int(offs[-1])) if n * w else b""
                vals = [blob[int(offs[j]):int(offs[j + 1])]
                        for j in range(n * w)]
                if w == 1:
                    out[name] = ("bytes", vals)
                else:
                    out[name] = ("bytes", [vals[j * w:(j + 1) * w]
                                           for j in range(n)])
            else:
                out[name] = (None, [None] * n)
        return out
    finally:
        lib.colb_free(h)


def _columnar_fallback(path):
    """Per-row decode assembled into columns (pure-python / ragged path).
    Ragged numeric features stay lists-of-lists; fixed-width ones become
    the same arrays the native path produces."""
    names = None
    cols = {}
    kinds = {}
    n = 0
    for rec in TFRecordReader(path):
        row = decode_example(rec)
        if names is None:
            names = sorted(row)
            for name in names:
                kinds[name], _ = row[name]
                cols[name] = []
        elif set(row) != set(names):
            # surfacing drift beats silently dropping the extra features
            raise ValueError(
                f"record {n} features {sorted(row)} do not match the "
                f"first record's schema {names}; use the row-level "
                "load_tfrecords for schema-drifting files")
        for name in names:
            kind, values = row.get(name, (None, None))
            if values is None:
                raise ValueError(
                    f"record {n} is missing feature {name!r}")
            cols[name].append(values[0] if len(values) == 1 else values)
        n += 1
    out = {}
    for name in (names or []):
        vals = cols[name]
        kind = kinds[name]
        if kind in ("float", "int64"):
            widths = {1 if not isinstance(v, list) else len(v) for v in vals}
            if len(widths) == 1:
                dt = _np.float32 if kind == "float" else _np.int64
                out[name] = (kind, _np.asarray(vals, dt))
                continue
        out[name] = (kind, vals)
    return out
