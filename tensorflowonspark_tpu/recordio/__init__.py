"""Record IO: TFRecord files + tf.train.Example codec, native-accelerated.

Component parity (SURVEY.md §2.2 ⚙): the reference vendors the
tensorflow-hadoop jar for record-level TFRecord IO and does Example⇄Row
marshalling in Scala/JNI; here a C++ library (native/tfrecord.cpp) does
framing, crc32c, and Example wire encode/decode, loaded via ctypes with a
pure-Python fallback (pyimpl.py).  No TensorFlow dependency anywhere.

API:
    with TFRecordWriter(path) as w: w.write(b"...")
    for rec in TFRecordReader(path): ...
    encode_example({"x": ("float", [1.0])}) -> bytes
    decode_example(b) -> {"x": ("float", [1.0])}
"""

from __future__ import annotations

import ctypes

from tensorflowonspark_tpu.recordio import fs as _fs
from tensorflowonspark_tpu.recordio import native as _native
from tensorflowonspark_tpu.recordio import pyimpl as _py


class TFRecordWriter:
    """Writes TFRecord framing to any filesystem.

    Local paths go straight through the C library's buffered FILE* writer;
    remote URLs (gs://, hdfs://, s3://, memory://) are framed in memory by
    the C codec and flushed to the object store through fsspec on close
    (objects on these stores are immutable — a single terminal PUT is the
    native write pattern, not a defect of this path).
    """

    def __init__(self, path):
        self._lib = _native.load()
        self._h = self._mh = self._f = None
        self._remote_path = None
        if _fs.is_local(path):
            lp = _fs.local_path(path)
            if self._lib is not None:
                self._h = self._lib.tfr_writer_open(str(lp).encode())
                if not self._h:
                    raise IOError(f"cannot open {lp} for writing")
            else:
                self._f = open(lp, "wb")
        elif self._lib is not None and getattr(self._lib, "_tfos_mem_api", False):
            self._mh = self._lib.tfr_mem_writer_new()
            self._remote_path = str(path)
        else:
            self._f = _fs.open_file(path, "wb")

    def write(self, data: bytes):
        if self._h is not None:
            if self._lib.tfr_writer_write(self._h, data, len(data)) != 0:
                raise IOError("TFRecord write failed")
        elif self._mh is not None:
            self._lib.tfr_mem_writer_write(self._mh, data, len(data))
        else:
            _py.write_record(self._f, data)

    def close(self):
        if self._h is not None:
            self._lib.tfr_writer_close(self._h)
            self._h = None
        elif self._mh is not None:
            try:
                n = ctypes.c_uint64()
                p = self._lib.tfr_mem_writer_data(self._mh, ctypes.byref(n))
                _fs.write_bytes(self._remote_path,
                                ctypes.string_at(p, n.value) if n.value else b"")
            finally:
                self._lib.tfr_mem_writer_free(self._mh)
                self._mh = None
        elif self._f is not None:
            self._f.close()
            self._f = None

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()


class TFRecordReader:
    """Iterates raw record bytes from one TFRecord file on any filesystem."""

    def __init__(self, path):
        self._path = path
        self._lib = _native.load()

    def __iter__(self):
        if _fs.is_local(self._path):
            yield from self._iter_local()
        else:
            yield from self._iter_remote()

    def _iter_local(self):
        if self._lib is not None:
            h = self._lib.tfr_reader_open(
                str(_fs.local_path(self._path)).encode()
            )
            if not h:
                raise IOError(f"cannot open {self._path}")
            try:
                buf = ctypes.POINTER(ctypes.c_uint8)()
                while True:
                    n = self._lib.tfr_reader_next(h, ctypes.byref(buf))
                    if n == -1:
                        return  # clean EOF
                    if n < -1:
                        raise IOError(f"corrupt TFRecord ({n}) in {self._path}")
                    yield ctypes.string_at(buf, n) if n else b""
            finally:
                self._lib.tfr_reader_close(h)
        else:
            with open(_fs.local_path(self._path), "rb") as f:
                yield from _py.read_records(f)

    def _iter_remote(self):
        data = _fs.read_bytes(self._path)
        if self._lib is not None and getattr(self._lib, "_tfos_mem_api", False):
            h = self._lib.tfr_mem_reader_new(data, len(data))
            try:
                buf = ctypes.POINTER(ctypes.c_uint8)()
                while True:
                    n = self._lib.tfr_mem_reader_next(h, ctypes.byref(buf))
                    if n == -1:
                        return
                    if n < -1:
                        raise IOError(f"corrupt TFRecord ({n}) in {self._path}")
                    yield ctypes.string_at(buf, n) if n else b""
            finally:
                self._lib.tfr_mem_reader_free(h)
        else:
            import io

            yield from _py.read_records(io.BytesIO(data))


def encode_example(features: dict) -> bytes:
    """{name: (kind, values)} → serialized tf.train.Example."""
    lib = _native.load()
    if lib is None:
        return _py.encode_example(features)
    b = lib.exb_new()
    try:
        for name in sorted(features):
            kind, values = features[name]
            cname = name.encode()
            if kind == "int64":
                arr = (ctypes.c_int64 * len(values))(*values)
                lib.exb_add_int64(b, cname, arr, len(values))
            elif kind == "float":
                arr = (ctypes.c_float * len(values))(*values)
                lib.exb_add_float(b, cname, arr, len(values))
            elif kind == "bytes":
                bufs = (ctypes.c_char_p * len(values))(*values)
                lens = (ctypes.c_uint64 * len(values))(*[len(v) for v in values])
                lib.exb_add_bytes(b, cname, bufs, lens, len(values))
            else:
                raise ValueError(f"unknown feature kind {kind!r}")
        n = ctypes.c_uint64()
        p = lib.exb_serialize(b, ctypes.byref(n))
        return ctypes.string_at(p, n.value)
    finally:
        lib.exb_free(b)


def decode_example(data: bytes) -> dict:
    """Serialized tf.train.Example → {name: (kind, values)}."""
    lib = _native.load()
    if lib is None:
        return _py.decode_example(data)
    d = lib.exd_parse(data, len(data))
    if not d:
        raise ValueError("unparseable tf.train.Example")
    try:
        out = {}
        for i in range(lib.exd_num_features(d)):
            name = lib.exd_name(d, i).decode()
            kind = lib.exd_kind(d, i)
            cnt = lib.exd_value_count(d, i)
            if kind == 2:
                p = lib.exd_floats(d, i)
                out[name] = ("float", [p[j] for j in range(cnt)])
            elif kind == 3:
                p = lib.exd_int64s(d, i)
                out[name] = ("int64", [p[j] for j in range(cnt)])
            elif kind == 1:
                vals = []
                n = ctypes.c_uint64()
                for j in range(cnt):
                    p = lib.exd_bytes(d, i, j, ctypes.byref(n))
                    vals.append(ctypes.string_at(p, n.value))
                out[name] = ("bytes", vals)
            else:
                out[name] = (None, [])
        return out
    finally:
        lib.exd_free(d)
