"""ctypes bindings for the native record-IO / shm-queue library.

Loads ``libtfos_native.so`` (built from /native via ``make``); call sites
fall back to the pure-Python implementation (pyimpl.py) when the library
is unavailable — behavior is identical, speed is not.
"""

from __future__ import annotations

import ctypes
import logging
import os
import subprocess

logger = logging.getLogger(__name__)

_LIB = None
_TRIED = False


def _candidates():
    here = os.path.dirname(os.path.abspath(__file__))
    repo = os.path.dirname(os.path.dirname(here))
    env = os.environ.get("TFOS_NATIVE_LIB")
    if env:
        yield env
    yield os.path.join(here, "libtfos_native.so")
    yield os.path.join(repo, "native", "libtfos_native.so")


def load():
    """Load (and lazily build) the native library; None if unavailable."""
    global _LIB, _TRIED
    if _LIB is not None or _TRIED:
        return _LIB
    _TRIED = True
    for path in _candidates():
        if os.path.exists(path):
            try:
                _LIB = _bind(ctypes.CDLL(path))
                logger.info("loaded native record-io: %s", path)
                return _LIB
            except OSError as e:  # half-written or foreign .so
                logger.warning("cannot load %s: %s", path, e)
    # try building once from the in-repo sources; an exclusive flock keeps
    # N concurrently-starting executor processes from interleaving builds,
    # and losers of the race load the winner's output
    here = os.path.dirname(os.path.abspath(__file__))
    src = os.path.join(os.path.dirname(os.path.dirname(here)), "native")
    if os.path.exists(os.path.join(src, "Makefile")):
        try:
            import fcntl
            import tempfile

            lock = open(os.path.join(tempfile.gettempdir(), ".tfos-native-build.lock"), "w")
            with lock:
                fcntl.flock(lock, fcntl.LOCK_EX)
                path = os.path.join(src, "libtfos_native.so")
                if not os.path.exists(path):
                    subprocess.run(["make", "-C", src], check=True,
                                   capture_output=True)
                if os.path.exists(path):
                    _LIB = _bind(ctypes.CDLL(path))
                    logger.info("built+loaded native record-io: %s", path)
                    return _LIB
        except Exception as e:  # noqa: BLE001 - fall back to pure python
            logger.warning("native build failed (%s); using pure-python IO", e)
    return None


def _bind(lib):
    c = ctypes
    u8p = c.POINTER(c.c_uint8)

    lib.tfr_writer_open.restype = c.c_void_p
    lib.tfr_writer_open.argtypes = [c.c_char_p]
    lib.tfr_writer_write.restype = c.c_int
    lib.tfr_writer_write.argtypes = [c.c_void_p, c.c_char_p, c.c_uint64]
    lib.tfr_writer_close.restype = c.c_int
    lib.tfr_writer_close.argtypes = [c.c_void_p]

    lib.tfr_reader_open.restype = c.c_void_p
    lib.tfr_reader_open.argtypes = [c.c_char_p]
    lib.tfr_reader_next.restype = c.c_int64
    lib.tfr_reader_next.argtypes = [c.c_void_p, c.POINTER(u8p)]
    lib.tfr_reader_close.restype = c.c_int
    lib.tfr_reader_close.argtypes = [c.c_void_p]

    lib.exb_new.restype = c.c_void_p
    lib.exb_free.argtypes = [c.c_void_p]
    lib.exb_add_int64.argtypes = [c.c_void_p, c.c_char_p,
                                  c.POINTER(c.c_int64), c.c_int]
    lib.exb_add_float.argtypes = [c.c_void_p, c.c_char_p,
                                  c.POINTER(c.c_float), c.c_int]
    lib.exb_add_bytes.argtypes = [c.c_void_p, c.c_char_p,
                                  c.POINTER(c.c_char_p),
                                  c.POINTER(c.c_uint64), c.c_int]
    lib.exb_serialize.restype = u8p
    lib.exb_serialize.argtypes = [c.c_void_p, c.POINTER(c.c_uint64)]

    lib.exd_parse.restype = c.c_void_p
    lib.exd_parse.argtypes = [c.c_char_p, c.c_uint64]
    lib.exd_free.argtypes = [c.c_void_p]
    lib.exd_num_features.restype = c.c_int
    lib.exd_num_features.argtypes = [c.c_void_p]
    lib.exd_name.restype = c.c_char_p
    lib.exd_name.argtypes = [c.c_void_p, c.c_int]
    lib.exd_kind.restype = c.c_int
    lib.exd_kind.argtypes = [c.c_void_p, c.c_int]
    lib.exd_value_count.restype = c.c_int64
    lib.exd_value_count.argtypes = [c.c_void_p, c.c_int]
    lib.exd_floats.restype = c.POINTER(c.c_float)
    lib.exd_floats.argtypes = [c.c_void_p, c.c_int]
    lib.exd_int64s.restype = c.POINTER(c.c_int64)
    lib.exd_int64s.argtypes = [c.c_void_p, c.c_int]
    lib.exd_bytes.restype = u8p
    lib.exd_bytes.argtypes = [c.c_void_p, c.c_int, c.c_int,
                              c.POINTER(c.c_uint64)]

    lib.shq_create.restype = c.c_void_p
    lib.shq_create.argtypes = [c.c_char_p, c.c_uint64]
    lib.shq_open.restype = c.c_void_p
    lib.shq_open.argtypes = [c.c_char_p, c.c_int]
    lib.shq_push.restype = c.c_int
    lib.shq_push.argtypes = [c.c_void_p, c.c_char_p, c.c_uint64, c.c_int]
    lib.shq_pop.restype = c.c_int64
    lib.shq_pop.argtypes = [c.c_void_p, c.c_int]
    try:
        lib.shq_push_iov.restype = c.c_int
        lib.shq_push_iov.argtypes = [c.c_void_p, c.POINTER(c.c_void_p),
                                     c.POINTER(c.c_uint64), c.c_int, c.c_int]
        lib.shq_peek_len.restype = c.c_int64
        lib.shq_peek_len.argtypes = [c.c_void_p, c.c_int]
        lib.shq_pop_into.restype = c.c_int64
        lib.shq_pop_into.argtypes = [c.c_void_p, c.c_void_p]
        lib.tfos_has_iov = True
    except AttributeError:
        # pre-round-4 .so without the scatter-gather entry points: the
        # queue layer checks tfos_has_iov and stays on the classic path
        lib.tfos_has_iov = False
    lib.shq_buffer.restype = u8p
    lib.shq_buffer.argtypes = [c.c_void_p]
    lib.shq_close_write.argtypes = [c.c_void_p]
    lib.shq_size.restype = c.c_uint64
    lib.shq_size.argtypes = [c.c_void_p]
    lib.shq_free.argtypes = [c.c_void_p]

    lib.tfr_crc32c.restype = c.c_uint32
    lib.tfr_crc32c.argtypes = [c.c_char_p, c.c_uint64]

    # columnar bulk loader (round 3+; callers check lib._tfos_colb_api)
    try:
        lib.tfr_load_columnar.restype = c.c_void_p
        lib.tfr_load_columnar.argtypes = [c.c_char_p]
        lib.tfr_load_columnar_mem.restype = c.c_void_p
        lib.tfr_load_columnar_mem.argtypes = [c.c_char_p, c.c_uint64]
        lib.colb_ok.restype = c.c_int
        lib.colb_ok.argtypes = [c.c_void_p]
        lib.colb_error.restype = c.c_char_p
        lib.colb_error.argtypes = [c.c_void_p]
        lib.colb_num_rows.restype = c.c_int64
        lib.colb_num_rows.argtypes = [c.c_void_p]
        lib.colb_num_features.restype = c.c_int
        lib.colb_num_features.argtypes = [c.c_void_p]
        lib.colb_name.restype = c.c_char_p
        lib.colb_name.argtypes = [c.c_void_p, c.c_int]
        lib.colb_kind.restype = c.c_int
        lib.colb_kind.argtypes = [c.c_void_p, c.c_int]
        lib.colb_width.restype = c.c_int64
        lib.colb_width.argtypes = [c.c_void_p, c.c_int]
        lib.colb_floats.restype = c.POINTER(c.c_float)
        lib.colb_floats.argtypes = [c.c_void_p, c.c_int]
        lib.colb_int64s.restype = c.POINTER(c.c_int64)
        lib.colb_int64s.argtypes = [c.c_void_p, c.c_int]
        lib.colb_bytes_blob.restype = u8p
        lib.colb_bytes_blob.argtypes = [c.c_void_p, c.c_int]
        lib.colb_bytes_offsets.restype = c.POINTER(c.c_uint64)
        lib.colb_bytes_offsets.argtypes = [c.c_void_p, c.c_int]
        lib.colb_free.argtypes = [c.c_void_p]
        lib._tfos_colb_api = True
    except AttributeError:
        logger.warning("native lib lacks the columnar API (stale build); "
                       "bulk TFRecord loads will decode per row")
        lib._tfos_colb_api = False

    # memory-buffer framing (remote-FS path: fsspec moves the bytes,
    # the C library still does framing + crc); absent in pre-round-3 .so
    # builds — callers check lib._tfos_mem_api and fall back to pyimpl
    try:
        lib.tfr_mem_writer_new.restype = c.c_void_p
        lib.tfr_mem_writer_write.restype = c.c_int
        lib.tfr_mem_writer_write.argtypes = [c.c_void_p, c.c_char_p, c.c_uint64]
        lib.tfr_mem_writer_data.restype = u8p
        lib.tfr_mem_writer_data.argtypes = [c.c_void_p, c.POINTER(c.c_uint64)]
        lib.tfr_mem_writer_clear.argtypes = [c.c_void_p]
        lib.tfr_mem_writer_free.argtypes = [c.c_void_p]
        lib.tfr_mem_reader_new.restype = c.c_void_p
        lib.tfr_mem_reader_new.argtypes = [c.c_char_p, c.c_uint64]
        lib.tfr_mem_reader_next.restype = c.c_int64
        lib.tfr_mem_reader_next.argtypes = [c.c_void_p, c.POINTER(u8p)]
        lib.tfr_mem_reader_free.argtypes = [c.c_void_p]
        lib._tfos_mem_api = True
    except AttributeError:
        logger.warning("native lib lacks the mem-buffer API (stale build); "
                       "remote-FS record IO will use the python codec")
        lib._tfos_mem_api = False
    return lib
