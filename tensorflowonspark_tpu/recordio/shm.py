"""Shared-memory ring queue binding (native/shmqueue.cpp).

The fast same-host feed path: the feeder pushes serialized record chunks
into a SPSC byte ring in POSIX shm; the training process pops them with
no per-record IPC and no manager round-trips.  Used by the feed layer as
an accelerated transport when the native library is present; the manager
queue remains the control/compat path.
"""

from __future__ import annotations

import ctypes
import os
import pickle

from tensorflowonspark_tpu.recordio import native as _native

# fast-path frame magic: cannot collide with a pickle stream (protocol 2+
# starts with b'\x80'), so legacy and columnar messages share one ring
_COLMAGIC = b"TFC\x01"


def _align8(n):
    return (n + 7) & ~7


def _decode_columnar(buf):
    """Rebuild a ColumnChunk from a fast-path frame: columns are numpy
    VIEWS over ``buf`` (owned by the returned arrays via .base) — zero
    further copies.  Every column starts 8-byte aligned (the producer
    pads), so int64/float64 views never take numpy's unaligned paths."""
    import numpy as np

    from tensorflowonspark_tpu import marker as _marker

    hlen = int.from_bytes(bytes(buf[4:8]), "little")
    hdr = pickle.loads(bytes(buf[8:8 + hlen]))
    spec, shapes, descrs = hdr[:3]
    meta = hdr[3] if len(hdr) > 3 else None
    off = _align8(8 + hlen)
    cols = []
    mv = memoryview(buf)
    for dtype_str, shape in descrs:
        dt = np.dtype(dtype_str)
        count = 1
        for s in shape:
            count *= s
        a = np.frombuffer(mv, dtype=dt, count=count, offset=off)
        cols.append(a.reshape(shape))
        off = _align8(off + a.nbytes)
    return _marker.ColumnChunk(spec, tuple(cols), shapes=shapes, meta=meta)


def _lock_path(name):
    import tempfile

    return os.path.join(
        tempfile.gettempdir(), f".tfosq{name.replace('/', '_')}.lock"
    )


def producer_active(name):
    """True while some producer holds the ring's exclusive producer flock.

    Lets a draining consumer distinguish "ring momentarily empty but a
    feeder is still mid-partition" from "truly no more data coming"
    without guessing from timeouts (the reference had to guess,
    TFNode.py:307-329; the flock makes the check race-free here)."""
    import fcntl

    try:
        f = open(_lock_path(name), "w")
    except OSError:
        return False
    try:
        fcntl.flock(f, fcntl.LOCK_EX | fcntl.LOCK_NB)
        fcntl.flock(f, fcntl.LOCK_UN)
        return False
    except OSError:
        return True
    finally:
        f.close()


class ShmQueue:
    """Producer or consumer endpoint of a named shm ring.

    The ring is single-producer/single-consumer; pass ``producer=True``
    when opening as a writer — an exclusive flock serializes producer
    sessions (e.g. concurrent feeder tasks on a multi-core Spark
    executor), matching the multi-producer safety of the manager queue
    it replaces."""

    def __init__(self, name, capacity=64 << 20, create=False,
                 open_timeout_ms=60000, producer=False,
                 producer_nonblock=False):
        lib = _native.load()
        if lib is None:
            raise RuntimeError("native library unavailable; ShmQueue disabled")
        self._lib = lib
        self.name = name
        self._lockf = None
        if producer and not create:
            import fcntl

            self._lockf = open(_lock_path(name), "w")
            if producer_nonblock:
                # dynamic-dispatch ring handover: the new owner retries
                # instead of wedging behind the old owner's session flock
                try:
                    fcntl.flock(self._lockf,
                                fcntl.LOCK_EX | fcntl.LOCK_NB)
                except OSError:
                    self._lockf.close()
                    self._lockf = None
                    raise BlockingIOError(
                        f"shm queue {name}: producer flock held by "
                        "another session") from None
            else:
                fcntl.flock(self._lockf, fcntl.LOCK_EX)
        if create:
            self._h = lib.shq_create(name.encode(), capacity)
        else:
            self._h = lib.shq_open(name.encode(), open_timeout_ms)
        if not self._h:
            if self._lockf:
                self._lockf.close()
            raise OSError(f"cannot {'create' if create else 'open'} shm queue {name}")

    def put_bytes(self, data: bytes, timeout_ms=-1):
        rc = self._lib.shq_push(self._h, data, len(data), timeout_ms)
        if rc == -1:
            raise TimeoutError(f"shm queue {self.name} full")
        if rc == -2:
            raise BrokenPipeError(f"shm queue {self.name} closed")
        if rc == -3:
            raise ValueError("message larger than ring capacity")

    def get_bytes(self, timeout_ms=-1):
        """Returns payload bytes (possibly b""), or None at EOF."""
        n = self._lib.shq_pop(self._h, timeout_ms)
        if n == -1:
            raise TimeoutError(f"shm queue {self.name} empty")
        if n == -2:
            return None  # closed and drained
        return ctypes.string_at(self._lib.shq_buffer(self._h), n) if n else b""

    def put(self, obj, timeout_ms=-1):
        """Push one object.  ColumnChunks with contiguous numeric columns
        take a scatter-gather fast path: a small pickled header plus the
        raw column bytes memcpy'd straight from the numpy buffers into
        the ring — ONE payload copy on the producer side, vs pickling the
        arrays into an intermediate bytes first.  Everything else (row
        lists, markers, None) rides classic pickle."""
        fast = self._put_columnar(obj, timeout_ms)
        if not fast:
            self.put_bytes(
                pickle.dumps(obj, protocol=pickle.HIGHEST_PROTOCOL),
                timeout_ms)

    def get(self, timeout_ms=-1):
        """Pop one object.  Fast-path messages are popped directly into a
        caller-owned buffer (one copy) and the columns come back as numpy
        VIEWS over it — no pickle, no further copies."""
        if getattr(self._lib, "tfos_has_iov", False):
            import numpy as np

            n = self._lib.shq_peek_len(self._h, timeout_ms)
            if n == -1:
                raise TimeoutError(f"shm queue {self.name} empty")
            if n == -2:
                return None  # closed and drained
            # np.empty, NOT bytearray: bytearray(n) zero-fills, which is
            # a full hidden extra write of the payload size per message
            buf = np.empty(n, np.uint8)
            if n:
                got = self._lib.shq_pop_into(
                    self._h, ctypes.c_void_p(buf.ctypes.data))
            else:
                got = self._lib.shq_pop_into(self._h, None)
            if got != n:  # single-consumer contract violated
                raise RuntimeError(
                    f"shm queue {self.name}: peeked {n} bytes but popped "
                    f"{got} (concurrent consumer?)")
            if n >= 4 and bytes(buf[:4]) == _COLMAGIC:
                return _decode_columnar(buf)
            # loads() takes any bytes-like: no tobytes() copy of the
            # whole payload just to unpickle a legacy message
            return pickle.loads(memoryview(buf) if n else b"")
        data = self.get_bytes(timeout_ms)
        if data is None:
            return None
        if data[:4] == _COLMAGIC:
            return _decode_columnar(bytearray(data))
        return pickle.loads(data)

    def _put_columnar(self, obj, timeout_ms):
        """Scatter-gather push of a ColumnChunk; False when not eligible
        (no iov support, non-chunk payload, object/non-contiguous
        columns) so put() falls back to pickle."""
        if not getattr(self._lib, "tfos_has_iov", False):
            return False
        from tensorflowonspark_tpu import marker as _marker

        if not isinstance(obj, _marker.ColumnChunk):
            return False
        import numpy as np

        cols = obj.columns
        if not cols or any(
            not isinstance(a, np.ndarray) or a.dtype.hasobject
            or not a.flags.c_contiguous
            for a in cols
        ):
            return False
        header = pickle.dumps(
            (obj.spec, getattr(obj, "shapes", None),
             [(a.dtype.str, a.shape) for a in cols],
             getattr(obj, "meta", None)),
            protocol=pickle.HIGHEST_PROTOCOL)
        # pad so every column lands 8-byte aligned in the frame (the
        # consumer views them in place; unaligned int64/float64 views
        # would take numpy's slow paths on every message)
        pad8 = b"\0" * 8
        segs = [(_COLMAGIC, len(_COLMAGIC)),
                (len(header).to_bytes(4, "little"), 4),
                (header, len(header))]
        off = 8 + len(header)
        if off % 8:
            segs.append((pad8, 8 - off % 8))
        col_segs = []
        for a in cols:
            col_segs.append((a, a.nbytes))
            if a.nbytes % 8:
                col_segs.append((pad8, 8 - a.nbytes % 8))
        n = len(segs) + len(col_segs)
        bufs = (ctypes.c_void_p * n)()
        lens = (ctypes.c_uint64 * n)()
        keepalive = []
        for i, (s, ln) in enumerate(segs):
            b = ctypes.create_string_buffer(s, len(s))
            keepalive.append(b)
            bufs[i] = ctypes.addressof(b)
            lens[i] = ln
        pad_buf = ctypes.create_string_buffer(pad8, 8)
        for j, (a, ln) in enumerate(col_segs):
            if a is pad8:
                bufs[len(segs) + j] = ctypes.addressof(pad_buf)
            else:
                bufs[len(segs) + j] = a.ctypes.data
                keepalive.append(a)
            lens[len(segs) + j] = ln
        rc = self._lib.shq_push_iov(self._h, bufs, lens, n, timeout_ms)
        if rc == -1:
            raise TimeoutError(f"shm queue {self.name} full")
        if rc == -2:
            raise BrokenPipeError(f"shm queue {self.name} closed")
        if rc == -3:
            raise ValueError("message larger than ring capacity")
        return True

    def close_write(self):
        self._lib.shq_close_write(self._h)

    def qsize_bytes(self):
        return self._lib.shq_size(self._h)

    def close(self):
        if self._h:
            self._lib.shq_free(self._h)
            self._h = None
        if self._lockf:
            self._lockf.close()  # releases the producer flock
            self._lockf = None

    def __del__(self):
        try:
            self.close()
        except Exception:  # noqa: BLE001
            pass


def available():
    return _native.load() is not None
