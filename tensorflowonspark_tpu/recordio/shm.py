"""Shared-memory ring queue binding (native/shmqueue.cpp).

The fast same-host feed path: the feeder pushes serialized record chunks
into a SPSC byte ring in POSIX shm; the training process pops them with
no per-record IPC and no manager round-trips.  Used by the feed layer as
an accelerated transport when the native library is present; the manager
queue remains the control/compat path.
"""

from __future__ import annotations

import ctypes
import os
import pickle

from tensorflowonspark_tpu.recordio import native as _native


def _lock_path(name):
    import tempfile

    return os.path.join(
        tempfile.gettempdir(), f".tfosq{name.replace('/', '_')}.lock"
    )


def producer_active(name):
    """True while some producer holds the ring's exclusive producer flock.

    Lets a draining consumer distinguish "ring momentarily empty but a
    feeder is still mid-partition" from "truly no more data coming"
    without guessing from timeouts (the reference had to guess,
    TFNode.py:307-329; the flock makes the check race-free here)."""
    import fcntl

    try:
        f = open(_lock_path(name), "w")
    except OSError:
        return False
    try:
        fcntl.flock(f, fcntl.LOCK_EX | fcntl.LOCK_NB)
        fcntl.flock(f, fcntl.LOCK_UN)
        return False
    except OSError:
        return True
    finally:
        f.close()


class ShmQueue:
    """Producer or consumer endpoint of a named shm ring.

    The ring is single-producer/single-consumer; pass ``producer=True``
    when opening as a writer — an exclusive flock serializes producer
    sessions (e.g. concurrent feeder tasks on a multi-core Spark
    executor), matching the multi-producer safety of the manager queue
    it replaces."""

    def __init__(self, name, capacity=64 << 20, create=False,
                 open_timeout_ms=60000, producer=False):
        lib = _native.load()
        if lib is None:
            raise RuntimeError("native library unavailable; ShmQueue disabled")
        self._lib = lib
        self.name = name
        self._lockf = None
        if producer and not create:
            import fcntl

            self._lockf = open(_lock_path(name), "w")
            fcntl.flock(self._lockf, fcntl.LOCK_EX)
        if create:
            self._h = lib.shq_create(name.encode(), capacity)
        else:
            self._h = lib.shq_open(name.encode(), open_timeout_ms)
        if not self._h:
            if self._lockf:
                self._lockf.close()
            raise OSError(f"cannot {'create' if create else 'open'} shm queue {name}")

    def put_bytes(self, data: bytes, timeout_ms=-1):
        rc = self._lib.shq_push(self._h, data, len(data), timeout_ms)
        if rc == -1:
            raise TimeoutError(f"shm queue {self.name} full")
        if rc == -2:
            raise BrokenPipeError(f"shm queue {self.name} closed")
        if rc == -3:
            raise ValueError("message larger than ring capacity")

    def get_bytes(self, timeout_ms=-1):
        """Returns payload bytes (possibly b""), or None at EOF."""
        n = self._lib.shq_pop(self._h, timeout_ms)
        if n == -1:
            raise TimeoutError(f"shm queue {self.name} empty")
        if n == -2:
            return None  # closed and drained
        return ctypes.string_at(self._lib.shq_buffer(self._h), n) if n else b""

    def put(self, obj, timeout_ms=-1):
        self.put_bytes(pickle.dumps(obj, protocol=pickle.HIGHEST_PROTOCOL),
                       timeout_ms)

    def get(self, timeout_ms=-1):
        data = self.get_bytes(timeout_ms)
        return None if data is None else pickle.loads(data)

    def close_write(self):
        self._lib.shq_close_write(self._h)

    def qsize_bytes(self):
        return self._lib.shq_size(self._h)

    def close(self):
        if self._h:
            self._lib.shq_free(self._h)
            self._h = None
        if self._lockf:
            self._lockf.close()  # releases the producer flock
            self._lockf = None

    def __del__(self):
        try:
            self.close()
        except Exception:  # noqa: BLE001
            pass


def available():
    return _native.load() is not None
