"""Supervised-actor runtime: spawn, mailboxes, liveness, failover.

Parity anchor: the reference delegates ALL process supervision to
Spark's executor runtime (SURVEY §1; reference ``TFSparkNode.py`` just
assumes a re-run task lands somewhere and reattaches).  The TPU-native
stack needs its own, and both TF's distributed runtime (PAPERS.md arxiv
1605.08695 — a generic dataflow worker + one service protocol) and the
tf.data service (arxiv 2101.12127 — dispatcher/worker with heartbeats
and task ledgers) show the winning shape: ONE generic supervised-worker
substrate with typed RPC, on which every tier is a thin policy layer.

This module is that substrate.  An :class:`Actor` subclass defines
behavior (``on_start/on_message/on_tick/on_stop``); an
:class:`ActorSystem` places N members of it on ``LocalEngine`` executor
slots and supervises them:

- **spawn/respawn** ride the engine's retryable-task machinery
  (``foreach_partition(placement=..., retryable=True)``): a SIGKILLed
  member is respawned by engine supervision and its task blob
  re-dispatched byte-identically — the exact mechanism the serving
  replica pool proved out.
- **liveness** is the keyed manager-KV heartbeat (``actors.liveness``)
  plus direct executor-process checks; a wedged member (beating stopped,
  process alive) is killed so the engine path takes over.
- **mailboxes** are manager queues with the ``actors.mailbox`` envelope
  grammar: bounded ``tell`` / ``ask`` with epoch fencing; replies
  resolve :class:`~tensorflowonspark_tpu.actors.ledger.ResolveOnce`
  futures, so re-dispatched asks answered twice resolve exactly once.
- **policy** is declarative per group
  (:class:`~tensorflowonspark_tpu.actors.policy.SupervisionPolicy`).
- **fault injection**: ``TFOS_FAULT_PLAN`` sites ``actor.spawn`` /
  ``actor.receive`` / ``actor.tick`` fire inside the member loop.

See ``docs/actors.md`` for the supervision model and how to write one.
"""

from __future__ import annotations

import logging
import os
import queue as _queue
import signal
import threading
import time
import traceback
import weakref

import cloudpickle

from tensorflowonspark_tpu import manager as tfmanager
from tensorflowonspark_tpu.actors import ledger as _ledger
from tensorflowonspark_tpu.actors import liveness, mailbox
from tensorflowonspark_tpu.actors.dispatch import InFlightTable
from tensorflowonspark_tpu.actors.policy import SupervisionPolicy
from tensorflowonspark_tpu.utils import faults, metrics_registry, telemetry

logger = logging.getLogger(__name__)

__all__ = ["Actor", "ActorContext", "ActorSystem", "ActorGroup",
           "AskFuture", "EchoActor", "actor_table"]


class Actor:
    """Behavior of one supervised member.  Subclass and override; the
    instance is cloudpickled to every member, so keep state picklable
    (per-member state diverges after spawn)."""

    def on_start(self, ctx):
        """Runs once per incarnation, before the mailbox loop."""

    def on_message(self, ctx, kind, payload):
        """Handle one ``tell``/``ask`` envelope; the return value is the
        ask reply.  May be re-invoked for the same logical message after
        a failover (at-least-once); use ``ctx.ledger`` for exactly-once
        effects."""
        raise NotImplementedError(f"unhandled message kind {kind!r}")

    def on_tick(self, ctx):
        """Runs when the mailbox is idle for ``policy.tick_secs``."""

    def on_stop(self, ctx):
        """Runs on clean shutdown (never on SIGKILL — by definition)."""


class ActorContext:
    """What a running member sees: identity, the manager KV, and an
    exactly-once ledger surviving its own death."""

    __slots__ = ("group", "index", "epoch", "mgr", "ledger", "_outq")

    def __init__(self, group, index, epoch, mgr, outq):
        self.group = group
        self.index = index
        self.epoch = epoch
        self.mgr = mgr
        #: KV-backed exactly-once ledger namespaced by group: an effect
        #: recorded here is skipped by every later incarnation.
        self.ledger = _ledger.KVLedger(mgr, group)
        self._outq = outq

    def kv_get(self, key):
        return self.mgr.get(f"actor_kv:{self.group}:{key}")

    def kv_set(self, key, value):
        self.mgr.set(f"actor_kv:{self.group}:{key}", value)

    def emit(self, kind, payload=None):
        """Unsolicited notification to the driver (group ``events``)."""
        self._outq.put(("event", self.index, kind,
                        cloudpickle.dumps(payload)))


class EchoActor(Actor):
    """Test/bench actor: echoes asks; ``pid``/``sleep``/``crash`` kinds
    exercise identity, slowness and SIGKILL-failover paths."""

    def __init__(self):
        self.ticks = 0

    def on_tick(self, ctx):
        self.ticks += 1

    def on_message(self, ctx, kind, payload):
        if kind == "pid":
            return os.getpid()
        if kind == "sleep":
            time.sleep(float(payload))
            return payload
        if kind == "crash":
            os.kill(os.getpid(), signal.SIGKILL)
        if kind == "ticks":
            return self.ticks
        return payload


def _make_actor_task(actor_blob, policy_blob, group, mgr_addr, mgr_authkey):
    """The engine task every member runs.  A real module-level factory
    (not a heredoc/driver lambda): the closure is cloudpickled into the
    executor and must resolve this module by import there."""

    def _actor_task(it):
        items = list(it)
        idx = int(os.environ.get(
            "TFOS_PARTITION_INDEX", items[0] if items else 0))
        mgr = tfmanager.connect(mgr_addr, mgr_authkey)
        inq = mgr.get_queue(mailbox.in_queue(group, idx))
        outq = mgr.get_queue(mailbox.out_queue(group))
        telemetry.configure(node_id=f"actor-{group}-{idx}", role="actor")
        try:
            faults.check("actor.spawn", group=group, actor=idx)
            actor = cloudpickle.loads(actor_blob)
            policy = cloudpickle.loads(policy_blob)
            # The boot epoch fences the PREVIOUS incarnation's inherited
            # mail: the supervisor bumps the KV before this respawn, so
            # envelopes stamped older than it are the dead twin's.
            epoch = int(mgr.get(mailbox.epoch_key(group, idx)) or 0)
            ctx = ActorContext(group, idx, epoch, mgr, outq)
            actor.on_start(ctx)
        except BaseException as e:  # noqa: BLE001 - report, then fail task
            outq.put(("init_error", idx, repr(e)))
            raise
        stop_beat = liveness.start_heartbeat(
            mgr, mailbox.beat_key(group, idx), policy.heartbeat_secs)
        outq.put(("up", idx, os.getpid(), epoch))
        try:
            while True:
                try:
                    msg = inq.get(timeout=policy.tick_secs)
                except _queue.Empty:
                    faults.check("actor.tick", group=group, actor=idx)
                    try:
                        actor.on_tick(ctx)
                    except Exception:  # noqa: BLE001 - tick must not kill
                        logger.exception("actor %s[%d] on_tick failed",
                                         group, idx)
                        outq.put(("event", idx, "tick_error",
                                  cloudpickle.dumps(traceback.format_exc())))
                    continue
                kind = msg[0]
                if kind == "stop":
                    break
                if kind == "tell":
                    # trailing trace element is optional (mailbox.py
                    # grammar): pre-trace senders stay valid
                    m_epoch, m_kind, blob = msg[1], msg[2], msg[3]
                    m_trace = msg[4] if len(msg) > 4 else None
                    if policy.epoch_fencing and m_epoch < epoch:
                        continue  # dead incarnation's inherited mail
                    try:
                        faults.check("actor.receive", group=group,
                                     actor=idx, msg=m_kind)
                        with telemetry.activate(m_trace), \
                                telemetry.span(telemetry.ACTOR_MESSAGE,
                                               group=group, actor=idx,
                                               kind=m_kind, ask=False):
                            actor.on_message(ctx, m_kind,
                                             cloudpickle.loads(blob))
                    except Exception:  # noqa: BLE001 - one bad tell must
                        # not take the member down
                        logger.exception("actor %s[%d] failed tell %r",
                                         group, idx, m_kind)
                        outq.put(("event", idx, "tell_error",
                                  cloudpickle.dumps(traceback.format_exc())))
                elif kind == "ask":
                    m_epoch, req_id, m_kind, blob = msg[1:5]
                    m_trace = msg[5] if len(msg) > 5 else None
                    if policy.epoch_fencing and m_epoch < epoch:
                        # fenced: the supervisor re-stamped and re-sent a
                        # copy; answering this one too would be harmless
                        # (resolve-once) but wastes the device
                        continue
                    try:
                        faults.check("actor.receive", group=group,
                                     actor=idx, msg=m_kind)
                        with telemetry.activate(m_trace), \
                                telemetry.span(telemetry.ACTOR_MESSAGE,
                                               group=group, actor=idx,
                                               kind=m_kind, ask=True):
                            out = actor.on_message(ctx, m_kind,
                                                   cloudpickle.loads(blob))
                        outq.put(("reply", idx, req_id, True,
                                  cloudpickle.dumps(out)))
                    except BaseException:  # noqa: BLE001 - the asker gets
                        # the traceback; the member keeps serving
                        outq.put(("reply", idx, req_id, False,
                                  cloudpickle.dumps(traceback.format_exc())))
        finally:
            stop_beat.set()
            try:
                actor.on_stop(ctx)
            except Exception:  # noqa: BLE001 - teardown
                logger.exception("actor %s[%d] on_stop failed", group, idx)
            outq.put(("down", idx))
            telemetry.flush()

    return _actor_task


class AskFuture(_ledger.ResolveOnce):
    """A pending ask reply.  ``result(timeout)`` blocks; re-dispatched
    asks answered by two incarnations resolve exactly once."""

    __slots__ = ("req_id",)

    def __init__(self, req_id):
        super().__init__()
        self.req_id = req_id

    def result(self, timeout=60.0):
        return self.wait(timeout, "actor reply not delivered")


class ActorGroup:
    """N supervised members of one actor class.  Created by
    :meth:`ActorSystem.spawn`; the driver-facing handle."""

    def __init__(self, system, name, actor, count, policy, slots):
        self.name = name
        self.count = count
        self.policy = policy
        self.slots = list(slots)          # member idx -> engine slot
        self._system = system
        self._mgr = system._mgr
        self._inqs = {i: self._mgr.get_queue(mailbox.in_queue(name, i))
                      for i in range(count)}
        self._outq = self._mgr.get_queue(mailbox.out_queue(name))
        self._table = InFlightTable(count)
        self._epochs = {i: 0 for i in range(count)}
        self._epoch_lock = threading.Lock()
        self._req_counter = 0
        self._registered = threading.Event()
        self._stop = threading.Event()
        self._job_error = None
        self._init_errors = []
        self.events = []                  # [(idx, kind, payload)] tail
        self.spawns_observed = 0
        self.respawns_observed = 0
        self._threads = []
        blob = cloudpickle.dumps(actor)
        pblob = cloudpickle.dumps(policy)
        self._task = _make_actor_task(
            blob, pblob, name, tuple(self._mgr.address), system._authkey)

    # -- lifecycle -----------------------------------------------------------
    def _start(self, timeout):
        def _launch():
            try:
                ds = self._system._engine.parallelize(
                    list(range(self.count)), self.count)
                ds.foreach_partition(
                    self._task, placement=self.slots, retryable=True,
                    max_retries=self.policy.respawns)
            except BaseException as e:  # noqa: BLE001 - surfaced below
                self._job_error = e
                logger.error("actor group %s job failed: %s", self.name, e)

        for name, target in ((f"tfos-actors-{self.name}-launch", _launch),
                             (f"tfos-actors-{self.name}-collect",
                              self._collect),
                             (f"tfos-actors-{self.name}-monitor",
                              self._monitor)):
            t = threading.Thread(target=target, name=name, daemon=True)
            t.start()
            self._threads.append(t)
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            if self._job_error is not None:
                raise RuntimeError(
                    f"actor group {self.name} failed to start: "
                    f"{self._job_error}")
            if self._init_errors:
                raise RuntimeError(
                    f"actor group {self.name} failed to start: "
                    f"{self._init_errors[0]}")
            if len(self._table.live()) >= self.count:
                return self
            self._registered.wait(0.2)
            self._registered.clear()
        raise TimeoutError(
            f"actor group {self.name} not up within {timeout}s "
            f"({len(self._table.live())}/{self.count})")

    def stop(self):
        if self._stop.is_set():
            return
        self._stop.set()
        err = RuntimeError(f"actor group {self.name} stopped")
        for _key, entry in self._table.drain():
            entry["future"].reject(err)
        for inq in self._inqs.values():
            try:
                inq.put(("stop",))
            except Exception:  # noqa: BLE001 - manager may be gone
                pass
        for t in self._threads:
            if t.name.endswith("-launch"):
                t.join(timeout=15)

    # -- messaging -----------------------------------------------------------
    def _pick(self, index):
        if index is not None:
            return int(index)
        live = self._table.live() or list(range(self.count))
        loads = self._table.loads()
        return min(live, key=lambda i: (loads.get(i, 0), i))

    def _send(self, idx, envelope):
        depth = mailbox.checked_put(
            self._inqs[idx], mailbox.in_queue(self.name, idx), envelope,
            self.policy.mailbox_depth)
        metrics_registry.set_gauge("tfos_actor_mailbox_depth", depth,
                                   group=self.name)

    def tell(self, kind, payload=None, index=None):
        """One-way send to ``index`` (default: least-loaded live member).
        Raises :class:`~.mailbox.MailboxFull` past the depth bound."""
        self._raise_if_dead()
        idx = self._pick(index)
        with self._epoch_lock:
            epoch = self._epochs[idx]
        ctx = telemetry.current()
        self._send(idx, ("tell", epoch, kind, cloudpickle.dumps(payload),
                         ctx.to_header() if ctx is not None else None))
        return idx

    def ask(self, kind, payload=None, index=None):
        """Request/reply: returns an :class:`AskFuture`.  A member lost
        mid-flight gets its asks re-dispatched to survivors (or
        re-stamped for its own respawn); the future resolves once."""
        self._raise_if_dead()
        blob = cloudpickle.dumps(payload)
        ctx = telemetry.current()
        trace = ctx.to_header() if ctx is not None else None
        with self._epoch_lock:
            self._req_counter += 1
            req_id = self._req_counter
        future = AskFuture(req_id)
        idx = self._table.add(
            req_id, {"future": future, "kind": kind, "blob": blob,
                     "trace": trace},
            owner=(None if index is None else int(index)))
        with self._epoch_lock:
            epoch = self._epochs[idx]
        try:
            self._send(idx, ("ask", epoch, req_id, kind, blob, trace))
        except BaseException:
            self._table.pop(req_id)
            raise
        return future

    def broadcast(self, kind, payload=None):
        """Tell every live member; returns the indices reached."""
        reached = []
        for idx in self._table.live():
            try:
                self.tell(kind, payload, index=idx)
                reached.append(idx)
            except mailbox.MailboxFull:
                pass
        return reached

    def _raise_if_dead(self):
        if self._job_error is not None and not self._table.live():
            raise RuntimeError(
                f"actor group {self.name} has no members left "
                f"(job failed: {self._job_error})")

    # -- background threads ---------------------------------------------------
    def _collect(self):
        """Drain the group out-queue: registrations, replies, events."""
        while not self._stop.is_set():
            try:
                msg = self._outq.get(timeout=0.25)
            except _queue.Empty:
                continue
            except Exception:  # noqa: BLE001 - manager shut down
                return
            kind = msg[0]
            if kind == "up":
                _, idx, pid, epoch = msg
                respawned = self._table.up(idx, pid)
                self.spawns_observed += 1
                metrics_registry.inc("tfos_actor_spawns_total",
                                     group=self.name)
                self._registered.set()
                telemetry.event("actor/up", group=self.name, actor=idx,
                                pid=pid, epoch=epoch)
                if respawned:
                    # A respawn can beat the monitor's death poll — in
                    # that ordering the lost-path never ran, the epoch
                    # was never bumped, and the dead incarnation would
                    # stay unfenced forever.  Fence here too: future
                    # mail and the redispatch below carry a NEWER epoch
                    # (fencing drops only envelopes older than a
                    # member's boot epoch, so the live member keeps
                    # accepting; when the scan did win this is a second
                    # bump, which is harmless for the same reason).
                    with self._epoch_lock:
                        self._epochs[idx] += 1
                        fence = self._epochs[idx]
                    try:
                        self._mgr.set(mailbox.epoch_key(self.name, idx),
                                      fence)
                    except Exception:  # noqa: BLE001 - manager teardown
                        pass
                    self.respawns_observed += 1
                    metrics_registry.inc("tfos_actor_respawns_total",
                                         group=self.name)
                    telemetry.event("actor/respawn", group=self.name,
                                    actor=idx, pid=pid, epoch=epoch)
                    # This is the authoritative failover trigger: the
                    # dead incarnation's popped asks are gone; queued
                    # ones will at worst be answered twice (futures
                    # resolve once).  Re-dispatch everything it owned.
                    self._redispatch({idx})
            elif kind == "reply":
                _, idx, req_id, ok, blob = msg
                entry = self._table.pop(req_id)
                if entry is None:
                    continue  # duplicate answer after a re-dispatch
                try:
                    value = cloudpickle.loads(blob)
                except Exception as e:  # noqa: BLE001
                    entry["future"].reject(e)
                    continue
                if ok:
                    entry["future"].resolve(value)
                else:
                    entry["future"].reject(RuntimeError(
                        f"actor {self.name}[{idx}] failed "
                        f"{entry['kind']!r}:\n{value}"))
            elif kind == "event":
                _, idx, ekind, blob = msg
                try:
                    payload = cloudpickle.loads(blob)
                except Exception:  # noqa: BLE001
                    payload = None
                self.events.append((idx, ekind, payload))
                del self.events[:-256]
            elif kind == "init_error":
                self._init_errors.append(msg[2])
                logger.warning("actor %s[%s] init_error: %s",
                               self.name, msg[1], msg[2])
            elif kind == "down":
                self._table.down(msg[1])

    def _monitor(self):
        """Liveness sweep: engine-process death (fast path) and stale KV
        heartbeats (wedged-member path).  A wedged member is killed so
        the engine's respawn machinery takes over; either way the epoch
        is bumped (fencing its inherited mail) and its in-flight asks
        re-dispatched."""
        while not self._stop.wait(0.2):
            live = self._table.live()
            lost = liveness.scan(
                live, self._proc_alive,
                lambda i: liveness.beat_age(
                    self._mgr, mailbox.beat_key(self.name, i)),
                self.policy.stale_secs)
            ages = [liveness.beat_age(self._mgr,
                                      mailbox.beat_key(self.name, i))
                    for i in live]
            known = [a for a in ages if a is not None]
            if known and metrics_registry.enabled():
                metrics_registry.set_gauge("tfos_actor_heartbeat_age_s",
                                           max(known), group=self.name)
            for idx, why in lost:
                self._table.lost(idx)
                with self._epoch_lock:
                    self._epochs[idx] += 1
                    epoch = self._epochs[idx]
                try:
                    self._mgr.set(mailbox.epoch_key(self.name, idx), epoch)
                except Exception:  # noqa: BLE001 - manager tearing down
                    pass
                telemetry.event("actor/lost", group=self.name, actor=idx,
                                reason=why, epoch=epoch)
                logger.warning("actor %s[%d] lost (%s); epoch -> %d",
                               self.name, idx, why, epoch)
                try:  # black-box flight dump (docs/telemetry.md)
                    from tensorflowonspark_tpu.obs import flight as _flight

                    _flight.snapshot(
                        "actor/lost", node=f"{self.name}[{idx}]",
                        reason=why, inflight=self._inflight_summary())
                except Exception:  # noqa: BLE001 - never block failover
                    logger.debug("flight snapshot failed", exc_info=True)
                if "stale" in why:
                    # wedged, not dead: kill it so engine supervision
                    # respawns the slot (process death is the signal the
                    # engine acts on)
                    pid = self._table.pids().get(idx)
                    if pid:
                        try:
                            os.kill(pid, signal.SIGKILL)
                        except OSError:
                            pass
            if lost:
                self._redispatch({idx for idx, _ in lost})
            # request-timeout sweep: fail asks stuck past the deadline
            # (None by default: asks wait at the future)
            timeout = getattr(self.policy, "request_timeout", None)
            for _key, entry in self._table.stale(timeout):
                entry["future"].reject(TimeoutError(
                    f"ask not answered within {timeout}s"))

    def _inflight_summary(self, limit=32):
        """Small-scalar view of outstanding asks for flight dumps —
        ids, kinds and trace headers only, never payload blobs
        (redaction contract, docs/telemetry.md "Flight recorder")."""
        out = []
        for req_id in list(self._table.keys())[:limit]:
            entry = self._table.get(req_id)
            if entry is None:
                continue
            item = {"req_id": req_id, "kind": str(entry.get("kind"))}
            if entry.get("trace"):
                item["trace"] = entry["trace"]
            out.append(item)
        return out

    def _redispatch(self, dead_idxs):
        """Re-dispatch asks owned by ``dead_idxs``: to the least-loaded
        survivor, or — when none is live — re-stamped into the dead
        member's own mailbox for its respawn (the bumped epoch fences
        the inherited duplicate)."""
        moved = 0
        for req_id in self._table.owned_by(dead_idxs):
            entry = self._table.get(req_id)
            if entry is None:
                continue
            old = entry["owner"]
            idx = self._table.reassign(req_id)
            if idx is None:
                idx = old
            with self._epoch_lock:
                epoch = self._epochs[idx]
            try:
                self._inqs[idx].put(
                    ("ask", epoch, req_id, entry["kind"], entry["blob"],
                     entry.get("trace")))
                moved += 1
            except Exception:  # noqa: BLE001 - manager tearing down
                pass
        if moved:
            telemetry.event("actor/redispatch", group=self.name,
                            asks=moved, dead=sorted(dead_idxs))

    def _proc_alive(self, idx):
        procs = getattr(self._system._engine, "_procs", None)
        slot = self.slots[idx]
        if procs is None or slot >= len(procs):
            return True  # foreign engine: no process visibility
        try:
            return procs[slot].is_alive()
        except Exception:  # noqa: BLE001
            return True

    # -- introspection ---------------------------------------------------------
    def live(self):
        return self._table.live()

    def pids(self):
        return self._table.pids()

    def epochs(self):
        with self._epoch_lock:
            return dict(self._epochs)

    def outstanding(self):
        return len(self._table)

    def rows(self):
        """Status rows for ``/statusz`` (one per member)."""
        live = set(self._table.live())
        pids = self._table.pids()
        loads = self._table.loads()
        epochs = self.epochs()
        out = []
        for i in range(self.count):
            age = liveness.beat_age(self._mgr,
                                    mailbox.beat_key(self.name, i))
            out.append({
                "group": self.name, "actor": i,
                "live": i in live, "pid": pids.get(i),
                "epoch": epochs.get(i, 0),
                "in_flight": loads.get(i, 0),
                "beat_age_s": None if age is None else round(age, 1),
            })
        return out


class ActorSystem:
    """Owns the engine slots, the IPC manager and every group spawned
    through it.  ``capacity`` is the executor-slot count; groups take
    slots in spawn order."""

    def __init__(self, capacity, engine=None, env=None):
        if engine is None:
            from tensorflowonspark_tpu.engine import LocalEngine

            engine = LocalEngine(int(capacity), env=dict(env) if env else None)
            self._owns_engine = True
        else:
            self._owns_engine = False
        self._engine = engine
        self.capacity = int(capacity)
        self._authkey = os.urandom(16)
        self._mgr = tfmanager.start(self._authkey, [])
        self._groups = {}
        self._next_slot = 0
        self._stopped = False
        _SYSTEMS.add(self)

    def spawn(self, actor, name, count=1, policy=None, timeout=120.0):
        """Place ``count`` members of ``actor`` on the next free slots;
        blocks until all are up.  Returns the :class:`ActorGroup`."""
        if name in self._groups:
            raise ValueError(f"actor group {name!r} already exists")
        count = int(count)
        if self._next_slot + count > self.capacity:
            raise ValueError(
                f"cannot spawn {count} member(s) of {name!r}: "
                f"{self.capacity - self._next_slot} of {self.capacity} "
                "slots free")
        slots = list(range(self._next_slot, self._next_slot + count))
        self._next_slot += count
        group = ActorGroup(self, name, actor,
                           count, policy or SupervisionPolicy(), slots)
        self._groups[name] = group
        return group._start(timeout)

    def group(self, name):
        return self._groups[name]

    def groups(self):
        return dict(self._groups)

    def stop(self):
        if self._stopped:
            return
        self._stopped = True
        for group in self._groups.values():
            group.stop()
        if self._owns_engine:
            self._engine.stop()
        try:
            self._mgr.shutdown()
        except Exception:  # noqa: BLE001
            pass

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.stop()


#: Live systems, for /statusz introspection (obs/http.actor rows).
_SYSTEMS = weakref.WeakSet()


def actor_table():
    """Status rows for every member of every live :class:`ActorSystem`
    (the ``/statusz`` actor table)."""
    rows = []
    for system in list(_SYSTEMS):
        if system._stopped:
            continue
        for group in system.groups().values():
            try:
                rows.extend(group.rows())
            except Exception:  # noqa: BLE001 - system tearing down
                continue
    return sorted(rows, key=lambda r: (r["group"], r["actor"]))
