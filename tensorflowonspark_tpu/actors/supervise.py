"""Respawn budgets, retry schedules and orphan reaping — THE copy.

Parity anchor: the reference leans on Spark's task-retry machinery
(``spark.task.maxFailures``; reference ``TFSparkNode.py`` assumes the
re-run task reattaches by executor id).  This repo's ``LocalEngine``
reimplemented that supervision inline (budgeted executor respawns,
jittered-exponential task retries, orphan-child reaping on respawn and
teardown); this module is those mechanisms extracted so the engine — and
every other supervisor — is a thin policy layer over them (ISSUE 10
lint: no bespoke respawn code outside ``actors/``).
"""

from __future__ import annotations

import logging
import random

logger = logging.getLogger(__name__)

__all__ = ["BudgetExhausted", "RespawnBudget", "RetrySchedule",
           "reap_orphans"]


class BudgetExhausted(RuntimeError):
    """A supervised member died more times than its policy allows."""


class RespawnBudget:
    """Counted permission to replace dead members of a pool.

    ``consume(index)`` either counts one respawn or raises ``error_cls``
    with the canonical exhaustion message (naming the env knob, so the
    operator reading the traceback knows what to raise)."""

    __slots__ = ("budget", "used", "what", "env_name", "error_cls")

    def __init__(self, budget, what="executor",
                 env_name="TFOS_ACTOR_RESPAWNS", error_cls=BudgetExhausted):
        self.budget = int(budget)
        self.used = 0
        self.what = what
        self.env_name = env_name
        self.error_cls = error_cls

    def consume(self, index):
        if self.used >= self.budget:
            raise self.error_cls(
                f"{self.what} {index} died and the respawn budget "
                f"({self.env_name}={self.budget}) is exhausted")
        self.used += 1
        return self.used


class RetrySchedule:
    """Per-key retry bookkeeping with jittered exponential backoff.

    Keys are task ids (engine jobs) or actor indices; the schedule keeps
    every failure reason in arrival order so the permanent error carries
    the full attempt history."""

    __slots__ = ("max_retries", "backoff", "cap", "attempts", "failures")

    def __init__(self, max_retries, backoff, cap=5.0):
        self.max_retries = int(max_retries)
        self.backoff = float(backoff)
        self.cap = float(cap)
        self.attempts = {}   # key -> retries consumed
        self.failures = {}   # key -> [reason], in order

    def record_failure(self, key, reason):
        self.failures.setdefault(key, []).append(reason)

    def exhausted(self, key):
        return self.attempts.get(key, 0) >= self.max_retries

    def next_delay(self, key):
        """Consume one retry; seconds to wait before re-dispatching
        (exponential in the attempt number, capped, jittered to
        desynchronize sibling retries)."""
        a = self.attempts.get(key, 0) + 1
        self.attempts[key] = a
        delay = min(self.backoff * (2 ** (a - 1)), self.cap)
        return delay * (0.5 + random.random())

    def attempt(self, key):
        return self.attempts.get(key, 0)

    def permanent_error(self, key, subject):
        """The canonical gave-up message: latest failure first, earlier
        attempts chained (the engine's poison-task format)."""
        reasons = self.failures.get(key) or ["(no failure recorded)"]
        msg = f"{subject}:\n{reasons[-1]}"
        if len(reasons) > 1:
            chain = "\n--- earlier attempt ---\n".join(reasons[:-1])
            msg += (f"\n(permanent after {len(reasons)} attempts; "
                    f"earlier attempts:\n{chain})")
        return msg


def reap_orphans(dirs, what="child"):
    """Kill + forget every still-live pid recorded in the given member
    working dirs (``utils.track_child_pid`` ledger).  A dead member's
    forked children (IPC-manager server, background trainer) are part of
    its failure domain: they die before a replacement starts, so a
    relaunched member never fights a half-dead twin for its identity.
    Returns the pids killed."""
    from tensorflowonspark_tpu.utils import (
        clear_child_pids, kill_pid, read_child_pids,
    )

    killed = []
    for d in dirs:
        for pid in read_child_pids(d):
            if kill_pid(pid, 0):  # still alive
                logger.warning("reaping orphaned %s pid %d", what, pid)
                kill_pid(pid)
                killed.append(pid)
        clear_child_pids(d)
    return killed
