"""Resolve-once futures and exactly-once delivery ledgers — THE copy.

Parity anchor: the reference's only exactly-once accounting is Spark's
task-retry bookkeeping (reference ``TFSparkNode.py:448-515`` relies on
"a partition is re-fed whole if its task died"); this repo grew three
independent refinements of that idea — the rendezvous feed ledger
(PDONE/PQUERY), the serving batch resolve-once (``batcher.Batch`` /
``PendingResult``) and the decode token ledger
(``decode/scheduler.PendingSession``).  This module is the single
implementation all of them now delegate to (ISSUE 10 satellite:
"no bespoke respawn/ledger code outside actors/",
``tests/test_actors.py::test_no_bespoke_supervision_outside_actors``).

Three primitives, composable:

- :class:`OnceGate` — a claim that exactly one caller wins (the
  duplicate-answer guard of a re-dispatched unit of work).
- :class:`ResolveOnce` — a thread-safe future whose first ``resolve`` /
  ``reject`` wins; later calls are no-ops.  A re-dispatched request
  answered by both the dead owner's inherited queue and the survivor
  resolves exactly once by construction.
- :class:`IndexLedger` — first-arrival-wins values keyed by a dense
  index (streaming token ledger): a deterministic replay after a
  failover re-delivers identical ``(index, value)`` pairs and the
  ledger keeps the originals (timestamps included, so latency stats
  survive the failover).
- :class:`DeliveryLedger` — named done-sets (``feed -> {unit}``): the
  PDONE/PQUERY table.  :class:`KVLedger` is the same contract persisted
  in a manager KV (one key per unit — no read-modify-write race), which
  survives the *recording* process's death: an actor respawn resumes
  past everything already recorded.

Stdlib-only: imported by engine executors, replicas, data workers and
the driver alike.
"""

from __future__ import annotations

import threading
import time


class OnceGate:
    """First ``claim()`` returns True, every later one False."""

    __slots__ = ("_lock", "_claimed")

    def __init__(self):
        self._lock = threading.Lock()
        self._claimed = False

    def claim(self):
        with self._lock:
            if self._claimed:
                return False
            self._claimed = True
            return True

    def claimed(self):
        with self._lock:
            return self._claimed


class ResolveOnce:
    """A thread-safe future: the first ``resolve``/``reject`` wins.

    Subclasses add domain payloads (request example, session prompt);
    the resolution discipline — and therefore the zero-drop/zero-dup
    failover argument — lives here, once.
    """

    __slots__ = ("_event", "_value", "_error")

    def __init__(self):
        self._event = threading.Event()
        self._value = None
        self._error = None

    def done(self):
        return self._event.is_set()

    def resolve(self, value):
        """Resolve with ``value``; True iff this call won the race."""
        if self._event.is_set():
            return False
        self._value = value
        self._event.set()
        return True

    def reject(self, exc):
        """Resolve exceptionally; True iff this call won the race."""
        if self._event.is_set():
            return False
        self._error = exc
        self._event.set()
        return True

    def wait(self, timeout, what="result not available"):
        """Block for the value; raises the stored error, or
        ``TimeoutError`` ("``{what}`` within ``{timeout}``s") — callers
        phrase ``what`` as the failure ("request not served")."""
        if not self._event.wait(timeout):
            raise TimeoutError(f"{what} within {timeout}s")
        if self._error is not None:
            raise self._error
        return self._value


class IndexLedger:
    """First-arrival-wins values keyed by index, timestamps kept."""

    __slots__ = ("_lock", "_values", "_times")

    def __init__(self):
        self._lock = threading.Lock()
        self._values = {}
        self._times = {}

    def record(self, index, value):
        """Record ``value`` at ``index``; True iff it was the first."""
        with self._lock:
            if index in self._values:
                return False
            self._values[index] = value
            self._times[index] = time.perf_counter()
            return True

    def values(self):
        """Recorded values in index order."""
        with self._lock:
            return [self._values[i] for i in sorted(self._values)]

    def times(self):
        """{index: perf_counter-of-first-arrival} copy."""
        with self._lock:
            return dict(self._times)

    def __len__(self):
        with self._lock:
            return len(self._values)


class DeliveryLedger:
    """Named done-sets: ``record(feed, unit)`` / ``done_units(feed)``.

    The in-memory form of the PDONE/PQUERY feed ledger
    (``rendezvous.Server`` holds one; the data service and recovery
    re-feed only what is NOT recorded)."""

    __slots__ = ("_lock", "_done")

    def __init__(self):
        self._lock = threading.Lock()
        self._done = {}

    def record(self, feed, unit):
        """Mark ``unit`` done for ``feed``; True iff newly recorded."""
        with self._lock:
            units = self._done.setdefault(str(feed), set())
            if unit in units:
                return False
            units.add(unit)
            return True

    def done(self, feed, unit):
        with self._lock:
            return unit in self._done.get(str(feed), ())

    def done_units(self, feed):
        """Sorted units recorded done for ``feed``."""
        with self._lock:
            return sorted(self._done.get(str(feed), ()))

    def reset(self, feed):
        """Forget ``feed``'s done-set (one replay scope per owner)."""
        with self._lock:
            self._done.pop(str(feed), None)

    def items(self):
        """[(feed, frozenset(units))] snapshot (introspection/statusz)."""
        with self._lock:
            return sorted((f, frozenset(u)) for f, u in self._done.items())

    def __len__(self):
        with self._lock:
            return len(self._done)

    def __bool__(self):
        with self._lock:
            return bool(self._done)


class KVLedger:
    """A :class:`DeliveryLedger` persisted in a manager KV store.

    One KV key per ``(feed, unit)`` — writes are idempotent and never
    read-modify-write, so concurrent recorders cannot race.  The KV
    lives in the driver-owned manager server process, so the ledger
    survives the recording actor's death: a respawned incarnation skips
    everything already recorded (the eval sidecar's exactly-once
    argument, ``workloads/eval_sidecar.py``).
    """

    __slots__ = ("_mgr", "_prefix")

    def __init__(self, mgr, namespace):
        self._mgr = mgr
        self._prefix = f"actor_ledger:{namespace}:"

    def _key(self, feed, unit):
        return f"{self._prefix}{feed}:{unit!r}"

    def record(self, feed, unit):
        if self.done(feed, unit):
            return False
        self._mgr.set(self._key(feed, unit), unit)
        return True

    def done(self, feed, unit):
        try:
            return self._mgr.get(self._key(feed, unit)) is not None
        except Exception:  # noqa: BLE001 - manager tearing down
            return False

    def done_units(self, feed):
        want = f"{self._prefix}{feed}:"
        try:
            items = self._mgr.kv().items()
        except Exception:  # noqa: BLE001 - manager tearing down
            return []
        return sorted(v for k, v in items if str(k).startswith(want))


class NullLedgerClient:
    """Ledger stand-in when no rendezvous server is reachable
    (standalone DataService / actor use in tests and benches)."""

    def fed_partitions(self, feed):
        return []

    def partition_done(self, feed, part):
        pass

    def close(self):
        pass


def resume_cursor(done_units, start=0):
    """First unit index >= ``start`` NOT in ``done_units`` — the shard
    cursor a respawned worker resumes at (data/service.py contract)."""
    done = set(done_units)
    unit = int(start)
    while unit in done:
        unit += 1
    return unit
