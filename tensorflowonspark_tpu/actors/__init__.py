"""Supervised-actor substrate (ISSUE 10; ROADMAP item 5).

One generic supervised-worker runtime — typed mailboxes, KV-heartbeat
liveness, declarative supervision policy, resolve-once delivery ledgers,
a single fault surface — on which the engine, serving and data tiers are
thin policy layers.  See ``docs/actors.md``.

Parity anchor: the reference delegates supervision to Spark's executor
runtime (SURVEY §1); the shape here follows TF's distributed runtime
(arxiv 1605.08695) and the tf.data service (arxiv 2101.12127).
"""

from tensorflowonspark_tpu.actors.ledger import (  # noqa: F401
    DeliveryLedger, IndexLedger, KVLedger, NullLedgerClient, OnceGate,
    ResolveOnce, resume_cursor,
)
from tensorflowonspark_tpu.actors.mailbox import MailboxFull  # noqa: F401
from tensorflowonspark_tpu.actors.policy import (  # noqa: F401
    SupervisionPolicy,
)
from tensorflowonspark_tpu.actors.supervise import (  # noqa: F401
    BudgetExhausted, RespawnBudget, RetrySchedule, reap_orphans,
)
from tensorflowonspark_tpu.actors.runtime import (  # noqa: F401
    Actor, ActorContext, ActorGroup, ActorSystem, AskFuture, EchoActor,
    actor_table,
)
