"""Typed mailboxes over the manager queue wire: envelopes + backpressure.

Parity anchor: the reference's only executor-directed messaging is the
driver pushing shutdown markers into per-executor manager queues
(reference ``TFCluster.py:186-194``); this repo's serving pool extended
that into a real request/reply wire (``serve_in_{i}`` / ``serve_out``).
This module names that wire's envelope grammar once, adds the two things
every tier re-derived by hand — request ids for reply correlation and a
bounded-depth send — and leaves transport to ``manager.TFManager``
queues (loopback TCP proxies; a queue name IS a mailbox).

Envelope grammar (plain tuples — cloudpickle-free on the control path):

driver -> actor (per-member in-queue)::

    ("tell", epoch, kind, blob[, trace])          one-way, no reply
    ("ask",  epoch, req_id, kind, blob[, trace])  reply on the out-queue
    ("stop",)                                     drain & exit

``trace`` is an optional trailing W3C-traceparent string (see
``utils/telemetry.py`` "Causal tracing"): senders stamp the active
TraceContext so the receiver's ``actor/message`` span joins the
originating request's tree; receivers unpack it tolerantly, so
pre-trace senders (and re-dispatched legacy envelopes) stay valid.

actor -> driver (shared group out-queue)::

    ("up", idx, pid, epoch)               mailbox loop entered
    ("reply", idx, req_id, ok, blob)      ask answer (ok=False: traceback)
    ("event", idx, kind, blob)            unsolicited notification
    ("init_error", idx, repr)             on_start raised
    ("down", idx)                         clean exit

Epoch fencing: every driver->actor envelope carries the sender's epoch
for that member; a member drops envelopes from epochs OLDER than its
boot epoch (a bumped epoch fences the dead incarnation's inherited
mail), and accepts current-or-newer (a respawn that raced the bump must
not drop re-stamped work).  Replies correlate by ``req_id`` into a
resolve-once future (``actors.ledger.ResolveOnce``), so a duplicate
answer — old incarnation's inherited copy plus the re-dispatched one —
resolves exactly once.
"""

from __future__ import annotations

__all__ = ["MailboxFull", "in_queue", "out_queue", "beat_key", "epoch_key",
           "checked_put"]


class MailboxFull(RuntimeError):
    """A bounded mailbox rejected a send (backpressure, not an outage).

    Mirrors the serving front door's ``Overloaded`` contract: carries
    the observed depth and the limit so callers can shed or retry."""

    def __init__(self, name, depth, limit):
        super().__init__(
            f"mailbox {name} is full ({depth} >= limit {limit}); "
            "receiver is not keeping up — retry later or raise "
            "TFOS_ACTOR_MAILBOX_DEPTH")
        self.name = name
        self.depth = depth
        self.limit = limit


def in_queue(group, idx):
    """Manager queue name of member ``idx``'s mailbox."""
    return f"actor_in:{group}:{idx}"


def out_queue(group):
    """Manager queue name of the group's shared driver-bound queue."""
    return f"actor_out:{group}"


def beat_key(group, idx):
    """Manager KV key member ``idx`` heartbeats under."""
    return f"actor_beat:{group}:{idx}"


def epoch_key(group, idx):
    """Manager KV key holding member ``idx``'s current epoch."""
    return f"actor_epoch:{group}:{idx}"


def checked_put(q, name, envelope, depth_limit):
    """Backpressured send: raises :class:`MailboxFull` instead of
    queueing past ``depth_limit``.  Returns the observed depth (the
    mailbox-depth gauge's sample)."""
    try:
        depth = q.qsize()
    except Exception:  # noqa: BLE001 - proxy without qsize support
        depth = 0
    if depth_limit and depth >= depth_limit:
        raise MailboxFull(name, depth, depth_limit)
    q.put(envelope)
    return depth + 1
