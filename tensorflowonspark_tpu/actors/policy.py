"""Declarative supervision policy for actor classes.

Parity anchor: the reference has no policy layer — supervision knobs are
whatever Spark exposes (``spark.task.maxFailures``, reference
``test/run_tests.sh``'s fixed 2-worker standalone cluster).  Here every
actor class declares its supervision contract as data and the runtime
enforces it: respawn budget, retry backoff, heartbeat cadence, mailbox
bound, epoch fencing.

Env family (``TFOS_ACTOR_*``) with documented fallbacks onto the older
per-tier names so existing deployments keep their tuning:

=============================  =========================  =======
new name                       legacy alias               default
=============================  =========================  =======
TFOS_ACTOR_HEARTBEAT_SECS      TFOS_HEARTBEAT_SECS        2
TFOS_ACTOR_HEARTBEAT_STALE     TFOS_HEARTBEAT_STALE       60
TFOS_ACTOR_RESPAWNS            TFOS_EXECUTOR_RESPAWNS     8
TFOS_ACTOR_RETRIES             TFOS_TASK_RETRIES          2
TFOS_ACTOR_BACKOFF             TFOS_RETRY_BACKOFF         0.25
TFOS_ACTOR_MAILBOX_DEPTH       —                          256
TFOS_ACTOR_TICK_SECS           —                          0.5
=============================  =========================  =======

The heartbeat pair is resolved inside ``manager.heartbeat_interval`` /
``manager.stale_after`` — the single chokepoint every liveness consumer
(engine KV heartbeat, replica liveness poll, data consumer-liveness)
already reads — so setting the new name retunes all three tiers at once.
"""

from __future__ import annotations

import os

from tensorflowonspark_tpu.manager import heartbeat_interval, stale_after

__all__ = ["SupervisionPolicy", "heartbeat_interval", "stale_after",
           "env_float", "env_int"]


def env_float(default, *names):
    """First set env var among ``names`` as float, else ``default``."""
    for name in names:
        raw = os.environ.get(name)
        if raw is not None and raw != "":
            return float(raw)
    return float(default)


def env_int(default, *names):
    for name in names:
        raw = os.environ.get(name)
        if raw is not None and raw != "":
            return int(raw)
    return int(default)


class SupervisionPolicy:
    """How a group of actors is supervised.  Cloudpickled into the actor
    task, so keep it plain data."""

    __slots__ = ("respawns", "retries", "backoff", "heartbeat_secs",
                 "stale_secs", "mailbox_depth", "tick_secs",
                 "epoch_fencing")

    def __init__(self, respawns=None, retries=None, backoff=None,
                 heartbeat_secs=None, stale_secs=None, mailbox_depth=None,
                 tick_secs=None, epoch_fencing=True):
        self.respawns = (env_int(8, "TFOS_ACTOR_RESPAWNS",
                                 "TFOS_EXECUTOR_RESPAWNS")
                         if respawns is None else int(respawns))
        self.retries = (env_int(2, "TFOS_ACTOR_RETRIES", "TFOS_TASK_RETRIES")
                        if retries is None else int(retries))
        self.backoff = (env_float(0.25, "TFOS_ACTOR_BACKOFF",
                                  "TFOS_RETRY_BACKOFF")
                        if backoff is None else float(backoff))
        self.heartbeat_secs = (heartbeat_interval() if heartbeat_secs is None
                               else float(heartbeat_secs))
        self.stale_secs = (stale_after() if stale_secs is None
                           else float(stale_secs))
        self.mailbox_depth = (env_int(256, "TFOS_ACTOR_MAILBOX_DEPTH")
                              if mailbox_depth is None
                              else int(mailbox_depth))
        self.tick_secs = (env_float(0.5, "TFOS_ACTOR_TICK_SECS")
                          if tick_secs is None else float(tick_secs))
        self.epoch_fencing = bool(epoch_fencing)

    def __repr__(self):
        return ("SupervisionPolicy(" + ", ".join(
            f"{k}={getattr(self, k)!r}" for k in self.__slots__) + ")")
