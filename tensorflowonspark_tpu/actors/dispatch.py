"""Driver-side dispatch state: live set, loads, in-flight table — THE copy.

Parity anchor: Spark's driver holds this table per job (pending tasks,
preferred locations, speculative copies); the reference never sees it.
This repo's ``ReplicaPool`` reimplemented it inline — a ``_live`` set,
``_loads`` counters, ``_inflight``/``_sessions`` entry dicts and five
near-identical pop-entry-decrement-load blocks.  Extracted here once:
any pool-style driver (serving batches, decode sessions, actor asks)
gets least-loaded pick, load accounting, orphan collection and stale
sweeps from one lock-consistent table.

Keys are caller-chosen and namespaced by the caller (e.g. ``("batch",
id)`` vs ``("gen", sid)``), so one table serves several request kinds
without id collisions.  Entries are caller-owned dicts; the table adds
``"owner"`` and ``"t"`` (monotonic dispatch/refresh time).
"""

from __future__ import annotations

import threading
import time

__all__ = ["InFlightTable"]


class InFlightTable:
    """Lock-consistent (members x in-flight-requests) bookkeeping."""

    def __init__(self, pool_size=0):
        self._lock = threading.Lock()
        self.pool_size = int(pool_size)
        self._live = set()       # member idx with an active loop
        self._pids = {}          # idx -> os pid (latest incarnation)
        self._loads = {}         # idx -> in-flight count
        self._entries = {}       # key -> entry dict (+"owner"/"t")
        self._quiesced = set()   # live but not accepting NEW work (drain)

    # -- membership -----------------------------------------------------------
    def up(self, idx, pid):
        """Record a member's ``up``; True when this is a RESPAWN (same
        index, different pid) — the new incarnation holds nothing in
        hand, so its load resets and the caller re-dispatches."""
        with self._lock:
            respawned = idx in self._pids and self._pids[idx] != pid
            if respawned:
                self._loads[idx] = 0
            self._live.add(idx)
            self._pids[idx] = pid
            return respawned

    def down(self, idx):
        with self._lock:
            self._live.discard(idx)

    def lost(self, idx):
        """Remove a member declared dead; its load bucket goes with it
        (orphaned entries keep their ``owner`` until re-assigned)."""
        with self._lock:
            self._live.discard(idx)
            self._loads.pop(idx, None)
            self._quiesced.discard(idx)

    def quiesce(self, idx):
        """Stop routing NEW work to a live member (graceful drain: it
        keeps its in-flight entries and stays live until retired)."""
        with self._lock:
            self._quiesced.add(idx)

    def unquiesce(self, idx):
        with self._lock:
            self._quiesced.discard(idx)

    def live(self):
        with self._lock:
            return sorted(self._live)

    def pids(self):
        with self._lock:
            return dict(self._pids)

    def loads(self):
        with self._lock:
            return dict(self._loads)

    # -- dispatch -------------------------------------------------------------
    def _pick_locked(self):
        # quiesced members are skipped while any other live member can
        # take the work; when every live member is draining they are
        # still preferred over a blind pool_size guess.
        candidates = (sorted(self._live - self._quiesced)
                      or sorted(self._live)
                      or list(range(self.pool_size)))
        return min(candidates, key=lambda i: (self._loads.get(i, 0), i))

    def add(self, key, entry, owner=None):
        """Insert an in-flight entry; picks the least-loaded live member
        when ``owner`` is None.  Returns the owner chosen."""
        with self._lock:
            idx = self._pick_locked() if owner is None else owner
            entry["owner"] = idx
            entry["t"] = time.monotonic()
            self._entries[key] = entry
            self._loads[idx] = self._loads.get(idx, 0) + 1
            return idx

    def pop(self, key):
        """Resolve an entry (answer arrived): removes it and decrements
        its owner's load.  None when already resolved — the duplicate-
        answer-after-re-dispatch case, a no-op by design."""
        with self._lock:
            entry = self._entries.pop(key, None)
            if entry is not None:
                i = entry["owner"]
                self._loads[i] = max(0, self._loads.get(i, 1) - 1)
            return entry

    def get(self, key):
        with self._lock:
            return self._entries.get(key)

    def touch(self, key):
        """Refresh an entry's liveness clock (a streamed partial answer
        proves the owner is making progress)."""
        with self._lock:
            entry = self._entries.get(key)
            if entry is not None:
                entry["t"] = time.monotonic()
            return entry

    def reassign(self, key):
        """Move an orphaned entry to the least-loaded live member (its
        re-dispatch target); None when no member is live — the entry
        stays assigned and the respawned owner drains its inherited
        mailbox instead."""
        with self._lock:
            entry = self._entries.get(key)
            if entry is None or not self._live:
                return None
            idx = self._pick_locked()
            entry["owner"] = idx
            entry["t"] = time.monotonic()
            self._loads[idx] = self._loads.get(idx, 0) + 1
            return idx

    def owned_by(self, idxs):
        """Keys of entries whose owner is in ``idxs`` (a dead member's
        orphans, in insertion order)."""
        with self._lock:
            return [k for k, e in self._entries.items()
                    if e["owner"] in idxs]

    def owned_count(self, idx):
        """In-flight entries currently assigned to ``idx`` (the drain
        loop polls this down to zero before retiring a member)."""
        with self._lock:
            return sum(1 for e in self._entries.values()
                       if e["owner"] == idx)

    def stale(self, timeout, now=None):
        """Pop and return [(key, entry)] older than ``timeout`` —
        the request-timeout sweep."""
        if not timeout:
            return []
        now = time.monotonic() if now is None else now
        out = []
        with self._lock:
            for key, entry in list(self._entries.items()):
                if now - entry["t"] > timeout:
                    self._entries.pop(key)
                    i = entry["owner"]
                    self._loads[i] = max(0, self._loads.get(i, 1) - 1)
                    out.append((key, entry))
        return out

    def keys(self):
        """All in-flight keys, insertion-ordered (introspection: callers
        count per-namespace, e.g. outstanding decode sessions)."""
        with self._lock:
            return list(self._entries)

    def drain(self):
        """Pop everything (pool teardown fails all outstanding work)."""
        with self._lock:
            entries = list(self._entries.items())
            self._entries.clear()
            self._loads.clear()
            return entries

    def __len__(self):
        with self._lock:
            return len(self._entries)
