"""Uniform KV-heartbeat liveness — the keyed form of ``manager.beat``.

Parity anchor: the reference's only liveness signal is Spark's executor
heartbeat to the driver (SURVEY §1); this repo's trainer heartbeat
(``manager.beat``, single well-known key) grew a keyed sibling inside
``serving/replicas.py`` so N replicas could beat through one manager.
This module is that keyed form extracted once, used by every actor —
replica tasks, data workers and any user actor get the identical
beat/age/scan discipline with no per-tier thread code.

The cadence and staleness threshold come from
``manager.heartbeat_interval()`` / ``manager.stale_after()`` — the
``TFOS_ACTOR_HEARTBEAT_*`` env family (legacy ``TFOS_HEARTBEAT_*``
aliases honored), see ``actors/policy.py``.
"""

from __future__ import annotations

import threading
import time

from tensorflowonspark_tpu import manager as tfmanager

__all__ = ["beat", "beat_age", "start_heartbeat", "scan"]


def beat(mgr, key):
    """Record liveness under ``key`` now (KV write = proof of scheduling)."""
    mgr.set(key, time.time())


def beat_age(mgr, key):
    """Seconds since the last beat under ``key``; None = never beat (or
    KV unreadable) — callers treat None as 'unknown', never 'dead'."""
    try:
        v = mgr.get(key)
    except Exception:  # noqa: BLE001 - manager tearing down
        return None
    if v is None:
        return None
    try:
        return max(0.0, time.time() - float(v))
    except (TypeError, ValueError):
        return None


def start_heartbeat(mgr, key, interval=None):
    """Daemon thread beating ``key`` every ``interval`` (default:
    ``manager.heartbeat_interval()``); returns a stop Event.  The thread
    exits silently when the manager goes away — the process is ending."""
    interval = (tfmanager.heartbeat_interval() if interval is None
                else float(interval))
    stop = threading.Event()

    def _run():
        while not stop.is_set():
            try:
                beat(mgr, key)
            except Exception:  # noqa: BLE001 - manager gone: member exiting
                return
            stop.wait(interval)

    threading.Thread(target=_run, name="tfos-actor-beat",
                     daemon=True).start()
    return stop


def scan(indices, proc_alive, age_of, stale_secs):
    """One liveness sweep: ``[(idx, why)]`` members to declare lost.

    ``proc_alive(idx)`` is the fast path (executor process death);
    ``age_of(idx)`` the wedged-member path (beating stopped while the
    process lives).  A member is lost on either signal — the same two
    signals engine/node supervision uses.
    """
    lost = []
    for idx in indices:
        if not proc_alive(idx):
            lost.append((idx, "process death"))
            continue
        age = age_of(idx)
        if age is not None and age > stale_secs:
            lost.append((idx, f"heartbeat stale ({age:.1f}s)"))
    return lost
