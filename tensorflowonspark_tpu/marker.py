"""Sentinel types placed in data queues (parity: reference marker.py:11-18).

``None`` in a queue still means end-of-feed, by convention, exactly as in
the reference.  Because our queues carry *batches* (lists of records), the
sentinels are distinguishable from data without isinstance checks on every
record.
"""


class Marker:
    """Base class for data-queue sentinels."""


class EndPartition(Marker):
    """Marks the end of one input partition (flush partial batch)."""

    def __repr__(self):
        return "EndPartition()"
