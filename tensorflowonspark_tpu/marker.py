"""Sentinel types placed in data queues (parity: reference marker.py:11-18).

``None`` in a queue still means end-of-feed, by convention, exactly as in
the reference.  Because our queues carry *batches* (lists of records), the
sentinels are distinguishable from data without isinstance checks on every
record.
"""


class Marker:
    """Base class for data-queue sentinels."""


class EndPartition(Marker):
    """Marks the end of one input partition (flush partial batch)."""

    def __repr__(self):
        return "EndPartition()"


class ColumnChunk:
    """A feed chunk in columnar form: dense per-column arrays instead of a
    list of row tuples.

    The feeder converts all-numeric row chunks with
    ``recordio.marshal.rows_to_columns`` before queueing: ~10x cheaper to
    serialize and ~2x smaller on the wire than pickled row lists (numpy
    buffers vs per-value pickle opcodes), and the consumer can slice
    columns straight into batches with no per-record python work — the
    TPU-native answer to the reference's per-record pickle hop
    (TFSparkNode.py:480-482).
    """

    __slots__ = ("spec", "columns", "shapes", "meta")

    def __init__(self, spec, columns, shapes=None, meta=None):
        self.spec = spec          # [(dtype_code, width), ...]
        self.columns = columns    # tuple of np.ndarray, one per field
        # per-field original trailing shape for n-D tensor fields the
        # feeder flattened to 1-D (images: (H, W, C) stored as a width
        # H*W*C column), or None per field / None overall when every
        # field was scalar/1-D already.  Consumers reshape VIEWS — the
        # flatten/unflatten round-trip copies nothing.
        self.shapes = shapes
        # optional small delivery tag riding the wire with the chunk —
        # dynamic split dispatch labels chunks ("split", sid, seq,
        # nblocks) so DataFeed can drop the already-consumed prefix of a
        # re-served split (data/splits.py exactly-once contract).  None
        # for untagged (feeder / static-service) chunks.
        self.meta = meta

    def __getstate__(self):
        return (self.spec, self.columns, self.shapes, self.meta)

    def __setstate__(self, state):
        self.spec, self.columns, self.shapes = state[:3]
        self.meta = state[3] if len(state) > 3 else None

    def __len__(self):
        return len(self.columns[0]) if self.columns else 0

    def __repr__(self):
        return f"ColumnChunk(n={len(self)}, spec={self.spec})"
