"""TPU chip discovery and per-worker arbitration (parity: reference gpu_info.py).

The reference polls ``nvidia-smi`` for free GPUs and assigns them by worker
index when several executors share a host (gpu_info.py:31-98).  On TPU VMs
the equivalent questions are:

- *are there chips here?*  → ``/dev/accel*`` / ``/dev/vfio`` device nodes,
  or a live JAX TPU backend;
- *which chips may THIS process use?* → libtpu visible-chip env vars
  (``TPU_VISIBLE_CHIPS`` + process-bounds), the TPU analogue of
  ``CUDA_VISIBLE_DEVICES`` index placement at gpu_info.py:81-91.

All discovery goes through module-level functions so tests can patch them
exactly the way the reference tests patch ``gpu_info.get_gpus``
(test_TFSparkNode.py:49-187).
"""

from __future__ import annotations

import glob
import logging
import os
import time

logger = logging.getLogger(__name__)

MAX_RETRIES = 3  # parity: gpu_info.py:17


def is_tpu_available():
    """True if this host has TPU chips (parity: gpu_info.is_gpu_available)."""
    return count_chips() > 0


def count_chips():
    """Number of TPU chips attached to this host.

    Honors ``TFOS_TPU_CHIPS_PER_HOST`` as an override (tests / forced
    topologies), else counts accelerator device nodes.
    """
    override = os.environ.get("TFOS_TPU_CHIPS_PER_HOST")
    if override:
        return int(override)
    return len(glob.glob("/dev/accel*")) or len(glob.glob("/dev/vfio/[0-9]*"))


def get_chips(num_chips, worker_index=-1):
    """Claim ``num_chips`` chips for this worker; returns chip indices.

    With ``worker_index >= 0`` and multiple workers per host, each worker
    takes a disjoint contiguous block (index-based placement, parity:
    gpu_info.py:81-91).  Retries with linear backoff like the reference's
    busy-GPU retry loop (gpu_info.py:58-80).
    """
    if num_chips <= 0:
        return []
    for attempt in range(1, MAX_RETRIES + 1):
        available = count_chips()
        if available >= num_chips:
            if worker_index < 0:
                chips = list(range(num_chips))
            else:
                base = worker_index * num_chips
                if base + num_chips > available:
                    raise RuntimeError(
                        f"worker {worker_index} needs chips "
                        f"[{base}, {base + num_chips}) but host has only "
                        f"{available}; total per-host demand exceeds supply"
                    )
                chips = list(range(base, base + num_chips))
            logger.info(
                "claimed TPU chips %s (worker_index=%d, host has %d)",
                chips, worker_index, available,
            )
            return chips
        if attempt < MAX_RETRIES:
            wait = 30 * attempt
            logger.warning(
                "requested %d TPU chips, host reports %d; retry %d/%d in %ds",
                num_chips, available, attempt, MAX_RETRIES, wait,
            )
            time.sleep(wait)
    raise RuntimeError(
        f"unable to claim {num_chips} TPU chips (host has {count_chips()})"
    )


def set_visible_chips(num_chips, worker_index=-1):
    """Export visible-chip env so the TPU runtime scopes this process.

    TPU analogue of exporting ``CUDA_VISIBLE_DEVICES``
    (gpu_info.py format='CUDA' path).  Must run before jax initializes.
    """
    chips = get_chips(num_chips, worker_index)
    _export_visible(chips)
    return chips


def _export_visible(chips):
    os.environ["TPU_VISIBLE_CHIPS"] = ",".join(str(c) for c in chips)
    os.environ["TPU_CHIPS_PER_PROCESS_BOUNDS"] = f"1,{len(chips)},1"
    os.environ["TPU_PROCESS_BOUNDS"] = "1,1,1"


# -- scheduler-integrated discovery (parity: TFSparkNode.py:170-229) ---------

# Spark resource names that may carry accelerator addresses for this node.
RESOURCE_NAMES = ("tpu", "gpu", "accelerator")


def _has_spark_resource_api():
    """True when a pyspark >= 3 TaskContext with resources() is importable
    (parity: reference TFSparkNode._has_spark_resource_api)."""
    try:
        from pyspark import TaskContext  # noqa: F401

        return hasattr(TaskContext, "resources")
    except ImportError:
        return False


def _task_resources():
    """{resource_name: [addresses]} from the scheduler's task context, or
    None outside a Spark-3 task (patched by tests exactly like the
    reference patches TaskContext.resources, test_TFSparkNode.py:49-187)."""
    if not _has_spark_resource_api():
        return None
    from pyspark import TaskContext

    context = TaskContext.get()
    if context is None:
        return None
    resources = context.resources()
    return {
        name: list(info.addresses) for name, info in (resources or {}).items()
    }


def is_k8s():
    """True inside a Spark-on-K8s executor pod (reference TFSparkNode.py:172
    checks SPARK_EXECUTOR_POD_IP to work around device-plugin over-report)."""
    return "SPARK_EXECUTOR_POD_IP" in os.environ


def claim_chips(num_chips=0, worker_index=-1):
    """Claim TPU chips for this process — the reference's _get_gpus decision
    table (TFSparkNode.py:170-229) with chips instead of CUDA devices:

    1. scheduler first: Spark-3 ``TaskContext.resources()`` addresses win
       when present (truncated to ``num_chips`` when the user explicitly
       asked for fewer);
    2. otherwise, host scan — but NOT inside a K8s pod (the reference
       skips the probe there: device plugins can advertise accelerators
       to non-accelerator pods on shared nodes);
    3. an explicit request that cannot be satisfied raises.

    Exports the visible-chip env and returns the chip list (possibly []).
    """
    user_requested = num_chips > 0
    resources = _task_resources()
    chips = []
    if resources:
        for name in RESOURCE_NAMES:
            if resources.get(name):
                chips = [str(a) for a in resources[name]]
                logger.info("scheduler %s resources: %s", name, chips)
                break
        if chips and user_requested and num_chips < len(chips):
            logger.warning(
                "requested %d chip(s), scheduler assigned %d; truncating",
                num_chips, len(chips),
            )
            chips = chips[:num_chips]

    # host scan only for an explicit request: unlike the reference's
    # "default to 1 GPU", an unconstrained TPU process should keep the
    # runtime's natural visibility of every host chip (SPMD-first).
    if not chips and user_requested and not is_k8s() and is_tpu_available():
        chips = [str(c) for c in get_chips(num_chips, worker_index)]

    if user_requested and len(chips) < num_chips:
        raise RuntimeError(
            f"unable to allocate {num_chips} TPU chip(s); "
            f"scheduler/host offered {chips}"
        )
    if chips:
        _export_visible(chips)
    return chips


def local_device_info():
    """Describe local accelerators from a live JAX backend (best-effort)."""
    try:
        import jax

        devs = jax.local_devices()
        return [
            {
                "id": d.id,
                "platform": d.platform,
                "kind": getattr(d, "device_kind", "unknown"),
            }
            for d in devs
        ]
    except Exception as e:  # noqa: BLE001 - discovery is best-effort
        logger.debug("no live jax backend for device info: %s", e)
        return []


def slice_health(expected_processes=None, expected_local_devices=None,
                 smoke=True, timeout=None):
    """Health-check the accelerator slice from a live JAX backend.

    The new-build counterpart of the reference's implicit "TF server came
    up" signal (SURVEY.md §5: recovery remains restart-from-checkpoint,
    *plus TPU-slice health checks*): after ``ctx.jax_initialize()`` every
    process can verify that (a) it sees its local chips, (b) the global
    device count matches processes x local devices, and (c) a trivial
    computation executes on every local device.  Returns a dict with
    ``healthy`` plus details; never raises and never hangs past
    ``timeout`` — callers decide whether a sick slice is fatal.

    ``timeout`` defaults to ``TFOS_SLICE_HEALTH_TIMEOUT`` (seconds, 60 if
    unset) — first TPU contact through a slow pool/tunnel can legitimately
    exceed a fixed window, so deployments can widen it without code
    changes.  A probe that is merely *slow* is reported distinctly: the
    returned dict's ``timed_out`` flag is set and the probe's findings so
    far are snapshotted, letting callers treat "no answer yet" differently
    from definite failures (wrong counts, CPU fallback, smoke failure).
    """
    import copy
    import threading

    if timeout is None:
        try:
            timeout = float(os.environ.get("TFOS_SLICE_HEALTH_TIMEOUT", 60))
        except ValueError:
            timeout = float("nan")
        if not (timeout > 0):  # rejects nan, 0, negatives
            logger.warning("bad TFOS_SLICE_HEALTH_TIMEOUT=%r; using 60",
                           os.environ.get("TFOS_SLICE_HEALTH_TIMEOUT"))
            timeout = 60.0
    # 'inf' / huge values would make t.join() raise OverflowError,
    # breaking the never-raises contract — cap at what join() accepts
    timeout = min(timeout, threading.TIMEOUT_MAX)

    # the probe thread mutates ``work`` under ``lock``; the caller gets a
    # snapshot taken after join(), so a probe that outlives the timeout
    # can never mutate the dict the caller is already reading
    lock = threading.Lock()
    work = {
        "healthy": False,
        "platform": None,
        "local_devices": 0,
        "global_devices": 0,
        "process_index": None,
        "timed_out": False,
        "bare_timeout": False,
        "errors": [],
    }

    # the whole probe runs on a bounded worker: on a wedged backend the
    # FIRST jax call (backend-client creation) is a common hang point,
    # not just the smoke compute — a hang must become a report, not
    # wedge bring-up
    def err(msg):
        # flush each finding under the lock AS FOUND: a probe that later
        # hangs (e.g. in the smoke compute) must not take already-detected
        # definite failures down with it — the caller's timeout snapshot
        # includes everything known so far
        with lock:
            work["errors"].append(msg)

    def probe():
        try:
            import jax

            # all jax calls OUTSIDE the lock: a backend that wedges
            # mid-call must not wedge the caller's snapshot deepcopy too
            devs = jax.local_devices()
            platform = devs[0].platform if devs else None
            n_global = jax.device_count()
            proc_idx = jax.process_index()
            with lock:
                work["platform"] = platform
                work["local_devices"] = len(devs)
                work["global_devices"] = n_global
                work["process_index"] = proc_idx
            if not devs:
                err("no local devices visible")
                return
            plats = os.environ.get("JAX_PLATFORMS", "").lower()
            forced_cpu = (
                plats.split(",")[0].strip() == "cpu"  # incl. "cpu,tpu"
                or os.environ.get("JAX_PLATFORM_NAME", "").lower() == "cpu"
            )
            if platform == "cpu" and not forced_cpu \
                    and count_chips() > 0:
                # libtpu failed to load and jax silently fell back to
                # host CPU — counts all match, but this is not the slice.
                # An explicit JAX_PLATFORMS=cpu is an intentional choice
                # (tests run forced-cpu on TPU VMs while a bench owns the
                # chips), not a fallback.
                err(
                    f"{count_chips()} TPU chips present on this host but "
                    "the jax backend is 'cpu' (accelerator runtime failed "
                    "to initialize?)")
            if expected_local_devices is not None and \
                    len(devs) != expected_local_devices:
                err(
                    f"local devices {len(devs)} != expected "
                    f"{expected_local_devices}")
            if expected_processes is not None and \
                    jax.process_count() != expected_processes:
                err(
                    f"process count {jax.process_count()} != expected "
                    f"{expected_processes}")
            # global cross-check: slices are homogeneous, so even without
            # an explicit expectation a peer host that came up short shows
            # as global != processes x local
            want = ((expected_processes or jax.process_count())
                    * (expected_local_devices or len(devs)))
            if n_global != want:
                err(
                    f"global devices {n_global} != expected "
                    f"{want} (a peer host may be short of chips)")
            if smoke:
                import numpy as np

                # a tiny add on each local device proves the runtime
                # executes (a wedged chip typically hangs or errors here)
                for d in devs:
                    got = jax.device_put(np.int32(20), d) + 22
                    if int(got) != 42:
                        err(
                            f"device {d.id} smoke compute returned "
                            f"{int(got)}")
        except Exception as e:  # noqa: BLE001 - report, never raise
            err(f"{type(e).__name__}: {str(e)[:160]}")
        finally:
            with lock:
                work["done"] = True

    t = threading.Thread(target=probe, daemon=True, name="tfos-slice-health")
    t.start()
    t.join(timeout=timeout)
    with lock:
        report = copy.deepcopy(work)
    # ``report`` is now a private snapshot: a probe thread that outlives
    # the timeout can keep mutating ``work`` without the caller observing
    # fields change under it
    if not report.pop("done", False):
        report["timed_out"] = True
        # explicit "slow but nothing definite found" signal: callers
        # branch on this, not on the error-list composition
        report["bare_timeout"] = not report["errors"]
        report["errors"].append(
            f"health probe still hung after {timeout}s (wedged backend "
            "or device, or a first-contact compile slower than "
            "TFOS_SLICE_HEALTH_TIMEOUT?)")
    report["healthy"] = not report["errors"]
    return report
