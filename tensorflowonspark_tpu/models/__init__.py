"""Model zoo: the workloads the reference ships as examples (SURVEY.md §2.5)
re-built as pure-JAX functional models — MNIST CNN, ResNet (CIFAR +
ImageNet variants), U-Net segmentation — plus the decoder-only
transformer family (long-context flagship; no reference counterpart)."""

from tensorflowonspark_tpu.models import layers  # noqa: F401
