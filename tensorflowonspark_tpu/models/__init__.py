"""Model zoo: the workloads the reference ships as examples (SURVEY.md §2.5)
re-built as pure-JAX functional models — MNIST CNN, ResNet (CIFAR +
ImageNet variants), and encoder-decoder segmentation."""

from tensorflowonspark_tpu.models import layers  # noqa: F401
