"""Decoder-only transformer — the long-context flagship model family.

No counterpart exists in the reference (pre-LLM design, SURVEY.md §5
"Long-context — absent"); this is the model family that exercises the
framework's first-class mesh axes: data/fsdp (batch), model (tensor
parallel, Megatron-style column→row sharded matmul pairs where GSPMD
inserts the all-reduces), and seq (ring-attention sequence parallelism
via parallel/ring.py).

TPU-first choices mirror models/resnet.py: params live in float32,
activations/matmuls run in the config compute dtype (bfloat16 on TPU)
with f32 accumulation; layers are scanned (one compiled layer body);
attention is ops.flash_attention (pallas) unless a sequence-parallel
attn_fn is injected.
"""

from __future__ import annotations

import dataclasses
import functools

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import PartitionSpec as P

from tensorflowonspark_tpu import ops
from tensorflowonspark_tpu.models import layers as L


@dataclasses.dataclass(frozen=True)
class Config:
    vocab_size: int = 32000
    dim: int = 512
    n_layers: int = 4
    n_heads: int = 8
    max_seq: int = 2048
    mlp_ratio: int = 4
    rope_base: float = 10000.0
    dtype: str = "bfloat16"  # compute dtype; params always float32
    # 'flash' = pallas kernel (single-chip / shard_map contexts only:
    # GSPMD cannot auto-partition a pallas_call); 'reference' = pure XLA
    # einsum formulation, partitionable by GSPMD on any mesh.
    attn_impl: str = "flash"

    @property
    def head_dim(self):
        assert self.dim % self.n_heads == 0
        return self.dim // self.n_heads

    @property
    def compute_dtype(self):
        return jnp.dtype(self.dtype)


def _layer_init(key, cfg):
    ks = jax.random.split(key, 6)
    dim, mlp = cfg.dim, cfg.dim * cfg.mlp_ratio
    dense = lambda k, i, o: L._he_init(k, (i, o), i, jnp.float32)
    return {
        "ln1": jnp.ones((dim,), jnp.float32),
        "wqkv": dense(ks[0], dim, 3 * dim),
        "wo": dense(ks[1], dim, dim),
        "ln2": jnp.ones((dim,), jnp.float32),
        "w1": dense(ks[2], dim, mlp),
        "w2": dense(ks[3], mlp, dim),
    }


def init(key, cfg: Config):
    """Params pytree; per-layer trees stacked on a leading n_layers axis
    so apply() scans one compiled layer body."""
    k_embed, k_head, k_layers = jax.random.split(key, 3)
    layer_keys = jax.random.split(k_layers, cfg.n_layers)
    layers = jax.vmap(lambda k: _layer_init(k, cfg))(layer_keys)
    return {
        "embed": jax.random.normal(
            k_embed, (cfg.vocab_size, cfg.dim), jnp.float32
        ) * 0.02,
        "layers": layers,
        "ln_f": jnp.ones((cfg.dim,), jnp.float32),
        "head": L._he_init(k_head, (cfg.dim, cfg.vocab_size), cfg.dim,
                           jnp.float32),
    }


def param_specs(cfg: Config, *, tp_axis="model", fsdp_axis="fsdp", mesh=None):
    """Megatron-style PartitionSpecs matching init()'s tree.

    Column-parallel (out-dim on tp): wqkv, w1, head; row-parallel (in-dim
    on tp): wo, w2 — each column→row pair needs exactly one all-reduce,
    which GSPMD inserts from these annotations.  Layer trees carry the
    leading scan axis (None).  Pass ``mesh`` to drop axes the mesh does
    not define (e.g. a data x seq x model mesh without fsdp).
    """
    if mesh is not None:
        axes = set(mesh.shape)
        tp_axis = tp_axis if tp_axis in axes else None
        fsdp_axis = fsdp_axis if fsdp_axis in axes else None
    col = P(fsdp_axis, tp_axis)
    row = P(tp_axis, fsdp_axis)
    lcol = P(None, fsdp_axis, tp_axis)
    lrow = P(None, tp_axis, fsdp_axis)
    return {
        "embed": P(None, fsdp_axis),
        "layers": {
            "ln1": P(None, None),
            "wqkv": lcol,
            "wo": lrow,
            "ln2": P(None, None),
            "w1": lcol,
            "w2": lrow,
        },
        "ln_f": P(None),
        "head": col,
    }


def _matmul(x, w):
    return jnp.dot(
        x, w.astype(x.dtype), preferred_element_type=jnp.float32
    ).astype(x.dtype)


def _layer_apply(p, x, cfg, rope, attn_fn):
    b, s, dim = x.shape
    h, hd = cfg.n_heads, cfg.head_dim
    cos, sin, positions = rope

    y = ops.rmsnorm_reference(x, p["ln1"])
    qkv = _matmul(y, p["wqkv"]).reshape(b, s, 3, h, hd)
    q, k, v = qkv[:, :, 0], qkv[:, :, 1], qkv[:, :, 2]
    q = ops.apply_rope(q, cos, sin, positions=positions)
    k = ops.apply_rope(k, cos, sin, positions=positions)
    attn = attn_fn(q, k, v).reshape(b, s, dim)
    x = x + _matmul(attn, p["wo"])

    y = ops.rmsnorm_reference(x, p["ln2"])
    y = _matmul(jax.nn.gelu(_matmul(y, p["w1"])), p["w2"])
    return x + y


def apply(params, tokens, cfg: Config, *, attn_fn=None,
          logits_dtype=jnp.float32, remat=False, positions=None,
          return_hidden=False):
    """tokens [B, S] int32 -> logits [B, S, vocab] (``logits_dtype``,
    default float32; pass None to keep the compute dtype — the training
    loss does, so the [B,S,vocab] activation stays bfloat16 in HBM).
    ``return_hidden=True`` skips the head matmul and returns the final
    hidden states [B, S, dim] (the blockwise-CE loss consumes these).

    ``attn_fn(q, k, v) -> out`` on [B, S, H, D]; default is causal
    pallas flash attention.  Pass
    ``parallel.sequence_parallel_attention(mesh, 'ring', causal=True)``
    for sequence-parallel long-context runs.

    ``positions`` ([S] or [B, S] int32): explicit global rope positions
    for sequences not in contiguous order — e.g. zigzag-permuted
    long-context batches (``parallel.zigzag_permutation``).  The default
    causal flash mask assumes CONTIGUOUS order; with permuted input,
    pass an ``attn_fn`` whose masking understands the layout
    (``sequence_parallel_attention(mesh, 'zigzag', causal=True)``).

    ``remat=True`` checkpoints each scanned layer: the backward pass
    recomputes layer internals instead of keeping ~10·dim·B·S bytes per
    layer resident, trading ~30% more FLOPs for an O(L·B·S·dim) →
    O(B·S·dim) activation footprint (how the bigger sweep batches fit).
    ``remat="dots"`` is the selective policy: every matmul output is
    saved and only the cheap elementwise chain is recomputed (jax
    dots_with_no_batch_dims_saveable — the attention einsums inside the
    flash kernel are custom-VJP-opaque and unaffected).  A
    save-only-attn-output policy was evaluated and rejected: the flash
    custom-VJP's residuals (lse etc.) are not name-saveable, so its
    forward re-runs on backward regardless — full remat cost plus extra
    residency.
    """
    if positions is not None and attn_fn is None:
        # the default flash mask is causal by ARRAY INDEX; on permuted
        # input that silently attends to the future — demand an attn_fn
        # whose masking understands the layout
        raise ValueError(
            "positions= implies a non-contiguous sequence layout; pass an "
            "attn_fn that masks by global position (e.g. "
            "sequence_parallel_attention(mesh, 'zigzag', causal=True))")
    if attn_fn is None:
        base = (ops.flash_attention if cfg.attn_impl == "flash"
                else ops.mha_reference)
        attn_fn = functools.partial(base, causal=True)
    dtype = cfg.compute_dtype
    x = params["embed"].astype(dtype)[tokens]
    if positions is None:
        rope_len = tokens.shape[1]
        pos2d = None
    else:
        # cover every global position: jax gather would silently CLAMP
        # an index past the table instead of erroring
        rope_len = max(tokens.shape[1], cfg.max_seq)
        pos = jnp.asarray(positions, jnp.int32)
        pos2d = jnp.broadcast_to(
            pos[None] if pos.ndim == 1 else pos, tokens.shape)
    cos, sin = ops.rope_angles(rope_len, cfg.head_dim, cfg.rope_base)
    rope = (cos, sin, pos2d)

    layer_fn = _layer_apply
    if remat:
        if remat == "dots":
            policy = jax.checkpoint_policies.dots_with_no_batch_dims_saveable
        elif remat is True:
            policy = None  # full remat
        else:
            raise ValueError(
                f"remat must be bool or 'dots'; got {remat!r}")
        layer_fn = jax.checkpoint(
            _layer_apply, static_argnums=(2, 4),  # cfg, attn_fn
            policy=policy)

    def body(x, layer_params):
        return layer_fn(layer_params, x, cfg, rope, attn_fn), None

    x, _ = lax.scan(body, x, params["layers"])
    x = ops.rmsnorm_reference(x, params["ln_f"])
    if return_hidden:
        return x
    logits = _matmul(x, params["head"])
    return logits if logits_dtype is None else logits.astype(logits_dtype)


def _blockwise_nll(x, head, labels, block_v):
    """Per-position next-token NLL WITHOUT materializing [N, vocab].

    Streams the vocabulary in ``block_v`` slices: each scan step does
    one [N, D] x [D, block_v] matmul and folds it into a running
    (max, sumexp, gold-logit) online-logsumexp state — the CE analogue
    of flash attention's online softmax.  The body is jax.checkpoint'd,
    so the backward recomputes each block's logits instead of keeping
    them: peak logits memory drops from N·V to N·block_v (at dim 1024 /
    seq 2048 / vocab 16k / batch 32 that is ~2 GB of bf16 logits that
    never hit HBM), buying batch headroom the sweep can spend.

    ``x``: [N, D] final hidden states (compute dtype); ``head``:
    [D, V] f32 params; ``labels``: [N] int.  Single-chip / data-parallel
    path — under Megatron TP keep the dense CE (the column-parallel
    head wants the per-shard logsumexp exchange instead).
    """
    n, _d = x.shape
    v = head.shape[1]
    if v % block_v:
        raise ValueError(f"vocab {v} not divisible by ce_block {block_v}")
    nb = v // block_v
    # [nb, D, block_v] scan operand: reshape splits V contiguously
    head_blocks = head.reshape(-1, nb, block_v).transpose(1, 0, 2)
    labels = labels.astype(jnp.int32)

    def body(carry, inp):
        m, s, gold = carry
        vb, w = inp
        logits = jnp.dot(
            x, w.astype(x.dtype), preferred_element_type=jnp.float32)
        bm = jnp.max(logits, axis=-1)
        nm = jnp.maximum(m, bm)
        s = s * jnp.exp(m - nm) \
            + jnp.sum(jnp.exp(logits - nm[:, None]), axis=-1)
        base = vb * block_v
        in_blk = (labels >= base) & (labels < base + block_v)
        idx = jnp.clip(labels - base, 0, block_v - 1)
        g = jnp.take_along_axis(logits, idx[:, None], axis=1)[:, 0]
        gold = gold + jnp.where(in_blk, g, 0.0)
        return (nm, s, gold), None

    # finite lower bound, not -inf: exp(min - nm) underflows to exactly
    # 0 like -inf would, but the backward pass never sees inf arithmetic
    init = (jnp.full((n,), jnp.finfo(jnp.float32).min, jnp.float32),
            jnp.zeros((n,), jnp.float32),
            jnp.zeros((n,), jnp.float32))
    (m, s, gold), _ = lax.scan(
        jax.checkpoint(body), init,
        (jnp.arange(nb), head_blocks))
    return m + jnp.log(s) - gold


def loss_fn(params, tokens, cfg: Config, *, attn_fn=None, remat=False,
            labels=None, positions=None, ce_impl="dense", ce_block=2048):
    """Next-token cross entropy (mean over B, S-1).

    Default: labels are ``tokens`` shifted by one (contiguous order).
    For permuted layouts (zigzag long-context), pass explicit ``labels``
    aligned with ``tokens``' positions (-1 = ignore, e.g. each row's
    final global position) plus matching ``positions`` — see
    ``zigzag_lm_batch``.

    ``ce_impl="dense"`` (default): logits stay in the compute dtype
    (bfloat16); the softmax/CE reductions accumulate in float32 — XLA
    fuses the upcast into the reduce, so no [B, S, vocab] float32
    tensor ever hits HBM (round-2 finding: the f32 logits path cost
    ~2 GB of HBM traffic per step at dim 1024 / seq 2048 / vocab 16k).

    ``ce_impl="blockwise"``: never materializes [B, S, vocab] at all —
    the head matmul streams in ``ce_block``-wide vocab slices through an
    online logsumexp (``_blockwise_nll``), checkpointed so the backward
    recomputes each slice.  Single-chip / data-parallel option for when
    logits memory bounds the batch size (a sweep axis)."""
    if ce_impl not in ("dense", "blockwise"):
        raise ValueError(f"unknown ce_impl {ce_impl!r}")
    if ce_impl == "blockwise":
        x = apply(params, tokens, cfg, attn_fn=attn_fn, remat=remat,
                  positions=positions, return_hidden=True)
        if labels is None:
            x = x[:, :-1]
            labels = tokens[:, 1:]
            valid = None
        else:
            valid = labels >= 0
            labels = jnp.maximum(labels, 0)
        b, s, d = x.shape
        nll = _blockwise_nll(
            x.reshape(b * s, d), params["head"],
            labels.reshape(b * s), ce_block).reshape(b, s)
    else:
        logits = apply(params, tokens, cfg, attn_fn=attn_fn,
                       logits_dtype=None, remat=remat, positions=positions)
        if labels is None:
            logits = logits[:, :-1]
            labels = tokens[:, 1:]
            valid = None
        else:
            valid = labels >= 0
            labels = jnp.maximum(labels, 0)
        lse = jax.nn.logsumexp(logits.astype(jnp.float32), axis=-1)
        gold = jnp.take_along_axis(
            logits, labels[..., None].astype(jnp.int32), axis=-1
        )[..., 0].astype(jnp.float32)
        nll = lse - gold
    if valid is None:
        return jnp.mean(nll)
    vf = valid.astype(jnp.float32)
    return jnp.sum(nll * vf) / jnp.maximum(jnp.sum(vf), 1.0)


# ---------------------------------------------------------------------------
# Incremental (KV-cached) decode — the serving/decode tier's model half.
#
# No reference counterpart (the reference delegates all inference to TF
# Serving, SURVEY.md §2.2): ``prefill`` runs the prompt once and hands back
# the per-layer keys/values, ``decode_step`` extends every active slot of a
# preallocated slot-paged cache (serving/decode/kvcache.py) by one token.
# Both reuse the exact ``_layer_apply`` arithmetic (rmsnorm / rope / gelu
# MLP / f32-accumulated matmuls), so a KV-cached greedy decode is
# token-identical to re-running ``apply`` on the growing sequence —
# ``greedy_decode_reference`` below is that oracle, and
# tests/test_decode.py gates the parity.
# ---------------------------------------------------------------------------

_NEG_INF = -1e30  # finite mask fill (ops.attention convention: never -inf)


def _layer_apply_kv(p, x, cfg, rope, attn_fn):
    """``_layer_apply`` that also returns the layer's rope-rotated keys
    and values in cache layout [B, H, S, D].  Keys are cached
    POST-rotation, so a cached entry never needs its position again."""
    b, s, dim = x.shape
    h, hd = cfg.n_heads, cfg.head_dim
    cos, sin, positions = rope

    y = ops.rmsnorm_reference(x, p["ln1"])
    qkv = _matmul(y, p["wqkv"]).reshape(b, s, 3, h, hd)
    q, k, v = qkv[:, :, 0], qkv[:, :, 1], qkv[:, :, 2]
    q = ops.apply_rope(q, cos, sin, positions=positions)
    k = ops.apply_rope(k, cos, sin, positions=positions)
    attn = attn_fn(q, k, v).reshape(b, s, dim)
    x = x + _matmul(attn, p["wo"])

    y = ops.rmsnorm_reference(x, p["ln2"])
    y = _matmul(jax.nn.gelu(_matmul(y, p["w1"])), p["w2"])
    return x + y, k.transpose(0, 2, 1, 3), v.transpose(0, 2, 1, 3)


def prefill(params, tokens, cfg: Config, *, lengths=None, attn_fn=None):
    """Prompt pass for incremental decode.

    ``tokens`` [B, T] int32 right-padded prompts, ``lengths`` [B] true
    prompt lengths (default: all T).  Returns ``(logits, k, v)`` —
    ``logits`` [B, vocab] float32 at each row's final REAL position (the
    next-token distribution), ``k``/``v`` [B, n_layers, n_heads, T,
    head_dim] in the slot-cache layout (keys rope-rotated).

    Padded tail positions produce garbage k/v, but they are never read:
    causal masking keeps them out of the real positions' attention here,
    and ``decode_step`` masks to ``position <= cursor`` while its next
    write lands AT the cursor, overwriting the first padded column.
    """
    if attn_fn is None:
        base = (ops.flash_attention if cfg.attn_impl == "flash"
                else ops.mha_reference)
        attn_fn = functools.partial(base, causal=True)
    dtype = cfg.compute_dtype
    b, t = tokens.shape
    x = params["embed"].astype(dtype)[tokens]
    cos, sin = ops.rope_angles(t, cfg.head_dim, cfg.rope_base)
    rope = (cos, sin, None)

    def body(x, layer_params):
        x, k, v = _layer_apply_kv(layer_params, x, cfg, rope, attn_fn)
        return x, (k, v)

    x, (k, v) = lax.scan(body, x, params["layers"])
    x = ops.rmsnorm_reference(x, params["ln_f"])
    if lengths is None:
        last = jnp.full((b,), t - 1, jnp.int32)
    else:
        last = jnp.asarray(lengths, jnp.int32) - 1
    x_last = jnp.take_along_axis(
        x, jnp.clip(last, 0, t - 1)[:, None, None], axis=1)[:, 0]
    logits = _matmul(x_last, params["head"]).astype(jnp.float32)
    # scan stacks layers leading: [L, B, H, T, D] -> [B, L, H, T, D]
    return logits, k.transpose(1, 0, 2, 3, 4), v.transpose(1, 0, 2, 3, 4)


def _cache_write(cache_l, new, cursors):
    """Write one [H, D] entry per slot at its cursor column:
    ``cache_l`` [S, H, M, D], ``new`` [S, H, D], ``cursors`` [S]."""

    def one(c, n, p):
        return lax.dynamic_update_slice(c, n[:, None, :], (0, p, 0))

    return jax.vmap(one)(cache_l, new, cursors)


def decode_step(params, tokens, cfg: Config, cache_k, cache_v, lengths):
    """One fused continuous-batching decode iteration over ALL slots.

    ``tokens`` [S] int32 — each slot's incoming token (sitting at
    position ``lengths[s]``); ``cache_k``/``cache_v``
    [S, n_layers, n_heads, max_seq, head_dim] (kvcache.SlotKVCache
    arrays, keys rope-rotated); ``lengths`` [S] int32 — tokens already
    resident per slot.  Writes each slot's new k/v at its cursor,
    attends over ``position <= cursor`` only, and returns
    ``(logits [S, vocab] float32, new_cache_k, new_cache_v)``.

    Free/padding slots are numerically inert by construction: with
    length 0 and token 0 a free slot attends exactly its own position-0
    cache column — finite garbage confined to that slot's logits row,
    which the scheduler discards.  No operation mixes slots.
    """
    dtype = cfg.compute_dtype
    h, hd = cfg.n_heads, cfg.head_dim
    s_slots = tokens.shape[0]
    m = cache_k.shape[3]
    lengths = jnp.asarray(lengths, jnp.int32)
    cursors = jnp.clip(lengths, 0, m - 1)
    pos = cursors[:, None]                              # [S, 1] rope rows
    scale = 1.0 / (hd ** 0.5)
    # [S, 1, M] -> broadcasts over heads in the masked-score add below
    kv_mask = jnp.arange(m)[None, None, :] <= cursors[:, None, None]

    x = params["embed"].astype(dtype)[tokens][:, None, :]   # [S, 1, dim]
    cos, sin = ops.rope_angles(m, cfg.head_dim, cfg.rope_base)

    def body(carry, inp):
        x, = carry
        p, ck_l, cv_l = inp                     # ck_l/cv_l: [S, H, M, D]
        y = ops.rmsnorm_reference(x, p["ln1"])
        qkv = _matmul(y, p["wqkv"]).reshape(s_slots, 1, 3, h, hd)
        q, k, v = qkv[:, :, 0], qkv[:, :, 1], qkv[:, :, 2]
        q = ops.apply_rope(q, cos, sin, positions=pos)
        k = ops.apply_rope(k, cos, sin, positions=pos)
        ck_l = _cache_write(ck_l, k[:, 0], cursors)
        cv_l = _cache_write(cv_l, v[:, 0], cursors)
        # f32 masked softmax, ops.mha_reference convention
        qf = q[:, 0].astype(jnp.float32)                      # [S, H, D]
        scores = jnp.einsum(
            "shd,shmd->shm", qf, ck_l.astype(jnp.float32)) * scale
        scores = jnp.where(kv_mask, scores, _NEG_INF)
        probs = jax.nn.softmax(scores, axis=-1)
        attn = jnp.einsum(
            "shm,shmd->shd", probs, cv_l.astype(jnp.float32))
        attn = attn.astype(dtype).reshape(s_slots, 1, h * hd)
        x = x + _matmul(attn, p["wo"])
        y = ops.rmsnorm_reference(x, p["ln2"])
        y = _matmul(jax.nn.gelu(_matmul(y, p["w1"])), p["w2"])
        return (x + y,), (ck_l, cv_l)

    # scan over layers: cache arrives [S, L, ...] -> scan axis leading
    (x,), (new_k, new_v) = lax.scan(
        body, (x,),
        (params["layers"],
         cache_k.transpose(1, 0, 2, 3, 4), cache_v.transpose(1, 0, 2, 3, 4)))
    x = ops.rmsnorm_reference(x, params["ln_f"])
    logits = _matmul(x[:, 0], params["head"]).astype(jnp.float32)
    return (logits,
            new_k.transpose(1, 0, 2, 3, 4),
            new_v.transpose(1, 0, 2, 3, 4))


def decode_step_paged(params, tokens, cfg: Config, pool_k, pool_v,
                      block_tables, lengths):
    """One fused decode iteration over a block-paged KV pool.

    The windowed generalization of ``decode_step`` for
    ``kvcache.PagedKVCache``: ``tokens`` [S, W] int32 is a WINDOW of W
    tokens per slot (W=1 is the plain paged step; W=K is the
    speculative-verify step over a draft window), token j of slot s
    sitting at logical position ``lengths[s] + j``.  ``pool_k``/
    ``pool_v`` are the shared pools [num_blocks, n_layers, n_heads,
    block_size, head_dim]; ``block_tables`` [S, blocks_per_slot] int32
    maps each slot's logical blocks to physical ones (unused entries
    point at sentinel block 0); ``lengths`` [S] int32.  Returns
    ``(logits [S, W, vocab] float32, new_pool_k, new_pool_v)``.

    Write discipline: every window token's k/v is scattered to
    ``table[s, pos//bs]*bs + pos%bs``; positions past the slot's mapped
    capacity are routed into the sentinel block, so a window that
    overruns ``max_seq`` can never clobber another slot's live blocks.
    Query j attends ``position <= lengths[s] + j`` — causal inside the
    window, and stale entries past a rejected draft's rollback cursor
    are unreachable until a later (correct) write lands on them.  Free
    slots (length 0, all-sentinel table) stay numerically inert exactly
    as in ``decode_step``.
    """
    dtype = cfg.compute_dtype
    h, hd = cfg.n_heads, cfg.head_dim
    s_slots, w = tokens.shape
    nb = pool_k.shape[0]
    bs = pool_k.shape[3]
    nbs = block_tables.shape[1]
    cap = nbs * bs                        # per-slot mapped capacity
    lengths = jnp.asarray(lengths, jnp.int32)
    tables = jnp.asarray(block_tables, jnp.int32)
    scale = 1.0 / (hd ** 0.5)

    # positions of the window tokens, [S, W]
    pos = lengths[:, None] + jnp.arange(w, dtype=jnp.int32)[None, :]
    posc = jnp.clip(pos, 0, cap - 1)
    # scatter rows into the flattened [NB*bs, H, D] pool; overflow
    # (pos >= cap) lands in the sentinel block's matching row
    blk = jnp.take_along_axis(tables, posc // bs, axis=1)   # [S, W]
    widx = jnp.where(pos < cap, blk * bs + posc % bs, pos % bs)
    widx = widx.reshape(-1)
    # gather map: every slot's mapped positions, [S, cap]
    gidx = (tables[:, :, None] * bs
            + jnp.arange(bs, dtype=jnp.int32)).reshape(s_slots, cap)
    # [S, 1, W, cap] — query j sees position <= lengths + j
    kv_mask = (jnp.arange(cap)[None, None, None, :]
               <= pos[:, None, :, None])

    x = params["embed"].astype(dtype)[tokens]               # [S, W, dim]
    cos, sin = ops.rope_angles(cap, cfg.head_dim, cfg.rope_base)

    def body(carry, inp):
        x, = carry
        p, pk_l, pv_l = inp             # pk_l/pv_l: [NB, H, bs, D]
        y = ops.rmsnorm_reference(x, p["ln1"])
        qkv = _matmul(y, p["wqkv"]).reshape(s_slots, w, 3, h, hd)
        q, k, v = qkv[:, :, 0], qkv[:, :, 1], qkv[:, :, 2]
        q = ops.apply_rope(q, cos, sin, positions=posc)
        k = ops.apply_rope(k, cos, sin, positions=posc)
        # flatten pool block axis with its in-block axis: [NB*bs, H, D]
        pk_f = pk_l.transpose(0, 2, 1, 3).reshape(nb * bs, h, hd)
        pv_f = pv_l.transpose(0, 2, 1, 3).reshape(nb * bs, h, hd)
        pk_f = pk_f.at[widx].set(k.reshape(-1, h, hd))
        pv_f = pv_f.at[widx].set(v.reshape(-1, h, hd))
        kg = pk_f[gidx].astype(jnp.float32)          # [S, cap, H, D]
        vg = pv_f[gidx].astype(jnp.float32)
        qf = q.astype(jnp.float32)                   # [S, W, H, D]
        scores = jnp.einsum("swhd,smhd->shwm", qf, kg) * scale
        scores = jnp.where(kv_mask, scores, _NEG_INF)
        probs = jax.nn.softmax(scores, axis=-1)
        attn = jnp.einsum("shwm,smhd->swhd", probs, vg)
        attn = attn.astype(dtype).reshape(s_slots, w, h * hd)
        x = x + _matmul(attn, p["wo"])
        y = ops.rmsnorm_reference(x, p["ln2"])
        y = _matmul(jax.nn.gelu(_matmul(y, p["w1"])), p["w2"])
        pk_l = pk_f.reshape(nb, bs, h, hd).transpose(0, 2, 1, 3)
        pv_l = pv_f.reshape(nb, bs, h, hd).transpose(0, 2, 1, 3)
        return (x + y,), (pk_l, pv_l)

    # scan over layers: pools arrive [NB, L, ...] -> scan axis leading
    (x,), (new_k, new_v) = lax.scan(
        body, (x,),
        (params["layers"],
         pool_k.transpose(1, 0, 2, 3, 4), pool_v.transpose(1, 0, 2, 3, 4)))
    x = ops.rmsnorm_reference(x, params["ln_f"])
    logits = _matmul(x, params["head"]).astype(jnp.float32)
    return (logits,
            new_k.transpose(1, 0, 2, 3, 4),
            new_v.transpose(1, 0, 2, 3, 4))


def prefill_extend(params, tokens, cfg: Config, pool_k, pool_v,
                   prefix_tables, prefix_lens, *, lengths=None):
    """Tail prefill on top of trie-matched resident prefix blocks.

    The prefix-sharing half of admission: the matched prompt prefix's
    k/v already live in the paged pool, so only the unmatched TAIL is
    computed.  ``tokens`` [B, T] int32 right-padded tails; ``lengths``
    [B] true tail lengths (default: all T); ``prefix_tables``
    [B, nbp] int32 physical blocks of each row's matched prefix (pad
    rows with sentinel 0); ``prefix_lens`` [B] int32 matched token
    counts (whole blocks, possibly 0).  Tail queries attend the
    gathered prefix (masked to ``position < prefix_lens``) plus the
    tail causally; rope positions are ``prefix_lens + arange(T)``.

    Returns ``(logits [B, vocab] float32 at the last REAL tail
    position, k, v [B, n_layers, n_heads, T, head_dim])`` — the tail
    k/v in prefill layout, which ``PagedKVCache.insert_tail`` scatters
    into the slot's private blocks (the tail starts block-aligned, so
    the writes never touch shared blocks).
    """
    dtype = cfg.compute_dtype
    h, hd = cfg.n_heads, cfg.head_dim
    b, t = tokens.shape
    nb = pool_k.shape[0]
    bs = pool_k.shape[3]
    nbp = prefix_tables.shape[1]
    pcap = nbp * bs
    plens = jnp.asarray(prefix_lens, jnp.int32)
    ptab = jnp.asarray(prefix_tables, jnp.int32)
    scale = 1.0 / (hd ** 0.5)

    pos = plens[:, None] + jnp.arange(t, dtype=jnp.int32)[None, :]
    cos, sin = ops.rope_angles(pcap + t, cfg.head_dim, cfg.rope_base)
    gidx = (ptab[:, :, None] * bs
            + jnp.arange(bs, dtype=jnp.int32)).reshape(b, pcap)
    # [B, 1, 1, P] prefix visibility; [T, T] causal within the tail
    pmask = (jnp.arange(pcap)[None, :] < plens[:, None])[:, None, None, :]
    cmask = (jnp.arange(t)[None, :] <= jnp.arange(t)[:, None]
             )[None, None, :, :]

    x = params["embed"].astype(dtype)[tokens]               # [B, T, dim]

    def layer(carry, inp):
        x, = carry
        p, pk_l, pv_l = inp
        y = ops.rmsnorm_reference(x, p["ln1"])
        qkv = _matmul(y, p["wqkv"]).reshape(b, t, 3, h, hd)
        q, k, v = qkv[:, :, 0], qkv[:, :, 1], qkv[:, :, 2]
        q = ops.apply_rope(q, cos, sin, positions=pos)
        k = ops.apply_rope(k, cos, sin, positions=pos)
        pk_f = pk_l.transpose(0, 2, 1, 3).reshape(nb * bs, h, hd)
        pv_f = pv_l.transpose(0, 2, 1, 3).reshape(nb * bs, h, hd)
        kp = pk_f[gidx].astype(jnp.float32)          # [B, P, H, D]
        vp = pv_f[gidx].astype(jnp.float32)
        qf = q.astype(jnp.float32)
        sp = jnp.einsum("bthd,bphd->bhtp", qf, kp) * scale
        st = jnp.einsum("bthd,bshd->bhts", qf,
                        k.astype(jnp.float32)) * scale
        sp = jnp.where(pmask, sp, _NEG_INF)
        st = jnp.where(cmask, st, _NEG_INF)
        probs = jax.nn.softmax(
            jnp.concatenate([sp, st], axis=-1), axis=-1)
        pp, pt = probs[..., :pcap], probs[..., pcap:]
        attn = (jnp.einsum("bhtp,bphd->bthd", pp, vp)
                + jnp.einsum("bhts,bshd->bthd", pt,
                             v.astype(jnp.float32)))
        attn = attn.astype(dtype).reshape(b, t, h * hd)
        x = x + _matmul(attn, p["wo"])
        y = ops.rmsnorm_reference(x, p["ln2"])
        y = _matmul(jax.nn.gelu(_matmul(y, p["w1"])), p["w2"])
        return (x + y,), (k.transpose(0, 2, 1, 3), v.transpose(0, 2, 1, 3))

    (x,), (k, v) = lax.scan(
        layer, (x,),
        (params["layers"],
         pool_k.transpose(1, 0, 2, 3, 4), pool_v.transpose(1, 0, 2, 3, 4)))
    x = ops.rmsnorm_reference(x, params["ln_f"])
    if lengths is None:
        last = jnp.full((b,), t - 1, jnp.int32)
    else:
        last = jnp.asarray(lengths, jnp.int32) - 1
    x_last = jnp.take_along_axis(
        x, jnp.clip(last, 0, t - 1)[:, None, None], axis=1)[:, 0]
    logits = _matmul(x_last, params["head"]).astype(jnp.float32)
    return logits, k.transpose(1, 0, 2, 3, 4), v.transpose(1, 0, 2, 3, 4)


def greedy_decode_reference(params, prompt, cfg: Config, *, max_tokens,
                            eos_id=None, attn_fn=None):
    """Full-recompute greedy decode — the KV-cache parity oracle
    (tests/test_decode.py): each step re-runs ``apply`` on the whole
    growing sequence and argmaxes the final position.  O(T²) per token;
    test-sized models only."""
    toks = [int(t) for t in prompt]
    out = []
    for _ in range(int(max_tokens)):
        logits = apply(params, jnp.asarray([toks], jnp.int32), cfg,
                       attn_fn=attn_fn)
        nxt = int(jnp.argmax(logits[0, -1]))
        out.append(nxt)
        toks.append(nxt)
        if eos_id is not None and nxt == int(eos_id):
            break
    return out


def zigzag_lm_batch(tokens, perm):
    """Prepare a contiguous-order LM batch for zigzag training:
    returns ``(tokens_p, labels_p, positions)`` where ``tokens_p`` is
    the zigzag-permuted sequence, ``labels_p`` the next token of each
    position in ORIGINAL order (-1 at the final global position), and
    ``positions`` the global rope positions — feed to ``loss_fn(...,
    labels=labels_p, positions=positions)`` with a zigzag ``attn_fn``.
    """
    # roll + where, NOT concatenate(tokens[:, 1:], -1): under the SPMD
    # partitioner (seq-sharded tokens, jitted) the slice+concat lowering
    # summed the two seq shards' contributions — every label came back
    # exactly doubled, overran the vocab, and take_along_axis's
    # out-of-bounds fill turned the loss into NaN.  roll keeps the shift
    # a collective-permute, which partitions correctly.
    s = tokens.shape[1]
    labels = jnp.where(jnp.arange(s) == s - 1, jnp.array(-1, tokens.dtype),
                       jnp.roll(tokens, -1, axis=1))
    return tokens[:, perm], labels[:, perm], jnp.asarray(perm, jnp.int32)
