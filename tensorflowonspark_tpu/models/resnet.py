"""ResNet family (parity workloads: reference examples/resnet — ResNet-56
CIFAR-10 via resnet_cifar_dist.py — and the ResNet-50/ImageNet north-star
config from BASELINE.json).

TPU-first choices:
- NHWC + HWIO everywhere (XLA:TPU's preferred conv layout for MXU tiling);
- parameters in float32, activations/conv compute in bfloat16 (the TPU
  MXU accumulates bf16 convolutions in float32 natively);
- BN running stats in a separate state tree (no optimizer traffic);
- train step is one jittable function — under a mesh-sharded batch, XLA
  emits the gradient all-reduce over ICI.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import optax

from tensorflowonspark_tpu.models import layers as L

# stage plans: depth -> (block, per-stage block counts).
# ImageNet family: 4 stages, width 64.  CIFAR family (6n+2 layers): 3
# stages of n basic blocks — use width=16, small_inputs=True, e.g.
# init(key, depth=56, num_classes=10, width=16, small_inputs=True).
_PLANS = {
    18: ("basic", (2, 2, 2, 2)),
    34: ("basic", (3, 4, 6, 3)),
    50: ("bottleneck", (3, 4, 6, 3)),
    101: ("bottleneck", (3, 4, 23, 3)),
    152: ("bottleneck", (3, 8, 36, 3)),
    # CIFAR 6n+2 plans (reference resnet_cifar_dist.py workload family)
    20: ("basic", (3, 3, 3)),
    32: ("basic", (5, 5, 5)),
    44: ("basic", (7, 7, 7)),
    56: ("basic", (9, 9, 9)),
    110: ("basic", (18, 18, 18)),
}


def _block_init(key, kind, in_ch, ch, stride, dtype):
    ks = jax.random.split(key, 4)
    p, s = {}, {}
    if kind == "bottleneck":
        out_ch = ch * 4
        p["conv1"] = L.conv_init(ks[0], 1, 1, in_ch, ch, dtype, use_bias=False)
        p["bn1"], s["bn1"] = L.batchnorm_init(ch)
        p["conv2"] = L.conv_init(ks[1], 3, 3, ch, ch, dtype, use_bias=False)
        p["bn2"], s["bn2"] = L.batchnorm_init(ch)
        p["conv3"] = L.conv_init(ks[2], 1, 1, ch, out_ch, dtype, use_bias=False)
        p["bn3"], s["bn3"] = L.batchnorm_init(out_ch)
    else:
        out_ch = ch
        p["conv1"] = L.conv_init(ks[0], 3, 3, in_ch, ch, dtype, use_bias=False)
        p["bn1"], s["bn1"] = L.batchnorm_init(ch)
        p["conv2"] = L.conv_init(ks[1], 3, 3, ch, ch, dtype, use_bias=False)
        p["bn2"], s["bn2"] = L.batchnorm_init(ch)
    if stride != 1 or in_ch != out_ch:
        p["proj"] = L.conv_init(ks[3], 1, 1, in_ch, out_ch, dtype, use_bias=False)
        p["bn_proj"], s["bn_proj"] = L.batchnorm_init(out_ch)
    return p, s, out_ch


def _block_apply(p, s, x, kind, stride, train, bn_fused=True):
    ns = {}
    bn = functools.partial(L.batchnorm, train=train, fused=bn_fused)
    # BN→ReLU pairs (and the block-end BN→add→ReLU) route through
    # combined custom VJPs — no stored pre-activation residuals — when
    # bn_fused; see layers.batchnorm_relu / batchnorm_add_relu
    bnr = functools.partial(L.batchnorm_relu, train=train, fused=bn_fused)
    bnar = functools.partial(L.batchnorm_add_relu, train=train,
                             fused=bn_fused)
    shortcut = x
    if "proj" in p:
        shortcut = L.conv(p["proj"], x, stride=stride)
        shortcut, ns["bn_proj"] = bn(p["bn_proj"], s["bn_proj"], shortcut)
    if kind == "bottleneck":
        y = L.conv(p["conv1"], x)
        y, ns["bn1"] = bnr(p["bn1"], s["bn1"], y)
        y = L.conv(p["conv2"], y, stride=stride)
        y, ns["bn2"] = bnr(p["bn2"], s["bn2"], y)
        y = L.conv(p["conv3"], y)
        y, ns["bn3"] = bnar(p["bn3"], s["bn3"], y, shortcut)
    else:
        y = L.conv(p["conv1"], x, stride=stride)
        y, ns["bn1"] = bnr(p["bn1"], s["bn1"], y)
        y = L.conv(p["conv2"], y)
        y, ns["bn2"] = bnar(p["bn2"], s["bn2"], y, shortcut)
    return y, ns


def init(key, depth=50, num_classes=1000, width=64, small_inputs=False,
         dtype=jnp.float32):
    """Build (params, state).  ``small_inputs``: CIFAR-style 3x3 stem
    without max-pool (reference resnet_cifar uses the small stem)."""
    kind, counts = _PLANS[depth]
    keys = jax.random.split(key, sum(counts) + 2)
    ki = iter(keys)
    params, state = {}, {}
    if small_inputs:
        params["stem"] = L.conv_init(next(ki), 3, 3, 3, width, dtype, use_bias=False)
    else:
        params["stem"] = L.conv_init(next(ki), 7, 7, 3, width, dtype, use_bias=False)
    params["bn_stem"], state["bn_stem"] = L.batchnorm_init(width)
    in_ch = width
    for stage, nblocks in enumerate(counts):
        ch = width * (2 ** stage)
        for b in range(nblocks):
            stride = 2 if (b == 0 and stage > 0) else 1
            name = f"s{stage}b{b}"
            params[name], state[name], in_ch = _block_init(
                next(ki), kind, in_ch, ch, stride, dtype
            )
    params["fc"] = L.dense_init(next(ki), in_ch, num_classes, dtype)
    return params, state


def _stem_space_to_depth(w7, x):
    """The 7x7/s2 stem as a 4x4/s1 conv over 2x2 space-to-depth input.

    MXU-tiling fix for the ImageNet stem: a 3-input-channel conv wastes
    most of a (128-lane) MXU pass.  Grouping 2x2 pixels into channels
    (H,W,3 -> H/2,W/2,12) and folding the kernel accordingly computes the
    EXACT same outputs (the 7x7 kernel zero-pads to 8x8 = 4 taps of
    stride-2 phase pairs) with a 192-deep contraction instead of 147 on a
    much squarer operand — the standard MLPerf-ResNet space-to-depth
    transform, applied in-model so checkpoints keep the 7x7 layout.
    """
    b, h, w, c = x.shape
    xs = x.reshape(b, h // 2, 2, w // 2, 2, c)
    xs = xs.transpose(0, 1, 3, 2, 4, 5).reshape(b, h // 2, w // 2, 4 * c)
    # kernel: (7,7,C,O) -> zero row/col after -> (4,2,4,2,C,O) ->
    # (p,q,u,v,C,O) -> (4,4,4C,O); channel order (u,v,c) matches xs
    k = jnp.pad(w7, ((0, 1), (0, 1), (0, 0), (0, 0)))
    k = k.reshape(4, 2, 4, 2, c, -1).transpose(0, 2, 1, 3, 4, 5)
    k = k.reshape(4, 4, 4 * c, -1).astype(x.dtype)
    # SAME geometry of the original: out 112 = in 112 with pad (1, 2)
    return jax.lax.conv_general_dilated(
        xs, k, window_strides=(1, 1), padding=((1, 2), (1, 2)),
        dimension_numbers=("NHWC", "HWIO", "NHWC"),
    )


def apply(params, state, images, depth=50, train=True, small_inputs=False,
          compute_dtype=jnp.bfloat16, stem_s2d=True, bn_fused=True):
    """images [N,H,W,3] → logits [N,num_classes]; returns (logits, new_state)."""
    kind, counts = _PLANS[depth]
    x = images.astype(compute_dtype)
    new_state = {}
    if small_inputs:
        x = L.conv(params["stem"], x)
    elif stem_s2d and x.shape[1] % 2 == 0 and x.shape[2] % 2 == 0:
        x = _stem_space_to_depth(params["stem"]["w"], x)
    else:
        x = L.conv(params["stem"], x, stride=2)
    x, new_state["bn_stem"] = L.batchnorm_relu(
        params["bn_stem"], state["bn_stem"], x, train, fused=bn_fused)
    if not small_inputs:
        # SAME padding: 112 -> 56 (the standard ResNet stem; VALID's 55
        # also breaks the TPU's (8,128) tiling on every stage-1 tensor)
        x = L.max_pool(x, window=3, stride=2, padding="SAME")
    for stage, nblocks in enumerate(counts):
        for b in range(nblocks):
            stride = 2 if (b == 0 and stage > 0) else 1
            name = f"s{stage}b{b}"
            x, new_state[name] = _block_apply(
                params[name], state[name], x, kind, stride, train, bn_fused
            )
    x = L.avg_pool_global(x).astype(jnp.float32)
    return L.dense(params["fc"], x), new_state


def make_train_step(optimizer, depth=50, small_inputs=False,
                    compute_dtype=jnp.bfloat16, remat=False, stem_s2d=True,
                    accum_steps=1, bn_fused=True):
    """(params, state, opt_state, images, labels) →
    (params, state, opt_state, loss, acc); jittable, SPMD-ready.

    ``accum_steps>1`` accumulates gradients over that many microbatches
    under one jit (effective batch beyond HBM limits).  BatchNorm
    normalizes each microbatch with its own statistics (as a sequential
    small-batch loop would), so results are close to — not bit-identical
    with — the one-big-batch step; the running-statistics EMA is
    threaded through the chain and advances once per microbatch.
    Accuracy is the last microbatch's.
    """

    fwd = apply
    if remat:
        fwd = jax.checkpoint(apply, static_argnums=(3, 4, 5, 6, 7, 8))

    def loss_fn(params, state, images, labels):
        logits, new_state = fwd(
            params, state, images, depth, True, small_inputs, compute_dtype,
            stem_s2d, bn_fused
        )
        return L.softmax_cross_entropy(logits, labels), (logits, new_state)

    def value_and_grad(params, state, images, labels):
        if accum_steps == 1:
            return jax.value_and_grad(loss_fn, has_aux=True)(
                params, state, images, labels)
        from tensorflowonspark_tpu.utils.train import \
            accumulated_value_and_grad

        def micro_loss(p, aux_prev, x, y):
            _, st = aux_prev  # BN running stats advance per microbatch
            return loss_fn(p, st, x, y)

        vg = accumulated_value_and_grad(micro_loss, accum_steps,
                                        has_aux=True, carry_aux=True)
        micro_b = images.shape[0] // accum_steps
        logits0 = jnp.zeros((micro_b, params["fc"]["w"].shape[1]),
                            jnp.float32)
        return vg(params, images, labels, init_aux=(logits0, state))

    def train_step(params, state, opt_state, images, labels):
        (loss, (logits, new_state)), grads = value_and_grad(
            params, state, images, labels)
        updates, opt_state = optimizer.update(grads, opt_state, params)
        params = optax.apply_updates(params, updates)
        # accum path: logits/labels are the last microbatch's slice
        acc_labels = (labels if accum_steps == 1
                      else labels[-logits.shape[0]:])
        return (params, new_state, opt_state, loss,
                L.accuracy(logits, acc_labels))

    return train_step


def flops_per_image(depth=50, image_size=224):
    """Forward-pass FLOPs per image, 2 FLOPs per MAC — the standard MFU
    convention (PaLM appendix B; same convention as
    utils.metrics.transformer_flops_per_token).

    The 224x224 table is multiply-accumulate counts (torchvision's
    published GMacs; cross-checked shape-exactly by
    scripts/resnet_traffic.py at 4.12 GMACs for depth 50), doubled here.
    NOTE: before round 4 this function returned the MAC count mislabeled
    as 2*MACs, so every earlier reported ResNet MFU (BENCH_r01–r03,
    PERF.md history) undercounts by exactly 2x; step times and img/s
    were always convention-free.  bench_config.json's stored resnet
    "mfu" was rescaled in the same commit as this fix.
    """
    if depth in (18, 34, 50, 101, 152):
        # standard 224x224 multiply-accumulate counts
        macs = {18: 1.81e9, 34: 3.66e9, 50: 4.09e9,
                101: 7.8e9, 152: 11.5e9}[depth]
        ref = 224
    else:
        # CIFAR 6n+2 family at 32x32 (these were already 2*MACs)
        macs = {20: 0.041e9, 32: 0.069e9, 44: 0.097e9,
                56: 0.126e9, 110: 0.255e9}[depth]
        ref = 32
    return 2.0 * macs * (image_size / ref) ** 2
