"""Image segmentation family: MobileNetV2-style encoder + U-Net decoder.

Parity workload: reference examples/segmentation/segmentation*.py (the
Oxford-IIIT pet U-Net built on a MobileNetV2 encoder with pix2pix-style
upsample blocks; see SURVEY.md §2.5).  Re-designed functionally like the
rest of the zoo: inverted-residual bottlenecks (expand 1x1 → depthwise
3x3 → project 1x1), skip taps after each stride-2 stage, and a
transposed-conv decoder that concatenates the taps U-Net style.

TPU-first notes: NHWC/HWIO; depthwise convs via feature_group_count
(XLA lowers these onto the VPU/MXU efficiently); params fp32 with bf16
compute supported via the input dtype like models/resnet.py.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax
import optax

from tensorflowonspark_tpu.models import layers as L


def _dwconv_init(key, ch, dtype=jnp.float32):
    # depthwise 3x3: HWIO with I=1, O=ch, feature_group_count=ch
    return {"w": L._he_init(key, (3, 3, 1, ch), 9, dtype)}


def _dwconv(params, x, stride=1):
    return lax.conv_general_dilated(
        x,
        params["w"].astype(x.dtype),
        window_strides=(stride, stride),
        padding="SAME",
        dimension_numbers=("NHWC", "HWIO", "NHWC"),
        feature_group_count=x.shape[-1],
    )


def _invres_init(key, in_ch, out_ch, expand, dtype):
    ks = jax.random.split(key, 3)
    mid = in_ch * expand
    p, s = {}, {}
    if expand != 1:
        p["expand"] = L.conv_init(ks[0], 1, 1, in_ch, mid, dtype, use_bias=False)
        p["bn_e"], s["bn_e"] = L.batchnorm_init(mid)
    p["dw"] = _dwconv_init(ks[1], mid, dtype)
    p["bn_d"], s["bn_d"] = L.batchnorm_init(mid)
    p["project"] = L.conv_init(ks[2], 1, 1, mid, out_ch, dtype, use_bias=False)
    p["bn_p"], s["bn_p"] = L.batchnorm_init(out_ch)
    return p, s


def _invres_apply(p, s, x, stride, train):
    ns = {}
    y = x
    if "expand" in p:
        y = L.conv(p["expand"], y)
        # fused BN→ReLU6 pair: no stored pre-activation residual in
        # the backward (layers.batchnorm_relu6)
        y, ns["bn_e"] = L.batchnorm_relu6(p["bn_e"], s["bn_e"], y, train)
    y = _dwconv(p["dw"], y, stride=stride)
    y, ns["bn_d"] = L.batchnorm_relu6(p["bn_d"], s["bn_d"], y, train)
    y = L.conv(p["project"], y)
    y, ns["bn_p"] = L.batchnorm(p["bn_p"], s["bn_p"], y, train)
    if stride == 1 and x.shape[-1] == y.shape[-1]:
        y = x + y
    return y, ns


# encoder stage plan: (out_ch, stride, expand) — a compact MobileNetV2;
# each stride-2 output (pre-stride feature) is a U-Net skip tap.
_ENCODER = [
    (16, 1, 1),
    (24, 2, 6),
    (32, 2, 6),
    (64, 2, 6),
    (96, 1, 6),
]


def _upconv_init(key, in_ch, out_ch, dtype):
    # 3x3 stride-2 transposed conv (pix2pix upsample block sans dropout)
    return {"w": L._he_init(key, (3, 3, in_ch, out_ch), 9 * in_ch, dtype)}


def _upconv(params, x):
    return lax.conv_transpose(
        x,
        params["w"].astype(x.dtype),
        strides=(2, 2),
        padding="SAME",
        dimension_numbers=("NHWC", "HWIO", "NHWC"),
    )


def init(key, num_classes=3, in_ch=3, width=1.0, dtype=jnp.float32):
    """(params, state) for a U-Net over the MobileNetV2-style encoder."""
    ks = iter(jax.random.split(key, 64))
    p, s = {}, {}
    ch = max(8, int(16 * width))
    p["stem"] = L.conv_init(next(ks), 3, 3, in_ch, ch, dtype, use_bias=False)
    p["bn_stem"], s["bn_stem"] = L.batchnorm_init(ch)

    taps = []
    for i, (out_ch, stride, expand) in enumerate(_ENCODER):
        out_ch = max(8, int(out_ch * width))
        if stride == 2:
            taps.append(ch)
        p[f"enc{i}"], s[f"enc{i}"] = _invres_init(next(ks), ch, out_ch, expand, dtype)
        ch = out_ch

    for i, skip_ch in enumerate(reversed(taps)):
        p[f"up{i}"] = _upconv_init(next(ks), ch, skip_ch, dtype)
        p[f"bn_up{i}"], s[f"bn_up{i}"] = L.batchnorm_init(skip_ch)
        ch = skip_ch * 2  # concat with the tap
    p["head"] = _upconv_init(next(ks), ch, num_classes, dtype)
    return p, s


def apply(params, state, x, train=False):
    """[B, H, W, C] -> ([B, H, W, num_classes] logits, new_state).
    H and W must be divisible by 2**(#stride-2 stages + stem)."""
    ns = {}
    y = L.conv(params["stem"], x, stride=2)
    y, ns["bn_stem"] = L.batchnorm_relu6(
        params["bn_stem"], state["bn_stem"], y, train)

    taps = []
    for i, (_, stride, _) in enumerate(_ENCODER):
        if stride == 2:
            taps.append(y)
        y, ns[f"enc{i}"] = _invres_apply(
            params[f"enc{i}"], state[f"enc{i}"], y, stride, train
        )

    for i, tap in enumerate(reversed(taps)):
        y = _upconv(params[f"up{i}"], y)
        # fused BN→ReLU pair: no stored pre-activation residual in the
        # backward (layers.batchnorm_relu)
        y, ns[f"bn_up{i}"] = L.batchnorm_relu(
            params[f"bn_up{i}"], state[f"bn_up{i}"], y, train
        )
        y = jnp.concatenate([y, tap], axis=-1)
    logits = _upconv(params["head"], y)
    return logits, ns


def loss_fn(params, state, images, masks, train=True):
    """Per-pixel CE; masks [B, H, W] int. Returns (loss, new_state)."""
    logits, ns = apply(params, state, images, train=train)
    loss = L.softmax_cross_entropy(
        logits.reshape(-1, logits.shape[-1]), masks.reshape(-1)
    )
    return loss, ns


def make_train_step(opt):
    """Jittable (params, state, opt_state, images, masks) -> updated + loss.
    Under a mesh-sharded batch, GSPMD emits the gradient all-reduce."""

    def step(params, state, opt_state, images, masks):
        (loss, ns), grads = jax.value_and_grad(loss_fn, has_aux=True)(
            params, state, images, masks
        )
        updates, opt_state = opt.update(grads, opt_state, params)
        params = optax.apply_updates(params, updates)
        return params, ns, opt_state, loss

    return step
