"""Minimal functional layer library: pytree params + pure apply functions.

Design: every layer is an ``init(key, ...) -> params`` plus a pure
``apply(params, x, ...)``; models are compositions.  No module classes,
no tracing magic — everything is jit/grad/shard_map friendly, params are
plain nested dicts that shard naturally with NamedSharding trees.

Convolutions use NHWC with HWIO kernels — the layout XLA:TPU maps best
onto the MXU; matmuls accumulate in float32 while activations/weights
may be bfloat16 (``compute_dtype``).
"""

from __future__ import annotations

import math

import jax
import jax.numpy as jnp
from jax import lax


def _he_init(key, shape, fan_in, dtype):
    return jax.random.normal(key, shape, dtype) * jnp.asarray(
        math.sqrt(2.0 / fan_in), dtype
    )


# -- dense -------------------------------------------------------------------

def dense_init(key, in_dim, out_dim, dtype=jnp.float32):
    wkey, _ = jax.random.split(key)
    return {
        "w": _he_init(wkey, (in_dim, out_dim), in_dim, dtype),
        "b": jnp.zeros((out_dim,), dtype),
    }


def dense(params, x, precision=None):
    return (
        jnp.dot(x, params["w"], precision=precision,
                preferred_element_type=jnp.float32).astype(x.dtype)
        + params["b"]
    )


# -- conv --------------------------------------------------------------------

def conv_init(key, h, w, in_ch, out_ch, dtype=jnp.float32, use_bias=True):
    wkey, _ = jax.random.split(key)
    p = {"w": _he_init(wkey, (h, w, in_ch, out_ch), h * w * in_ch, dtype)}
    if use_bias:
        p["b"] = jnp.zeros((out_ch,), dtype)
    return p


def conv(params, x, stride=1, padding="SAME"):
    strides = (stride, stride) if isinstance(stride, int) else stride
    y = lax.conv_general_dilated(
        x,
        params["w"],
        window_strides=strides,
        padding=padding,
        dimension_numbers=("NHWC", "HWIO", "NHWC"),
        preferred_element_type=jnp.float32,
    ).astype(x.dtype)
    if "b" in params:
        y = y + params["b"]
    return y


# -- norm --------------------------------------------------------------------

def batchnorm_init(ch, dtype=jnp.float32):
    return {
        "scale": jnp.ones((ch,), dtype),
        "bias": jnp.zeros((ch,), dtype),
        "mean": jnp.zeros((ch,), jnp.float32),
        "var": jnp.ones((ch,), jnp.float32),
    }


def batchnorm(params, x, train=True, momentum=0.9, eps=1e-5, axis_name=None):
    """BatchNorm over N,H,W.  In SPMD training under jit, batch statistics
    are computed over the *global* batch automatically when the batch dim
    is mesh-sharded (XLA turns the mean reductions into all-reduces); no
    explicit axis_name is required inside pjit-style code.

    Returns (y, new_params) in train mode; (y, params) in eval mode.
    """
    reduce_axes = tuple(range(x.ndim - 1))
    if train:
        mean = jnp.mean(x.astype(jnp.float32), axis=reduce_axes)
        var = jnp.var(x.astype(jnp.float32), axis=reduce_axes)
        if axis_name is not None:
            mean = lax.pmean(mean, axis_name)
            var = lax.pmean(var, axis_name)
        new = dict(params)
        new["mean"] = momentum * params["mean"] + (1 - momentum) * mean
        new["var"] = momentum * params["var"] + (1 - momentum) * var
    else:
        mean, var = params["mean"], params["var"]
        new = params
    inv = lax.rsqrt(var + eps)
    y = (x - mean.astype(x.dtype)) * (inv.astype(x.dtype))
    y = y * params["scale"] + params["bias"]
    return y, new


def layernorm_init(dim, dtype=jnp.float32):
    return {"scale": jnp.ones((dim,), dtype), "bias": jnp.zeros((dim,), dtype)}


def layernorm(params, x, eps=1e-6):
    mu = jnp.mean(x, axis=-1, keepdims=True)
    var = jnp.var(x, axis=-1, keepdims=True)
    y = (x - mu) * lax.rsqrt(var + eps)
    return y * params["scale"] + params["bias"]


# -- pooling / activations ---------------------------------------------------

def max_pool(x, window=2, stride=2):
    return lax.reduce_window(
        x,
        -jnp.inf,
        lax.max,
        (1, window, window, 1),
        (1, stride, stride, 1),
        "VALID",
    )


def avg_pool_global(x):
    return jnp.mean(x, axis=(1, 2))


def relu(x):
    return jnp.maximum(x, 0)


# -- losses ------------------------------------------------------------------

def softmax_cross_entropy(logits, labels, num_classes=None):
    """Mean CE; integer labels.  Stable log-softmax in float32."""
    logits = logits.astype(jnp.float32)
    logz = jax.nn.logsumexp(logits, axis=-1, keepdims=True)
    logp = logits - logz
    nll = -jnp.take_along_axis(logp, labels[..., None].astype(jnp.int32), axis=-1)
    return jnp.mean(nll)


def accuracy(logits, labels):
    return jnp.mean((jnp.argmax(logits, axis=-1) == labels).astype(jnp.float32))
