"""Minimal functional layer library: pytree params + pure apply functions.

Design: every layer is an ``init(key, ...) -> params`` plus a pure
``apply(params, x, ...)``; models are compositions.  No module classes,
no tracing magic — everything is jit/grad/shard_map friendly, params are
plain nested dicts that shard naturally with NamedSharding trees.

Convolutions use NHWC with HWIO kernels — the layout XLA:TPU maps best
onto the MXU; matmuls accumulate in float32 while activations/weights
may be bfloat16 (``compute_dtype``).
"""

from __future__ import annotations

import functools
import math

import jax
import jax.numpy as jnp
from jax import lax


def _he_init(key, shape, fan_in, dtype):
    return jax.random.normal(key, shape, dtype) * jnp.asarray(
        math.sqrt(2.0 / fan_in), dtype
    )


# -- dense -------------------------------------------------------------------

def dense_init(key, in_dim, out_dim, dtype=jnp.float32):
    wkey, _ = jax.random.split(key)
    return {
        "w": _he_init(wkey, (in_dim, out_dim), in_dim, dtype),
        "b": jnp.zeros((out_dim,), dtype),
    }


def dense(params, x, precision=None):
    w = params["w"].astype(x.dtype)  # params live in fp32; compute dtype follows x
    return (
        jnp.dot(x, w, precision=precision,
                preferred_element_type=jnp.float32).astype(x.dtype)
        + params["b"].astype(x.dtype)
    )


# -- conv --------------------------------------------------------------------

def conv_init(key, h, w, in_ch, out_ch, dtype=jnp.float32, use_bias=True):
    wkey, _ = jax.random.split(key)
    p = {"w": _he_init(wkey, (h, w, in_ch, out_ch), h * w * in_ch, dtype)}
    if use_bias:
        p["b"] = jnp.zeros((out_ch,), dtype)
    return p


def conv(params, x, stride=1, padding="SAME"):
    strides = (stride, stride) if isinstance(stride, int) else stride
    # No explicit preferred_element_type: the TPU MXU already accumulates
    # bf16 convs in f32, and an f32 result dtype breaks the conv transpose
    # (bf16 operands meet an f32 cotangent in the backward pass).
    y = lax.conv_general_dilated(
        x,
        params["w"].astype(x.dtype),  # fp32 master weights, bf16 compute
        window_strides=strides,
        padding=padding,
        dimension_numbers=("NHWC", "HWIO", "NHWC"),
    )
    if "b" in params:
        y = y + params["b"].astype(x.dtype)
    return y


# -- norm --------------------------------------------------------------------

def batchnorm_init(ch, dtype=jnp.float32):
    """Returns (params, state): trainable scale/bias vs running stats.

    Keeping running statistics in a separate state tree keeps the
    optimizer and grad transform off them (they receive no gradient)."""
    params = {"scale": jnp.ones((ch,), dtype), "bias": jnp.zeros((ch,), dtype)}
    state = {"mean": jnp.zeros((ch,), jnp.float32), "var": jnp.ones((ch,), jnp.float32)}
    return params, state


def _bn_stats(x, eps):
    """One-pass E[x]/E[x^2] (f32 accumulation over one bf16 read) →
    (mean, var, inv)."""
    reduce_axes = tuple(range(x.ndim - 1))
    n = x.size // x.shape[-1]
    xf = x.astype(jnp.float32)
    mean = jnp.sum(xf, axis=reduce_axes) / n
    mean_sq = jnp.sum(xf * xf, axis=reduce_axes) / n
    var = jnp.maximum(mean_sq - mean * mean, 0.0)
    return mean, var, lax.rsqrt(var + eps)


def _bn_scale_bias(mean, inv, scale, bias, dtype):
    # fold (mean, inv, scale, bias) in f32, apply as one fused
    # multiply-add in the compute dtype — keeps activations bf16 (an f32
    # scale would silently upcast the whole network downstream)
    sf = scale.astype(jnp.float32)
    mul = (inv * sf).astype(dtype)
    add = (bias.astype(jnp.float32) - mean * inv * sf).astype(dtype)
    return mul, add


@functools.partial(jax.custom_vjp, nondiff_argnums=(3,))
def _bn_train_fused(x, scale, bias, eps):
    """Training-mode BN core with a two-pass hand-written backward.

    The autodiff backward of the folded form materializes several
    standalone activation-sized multiplies (x̂ recompute, dvar/dmean
    broadcasts) that XLA:TPU does not fuse — measured ~37ms of a 97ms
    ResNet-50/b256 step on v5e (PERF_BREAKDOWN.md).  The custom VJP
    expresses the whole backward as one reduction pass over (g, x) and
    one elementwise pass dx = a·g + b·x + c, each a single fusion.
    """
    mean, var, inv = _bn_stats(x, eps)
    mul, add = _bn_scale_bias(mean, inv, scale, bias, x.dtype)
    return x * mul + add, mean, var


def _bn_train_fused_fwd(x, scale, bias, eps):
    mean, var, inv = _bn_stats(x, eps)
    mul, add = _bn_scale_bias(mean, inv, scale, bias, x.dtype)
    return (x * mul + add, mean, var), (x, mean, inv, scale)


def _bn_bwd_core(gm, x, mean, inv, scale, mean_ct, var_ct):
    """Shared two-pass BN backward given the (possibly relu-gated)
    f32 cotangent ``gm``; returns (dx, dscale, dbias).

    Pass 1 is one fused reduction over (gm, x); pass 2 is
    dx = a·gm + b·x + c — γ·inv·(gm − Σgm/n − x̂·Σ(gm·x̂)/n) rearranged
    so the whole thing is a single elementwise fusion.  The (mean, var)
    output cotangents (zero in the training path — they only feed the
    non-differentiated EMA state — but cheap to honor exactly) fold
    into the same b/c vectors."""
    reduce_axes = tuple(range(x.ndim - 1))
    n = x.size // x.shape[-1]
    xf = x.astype(jnp.float32)
    sum_g = jnp.sum(gm, axis=reduce_axes)
    sum_gx = jnp.sum(gm * xf, axis=reduce_axes)
    sum_g_xhat = (sum_gx - mean * sum_g) * inv
    sf = scale.astype(jnp.float32)
    a = sf * inv
    b = -sf * inv * inv * sum_g_xhat / n
    c = -a * sum_g / n - b * mean
    b = b + 2.0 * var_ct / n
    c = c + (mean_ct - 2.0 * var_ct * mean) / n
    dx = (a * gm + b * xf + c).astype(x.dtype)
    return dx, sum_g_xhat.astype(scale.dtype), sum_g.astype(scale.dtype)


def _bn_train_fused_bwd(eps, res, cts):
    x, mean, inv, scale = res
    g, mean_ct, var_ct = cts
    return _bn_bwd_core(g.astype(jnp.float32), x, mean, inv, scale,
                        mean_ct, var_ct)


_bn_train_fused.defvjp(_bn_train_fused_fwd, _bn_train_fused_bwd)


def _make_bn_act_fused(act, gate):
    """Factory for BN→activation pairs sharing one custom VJP.

    Autodiff stores two activation-sized residuals per pair (x for the
    BN backward, the pre-activation for the act gate).  Here only x is
    saved; the backward recomputes the gate from x and the per-channel
    (mean, inv, scale, bias) vectors inside its existing passes — one
    fewer activation HBM round-trip per pair, on top of the fused-BN
    backward's two-pass structure (see ``_bn_train_fused``).

    ``act(pre)`` is the forward activation; ``gate(pre)`` its f32
    derivative evaluated on the pre-activation recomputed EXACTLY as
    the forward computed it (same ops, same dtype), so the subgradient
    convention at ties is whatever ``gate`` encodes."""

    @functools.partial(jax.custom_vjp, nondiff_argnums=(3,))
    def bn_act(x, scale, bias, eps):
        mean, var, inv = _bn_stats(x, eps)
        mul, add = _bn_scale_bias(mean, inv, scale, bias, x.dtype)
        return act(x * mul + add), mean, var

    def fwd(x, scale, bias, eps):
        mean, var, inv = _bn_stats(x, eps)
        mul, add = _bn_scale_bias(mean, inv, scale, bias, x.dtype)
        return (act(x * mul + add), mean, var), (x, mean, inv, scale, bias)

    def bwd(eps, res, cts):
        x, mean, inv, scale, bias = res
        g, mean_ct, var_ct = cts
        mul, add = _bn_scale_bias(mean, inv, scale, bias, x.dtype)
        gm = g.astype(jnp.float32) * gate(x * mul + add)
        return _bn_bwd_core(gm, x, mean, inv, scale, mean_ct, var_ct)

    bn_act.defvjp(fwd, bwd)
    return bn_act


# relu: sign() reproduces jnp.maximum's tie convention (gradient 1/2
# where the pre-activation is exactly 0)
_bn_relu_train_fused = _make_bn_act_fused(
    lambda pre: jnp.maximum(pre, 0),
    lambda pre: (jnp.sign(pre.astype(jnp.float32)) + 1.0) * 0.5)
# relu6 (MobileNet-style blocks): jax.nn.relu6's gradient is 0 at BOTH
# saturation boundaries (strict inequalities)
_bn_relu6_train_fused = _make_bn_act_fused(
    jax.nn.relu6,
    lambda pre: ((pre.astype(jnp.float32) > 0)
                 & (pre.astype(jnp.float32) < 6)).astype(jnp.float32))


@functools.partial(jax.custom_vjp, nondiff_argnums=(4,))
def _bn_add_relu_train_fused(x, shortcut, scale, bias, eps):
    """relu(bn(x) + shortcut) — the ResNet block-end pattern — as one
    custom VJP.

    Autodiff saves x (BN backward) plus the pre-relu sum (relu gate) —
    two activation-sized residuals at the block's WIDEST tensor.  Here
    the residuals are x and shortcut, and for identity blocks the
    shortcut is the block input that the first conv's backward already
    keeps resident, so XLA stores one activation instead of two; the
    gate is recomputed from (x, shortcut) inside the backward's
    existing passes."""
    mean, var, inv = _bn_stats(x, eps)
    mul, add = _bn_scale_bias(mean, inv, scale, bias, x.dtype)
    return jnp.maximum(x * mul + add + shortcut, 0), mean, var


def _bn_add_relu_train_fused_fwd(x, shortcut, scale, bias, eps):
    mean, var, inv = _bn_stats(x, eps)
    mul, add = _bn_scale_bias(mean, inv, scale, bias, x.dtype)
    y = jnp.maximum(x * mul + add + shortcut, 0)
    return (y, mean, var), (x, shortcut, mean, inv, scale, bias)


def _bn_add_relu_train_fused_bwd(eps, res, cts):
    x, shortcut, mean, inv, scale, bias = res
    g, mean_ct, var_ct = cts
    # recompute the pre-activation exactly as the forward did; sign()
    # reproduces jnp.maximum's tie convention (gradient 1/2 at 0)
    mul, add = _bn_scale_bias(mean, inv, scale, bias, x.dtype)
    pre = x * mul + add + shortcut
    gate = (jnp.sign(pre.astype(jnp.float32)) + 1.0) * 0.5
    gm = g.astype(jnp.float32) * gate
    dx, dscale, dbias = _bn_bwd_core(gm, x, mean, inv, scale,
                                     mean_ct, var_ct)
    return dx, gm.astype(shortcut.dtype), dscale, dbias


_bn_add_relu_train_fused.defvjp(_bn_add_relu_train_fused_fwd,
                                _bn_add_relu_train_fused_bwd)


def _ema_state(state, mean, var, momentum):
    return {
        "mean": momentum * state["mean"] + (1 - momentum) * mean,
        "var": momentum * state["var"] + (1 - momentum) * var,
    }


def batchnorm(params, state, x, train=True, momentum=0.9, eps=1e-5,
              fused=True):
    """BatchNorm over N,H,W.  In SPMD training under jit, batch statistics
    are computed over the *global* batch automatically when the batch dim
    is mesh-sharded (XLA turns the mean reductions into all-reduces).

    ``fused=True`` (training only) routes through a custom-VJP core whose
    backward is two fused HBM passes instead of autodiff's unfused chain
    (see ``_bn_train_fused``); set False for the plain autodiff path.

    Returns (y, new_state); state is unchanged in eval mode.
    """
    if train:
        if fused:
            y, mean, var = _bn_train_fused(
                x, params["scale"], params["bias"], eps)
        else:
            mean, var, inv = _bn_stats(x, eps)
            mul, add = _bn_scale_bias(
                mean, inv, params["scale"], params["bias"], x.dtype)
            y = x * mul + add
        return y, _ema_state(state, mean, var, momentum)
    mean, var = state["mean"], state["var"]
    inv = lax.rsqrt(var + eps)
    mul, add = _bn_scale_bias(mean, inv, params["scale"], params["bias"],
                              x.dtype)
    return x * mul + add, state


def _batchnorm_act(fused_core, act, params, state, x, train, momentum,
                   eps, fused):
    if train and fused:
        y, mean, var = fused_core(x, params["scale"], params["bias"], eps)
        return y, _ema_state(state, mean, var, momentum)
    y, new_state = batchnorm(params, state, x, train=train,
                             momentum=momentum, eps=eps, fused=fused)
    return act(y), new_state


def batchnorm_relu(params, state, x, train=True, momentum=0.9, eps=1e-5,
                   fused=True):
    """BatchNorm followed by ReLU.  In fused training mode the pair
    shares one custom VJP (``_make_bn_act_fused``) that stores no
    pre-activation residual; otherwise it is exactly
    ``relu(batchnorm(...))``.  Returns (y, new_state)."""
    return _batchnorm_act(_bn_relu_train_fused, relu, params, state, x,
                          train, momentum, eps, fused)


def batchnorm_relu6(params, state, x, train=True, momentum=0.9, eps=1e-5,
                    fused=True):
    """BatchNorm followed by ReLU6 (MobileNet-style blocks); fused
    training mode shares one custom VJP, otherwise exactly
    ``jax.nn.relu6(batchnorm(...))``.  Returns (y, new_state)."""
    return _batchnorm_act(_bn_relu6_train_fused, jax.nn.relu6, params,
                          state, x, train, momentum, eps, fused)


def batchnorm_add_relu(params, state, x, shortcut, train=True, momentum=0.9,
                       eps=1e-5, fused=True):
    """relu(batchnorm(x) + shortcut) — the ResNet block-end.  In fused
    training mode the whole pattern shares one custom VJP
    (``_bn_add_relu_train_fused``) that stores no pre-relu sum;
    otherwise it is exactly relu(batchnorm(...) + shortcut).
    Returns (y, new_state)."""
    if train and fused:
        y, mean, var = _bn_add_relu_train_fused(
            x, shortcut, params["scale"], params["bias"], eps)
        return y, _ema_state(state, mean, var, momentum)
    y, new_state = batchnorm(params, state, x, train=train,
                             momentum=momentum, eps=eps, fused=fused)
    return relu(y + shortcut), new_state


def layernorm_init(dim, dtype=jnp.float32):
    return {"scale": jnp.ones((dim,), dtype), "bias": jnp.zeros((dim,), dtype)}


def layernorm(params, x, eps=1e-6):
    mu = jnp.mean(x, axis=-1, keepdims=True)
    var = jnp.var(x, axis=-1, keepdims=True)
    y = (x - mu) * lax.rsqrt(var + eps)
    return y * params["scale"] + params["bias"]


# -- pooling / activations ---------------------------------------------------

def max_pool(x, window=2, stride=2, padding="VALID"):
    return lax.reduce_window(
        x,
        -jnp.inf,
        lax.max,
        (1, window, window, 1),
        (1, stride, stride, 1),
        padding,
    )


def avg_pool_global(x):
    return jnp.mean(x, axis=(1, 2))


def relu(x):
    return jnp.maximum(x, 0)


# -- losses ------------------------------------------------------------------

def softmax_cross_entropy(logits, labels, num_classes=None):
    """Mean CE; integer labels.  Stable log-softmax in float32."""
    logits = logits.astype(jnp.float32)
    logz = jax.nn.logsumexp(logits, axis=-1, keepdims=True)
    logp = logits - logz
    nll = -jnp.take_along_axis(logp, labels[..., None].astype(jnp.int32), axis=-1)
    return jnp.mean(nll)


def accuracy(logits, labels):
    return jnp.mean((jnp.argmax(logits, axis=-1) == labels).astype(jnp.float32))
