"""Mixture-of-Experts MLP with expert parallelism (ep mesh axis).

No reference counterpart (pre-MoE era); built so expert weights shard
over a named mesh axis and the dispatch/combine einsums lower to XLA
all-to-all/all-reduce collectives under GSPMD — no hand-written routing
comms.

Design: top-1 switch routing (Switch Transformer style) with a dense
one-hot dispatch: for the moderate expert counts the zoo targets, the
dense [B*S, E] dispatch einsum is MXU-friendly and exactly
differentiable (no sort/scatter, no dynamic shapes under jit), at the
cost of E-way redundant FLOPs vs capacity-based gather — the classic
correctness-first TPU formulation.  A load-balance aux loss keeps the
router from collapsing.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from tensorflowonspark_tpu.models import layers as L


def init(key, dim, hidden, num_experts, dtype=jnp.float32):
    kr, k1, k2 = jax.random.split(key, 3)
    return {
        "router": L._he_init(kr, (dim, num_experts), dim, dtype),
        "w1": L._he_init(k1, (num_experts, dim, hidden), dim, dtype),
        "w2": L._he_init(k2, (num_experts, hidden, dim), hidden, dtype),
    }


def param_specs(*, ep_axis="model", fsdp_axis=None):
    """Expert axis sharded over ``ep_axis``: each device holds E/n experts;
    GSPMD inserts the dispatch/combine collectives."""
    return {
        "router": P(None, None),
        "w1": P(ep_axis, fsdp_axis, None),
        "w2": P(ep_axis, None, fsdp_axis),
    }


def apply(params, x, *, balance_weight=1e-2):
    """x [B, S, D] -> (y [B, S, D], aux_loss).

    aux_loss is the switch load-balance term E * sum_e f_e * p_e
    (fraction routed * mean router prob), 1.0 at perfect balance.
    """
    b, s, d = x.shape
    xf = x.reshape(b * s, d)
    logits = jnp.dot(
        xf, params["router"].astype(x.dtype),
        preferred_element_type=jnp.float32,
    )
    probs = jax.nn.softmax(logits.astype(jnp.float32), axis=-1)
    expert = jnp.argmax(probs, axis=-1)
    num_experts = params["w1"].shape[0]
    onehot = jax.nn.one_hot(expert, num_experts, dtype=x.dtype)
    gate = jnp.take_along_axis(probs, expert[:, None], axis=-1).astype(x.dtype)

    # dense dispatch: every expert sees every token, masked by routing —
    # [T, E, D] x [E, D, H] contract over D per expert
    dispatched = jnp.einsum("te,td->etd", onehot, xf)
    h = jax.nn.gelu(jnp.einsum(
        "etd,edh->eth", dispatched, params["w1"].astype(x.dtype),
        preferred_element_type=jnp.float32,
    ).astype(x.dtype))
    out = jnp.einsum(
        "eth,ehd->etd", h, params["w2"].astype(x.dtype),
        preferred_element_type=jnp.float32,
    ).astype(x.dtype)
    combined = jnp.einsum("etd,te->td", out, onehot) * gate

    frac_routed = jnp.mean(onehot.astype(jnp.float32), axis=0)
    mean_prob = jnp.mean(probs, axis=0)
    aux = balance_weight * num_experts * jnp.sum(frac_routed * mean_prob)
    return combined.reshape(b, s, d), aux
