"""MNIST CNN (parity workload: reference examples/mnist/keras/mnist_*.py —
Conv(32)→Conv(64)→pool→Dense(128)→Dense(10), mnist_tf.py model shape).

Pure-functional model + a data-parallel train step designed for pjit over
a mesh: params replicated (or fsdp-sharded), batch sharded on 'data'.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import optax

from tensorflowonspark_tpu.models import layers as L


def init_params(key, dtype=jnp.float32):
    k = jax.random.split(key, 4)
    return {
        "conv1": L.conv_init(k[0], 3, 3, 1, 32, dtype),
        "conv2": L.conv_init(k[1], 3, 3, 32, 64, dtype),
        "fc1": L.dense_init(k[2], 7 * 7 * 64, 128, dtype),
        "fc2": L.dense_init(k[3], 128, 10, dtype),
    }


def apply(params, images):
    """images: [N, 28, 28, 1] float in [0,1] → logits [N, 10]."""
    x = L.relu(L.conv(params["conv1"], images))
    x = L.max_pool(x)                      # 14x14
    x = L.relu(L.conv(params["conv2"], x))
    x = L.max_pool(x)                      # 7x7
    x = x.reshape(x.shape[0], -1)
    x = L.relu(L.dense(params["fc1"], x))
    return L.dense(params["fc2"], x)


def loss_fn(params, images, labels):
    logits = apply(params, images)
    return L.softmax_cross_entropy(logits, labels), logits


def make_train_step(optimizer):
    """Returns jittable (params, opt_state, images, labels) → (params,
    opt_state, loss, accuracy).  Under a sharded batch, XLA inserts the
    gradient all-reduce (the MultiWorkerMirroredStrategy equivalent)."""

    def train_step(params, opt_state, images, labels):
        (loss, logits), grads = jax.value_and_grad(loss_fn, has_aux=True)(
            params, images, labels
        )
        updates, opt_state = optimizer.update(grads, opt_state, params)
        params = optax.apply_updates(params, updates)
        return params, opt_state, loss, L.accuracy(logits, labels)

    return train_step


def synthetic_batch(key, n):
    """Deterministic learnable synthetic data: class = (sum of a fixed
    pixel mask) bucket.  Used by tests and the CI slice when no real
    MNIST files exist (zero-egress environments)."""
    kimg, = jax.random.split(key, 1)
    images = jax.random.uniform(kimg, (n, 28, 28, 1))
    # label depends linearly on mean brightness of quadrants → learnable
    q = jnp.stack(
        [
            images[:, :14, :14, 0].mean((1, 2)),
            images[:, :14, 14:, 0].mean((1, 2)),
            images[:, 14:, :14, 0].mean((1, 2)),
            images[:, 14:, 14:, 0].mean((1, 2)),
        ],
        axis=-1,
    )
    labels = (jnp.argmax(q, axis=-1) * 2 + (q.sum(-1) > 2.0)).astype(jnp.int32)
    return images, labels


def predict(params, inputs):
    """Export predict signature ({tensor_name: ndarray} -> outputs dict),
    referenced from export metadata as
    ``tensorflowonspark_tpu.models.mnist:predict``."""
    import numpy as np

    (x,) = inputs.values()
    x = np.asarray(x, dtype=np.float32)
    if x.ndim == 2:  # flat 784 rows (CSV / TFRecord ingestion)
        x = x.reshape(-1, 28, 28, 1)
    logits = np.asarray(apply(params, x))
    return {"prediction": logits.argmax(-1), "logits": logits}


def serve_predict(params, inputs):
    """jax-pure variant of :func:`predict` for the online serving path
    (``tensorflowonspark_tpu.models.mnist:serve_predict``): no numpy
    round-trips, so serving's per-bucket AOT compilation
    (``jax.jit(fn).lower(...).compile()``) applies — one executable per
    shape bucket (serving/replicas._Predictor)."""
    (x,) = inputs.values()
    x = jnp.asarray(x, jnp.float32)
    if x.ndim == 2:  # flat 784 rows
        x = x.reshape(-1, 28, 28, 1)
    logits = apply(params, x)
    return {"prediction": jnp.argmax(logits, axis=-1), "logits": logits}
