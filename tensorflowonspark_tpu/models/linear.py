"""Linear regression — the smallest model family.

The reference's pipeline CI gate trains exactly this shape (Keras
Dense(1) on two features, test_pipeline.py:89-172); kept here both as
that parity workload and as the simplest exported-predict example.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import optax


def init_params(key=None, dim=2, dtype=jnp.float32):
    del key  # zero init is standard for linear regression
    return {"w": jnp.zeros((dim,), dtype), "b": jnp.zeros((), dtype)}


def apply(params, x):
    return jnp.asarray(x) @ params["w"] + params["b"]


def make_train_step(optimizer):
    """(params, opt_state, x, y) -> (params, opt_state, loss); jittable."""

    def loss_fn(params, x, y):
        pred = apply(params, x)
        return jnp.mean((pred - y) ** 2)

    def step(params, opt_state, x, y):
        loss, grads = jax.value_and_grad(loss_fn)(params, x, y)
        updates, opt_state = optimizer.update(grads, opt_state, params)
        params = optax.apply_updates(params, updates)
        return params, opt_state, loss

    return step


def predict(params, inputs):
    """Export predict signature: {tensor_name: ndarray} -> predictions.

    Referenced from export metadata as
    ``tensorflowonspark_tpu.models.linear:predict``.
    """
    import numpy as np

    (x,) = inputs.values()
    return np.asarray(apply(params, np.asarray(x, dtype=np.float32)))
