"""Stall-driven data-worker autoscaling.

Parity target: the ingest-driven horizontal scaling of the tf.data
service design (PAPERS.md arxiv 2101.12127 §3.3: add workers while the
trainers' input wait is the bottleneck, remove them when it is not);
the reference TensorFlowOnSpark had a fixed feeder-per-partition
topology and no scaling signal at all.

Signal: the trainers' **feed-wait ratio** — the fraction of wall time
trainers spent blocked on the input queue, straight from the
``tfos_feed_wait_seconds_total`` counters every instrumented trainer
already publishes through its manager obs channel (no new trainer-side
plumbing).  Control: a slow hysteresis loop — above ``high`` for one
interval, add a worker; below ``low``, retire one; a cooldown between
actions damps flapping.  Actuation is deliberately indirect so the
loop stays trivial to test:

- **scale up** calls ``scale_up(widx)`` — cluster wiring dispatches one
  more dynamic worker task on the engine (``data.service
  .dynamic_serve_task``) and appends ``widx`` to the split board plan,
  which re-partitions ring ownership (workers observe the plan change
  and hand rings over);
- **scale down** calls ``scale_down(widx)`` — wiring removes ``widx``
  from the plan; the worker notices it is planned out, drains, records
  and exits.  The engine task ends normally.

Gauge ``tfos_data_workers`` tracks the active count; telemetry events
``data/scale_up`` / ``data/scale_down`` mark the decisions.
"""

from __future__ import annotations

import logging
import threading
import time

from tensorflowonspark_tpu.utils import metrics_registry, telemetry

logger = logging.getLogger(__name__)

MAX_WORKERS_ENV = "TFOS_DATA_MAX_WORKERS"


class StallAutoscaler:
    """Hysteresis controller over a stall-ratio signal (module
    docstring).  ``read_stall() -> float | None`` returns the feed-wait
    ratio over the last interval (None = no signal yet: do nothing).
    Runs its own daemon thread between :meth:`start` and :meth:`stop`;
    :meth:`step` is the pure decision kernel the tests drive directly.
    """

    def __init__(self, read_stall, scale_up, scale_down,
                 min_workers=1, max_workers=1, high=0.25, low=0.05,
                 interval=2.0, cooldown=10.0):
        if min_workers < 1 or max_workers < min_workers:
            raise ValueError(
                f"need 1 <= min_workers <= max_workers, got "
                f"{min_workers}/{max_workers}")
        if not 0.0 <= low < high:
            raise ValueError(f"need 0 <= low < high, got {low}/{high}")
        self.read_stall = read_stall
        self.scale_up = scale_up
        self.scale_down = scale_down
        self.min_workers = int(min_workers)
        self.max_workers = int(max_workers)
        self.high = float(high)
        self.low = float(low)
        self.interval = float(interval)
        self.cooldown = float(cooldown)
        self.workers = self.min_workers   # current active count
        self._next_widx = self.min_workers
        self._retired = []                # widx stack for scale-down
        self._last_action = 0.0
        self._stop = threading.Event()
        self._thread = None

    # -- decision kernel ---------------------------------------------------

    def step(self, now=None):
        """One control decision; returns "up", "down" or None."""
        now = time.monotonic() if now is None else now
        if now - self._last_action < self.cooldown:
            return None
        stall = self.read_stall()
        if stall is None:
            return None
        if stall > self.high and self.workers < self.max_workers:
            widx = self._next_widx
            self._next_widx += 1
            self.scale_up(widx)
            self.workers += 1
            self._retired.append(widx)
            self._last_action = now
            metrics_registry.set_gauge("tfos_data_workers", self.workers)
            telemetry.event("data/scale_up", worker=widx,
                            workers=self.workers, stall=round(stall, 4))
            logger.info("data autoscaler: stall %.0f%% > %.0f%%, scaled "
                        "up to %d workers (+%d)", stall * 100,
                        self.high * 100, self.workers, widx)
            return "up"
        if stall < self.low and self.workers > self.min_workers:
            # retire the most recently added worker first: the baseline
            # workers were placed by the original dispatch plan
            widx = self._retired.pop()
            self.scale_down(widx)
            self.workers -= 1
            self._last_action = now
            metrics_registry.set_gauge("tfos_data_workers", self.workers)
            telemetry.event("data/scale_down", worker=widx,
                            workers=self.workers, stall=round(stall, 4))
            logger.info("data autoscaler: stall %.1f%% < %.0f%%, scaled "
                        "down to %d workers (-%d)", stall * 100,
                        self.low * 100, self.workers, widx)
            return "down"
        return None

    # -- thread ------------------------------------------------------------

    def start(self):
        metrics_registry.set_gauge("tfos_data_workers", self.workers)
        self._stop.clear()
        self._thread = threading.Thread(
            target=self._run, name="tfos-data-autoscaler", daemon=True)
        self._thread.start()
        return self

    def _run(self):
        while not self._stop.wait(self.interval):
            try:
                self.step()
            except Exception:  # noqa: BLE001 - scaling is best-effort
                logger.exception("data autoscaler: step failed")

    def stop(self):
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5)
            self._thread = None


def obs_stall_reader(snapshots_fn, counter="tfos_feed_wait_seconds_total"):
    """A ``read_stall`` over trainer obs snapshots: per call, the delta
    of the summed trainer feed-wait counters over the delta of wall
    time, normalized per trainer — i.e. the mean fraction of the last
    window each trainer spent waiting on input.  ``snapshots_fn()``
    returns the manager's ``obs_snapshots()`` dict (payloads as
    published by ``obs/publish.py``: {"role": ..., "metrics":
    {name: {"series": [{"value": v}, ...]}}}).
    """
    state = {"t": None, "total": None}

    def _sum_counters():
        total = 0.0
        trainers = 0
        for payload in snapshots_fn().values():
            if not isinstance(payload, dict):
                continue
            if payload.get("role") in ("data", "driver"):
                continue  # only trainer-side wait counts as ingest stall
            ent = (payload.get("metrics") or {}).get(counter)
            if not ent:
                continue
            total += sum(float(s.get("value") or 0.0)
                         for s in ent.get("series", ()))
            trainers += 1
        return total, trainers

    def _read():
        now = time.monotonic()
        try:
            total, trainers = _sum_counters()
        except Exception:  # noqa: BLE001 - manager momentarily unreachable
            return None
        prev_t, prev_total = state["t"], state["total"]
        state["t"], state["total"] = now, total
        if prev_t is None or trainers == 0:
            return None
        dt = now - prev_t
        if dt <= 0:
            return None
        return max(0.0, (total - prev_total) / dt / trainers)

    return _read
