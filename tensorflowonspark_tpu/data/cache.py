"""Shared epoch cache: one materialization of a pipeline epoch, many
readers.

Parity target: the shared-cache tier of the tf.data service design
(PAPERS.md arxiv 2101.12127 §4; reference TensorFlowOnSpark has no
analogue — every Spark feeder re-read its partition).  M consumers of
the same pipeline epoch — dynamic-split data workers serving several
trainers, a train + eval-sidecar pair, sweep arms over one dataset —
pay the decode/transform cost once; everyone else reads blocks from
memory (or the disk spill) at replay speed.

Two pieces:

- :class:`EpochCache` — an *incremental* block store over one pipeline:
  ``block(i)`` drives the single underlying iterator just far enough to
  materialize block ``i`` (filling as it goes), so random-ish access
  from split serving (``blocks_range(k*B, B)``) never recomputes the
  prefix and never needs a complete first pass the way
  ``Pipeline.cache()`` does.  Thread-safe; blocks beyond
  ``memory_bytes`` spill to one pickle file with a per-block offset
  index (seek, not scan).

- a process-wide registry keyed by :meth:`Pipeline.signature` —
  ``shared(pipeline)`` returns THE cache for that pipeline's content,
  so consumers that never see each other's objects still share the
  materialization.  Scope is one process (workers in separate executor
  processes each hold their own copy; a cross-process tier would need a
  shm/disk block store — noted in docs/data.md as future work).

Metrics (CATALOG): ``tfos_data_cache_hits_total`` /
``tfos_data_cache_misses_total`` (registry lookups),
``tfos_data_cache_blocks`` / ``tfos_data_cache_bytes`` (gauges),
``tfos_data_cache_spilled_total``.
"""

from __future__ import annotations

import logging
import os
import pickle
import tempfile
import threading
import weakref

from tensorflowonspark_tpu.utils import metrics_registry

logger = logging.getLogger(__name__)

CACHE_MB_ENV = "TFOS_DATA_CACHE_MB"
CACHE_DIR_ENV = "TFOS_DATA_CACHE_DIR"


def default_memory_bytes():
    """Memory budget for one epoch cache: ``TFOS_DATA_CACHE_MB`` (256)."""
    try:
        return max(1, int(os.environ.get(CACHE_MB_ENV, "256"))) << 20
    except ValueError:
        return 256 << 20


class EpochCache:
    """Incrementally materialized epoch of one pipeline (see module
    docstring).  ``block(i)`` returns block ``i`` or None past the end;
    ``num_blocks`` is known once the end was reached."""

    def __init__(self, pipeline, memory_bytes=None, spill_dir=None):
        self.signature = pipeline.signature()
        self.memory_bytes = (default_memory_bytes()
                             if memory_bytes is None else int(memory_bytes))
        self.spill_dir = spill_dir or os.environ.get(CACHE_DIR_ENV) or None
        self._lock = threading.RLock()
        self._it = pipeline._iter()  # THE single fill iterator
        self._mem = []               # blocks resident in memory
        self._mem_bytes = 0
        self._spill_f = None         # append handle while filling
        self._spill_path = None
        self._spill_offsets = []     # byte offset per spilled block
        self._count = 0              # blocks materialized so far
        self._eof = None             # total block count once known
        self._finalizer = None

    # -- size accounting ---------------------------------------------------

    @staticmethod
    def _block_bytes(block):
        import numpy as np

        total = 0
        for col in block.values():
            if isinstance(col, np.ndarray):
                total += col.nbytes
            else:
                total += sum(len(v) if isinstance(v, (bytes, str)) else 64
                             for v in col)
        return total

    # -- fill --------------------------------------------------------------

    def _fill_to(self, i):
        """Advance the fill iterator until block ``i`` exists or EOF.
        Caller holds the lock."""
        while self._eof is None and self._count <= i:
            block = next(self._it, None)
            if block is None:
                self._eof = self._count
                if self._spill_f is not None:
                    self._spill_f.flush()
                break
            self._store(block)

    def _store(self, block):
        nbytes = self._block_bytes(block)
        if self._spill_f is None \
                and self._mem_bytes + nbytes <= self.memory_bytes:
            self._mem.append(block)
            self._mem_bytes += nbytes
        else:
            if self._spill_f is None:
                fd, self._spill_path = tempfile.mkstemp(
                    prefix="tfos-epoch-cache-", suffix=".pkl",
                    dir=self.spill_dir)
                self._spill_f = os.fdopen(fd, "wb")
                self._finalizer = weakref.finalize(
                    self, _unlink_quiet, self._spill_path)
            self._spill_offsets.append(self._spill_f.tell())
            pickle.dump(block, self._spill_f,
                        protocol=pickle.HIGHEST_PROTOCOL)
            metrics_registry.inc("tfos_data_cache_spilled_total")
        self._count += 1
        if metrics_registry.enabled():
            metrics_registry.set_gauge("tfos_data_cache_blocks",
                                       self._count)
            metrics_registry.set_gauge("tfos_data_cache_bytes",
                                       self._mem_bytes)

    # -- read --------------------------------------------------------------

    def block(self, i):
        """Block ``i`` (filling the cache up to it), or None past EOF."""
        with self._lock:
            if self._eof is None and i >= self._count:
                self._fill_to(i)
            if self._eof is not None and i >= self._eof:
                return None
            if i < len(self._mem):
                return self._mem[i]
            j = i - len(self._mem)
            self._spill_f.flush()
            offset = self._spill_offsets[j]
        # read outside the lock: offsets are append-only and the block
        # at a recorded offset is fully written (flushed above)
        with open(self._spill_path, "rb") as f:
            f.seek(offset)
            return pickle.load(f)

    def blocks_range(self, skip_blocks=0, num_blocks=None):
        """Iterate blocks [skip, skip+num) — the split-serving read."""
        i = skip_blocks
        served = 0
        while num_blocks is None or served < num_blocks:
            block = self.block(i)
            if block is None:
                return
            yield block
            i += 1
            served += 1

    @property
    def num_blocks(self):
        """Total block count, or None while the end is undiscovered."""
        return self._eof

    def close(self):
        with self._lock:
            if self._spill_f is not None:
                try:
                    self._spill_f.close()
                except OSError:
                    pass
                self._spill_f = None
            if self._finalizer is not None:
                self._finalizer()
                self._finalizer = None
            self._mem = []
            self._spill_offsets = []


def _unlink_quiet(path):
    try:
        os.unlink(path)
    except OSError:
        pass


# --------------------------------------------------------------------------
# process-wide registry


_registry = {}
_registry_lock = threading.Lock()


def shared(pipeline, memory_bytes=None, spill_dir=None):
    """THE :class:`EpochCache` for this pipeline's content signature in
    this process — created on first call (a miss), returned to every
    later caller with an equal-signature pipeline (hits)."""
    sig = pipeline.signature()
    with _registry_lock:
        cache = _registry.get(sig)
        if cache is not None:
            metrics_registry.inc("tfos_data_cache_hits_total")
            return cache
        metrics_registry.inc("tfos_data_cache_misses_total")
        cache = EpochCache(pipeline, memory_bytes=memory_bytes,
                           spill_dir=spill_dir)
        _registry[sig] = cache
        return cache


def drop(signature):
    """Evict one cache from the registry (tests / explicit refresh)."""
    with _registry_lock:
        cache = _registry.pop(signature, None)
    if cache is not None:
        cache.close()


def clear():
    """Evict every cache (tests)."""
    with _registry_lock:
        caches = list(_registry.values())
        _registry.clear()
    for c in caches:
        c.close()
