"""Composable input-pipeline subsystem: lazy dataset graph + data service.

Parity target: reference ``tensorflowonspark/TFNode.py:221-329`` (the
DataFeed bridge) plus the tf.data recipes hard-coded in the examples
(``examples/mnist/keras/mnist_spark.py:33-66``: shuffle/batch/prefetch
between DataFeed and model.fit).  The reference delegates all pipeline
*structure* to tf.data and only owns the Spark↔TF hop; here the whole
graph is owned: sources -> transforms -> device staging, with the
columnar chunk wire (``marker.ColumnChunk``) as the zero-copy leaf
format, and a disaggregated data-service mode
(:class:`~tensorflowonspark_tpu.data.service.DataService`) that scales
preprocessing independently of trainers (PAPERS.md: tf.data,
arxiv 2101.12127; tf.data service disaggregation).

Quick start::

    from tensorflowonspark_tpu import data

    pipe = (data.from_tfrecords("/data/train")
                .interleave(cycle_length=4)
                .shuffle(buffer_size=10_000, seed=42)
                .parallel_map(normalize, num_workers=4)
                .batch(256, drop_remainder=True)
                .prefetch(2))
    for block in pipe.blocks():          # host: {name: ndarray[b, ...]}
        ...
    for staged in pipe.to_device():      # device: double-buffered staging
        ...

Service mode (``cluster.run(..., data_workers=N)``)::

    cluster = TFCluster.run(sc, main_fun, args, num_executors,
                            input_mode=InputMode.SPARK, data_workers=2)
    cluster.train(pipe, num_epochs=4)    # N executors run the pipeline

Knobs: ``TFOS_DATA_WORKERS`` (default service worker count),
``TFOS_DATA_PREFETCH`` (default prefetch depth), see docs/data.md.
"""

from tensorflowonspark_tpu.data.pipeline import (  # noqa: F401
    Pipeline,
    block_len,
    block_to_chunk,
    from_arrays,
    from_dataset,
    from_tfrecords,
)
from tensorflowonspark_tpu.data.service import DataService  # noqa: F401

__all__ = [
    "Pipeline",
    "DataService",
    "from_tfrecords",
    "from_dataset",
    "from_arrays",
    "block_to_chunk",
    "block_len",
]
