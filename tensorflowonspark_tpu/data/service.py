"""Disaggregated data service: dedicated executors run the pipeline and
feed trainers over the manager/shm wire.

Parity target: the reference's feeding plane — ``TFSparkNode.train``
(reference ``TFSparkNode.py:448-515``, the per-partition feeder loop with
await-consumption) and its ledgered exactly-once recovery — generalized
from "one Spark partition per feeder task" to "N long-lived data workers
each serving a deterministic shard of a composable pipeline" (the
tf.data-service disaggregation, PAPERS.md arxiv 2101.12127).

Topology: ``cluster.run(..., data_workers=N)`` keeps the trainer cluster
unchanged and launches N *service tasks* on the engine.  Trainers (the
compute jobs of ``cluster_info``) are ranked 0..T-1; worker ``j`` serves
every trainer with ``rank % N == j``.  Each trainer's stream is the
pipeline sharded ``shard(rank, T)`` — the strided exactly-once split —
converted to ``marker.ColumnChunk`` wire chunks and pushed through the
SAME transport handshake as the feeder path (``feed.open_feed_ring``:
shm ring when advertised, manager queue otherwise), with the same
backpressure discipline: a put blocked on a full ring re-checks the
consumer state and heartbeat every second, so a dead trainer fails the
worker fast instead of wedging it.

Exactly-once accounting rides the existing PDONE/PQUERY feed ledger
(``rendezvous.Client.partition_done`` / ``fed_partitions``), keyed per
trainer as ``"<qname>:data:<rank>"``: the stream is cut into **units**
of ``unit_blocks`` consecutive blocks and a unit is recorded done only
after every chunk of it was pushed AND the handoff is consumption-safe.
A killed worker (``TFOS_FAULT_PLAN="data.serve:kill"``; the engine's
``retryable`` supervision respawns the task) queries the ledger and
resumes at its shard cursor — the first un-done unit — by recomputing
and skipping, which the pipeline determinism contract makes exact.  A
unit interrupted mid-push is re-pushed whole (duplicates bounded by one
unit), the same at-least-once-within/exactly-once-across granularity the
reference had per Spark partition.

End-of-feed stays owned by ``cluster.shutdown`` (``node.shutdown``
pushes the terminal ``None``), exactly as in feeder mode.
"""

from __future__ import annotations

import logging
import os
import time

from tensorflowonspark_tpu import rendezvous
from tensorflowonspark_tpu.actors.ledger import (
    NullLedgerClient, resume_cursor,
)
from tensorflowonspark_tpu.obs import publish as obs_publish
from tensorflowonspark_tpu.utils import faults, metrics_registry, telemetry

logger = logging.getLogger(__name__)

WORKERS_ENV = "TFOS_DATA_WORKERS"


def trainer_ranks(cluster_info):
    """[(rank, node_meta)] for the feedable compute nodes of a cluster,
    rank-ordered by executor id (the stable order both the service and
    the shard split key on)."""
    from tensorflowonspark_tpu import node as tfnode

    metas = sorted(
        (m for m in cluster_info if m["job_name"] in tfnode.COMPUTE_JOBS),
        key=lambda m: m["executor_id"])
    return list(enumerate(metas))


def ledger_feed(qname, rank):
    """The per-trainer feed-ledger key (PDONE/PQUERY namespace)."""
    return f"{qname}:data:{rank}"


class DataService:
    """One data worker's serving loop (see module docstring).

    ``run()`` serves every assigned trainer round-robin — one bounded
    push attempt each per round — so a trainer with a full ring never
    starves its siblings, and returns when every assigned stream is
    fully pushed and consumed.
    """

    def __init__(self, pipeline, cluster_info, cluster_meta, qname="input",
                 num_workers=1, worker_index=0, unit_blocks=8,
                 feed_timeout=600):
        if not 0 <= worker_index < num_workers:
            raise ValueError(
                f"need 0 <= worker_index < num_workers, "
                f"got {worker_index}/{num_workers}")
        self.pipeline = pipeline
        self.cluster_info = cluster_info
        self.cluster_meta = cluster_meta
        self.qname = qname
        self.num_workers = int(num_workers)
        self.worker_index = int(worker_index)
        self.unit_blocks = max(1, int(unit_blocks))
        self.feed_timeout = feed_timeout

    # -- per-trainer stream state -----------------------------------------

    class _Stream:
        __slots__ = ("rank", "meta", "mgr", "ring", "queue", "equeue",
                     "chunks", "unit", "unit_off", "pending", "pushed",
                     "done", "client_done")

        def __init__(self, rank, meta):
            self.rank = rank
            self.meta = meta
            self.mgr = None
            self.ring = None
            self.queue = None
            self.equeue = None
            self.chunks = None
            self.unit = 0        # current unit index
            self.unit_off = 0    # blocks pushed within the current unit
            self.pending = None  # chunk that timed out on a full ring
            self.pushed = 0      # records pushed (telemetry)
            self.done = False    # stream exhausted and consumption-safe
            self.client_done = False  # trainer went terminating/stopped

    def _open(self, st, client):
        """Connect to the trainer's manager, resolve the resume cursor
        from the ledger, and open the sharded chunk stream."""
        from tensorflowonspark_tpu import node as tfnode

        st.mgr = tfnode._get_manager(
            self.cluster_info, st.meta["host"], st.meta["executor_id"])
        telemetry.register_with(st.mgr)
        state = str(st.mgr.get("state"))
        if state in ("terminating", "stopped"):
            logger.info("data worker %d: trainer %d state=%s, skipping",
                        self.worker_index, st.rank, state)
            st.client_done = st.done = True
            return
        st.ring = tfnode._open_feed_ring(st.mgr, self.qname)
        st.queue = (None if st.ring is not None
                    else st.mgr.get_queue(self.qname))
        st.equeue = st.mgr.get_queue("error")
        consumed = ()
        try:
            consumed = client.fed_partitions(ledger_feed(self.qname, st.rank))
        except Exception as e:  # noqa: BLE001 - no ledger in standalone use
            logger.debug("data worker: no feed ledger (%s)", e)
        st.unit = resume_cursor(consumed, start=st.unit)
        skip = st.unit * self.unit_blocks
        if skip:
            logger.info(
                "data worker %d: trainer %d resumes at unit %d "
                "(skipping %d blocks already consumed)",
                self.worker_index, st.rank, st.unit, skip)
            telemetry.event("data/serve_resume", trainer=st.rank,
                            unit=st.unit, skip_blocks=skip)
            metrics_registry.inc("tfos_data_resumes_total")
        n_trainers = len(trainer_ranks(self.cluster_info))
        st.chunks = self.pipeline.shard(st.rank, n_trainers).chunks(
            skip_blocks=skip)

    def _push(self, st, chunk):
        """One bounded push attempt; returns True when the chunk landed.
        False means the ring stayed full for the slice — the caller
        round-robins on.  Raises when the trainer errored or died."""
        from tensorflowonspark_tpu import node as tfnode

        if st.ring is not None:
            try:
                st.ring.put(chunk, timeout_ms=1000)
                return True
            except TimeoutError:
                if str(st.mgr.get("state")) == "terminating":
                    st.client_done = True
                    return True  # consumer stopped draining: drop + finish
                tfnode._raise_if_consumer_lost(st.mgr, st.equeue)
                return False
        st.queue.put(chunk, block=True)
        return True

    def _advance(self, st, client):
        """Push up to one unit boundary for one trainer; updates the
        ledger when a unit completes."""
        from tensorflowonspark_tpu import node as tfnode

        if st.pending is None:
            if st.unit_off == 0:
                faults.check("data.serve", worker=self.worker_index,
                             trainer=st.rank, unit=st.unit)
            nxt = next(st.chunks, None)
            if nxt is None:
                # stream exhausted: the final (short) unit is recorded
                # done only after the trainer drained it, so a crash in
                # this window re-pushes instead of losing the tail
                if st.ring is not None:
                    tfnode._await_consumption(
                        st.mgr, lambda: st.ring.qsize_bytes() > 0,
                        self.feed_timeout, poll=0.2)
                if st.unit_off and not st.client_done:
                    self._record_done(st, client)
                st.done = True
                return
            st.pending = nxt
        chunk = st.pending
        if not self._push(st, chunk):
            return  # ring full: retry next round
        st.pending = None
        if st.client_done:
            st.done = True
            return
        st.pushed += len(chunk)
        metrics_registry.inc("tfos_data_records_total", len(chunk),
                             trainer=st.rank)
        st.unit_off += 1
        if st.unit_off >= self.unit_blocks:
            # exactly-once barrier: a unit enters the ledger only after
            # the trainer drained it from the ring.  Recording on push
            # would lose the whole in-flight window when a recovery
            # tears down the trainer manager (ring contents die with
            # it) — the resumed worker would skip data nobody trained
            # on.  Amortized over unit_blocks; raises if the trainer
            # died, which routes into the engine retry path.
            if st.ring is not None:
                tfnode._await_consumption(
                    st.mgr, lambda: st.ring.qsize_bytes() > 0,
                    self.feed_timeout, poll=0.2)
            self._record_done(st, client)
            st.unit += 1
            st.unit_off = 0

    def _record_done(self, st, client):
        try:
            client.partition_done(ledger_feed(self.qname, st.rank), st.unit)
            metrics_registry.inc("tfos_data_units_total")
            # one exactly-once unit delivered; joins the run trace via
            # the TFOS_TRACE_PARENT env the engine task exported
            telemetry.event(telemetry.DATA_UNIT, worker=self.worker_index,
                            trainer=st.rank, unit=st.unit,
                            blocks=st.unit_off or self.unit_blocks)
        except Exception as e:  # noqa: BLE001 - accounting only
            logger.warning("data worker: could not record unit %d for "
                           "trainer %d: %s", st.unit, st.rank, e)

    def _publish_obs(self, assigned):
        """Ship this worker's registry snapshot through the first live
        trainer manager (any reachable manager KV works — the driver's
        ObsServer sweeps every ``obs:*`` key it can see)."""
        if not metrics_registry.enabled():
            return
        for st in assigned:
            if st.mgr is not None:
                if obs_publish.publish_once(
                        st.mgr, f"data-{self.worker_index}", role="data"):
                    return

    def run(self):
        """Serve all assigned trainers to completion; returns a summary
        dict {trainer_rank: records_pushed}."""
        assigned = [DataService._Stream(r, m)
                    for r, m in trainer_ranks(self.cluster_info)
                    if r % self.num_workers == self.worker_index]
        if not assigned:
            logger.info("data worker %d: no trainers assigned (of %d "
                        "workers)", self.worker_index, self.num_workers)
            return {}
        client = None
        try:
            client = rendezvous.Client(self.cluster_meta["server_addr"])
        except Exception as e:  # noqa: BLE001 - standalone use, no ledger
            logger.debug("data worker: rendezvous unavailable (%s)", e)
            client = _NullClient()
        t0 = time.perf_counter()
        next_pub = 0.0
        try:
            for st in assigned:
                self._open(st, client)
            while not all(st.done for st in assigned):
                for st in assigned:
                    if not st.done:
                        self._advance(st, client)
                if (metrics_registry.enabled()
                        and time.monotonic() >= next_pub):
                    next_pub = (time.monotonic()
                                + metrics_registry.interval())
                    self._publish_obs(assigned)
        finally:
            self._publish_obs(assigned)
            for st in assigned:
                if st.ring is not None:
                    try:
                        st.ring.close()
                    except Exception:  # noqa: BLE001 - teardown
                        pass
            try:
                client.close()
            except Exception:  # noqa: BLE001 - teardown
                pass
        summary = {st.rank: st.pushed for st in assigned}
        telemetry.record_span(
            "data/serve", time.perf_counter() - t0,
            worker=self.worker_index,
            trainers=[st.rank for st in assigned],
            records=sum(summary.values()))
        logger.info("data worker %d served %s", self.worker_index, summary)
        return summary


# Ledger stand-in when no rendezvous server is reachable (standalone
# DataService use in tests/benches) — the shared actors copy.
_NullClient = NullLedgerClient


# --------------------------------------------------------------------------
# dynamic FCFS split dispatch (data/splits.py is the control plane)


SPLIT_BOARD_META = "split_board"  # cluster_meta key carrying board coords
DISPATCH_ENV = "TFOS_DATA_DISPATCH"
SHARED_CACHE_ENV = "TFOS_DATA_SHARED_CACHE"
QUEUE_CAP_ENV = "TFOS_DATA_QUEUE_CAP"


def default_split_blocks():
    """Split width in blocks: ``TFOS_DATA_SPLIT_BLOCKS`` (8)."""
    from tensorflowonspark_tpu.data import splits as _splits

    try:
        return max(1, int(os.environ.get(_splits.SPLIT_BLOCKS_ENV, "8")))
    except ValueError:
        return 8


def dispatch_mode(cluster_meta=None):
    """``"dynamic"`` (default) or ``"static"`` — env beats the
    ``data_dispatch`` cluster-meta key beats the default."""
    mode = os.environ.get(DISPATCH_ENV)
    if not mode and cluster_meta:
        mode = cluster_meta.get("data_dispatch")
    mode = (mode or "dynamic").strip().lower()
    if mode not in ("static", "dynamic"):
        raise ValueError(f"unknown {DISPATCH_ENV}={mode!r} "
                         "(want static|dynamic)")
    return mode


class DynamicDataService:
    """One dynamic data worker: claim splits FCFS from the board, serve
    their blocks to the least-loaded owned trainer, record each split in
    the PDONE ledger once its records are consumption-safe.

    Differences from the static :class:`DataService`:

    - **what** to serve comes from the split queue (``data/splits.py``),
      not a rank-strided shard — a slow trainer claims fewer splits
      instead of stretching the epoch;
    - **where** it goes is chosen per split: the least-loaded trainer
      among those this worker owns under the board *plan* (the shm ring
      is single-producer, so trainer rings are partitioned across the
      live workers; the plan changing re-partitions them, which is how
      autoscaling adds serving capacity);
    - exactly-once is per split id on the ``split_feed`` ledger:
      record-on-drain as before, plus chunk-level ``("split", sid, seq,
      n)`` tags so a re-served split's already-consumed prefix is
      dropped by the trainer's DataFeed instead of trained on twice;
    - epoch replay reads the shared :mod:`data.cache` epoch cache
      (decode once, replay from memory/spill) unless
      ``TFOS_DATA_SHARED_CACHE=0``.
    """

    def __init__(self, pipeline, cluster_info, cluster_meta, qname="input",
                 worker_index=0, split_blocks=None, feed_timeout=600,
                 use_cache=None):
        self.pipeline = pipeline
        self.cluster_info = cluster_info
        self.cluster_meta = cluster_meta
        self.qname = qname
        self.worker_index = int(worker_index)
        self.split_blocks = (default_split_blocks() if split_blocks is None
                             else max(1, int(split_blocks)))
        self.feed_timeout = feed_timeout
        if use_cache is None:
            use_cache = os.environ.get(SHARED_CACHE_ENV, "1") != "0"
        self.use_cache = bool(use_cache)
        try:
            self.queue_cap = max(
                1, int(os.environ.get(QUEUE_CAP_ENV, "32")))
        except ValueError:
            self.queue_cap = 32
        self._source = None

    class _Sink:
        __slots__ = ("rank", "meta", "mgr", "ring", "queue", "equeue",
                     "pending", "lost")

        def __init__(self, rank, meta):
            self.rank = rank
            self.meta = meta
            self.mgr = None
            self.ring = None
            self.queue = None
            self.equeue = None
            self.pending = []   # sids pushed, awaiting drain before record
            self.lost = False   # trainer terminating/stopped

    # -- plan / ownership --------------------------------------------------

    def _owned_ranks(self, plan, ranks):
        """Trainer ranks this worker serves under ``plan`` (the board's
        active-worker list): position-strided, so every trainer has
        exactly one producer for its ring."""
        if self.worker_index not in plan:
            return []
        pos = plan.index(self.worker_index)
        return [r for r in ranks if r % len(plan) == pos]

    def _open_sink(self, sink):
        from tensorflowonspark_tpu import node as tfnode

        sink.mgr = tfnode._get_manager(
            self.cluster_info, sink.meta["host"], sink.meta["executor_id"])
        telemetry.register_with(sink.mgr)
        if str(sink.mgr.get("state")) in ("terminating", "stopped"):
            sink.lost = True
            return
        # ring handover: the previous owner's producer flock may linger
        # a beat after a plan change — retry instead of wedging on it
        deadline = time.monotonic() + float(self.feed_timeout)
        while True:
            try:
                sink.ring = tfnode._open_feed_ring(
                    sink.mgr, self.qname, producer_nonblock=True)
                break
            except BlockingIOError:
                if time.monotonic() > deadline:
                    raise
                time.sleep(0.2)
        sink.queue = (None if sink.ring is not None
                      else sink.mgr.get_queue(self.qname))
        sink.equeue = sink.mgr.get_queue("error")

    def _close_sink(self, sink):
        if sink.ring is not None:
            try:
                sink.ring.close()
            except Exception:  # noqa: BLE001 - teardown
                pass
            sink.ring = None

    def _depth(self, sink):
        """Backlog of one sink, for least-loaded target choice (bytes
        for rings, queued-chunk count for manager queues — only ever
        compared within one transport)."""
        try:
            if sink.ring is not None:
                return sink.ring.qsize_bytes()
            return sink.queue.qsize()
        except Exception:  # noqa: BLE001 - depth is best-effort
            return 0

    # -- serving -----------------------------------------------------------

    def _blocks_of(self, sid):
        k = sid[1]
        return self._source.blocks_range(k * self.split_blocks,
                                         self.split_blocks)

    def _push_pinned(self, sink, chunk):
        """Push one chunk to its pinned trainer, waiting out a full
        ring/queue; raises when the trainer errored, returns False when
        it is terminating (stop serving, do not record)."""
        from tensorflowonspark_tpu import node as tfnode

        while True:
            if sink.ring is not None:
                try:
                    sink.ring.put(chunk, timeout_ms=1000)
                    return True
                except TimeoutError:
                    pass
            else:
                if sink.queue.qsize() < self.queue_cap:
                    sink.queue.put(chunk, block=True)
                    return True
                time.sleep(0.05)
            if str(sink.mgr.get("state")) in ("terminating", "stopped"):
                sink.lost = True
                return False
            tfnode._raise_if_consumer_lost(sink.mgr, sink.equeue)

    def _serve_split(self, board, client, sid, sink):
        """Serve every block of ``sid`` to ``sink``; returns the block
        count (0 = split past end of data)."""
        from tensorflowonspark_tpu.data import splits as _splits
        from tensorflowonspark_tpu.data.pipeline import block_to_chunk

        seq = 0
        pushed_records = 0
        for block in self._blocks_of(sid):
            faults.check("data.split_serve", worker=self.worker_index,
                         sid=_splits.sid_str(sid), seq=seq)
            chunk = block_to_chunk(block)
            chunk.meta = ("split", sid, seq, seq + 1)
            if not self._push_pinned(sink, chunk):
                return -1  # trainer shutting down: drop, do not record
            seq += 1
            pushed_records += len(chunk)
        if pushed_records:
            metrics_registry.inc("tfos_data_records_total", pushed_records,
                                 trainer=sink.rank)
        return seq

    def _record_split(self, board, client, sid):
        from tensorflowonspark_tpu.data import splits as _splits

        try:
            client.partition_done(_splits.split_feed(self.qname),
                                  _splits.sid_to_part(sid))
        except Exception as e:  # noqa: BLE001 - accounting only
            logger.warning("data worker %d: could not record split %s: %s",
                           self.worker_index, _splits.sid_str(sid), e)
            return
        board.clear_claim(sid)
        metrics_registry.inc("tfos_data_splits_served_total")
        telemetry.event(telemetry.DATA_UNIT, worker=self.worker_index,
                        split=_splits.sid_str(sid), epoch=sid[0])

    def _flush_drained(self, board, client, sinks, block=False):
        """Record pending splits whose ring the trainer drained.  The
        non-blocking form runs once per loop; the blocking form (stream
        end, ownership handoff) waits out the drain."""
        from tensorflowonspark_tpu import node as tfnode

        for sink in sinks:
            if not sink.pending:
                continue
            if sink.lost:
                sink.pending = []   # trainer gone: provider requeues
                continue
            if sink.ring is not None:
                if block:
                    tfnode._await_consumption(
                        sink.mgr, lambda s=sink: s.ring.qsize_bytes() > 0,
                        self.feed_timeout, poll=0.2)
                elif sink.ring.qsize_bytes() > 0:
                    continue
            for sid in sink.pending:
                self._record_split(board, client, sid)
            sink.pending = []

    # -- main loop ---------------------------------------------------------

    def run(self):
        """Claim-and-serve until the board declares completion (or this
        worker is planned out); returns {"splits": n, "records": n}."""
        from tensorflowonspark_tpu.data import cache as data_cache
        from tensorflowonspark_tpu.data import splits as _splits

        coords = self.cluster_meta[SPLIT_BOARD_META]
        board = _splits.SplitBoard.connect(
            coords["address"], coords["authkey"], self.qname)
        hb = board.start_heartbeat(self.worker_index)
        try:
            client = rendezvous.Client(self.cluster_meta["server_addr"])
        except Exception as e:  # noqa: BLE001 - standalone use, no ledger
            logger.debug("data worker: rendezvous unavailable (%s)", e)
            client = _NullClient()
        self._source = (data_cache.shared(self.pipeline) if self.use_cache
                        else self.pipeline)
        ranks = trainer_ranks(self.cluster_info)
        sinks = {r: DynamicDataService._Sink(r, m) for r, m in ranks}
        all_ranks = sorted(sinks)
        open_ranks = set()
        last_pick = {}
        pick_seq = 0
        served = 0
        t0 = time.perf_counter()
        next_pub = 0.0
        idle_t0 = None
        try:
            while True:
                plan = board.plan() or [self.worker_index]
                if self.worker_index not in plan:
                    # scaled down: hand the rings over cleanly
                    self._flush_drained(board, client,
                                        list(sinks.values()), block=True)
                    logger.info("data worker %d: planned out, exiting",
                                self.worker_index)
                    break
                owned = self._owned_ranks(plan, all_ranks)
                for r in list(open_ranks):
                    if r not in owned:   # disowned: drain, record, release
                        self._flush_drained(board, client, [sinks[r]],
                                            block=True)
                        self._close_sink(sinks[r])
                        open_ranks.discard(r)
                self._flush_drained(board, client,
                                    [sinks[r] for r in open_ranks])
                if board.complete():
                    self._flush_drained(board, client,
                                        [sinks[r] for r in open_ranks],
                                        block=True)
                    break
                sid = board.claim_next(owned)
                if sid is None:
                    if idle_t0 is None:
                        idle_t0 = time.perf_counter()
                    time.sleep(0.05)
                    continue
                if idle_t0 is not None and telemetry.enabled():
                    telemetry.record_span(
                        "data/stage", 0.0, stage="split_queue_wait",
                        wait_ms=round(
                            (time.perf_counter() - idle_t0) * 1e3, 3),
                        records=0, worker=self.worker_index)
                idle_t0 = None
                board.set_claim(sid, self.worker_index)
                metrics_registry.inc("tfos_data_splits_claimed_total")
                faults.check("data.split_claim", worker=self.worker_index,
                             sid=_splits.sid_str(sid))
                done = ()
                try:
                    done = client.fed_partitions(
                        _splits.split_feed(self.qname))
                except Exception:  # noqa: BLE001 - ledgerless harness
                    pass
                if _splits.sid_to_part(sid) in set(done):
                    board.clear_claim(sid)   # raced a recorded re-serve
                    continue
                pin = board.pin_of(sid)
                if pin is not None and pin in owned:
                    rank = pin
                else:
                    live = [r for r in owned if not sinks[r].lost]
                    if not live:
                        break   # nothing left to serve into
                    # least backlogged first; LRU round-robin breaks the
                    # frequent all-drained tie (depth 0 everywhere) so
                    # equal-speed trainers share splits evenly instead
                    # of min() always electing the lowest rank
                    rank = min(live, key=lambda r: (
                        self._depth(sinks[r]) if r in open_ranks else 0,
                        last_pick.get(r, -1)))
                    last_pick[rank] = pick_seq
                    pick_seq += 1
                board.set_pin(sid, rank)   # pin BEFORE the first push
                sink = sinks[rank]
                if rank not in open_ranks:
                    self._open_sink(sink)
                    if sink.lost:
                        continue   # claim goes stale -> provider requeues
                    open_ranks.add(rank)
                n = self._serve_split(board, client, sid, sink)
                if n < 0:
                    continue   # trainer shutting down mid-split
                if n == 0:
                    board.set_eof(sid[1])
                    # an empty split is trivially consumption-safe
                    self._record_split(board, client, sid)
                    continue
                if n < self.split_blocks:
                    board.set_eof(sid[1] + 1)   # short split = the tail
                served += 1
                if sink.ring is not None:
                    sink.pending.append(sid)
                else:
                    # manager-queue path: the queue lives in the trainer
                    # manager, same exposure as the static queue path
                    self._record_split(board, client, sid)
                if (metrics_registry.enabled()
                        and time.monotonic() >= next_pub):
                    next_pub = (time.monotonic()
                                + metrics_registry.interval())
                    self._publish_obs(sinks, open_ranks)
        finally:
            hb.set()
            self._publish_obs(sinks, open_ranks)
            for r in open_ranks:
                self._close_sink(sinks[r])
            try:
                client.close()
            except Exception:  # noqa: BLE001 - teardown
                pass
        telemetry.record_span(
            "data/serve", time.perf_counter() - t0,
            worker=self.worker_index, splits=served, dispatch="dynamic")
        logger.info("data worker %d served %d splits", self.worker_index,
                    served)
        return {"splits": served}

    def _publish_obs(self, sinks, open_ranks):
        if not metrics_registry.enabled():
            return
        for r in sorted(open_ranks):
            mgr = sinks[r].mgr
            if mgr is not None and obs_publish.publish_once(
                    mgr, f"data-{self.worker_index}", role="data"):
                return


def dynamic_serve_task(pipeline, cluster_info, cluster_meta, qname="input",
                       split_blocks=None, feed_timeout=600):
    """Engine closure running one dynamic data worker per partition —
    the FCFS counterpart of :func:`serve_task`.  Also used by the
    autoscaler to launch additional workers one at a time."""

    def _serve(iterator):
        items = list(iterator)
        if items:
            widx = int(items[0])
        else:
            widx = int(os.environ.get("TFOS_PARTITION_INDEX", "0"))
        svc = DynamicDataService(
            pipeline, cluster_info, cluster_meta, qname=qname,
            worker_index=widx, split_blocks=split_blocks,
            feed_timeout=feed_timeout)
        svc.run()

    return _serve


def default_workers():
    """Worker count default: ``TFOS_DATA_WORKERS`` (1)."""
    try:
        return max(1, int(os.environ.get(WORKERS_ENV, "1")))
    except ValueError:
        return 1


def serve_task(pipeline, cluster_info, cluster_meta, qname="input",
               num_workers=1, unit_blocks=8, feed_timeout=600):
    """Engine closure running one data worker per partition
    (``engine.parallelize(range(N), N).foreach_partition(...)``).  The
    worker index comes from the partition's element (falling back to the
    engine-exported ``TFOS_PARTITION_INDEX`` for respawned retries)."""

    def _serve(iterator):
        items = list(iterator)
        if items:
            widx = int(items[0])
        else:
            widx = int(os.environ.get("TFOS_PARTITION_INDEX", "0"))
        svc = DataService(
            pipeline, cluster_info, cluster_meta, qname=qname,
            num_workers=num_workers, worker_index=widx,
            unit_blocks=unit_blocks, feed_timeout=feed_timeout)
        svc.run()

    return _serve
