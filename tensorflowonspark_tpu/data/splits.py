"""Dynamic FCFS split dispatch: the control plane of the data service.

Parity target: the split-provider/dispatcher design of the tf.data
service paper (PAPERS.md arxiv 2101.12127 §3.2 first-come-first-served
split provisioning); the reference TensorFlowOnSpark has no analogue —
its feeding plane statically binds one Spark partition per feeder task
(TFSparkNode.py:448-515).  Here the binding is dynamic: the pipeline's
block stream is cut into fixed-width **splits** and data workers claim
them first-come-first-served, so fast trainers consume more splits and
a slowed trainer no longer multiplies epoch wall-clock
(``data.service.DynamicDataService`` is the data plane).

Split identity: ``sid = (epoch, k)`` — split ``k`` of one deterministic
epoch iteration covers blocks ``[k*B, (k+1)*B)`` of the *base* pipeline
(``Pipeline.blocks_range``), identical for every epoch by the
determinism contract, so epochs are pure id arithmetic and never need
``repeat()``.  The per-epoch split count is discovered, not declared: a
worker that claims a split past the data sets the ``eof`` mark.

Coordination lives in two places, matching the existing recovery split:

- **manager KV + queues** (ephemeral, driver-side — the
  ``ActorSystem``'s manager): the ordered split queue (a manager queue
  — ``get()`` is atomic, which IS the FCFS claim), per-split claim
  marks, per-split trainer pins, the eof/complete marks and the worker
  plan.  All of it is reconstructable, so losing the manager only costs
  re-posting work.
- **rendezvous PDONE/PQUERY ledger** (durable across cluster recovery):
  a split id enters the ledger only when its records are
  consumption-safe (``record-on-drain``), exactly like the static
  service's unit ledger.  The provider requeues claimed-but-undone
  splits whose claimant stopped heartbeating — a SIGKILLed worker's
  splits return to the queue; re-serves are pinned to the originally
  targeted trainer whose ``DataFeed`` drops the already-consumed prefix
  (``ColumnChunk.meta`` split tags), closing the duplicate window.

:class:`SplitProvider` is a supervised actor (``actors.runtime``): its
durable state is the board + ledger, so a respawned incarnation resumes
from the posting cursor and re-sweeps claims.
"""

from __future__ import annotations

import logging
import queue as _queue
import time

from tensorflowonspark_tpu import manager as tfmanager
from tensorflowonspark_tpu.actors import liveness
from tensorflowonspark_tpu.actors.runtime import Actor
from tensorflowonspark_tpu.utils import metrics_registry, telemetry

logger = logging.getLogger(__name__)

SPLIT_BLOCKS_ENV = "TFOS_DATA_SPLIT_BLOCKS"
WINDOW_ENV = "TFOS_DATA_SPLIT_WINDOW"


def split_feed(qname):
    """The split ledger's PDONE/PQUERY namespace for one feed queue."""
    return f"{qname}:splits"


def sid_str(sid):
    return f"{sid[0]}.{sid[1]}"


def sid_to_part(sid):
    """Pack a sid into the single int the PDONE ledger stores
    (``rendezvous.Client.partition_done`` coerces parts to int)."""
    return (int(sid[0]) << 32) | int(sid[1])


def part_to_sid(part):
    part = int(part)
    return (part >> 32, part & 0xFFFFFFFF)


class SplitBoard:
    """The manager-KV face of split dispatch — queue handles and typed
    accessors shared by the provider, the workers and the tests.  One
    board per (manager, qname)."""

    def __init__(self, mgr, qname):
        self.mgr = mgr
        self.qname = qname
        self._q = mgr.get_queue(f"splits:{qname}")
        self._pinq = {}  # rank -> pinned-requeue queue handle

    @classmethod
    def connect(cls, address, authkey, qname):
        """Worker-side board over a remote manager."""
        return cls(tfmanager.connect(tuple(address), authkey), qname)

    # -- FCFS queue --------------------------------------------------------

    def post(self, sid):
        self._q.put(sid)

    def claim_next(self, ranks=()):
        """One non-blocking FCFS claim attempt: pinned requeues for the
        given trainer ``ranks`` first (recovery traffic beats new work),
        then the shared queue.  Returns a sid or None."""
        for rank in ranks:
            try:
                sid = self.pin_queue(rank).get(block=False)
            except _queue.Empty:
                continue
            self.pin_queue(rank).task_done()
            return sid
        try:
            sid = self._q.get(block=False)
        except _queue.Empty:
            return None
        self._q.task_done()
        return sid

    def queue_depth(self):
        try:
            return self._q.qsize()
        except Exception:  # noqa: BLE001 - depth is best-effort
            return 0

    def pin_queue(self, rank):
        q = self._pinq.get(rank)
        if q is None:
            q = self._pinq[rank] = self.mgr.get_queue(
                f"splits:{self.qname}:pin:{rank}")
        return q

    # -- claims / pins -----------------------------------------------------

    def set_claim(self, sid, worker):
        self.mgr.set(f"splits:{self.qname}:claim:{sid_str(sid)}",
                     (worker, time.time()))

    def claim_of(self, sid):
        return self.mgr.get(f"splits:{self.qname}:claim:{sid_str(sid)}")

    def clear_claim(self, sid):
        self.mgr.set(f"splits:{self.qname}:claim:{sid_str(sid)}", None)

    def set_pin(self, sid, rank):
        self.mgr.set(f"splits:{self.qname}:pin:{sid_str(sid)}", rank)

    def pin_of(self, sid):
        return self.mgr.get(f"splits:{self.qname}:pin:{sid_str(sid)}")

    # -- end-of-data / completion -----------------------------------------

    def eof(self):
        """Per-epoch split count once discovered, else None."""
        return self.mgr.get(f"splits:{self.qname}:eof")

    def set_eof(self, k):
        """Record that epoch block space ends at split ``k`` (min wins:
        concurrent discoverers can only tighten the bound)."""
        cur = self.eof()
        if cur is None or k < cur:
            self.mgr.set(f"splits:{self.qname}:eof", int(k))

    def complete(self):
        return bool(self.mgr.get(f"splits:{self.qname}:complete"))

    def set_complete(self):
        self.mgr.set(f"splits:{self.qname}:complete", True)

    # -- worker plan / liveness -------------------------------------------

    def plan(self):
        """Active worker indexes (ownership order).  Empty until the
        driver publishes one."""
        return list(self.mgr.get(f"splits:{self.qname}:plan") or ())

    def set_plan(self, workers):
        self.mgr.set(f"splits:{self.qname}:plan",
                     tuple(int(w) for w in workers))

    def beat_key(self, worker):
        return f"dataw:{self.qname}:{worker}"

    def worker_beat_age(self, worker):
        return liveness.beat_age(self.mgr, self.beat_key(worker))

    def start_heartbeat(self, worker):
        return liveness.start_heartbeat(self.mgr, self.beat_key(worker))


class SplitProvider(Actor):
    """Driver-side split provider (supervised actor): posts split ids in
    a bounded window ahead of consumption, sweeps stale claims back onto
    the queue, and declares completion (see module docstring).

    The posting cursor lives in the actor KV (``ctx.kv_set``) so a
    respawned incarnation resumes instead of re-posting; a fresh manager
    (cluster-level recovery) starts the cursor over, and the done-set
    check skips every split the ledger already has.
    """

    def __init__(self, qname, server_addr=None, num_epochs=1,
                 window=16, stale_secs=None):
        self.qname = qname
        self.server_addr = server_addr
        self.num_epochs = max(1, int(num_epochs))
        self.window = max(1, int(window))
        self.stale_secs = stale_secs

    def on_start(self, ctx):
        from tensorflowonspark_tpu import rendezvous
        from tensorflowonspark_tpu.actors.ledger import NullLedgerClient

        self._board = SplitBoard(ctx.mgr, self.qname)
        if self.stale_secs is None:
            self.stale_secs = tfmanager.stale_after()
        self._client = None
        if self.server_addr is not None:
            try:
                self._client = rendezvous.Client(self.server_addr)
            except Exception as e:  # noqa: BLE001 - ledgerless harnesses
                logger.debug("split provider: rendezvous unavailable "
                             "(%s)", e)
        if self._client is None:
            self._client = NullLedgerClient()
        cursor = ctx.kv_get("split_cursor") or (0, 0)
        self._epoch, self._k = int(cursor[0]), int(cursor[1])
        self._outstanding = set(ctx.kv_get("split_outstanding") or ())
        self._exhausted = False
        telemetry.event("data/split_provider_start", qname=self.qname,
                        epoch=self._epoch, k=self._k,
                        outstanding=len(self._outstanding))

    def on_message(self, ctx, kind, payload):
        if kind == "status":
            return {"cursor": (self._epoch, self._k),
                    "outstanding": len(self._outstanding),
                    "eof": self._board.eof(),
                    "complete": self._board.complete(),
                    "exhausted": self._exhausted}
        raise NotImplementedError(f"unhandled message kind {kind!r}")

    def on_tick(self, ctx):
        board = self._board
        if board.complete():
            return
        done = self._done_set()
        for sid in list(self._outstanding):
            if sid in done:
                self._outstanding.discard(sid)
                board.clear_claim(sid)
        self._sweep(board, done)
        self._top_up(board, done)
        ctx.kv_set("split_cursor", (self._epoch, self._k))
        ctx.kv_set("split_outstanding", tuple(self._outstanding))
        if metrics_registry.enabled():
            metrics_registry.set_gauge("tfos_data_split_queue_depth",
                                       board.queue_depth())
        if self._exhausted and not self._outstanding:
            board.set_complete()
            telemetry.event("data/splits_complete", qname=self.qname,
                            eof=board.eof(), epochs=self.num_epochs)

    def _done_set(self):
        try:
            parts = self._client.fed_partitions(split_feed(self.qname))
        except Exception:  # noqa: BLE001 - ledger momentarily unreachable
            return set()
        return {part_to_sid(p) for p in parts}

    def _sweep(self, board, done):
        """Requeue claimed-but-undone splits of dead claimants: claim
        older than ``stale_secs`` AND the claimant's heartbeat stale (or
        never seen).  Pinned splits go to the pin queue so the owner of
        the originally targeted trainer re-serves them."""
        now = time.time()
        for sid in list(self._outstanding):
            claim = board.claim_of(sid)
            if claim is None:
                continue  # still queued, or already swept
            worker, t_claim = claim
            if now - t_claim <= self.stale_secs:
                continue
            age = board.worker_beat_age(worker)
            if age is not None and age <= self.stale_secs:
                continue  # claimant alive, just slow
            board.clear_claim(sid)
            pin = board.pin_of(sid)
            if pin is not None:
                board.pin_queue(pin).put(sid)
            else:
                board.post(sid)
            metrics_registry.inc("tfos_data_splits_requeued_total")
            telemetry.event("data/split_requeued", sid=sid_str(sid),
                            worker=worker, pin=pin)
            logger.info("split provider: requeued %s (worker %s dead, "
                        "pin=%s)", sid_str(sid), worker, pin)

    def _top_up(self, board, done):
        """Keep up to ``window`` splits outstanding, advancing epochs as
        the per-epoch split count becomes known.  Splits the durable
        ledger already has (a previous incarnation served them) are
        skipped, never re-posted — the cross-recovery exactly-once
        half."""
        eof = board.eof()
        posted = 0
        while len(self._outstanding) < self.window and not self._exhausted:
            if eof is not None and self._k >= eof:
                if self._epoch + 1 >= self.num_epochs:
                    self._exhausted = True
                    break
                self._epoch += 1
                self._k = 0
                if eof == 0:  # empty dataset: nothing to post, any epoch
                    self._exhausted = True
                    break
            sid = (self._epoch, self._k)
            self._k += 1
            if sid in done:
                continue  # already consumed in a previous incarnation
            board.post(sid)
            self._outstanding.add(sid)
            metrics_registry.inc("tfos_data_splits_posted_total")
            posted += 1
        if posted:
            telemetry.event("data/splits_posted", count=posted,
                            epoch=self._epoch, next_k=self._k)
