"""Lazy, composable dataset-pipeline graph over columnar blocks.

Parity target: the pipeline *structure* the reference delegated to
tf.data — shuffle/batch/prefetch between DataFeed and the model
(reference ``examples/mnist/keras/mnist_spark.py:33-66``), TFRecord
ingestion (reference ``tensorflowonspark/dfutil.py:44-81``), and the
record hop itself (``TFNode.py:221-329``).  The clean-room redesign owns
the whole graph: a :class:`Pipeline` is an immutable node DAG whose
elements are **columnar blocks** — ``{name: ndarray[b, ...] | list}`` —
the exact shape :func:`dfutil.iter_tfrecords_columnar` yields, so record
streams stay dense end-to-end and convert to the zero-copy wire format
(``marker.ColumnChunk``) without a per-record python loop.

Stages (all lazy; nothing runs until a terminal is iterated):

==================  =====================================================
``map``             block-wise transform (vectorize over the block)
``parallel_map``    same, in a spawn-safe process pool (ordered/unordered)
``batch``           re-chunk to exactly-N-record blocks
``shuffle``         seeded windowed record shuffle (deterministic)
``interleave``      round-robin blocks across source shard files
``cache``           memory cache with spill-to-disk overflow
``prefetch``        background-thread block staging (host side)
``repeat``          epoch repetition
``shard``           strided exactly-once record split across consumers
==================  =====================================================

Terminals: :meth:`Pipeline.blocks` (host blocks),
:meth:`Pipeline.chunks` (``ColumnChunk`` wire stream — what the data
service pushes), :meth:`Pipeline.to_device` (double-buffered device
staging via ``infeed.prefetch_to_device``).

Determinism contract (the fault-tolerant-resume gate, tested in
``tests/test_data.py``): a pipeline with seeded ``shuffle`` produces an
identical block sequence on every fresh iteration, so (a) two same-seed
runs see identical batch order, (b) ``shard(i, n)`` consumers partition
every record exactly once per epoch, and (c) a restarted consumer can
resume mid-stream by *recomputing* and skipping ``skip_blocks`` blocks
(see ``data.service``'s cursor-based restart).

Per-stage telemetry (``TFOS_TELEMETRY_DIR``): every instrumented stage
emits one ``data/stage`` span per produced block with ``stage``,
``wait_ms`` (time blocked in its upstream) and ``records`` attrs —
``scripts/trace_merge.py``'s ``-- data --`` section turns these into
per-stage stall percentiles.
"""

from __future__ import annotations

import hashlib
import itertools
import logging
import os
import pickle
import queue as _queue
import tempfile
import threading
import time
import weakref

from tensorflowonspark_tpu.utils import telemetry

logger = logging.getLogger(__name__)

PREFETCH_ENV = "TFOS_DATA_PREFETCH"
CHUNKSIZE_ENV = "TFOS_DATA_CHUNKSIZE"

_tls = threading.local()

# Serializes the PYTHONPATH save/clear/restore around spawn-pool
# construction (_ParallelMap): two pipelines building pools concurrently
# would otherwise race the env mutation and could leak an empty
# PYTHONPATH into one of them permanently.
_SPAWN_ENV_LOCK = threading.Lock()


def _pool_chunksize():
    """``imap`` chunksize for parallel_map pools: ``TFOS_DATA_CHUNKSIZE``
    (default 1).  chunksize=1 is one IPC round-trip per block — pure
    overhead for small blocks; raising it batches blocks per worker
    dispatch at the cost of coarser load balance."""
    try:
        return max(1, int(os.environ.get(CHUNKSIZE_ENV, "1")))
    except ValueError:
        return 1


# --------------------------------------------------------------------------
# block helpers: a block is {name: ndarray[b, ...] | list-of-objects}


def block_len(block):
    """Record count of a columnar block."""
    return len(next(iter(block.values())))


def _slice_block(block, lo, hi):
    return {name: col[lo:hi] for name, col in block.items()}


def _take_rows(block, idx):
    """Row subset/permutation ``idx`` (ndarray of indices) of a block."""
    import numpy as np

    out = {}
    for name, col in block.items():
        if isinstance(col, np.ndarray):
            out[name] = col[idx]
        else:
            out[name] = [col[i] for i in idx]
    return out


def _concat_columns(parts):
    import numpy as np

    if isinstance(parts[0], np.ndarray):
        return parts[0] if len(parts) == 1 else np.concatenate(parts)
    out = []
    for p in parts:
        out.extend(p)
    return out


def _concat_blocks(blocks):
    if len(blocks) == 1:
        return blocks[0]
    names = blocks[0].keys()
    return {n: _concat_columns([b[n] for b in blocks]) for n in names}


def _rows_to_block(rows):
    """List of rows -> one columnar block (ndarray where dense).

    Rows are dicts (``{name: value}``) or positional tuples — the
    feeder-RDD convention of ``(features, label)`` — which get synthetic
    ``c000..`` names so positional order survives ``block_to_chunk``'s
    sorted-by-name wire order."""
    import numpy as np

    first_row = rows[0]
    if not isinstance(first_row, dict):
        if not isinstance(first_row, (tuple, list)):
            rows = [(r,) for r in rows]
        rows = [{f"c{i:03d}": v for i, v in enumerate(r)} for r in rows]
    names = list(rows[0].keys())
    block = {}
    for n in names:
        vals = [r[n] for r in rows]
        first = vals[0]
        if isinstance(first, (bytes, str)):
            block[n] = vals
        else:
            try:
                block[n] = np.asarray(vals)
            except Exception:  # noqa: BLE001 - ragged: keep the list column
                block[n] = vals
    return block


def block_to_chunk(block):
    """Columnar block -> ``marker.ColumnChunk`` wire chunk, zero-copy.

    Field order is sorted by name — the same convention
    ``DataFeed.input_tensors`` uses (``sorted(input_mapping.values())``),
    so service-pushed chunks slice straight into
    ``next_batch_columns``.  n-D columns (images ``[b, H, W, C]``) are
    flattened to ``[b, H*W*C]`` reshape views with the trailing shape in
    ``ColumnChunk.shapes`` (the wire shape contract of
    ``feed._sliced_column``); object columns (bytes) ride as lists.
    """
    import numpy as np

    from tensorflowonspark_tpu import marker
    from tensorflowonspark_tpu.recordio import marshal

    spec = []
    columns = []
    shapes = []
    for name in sorted(block):
        col = block[name]
        if isinstance(col, np.ndarray):
            code = marshal._ndarray_code(col.dtype)
            if col.ndim == 1:
                spec.append((code, 0))
                shapes.append(None)
            elif col.ndim == 2:
                spec.append((code, col.shape[1]))
                shapes.append(None)
            else:
                trail = col.shape[1:]
                col = col.reshape(len(col), -1)
                spec.append((code, col.shape[1]))
                shapes.append(trail)
        else:
            spec.append(("O", 0))
            shapes.append(None)
        columns.append(col)
    shp = tuple(shapes) if any(s is not None for s in shapes) else None
    return marker.ColumnChunk(spec, columns, shapes=shp)


# --------------------------------------------------------------------------
# stage instrumentation: nested self/wait decomposition


def _instrumented(name, gen, total_is_wait=False):
    """Wrap a stage generator with per-block ``data/stage`` spans.

    Accounting is a thread-local span stack: the wall time of one
    ``next()`` on THIS stage, minus the wall time its direct upstream
    ``next()`` calls recorded into our stack slot, is this stage's
    *self* (produce) time; the remainder is *wait*.  Cardinality changes
    (batch consuming k upstream blocks per emitted block) fall out
    naturally because every upstream pull lands in the same slot.

    ``total_is_wait``: stages whose work happens elsewhere (prefetch's
    background thread) report their whole blocked time as wait.
    """
    it = iter(gen)
    while True:
        stack = getattr(_tls, "stack", None)
        if stack is None:
            stack = _tls.stack = []
        stack.append(0.0)
        t0 = time.perf_counter()
        try:
            block = next(it)
            alive = True
        except StopIteration:
            alive = False
        total = time.perf_counter() - t0
        child = stack.pop()
        if stack:
            stack[-1] += total
        if not alive:
            return
        if telemetry.enabled():
            wait = total if total_is_wait else min(child, total)
            telemetry.record_span(
                "data/stage", max(total - wait, 0.0), stage=name,
                wait_ms=round(wait * 1e3, 3), records=block_len(block))
        yield block


# --------------------------------------------------------------------------
# parallel_map function shipping (spawn-safe)


class _CloudFn:
    """Carrier for a callable plain pickle rejects (lambda/closure):
    serialized with cloudpickle when available, rebuilt lazily in the
    pool child."""

    __slots__ = ("payload", "_fn")

    def __init__(self, payload):
        self.payload = payload
        self._fn = None

    def __getstate__(self):
        return self.payload

    def __setstate__(self, payload):
        self.payload = payload
        self._fn = None

    def __call__(self, block):
        if self._fn is None:
            import pickle as _p

            self._fn = _p.loads(self.payload)
        return self._fn(block)


def _shippable(fn):
    """Return a picklable callable equivalent to ``fn`` (spawn pools
    re-import and unpickle in the child)."""
    try:
        pickle.dumps(fn)
        return fn
    except Exception:  # noqa: BLE001 - try cloudpickle for closures
        try:
            import cloudpickle

            return _CloudFn(cloudpickle.dumps(fn))
        except Exception as e:  # noqa: BLE001
            raise ValueError(
                "parallel_map fn must be picklable (module-level) for the "
                f"spawn pool; pickling failed and cloudpickle is "
                f"unavailable: {e}") from e


# --------------------------------------------------------------------------
# the graph


class Pipeline:
    """One node of the lazy pipeline DAG.  Construct via the module
    sources (:func:`from_tfrecords` / :func:`from_arrays` /
    :func:`from_dataset`) and chain transforms; every transform returns
    a NEW node (nodes are immutable and reusable)."""

    stage_name = "pipeline"
    _total_is_wait = False

    def __init__(self, parent=None):
        self.parent = parent

    # -- structure ---------------------------------------------------------

    def _blocks(self):
        raise NotImplementedError

    def _iter(self):
        """Instrumented block iterator for THIS node (internal)."""
        if not telemetry.enabled():
            return self._blocks()
        return _instrumented(self.stage_name, self._blocks(),
                             self._total_is_wait)

    def _substreams(self):
        """Per-shard sub-iterators for interleave; sources that have a
        natural file split override this."""
        raise ValueError(
            f"interleave() needs a multi-shard source upstream; "
            f"{type(self).__name__} has no sub-streams")

    def _skip_fast(self, skip_blocks):
        """Iterator starting at block ``skip_blocks`` WITHOUT recomputing
        the prefix, or None when this node cannot (the generic path then
        recomputes and discards).  Sources with O(1) random block access
        (in-memory arrays) and completed caches override this — the
        split-aware fast path dynamic split dispatch leans on so serving
        split k is O(split), not O(k) (docs/data.md)."""
        return None

    def _skip_iter(self, skip_blocks):
        """Block iterator from ``skip_blocks`` on: the fast path when the
        node supports it, recompute-and-discard otherwise."""
        if skip_blocks:
            fast = self._skip_fast(skip_blocks)
            if fast is not None:
                if not telemetry.enabled():
                    return fast
                return _instrumented(self.stage_name, fast,
                                     self._total_is_wait)
        it = self._iter()
        for _ in range(skip_blocks):
            if next(it, None) is None:
                return iter(())
        return it

    # -- identity ----------------------------------------------------------

    def signature(self):
        """Stable structural digest of the pipeline graph — stage chain +
        content-relevant parameters — used to key the shared epoch cache
        (``data.cache``): two pipeline objects with the same signature
        produce the same block sequence (determinism contract), so M
        consumers can share one materialized epoch.  Parameters that do
        not change the produced records (pool width, prefetch depth) are
        excluded."""
        return hashlib.sha1(
            "|".join(self._sig_parts()).encode()).hexdigest()[:16]

    def _sig_parts(self):
        parts = [] if self.parent is None else self.parent._sig_parts()
        parts.append(self._sig())
        return parts

    def _sig(self):
        return self.stage_name

    # -- transforms --------------------------------------------------------

    def map(self, fn):
        """Block-wise transform: ``fn({name: column}) -> block``.  The
        unit is a BLOCK, not a record — write ``fn`` vectorized (the
        tf.data ``map`` analogue at batch granularity)."""
        return _Map(self, fn)

    def parallel_map(self, fn, num_workers=2, ordered=True):
        """``map`` in a spawn-context process pool.  ``ordered=False``
        trades block order for completion order (throughput when block
        costs vary).  ``fn`` must be importable in a spawn child
        (module-level; closures need cloudpickle)."""
        return _ParallelMap(self, fn, num_workers, ordered)

    def batch(self, batch_size, drop_remainder=False):
        """Re-chunk the record stream into exactly-``batch_size`` blocks
        (a short final block is dropped with ``drop_remainder=True`` —
        SPMD steps want full shapes, cf. ``dfutil.iter_tfrecords_columnar``)."""
        if batch_size < 1:
            raise ValueError(f"batch_size must be >= 1, got {batch_size}")
        return _Batch(self, int(batch_size), bool(drop_remainder))

    def shuffle(self, buffer_size, seed=0):
        """Seeded windowed record shuffle: fill a ``buffer_size``-record
        window, emit one full permutation of it, repeat; the tail window
        is permuted too, so every record is emitted exactly once.  A
        buffer at least the dataset size is a global shuffle.  Fresh
        iterations replay the identical order (determinism contract)."""
        if buffer_size < 1:
            raise ValueError(f"buffer_size must be >= 1, got {buffer_size}")
        return _Shuffle(self, int(buffer_size), int(seed))

    def interleave(self, cycle_length=2):
        """Round-robin blocks from ``cycle_length`` source shard files at
        a time (the tf.data ``interleave`` analogue over ``part-*``
        files) — hides per-shard open/decode latency behind the other
        open shards.  Requires a multi-shard source as the direct
        upstream."""
        if cycle_length < 1:
            raise ValueError(f"cycle_length must be >= 1, got {cycle_length}")
        return _Interleave(self, int(cycle_length))

    def cache(self, spill_dir=None, memory_bytes=256 << 20):
        """Materialize the upstream once; later iterations replay.  The
        first ``memory_bytes`` of blocks stay in memory, overflow spills
        to one pickle file under ``spill_dir`` (default: tempdir).  The
        cache only becomes authoritative after a COMPLETE first pass —
        an abandoned pass is discarded."""
        return _Cache(self, spill_dir, int(memory_bytes))

    def prefetch(self, depth=2):
        """Stage up to ``depth`` upstream blocks ahead on a background
        thread (host-side; ``to_device`` adds the device half)."""
        if depth < 1:
            raise ValueError(f"depth must be >= 1, got {depth}")
        return _Prefetch(self, int(depth))

    def repeat(self, count=None):
        """Repeat the upstream ``count`` times (``None`` = forever).
        Each epoch is a fresh deterministic iteration of the graph."""
        if count is not None and count < 1:
            raise ValueError(f"repeat count must be >= 1, got {count}")
        return _Repeat(self, count)

    def shard(self, index, count):
        """Keep records whose GLOBAL record index ``% count == index`` —
        the exactly-once split for ``count`` consumers (every record
        goes to exactly one shard; deterministic, so it composes with
        seeded ``shuffle`` for fault-tolerant resume)."""
        if not 0 <= index < count:
            raise ValueError(f"need 0 <= index < count, got {index}/{count}")
        return _Shard(self, int(index), int(count))

    # -- terminals ---------------------------------------------------------

    def blocks(self, skip_blocks=0):
        """Iterate host blocks.  ``skip_blocks``: resume support — the
        first N blocks are skipped via the node's fast path when it has
        one (arrays, completed caches), else recomputed and discarded
        (cheap relative to re-feeding a trainer; the determinism
        contract makes the skip land exactly where the previous consumer
        stopped)."""
        return self._skip_iter(skip_blocks)

    def blocks_range(self, skip_blocks=0, num_blocks=None):
        """Iterate at most ``num_blocks`` host blocks starting at block
        ``skip_blocks`` — the split-serving terminal of dynamic split
        dispatch (``data.splits``): split k of width B is
        ``blocks_range(k * B, B)``.  ``num_blocks=None`` reads to the
        end."""
        it = self._skip_iter(skip_blocks)
        if num_blocks is None:
            return it
        return itertools.islice(it, num_blocks)

    def chunks(self, skip_blocks=0):
        """Iterate ``marker.ColumnChunk`` wire chunks (one per block) —
        what the feed ring and data service transport."""
        return (block_to_chunk(b) for b in self.blocks(skip_blocks))

    def to_device(self, depth=None, placement=None, collate=None):
        """Terminate into the existing double-buffered device staging
        (``infeed.prefetch_to_device``): blocks are placed ``depth``
        ahead while the device consumes.  ``collate(block) -> pytree``
        (default: the block dict as-is); ``placement`` as in infeed.
        Default ``depth``: ``TFOS_DATA_PREFETCH`` (2)."""
        from tensorflowonspark_tpu import infeed

        if depth is None:
            depth = int(os.environ.get(PREFETCH_ENV, "2"))
        it = self.blocks()
        if collate is not None:
            it = map(collate, it)
        return infeed.prefetch_to_device(it, depth=depth,
                                         placement=placement)


def _fn_digest(fn):
    """Deterministic content digest of a stage callable for
    ``signature()``: the pickle (or cloudpickle) bytes when obtainable,
    else the qualified name — per-process identity as a last resort."""
    payload = getattr(fn, "payload", None)  # _CloudFn carrier
    if payload is None:
        try:
            payload = pickle.dumps(fn, protocol=4)
        except Exception:  # noqa: BLE001 - closures without cloudpickle
            try:
                import cloudpickle

                payload = cloudpickle.dumps(fn)
            except Exception:  # noqa: BLE001
                return f"{getattr(fn, '__qualname__', repr(fn))}@{id(fn)}"
    return hashlib.sha1(payload).hexdigest()[:12]


class _Map(Pipeline):
    stage_name = "map"

    def __init__(self, parent, fn):
        super().__init__(parent)
        self.fn = fn

    def _blocks(self):
        fn = self.fn
        for block in self.parent._iter():
            yield fn(block)

    def _skip_fast(self, skip_blocks):
        # 1:1 block-wise: a skippable upstream makes this node skippable
        fast = self.parent._skip_fast(skip_blocks)
        if fast is None:
            return None
        return map(self.fn, fast)

    def _sig(self):
        return f"map:{_fn_digest(self.fn)}"


class _ParallelMap(Pipeline):
    stage_name = "parallel_map"

    def __init__(self, parent, fn, num_workers, ordered):
        super().__init__(parent)
        if num_workers < 1:
            raise ValueError(f"num_workers must be >= 1, got {num_workers}")
        self.fn = _shippable(fn)
        self.num_workers = int(num_workers)
        self.ordered = bool(ordered)

    def _blocks(self):
        import multiprocessing as mp

        ctx = mp.get_context("spawn")
        # Children must not run the axon site hook (it dials the TPU pool
        # at interpreter start and HANGS when the tunnel is down): clear
        # PYTHONPATH around the spawn — the spawn protocol ships the
        # parent's sys.path explicitly, so package imports still resolve.
        # Under _SPAWN_ENV_LOCK: the mutation is process-global.
        with _SPAWN_ENV_LOCK:
            saved = os.environ.get("PYTHONPATH")
            os.environ["PYTHONPATH"] = ""
            try:
                pool = ctx.Pool(self.num_workers)
            finally:
                if saved is None:
                    os.environ.pop("PYTHONPATH", None)
                else:
                    os.environ["PYTHONPATH"] = saved
        try:
            imap = pool.imap if self.ordered else pool.imap_unordered
            yield from imap(self.fn, self.parent._iter(),
                            chunksize=_pool_chunksize())
        finally:
            pool.terminate()
            pool.join()

    def _sig(self):
        # num_workers does not change the produced records; ordered does
        return f"parallel_map:{_fn_digest(self.fn)}:{int(self.ordered)}"


class _Batch(Pipeline):
    stage_name = "batch"

    def __init__(self, parent, batch_size, drop_remainder):
        super().__init__(parent)
        self.batch_size = batch_size
        self.drop_remainder = drop_remainder

    def _blocks(self):
        n = self.batch_size
        pending = []  # [(block, offset)] not yet emitted
        have = 0
        for block in self.parent._iter():
            pending.append((block, 0))
            have += block_len(block)
            while have >= n:
                parts = []
                need = n
                while need:
                    blk, off = pending[0]
                    take = min(need, block_len(blk) - off)
                    parts.append(_slice_block(blk, off, off + take))
                    need -= take
                    if off + take < block_len(blk):
                        pending[0] = (blk, off + take)
                    else:
                        pending.pop(0)
                have -= n
                yield _concat_blocks(parts)
        if have and not self.drop_remainder:
            yield _concat_blocks(
                [_slice_block(b, off, block_len(b)) for b, off in pending])

    def _sig(self):
        return f"batch:{self.batch_size}:{int(self.drop_remainder)}"


class _Shuffle(Pipeline):
    stage_name = "shuffle"

    def __init__(self, parent, buffer_size, seed):
        super().__init__(parent)
        self.buffer_size = buffer_size
        self.seed = seed

    def _blocks(self):
        import numpy as np

        rng = np.random.default_rng(self.seed)
        window = []  # accumulated blocks
        have = 0

        def emit(blocks, count):
            merged = _concat_blocks(blocks)
            perm = rng.permutation(count)
            return _take_rows(merged, perm)

        for block in self.parent._iter():
            window.append(block)
            have += block_len(block)
            while have >= self.buffer_size:
                take = self.buffer_size
                parts, rest = [], []
                for blk in window:
                    if take >= block_len(blk):
                        parts.append(blk)
                        take -= block_len(blk)
                    elif take:
                        parts.append(_slice_block(blk, 0, take))
                        rest.append(_slice_block(blk, take, block_len(blk)))
                        take = 0
                    else:
                        rest.append(blk)
                window = rest
                have -= self.buffer_size
                yield emit(parts, self.buffer_size)
        if have:
            yield emit(window, have)

    def _sig(self):
        return f"shuffle:{self.buffer_size}:{self.seed}"


class _Interleave(Pipeline):
    stage_name = "interleave"

    def __init__(self, parent, cycle_length):
        super().__init__(parent)
        self.cycle_length = cycle_length
        if type(parent)._substreams is Pipeline._substreams:
            parent._substreams()  # eager: raises on unsupported source

    def _blocks(self):
        pending = list(self.parent._substreams())
        live = []
        while pending and len(live) < self.cycle_length:
            live.append(iter(pending.pop(0)()))
        while live:
            nxt = []
            for it in live:
                block = next(it, None)
                if block is None:
                    if pending:
                        nxt.append(iter(pending.pop(0)()))
                    continue
                yield block
                nxt.append(it)
            live = nxt

    def _sig(self):
        return f"interleave:{self.cycle_length}"


class _Cache(Pipeline):
    stage_name = "cache"

    def __init__(self, parent, spill_dir, memory_bytes):
        super().__init__(parent)
        self.spill_dir = spill_dir
        self.memory_bytes = memory_bytes
        self._lock = threading.Lock()
        self._complete = False
        self._mem = []
        self._spill_path = None
        self._spill_offsets = []  # byte offset of each spilled block
        self._finalizer = None

    def _col_bytes(self, block):
        import numpy as np

        total = 0
        for col in block.values():
            if isinstance(col, np.ndarray):
                total += col.nbytes
            else:
                total += sum(len(v) if isinstance(v, (bytes, str)) else 64
                             for v in col)
        return total

    def _blocks(self):
        with self._lock:
            if self._complete:
                replay_mem = list(self._mem)
                spill = self._spill_path
            else:
                replay_mem = None
                spill = None
        if replay_mem is not None:
            yield from replay_mem
            if spill is not None:
                with open(spill, "rb") as f:
                    while True:
                        try:
                            yield pickle.load(f)
                        except EOFError:
                            return
            return

        # first (filling) pass; only a COMPLETE pass publishes the cache
        mem, used, spill_f, spill_path = [], 0, None, None
        offsets = []
        try:
            for block in self.parent._iter():
                if spill_f is None and used + self._col_bytes(block) \
                        <= self.memory_bytes:
                    mem.append(block)
                    used += self._col_bytes(block)
                else:
                    if spill_f is None:
                        fd, spill_path = tempfile.mkstemp(
                            prefix="tfos-data-cache-", suffix=".pkl",
                            dir=self.spill_dir)
                        spill_f = os.fdopen(fd, "wb")
                    offsets.append(spill_f.tell())
                    pickle.dump(block, spill_f,
                                protocol=pickle.HIGHEST_PROTOCOL)
                yield block
        except BaseException:
            if spill_f is not None:
                spill_f.close()
                os.unlink(spill_path)
            raise
        if spill_f is not None:
            spill_f.close()
        with self._lock:
            if not self._complete:
                self._mem, self._spill_path = mem, spill_path
                self._spill_offsets = offsets
                self._complete = True
                if spill_path is not None:
                    self._finalizer = weakref.finalize(
                        self, _unlink_quiet, spill_path)
            elif spill_path is not None:  # raced: keep the first pass
                os.unlink(spill_path)

    def _skip_fast(self, skip_blocks):
        """O(1) skip once the cache is complete: index into the memory
        list, seek the spill file to the recorded per-block offset."""
        with self._lock:
            if not self._complete:
                return None
            replay_mem = list(self._mem)
            spill = self._spill_path
            offsets = list(self._spill_offsets)

        def _replay():
            if skip_blocks < len(replay_mem):
                yield from replay_mem[skip_blocks:]
                spill_at = 0
            else:
                spill_at = skip_blocks - len(replay_mem)
            if spill is None or spill_at >= len(offsets):
                return
            with open(spill, "rb") as f:
                f.seek(offsets[spill_at])
                while True:
                    try:
                        yield pickle.load(f)
                    except EOFError:
                        return

        return _replay()

    def purge(self):
        """Drop cached state (memory + spill file)."""
        with self._lock:
            self._complete = False
            self._mem = []
            if self._finalizer is not None:
                self._finalizer()
                self._finalizer = None
            self._spill_path = None
            self._spill_offsets = []


def _unlink_quiet(path):
    try:
        os.unlink(path)
    except OSError:
        pass


class _Prefetch(Pipeline):
    stage_name = "prefetch"
    _total_is_wait = True  # its work runs on the background thread

    def __init__(self, parent, depth):
        super().__init__(parent)
        self.depth = depth

    def _blocks(self):
        _END = object()
        q = _queue.Queue(maxsize=self.depth)
        cancelled = threading.Event()

        def worker():
            try:
                for block in self.parent._iter():
                    while not cancelled.is_set():
                        try:
                            q.put(block, timeout=0.2)
                            break
                        except _queue.Full:
                            continue
                    if cancelled.is_set():
                        return
            except Exception as e:  # noqa: BLE001 - forwarded to consumer
                q.put(("__data_prefetch_error__", e))
            finally:
                try:
                    q.put(_END, timeout=1)
                except _queue.Full:
                    pass

        t = threading.Thread(target=worker, daemon=True,
                             name="tfos-data-prefetch")
        t.start()
        try:
            while True:
                item = q.get()
                if item is _END:
                    return
                if isinstance(item, tuple) and len(item) == 2 \
                        and item[0] == "__data_prefetch_error__":
                    raise item[1]
                yield item
        finally:
            cancelled.set()
            while True:  # unblock a worker stuck on the full queue
                try:
                    q.get_nowait()
                except _queue.Empty:
                    break
            t.join(timeout=2)


class _Repeat(Pipeline):
    stage_name = "repeat"

    def __init__(self, parent, count):
        super().__init__(parent)
        self.count = count

    def _blocks(self):
        epoch = 0
        while self.count is None or epoch < self.count:
            yield from self.parent._iter()
            epoch += 1

    def _sig(self):
        return f"repeat:{self.count}"


class _Shard(Pipeline):
    stage_name = "shard"

    def __init__(self, parent, index, count):
        super().__init__(parent)
        self.index = index
        self.count = count

    def _blocks(self):
        import numpy as np

        cursor = 0  # global record index of the next upstream record
        for block in self.parent._iter():
            n = block_len(block)
            first = (self.index - cursor) % self.count
            cursor += n
            if first >= n:
                continue
            idx = np.arange(first, n, self.count)
            yield _take_rows(block, idx)

    def _sig(self):
        return f"shard:{self.index}:{self.count}"


# --------------------------------------------------------------------------
# sources


class _TFRecordSource(Pipeline):
    """TFRecord dir/file/shard-list -> columnar blocks, one shard resident
    at a time (``dfutil.iter_tfrecords_columnar``; reference
    ``dfutil.py:44-81`` / the tensorflow-hadoop input format)."""

    stage_name = "tfrecords"

    def __init__(self, source, block_size):
        super().__init__(None)
        if block_size < 1:
            raise ValueError(f"block_size must be >= 1, got {block_size}")
        from tensorflowonspark_tpu import dfutil

        self.files = (list(source) if isinstance(source, (list, tuple))
                      else dfutil.part_files(source))
        self.block_size = int(block_size)

    def _blocks(self):
        from tensorflowonspark_tpu import dfutil

        yield from dfutil.iter_tfrecords_columnar(
            self.files, self.block_size, drop_remainder=False)

    def _substreams(self):
        from tensorflowonspark_tpu import dfutil

        def one(f):
            return lambda: dfutil.iter_tfrecords_columnar(
                [f], self.block_size, drop_remainder=False)

        return [one(f) for f in self.files]

    def _sig(self):
        return f"tfrecords:{self.block_size}:" + ",".join(self.files)


class _ArraySource(Pipeline):
    stage_name = "arrays"

    def __init__(self, columns, block_size):
        super().__init__(None)
        if block_size < 1:
            raise ValueError(f"block_size must be >= 1, got {block_size}")
        if not columns:
            raise ValueError("from_arrays needs at least one column")
        lens = {name: len(col) for name, col in columns.items()}
        if len(set(lens.values())) != 1:
            raise ValueError(f"column length mismatch: {lens}")
        self.columns = dict(columns)
        self.block_size = int(block_size)

    def _blocks(self):
        n = len(next(iter(self.columns.values())))
        for lo in range(0, n, self.block_size):
            yield _slice_block(self.columns, lo, lo + self.block_size)

    def _skip_fast(self, skip_blocks):
        n = len(next(iter(self.columns.values())))
        start = skip_blocks * self.block_size
        return (_slice_block(self.columns, lo, lo + self.block_size)
                for lo in range(start, n, self.block_size))

    def _sig(self):
        import numpy as np

        parts = [f"arrays:{self.block_size}"]
        for name in sorted(self.columns):
            col = self.columns[name]
            if isinstance(col, np.ndarray):
                head = np.ascontiguousarray(col[:64]).tobytes()
                fp = hashlib.sha1(head).hexdigest()[:8]
                parts.append(
                    f"{name}:{col.dtype.str}:{col.shape}:{fp}")
            else:
                parts.append(f"{name}:list:{len(col)}:{id(col)}")
        return ";".join(parts)


class _RowSource(Pipeline):
    stage_name = "rows"

    def __init__(self, rows, block_size):
        super().__init__(None)
        if block_size < 1:
            raise ValueError(f"block_size must be >= 1, got {block_size}")
        self.rows = rows
        self.block_size = int(block_size)

    def _blocks(self):
        buf = []
        for row in self.rows:
            buf.append(row)
            if len(buf) >= self.block_size:
                yield _rows_to_block(buf)
                buf = []
        if buf:
            yield _rows_to_block(buf)

    def _sig(self):
        return f"rows:{self.block_size}:{id(self.rows)}"


def from_tfrecords(source, block_size=1024):
    """Pipeline over a TFRecord dir, single file, or explicit shard list
    (``part-*`` convention, ``dfutil.part_files``).  Blocks are dense
    column dicts of up to ``block_size`` records; ``interleave`` on this
    source round-robins across the shard files."""
    return _TFRecordSource(source, block_size)


def from_arrays(columns, block_size=1024):
    """Pipeline over in-memory columns ``{name: ndarray | list}`` (equal
    lengths).  Blocks are zero-copy views of the arrays."""
    return _ArraySource(columns, block_size)


def from_dataset(dataset, block_size=1024):
    """Pipeline over an engine dataset or any iterable of row dicts
    (``dfutil.load_tfrecords`` output shape).  Engine datasets
    (LocalDataset / RDD-likes exposing ``collect``) are collected on the
    driver — use :func:`from_tfrecords` for larger-than-RAM inputs."""
    rows = dataset.collect() if hasattr(dataset, "collect") else dataset
    return _RowSource(rows, block_size)
