"""Pipeline parallelism: GPipe-style microbatching over a 'pp' mesh axis.

Absent from the reference (its model parallelism was "users place ops",
SURVEY.md §2.3); here stages are placed on devices along a named mesh
axis and activations flow stage-to-stage over ICI via ``lax.ppermute``
inside ``shard_map``:

- stage parameters are stacked on a leading axis sharded P('pp', ...)
  — device i holds stage i's weights only;
- the batch is split into m microbatches; at step t, device i runs
  microbatch t-i (the classic pipeline schedule — bubble fraction
  (S-1)/(m+S-1));
- everything is one jittable function, differentiable end to end
  (ppermute has a transpose, the schedule is a lax.scan).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import NamedSharding, PartitionSpec as P

from tensorflowonspark_tpu.parallel.ring import shard_map


def pipeline_apply(stage_fn, stage_params, x, *, mesh, n_microbatches,
                   axis_name="pp"):
    """Run ``stage_fn(params_i, x) -> x`` through S pipelined stages.

    stage_params: pytree stacked on a leading stage axis of size S
    (shard it P('pp', ...)).  x: [B, ...] global batch; B must divide by
    n_microbatches.  Returns the final stage's output, same shape as x
    (stage_fn must preserve shape — pad/project inside the stage
    otherwise).
    """
    n_stages = mesh.shape[axis_name]
    b = x.shape[0]
    assert b % n_microbatches == 0, (b, n_microbatches)
    mb = b // n_microbatches

    # microbatch-major: [m, mb, ...]
    xm = x.reshape(n_microbatches, mb, *x.shape[1:])

    def pipelined(params, xm):
        # inside shard_map: params = this device's stage (leading axis 1),
        # xm = the full microbatch stream (replicated over pp)
        params = jax.tree.map(lambda p: p[0], params)
        idx = lax.axis_index(axis_name)
        shift = [(i, (i + 1) % n_stages) for i in range(n_stages)]

        total = n_microbatches + n_stages - 1
        state = jnp.zeros_like(xm[0])  # activation entering this device
        outputs = jnp.zeros_like(xm)

        def step(carry, t):
            state, outputs = carry
            # stage 0 ingests microbatch t (when in range)
            take = jnp.clip(t, 0, n_microbatches - 1)
            state = jnp.where(idx == 0, xm[take], state)
            y = stage_fn(params, state)
            # device i finishes microbatch t-i; the last stage banks it
            out_idx = jnp.clip(t - (n_stages - 1), 0, n_microbatches - 1)
            bank = (idx == n_stages - 1) & (t >= n_stages - 1)
            outputs = jnp.where(
                bank,
                lax.dynamic_update_index_in_dim(outputs, y, out_idx, 0),
                outputs,
            )
            # hand activations to the next stage
            state = lax.ppermute(y, axis_name, shift)
            return (state, outputs), None

        (state, outputs), _ = lax.scan(
            step, (state, outputs), jnp.arange(total)
        )
        # everyone returns the last stage's bank; psum-of-one-hot keeps it
        # replicated (only the last stage holds nonzero outputs)
        keep = (idx == n_stages - 1).astype(outputs.dtype)
        return lax.psum(outputs * keep, axis_name)

    pspec = jax.tree.map(lambda _: P(axis_name), stage_params)
    out = shard_map(
        pipelined,
        mesh,
        in_specs=(pspec, P()),
        out_specs=P(),
    )(stage_params, xm)
    return out.reshape(b, *x.shape[1:])


def stack_stage_params(params_list):
    """[per-stage pytrees] -> one pytree with a leading stage axis."""
    return jax.tree.map(lambda *xs: jnp.stack(xs), *params_list)


def stage_sharding(mesh, stage_params, axis_name="pp"):
    """NamedShardings placing the stacked stage axis on ``axis_name``."""
    return jax.tree.map(
        lambda _: NamedSharding(mesh, P(axis_name)), stage_params
    )
