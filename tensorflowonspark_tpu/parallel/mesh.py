"""Device mesh construction and sharding helpers.

The TPU-native replacement for the reference's communication planes
(SURVEY.md §2.4): instead of a gRPC/NCCL ring configured through
TF_CONFIG, compute processes join one SPMD job and lay tensors out over a
named-axis ``Mesh``; XLA inserts the collectives (all-reduce /
all-gather / reduce-scatter / ppermute) over ICI within a slice and DCN
across slices.

Axis convention (any subset may be size 1):
  ``data``  — data parallel (batch sharding)
  ``fsdp``  — parameter sharding over the data axis group (ZeRO-style)
  ``model`` — tensor/model parallel
  ``seq``   — sequence/context parallel (ring attention)
  ``pp``    — pipeline stages
  ``ep``    — MoE expert parallel
("pipe" and "expert" are accepted as aliases of pp/ep.)
"""

from __future__ import annotations

import logging
import math
from dataclasses import dataclass, field

import numpy as np

logger = logging.getLogger(__name__)

AXIS_ORDER = ("pp", "data", "fsdp", "seq", "ep", "model")

# accepted alternate spellings -> canonical axis name
AXIS_ALIASES = {"pipe": "pp", "expert": "ep"}


def canonical_axes(axes):
    """Alias-canonicalized copy of an axes dict ({'pipe': 2} -> {'pp': 2});
    raises when two spellings collide after canonicalization.  Shared by
    ``MeshSpec.resolve`` and the elastic virtual-device layer
    (``elastic/virtual.py``), which canonicalizes logical shapes that
    have no device count to resolve against yet."""
    sizes = {AXIS_ALIASES.get(k, k): v for k, v in axes.items()}
    if len(sizes) != len(axes):
        raise ValueError(
            f"mesh axes {list(axes)} collide after alias "
            f"canonicalization ({AXIS_ALIASES})"
        )
    return sizes


@dataclass
class MeshSpec:
    """Named axis sizes; -1 at most once to absorb remaining devices."""

    axes: dict = field(default_factory=dict)

    def resolve(self, n_devices):
        sizes = canonical_axes(self.axes)
        unknown = [k for k, v in sizes.items() if v == -1]
        known = math.prod(v for v in sizes.values() if v != -1)
        if len(unknown) > 1:
            raise ValueError("at most one mesh axis may be -1")
        if unknown:
            if n_devices % known:
                raise ValueError(
                    f"{n_devices} devices not divisible by fixed axes {sizes}"
                )
            sizes[unknown[0]] = n_devices // known
        if math.prod(sizes.values()) != n_devices:
            raise ValueError(f"mesh {sizes} != {n_devices} devices")
        return sizes


def make_mesh(axes=None, devices=None, backend=None):
    """Build a ``jax.sharding.Mesh`` with named axes.

    Args:
      axes: {name: size} with at most one -1; default {'data': -1}.
      devices: explicit device list (tests pass ``jax.devices('cpu')``);
        default: all global devices of ``backend``.

    Device order follows ``jax.devices()``, which orders TPU chips so
    that neighboring mesh coordinates are ICI neighbors; the trailing
    mesh axes change fastest, so put the highest-bandwidth axis
    (``model``) last — AXIS_ORDER does this.
    """
    import jax

    if devices is None:
        devices = jax.devices(backend) if backend else jax.devices()
    spec = MeshSpec(dict(axes) if axes else {"data": -1})
    sizes = spec.resolve(len(devices))
    names = [a for a in AXIS_ORDER if a in sizes] + [
        a for a in sizes if a not in AXIS_ORDER
    ]
    shape = [sizes[n] for n in names]
    arr = np.asarray(devices).reshape(shape)
    mesh = jax.sharding.Mesh(arr, tuple(names))
    logger.info("mesh: %s", dict(zip(names, shape)))
    return mesh


def sharded(mesh, *spec):
    """NamedSharding over the given PartitionSpec entries."""
    import jax
    from jax.sharding import NamedSharding, PartitionSpec

    return NamedSharding(mesh, PartitionSpec(*spec))


def replicated(mesh):
    import jax
    from jax.sharding import NamedSharding, PartitionSpec

    return NamedSharding(mesh, PartitionSpec())


def local_to_global(mesh, local_arrays, axis="data"):
    """Assemble per-process local batches into one global sharded array.

    Multi-controller equivalent of feeding a per-worker shard into a
    MultiWorkerMirroredStrategy step: each process contributes its local
    slice of the batch dimension; the result is one global jax.Array laid
    out over ``axis``.
    """
    import jax
    from jax.sharding import NamedSharding, PartitionSpec

    sh = NamedSharding(mesh, PartitionSpec(axis))

    def place(x):
        return jax.make_array_from_process_local_data(sh, x)

    return jax.tree_util.tree_map(place, local_arrays)
