"""Sharding rules: how parameter/optimizer/batch trees map onto the mesh.

This is the GSPMD replacement for the reference's strategy zoo
(SURVEY.md §2.3): instead of choosing a tf.distribute strategy, callers
pick mesh axis sizes and these helpers lay every tensor out; XLA inserts
the collectives (all-gather for fsdp parameter reassembly,
reduce-scatter/all-reduce for gradients) over ICI.
"""

from __future__ import annotations

from tensorflowonspark_tpu.parallel.mesh import replicated as replicated_sharding


def batch_sharding(mesh, axes=("data", "fsdp")):
    """Sharding for [batch, ...] arrays: batch dim split over data axes."""
    from jax.sharding import NamedSharding, PartitionSpec

    present = tuple(a for a in axes if a in mesh.shape and mesh.shape[a] > 1)
    return NamedSharding(mesh, PartitionSpec(present if present else None))


def fsdp_sharding(mesh, tree, axis="fsdp", min_shard_elems=2 ** 12):
    """ZeRO-style parameter sharding: for each leaf, shard the largest
    dimension divisible by the fsdp axis size; small/indivisible leaves
    stay replicated.  Applied to params AND optimizer state (optimizer
    moments follow their parameter's layout).

    Returns a pytree of NamedSharding matching ``tree``.
    """
    import jax
    from jax.sharding import NamedSharding, PartitionSpec

    n = mesh.shape.get(axis, 1)

    def rule(leaf):
        shape = getattr(leaf, "shape", ())
        if n <= 1 or not shape or leaf.size < min_shard_elems:
            return NamedSharding(mesh, PartitionSpec())
        # prefer the largest divisible dim (usually the output channels)
        dims = sorted(range(len(shape)), key=lambda d: -shape[d])
        for d in dims:
            if shape[d] % n == 0:
                spec = [None] * len(shape)
                spec[d] = axis
                return NamedSharding(mesh, PartitionSpec(*spec))
        return NamedSharding(mesh, PartitionSpec())

    return jax.tree_util.tree_map(rule, tree)


def apply_shardings(tree, shardings):
    """Device-put a pytree according to a matching sharding tree (single
    batched transfer)."""
    import jax

    return jax.device_put(tree, shardings)


def shard_train_state(mesh, params, state, opt_state, fsdp_axis="fsdp"):
    """Lay out the full train state: fsdp for params & optimizer moments,
    replicated BN state (tiny), returning (placed tensors, shardings)."""
    import jax

    p_sh = fsdp_sharding(mesh, params, fsdp_axis)
    s_sh = jax.tree_util.tree_map(lambda _: replicated_sharding(mesh), state)
    # optimizer moments mirror their parameter's layout; scalar step
    # counters replicate
    o_sh = jax.tree_util.tree_map(
        lambda leaf: fsdp_sharding(mesh, leaf, fsdp_axis)
        if getattr(leaf, "ndim", 0) else replicated_sharding(mesh),
        opt_state,
    )
    placed = (
        apply_shardings(params, p_sh),
        apply_shardings(state, s_sh),
        apply_shardings(opt_state, o_sh),
    )
    return placed, (p_sh, s_sh, o_sh)
