"""Parallelism library: meshes, shardings, and parallel layers.

This is where the reference's delegated parallelism (SURVEY.md §2.3 —
MultiWorkerMirroredStrategy all-reduce, ParameterServerStrategy, model
parallelism "insofar as users place ops") becomes first-class TPU-native
capability: one ``jax.sharding.Mesh`` with named axes, GSPMD shardings,
and XLA collectives over ICI/DCN.
"""

from tensorflowonspark_tpu.parallel.mesh import (  # noqa: F401
    MeshSpec,
    local_to_global,
    make_mesh,
    replicated,
    sharded,
)
from tensorflowonspark_tpu.parallel.ring import (  # noqa: F401
    inverse_permutation,
    ring_attention,
    sequence_parallel_attention,
    ulysses_attention,
    zigzag_permutation,
    zigzag_ring_attention,
)
from tensorflowonspark_tpu.parallel.sharding import (  # noqa: F401
    apply_shardings,
    batch_sharding,
    fsdp_sharding,
    replicated_sharding,
    shard_train_state,
)
from tensorflowonspark_tpu.parallel.pipeline_parallel import (  # noqa: F401
    pipeline_apply,
    stack_stage_params,
    stage_sharding,
)
