"""Sequence/context parallelism: ring attention + Ulysses all-to-all.

Long-context support is green-field relative to the reference (SURVEY.md
§5 "Long-context / sequence parallelism — absent"); here it is
first-class.  Two interchangeable schemes over a named sequence mesh
axis:

- **Ring attention** (``ring_attention``): every device keeps its local
  q shard and rotates the k/v shards around the ring with
  ``lax.ppermute`` (rides ICI neighbor links), accumulating blockwise
  online-softmax partials.  Peak memory is O(S_local²) per step and the
  k/v transfer overlaps the next block's compute under XLA's async
  collective scheduling.
- **Ulysses** (``ulysses_attention``): ``lax.all_to_all`` re-shards
  seq→heads so each device computes *full-sequence* attention for a
  subset of heads, then re-shards back.  One collective pair instead of
  ring steps; needs heads % axis_size == 0.

Both are meant to run inside ``shard_map`` (helpers below wrap that) and
are differentiable — ppermute/all_to_all have transposes, and the
blockwise softmax is plain traced math.
"""

from __future__ import annotations

import functools
import math

import jax
import jax.numpy as jnp
import numpy as _onp
from jax import lax
from jax.sharding import PartitionSpec as P

try:  # jax>=0.8
    from jax import shard_map as _shard_map_raw

    _CHECK_KW = "check_vma"
except ImportError:  # pragma: no cover
    from jax.experimental.shard_map import shard_map as _shard_map_raw

    _CHECK_KW = "check_rep"


def shard_map(f, mesh, in_specs, out_specs):
    """Version-stable shard_map with replication checking off (the ring
    primitives produce unreplicated outputs from psum-free math, which
    the checker cannot prove)."""
    return _shard_map_raw(
        f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
        **{_CHECK_KW: False},
    )


_NEG_INF = -1e30


def _online_softmax_update(carry, q_blk, k_blk, v_blk, scale, causal,
                           q_offset, kv_offset):
    """One blockwise online-softmax accumulation step (shared by the
    contiguous and zigzag rings — the delicate running-max/rescale math
    must never diverge between them)."""
    acc, m, l = carry
    s = _block_scores(q_blk, k_blk, scale, causal,
                      q_offset=q_offset, kv_offset=kv_offset)
    m_new = jnp.maximum(m, jnp.max(s, axis=-1))
    alpha = jnp.exp(m - m_new)
    p = jnp.exp(s - m_new[..., None])
    l_new = l * alpha + jnp.sum(p, axis=-1)
    acc_new = acc * alpha[..., None] + jnp.einsum(
        "bhqk,bkhd->bhqd", p, v_blk.astype(jnp.float32)
    )
    return acc_new, m_new, l_new


def _block_scores(q, k, scale, causal, q_offset, kv_offset):
    """[B,Sq,H,D]x[B,Skv,H,D] -> masked f32 scores [B,H,Sq,Skv]."""
    s = jnp.einsum(
        "bqhd,bkhd->bhqk",
        q.astype(jnp.float32),
        k.astype(jnp.float32),
    ) * scale
    if causal:
        qpos = q_offset + jnp.arange(q.shape[1])
        kpos = kv_offset + jnp.arange(k.shape[1])
        mask = qpos[:, None] >= kpos[None, :]
        s = jnp.where(mask[None, None], s, _NEG_INF)
    return s


def ring_attention(q, k, v, axis_name, *, causal=False, scale=None):
    """Attention over a sequence-sharded ring; call inside shard_map.

    q/k/v: local shards [B, S_local, H, D]; the global sequence is the
    concatenation over the ``axis_name`` ring order.  Returns the local
    output shard [B, S_local, H, D].
    """
    if scale is None:
        scale = 1.0 / math.sqrt(q.shape[-1])
    axis_size = lax.psum(1, axis_name)
    my_idx = lax.axis_index(axis_name)
    b, s_local, h, d = q.shape

    acc = jnp.zeros((b, h, s_local, d), jnp.float32)
    m = jnp.full((b, h, s_local), _NEG_INF, jnp.float32)
    l = jnp.zeros((b, h, s_local), jnp.float32)
    perm = [(i, (i + 1) % axis_size) for i in range(axis_size)]

    k_cur, v_cur = k, v
    for step in range(axis_size):
        # after `step` rotations each device holds the shard originally
        # at (my_idx - step); step 0 is the local diagonal block, so for
        # causal masking m is finite after step 0 for every valid row
        # and fully-masked later blocks contribute exp(-inf - m) = 0.
        kv_idx = (my_idx - step) % axis_size

        def do_block(carry, k_blk=k_cur, v_blk=v_cur, kv_i=kv_idx):
            return _online_softmax_update(
                carry, q, k_blk, v_blk, scale, causal,
                q_offset=my_idx * s_local, kv_offset=kv_i * s_local,
            )

        if causal:
            # a kv shard strictly after the q shard is fully masked —
            # skip its score/softmax compute entirely (the ring still
            # rotates it, but ~half the blocks cost nothing)
            acc, m, l = lax.cond(
                kv_idx > my_idx, lambda c: c, do_block, (acc, m, l)
            )
        else:
            acc, m, l = do_block((acc, m, l))
        if step + 1 < axis_size:
            k_cur = lax.ppermute(k_cur, axis_name, perm)
            v_cur = lax.ppermute(v_cur, axis_name, perm)

    out = acc / jnp.maximum(l, 1e-30)[..., None]
    return out.transpose(0, 2, 1, 3).astype(q.dtype)


def zigzag_permutation(seq_len, axis_size):
    """Global-position permutation for zigzag sequence sharding.

    The sequence is cut into ``2*axis_size`` stripes; device i owns
    stripes (i, 2*axis_size-1-i), so under the causal mask every device
    holds one early and one late stripe and computes the SAME number of
    unmasked blocks — the plain contiguous ring's device n-1 computes n
    blocks while device 0 computes 1, so its latency never improves no
    matter how many masked blocks are skipped (the classic zigzag /
    striped-attention load balance).

    Returns int32 index array ``perm`` with ``x[:, perm]`` reordering a
    [B, S, ...] sequence into zigzag order; invert with
    ``inverse_permutation(perm)``.
    """
    if seq_len % (2 * axis_size):
        raise ValueError(
            f"seq_len {seq_len} must divide into 2*axis_size="
            f"{2 * axis_size} stripes")
    stripe = seq_len // (2 * axis_size)
    order = []
    for i in range(axis_size):
        order.append(i)
        order.append(2 * axis_size - 1 - i)
    idx = _onp.concatenate(
        [_onp.arange(s * stripe, (s + 1) * stripe) for s in order])
    return jnp.asarray(idx, jnp.int32)


def inverse_permutation(perm):
    """Index array inverting ``zigzag_permutation`` (x_perm[inv] == x)."""
    inv = jnp.zeros_like(perm)
    return inv.at[perm].set(jnp.arange(perm.shape[0], dtype=perm.dtype))


def zigzag_ring_attention(q, k, v, axis_name, *, causal=False, scale=None):
    """Load-balanced causal ring attention; call inside shard_map.

    Inputs are local shards in ZIGZAG order: the global sequence was
    reordered with ``zigzag_permutation`` so this device's
    [B, S_local, H, D] shard is the concatenation of global stripes
    (my_idx, 2n-1-my_idx), each S_local/2 long.  Rotating kv around the
    ring, each (q stripe, kv stripe) pair is computed only when the
    causal mask can reach it — every device does axis_size+1 of the
    2*axis_size stripe-pairs per rotation on average, so causal latency
    is ~halved vs the contiguous ring, not just FLOPs.

    Returns the local output shard, still in zigzag order.
    """
    if scale is None:
        scale = 1.0 / math.sqrt(q.shape[-1])
    axis_size = lax.psum(1, axis_name)
    my_idx = lax.axis_index(axis_name)
    b, s_local, h, d = q.shape
    if s_local % 2:
        raise ValueError("zigzag shards must have even local length")
    s_h = s_local // 2

    # global stripe ids + positions of the two local q halves
    q_stripes = (my_idx, 2 * axis_size - 1 - my_idx)

    acc = jnp.zeros((b, h, s_local, d), jnp.float32)
    m = jnp.full((b, h, s_local), _NEG_INF, jnp.float32)
    l = jnp.zeros((b, h, s_local), jnp.float32)
    perm = [(i, (i + 1) % axis_size) for i in range(axis_size)]

    def half_update(carry, q_half_ix, q_stripe, k_half, v_half, kv_stripe):
        """Online-softmax update of q half ``q_half_ix`` against one kv
        stripe, skipped entirely when the stripe pair is fully masked."""
        acc, m, l = carry
        rows = slice(q_half_ix * s_h, (q_half_ix + 1) * s_h)

        def compute(sub):
            return _online_softmax_update(
                sub, q[:, rows], k_half, v_half, scale, causal,
                q_offset=q_stripe * s_h, kv_offset=kv_stripe * s_h,
            )

        sub = (acc[:, :, rows], m[:, :, rows], l[:, :, rows])
        if causal:
            sub = lax.cond(kv_stripe > q_stripe, lambda c: c, compute, sub)
        else:
            sub = compute(sub)
        return (
            acc.at[:, :, rows].set(sub[0]),
            m.at[:, :, rows].set(sub[1]),
            l.at[:, :, rows].set(sub[2]),
        )

    k_cur, v_cur = k, v
    for step in range(axis_size):
        kv_idx = (my_idx - step) % axis_size
        kv_stripes = (kv_idx, 2 * axis_size - 1 - kv_idx)
        carry = (acc, m, l)
        for qi, q_stripe in enumerate(q_stripes):
            for ki, kv_stripe in enumerate(kv_stripes):
                carry = half_update(
                    carry, qi, q_stripe,
                    k_cur[:, ki * s_h:(ki + 1) * s_h],
                    v_cur[:, ki * s_h:(ki + 1) * s_h],
                    kv_stripe,
                )
        acc, m, l = carry
        if step + 1 < axis_size:
            k_cur = lax.ppermute(k_cur, axis_name, perm)
            v_cur = lax.ppermute(v_cur, axis_name, perm)

    out = acc / jnp.maximum(l, 1e-30)[..., None]
    return out.transpose(0, 2, 1, 3).astype(q.dtype)


def ulysses_attention(q, k, v, axis_name, *, causal=False, scale=None,
                      attn_fn=None):
    """All-to-all sequence parallelism; call inside shard_map.

    Re-shards [B, S/n, H, D] -> [B, S, H/n, D], runs full-sequence
    attention locally (``attn_fn``, default the XLA reference; pass
    ops.flash_attention on TPU), and re-shards back.
    """
    from tensorflowonspark_tpu.ops import mha_reference

    if attn_fn is None:
        attn_fn = mha_reference
    n = lax.psum(1, axis_name)
    assert q.shape[2] % n == 0, (
        f"ulysses needs heads ({q.shape[2]}) divisible by axis size ({n})"
    )
    # seq-shard -> head-shard: split heads axis, gather seq axis
    a2a = functools.partial(
        lax.all_to_all, axis_name=axis_name, split_axis=2, concat_axis=1,
        tiled=True,
    )
    qg, kg, vg = a2a(q), a2a(k), a2a(v)
    out = attn_fn(qg, kg, vg, causal=causal, scale=scale)
    # head-shard -> seq-shard
    return lax.all_to_all(
        out, axis_name=axis_name, split_axis=1, concat_axis=2, tiled=True
    )


def sequence_parallel_attention(mesh, impl="ring", *, seq_axis="seq",
                                batch_axes=("data", "fsdp"),
                                head_axis="model", causal=False, scale=None):
    """shard_map-wrapped attention over ``mesh``: [B, S, H, D] global
    arrays, batch sharded over ``batch_axes``, sequence over
    ``seq_axis``, heads over ``head_axis`` (tp); returns same sharding.

    This is the building block models call when a 'seq' axis is present
    (models/transformer.py) — dp/fsdp/tp stay GSPMD-managed, only the
    sequence dimension's cross-shard exchange is explicit.

    ``impl="zigzag"`` expects the caller to have reordered the global
    sequence with ``zigzag_permutation(seq_len, mesh.shape[seq_axis])``
    (and to inverse-permute outputs / permute labels identically): the
    reorder is what balances causal work across the ring.
    """
    fns = {"ring": ring_attention, "zigzag": zigzag_ring_attention,
           "ulysses": ulysses_attention}
    inner = functools.partial(
        fns[impl], axis_name=seq_axis, causal=causal, scale=scale
    )
    axes = dict(mesh.shape)
    batch_axes = tuple(a for a in batch_axes if a in axes)
    head = head_axis if head_axis in axes else None
    spec = P(batch_axes if batch_axes else None, seq_axis, head, None)

    def call(q, k, v):
        return shard_map(
            lambda q, k, v: inner(q, k, v),
            mesh,
            in_specs=(spec, spec, spec),
            out_specs=spec,
        )(q, k, v)

    return call
