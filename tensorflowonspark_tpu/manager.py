"""Per-executor IPC manager (parity: reference TFManager.py).

A ``multiprocessing.managers.BaseManager`` singleton per executor exposing
named joinable queues plus a key/value store.  Two modes, exactly like the
reference (TFManager.py:40-65):

- ``'local'``  — loopback TCP, reachable only from processes on this host
  (the Spark/engine feeder task and the training process share the
  executor).
- ``'remote'`` — bound on all interfaces so the *driver* can connect to
  push control messages (used for ps/evaluator shutdown, parity:
  TFCluster.py:186-194).

Differences from the reference:
- Queue payloads are **batches** (lists of records) pushed by the feeder,
  not single records; the per-record pickle hop at reference
  TFSparkNode.py:480-482 was the documented throughput bottleneck
  (SURVEY.md §3.2).
- The KV store values go through plain dict semantics; state machine keys
  ('state': running/terminating/stopped) are identical.
"""

from __future__ import annotations

import logging
import os
import queue as _queue
from multiprocessing.managers import BaseManager, DictProxy

logger = logging.getLogger(__name__)


class JoinableItemQueue(_queue.Queue):
    """A joinable queue living inside the manager process.

    ``multiprocessing.JoinableQueue`` cannot be served by a BaseManager
    proxy cleanly across independent client processes; a plain
    ``queue.Queue`` (which *is* joinable via task_done/join) held in the
    manager server process gives identical semantics over proxies.
    """


class TFManager(BaseManager):
    """Typed manager; proxies registered at start/connect time.

    ``get``/``set`` are real instance methods over a DictProxy-backed KV
    store: registering raw callables would hand back AutoProxy objects
    whose ``==`` never matches plain values.
    """

    def get(self, key):
        return self.kv().get(key)

    def set(self, key, value):
        self.kv().update({key: value})

    # -- telemetry drain channel (utils/telemetry.py) ------------------
    # Every process on this executor advertises its spool dir under a
    # path-unique KV key (no read-modify-write race across the trainer,
    # feeder and node processes); the driver-side shutdown drain asks
    # for the set and collects the JSONL files (node.drain_telemetry).

    def telemetry_register(self, path):
        self.kv().update({"telemetry_spool:" + str(path): str(path)})

    def telemetry_spools(self):
        prefix = "telemetry_spool:"
        return sorted(v for k, v in self.kv().items()
                      if str(k).startswith(prefix))


# Server-side singletons (one manager process per executor).  Queues are
# created lazily *inside the manager server process* on first access: under
# a spawn start method the server re-imports this module, so parent-side
# pre-population would be invisible to it.
_mgr = None
_qdict = {}
_kdict = {}


def _get_queue(name):
    if name not in _qdict:
        _qdict[name] = JoinableItemQueue()
    return _qdict[name]


def _get_kv():
    return _kdict


def start(authkey, queues, mode="local"):
    """Start this executor's manager (parity: TFManager.py:40-65).

    Args:
      authkey: shared-secret bytes for connection auth.
      queues: queue names to create ('input', 'output', 'error', 'control').
      mode: 'local' (loopback) or 'remote' (any interface, for driver
        control of ps/evaluator nodes).

    Returns the started ``TFManager`` (its ``.address`` is (host, port)).
    """
    global _mgr
    TFManager.register("get_queue", callable=_get_queue)
    TFManager.register("kv", callable=_get_kv, proxytype=DictProxy)
    host = "localhost" if mode == "local" else ""
    _mgr = TFManager(address=(host, 0), authkey=authkey)
    _mgr.start()
    # record the server child so engine teardown can kill a survivor if
    # this executor dies un-gracefully (utils.track_child_pid contract)
    proc = getattr(_mgr, "_process", None)
    if proc is not None and proc.pid:
        from tensorflowonspark_tpu.utils import track_child_pid

        track_child_pid(proc.pid)
    for name in queues:  # pre-warm so queues exist before any consumer
        _mgr.get_queue(name)
    _mgr.set("state", "running")
    logger.info("started TFManager on %s (mode=%s)", _mgr.address, mode)
    return _mgr


def connect(address, authkey):
    """Connect to a running manager (parity: TFManager.py:68-83)."""
    TFManager.register("get_queue")
    TFManager.register("kv", proxytype=DictProxy)
    if not isinstance(authkey, bytes):
        authkey = bytes(authkey, "utf-8")
    m = TFManager(address=tuple(address), authkey=authkey)
    m.connect()
    return m
