"""Per-executor IPC manager (parity: reference TFManager.py).

A ``multiprocessing.managers.BaseManager`` singleton per executor exposing
named joinable queues plus a key/value store.  Two modes, exactly like the
reference (TFManager.py:40-65):

- ``'local'``  — loopback TCP, reachable only from processes on this host
  (the Spark/engine feeder task and the training process share the
  executor).
- ``'remote'`` — bound on all interfaces so the *driver* can connect to
  push control messages (used for ps/evaluator shutdown, parity:
  TFCluster.py:186-194).

Differences from the reference:
- Queue payloads are **batches** (lists of records) pushed by the feeder,
  not single records; the per-record pickle hop at reference
  TFSparkNode.py:480-482 was the documented throughput bottleneck
  (SURVEY.md §3.2).
- The KV store values go through plain dict semantics; state machine keys
  ('state': running/terminating/stopped) are identical.
"""

from __future__ import annotations

import logging
import os
import queue as _queue
import threading
import time
from multiprocessing.managers import BaseManager, DictProxy

logger = logging.getLogger(__name__)

# -- heartbeat liveness ----------------------------------------------------
# The trainer process beats a wall-clock timestamp into the KV; the feeder
# (and anything else awaiting the consumer) reads its age to distinguish
# DEAD from SLOW: a slow trainer keeps beating while it computes, a dead
# or wedged one goes stale and the waiter can fail fast instead of burning
# the whole feed_timeout.  Producer and consumer share the host (the
# manager is loopback), so one wall clock is authoritative.

HEARTBEAT_KEY = "heartbeat"

# KV key prefix for live metrics snapshots (obs/publish.py writes,
# obs/http.py polls); the suffix is the publishing process's node id.
OBS_KEY = "obs:"

# KV key prefixes for the on-demand obs control plane (driver writes a
# directive under CTL, the node's publish daemon consumes it and writes
# the result under ACK; obs/http.py /profilez and /flightz round-trip).
CTL_KEY = "obsctl:"
ACK_KEY = "obsack:"


def heartbeat_interval():
    """Beat cadence (seconds).  ``TFOS_ACTOR_HEARTBEAT_SECS`` is the
    canonical knob (actors/policy.py env family); the pre-actors name
    ``TFOS_HEARTBEAT_SECS`` remains a documented alias.  This function
    is the single chokepoint every liveness producer reads — trainer
    heartbeat, replica beats, actor beats — so one env retunes all."""
    return float(os.environ.get(
        "TFOS_ACTOR_HEARTBEAT_SECS",
        os.environ.get("TFOS_HEARTBEAT_SECS", "2")))


def stale_after():
    """Age (seconds) past which a heartbeat means 'consumer dead'.  The
    default tolerates long GIL-held stretches and first-compile stalls;
    tune down for fast failure detection in tests.
    ``TFOS_ACTOR_HEARTBEAT_STALE`` is canonical, ``TFOS_HEARTBEAT_STALE``
    the documented alias; every liveness consumer (replica monitor, data
    consumer-liveness, actor monitor) reads this one chokepoint."""
    return float(os.environ.get(
        "TFOS_ACTOR_HEARTBEAT_STALE",
        os.environ.get("TFOS_HEARTBEAT_STALE", "60")))


def beat(mgr):
    """Record liveness now (KV write = proof the process schedules)."""
    mgr.set(HEARTBEAT_KEY, time.time())


def heartbeat_age(mgr):
    """Seconds since the consumer last beat, or None when no beat was
    ever recorded (or the KV is unreadable) — callers must treat None as
    'unknown', not 'dead': nodes that predate the first beat and clusters
    without a heartbeat thread would otherwise be declared lost."""
    try:
        v = mgr.get(HEARTBEAT_KEY)
    except Exception:  # noqa: BLE001 - manager may be tearing down
        return None
    if v is None:
        return None
    try:
        return max(0.0, time.time() - float(v))
    except (TypeError, ValueError):
        return None


def start_heartbeat(mgr, interval=None):
    """Spawn a daemon thread beating every ``interval`` seconds; returns
    a stop Event.  Runs in the trainer (node wrapper_fn) for the life of
    user main_fun."""
    interval = heartbeat_interval() if interval is None else float(interval)
    stop = threading.Event()

    def _run():
        while not stop.is_set():
            try:
                beat(mgr)
            except Exception:  # noqa: BLE001 - manager gone: node exiting
                return
            stop.wait(interval)

    t = threading.Thread(target=_run, name="tfos-heartbeat", daemon=True)
    t.start()
    return stop


class JoinableItemQueue(_queue.Queue):
    """A joinable queue living inside the manager process.

    ``multiprocessing.JoinableQueue`` cannot be served by a BaseManager
    proxy cleanly across independent client processes; a plain
    ``queue.Queue`` (which *is* joinable via task_done/join) held in the
    manager server process gives identical semantics over proxies.
    """


class TFManager(BaseManager):
    """Typed manager; proxies registered at start/connect time.

    ``get``/``set`` are real instance methods over a DictProxy-backed KV
    store: registering raw callables would hand back AutoProxy objects
    whose ``==`` never matches plain values.

    The DictProxy is minted once per TFManager instance and reused
    (``_kv``): proxy *creation* is several small-packet roundtrips
    (~0.2s under delayed-ACK), which made every KV get/set cost 200ms+
    while queue proxies — created once — stayed sub-millisecond.  The
    cached proxy is thread-safe: BaseProxy keeps per-thread
    connections.
    """

    def _kv(self):
        p = getattr(self, "_kv_proxy", None)
        if p is None:
            p = self._kv_proxy = self.kv()
        return p

    def get(self, key):
        return self._kv().get(key)

    def set(self, key, value):
        self._kv().update({key: value})

    # -- telemetry drain channel (utils/telemetry.py) ------------------
    # Every process on this executor advertises its spool dir under a
    # path-unique KV key (no read-modify-write race across the trainer,
    # feeder and node processes); the driver-side shutdown drain asks
    # for the set and collects the JSONL files (node.drain_telemetry).

    def telemetry_register(self, path):
        self._kv().update({"telemetry_spool:" + str(path): str(path)})

    def telemetry_spools(self):
        prefix = "telemetry_spool:"
        return sorted(v for k, v in self._kv().items()
                      if str(k).startswith(prefix))

    # -- live metrics channel (utils/metrics_registry.py, obs/) --------
    # Every instrumented process reachable through this executor's
    # manager publishes its registry snapshot under an id-unique KV key
    # (same no-read-modify-write discipline as the spool channel); the
    # driver's ObsServer polls the set and merges them into /metrics.

    def obs_publish(self, node_id, payload):
        self._kv().update({OBS_KEY + str(node_id): payload})

    def obs_snapshots(self):
        return {str(k)[len(OBS_KEY):]: v for k, v in self._kv().items()
                if str(k).startswith(OBS_KEY)}

    # -- obs control plane (obs/http.py -> obs/publish.py) -------------
    # One directive slot and one ack slot per node id: the driver posts
    # {"cmd", "seq", ...}, the node's publish daemon pop()s it (atomic
    # on the DictProxy — consumed exactly once even with a respawned
    # daemon racing), executes, and acks with the same seq so the driver
    # can tell a fresh result from a stale one.  id-unique keys, no
    # read-modify-write — same discipline as the channels above.

    def obs_control_post(self, node_id, directive):
        self._kv().update({CTL_KEY + str(node_id): directive})

    def obs_control_take(self, node_id):
        return self._kv().pop(CTL_KEY + str(node_id), None)

    def obs_control_ack(self, node_id, result):
        self._kv().update({ACK_KEY + str(node_id): result})

    def obs_control_result(self, node_id):
        return self._kv().get(ACK_KEY + str(node_id))


# Server-side singletons (one manager process per executor).  Queues are
# created lazily *inside the manager server process* on first access: under
# a spawn start method the server re-imports this module, so parent-side
# pre-population would be invisible to it.
_mgr = None
_qdict = {}
_kdict = {}


def _get_queue(name):
    if name not in _qdict:
        _qdict[name] = JoinableItemQueue()
    return _qdict[name]


def _get_kv():
    return _kdict


def start(authkey, queues, mode="local"):
    """Start this executor's manager (parity: TFManager.py:40-65).

    Args:
      authkey: shared-secret bytes for connection auth.
      queues: queue names to create ('input', 'output', 'error', 'control').
      mode: 'local' (loopback) or 'remote' (any interface, for driver
        control of ps/evaluator nodes).

    Returns the started ``TFManager`` (its ``.address`` is (host, port)).
    """
    global _mgr
    TFManager.register("get_queue", callable=_get_queue)
    TFManager.register("kv", callable=_get_kv, proxytype=DictProxy)
    host = "localhost" if mode == "local" else ""
    _mgr = TFManager(address=(host, 0), authkey=authkey)
    _mgr.start()
    # record the server child so engine teardown can kill a survivor if
    # this executor dies un-gracefully (utils.track_child_pid contract)
    proc = getattr(_mgr, "_process", None)
    if proc is not None and proc.pid:
        from tensorflowonspark_tpu.utils import track_child_pid

        track_child_pid(proc.pid)
    for name in queues:  # pre-warm so queues exist before any consumer
        _mgr.get_queue(name)
    _mgr.set("state", "running")
    logger.info("started TFManager on %s (mode=%s)", _mgr.address, mode)
    return _mgr


def connect(address, authkey):
    """Connect to a running manager (parity: TFManager.py:68-83)."""
    TFManager.register("get_queue")
    TFManager.register("kv", proxytype=DictProxy)
    if not isinstance(authkey, bytes):
        authkey = bytes(authkey, "utf-8")
    m = TFManager(address=tuple(address), authkey=authkey)
    m.connect()
    return m
