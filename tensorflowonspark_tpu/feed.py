"""User-facing node API: path handling + the DataFeed queue consumer.

Parity target: reference ``tensorflowonspark/TFNode.py`` (hdfs_path,
DataFeed with next_batch/should_stop/batch_results/terminate, markers,
input_mapping).  Key redesign: queue items are **batches** (lists of
records) pushed by the feeder task, so a records-per-second hot loop costs
one IPC hop per *chunk* instead of one per record (the reference's
documented bottleneck, TFSparkNode.py:480-482 ↔ TFNode.py:265-287).

``DataFeed.next_batch`` therefore keeps a local leftover buffer: a consumed
chunk that overfills the requested batch carries into the next call.
"""

from __future__ import annotations

import logging
import queue as _queue
import threading
import time

from tensorflowonspark_tpu import marker
from tensorflowonspark_tpu.utils import faults, metrics_registry, telemetry

logger = logging.getLogger(__name__)


def hdfs_path(ctx, path):
    """Normalize a path against the cluster default FS (TFNode.py:29-64).

    Absolute schemes pass through; relative paths resolve against the
    engine's default filesystem (file://, hdfs://, gs://, s3a://...).
    """
    if path.startswith(
        ("file://", "hdfs://", "viewfs://", "gs://", "s3://", "s3a://", "har://")
    ):
        return path
    if ctx.default_fs.startswith(("hdfs://", "viewfs://", "gs://", "s3a://")):
        if path.startswith("/"):
            return ctx.default_fs + path
        return f"{ctx.default_fs}/user/{_user()}/{path}"
    if ctx.default_fs.startswith("file://"):
        if path.startswith("/"):
            return ctx.default_fs + path
        return f"file://{ctx.working_dir}/{path}"
    logger.warning("unknown default_fs %s, using path as-is", ctx.default_fs)
    return path


def _user():
    import getpass

    return getpass.getuser()


def open_feed_ring(mgr, qname="input", producer=False,
                   producer_nonblock=False):
    """Open the shm fast path advertised by the node, or None.

    THE transport handshake, shared by producer (feeder/shutdown closures)
    and consumer (DataFeed): the node's KV entry 'shm_input' is the single
    source of truth.  If a ring is advertised but cannot be opened on this
    side, raise — a silent one-sided fallback would leave producer and
    consumer on different transports and deadlock the feed.
    """
    if qname != "input":
        return None
    ring_name = mgr.get("shm_input")
    if not ring_name:
        return None
    try:
        from tensorflowonspark_tpu.recordio import shm as shmq

        return shmq.ShmQueue(str(ring_name), create=False, producer=producer,
                             producer_nonblock=producer_nonblock)
    except BlockingIOError:
        raise  # ring busy, not broken: dynamic-dispatch handover retries
    except Exception as e:
        raise RuntimeError(
            f"node advertised shm feed ring {ring_name!r} but this process "
            f"cannot open it: {e}; unset TFOS_SHM_FEED to disable the fast path"
        ) from e


def _sliced_column(chunk, i, off, take, shapes):
    """Field ``i``'s records [off, off+take) from a ColumnChunk, as an
    array slice — reshaped back to the original trailing shape when the
    feeder flattened an n-D field (``shapes``).  Pure views, no copies.
    THE single place the wire shape contract is applied; every consumer
    path (row reconstruction, per-tensor lists, dense batches) goes
    through it."""
    col = chunk.columns[i][off:off + take]
    if shapes is not None and shapes[i] is not None:
        col = col.reshape((-1,) + shapes[i])
    return col


class DataFeed:
    """Consumer side of the executor feed queues (TFNode.py:221-329)."""

    def __init__(
        self,
        mgr,
        train_mode=True,
        qname_in="input",
        qname_out="output",
        input_mapping=None,
        metrics=None,
    ):
        self.mgr = mgr
        self.train_mode = train_mode
        self.qname_in = qname_in
        self.qname_out = qname_out
        self.done_feeding = False
        # optional utils.metrics.TrainMetrics: feed-wait time lands in its
        # infeed-stall counter (SURVEY.md §5 observability target)
        self.metrics = metrics
        self.input_tensors = (
            sorted(input_mapping.values()) if input_mapping is not None else None
        )
        self._buffer = []  # leftover records from a partially-consumed chunk
        self._colblock = None  # (ColumnChunk, offset): partially-consumed
        self._col_meta = {}  # tensor -> (dtype, trailing shape) last seen
        # split-tagged delivery state (dynamic split dispatch): next
        # expected chunk seq per split id.  A re-served split (worker
        # SIGKILLed mid-split, provider requeued it pinned to this
        # trainer) replays from seq 0; chunks below the expected seq were
        # already consumed and are dropped here — the consumer half of
        # the exactly-once contract (data/splits.py).
        self._split_next = {}
        # The ring is single-consumer: a prefetch thread (infeed.py) and a
        # terminate() caller must never pop concurrently.  Gets poll under
        # this lock in short slices and re-check the stop flag between
        # slices, so terminate() from another thread can always interleave.
        self._lock = threading.Lock()
        self._stop_requested = False
        self._wait_acc = 0.0  # feed-wait seconds inside the current pull
        self._queue = None  # cached manager queue proxy (compat path)
        # shm fast path; the handshake (open_feed_ring) is shared with the
        # producer closures so both sides always agree on the transport
        self._ring = open_feed_ring(mgr, qname_in, producer=False)

    def _get_once(self, timeout_ms, honor_stop=False):
        """One bounded pop attempt; raises TimeoutError when empty.

        ``honor_stop`` (the consumer path): re-check the stop flag AFTER
        acquiring the lock — a consumer that queued on the lock behind
        terminate()'s drain (which holds it in up-to-1s slices) would
        otherwise act on a stop check from before the drain began and
        pop a chunk the drain was supposed to absorb.  terminate()
        itself pops with the flag set, so its calls leave this off."""
        with self._lock:
            if honor_stop and self._stop_requested:
                raise TimeoutError("feed terminating")
            if self._ring is not None:
                return self._ring.get(timeout_ms)
            if self._queue is None:  # resolve the manager proxy once
                self._queue = self.mgr.get_queue(self.qname_in)
            try:
                chunk = self._queue.get(block=True, timeout=timeout_ms / 1000.0)
            except _queue.Empty:
                raise TimeoutError("feed queue empty") from None
            self._queue.task_done()
            return chunk

    def _get_chunk(self):
        """Next chunk from the fast or compat transport: blocks until data
        arrives or terminate()/poison() is requested (then reports
        end-of-feed).  Poll slice: 100ms on the in-process shm ring (a
        local check), 1s on the manager-queue compat path where every
        attempt is a proxy RPC — the stop flag only needs sub-second
        responsiveness, not a 10Hz round-trip load on the manager."""
        timed = (self.metrics is not None or telemetry.enabled()
                 or metrics_registry.enabled())
        t0 = time.perf_counter() if timed else None
        slice_ms = 100 if self._ring is not None else 1000
        while True:
            if self._stop_requested:
                chunk = None  # terminate(): consume no further data
                break
            try:
                chunk = self._get_once(timeout_ms=slice_ms, honor_stop=True)
            except TimeoutError:
                continue
            faults.check("feed.get", eof=chunk is None)
            tag = getattr(chunk, "meta", None)
            if tag is not None and tag[0] == "split":
                _kind, sid, seq, _nblocks = tag
                expected = self._split_next.get(sid, 0)
                if seq < expected:  # re-served prefix: already consumed
                    metrics_registry.inc(
                        "tfos_data_split_dup_chunks_total")
                    continue
                self._split_next[sid] = seq + 1
            break
        if t0 is not None:
            # ONE measurement feeds both layers (TrainMetrics.infeed_wait
            # and the telemetry span), so the stall fractions they report
            # agree by construction.
            dt = time.perf_counter() - t0
            self._wait_acc += dt
            if self.metrics is not None:
                self.metrics.infeed_wait(dt)
            # depth read once, shared by telemetry and the live plane
            qbytes = qchunks = None
            if telemetry.enabled() or metrics_registry.enabled():
                try:
                    if self._ring is not None:
                        qbytes = self._ring.qsize_bytes()
                    elif self._queue is not None:
                        qchunks = self._queue.qsize()
                except Exception:  # noqa: BLE001 - depth is best-effort
                    pass
            if telemetry.enabled():
                attrs = {"eof": chunk is None}
                if qbytes is not None:
                    attrs["queue_bytes"] = qbytes
                elif qchunks is not None:
                    attrs["queue_chunks"] = qchunks
                telemetry.record_span("feed/wait", dt, **attrs)
            if metrics_registry.enabled():
                metrics_registry.inc("tfos_feed_wait_seconds_total", dt)
                metrics_registry.inc("tfos_feed_chunks_total")
                try:
                    metrics_registry.inc("tfos_feed_records_total",
                                         len(chunk))
                except TypeError:  # None (eof) or a length-less marker
                    pass
                if qbytes is not None:
                    metrics_registry.set_gauge("tfos_feed_ring_bytes",
                                               qbytes)
                elif qchunks is not None:
                    metrics_registry.set_gauge("tfos_feed_queue_depth",
                                               qchunks)
        return chunk

    def _consumer_span(self, t0, out):
        """Per-pull ``data/stage`` span (stage ``fed_consumer``): the
        pull's wall time minus the transport wait accumulated by
        ``_get_chunk`` is the consumer's own assembly (slice/concat/
        stack) cost — the decomposition ``trace_merge``'s ``-- data --``
        section reports alongside the pipeline stages."""
        dur = time.perf_counter() - t0
        wait = min(self._wait_acc, dur)
        if isinstance(out, dict):
            n = len(next(iter(out.values()))) if out else 0
        else:
            n = len(out)
        telemetry.record_span("data/stage", max(dur - wait, 0.0),
                              stage="fed_consumer",
                              wait_ms=round(wait * 1e3, 3), records=n)

    def next_batch(self, batch_size):
        """Gather up to ``batch_size`` records (TFNode.py:243-288).

        Returns a list of records, or — with ``input_mapping`` — a dict of
        {tensor_name: list_of_column_values}.  A ``None`` chunk in the
        queue means end-of-feed; an ``EndPartition`` marker ends the batch
        early in inference mode so results stay partition-aligned.
        """
        if telemetry.enabled():
            t0 = time.perf_counter()
            self._wait_acc = 0.0
            out = self._next_batch(batch_size)
            self._consumer_span(t0, out)
            return out
        return self._next_batch(batch_size)

    def _next_batch(self, batch_size):
        logger.debug("next_batch(%d) invoked", batch_size)
        tensors = (
            [] if self.input_tensors is None else {t: [] for t in self.input_tensors}
        )
        count = 0

        def _append(record):
            nonlocal count
            if self.input_tensors is None:
                tensors.append(record)
            else:
                for i, t in enumerate(self.input_tensors):
                    tensors[t].append(record[i])
            count += 1

        def _take_columns(block):
            """Consume up to the batch remainder from a columnar chunk.

            With input_mapping, column slices extend the per-tensor lists
            directly — no per-record python loop (scalar columns extend
            with numpy scalars, width columns with row views, both of
            which np.asarray/np.stack handle in one memcpy downstream).
            n-D fields the feeder flattened (``chunk.shapes``) come back
            as reshape views, so each record sees its original shape.
            """
            nonlocal count
            chunk, off = block
            shapes = getattr(chunk, "shapes", None)
            take = min(batch_size - count, len(chunk) - off)
            if self.input_tensors is None:
                if shapes is not None:
                    cols = [
                        _sliced_column(chunk, i, off, take, shapes)
                        for i in range(len(chunk.columns))
                    ]

                    def _rowval(i, c, j):
                        # match columns_to_rows exactly: PYTHON scalars
                        # for 1-D columns, python lists for width
                        # columns (tolist, not list: list() would keep
                        # numpy scalar elements).  Shaped fields COPY:
                        # records from this path are independent objects
                        # a consumer may retain, and a view would pin
                        # the whole multi-MB chunk buffer per record
                        # (the mapping/columns paths keep views — their
                        # consumers collate immediately)
                        if shapes[i] is not None:
                            return c[j].copy()
                        return c[j].item() if c.ndim == 1 else c[j].tolist()

                    self._buffer.extend(
                        tuple(_rowval(i, c, j) for i, c in enumerate(cols))
                        for j in range(take))
                else:
                    from tensorflowonspark_tpu.recordio import marshal

                    self._buffer.extend(marshal.columns_to_rows(
                        [c[off:off + take] for c in chunk.columns]
                    ))
            else:
                for i, t in enumerate(self.input_tensors):
                    tensors[t].extend(
                        _sliced_column(chunk, i, off, take, shapes))
                count += take
            off += take
            return (chunk, off) if off < len(chunk) else None

        while count < batch_size:
            if self._buffer:
                _append(self._buffer.pop(0))
                continue
            if self._colblock is not None:
                self._colblock = _take_columns(self._colblock)
                continue
            chunk = self._get_chunk()
            if chunk is None:
                logger.info("next_batch() got None: end of feed")
                self.done_feeding = True
                break
            if isinstance(chunk, marker.EndPartition):
                logger.debug("next_batch() got EndPartition")
                if not self.train_mode and count > 0:
                    break
                continue
            if isinstance(chunk, marker.ColumnChunk):
                self._colblock = (chunk, 0)
                continue
            # chunk is a list of records (the batched redesign); tolerate a
            # stray single record for compatibility with hand-fed queues.
            if isinstance(chunk, list):
                self._buffer.extend(chunk)
            else:
                _append(chunk)
        return tensors

    def next_batch_columns(self, batch_size):
        """Gather up to ``batch_size`` records as DENSE per-tensor arrays:
        ``{tensor_name: ndarray[n, ...]}`` — the zero-python-loop consumer
        for columnar feeds (requires ``input_mapping``).

        ColumnChunk data is consumed as array SEGMENTS: an aligned chunk
        covering the whole batch passes through as a zero-copy view;
        spanning chunks cost one ``np.concatenate`` (a single memcpy) —
        vs ``next_batch`` + ``np.stack``'s per-record python loop over
        row views (~12k img/s single-threaded at 224px, PERF.md).  Row
        chunks from non-columnar feeders degrade gracefully to a
        per-segment ``np.stack``.  n-D fields flattened by the feeder
        (``ColumnChunk.shapes``) come back reshaped, views again.
        """
        if self.input_tensors is None:
            raise ValueError("next_batch_columns requires input_mapping")
        if telemetry.enabled():
            t0 = time.perf_counter()
            self._wait_acc = 0.0
            out = self._next_batch_columns(batch_size)
            self._consumer_span(t0, out)
            return out
        return self._next_batch_columns(batch_size)

    def _next_batch_columns(self, batch_size):
        import numpy as np

        segments = {t: [] for t in self.input_tensors}
        count = 0

        def _rows_segment(rows):
            nonlocal count
            for i, t in enumerate(self.input_tensors):
                segments[t].append(np.asarray([r[i] for r in rows]))
            count += len(rows)

        while count < batch_size:
            if self._buffer:
                take = min(batch_size - count, len(self._buffer))
                rows, self._buffer = (self._buffer[:take],
                                      self._buffer[take:])
                _rows_segment(rows)
                continue
            if self._colblock is not None:
                chunk, off = self._colblock
                shapes = getattr(chunk, "shapes", None)
                take = min(batch_size - count, len(chunk) - off)
                for i, t in enumerate(self.input_tensors):
                    segments[t].append(
                        _sliced_column(chunk, i, off, take, shapes))
                count += take
                off += take
                self._colblock = ((chunk, off) if off < len(chunk)
                                  else None)
                continue
            chunk = self._get_chunk()
            if chunk is None:
                logger.info("next_batch_columns() got None: end of feed")
                self.done_feeding = True
                break
            if isinstance(chunk, marker.EndPartition):
                if not self.train_mode and count > 0:
                    break
                continue
            if isinstance(chunk, marker.ColumnChunk):
                self._colblock = (chunk, 0)
                continue
            if isinstance(chunk, list):
                self._buffer.extend(chunk)
            else:
                _rows_segment([chunk])
        out = {}
        for t in self.input_tensors:
            parts = segments[t]
            if not parts:
                # honor the dense contract even for an empty pull: use
                # the dtype/trailing-shape last seen for this tensor so
                # callers can concatenate tails without rank/dtype traps
                dtype, trail = self._col_meta.get(t, (None, ()))
                out[t] = np.empty((0,) + tuple(trail), dtype=dtype)
            elif len(parts) == 1:
                out[t] = parts[0]  # aligned chunk: zero copy
            else:
                out[t] = np.concatenate(parts, axis=0)
            if len(out[t]):
                self._col_meta[t] = (out[t].dtype, out[t].shape[1:])
        return out

    def should_stop(self):
        """True once the feeder pushed the end-of-feed None (TFNode.py:290)."""
        return self.done_feeding

    def batch_results(self, results):
        """Push one batch of inference results (TFNode.py:294-305)."""
        queue = self.mgr.get_queue(self.qname_out)
        queue.put(list(results))

    def poison(self):
        """End the feed for its consumer without the producer handshake:
        the next _get_chunk poll reports end-of-feed.  Used when a
        prefetch worker is abandoned mid-stream (infeed.py) so the orphan
        thread exits within one poll slice instead of polling forever;
        the ring stays single-consumer and terminate() may still run the
        full producer drain afterwards."""
        self._stop_requested = True

    def terminate(self):
        """Request early stop and drain the input queue (TFNode.py:307-329).

        Sets state to 'terminating' so feeder tasks that land later skip
        straight to draining; then empties what is already queued so the
        producer's queue.join() returns.  Safe to call while another
        thread (e.g. the infeed prefetcher) is blocked in next_batch: the
        stop flag turns that thread's pending get into end-of-feed, and
        all pops here go through the same per-attempt lock, so the
        single-consumer ring never sees two concurrent readers.

        Ring path: "drained" is decided by the producer flock, not a
        timeout — an empty ring only ends the drain once no feeder holds
        the producer lock, so a slow producer mid-partition cannot strand
        data (and its _await_consumption) behind a 5s guess.
        """
        logger.info("terminate() invoked")
        self._stop_requested = True
        self.mgr.set("state", "terminating")
        if self._ring is not None:
            from tensorflowonspark_tpu.recordio import shm as shmq

            empty_checks = 0
            while True:
                try:
                    if self._get_once(timeout_ms=1000) is None:
                        break  # producer closed the ring: EOF
                    empty_checks = 0
                except TimeoutError:
                    if (self._ring.qsize_bytes() == 0
                            and not shmq.producer_active(self._ring.name)):
                        empty_checks += 1
                        if empty_checks >= 2:
                            break
            return
        while True:
            try:
                self._get_once(timeout_ms=5000)
            except Exception:  # noqa: BLE001 - Empty/Timeout/dead manager
                # = fully drained: a manager already torn down at job end
                # must not crash an otherwise-successful terminate
                break


def start_cluster_server(ctx, num_gpus=1, rdma=False):
    """Deprecated TF1-era API (TFNode.py:67-151): in the reference this
    started a tf.train.Server on the reserved port.  TPU-native jobs have
    no per-node gRPC server; joining the cluster is ctx.jax_initialize().
    Kept so ported main_funs run; returns an object with a .target-like
    coordinator address.
    """
    import warnings

    warnings.warn(
        "start_cluster_server is deprecated; use ctx.jax_initialize()",
        DeprecationWarning,
        stacklevel=2,
    )
    env = ctx.jax_initialize()

    class _Server:  # minimal tf.train.Server stand-in
        target = env.get("coordinator_address")

        @staticmethod
        def join():
            raise RuntimeError(
                "server.join() has no TPU equivalent; ps-style blocking is "
                "handled by the framework's control queue"
            )

    return _Server()


def export_saved_model(sess=None, export_dir=None, tag_set=None,
                       signatures=None, params=None, ctx=None,
                       metadata=None):
    """Deprecated TF1-era export (TFNode.py:159-208).  The TPU-native
    export is utils.checkpoint.export_model(export_dir, params, ctx);
    this shim forwards to it (chief-only contract preserved)."""
    import warnings

    from tensorflowonspark_tpu.utils import checkpoint as _ckpt

    warnings.warn(
        "TFNode.export_saved_model is deprecated; use "
        "utils.checkpoint.export_model",
        DeprecationWarning,
        stacklevel=2,
    )
    assert export_dir is not None and params is not None
    return _ckpt.export_model(export_dir, params, ctx, metadata=metadata)
