"""Serving frontend: in-process Client, stdlib HTTP endpoint, SLO stats.

No reference equivalent (the reference's only inference surface is the
spark-submit batch CLI, Inference.scala:27-79 → our inference.py); this
is the online half, mirroring that CLI's conventions as the
``tfos-serve`` console entry point.

Composition: ``Server`` = :class:`~.replicas.ReplicaPool` (supervised
model replicas) + :class:`~.batcher.MicroBatcher` (request coalescing)
+ :class:`SLOStats` (latency percentiles, shed rate, device-batch
sizes).  Every completed request is recorded as a
``telemetry.SERVE_REQUEST`` span carrying ``queue_ms`` /
``batch_ms`` / ``device_ms`` attrs; every load-shed rejection is a
``telemetry.SERVE_SHED`` event — ``scripts/trace_merge.py`` summarizes
both into p50/p95/p99 and shed-rate.

Admission control semantics (docs/serving.md): past
``TFOS_SERVE_QUEUE_MAX`` pending requests, ``predict`` raises
:class:`~.batcher.Overloaded`; the HTTP frontend maps it to
``503`` + ``Retry-After``.  Shed requests are *rejected*, never
silently dropped — a client always gets an answer or an explicit error.
"""

from __future__ import annotations

import argparse
import itertools
import json
import logging
import random
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

import numpy as np

from tensorflowonspark_tpu.serving import batcher as _batcher
from tensorflowonspark_tpu.serving.batcher import MicroBatcher, Overloaded
from tensorflowonspark_tpu.serving.decode import sampling as _sampling
from tensorflowonspark_tpu.serving.decode import scheduler as _decode
from tensorflowonspark_tpu.serving.replicas import ModelSpec, ReplicaPool
from tensorflowonspark_tpu.utils import metrics_registry, telemetry

logger = logging.getLogger(__name__)


def _pct(sorted_vals, q):
    """Nearest-rank percentile (same convention as scripts/trace_merge)."""
    if not sorted_vals:
        return 0.0
    idx = min(len(sorted_vals) - 1, max(0, round(q * (len(sorted_vals) - 1))))
    return sorted_vals[idx]


class SLOStats:
    """Thread-safe request/batch/shed counters + latency percentiles."""

    def __init__(self, sample_cap=100_000):
        self._lock = threading.Lock()
        self._cap = sample_cap
        self.total_ms = []
        self.queue_ms = []
        self.device_ms = []
        self.completed = 0
        self.shed = 0
        self.errors = 0
        self.batches = 0
        self.batch_rows = 0
        self.buckets = set()

    def observe_request(self, attrs):
        with self._lock:
            self.completed += 1
            if len(self.total_ms) < self._cap:
                self.total_ms.append(attrs["total_ms"])
                self.queue_ms.append(attrs["queue_ms"])
                self.device_ms.append(attrs["device_ms"])

    def observe_batch(self, batch, meta):
        del meta
        with self._lock:
            self.batches += 1
            self.batch_rows += batch.n_valid
            self.buckets.add(batch.bucket)

    def observe_shed(self):
        with self._lock:
            self.shed += 1

    def observe_error(self):
        with self._lock:
            self.errors += 1

    def summary(self):
        with self._lock:
            totals = sorted(self.total_ms)
            queues = sorted(self.queue_ms)
            devices = sorted(self.device_ms)
            completed, shed, errors = self.completed, self.shed, self.errors
            batches, rows = self.batches, self.batch_rows
            buckets = sorted(self.buckets)
        seen = completed + shed + errors
        return {
            "requests": seen,
            "completed": completed,
            "shed": shed,
            "errors": errors,
            "shed_rate": round(shed / seen, 4) if seen else 0.0,
            "p50_ms": round(_pct(totals, 0.50), 3),
            "p95_ms": round(_pct(totals, 0.95), 3),
            "p99_ms": round(_pct(totals, 0.99), 3),
            "mean_queue_ms": (round(sum(queues) / len(queues), 3)
                              if queues else 0.0),
            "mean_device_ms": (round(sum(devices) / len(devices), 3)
                               if devices else 0.0),
            "batches": batches,
            "mean_device_batch": (round(rows / batches, 2)
                                  if batches else 0.0),
            "buckets": buckets,
        }


class DecodeStats:
    """Thread-safe decode-session counters + TTFT / per-token
    percentiles (docs/serving.md "Autoregressive decode").

    TTFT (time to first token) and per-token gap are the two decode
    SLOs; total-latency percentiles alone hide a slow-start server
    behind a fast steady state and vice versa.
    """

    def __init__(self, sample_cap=100_000):
        self._lock = threading.Lock()
        self._cap = sample_cap
        self.ttft_ms = []
        self.token_ms = []
        self.completed = 0
        self.shed = 0
        self.errors = 0
        self.tokens = 0

    def observe_session(self, result):
        with self._lock:
            self.completed += 1
            self.tokens += len(result.get("tokens") or ())
            if result.get("ttft_ms") is not None \
                    and len(self.ttft_ms) < self._cap:
                self.ttft_ms.append(result["ttft_ms"])
            if len(self.token_ms) < self._cap:
                self.token_ms.extend(result.get("token_ms") or ())

    def observe_shed(self):
        with self._lock:
            self.shed += 1

    def observe_error(self):
        with self._lock:
            self.errors += 1

    def summary(self):
        with self._lock:
            ttft = sorted(self.ttft_ms)
            gaps = sorted(self.token_ms)
            completed, shed, errors = self.completed, self.shed, self.errors
            tokens = self.tokens
        seen = completed + shed + errors
        return {
            "sessions": seen,
            "completed": completed,
            "shed": shed,
            "errors": errors,
            "tokens": tokens,
            "ttft_p50_ms": round(_pct(ttft, 0.50), 3),
            "ttft_p99_ms": round(_pct(ttft, 0.99), 3),
            "tok_p50_ms": round(_pct(gaps, 0.50), 3),
            "tok_p99_ms": round(_pct(gaps, 0.99), 3),
        }


class Server:
    """An online model service over the cluster runtime.

    Usage (in-process)::

        spec = ModelSpec(export_dir=..., ckpt_dir=...)
        srv = Server(spec, num_replicas=2).start()
        row = srv.predict({"image": x})     # {tensor_name: ndarray}
        srv.stop()

    or over HTTP: ``serve_http(srv, port=8500)`` / the ``tfos-serve``
    CLI.  ``engine=`` reuses an existing LocalEngine (e.g.
    ``TFCluster.serve``); otherwise the server owns a fresh one sized to
    ``num_replicas``.
    """

    def __init__(self, spec, num_replicas=None, max_batch=None,
                 max_delay_ms=None, queue_max=None, engine=None, env=None,
                 request_timeout=None, decode_queue_max=None,
                 seq_axis=None, seq_cap=None, elastic=False,
                 logical_replicas=None, fabric=False, fabric_hosts=None,
                 replicas_per_host=None, autoscale=False):
        self.spec = spec
        self.stats = SLOStats()
        self.decode_stats = DecodeStats()
        self.request_timeout = (request_timeout
                                or _batcher.request_timeout_default())
        self.decode_queue_max = (decode_queue_max
                                 or _decode.queue_max_default())
        # decode admission scales with elastic pool capacity the same
        # way the batcher's queue bound does (docs/serving.md "Degrade
        # by resize"); 1.0 until the pool reports otherwise
        self._decode_capacity = 1.0
        if fabric or fabric_hosts:
            # pod-scale fabric: multi-host dispatch + session-affinity
            # routing + optional autoscaling (docs/serving.md
            # "Pod-scale fabric")
            from tensorflowonspark_tpu.serving.fabric import FabricRouter

            self.pool = FabricRouter(
                spec, num_hosts=fabric_hosts,
                replicas_per_host=replicas_per_host or 1,
                engine=engine, env=env,
                request_timeout=self.request_timeout,
                autoscale=autoscale)
        elif elastic or logical_replicas:
            from tensorflowonspark_tpu.serving.elastic import (
                ElasticReplicaPool,
            )

            self.pool = ElasticReplicaPool(
                spec, num_replicas=num_replicas,
                logical_replicas=logical_replicas, engine=engine, env=env,
                request_timeout=self.request_timeout,
                on_capacity=self._on_capacity)
        else:
            self.pool = ReplicaPool(
                spec, num_replicas=num_replicas, engine=engine, env=env,
                request_timeout=self.request_timeout)
        self.batcher = MicroBatcher(
            self.pool.dispatch, max_batch=max_batch,
            max_delay_ms=max_delay_ms, queue_max=queue_max,
            observer=self._on_request, batch_observer=self._on_batch,
            on_shed=self._on_shed, seq_axis=seq_axis, seq_cap=seq_cap)
        self._session_ids = itertools.count(1)
        self._stopped = False

    # -- observers (batcher -> stats + telemetry + live metrics) ------------
    def _on_request(self, attrs):
        self.stats.observe_request(attrs)
        metrics_registry.inc("tfos_serve_requests_total", status="ok")
        metrics_registry.observe("tfos_serve_request_ms", attrs["total_ms"])
        span_attrs = dict(
            queue_ms=round(attrs["queue_ms"], 3),
            batch_ms=round(attrs["batch_ms"], 3),
            device_ms=round(attrs["device_ms"], 3),
            batch=attrs["batch"], bucket=attrs["bucket"])
        # version-tagged spans: trace_merge and /statusz split request
        # telemetry by the params version that answered (canary rollouts)
        if "version" in attrs:
            span_attrs["version"] = attrs["version"]
        if "replica" in attrs:
            span_attrs["replica"] = attrs["replica"]
        telemetry.record_span(
            telemetry.SERVE_REQUEST, attrs["total_ms"] / 1e3, **span_attrs)

    def _on_batch(self, batch, meta):
        self.stats.observe_batch(batch, meta)
        metrics_registry.inc("tfos_serve_batches_total")
        metrics_registry.inc("tfos_serve_batch_rows_total", batch.n_valid)

    def _on_shed(self, depth, limit):
        self.stats.observe_shed()
        metrics_registry.inc("tfos_serve_requests_total", status="shed")
        telemetry.event(telemetry.SERVE_SHED, depth=depth, limit=limit)

    def _on_capacity(self, frac, generation, degraded):
        """Elastic pool capacity hook: the declared degraded mode —
        admission shrinks with the pool, sheds stay explicit."""
        self.batcher.set_capacity(frac)
        self._decode_capacity = frac
        telemetry.event("serve/capacity", capacity=round(frac, 4),
                        generation=generation, degraded=degraded)

    # -- lifecycle ----------------------------------------------------------
    def start(self, timeout=180.0):
        self.pool.start(timeout=timeout)
        self.batcher.start()
        return self

    def stop(self):
        if self._stopped:
            return
        self._stopped = True
        self.batcher.close()
        self.pool.stop()
        telemetry.flush()

    def __enter__(self):
        return self.start()

    def __exit__(self, *exc):
        self.stop()
        return False

    # -- request path -------------------------------------------------------
    def predict(self, example, timeout=None, trace=None):
        """Serve one example ({tensor_name: array-like}, no batch axis);
        returns the outputs row.  Raises Overloaded on load shed,
        TimeoutError past ``timeout`` (default TFOS_SERVE_TIMEOUT).

        ``trace`` is an optional W3C-traceparent string (or
        :class:`~..utils.telemetry.TraceContext`) linking this request
        into a caller's trace; without one a fresh root is minted
        (docs/telemetry.md "Causal tracing")."""
        with telemetry.trace_span(telemetry.SERVE_PREDICT, header=trace):
            req = self.batcher.submit(example)
            try:
                return req.result(timeout or self.request_timeout)
            except Overloaded:
                raise
            except Exception:
                self.stats.observe_error()
                metrics_registry.inc("tfos_serve_requests_total",
                                     status="error")
                raise

    def generate(self, prompt, max_tokens=None, eos_id=None, timeout=None,
                 temperature=None, top_k=None, top_p=None, seed=None,
                 trace=None, route_id=None):
        """One autoregressive decode session: ``prompt`` is a list of
        int token ids; returns ``{"tokens": [...], "ttft_ms", "token_ms"
        (per-token gaps), "total_ms", ...engine meta}``.

        ``route_id`` is an opaque session-affinity key: with a fabric
        pool, requests sharing a route id land on the replica whose
        paged KV cache still holds their prefix blocks (docs/serving.md
        "Pod-scale fabric"); the result meta then carries the routing
        outcome under ``"affinity"`` (hit/miss/fallback).  Other pools
        ignore it.

        ``trace`` optionally links the session into a caller's trace
        (W3C-traceparent string or TraceContext); the context is
        carried inside the dispatch blob so replica-side decode spans
        join the same tree (docs/telemetry.md "Causal tracing").

        Sampling: ``temperature > 0`` switches the session from greedy
        argmax to seeded sampling (``top_k``/``top_p`` optional).  The
        seed is resolved HERE (random when unset) so the dispatch blob
        carries it: a failover replay re-draws the identical token
        stream (decode/sampling.py).  Out-of-range sampling values and
        invalid prompts raise ValueError (HTTP 400) before dispatch —
        an oversized prompt is a client error, never a replica-side
        crash or a shed.

        Admission control mirrors ``predict``: past
        ``TFOS_DECODE_QUEUE_MAX`` outstanding sessions, raises
        :class:`~.batcher.Overloaded` (HTTP maps it to 503 +
        Retry-After).  The session survives replica SIGKILL — the pool
        re-prefills it on a survivor, and the resolve-once ledger
        guarantees zero dropped / zero duplicated tokens.
        """
        if self.spec.decode is None:
            raise RuntimeError("spec has no decode engine; pass "
                               "ModelSpec(..., decode=DecodeSpec(...))")
        prompt = [int(t) for t in prompt]
        max_seq = self.spec.decode.cfg.max_seq
        if not prompt or len(prompt) > max_seq - 1:
            raise ValueError(
                f"prompt length {len(prompt)} not in [1, {max_seq - 1}] "
                f"(max_seq {max_seq})")
        if seed is None and temperature is not None and temperature > 0:
            seed = random.getrandbits(31)
        sampling = _sampling.make(temperature=temperature, top_k=top_k,
                                  top_p=top_p, seed=seed)
        with telemetry.trace_span(telemetry.SERVE_GENERATE, header=trace,
                                  prompt_len=len(prompt)):
            return self._generate_traced(prompt, max_tokens, eos_id,
                                         timeout, sampling, route_id)

    def _generate_traced(self, prompt, max_tokens, eos_id, timeout,
                         sampling, route_id=None):
        depth = self.pool.outstanding_sessions()
        limit = max(1, int(round(self.decode_queue_max
                                 * self._decode_capacity))) \
            if self._decode_capacity > 0 else 0
        if depth >= limit:
            self.decode_stats.observe_shed()
            metrics_registry.inc("tfos_decode_sessions_total", status="shed")
            telemetry.event(telemetry.DECODE_SHED, depth=depth, limit=limit)
            raise Overloaded(depth, limit,
                             retry_after=0.25 if self._decode_capacity < 1.0
                             else 0.1)
        ctx = telemetry.current()
        session = _decode.PendingSession(
            next(self._session_ids), prompt,
            max_tokens or (self.spec.decode.max_tokens
                           if self.spec.decode else None)
            or _decode.max_tokens_default(),
            self.spec.decode.eos_id if eos_id is None else eos_id,
            sampling=sampling,
            trace=ctx.to_header() if ctx is not None else None,
            route_id=None if route_id is None else str(route_id))
        self.pool.dispatch_session(session)
        try:
            out = session.result(timeout or self.request_timeout)
        except Overloaded:
            raise
        except Exception:
            self.pool.cancel_session(session.id)
            self.decode_stats.observe_error()
            metrics_registry.inc("tfos_decode_sessions_total",
                                 status="error")
            raise
        self.decode_stats.observe_session(out)
        metrics_registry.inc("tfos_decode_sessions_total", status="ok")
        metrics_registry.inc("tfos_decode_tokens_total",
                             len(out.get("tokens") or ()))
        if out.get("ttft_ms") is not None:
            metrics_registry.observe("tfos_decode_ttft_ms", out["ttft_ms"])
        for gap in out.get("token_ms") or ():
            metrics_registry.observe("tfos_decode_token_ms", gap)
        telemetry.record_span(
            telemetry.DECODE_SESSION, out["total_ms"] / 1e3,
            tokens=len(out.get("tokens") or ()),
            ttft_ms=out.get("ttft_ms"), replica=out.get("replica"))
        return out

    def client(self):
        return Client(self)

    def summary(self, include_replicas=False):
        """One JSON-able dict of SLO metrics (+ per-replica predictor
        stats when asked — a live round-trip to every replica)."""
        out = self.stats.summary()
        out["replicas"] = self.pool.live_replicas()
        out["versions"] = self.pool.versions()
        if self.spec.decode is not None:
            out["decode"] = self.decode_stats.summary()
        if hasattr(self.pool, "describe"):
            out["pool"] = self.pool.describe()
        if include_replicas:
            out["replica_stats"] = self.pool.stats()
        return out


class Client:
    """In-process client handle (the test-facing 'connection')."""

    def __init__(self, server):
        self._server = server

    def predict(self, example, timeout=None, trace=None):
        return self._server.predict(example, timeout=timeout, trace=trace)

    def generate(self, prompt, max_tokens=None, eos_id=None, timeout=None,
                 temperature=None, top_k=None, top_p=None, seed=None,
                 trace=None):
        return self._server.generate(prompt, max_tokens=max_tokens,
                                     eos_id=eos_id, timeout=timeout,
                                     temperature=temperature, top_k=top_k,
                                     top_p=top_p, seed=seed, trace=trace)


# ---------------------------------------------------------------------------
# HTTP frontend (stdlib http.server; one thread per connection)
# ---------------------------------------------------------------------------

class _Handler(BaseHTTPRequestHandler):
    server_version = "tfos-serve/0.1"

    def log_message(self, fmt, *args):  # route to logging, not stderr
        logger.debug("http: " + fmt, *args)

    def _reply(self, code, payload, headers=None):
        body = json.dumps(payload).encode()
        self.send_response(code)
        self.send_header("Content-Type", "application/json")
        self.send_header("Content-Length", str(len(body)))
        for k, v in (headers or {}).items():
            self.send_header(k, v)
        self.end_headers()
        self.wfile.write(body)

    def do_GET(self):
        srv = self.server.tfos_server
        if self.path == "/healthz":
            live = srv.pool.live_replicas()
            code = 200 if live else 503
            # an elastic pool below logical capacity is alive-but-
            # degraded: still 200 (load balancers keep routing), status
            # says so, and the generation/capacity ride along
            degraded = (not live) or getattr(srv.pool, "degraded", False)
            body = {"status": "degraded" if degraded else "ok",
                    "replicas": live}
            if hasattr(srv.pool, "generation"):
                body["generation"] = srv.pool.generation
                body["capacity"] = round(srv.pool.capacity_frac, 4)
            self._reply(code, body)
        elif self.path == "/stats":
            self._reply(200, srv.summary())
        else:
            self._reply(404, {"error": f"no route {self.path}"})

    def do_POST(self):
        srv = self.server.tfos_server
        if self.path == "/v1/generate":
            self._do_generate(srv)
            return
        if self.path != "/v1/predict":
            self._reply(404, {"error": f"no route {self.path}"})
            return
        try:
            length = int(self.headers.get("Content-Length", 0))
            payload = json.loads(self.rfile.read(length) or b"{}")
            inputs = payload.get("inputs")
            if not isinstance(inputs, dict) or not inputs:
                raise ValueError('body must be {"inputs": {name: values}}')
            example = {k: np.asarray(v) for k, v in inputs.items()}
        except (ValueError, TypeError) as e:
            self._reply(400, {"error": str(e)})
            return
        try:
            row = srv.predict(example,
                              trace=self.headers.get("traceparent"))
        except Overloaded as e:
            # explicit load shed: 503 + retry-after (docs/serving.md)
            self._reply(503, {"error": "overloaded",
                              "retry_after": round(e.retry_after, 3)},
                        headers={"Retry-After": f"{e.retry_after:.3f}"})
            return
        except TimeoutError as e:
            self._reply(504, {"error": str(e)})
            return
        except Exception as e:  # noqa: BLE001 - surface, don't crash
            self._reply(500, {"error": repr(e)})
            return
        self._reply(200, {
            "outputs": {k: np.asarray(v).tolist() for k, v in row.items()}
        })

    def _do_generate(self, srv):
        """POST /v1/generate: ``{"prompt": [ids], "max_tokens"?,
        "eos_id"?, "temperature"?, "top_k"?, "top_p"?, "seed"?,
        "route_id"?}`` -> the session result dict (docs/serving.md).
        ``route_id`` is the session-affinity key a fabric pool routes
        on.  Oversized prompts and out-of-range sampling knobs are
        client errors (400), never replica-side crashes."""
        try:
            length = int(self.headers.get("Content-Length", 0))
            payload = json.loads(self.rfile.read(length) or b"{}")
            prompt = payload.get("prompt")
            if not isinstance(prompt, list) or not prompt:
                raise ValueError(
                    'body must be {"prompt": [token ids], ...}')
            prompt = [int(t) for t in prompt]
        except (ValueError, TypeError) as e:
            self._reply(400, {"error": str(e)})
            return
        try:
            out = srv.generate(prompt,
                               max_tokens=payload.get("max_tokens"),
                               eos_id=payload.get("eos_id"),
                               temperature=payload.get("temperature"),
                               top_k=payload.get("top_k"),
                               top_p=payload.get("top_p"),
                               seed=payload.get("seed"),
                               trace=self.headers.get("traceparent"),
                               route_id=payload.get("route_id"))
        except ValueError as e:
            # oversized/empty prompt, bad sampling range: client error
            self._reply(400, {"error": str(e)})
            return
        except Overloaded as e:
            self._reply(503, {"error": "overloaded",
                              "retry_after": round(e.retry_after, 3)},
                        headers={"Retry-After": f"{e.retry_after:.3f}"})
            return
        except TimeoutError as e:
            self._reply(504, {"error": str(e)})
            return
        except Exception as e:  # noqa: BLE001 - surface, don't crash
            self._reply(500, {"error": repr(e)})
            return
        self._reply(200, out)


def serve_http(server, host="127.0.0.1", port=8500, block=True):
    """Expose ``server`` over HTTP.  ``block=False`` runs the listener on
    a daemon thread and returns the ``ThreadingHTTPServer`` (tests use
    its ``.server_address`` for the ephemeral port)."""
    httpd = ThreadingHTTPServer((host, port), _Handler)
    httpd.daemon_threads = True
    httpd.tfos_server = server
    if block:
        httpd.serve_forever()
        return httpd
    t = threading.Thread(target=httpd.serve_forever,
                         name="tfos-serve-http", daemon=True)
    t.start()
    return httpd


# ---------------------------------------------------------------------------
# CLI (console entry point: tfos-serve, mirroring tfos-inference)
# ---------------------------------------------------------------------------

def build_parser():
    p = argparse.ArgumentParser(
        prog="tfos-serve",
        description="Online inference serving for an exported model",
    )
    p.add_argument("--export_dir", default=None,
                   help="export directory (utils.checkpoint.export_model)")
    p.add_argument("--ckpt_dir", default=None,
                   help="checkpoint dir to hot-reload params from")
    p.add_argument("--signature_def_key", default=None,
                   help="module:function predict override")
    p.add_argument("--num_replicas", type=int, default=None,
                   help=f"model replicas (default ${'{'}TFOS_SERVE_REPLICAS{'}'} or 2)")
    p.add_argument("--host", default="127.0.0.1")
    p.add_argument("--port", type=int, default=8500)
    p.add_argument("--max_batch", type=int, default=None)
    p.add_argument("--max_delay_ms", type=float, default=None)
    p.add_argument("--queue_max", type=int, default=None)
    p.add_argument("--elastic", action="store_true",
                   help="degrade-by-resize pool (docs/serving.md "
                        "'Degrade by resize')")
    p.add_argument("--logical_replicas", type=int, default=None,
                   help="logical capacity for --elastic "
                        "(default: num_replicas)")
    p.add_argument("--fabric", action="store_true",
                   help="pod-scale fabric pool: multi-host dispatch + "
                        "session-affinity routing (docs/serving.md "
                        "'Pod-scale fabric')")
    p.add_argument("--fabric_hosts", type=int, default=None,
                   help="fabric host processes "
                        f"(default ${'{'}TFOS_FABRIC_HOSTS{'}'} or 2)")
    p.add_argument("--replicas_per_host", type=int, default=None,
                   help="initial replicas per fabric host (default 1)")
    p.add_argument("--autoscale", action="store_true",
                   help="run the ServeAutoscaler over the fabric "
                        "(TFOS_SERVE_MIN/MAX_REPLICAS clamp per host)")
    return p


def main(argv=None):
    logging.basicConfig(level=logging.INFO)
    args = build_parser().parse_args(argv)
    if not args.export_dir and not args.ckpt_dir:
        build_parser().error("--export_dir or --ckpt_dir is required")
    spec = ModelSpec(export_dir=args.export_dir, ckpt_dir=args.ckpt_dir,
                     predict=args.signature_def_key)
    server = Server(spec, num_replicas=args.num_replicas,
                    max_batch=args.max_batch,
                    max_delay_ms=args.max_delay_ms,
                    queue_max=args.queue_max,
                    elastic=args.elastic,
                    logical_replicas=args.logical_replicas,
                    fabric=args.fabric,
                    fabric_hosts=args.fabric_hosts,
                    replicas_per_host=args.replicas_per_host,
                    autoscale=args.autoscale)
    server.start()
    logger.info("serving on http://%s:%d (POST /v1/predict)",
                args.host, args.port)
    try:
        serve_http(server, host=args.host, port=args.port, block=True)
    except KeyboardInterrupt:
        pass
    finally:
        server.stop()


if __name__ == "__main__":
    main()
