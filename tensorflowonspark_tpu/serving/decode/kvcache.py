"""Slot- and block-paged KV caches for continuous-batching decode.

No reference counterpart (the reference delegates all inference to TF
Serving, SURVEY.md §2.2; reference Inference.scala:27-79 is offline
batch only).  Two tiers:

:class:`SlotKVCache` — vLLM-style paging simplified to one page per
session: two preallocated ``[slots, n_layers, n_heads, max_seq,
head_dim]`` arrays (keys cached rope-rotated) plus a per-slot length
cursor.  Admission/retirement are O(1) (pop/push a free slot) and the
fused ``models/transformer.decode_step`` always sees the same
``[slots, ...]`` arrays, so it compiles exactly once.

:class:`PagedKVCache` — full block paging with ref-counted prefix
sharing: the pool is ``[num_blocks, n_layers, n_heads, block_size,
head_dim]`` and each slot maps logical positions through a per-slot
block-table row (``models/transformer.decode_step_paged`` gathers
through it).  Blocks carry refcounts, so admission can map a new
request's matched prompt-prefix blocks from the :class:`PrefixTrie`
(bumping refcounts) instead of re-prefilling them — only the unmatched
tail is prefilled, and tail writes always land in session-private
blocks because trie matches are whole-block (copy-on-write by block
alignment, never in place).  Retired sessions decref; blocks a trie
path still references stay resident for future hits and are reclaimed
LRU-leaf-first only when allocation would otherwise fail.

Physical block 0 is a reserved SENTINEL: free slots' table rows point
at it, so their numerically-inert writes (and the padded rows of a
bucketed ``prefill_extend``) land in a block no live session ever
attends to — the paged analogue of SlotKVCache's stale-own-page
contract.  Capacity is validated so live sessions can never be starved:
``num_blocks - 1 >= slots * blocks_per_slot`` and everything above the
sentinel that is not session-referenced is trie-reclaimable.

jax is imported lazily: the classes are instantiated replica-side only
(scheduler.DecodeEngine); the driver half of serving never pulls jax.
"""

from __future__ import annotations

import numpy as np


class CacheOOM(RuntimeError):
    """Block allocation failed even after trie reclamation."""


class SlotKVCache:
    """Preallocated per-slot K/V pages + host-side cursor/free-list."""

    def __init__(self, cfg, slots, max_seq=None, dtype=None):
        import jax.numpy as jnp

        self.slots = int(slots)
        if self.slots < 1:
            raise ValueError("need at least one slot")
        self.max_seq = int(max_seq or cfg.max_seq)
        self.dtype = dtype or cfg.compute_dtype
        shape = (self.slots, cfg.n_layers, cfg.n_heads, self.max_seq,
                 cfg.head_dim)
        self.k = jnp.zeros(shape, self.dtype)
        self.v = jnp.zeros(shape, self.dtype)
        # host mirrors: the scheduler reads/writes these every iteration
        # without a device round-trip
        self.lengths = np.zeros((self.slots,), np.int32)
        self._free = list(range(self.slots - 1, -1, -1))  # pop() -> slot 0

    # -- slot lifecycle -----------------------------------------------------
    def alloc(self):
        """A free slot index, or None when the cache is full."""
        return self._free.pop() if self._free else None

    def retire(self, slot):
        """Return ``slot`` to the free list (cursor back to 0; the page
        itself is left stale — see the inertness contract above)."""
        if slot in self._free:
            raise ValueError(f"slot {slot} is already free")
        self.lengths[slot] = 0
        self._free.append(slot)

    def insert(self, slot, k, v, length):
        """Install a prefill result: ``k``/``v``
        [n_layers, n_heads, T, head_dim] into ``slot``'s first T
        columns, cursor to ``length`` (<= T <= max_seq)."""
        t = k.shape[2]
        if t > self.max_seq:
            raise ValueError(f"prefill length {t} > max_seq {self.max_seq}")
        self.k = self.k.at[slot, :, :, :t, :].set(k.astype(self.dtype))
        self.v = self.v.at[slot, :, :, :t, :].set(v.astype(self.dtype))
        self.lengths[slot] = int(length)

    # -- introspection ------------------------------------------------------
    @property
    def occupancy(self):
        return self.slots - len(self._free)

    @property
    def free_slots(self):
        return len(self._free)


class _TrieNode:
    __slots__ = ("children", "block", "tick")

    def __init__(self, block, tick):
        self.children = {}      # block-token tuple -> _TrieNode
        self.block = int(block)
        self.tick = tick


class PrefixTrie:
    """Prompt-prefix index over resident KV blocks.

    Keys are whole blocks of prompt tokens (tuples of ``block_size``
    ints), so a match is always block-aligned — the property that lets
    a matching session map the physical blocks directly (the KV of a
    prompt position depends only on the tokens at and before it, and
    keys are cached post-rope, so identical prompt blocks at identical
    positions have identical cache content).  Each node holds ONE
    refcount on its physical block (taken at insert, dropped at evict);
    session references stack on top, so ``refcount == 1`` means
    "trie-only" — the reclaimable state.

    Host-side bookkeeping only; the trie never touches device arrays.
    """

    def __init__(self, block_size):
        self.block_size = int(block_size)
        self.root = {}          # block-token tuple -> _TrieNode
        self._tick = 0
        self.nodes = 0

    def _blocks_of(self, tokens, limit=None):
        bs = self.block_size
        n = len(tokens) // bs if limit is None else min(
            len(tokens) // bs, limit)
        return [tuple(int(t) for t in tokens[i * bs:(i + 1) * bs])
                for i in range(n)]

    def match(self, tokens, limit=None):
        """Physical block ids of the longest resident whole-block
        prefix of ``tokens`` (at most ``limit`` blocks); touches the
        matched path's LRU ticks."""
        self._tick += 1
        out, children = [], self.root
        for key in self._blocks_of(tokens, limit):
            node = children.get(key)
            if node is None:
                break
            node.tick = self._tick
            out.append(node.block)
            children = node.children
        return out

    def insert(self, tokens, phys_blocks, incref):
        """Register ``tokens``' whole-block prefix as resident in
        ``phys_blocks`` (one id per block).  Existing nodes keep their
        own (content-identical) blocks; each NEWLY created node calls
        ``incref(block)`` to take the trie's reference."""
        self._tick += 1
        children = self.root
        for key, block in zip(self._blocks_of(tokens), phys_blocks):
            node = children.get(key)
            if node is None:
                node = _TrieNode(block, self._tick)
                children[key] = node
                self.nodes += 1
                incref(node.block)
            else:
                node.tick = self._tick
            children = node.children

    def reclaim(self, need, refcount, release):
        """Evict least-recently-matched leaf nodes whose blocks are
        trie-only (``refcount[block] == 1``) until ``need`` blocks were
        released or nothing else is evictable.  Returns the count
        released.  Evicting a leaf may expose its parent as the next
        candidate, so the scan loops to fixpoint."""
        freed = 0
        while freed < need:
            best = None  # (tick, parent_children, key, node)
            stack = [self.root]
            while stack:
                children = stack.pop()
                for key, node in children.items():
                    if node.children:
                        stack.append(node.children)
                    elif refcount[node.block] == 1 and (
                            best is None or node.tick < best[0]):
                        best = (node.tick, children, key, node)
            if best is None:
                return freed
            _, children, key, node = best
            del children[key]
            self.nodes -= 1
            release(node.block)
            freed += 1
        return freed


class PagedKVCache:
    """Block-paged K/V pool + per-slot block tables + prefix trie.

    Device side: ``k``/``v`` ``[num_blocks, n_layers, n_heads,
    block_size, head_dim]``.  Host side: ``block_tables`` [slots,
    blocks_per_slot] int32 (unused entries point at sentinel block 0),
    ``lengths`` [slots], ``refcount`` [num_blocks], a block free list
    and a slot free list.  ``models/transformer.decode_step_paged`` and
    ``prefill_extend`` consume the pool + tables directly.
    """

    def __init__(self, cfg, slots, block_size=None, num_blocks=None,
                 max_seq=None, dtype=None, prefix_sharing=True):
        import jax.numpy as jnp

        self.slots = int(slots)
        if self.slots < 1:
            raise ValueError("need at least one slot")
        self.max_seq = int(max_seq or cfg.max_seq)
        self.block_size = int(block_size or 16)
        if self.block_size < 1:
            raise ValueError("block_size must be >= 1")
        self.blocks_per_slot = -(-self.max_seq // self.block_size)
        min_blocks = 1 + self.slots * self.blocks_per_slot
        # default: 2x the live working set — the surplus is what lets
        # trie-retained prefixes of RETIRED sessions stay resident
        self.num_blocks = int(num_blocks or
                              1 + 2 * self.slots * self.blocks_per_slot)
        if self.num_blocks < min_blocks:
            raise ValueError(
                f"num_blocks {self.num_blocks} < sentinel + "
                f"slots*blocks_per_slot = {min_blocks}: live sessions "
                "could starve")
        self.dtype = dtype or cfg.compute_dtype
        shape = (self.num_blocks, cfg.n_layers, cfg.n_heads,
                 self.block_size, cfg.head_dim)
        self.k = jnp.zeros(shape, self.dtype)
        self.v = jnp.zeros(shape, self.dtype)
        self.block_tables = np.zeros((self.slots, self.blocks_per_slot),
                                     np.int32)
        self.lengths = np.zeros((self.slots,), np.int32)
        self.refcount = np.zeros((self.num_blocks,), np.int64)
        self.refcount[0] = 1            # sentinel: pinned forever
        self._nblocks = np.zeros((self.slots,), np.int32)
        self._free_blocks = list(range(self.num_blocks - 1, 0, -1))
        self._free = list(range(self.slots - 1, -1, -1))
        self.trie = PrefixTrie(self.block_size) if prefix_sharing else None

    # -- block accounting ---------------------------------------------------
    def _incref(self, block):
        self.refcount[block] += 1

    def _release(self, block):
        self.refcount[block] -= 1
        if self.refcount[block] < 0:
            raise AssertionError(f"block {block} refcount underflow")
        if self.refcount[block] == 0 and block != 0:
            self._free_blocks.append(block)

    def alloc_blocks(self, n):
        """``n`` fresh private blocks (refcount 1 each), reclaiming
        trie-only blocks LRU-first if the free list runs dry; raises
        :class:`CacheOOM` when live sessions hold everything."""
        if n > len(self._free_blocks) and self.trie is not None:
            self.trie.reclaim(n - len(self._free_blocks), self.refcount,
                              self._release)
        if n > len(self._free_blocks):
            raise CacheOOM(
                f"need {n} blocks, {len(self._free_blocks)} free "
                f"(pool {self.num_blocks}, in use {self.blocks_in_use})")
        out = [self._free_blocks.pop() for _ in range(n)]
        for b in out:
            self.refcount[b] += 1
        return out

    # -- slot lifecycle -----------------------------------------------------
    def alloc(self):
        """A free slot index, or None when all slots are occupied
        (blocks are allocated separately via :meth:`map_session`)."""
        return self._free.pop() if self._free else None

    def free_slot(self, slot):
        """Undo a bare :meth:`alloc` (admission rollback before any
        blocks were mapped)."""
        if slot in self._free:
            raise ValueError(f"slot {slot} is already free")
        self._free.append(slot)

    def map_session(self, slot, shared_blocks, own_blocks, length):
        """Install a session's block-table row: ``shared_blocks``
        (trie-matched, this call takes the session's refs) then
        ``own_blocks`` (already ref'd by :meth:`alloc_blocks`), cursor
        to ``length``."""
        blocks = list(shared_blocks) + list(own_blocks)
        if len(blocks) > self.blocks_per_slot:
            raise ValueError(
                f"{len(blocks)} blocks > blocks_per_slot "
                f"{self.blocks_per_slot}")
        for b in shared_blocks:
            self._incref(b)
        row = self.block_tables[slot]
        row[:] = 0
        row[:len(blocks)] = blocks
        self._nblocks[slot] = len(blocks)
        self.lengths[slot] = int(length)

    def ensure_capacity(self, slot, upto):
        """Grow ``slot``'s table so logical positions < ``upto`` are
        backed by real blocks (decode writes past the prompt)."""
        upto = min(int(upto), self.blocks_per_slot * self.block_size)
        need = -(-upto // self.block_size)
        have = int(self._nblocks[slot])
        if need <= have:
            return
        fresh = self.alloc_blocks(need - have)
        self.block_tables[slot, have:need] = fresh
        self._nblocks[slot] = need

    def retire(self, slot):
        """Free the slot and drop the session's block refs — shared
        blocks survive while the trie (or another session) still
        references them."""
        if slot in self._free:
            raise ValueError(f"slot {slot} is already free")
        for b in self.block_tables[slot, :self._nblocks[slot]]:
            self._release(int(b))
        self.block_tables[slot] = 0
        self._nblocks[slot] = 0
        self.lengths[slot] = 0
        self._free.append(slot)

    # -- prefix sharing -----------------------------------------------------
    def match_prefix(self, prompt):
        """(shared physical blocks, matched token count) for the
        longest resident whole-block prefix of ``prompt`` — capped one
        token short of the full prompt so admission always has a real
        tail to prefill (the tail's last position produces the
        first-token logits)."""
        if self.trie is None:
            return [], 0
        limit = (len(prompt) - 1) // self.block_size
        blocks = self.trie.match(prompt, limit=limit)
        return blocks, len(blocks) * self.block_size

    def register_prompt(self, slot, prompt):
        """Offer the session's whole-block prompt prefix to the trie
        (post-prefill, so the mapped blocks' content is final)."""
        if self.trie is None:
            return
        nb = len(prompt) // self.block_size
        self.trie.insert(prompt, [int(b) for b in
                                  self.block_tables[slot, :nb]],
                         self._incref)

    # -- device writes ------------------------------------------------------
    def insert_tail(self, slot, k, v, start, length):
        """Install prefill K/V ``[n_layers, n_heads, T, head_dim]``
        into the slot's blocks covering positions ``[start, start +
        length)``.  ``start`` must be block-aligned (trie matches are
        whole-block); the padded remainder of the last block is
        session-private scratch that decode overwrites in order."""
        bs = self.block_size
        if start % bs:
            raise ValueError(f"tail start {start} not block-aligned ({bs})")
        t = int(length)
        if start + t > self.max_seq:
            raise ValueError(
                f"prefill end {start + t} > max_seq {self.max_seq}")
        first = start // bs
        nch = -(-t // bs)
        phys = self.block_tables[slot, first:first + nch]
        kk = np.asarray(k)[:, :, :t]
        vv = np.asarray(v)[:, :, :t]
        pad = nch * bs - t
        if pad:
            padw = ((0, 0), (0, 0), (0, pad), (0, 0))
            kk = np.pad(kk, padw, mode="edge")
            vv = np.pad(vv, padw, mode="edge")
        # [L, H, nch*bs, D] -> [nch, L, H, bs, D] (pool layout)
        ll, hh, _, dd = kk.shape
        kk = kk.reshape(ll, hh, nch, bs, dd).transpose(2, 0, 1, 3, 4)
        vv = vv.reshape(ll, hh, nch, bs, dd).transpose(2, 0, 1, 3, 4)
        self.k = self.k.at[phys].set(kk.astype(self.dtype))
        self.v = self.v.at[phys].set(vv.astype(self.dtype))

    # -- introspection ------------------------------------------------------
    @property
    def occupancy(self):
        return self.slots - len(self._free)

    @property
    def free_slots(self):
        return len(self._free)

    @property
    def blocks_in_use(self):
        """Blocks referenced by live sessions and/or the trie (the
        sentinel is excluded)."""
        return self.num_blocks - 1 - len(self._free_blocks)

    def leaked_blocks(self):
        """Refcount lint: block ids that are neither free, sentinel,
        session-referenced, nor trie-referenced — must always be
        empty."""
        refs = np.zeros((self.num_blocks,), np.int64)
        refs[0] = 1
        for slot in range(self.slots):
            for b in self.block_tables[slot, :self._nblocks[slot]]:
                refs[int(b)] += 1
        if self.trie is not None:
            stack = [self.trie.root]
            while stack:
                children = stack.pop()
                for node in children.values():
                    refs[node.block] += 1
                    stack.append(node.children)
        if not np.array_equal(refs, self.refcount):
            bad = np.nonzero(refs != self.refcount)[0]
            raise AssertionError(
                f"refcount drift at blocks {bad.tolist()}: "
                f"counted {refs[bad].tolist()}, "
                f"stored {self.refcount[bad].tolist()}")
        free = set(self._free_blocks)
        return [b for b in range(1, self.num_blocks)
                if self.refcount[b] == 0 and b not in free]
