"""Slot-paged KV cache for continuous-batching autoregressive decode.

No reference counterpart (the reference delegates all inference to TF
Serving, SURVEY.md §2.2; reference Inference.scala:27-79 is offline
batch only).  The layout is vLLM-style slot paging simplified to one
page per session: two preallocated
``[slots, n_layers, n_heads, max_seq, head_dim]`` arrays (keys cached
rope-rotated) plus a per-slot length cursor.  A session owns exactly
one slot from admission to retirement, so

- admission is O(1): pop a free slot, ``insert`` the prefill K/V;
- retirement is O(1): push the slot back — no other session's cache
  moves, no compaction, no shape change (the fused
  ``models/transformer.decode_step`` always sees the same
  ``[slots, ...]`` arrays, so it compiles exactly once).

Numerical inertness contract (transformer.decode_step): a free slot
carries length 0 and is fed token 0, so it attends only position 0 of
its own page (zeros at init, a stale column after reuse — finite
either way); its logits row is discarded by the scheduler and no
operation mixes slots, so free slots cannot perturb occupied ones.

jax is imported lazily: the class is instantiated replica-side only
(scheduler.DecodeEngine); the driver half of serving never pulls jax.
"""

from __future__ import annotations

import numpy as np


class SlotKVCache:
    """Preallocated per-slot K/V pages + host-side cursor/free-list."""

    def __init__(self, cfg, slots, max_seq=None, dtype=None):
        import jax.numpy as jnp

        self.slots = int(slots)
        if self.slots < 1:
            raise ValueError("need at least one slot")
        self.max_seq = int(max_seq or cfg.max_seq)
        self.dtype = dtype or cfg.compute_dtype
        shape = (self.slots, cfg.n_layers, cfg.n_heads, self.max_seq,
                 cfg.head_dim)
        self.k = jnp.zeros(shape, self.dtype)
        self.v = jnp.zeros(shape, self.dtype)
        # host mirrors: the scheduler reads/writes these every iteration
        # without a device round-trip
        self.lengths = np.zeros((self.slots,), np.int32)
        self._free = list(range(self.slots - 1, -1, -1))  # pop() -> slot 0

    # -- slot lifecycle -----------------------------------------------------
    def alloc(self):
        """A free slot index, or None when the cache is full."""
        return self._free.pop() if self._free else None

    def retire(self, slot):
        """Return ``slot`` to the free list (cursor back to 0; the page
        itself is left stale — see the inertness contract above)."""
        if slot in self._free:
            raise ValueError(f"slot {slot} is already free")
        self.lengths[slot] = 0
        self._free.append(slot)

    def insert(self, slot, k, v, length):
        """Install a prefill result: ``k``/``v``
        [n_layers, n_heads, T, head_dim] into ``slot``'s first T
        columns, cursor to ``length`` (<= T <= max_seq)."""
        t = k.shape[2]
        if t > self.max_seq:
            raise ValueError(f"prefill length {t} > max_seq {self.max_seq}")
        self.k = self.k.at[slot, :, :, :t, :].set(k.astype(self.dtype))
        self.v = self.v.at[slot, :, :, :t, :].set(v.astype(self.dtype))
        self.lengths[slot] = int(length)

    # -- introspection ------------------------------------------------------
    @property
    def occupancy(self):
        return self.slots - len(self._free)

    @property
    def free_slots(self):
        return len(self._free)
