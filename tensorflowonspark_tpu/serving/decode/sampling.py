"""Seeded, replayable token sampling for the decode tier.

No reference counterpart (the reference delegates all inference to TF
Serving, SURVEY.md §2.2; reference Inference.scala:27-79 is offline
batch only).  The one invariant everything here serves: a sampled
token must be a PURE FUNCTION of ``(logits, params, index)`` — no
hidden RNG state threaded step to step.  That is what keeps the
resolve-once failover ledger token-exact: after a replica SIGKILL the
session re-prefills on a survivor, greedy-or-sampled decode replays
from index 0, and every ``(index, token)`` pair comes out identical,
so the driver-side IndexLedger dedupe (first arrival wins) sees zero
drift.  It is also what makes speculative decoding exact rather than
merely distribution-preserving: the verify step recomputes the target
sample at each index and accepts a draft token only when it EQUALS
that sample (scheduler._iterate_spec), so spec output == plain output
at the same seed by construction.

Per-index keying uses ``numpy.random.default_rng([seed, index])`` —
``SeedSequence`` spawning is deterministic across processes and
platforms (PCG64), unlike ``random.Random(seed); N draws``.

Pure stdlib + numpy: importable driver-side (server.py builds the
params dict), replica-side (scheduler samples host-side from fused
logits), never touches jax.
"""

from __future__ import annotations

import numpy as np

_SEED_MASK = 0x7FFFFFFF


def make(temperature=None, top_k=None, top_p=None, seed=None):
    """Validate request-level sampling knobs into the picklable params
    dict the dispatch blob carries (None == greedy argmax).

    ``temperature`` <= 0 (or unset) means greedy; ``top_k`` keeps the k
    highest logits; ``top_p`` keeps the smallest nucleus of cumulative
    probability >= p; ``seed`` keys the per-index RNG.  Raises
    ValueError on out-of-range values (the HTTP frontend maps it to
    400)."""
    if temperature is None and top_k is None and top_p is None \
            and seed is None:
        return None
    temperature = 0.0 if temperature is None else float(temperature)
    if not np.isfinite(temperature) or temperature < 0:
        raise ValueError(f"temperature must be >= 0, got {temperature}")
    if top_k is not None:
        top_k = int(top_k)
        if top_k < 1:
            raise ValueError(f"top_k must be >= 1, got {top_k}")
    if top_p is not None:
        top_p = float(top_p)
        if not 0.0 < top_p <= 1.0:
            raise ValueError(f"top_p must be in (0, 1], got {top_p}")
    if temperature == 0.0:
        return None  # top_k/top_p are no-ops under argmax
    seed = 0 if seed is None else int(seed)
    return {"temperature": temperature, "top_k": top_k, "top_p": top_p,
            "seed": seed & _SEED_MASK}


def is_greedy(params):
    return not params or not params.get("temperature")


def sample_token(logits, params, index):
    """One token from a logits row — pure in ``(logits, params, index)``.

    ``logits``: [vocab] float row (numpy or anything asarray-able);
    ``params``: the dict from :func:`make` (None == greedy);
    ``index``: the session's token index, which keys the RNG so a
    failover replay (or a speculative verify) of the same index draws
    the same uniform variate."""
    logits = np.asarray(logits, np.float64).reshape(-1)
    if is_greedy(params):
        return int(np.argmax(logits))
    z = logits / float(params["temperature"])
    top_k = params.get("top_k")
    if top_k and top_k < z.size:
        kth = np.partition(z, -top_k)[-top_k]
        z = np.where(z >= kth, z, -np.inf)
    p = np.exp(z - np.max(z))
    p /= p.sum()
    top_p = params.get("top_p")
    if top_p and top_p < 1.0:
        order = np.argsort(-p, kind="stable")
        csum = np.cumsum(p[order])
        keep = int(np.searchsorted(csum, top_p) + 1)
        mask = np.zeros(p.size, bool)
        mask[order[:keep]] = True
        p = np.where(mask, p, 0.0)
        p /= p.sum()
    rng = np.random.default_rng([int(params["seed"]), int(index)])
    u = rng.random()
    idx = int(np.searchsorted(np.cumsum(p), u, side="right"))
    return min(idx, p.size - 1)
