"""Open-loop Poisson-arrival load generator for serving SLOs.

No reference counterpart (the reference ships no load tooling; its
inference surface is the offline batch CLI, Inference.scala:27-79).
MLPerf-Inference-server-scenario semantics: arrivals are scheduled
from a seeded exponential inter-arrival process and fired ON SCHEDULE
regardless of how many requests are still outstanding.  A closed loop
(N clients, next request only after the last reply — what the serve
bench lane did before this) self-throttles exactly when the server
slows down, hiding queueing collapse; an open loop keeps offering the
configured rate, so p99 latency and shed counts reflect the arrival
process the SLO is actually written against.

Pure stdlib: usable from bench.py, tests, and examples without jax or
numpy on the path.
"""

from __future__ import annotations

import random
import threading
import time


def _pct(sorted_vals, q):
    """Nearest-rank percentile (server.SLOStats convention)."""
    if not sorted_vals:
        return 0.0
    idx = min(len(sorted_vals) - 1,
              max(0, round(q * (len(sorted_vals) - 1))))
    return sorted_vals[idx]


def shared_prefix_prompts(n, *, vocab_size, prefix_pool=4, prefix_len=64,
                          prefix_frac=0.6, tail_lo=9, tail_hi=16, seed=0):
    """Decode-lane traffic with a shared-system-prompt population.

    Returns ``(prompts, pool)``: ``prompts`` is ``n`` token-id lists of
    which a seeded ``prefix_frac`` fraction start with one of
    ``prefix_pool`` fixed ``prefix_len``-token "system prompts"
    (followed by a unique random tail), the rest are fully random —
    the fan-in shape the prefix trie exists for.  ``pool`` is the list
    of system prompts, so callers can warm the trie or compute
    expected savings.

    The prefix length is FIXED and the tail band ``[tail_lo,
    tail_hi]`` narrow, so shared-prefix requests fall into one
    (tail-bucket, prefix-block-bucket) compile group — the bench A/B
    measures paging, not compile-cache asymmetry.  Tokens stay in
    ``[1, vocab_size)``: 0 is left out so prompts never collide with
    inert padding.  Pure stdlib.
    """
    rng = random.Random(seed)
    draw = lambda ln: [rng.randrange(1, int(vocab_size)) for _ in range(ln)]
    pool = [draw(int(prefix_len)) for _ in range(int(prefix_pool))]
    prompts = []
    for _ in range(int(n)):
        tail = draw(rng.randint(int(tail_lo), int(tail_hi)))
        if rng.random() < float(prefix_frac):
            prompts.append(rng.choice(pool) + tail)
        else:
            prompts.append(draw(int(prefix_len)) + tail)
    return prompts, pool


def session_route_ids(n, sessions, seed=0):
    """``n`` request route-ids drawn from ``sessions`` stable sessions.

    Returns a list of ``n`` strings ``"s<k>"`` assigned by a seeded rng,
    modelling returning clients: every request carrying the same id is
    the *same* conversation, so the fabric's affinity router should land
    it on the replica whose paged KV cache already holds its prefix.
    Pure stdlib; pair with ``run_open_loop(..., route_fn=ids.__getitem__)``.
    """
    rng = random.Random(seed)
    return [f"s{rng.randrange(int(sessions))}" for _ in range(int(n))]


def run_open_loop(request_fn, *, rate_rps, n_requests, seed=0,
                  shed_exc=None, route_fn=None):
    """Fire ``n_requests`` calls of ``request_fn(i)`` at Poisson arrivals
    of ``rate_rps`` and aggregate SLO stats.

    ``request_fn`` runs on a per-arrival thread.  It may return None (a
    plain request — only wall latency is recorded) or a dict with any of
    ``ttft_ms`` (float), ``token_ms`` (list of per-token gap floats),
    ``tokens`` (int count), ``affinity`` (``"hit"``/``"miss"``/
    ``"fallback"`` as stamped by the fabric router).  Raising
    ``shed_exc`` counts as a shed; any other exception counts as an
    error.  Neither stops the run — an open loop keeps offering load.

    ``route_fn`` (optional) maps the request index to a stable session
    route-id (see :func:`session_route_ids`); when given, requests are
    fired as ``request_fn(i, route_fn(i))`` so the caller can thread the
    id to ``Server.generate(route_id=...)``.

    Returns one stats dict: request/shed/error counts, offered vs
    completed rate, latency p50/p99, TTFT p50/p99 and pooled per-token
    p50/p99 (when any request reported them), aggregate tokens/s, and —
    when any request reported an affinity outcome — affinity
    hit/miss/fallback counts plus ``affinity_hit_rate``.
    """
    rng = random.Random(seed)
    arrivals, t = [], 0.0
    for _ in range(int(n_requests)):
        arrivals.append(t)
        t += rng.expovariate(float(rate_rps))

    lock = threading.Lock()
    latency_ms, ttft_ms, token_ms = [], [], []
    counts = {"completed": 0, "shed": 0, "errors": 0, "tokens": 0}
    affinity = {"hit": 0, "miss": 0, "fallback": 0}

    def _one(i):
        t0 = time.perf_counter()
        try:
            if route_fn is not None:
                out = request_fn(i, route_fn(i))
            else:
                out = request_fn(i)
        except Exception as e:  # noqa: BLE001 - classified, never raised
            key = ("shed" if shed_exc is not None
                   and isinstance(e, shed_exc) else "errors")
            with lock:
                counts[key] += 1
            return
        dur = (time.perf_counter() - t0) * 1e3
        with lock:
            counts["completed"] += 1
            latency_ms.append(dur)
            if isinstance(out, dict):
                if out.get("ttft_ms") is not None:
                    ttft_ms.append(float(out["ttft_ms"]))
                token_ms.extend(float(g) for g in out.get("token_ms") or ())
                counts["tokens"] += int(out.get("tokens") or 0)
                if out.get("affinity") in affinity:
                    affinity[out["affinity"]] += 1

    threads = []
    start = time.perf_counter()
    for i, at in enumerate(arrivals):
        delay = start + at - time.perf_counter()
        if delay > 0:
            time.sleep(delay)
        th = threading.Thread(target=_one, args=(i,),
                              name=f"tfos-loadgen-{i}", daemon=True)
        th.start()
        threads.append(th)
    for th in threads:
        th.join()
    wall = max(time.perf_counter() - start, 1e-9)

    latency_ms.sort()
    ttft_ms.sort()
    token_ms.sort()
    out = {
        "requests": int(n_requests),
        "completed": counts["completed"],
        "shed": counts["shed"],
        "errors": counts["errors"],
        "offered_rps": round(rate_rps, 3),
        "completed_rps": round(counts["completed"] / wall, 3),
        "duration_s": round(wall, 3),
        "latency_p50_ms": round(_pct(latency_ms, 0.50), 3),
        "latency_p99_ms": round(_pct(latency_ms, 0.99), 3),
    }
    if ttft_ms:
        out["ttft_p50_ms"] = round(_pct(ttft_ms, 0.50), 3)
        out["ttft_p99_ms"] = round(_pct(ttft_ms, 0.99), 3)
    if token_ms:
        out["tok_p50_ms"] = round(_pct(token_ms, 0.50), 3)
        out["tok_p99_ms"] = round(_pct(token_ms, 0.99), 3)
    if counts["tokens"]:
        out["tokens"] = counts["tokens"]
        out["tokens_per_sec"] = round(counts["tokens"] / wall, 2)
    routed = sum(affinity.values())
    if routed:
        out["affinity_hits"] = affinity["hit"]
        out["affinity_misses"] = affinity["miss"]
        out["affinity_fallbacks"] = affinity["fallback"]
        out["affinity_hit_rate"] = round(affinity["hit"] / routed, 4)
    return out
