"""Iteration-level continuous batcher for autoregressive decode.

No reference counterpart (the reference delegates all inference to TF
Serving, SURVEY.md §2.2); this is the Orca-style iteration-level
scheduler the serving tier mounts behind
:class:`~tensorflowonspark_tpu.serving.replicas.ReplicaPool`:

- requests admit into free KV-cache slots **mid-flight** — there is no
  generation-boundary barrier; a new prompt joins the very next engine
  iteration after a slot frees up;
- each iteration runs (1) prefill for newly admitted prompts
  (sequence- and row-bucketed so compile count stays
  ``O(log slots · log max_seq)``), then (2) ONE fused
  ``models/transformer.decode_step`` over every occupied slot;
- a finished sequence (EOS or ``max_tokens``) retires its slot
  immediately and the slot is eligible for re-admission in the same
  loop pass.

Tokens stream back through the resolve-once machinery the predict path
already uses (batcher.PendingResult semantics): the driver-side
:class:`PendingSession` keys its token ledger by index, so a failover
replay after a replica SIGKILL (greedy decode is deterministic)
re-delivers identical ``(index, token)`` pairs — first arrival wins,
``_set``/``_fail`` resolve once, zero drop and zero dup by
construction.

Module import stays stdlib + numpy (driver-importable); jax and the
model only load inside :class:`DecodeEngine`'s replica-side thread.
"""

from __future__ import annotations

import collections
import logging
import os
import threading
import time

import numpy as np

from tensorflowonspark_tpu.actors.ledger import IndexLedger, ResolveOnce
from tensorflowonspark_tpu.serving import batcher as _batcher
from tensorflowonspark_tpu.utils import metrics_registry

logger = logging.getLogger(__name__)

SLOTS_ENV = "TFOS_DECODE_SLOTS"
QUEUE_MAX_ENV = "TFOS_DECODE_QUEUE_MAX"
MAX_TOKENS_ENV = "TFOS_DECODE_MAX_TOKENS"


def slots_default():
    return int(os.environ.get(SLOTS_ENV, "8"))


def queue_max_default():
    return int(os.environ.get(QUEUE_MAX_ENV, "64"))


def max_tokens_default():
    return int(os.environ.get(MAX_TOKENS_ENV, "64"))


class DecodeSpec:
    """The decode tier's picklable config, carried to replicas inside
    the ModelSpec payload (replicas.ModelSpec(..., decode=...)).

    ``cfg`` is a ``models/transformer.Config``; ``slots`` sizes the
    :class:`~.kvcache.SlotKVCache`; ``eos_id``/``max_tokens`` are
    per-session defaults a request may override (``max_tokens`` is
    always clamped to the cache page, ``max_seq - len(prompt)``).
    """

    def __init__(self, cfg, slots=None, eos_id=None, max_tokens=None):
        self.cfg = cfg
        self.slots = int(slots or slots_default())
        self.eos_id = eos_id
        self.max_tokens = int(max_tokens or max_tokens_default())


class PendingSession(ResolveOnce):
    """One decode session's future: a streaming token ledger plus the
    resolve-once result, mirroring ``batcher.PendingResult``.  Both
    pieces come from ``actors.ledger``.

    The :class:`~tensorflowonspark_tpu.actors.ledger.IndexLedger` keys
    on token INDEX: after a replica SIGKILL the session re-prefills on a
    survivor and greedy decode re-streams the same ``(index, token)``
    pairs — the first arrival of an index wins (its timestamp included,
    so TTFT/per-token stats survive failover), and a duplicate
    ``gen_done`` is swallowed by the resolve-once gate.
    """

    __slots__ = ("id", "prompt", "max_tokens", "eos_id", "t_submit",
                 "_ledger")

    def __init__(self, sid, prompt, max_tokens, eos_id):
        super().__init__()
        self.id = sid
        self.prompt = [int(t) for t in prompt]
        self.max_tokens = int(max_tokens)
        self.eos_id = eos_id
        self.t_submit = time.perf_counter()
        self._ledger = IndexLedger()   # index -> token, first arrival wins

    def tokens_so_far(self):
        return [int(t) for t in self._ledger.values()]

    def result(self, timeout=None):
        """Block for the session result dict (``tokens``, ``ttft_ms``,
        ``token_ms`` gaps, ``total_ms`` + engine meta); raises the
        session's error or TimeoutError."""
        timeout = (_batcher.request_timeout_default()
                   if timeout is None else timeout)
        return self.wait(timeout, "decode session not done")

    # -- resolve-once plumbing (pool._collect calls these) ------------------
    def _token(self, index, token):
        self._ledger.record(index, int(token))

    def _set(self, tokens, meta):
        if self.done():
            return
        now = time.perf_counter()
        times = self._ledger.times()
        gaps = []
        order = sorted(times)
        for a, b in zip(order, order[1:]):
            if b == a + 1:  # only adjacent indices time a real gap
                gaps.append((times[b] - times[a]) * 1e3)
        self.resolve({
            "tokens": [int(t) for t in tokens],
            "ttft_ms": (round((times[0] - self.t_submit) * 1e3, 3)
                        if 0 in times else None),
            "token_ms": [round(g, 3) for g in gaps],
            "total_ms": round((now - self.t_submit) * 1e3, 3),
            **(meta or {}),
        })

    def _fail(self, exc):
        self.reject(exc)


class _Slot:
    """Replica-side per-slot generation state."""

    __slots__ = ("sid", "prompt_len", "generated", "max_tokens", "eos_id",
                 "last", "t_admit")

    def __init__(self, sid, prompt_len, max_tokens, eos_id, first_token):
        self.sid = sid
        self.prompt_len = prompt_len
        self.max_tokens = max_tokens
        self.eos_id = eos_id
        self.generated = [first_token]
        self.last = first_token
        self.t_admit = time.perf_counter()


class DecodeEngine:
    """The replica-side continuous-batching loop.

    ``emit(kind, sid, *payload)`` is the wire back to the pool
    (replicas._make_replica_task routes it onto the manager out-queue):
    ``("token", sid, index, token)`` per generated token,
    ``("done", sid, tokens, meta)`` at retirement,
    ``("error", sid, message)`` on a per-session failure.

    jax, the transformer model and the KV cache are imported/built on
    the engine thread — constructing a DecodeEngine never touches jax,
    so driver-side imports stay cheap and axon-hook-safe.
    """

    def __init__(self, params, spec, emit, replica=0):
        self._params = params
        self._spec = spec
        self._emit = emit
        self._replica = replica
        self._q = collections.deque()
        self._qlock = threading.Lock()
        self._sids = set()          # sids queued or active (dedupe)
        self._active = {}           # slot index -> _Slot
        self._wake = threading.Event()
        self._stop = threading.Event()
        self._thread = None
        self._started = threading.Event()
        self._init_error = None
        self.iterations = 0
        self.prefills = 0
        self.retired = 0

    # -- lifecycle ----------------------------------------------------------
    def start(self, timeout=120.0):
        if self._thread is None:
            self._thread = threading.Thread(
                target=self._run, name="tfos-decode-engine", daemon=True)
            self._thread.start()
        if not self._started.wait(timeout):
            raise TimeoutError("decode engine did not start")
        if self._init_error is not None:
            raise self._init_error
        return self

    def stop(self, timeout=10.0):
        self._stop.set()
        self._wake.set()
        if self._thread is not None:
            self._thread.join(timeout)

    def set_params(self, params):
        """Hot-reload hook: swap params between iterations.  In-flight
        sessions finish against their already-cached K/V (old params)
        plus new-param compute for the remaining tokens — same in-band,
        no-drop semantics as the predict path's reload."""
        self._params = params

    def submit(self, sid, prompt, max_tokens=None, eos_id=None):
        """Queue one session; admission happens at the next iteration.
        Rejections (prompt too long, duplicate sid) are emitted as
        session errors, not raised — submit is called from the replica's
        message loop which must keep draining."""
        cfg = self._spec.cfg
        prompt = [int(t) for t in prompt]
        if not prompt or len(prompt) > cfg.max_seq - 1:
            self._emit("error", sid,
                       f"prompt length {len(prompt)} not in [1, "
                       f"{cfg.max_seq - 1}] (max_seq {cfg.max_seq})")
            return
        with self._qlock:
            if sid in self._sids:
                return              # failover re-send of a live session
            self._sids.add(sid)
            self._q.append({
                "sid": sid, "prompt": prompt,
                "max_tokens": int(max_tokens or self._spec.max_tokens),
                "eos_id": self._spec.eos_id if eos_id is None else eos_id,
            })
        self._wake.set()

    def stats(self):
        with self._qlock:
            queued = len(self._q)
        return {
            "iterations": self.iterations,
            "prefills": self.prefills,
            "retired": self.retired,
            "active": len(self._active),
            "queued": queued,
            "slots": self._spec.slots,
        }

    # -- engine thread ------------------------------------------------------
    def _run(self):
        try:
            import jax
            import jax.numpy as jnp

            from tensorflowonspark_tpu.models import transformer
            from tensorflowonspark_tpu.serving.decode import kvcache

            cfg = self._spec.cfg

            def _prefill(p, toks, lens):
                logits, k, v = transformer.prefill(p, toks, cfg,
                                                   lengths=lens)
                return jnp.argmax(logits, axis=-1).astype(jnp.int32), k, v

            def _step(p, toks, ck, cv, lens):
                logits, ck, cv = transformer.decode_step(
                    p, toks, cfg, ck, cv, lens)
                return (jnp.argmax(logits, axis=-1).astype(jnp.int32),
                        ck, cv)

            self._prefill_jit = jax.jit(_prefill)
            self._step_jit = jax.jit(_step)
            self._kvcache_mod = kvcache
            cache = kvcache.SlotKVCache(cfg, self._spec.slots)
        except BaseException as e:  # noqa: BLE001 - surface via start()
            self._init_error = e
            self._started.set()
            return
        self._started.set()
        while not self._stop.is_set():
            try:
                self._admit(cache)
                if not self._active:
                    self._wake.wait(0.02)
                    self._wake.clear()
                    continue
                self._iterate(cache)
            except BaseException as e:  # noqa: BLE001 - fail the cohort,
                # rebuild the cache, keep the replica serving
                logger.exception("decode engine iteration failed")
                self._fail_all(repr(e))
                cache = self._kvcache_mod.SlotKVCache(
                    self._spec.cfg, self._spec.slots)

    def _admit(self, cache):
        """Move queued sessions into free slots: bucketed prefill, then
        first-token emission (the prefill logits ARE token 0)."""
        batch = []
        with self._qlock:
            while self._q and len(batch) < cache.free_slots:
                batch.append(self._q.popleft())
        if not batch:
            return
        cfg = self._spec.cfg
        # group by sequence bucket so compile count stays logarithmic
        groups = {}
        for req in batch:
            t = _batcher.bucket_seq(len(req["prompt"]), cfg.max_seq)
            groups.setdefault(t, []).append(req)
        for t, members in groups.items():
            rows = _batcher.bucket_size(len(members), self._spec.slots)
            toks = np.stack([
                _batcher.pad_seq(np.asarray(m["prompt"], np.int32), t)
                for m in members])
            lens = np.asarray([len(m["prompt"]) for m in members], np.int32)
            toks = _batcher.pad_rows(toks, rows)
            lens = _batcher.pad_rows(lens, rows)
            firsts, k, v = self._prefill_jit(self._params, toks, lens)
            firsts = np.asarray(firsts)
            self.prefills += 1
            for i, req in enumerate(members):
                slot = cache.alloc()
                # cannot be None: admission is bounded by free_slots
                cache.insert(slot, k[i], v[i], len(req["prompt"]))
                first = int(firsts[i])
                mt = min(req["max_tokens"],
                         cache.max_seq - len(req["prompt"]))
                st = _Slot(req["sid"], len(req["prompt"]), max(1, mt),
                           req["eos_id"], first)
                self._active[slot] = st
                self._emit("token", st.sid, 0, first)
                if (st.eos_id is not None and first == st.eos_id) \
                        or st.max_tokens <= 1:
                    self._retire(cache, slot)
        metrics_registry.set_gauge("tfos_decode_slot_occupancy",
                                   cache.occupancy)

    def _iterate(self, cache):
        """One fused decode step over every occupied slot."""
        tokens = np.zeros((cache.slots,), np.int32)
        for slot, st in self._active.items():
            tokens[slot] = st.last
        nxt, cache.k, cache.v = self._step_jit(
            self._params, tokens, cache.k, cache.v, cache.lengths)
        nxt = np.asarray(nxt)
        self.iterations += 1
        for slot in list(self._active):
            st = self._active[slot]
            cache.lengths[slot] += 1
            tok = int(nxt[slot])
            st.generated.append(tok)
            st.last = tok
            self._emit("token", st.sid, len(st.generated) - 1, tok)
            if (st.eos_id is not None and tok == st.eos_id) \
                    or len(st.generated) >= st.max_tokens \
                    or cache.lengths[slot] >= cache.max_seq:
                self._retire(cache, slot)
        metrics_registry.set_gauge("tfos_decode_slot_occupancy",
                                   cache.occupancy)

    def _retire(self, cache, slot):
        st = self._active.pop(slot)
        cache.retire(slot)
        with self._qlock:
            self._sids.discard(st.sid)
        self.retired += 1
        metrics_registry.inc("tfos_decode_retired_total")
        self._emit("done", st.sid, list(st.generated), {
            "replica": self._replica,
            "prompt_len": st.prompt_len,
            "gen_ms": round((time.perf_counter() - st.t_admit) * 1e3, 3),
        })

    def _fail_all(self, message):
        with self._qlock:
            queued = list(self._q)
            self._q.clear()
            self._sids.clear()
        for req in queued:
            self._emit("error", req["sid"], message)
        for st in self._active.values():
            self._emit("error", st.sid, message)
        self._active.clear()
